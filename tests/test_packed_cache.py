"""Packed-batch cache (data/packed_cache.py) + multiprocess packer
(data/mp_pack.py): replay and pool packing must be bit-identical to the
inline batcher — same arrays, same order — and the cache key must change
whenever anything that shapes the stream changes (ISSUE 1)."""

import dataclasses
import json

import numpy as np
import pytest

from deepdfa_tpu.data.mp_pack import MpPacker, mp_shard_bucket_batches
from deepdfa_tpu.data.packed_cache import (
    PackedBatchCache,
    cache_key,
    corpus_digest,
)
from deepdfa_tpu.data.prefetch import PipelineStats, prefetch
from deepdfa_tpu.graphs import GraphBatch, shard_bucket_batches

from tests.test_graphs import make_graph

BUDGETS = dict(num_shards=2, num_graphs=4, node_budget=64, edge_budget=256)


def _corpus(rng, n=12):
    return [
        make_graph(rng, i, int(rng.integers(3, 30)), 10, label=float(i % 2))
        for i in range(n)
    ]


def assert_batches_identical(got, want):
    got, want = list(got), list(want)
    assert len(got) == len(want)
    for b, w in zip(got, want):
        assert b.num_graphs == w.num_graphs
        for f in dataclasses.fields(GraphBatch):
            if f.name == "num_graphs":
                continue
            bv, wv = getattr(b, f.name), getattr(w, f.name)
            assert (bv is None) == (wv is None), f.name
            if wv is None:
                continue
            bv, wv = np.asarray(bv), np.asarray(wv)
            assert bv.dtype == wv.dtype, f.name
            np.testing.assert_array_equal(bv, wv, err_msg=f.name)


def test_write_through_then_replay_bit_identical(tmp_path, rng):
    gs = _corpus(rng)
    direct = list(shard_bucket_batches(gs, **BUDGETS))
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))

    # cold pass: write-through yields the live stream unchanged
    cold = list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))
    assert_batches_identical(cold, direct)
    assert cache.has(key)

    # warm pass: replay (mmap) is the same stream, same order
    assert_batches_identical(cache.replay(key), direct)
    # and so is the eager-read mode
    assert_batches_identical(cache.replay(key, mmap=False), direct)


def test_get_or_pack_builds_once_then_replays(tmp_path, rng):
    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    calls = []

    def build():
        calls.append(1)
        return shard_bucket_batches(gs, **BUDGETS)

    first = list(cache.get_or_pack(key, build))
    second = list(cache.get_or_pack(key, build))
    assert len(calls) == 1  # warm hit never re-packs
    assert_batches_identical(second, first)


def test_abandoned_write_leaves_no_entry(tmp_path, rng):
    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    it = cache.write_through(key, shard_bucket_batches(gs, **BUDGETS))
    next(it)
    it.close()  # consumer abandons mid-stream
    assert not cache.has(key)
    # the partial spill is gone too — nothing for a later run to trip on
    assert cache.keys() == []
    assert list((tmp_path / "packed").iterdir()) == []


def test_replay_rejects_foreign_schema(tmp_path, rng):
    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))
    mpath = cache.entry_dir(key) / "manifest.json"
    m = json.loads(mpath.read_text())
    m["schema"] = -1
    mpath.write_text(json.dumps(m))
    with pytest.raises(ValueError, match="schema"):
        list(cache.replay(key))


def test_cache_key_sensitivity(rng):
    gs = _corpus(rng)
    src = corpus_digest(gs)
    base = cache_key(BUDGETS, src)
    assert base == cache_key(dict(BUDGETS), src)  # deterministic
    # insertion order is canonicalized away
    assert base == cache_key(dict(reversed(list(BUDGETS.items()))), src)
    assert base != cache_key(dict(BUDGETS, node_budget=128), src)
    assert base != cache_key(BUDGETS, src, vocab_digest="v2")
    assert base != cache_key(BUDGETS, corpus_digest(gs[:-1]))


def test_corpus_digest_tracks_content(rng):
    gs = _corpus(rng)
    base = corpus_digest(gs)
    assert base == corpus_digest(list(gs))
    edited = list(gs)
    feats = edited[3].node_feats.copy()
    feats[0, 0] += 1
    edited[3] = dataclasses.replace(edited[3], node_feats=feats)
    assert base != corpus_digest(edited)
    assert base != corpus_digest(gs[::-1])  # order matters: batches would


def test_prune_keeps_named_entries(tmp_path, rng):
    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed")
    k1 = cache_key(BUDGETS, corpus_digest(gs))
    k2 = cache_key(dict(BUDGETS, node_budget=128), corpus_digest(gs))
    list(cache.write_through(k1, shard_bucket_batches(gs, **BUDGETS)))
    list(
        cache.write_through(
            k2, shard_bucket_batches(gs, **dict(BUDGETS, node_budget=128))
        )
    )
    assert cache.prune(keep=[k1]) == 1
    assert cache.keys() == [k1]


def test_prefetch_ordering_over_cached_replay(tmp_path, rng):
    """The tests/test_prefetch.py ordering guarantee, extended to the
    cached path: replaying through the multi-producer prefetch pipeline
    yields the same batches in the same order as direct packing, and the
    source time lands in load_seconds (not pack_seconds)."""
    gs = _corpus(rng, n=20)
    direct = list(shard_bucket_batches(gs, **BUDGETS))
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    list(cache.get_or_pack(key, lambda: shard_bucket_batches(gs, **BUDGETS)))

    stats = PipelineStats()
    out = list(
        prefetch(
            cache.replay(key), size=2, producers=3, stats=stats,
            source_stage="load",
        )
    )
    assert_batches_identical(out, direct)
    assert stats.consumed == len(direct)
    assert stats.produced == len(direct)
    assert stats.load_seconds > 0
    assert stats.pack_seconds == 0


def test_max_entries_evicts_oldest(tmp_path, rng):
    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed", max_entries=2)
    keys = []
    for nb in (64, 96, 128):
        k = cache_key(dict(BUDGETS, node_budget=nb), corpus_digest(gs))
        keys.append(k)
        list(
            cache.write_through(
                k, shard_bucket_batches(gs, **dict(BUDGETS, node_budget=nb))
            )
        )
    # oldest entry evicted, newest two kept, the just-written one always
    assert sorted(cache.keys()) == sorted(keys[1:])


def test_replay_refreshes_lru_so_hot_entry_survives_eviction(tmp_path, rng):
    """Eviction is least-recently-USED: an entry replayed every epoch
    (the eval split) must outlive a stream of train-epoch writes even
    when it is the oldest by write time."""
    import os
    import time

    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed", max_entries=2)
    hot = cache_key(dict(BUDGETS, node_budget=64), corpus_digest(gs))
    list(
        cache.write_through(
            hot, shard_bucket_batches(gs, **dict(BUDGETS, node_budget=64))
        )
    )
    # age the hot manifest well below any later write, then replay it —
    # the LRU stamp must beat the write-time ordering
    old = time.time() - 3600
    os.utime(cache.entry_dir(hot) / "manifest.json", (old, old))
    for nb in (96, 128):
        list(cache.replay(hot))
        k = cache_key(dict(BUDGETS, node_budget=nb), corpus_digest(gs))
        mid = time.time() - 1800  # newer than `old`, older than the replay
        list(
            cache.write_through(
                k, shard_bucket_batches(gs, **dict(BUDGETS, node_budget=nb))
            )
        )
        os.utime(cache.entry_dir(k) / "manifest.json", (mid, mid))
        assert hot in cache.keys()


def test_cli_epoch_batches_replays_from_cache(tmp_path, rng, monkeypatch):
    """CLI wiring: with data.packed_cache=true, the second identical
    _epoch_batches call replays from disk — the packer never runs —
    and the batches are identical to the first (cold) pass."""
    import jax

    import deepdfa_tpu.graphs as graphs_mod
    from deepdfa_tpu.cli.main import _epoch_batches
    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.parallel import make_mesh

    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    cfg = config_mod.apply_overrides(
        Config(),
        [
            "data.packed_cache=true",
            "data.batch.graphs_per_batch=4",
            "data.batch.node_budget=64",
            "data.batch.edge_budget=256",
        ],
    )
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    gs = _corpus(rng)
    digest = corpus_digest(gs)

    calls = []
    real = graphs_mod.shard_bucket_batches

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(graphs_mod, "shard_bucket_batches", counting)
    cold = _epoch_batches(cfg, gs, mesh, phase="eval", source_digest=digest)
    assert len(calls) == 1
    warm = _epoch_batches(cfg, gs, mesh, phase="eval", source_digest=digest)
    assert len(calls) == 1  # warm hit: the packer never ran
    assert_batches_identical(warm, cold)
    # a different batcher config is a different key -> repacks
    cfg2 = config_mod.apply_overrides(cfg, ["data.batch.node_budget=128"])
    _epoch_batches(cfg2, gs, mesh, phase="eval", source_digest=digest)
    assert len(calls) == 2


def test_mp_packer_workers1_matches_inline(rng):
    gs = _corpus(rng)
    direct = list(shard_bucket_batches(gs, **BUDGETS))
    with MpPacker(gs, workers=1) as packer:
        got = list(packer.shard_bucket_batches(**BUDGETS))
    assert_batches_identical(got, direct)


def test_mp_packer_pool_matches_inline(rng):
    """Spawn-pool packing: same plans, same pack function, arrays round-
    tripped through shared memory — bit-identical to the inline batcher,
    including oversized singleton batches (ragged budgets)."""
    gs = _corpus(rng, n=10)
    gs.append(make_graph(rng, 100, 90, 10))  # > node_budget -> singleton
    stats_a: dict = {}
    stats_b: dict = {}
    direct = list(
        shard_bucket_batches(gs, oversized="singleton", stats=stats_a,
                             **BUDGETS)
    )
    got = list(
        mp_shard_bucket_batches(
            gs, oversized="singleton", stats=stats_b, workers=2, **BUDGETS
        )
    )
    assert_batches_identical(got, direct)
    assert stats_b == stats_a


def test_mp_packer_select_matches_inline(rng):
    """select=: one bound pool serves per-epoch subset selections (the
    undersample path) — plans are built over the selection and remapped
    to corpus indices, bit-identical to inline packing of the same
    selection."""
    gs = _corpus(rng)
    sel = [7, 2, 9, 0, 5]
    direct = list(shard_bucket_batches([gs[i] for i in sel], **BUDGETS))
    with MpPacker(gs, workers=2) as packer:
        got = list(
            packer.shard_bucket_batches(select=np.array(sel), **BUDGETS)
        )
    assert_batches_identical(got, direct)


def test_mp_packer_windowed_dispatch_and_abandon_drain(rng):
    """pack() must not race a whole epoch ahead of a training-paced
    consumer: dispatch is bounded to 2*workers outstanding plans (imap's
    task handler would eagerly consume every plan and pin every packed
    batch in /dev/shm until received), and abandoning the stream must
    drain + unlink the in-flight segments."""
    from deepdfa_tpu.data import mp_pack
    from deepdfa_tpu.graphs import plan_shard_bucket_batches

    gs = _corpus(rng, n=48)
    plans = list(
        plan_shard_bucket_batches(gs, 1, 2, BUDGETS["node_budget"],
                                  BUDGETS["edge_budget"])
    )
    pulled: list = []

    def lazy_plans():
        for p in plans:
            pulled.append(p)
            yield p

    with MpPacker(gs, workers=2) as packer:
        window = 2 * packer.workers
        assert len(plans) > window + 2, "corpus too small to observe"
        it = packer.pack(lazy_plans())
        next(it)
        # initial fill (window) + one refill after the first receive
        assert len(pulled) <= window + 1
        it.close()  # abandon mid-stream -> _drain
        if mp_pack._SHM_DIR.is_dir():
            left = list(mp_pack._SHM_DIR.glob(f"{packer._shm_prefix}*"))
            assert not left, left


def test_prune_spares_live_spill(tmp_path):
    """prune() must not rmtree another process's in-progress write_through
    spill: dot-dirs younger than SPILL_TTL_SECONDS are presumed live and
    only stale ones are collected as abandoned."""
    import os
    import time

    cache = PackedBatchCache(tmp_path / "packed")
    live = cache.root / ".k-live"
    live.mkdir()
    stale = cache.root / ".k-stale"
    stale.mkdir()
    old = time.time() - PackedBatchCache.SPILL_TTL_SECONDS - 60
    os.utime(stale, (old, old))
    assert cache.prune() == 1
    assert live.is_dir()
    assert not stale.exists()


def test_close_sweeps_own_shm_namespace():
    """close() after terminate() must unlink segments the parent never
    received (queued results / mid-pack workers) — they are named under
    the packer's prefix precisely so this sweep can find them — while a
    sibling packer's segments stay untouched."""
    import os

    from deepdfa_tpu.data import mp_pack

    if not mp_pack._SHM_DIR.is_dir():
        pytest.skip("no /dev/shm backing on this platform")
    packer, sibling = MpPacker([], workers=2), MpPacker([], workers=2)
    orphan = mp_pack._SHM_DIR / f"{packer._shm_prefix}{os.getpid()}-1"
    alive = mp_pack._SHM_DIR / f"{sibling._shm_prefix}{os.getpid()}-1"
    orphan.write_bytes(b"x")
    alive.write_bytes(b"x")
    try:
        class _DeadPool:
            def terminate(self):
                pass

            def join(self):
                pass

        packer._pool = _DeadPool()
        packer.close()
        assert not orphan.exists()
        assert alive.exists()
    finally:
        for p in (orphan, alive):
            p.unlink(missing_ok=True)


def test_sweep_stale_collects_dead_owners_only():
    """Pool construction garbage-collects segments whose parent pid is
    gone (crashed run, no close()); segments of LIVE pids — this process
    included — must survive."""
    import os
    import subprocess

    from deepdfa_tpu.data import mp_pack

    if not mp_pack._SHM_DIR.is_dir():
        pytest.skip("no /dev/shm backing on this platform")
    proc = subprocess.Popen(["true"])
    proc.wait()  # reaped: a pid guaranteed dead
    dead = mp_pack._SHM_DIR / f"{mp_pack._SHM_PREFIX}-{proc.pid}-0-1"
    mine = mp_pack._SHM_DIR / f"{mp_pack._SHM_PREFIX}-{os.getpid()}-0-1"
    dead.write_bytes(b"x")
    mine.write_bytes(b"x")
    try:
        mp_pack._sweep_stale()
        assert not dead.exists()
        assert mine.exists()
    finally:
        for p in (dead, mine):
            p.unlink(missing_ok=True)


def test_cli_epoch_key_constant_without_undersample(
    tmp_path, rng, monkeypatch
):
    """Without per-epoch undersampling the stream is epoch-invariant, so
    epoch must NOT enter the cache key: epoch 1 (and any re-run) replays
    epoch 0's entry instead of cold-packing a duplicate every epoch."""
    import jax

    import deepdfa_tpu.graphs as graphs_mod
    from deepdfa_tpu.cli.main import _epoch_batches
    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.parallel import make_mesh

    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    cfg = config_mod.apply_overrides(
        Config(),
        [
            "data.packed_cache=true",
            "data.undersample=false",
            "data.batch.graphs_per_batch=4",
            "data.batch.node_budget=64",
            "data.batch.edge_budget=256",
        ],
    )
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    gs = _corpus(rng)
    digest = corpus_digest(gs)

    calls = []
    real = graphs_mod.shard_bucket_batches

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(graphs_mod, "shard_bucket_batches", counting)
    e0 = _epoch_batches(cfg, gs, mesh, shuffle_epoch=0, source_digest=digest)
    assert len(calls) == 1
    e1 = _epoch_batches(cfg, gs, mesh, shuffle_epoch=1, source_digest=digest)
    assert len(calls) == 1  # warm hit: epoch is not part of the key
    assert_batches_identical(e1, e0)
    # with undersampling ON the selection IS epoch-dependent -> epoch keys
    cfg_u = config_mod.apply_overrides(cfg, ["data.undersample=true"])
    _epoch_batches(cfg_u, gs, mesh, shuffle_epoch=0, source_digest=digest)
    _epoch_batches(cfg_u, gs, mesh, shuffle_epoch=1, source_digest=digest)
    assert len(calls) == 3


def test_get_or_pack_rebuilds_when_entry_vanishes_mid_replay(
    tmp_path, rng
):
    """A concurrent run can evict/prune an entry between has() and the
    last np.load; replay must fall back to the builder and resume after
    the batches already yielded instead of crashing the run."""
    import shutil

    gs = _corpus(rng)
    direct = list(shard_bucket_batches(gs, **BUDGETS))
    assert len(direct) >= 2
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))

    builds = []

    def builder():
        builds.append(1)
        return shard_bucket_batches(gs, **BUDGETS)

    it = cache.get_or_pack(key, builder)
    got = [next(it)]
    shutil.rmtree(cache.entry_dir(key))  # concurrent evict
    got.extend(it)
    assert builds == [1]
    assert_batches_identical(got, direct)
    assert cache.has(key)  # the rebuild re-persisted the entry


def test_cli_lazy_stream_stage_labels(tmp_path, rng, monkeypatch):
    """lazy=True returns a stream labeled with the stage that will run:
    "pack" on a cold key, "load" on a warm one — what train/loop.py
    feeds PipelineStats so epoch records attribute host time correctly."""
    import jax

    from deepdfa_tpu.cli.main import _epoch_batches
    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.parallel import make_mesh

    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    cfg = config_mod.apply_overrides(
        Config(),
        [
            "data.packed_cache=true",
            "data.batch.graphs_per_batch=4",
            "data.batch.node_budget=64",
            "data.batch.edge_budget=256",
        ],
    )
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    gs = _corpus(rng)
    digest = corpus_digest(gs)

    cold = _epoch_batches(
        cfg, gs, mesh, phase="eval", source_digest=digest, lazy=True
    )
    assert cold.source_stage == "pack"
    cold_batches = list(cold)
    warm = _epoch_batches(
        cfg, gs, mesh, phase="eval", source_digest=digest, lazy=True
    )
    assert warm.source_stage == "load"
    assert_batches_identical(warm, cold_batches)


# -- corruption detection + quarantine (ISSUE 3, docs/resilience.md) -------


def test_replay_detects_truncated_shard_and_rebuilds(tmp_path, rng):
    """A shard truncated the way a killed writer leaves it: direct
    replay raises CacheCorruption; get_or_pack quarantines the entry and
    transparently repacks, bit-identical to direct packing."""
    from deepdfa_tpu.data.packed_cache import CacheCorruption
    from deepdfa_tpu.testing.faults import truncate_cache_file

    gs = _corpus(rng)
    direct = list(shard_bucket_batches(gs, **BUDGETS))
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))
    truncate_cache_file(tmp_path / "packed", key)

    with pytest.raises(CacheCorruption, match="size"):
        list(cache.replay(key))
    got = list(
        cache.get_or_pack(key, lambda: shard_bucket_batches(gs, **BUDGETS))
    )
    assert_batches_identical(got, direct)
    quarantined = list((tmp_path / "packed" / "quarantine").iterdir())
    assert len(quarantined) == 1
    assert cache.has(key)  # rebuilt entry is complete at the key's path
    # and the rebuilt entry replays cleanly
    assert_batches_identical(cache.replay(key), direct)


def test_replay_detects_same_size_bit_rot_via_digest(tmp_path, rng):
    """Bytes flipped WITHOUT a size change — only the content digest can
    catch this class of damage."""
    from deepdfa_tpu.data.packed_cache import CacheCorruption
    from deepdfa_tpu.testing.faults import corrupt_cache_file

    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))
    corrupt_cache_file(tmp_path / "packed", key)
    with pytest.raises(CacheCorruption, match="digest"):
        list(cache.replay(key))


def test_unreadable_manifest_is_quarantined_and_rebuilt(tmp_path, rng):
    gs = _corpus(rng)
    direct = list(shard_bucket_batches(gs, **BUDGETS))
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))
    (cache.entry_dir(key) / "manifest.json").write_text("{truncated")
    got = list(
        cache.get_or_pack(key, lambda: shard_bucket_batches(gs, **BUDGETS))
    )
    assert_batches_identical(got, direct)
    assert cache.has(key)


def test_quarantine_is_bounded(tmp_path, rng):
    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    for _ in range(cache.QUARANTINE_KEEP + 2):
        list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))
        cache.quarantine(key)
    q = tmp_path / "packed" / "quarantine"
    assert len(list(q.iterdir())) == cache.QUARANTINE_KEEP


def test_quarantine_retention_orders_by_quarantine_time(tmp_path, rng):
    """os.replace preserves the entry's ORIGINAL mtime — retention must
    order by quarantine time (embedded in the dest name), or an old
    entry quarantined just now would be evicted immediately."""
    import os

    gs = _corpus(rng)
    cache = PackedBatchCache(tmp_path / "packed")
    key = cache_key(BUDGETS, corpus_digest(gs))
    for _ in range(cache.QUARANTINE_KEEP):
        list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))
        cache.quarantine(key)
    list(cache.write_through(key, shard_bucket_batches(gs, **BUDGETS)))
    os.utime(cache.entry_dir(key), (0, 0))  # ancient original mtime
    dest = cache.quarantine(key)
    assert dest is not None and dest.exists()  # newest victim survives
