"""Transformer parity: HF numerics, ring attention, TP/SP shard_map."""

from functools import partial

import numpy as np
import pytest

from deepdfa_tpu.models import transformer as tfm

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def _random_ids(rng, b, t, vocab, pad_id=1, pad_tail=3):
    ids = rng.integers(5, vocab, (b, t))
    ids[:, -pad_tail:] = pad_id
    return ids.astype(np.int32)


def test_matches_hf_flax_roberta(rng):
    torch = pytest.importorskip("torch")
    from transformers import FlaxRobertaModel, RobertaConfig, RobertaModel

    hf_cfg = RobertaConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=40,
        type_vocab_size=1,
        hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
        pad_token_id=1,
    )
    torch_model = RobertaModel(hf_cfg, add_pooling_layer=True).eval()
    flax_model = FlaxRobertaModel(hf_cfg, seed=0)
    # load torch weights into flax for the oracle
    from transformers.modeling_flax_pytorch_utils import (
        convert_pytorch_state_dict_to_flax,
    )

    flax_params = convert_pytorch_state_dict_to_flax(
        torch_model.state_dict(), flax_model
    )

    cfg = tfm.TransformerConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position_embeddings=40, dropout_rate=0.0,
    )
    params = tfm.params_from_hf_torch(cfg, torch_model.state_dict())

    ids = _random_ids(rng, 2, 16, 128)
    mask = (ids != 1).astype(np.int32)

    want = flax_model(ids, attention_mask=mask, params=flax_params)
    got_hidden = tfm.encode(cfg, params, ids)
    np.testing.assert_allclose(
        np.asarray(got_hidden),
        np.asarray(want.last_hidden_state),
        rtol=2e-4,
        atol=3e-4,
    )
    got_pooled = tfm.cls_pool(cfg, params, got_hidden)
    np.testing.assert_allclose(
        np.asarray(got_pooled), np.asarray(want.pooler_output), rtol=2e-4, atol=3e-4
    )


def test_ring_attention_matches_full(rng):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from deepdfa_tpu.parallel.ring_attention import full_attention, ring_attention

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    b, h, t, d = 2, 4, 32, 16
    q = rng.standard_normal((b, h, t, d)).astype(np.float32)
    k = rng.standard_normal((b, h, t, d)).astype(np.float32)
    v = rng.standard_normal((b, h, t, d)).astype(np.float32)
    mask = np.ones((b, t), bool)
    mask[:, -5:] = False

    want = np.asarray(full_attention(q, k, v, mask))

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    ring = shard_map(
        partial(ring_attention, axis_name="sp"),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, None, "sp", None),
        check_vma=False,
    )
    got = np.asarray(jax.jit(ring)(q, k, v, mask))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)


def test_ulysses_attention_matches_full(rng):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from deepdfa_tpu.parallel.ring_attention import full_attention
    from deepdfa_tpu.parallel.ulysses import ulysses_attention

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    b, h, t, d = 2, 4, 32, 16
    q = rng.standard_normal((b, h, t, d)).astype(np.float32)
    k = rng.standard_normal((b, h, t, d)).astype(np.float32)
    v = rng.standard_normal((b, h, t, d)).astype(np.float32)
    mask = np.ones((b, t), bool)
    mask[:, -5:] = False

    want = np.asarray(full_attention(q, k, v, mask))

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    uly = shard_map(
        partial(ulysses_attention, axis_name="sp"),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, None, "sp", None),
        check_vma=False,
    )
    got = np.asarray(jax.jit(uly)(q, k, v, mask))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)


def test_ulysses_encoder_matches_single(rng):
    """sp_variant='ulysses' through the whole encoder == single device
    (the same contract test_sp_encoder_matches_single pins for the ring)."""
    import dataclasses as dc

    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    cfg = tfm.TransformerConfig.tiny(dropout_rate=0.0, sp_variant="ulysses")
    params = tfm.init_params(cfg, jax.random.key(1))
    t = 32
    ids = _random_ids(rng, 2, t, cfg.vocab_size, pad_tail=6)

    want = np.asarray(tfm.encode(cfg, params, ids))

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(), params), P(None, "sp")),
        out_specs=P(None, "sp", None),
        check_vma=False,
    )
    def sp_encode(params, ids):
        offset = jax.lax.axis_index("sp") * ids.shape[1]
        mask = ids != cfg.pad_token_id
        return tfm.encode(
            cfg, params, ids, attn_mask=mask, sp_axis="sp",
            position_offset=offset,
        )

    got = np.asarray(jax.jit(sp_encode)(params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)


def _layer_specs():
    return tfm.tp_layer_specs()


def test_tp_encoder_matches_single(rng):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    cfg = tfm.TransformerConfig.tiny(dropout_rate=0.0)
    params = tfm.init_params(cfg, jax.random.key(0))
    ids = _random_ids(rng, 2, 12, cfg.vocab_size)

    want = np.asarray(tfm.encode(cfg, params, ids))

    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))
    specs = {
        "embeddings": jax.tree.map(lambda _: P(), params["embeddings"]),
        "layers": _layer_specs(),
        "pooler": jax.tree.map(lambda _: P(), params["pooler"]),
    }

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    def tp_encode(params, ids):
        out = tfm.encode(cfg, params, ids, tp_axis="tp")
        return out

    got = np.asarray(jax.jit(tp_encode)(params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)


def test_sp_encoder_matches_single(rng):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    cfg = tfm.TransformerConfig.tiny(dropout_rate=0.0)
    params = tfm.init_params(cfg, jax.random.key(1))
    t = 32
    ids = _random_ids(rng, 2, t, cfg.vocab_size, pad_tail=6)

    want = np.asarray(tfm.encode(cfg, params, ids))

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(), params), P(None, "sp")),
        out_specs=P(None, "sp", None),
        check_vma=False,
    )
    def sp_encode(params, ids):
        # right-padded input: tokens before this shard = idx * local length
        offset = jax.lax.axis_index("sp") * ids.shape[1]
        mask = ids != cfg.pad_token_id
        return tfm.encode(
            cfg, params, ids, attn_mask=mask, sp_axis="sp",
            position_offset=offset,
        )

    got = np.asarray(jax.jit(sp_encode)(params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)
