"""CLI `fleet --smoke` end-to-end (real replica subprocesses, SIGKILL
failover, SIGTERM drain) plus the fleet halves of the schema checker and
the open-loop load bench — ISSUE 11 acceptance surface.

Subprocess-only by design (tests/conftest.py:run_cli): the CLI
normalizes to a 1-device CPU platform, which must never leak into this
8-virtual-device pytest process."""

import json
import os
import subprocess
import sys
from pathlib import Path

from tests.conftest import run_cli

REPO = Path(__file__).resolve().parent.parent


def _last_json(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line in output: {stdout[-800:]}"
    return json.loads(lines[-1])


def test_fleet_smoke_end_to_end(tmp_path):
    """`fleet --smoke`: a 2-replica fleet against a just-trained tiny
    checkpoint — router scores bit-identical to singleton serving, both
    replicas took traffic with a zero-recompile census each, an
    over-deadline burst shed before any device time, a SIGKILLed
    replica ejected with its in-flight work retried on the survivor (no
    request lost), and the survivor drained gracefully on SIGTERM
    leaving a postmortem + final SLO snapshot behind."""
    res = run_cli(tmp_path, "fleet", "--smoke", timeout=420)
    report = _last_json(res.stdout)

    # -- bit parity vs singleton serving, spread across both replicas
    assert report["bit_identical"] is True
    assert len(report["scored"]) >= 6
    assert all(
        s["status"] == 200 and s["request_id"] for s in report["scored"]
    )
    assert report["both_replicas_served"] is True
    # -- zero steady-state recompiles, pinned PER replica
    assert report["zero_recompiles_per_replica"] is True
    assert len(report["replica_census"]) == 2
    for census in report["replica_census"].values():
        assert census["steady_state_recompiles"] == 0
        assert census["jit_lowerings"] >= 1

    # -- deadline shed happened at the front door: 503s, replica
    # request counters untouched
    ds = report["deadline_shed"]
    assert ds["all_shed"] is True
    assert ds["no_device_time_spent"] is True
    assert all(s == 503 for s, _ in ds["statuses"])
    # -- token-bucket tenant: burst admitted, then 429
    assert report["rate_limit"]["statuses"][-1] == 429

    # -- failover: no request lost, scores still bit-identical
    fo = report["failover"]
    assert fo["all_ok"] is True
    assert fo["ejects"] >= 1
    assert fo["retries"] >= 1
    assert fo["survivor_routable"] is True

    # -- graceful drain: observed by the router, clean exit, postmortem
    dr = report["drain"]
    assert dr["exit_code"] == 0
    assert dr["router_observed"] is True
    assert dr["final_heartbeat_state"] == "drained"
    assert dr["postmortem"]["ok"] is True
    assert dr["postmortem"]["trigger"] == "sigterm"
    assert dr["final_serve_log"] is True

    # -- scheduled drill phase (ISSUE 18): one round on the stub-fleet
    # HA pair through a FaultableBackend, measured failover under the
    # documented 3.2 s bound, readmit + log-reseed completed
    dd = report["drill"]
    assert dd["ok"] is True
    assert dd["mode"] == "smoke"
    assert dd["drill_failover_s"] < dd["drill_bound_s"] == 3.2
    assert dd["drill_readmit_s"] > 0
    assert dd["drill_reseed_s"] > 0
    assert len(dd["per_round"]) == dd["rounds"] == 1
    # the drill genuinely ran through the faultable seam
    assert dd["per_round"][0]["coord_faults"].get("latency", 0) > 0

    # -- predictive autoscale phase (ISSUE 18): the ladder escalated
    # shed_stage2 -> tighten_admission, then scaled up BEFORE the
    # offered rate crossed measured capacity; the scaled fleet lost
    # nothing and every decision is a schema-valid fleet_log record
    az = report["autoscale"]
    assert az["ok"] is True
    assert az["scaled"] is True
    assert az["scaled_ahead"] is True
    assert az["rate_at_scale_rps"] < az["capacity_rps"] < az["peak_rps"]
    assert az["ladder_before_scale"] is True
    assert az["burst"]["lost"] == 0
    assert az["burst"]["routable_replicas"] == 2
    assert az["fleet_log"]["ok"] is True
    assert az["fleet_log"]["autoscale"] >= len(az["actions"])
    assert az["ramp_log_ok"] is True

    # -- data-flywheel phase (ISSUE 20): a shadow candidate rode the
    # stub fleet without ever taking live traffic; the losing ride was
    # refused with zero swaps, the drifting ride was halted mid-rollout
    # by the real drift gate and rolled back, and the winning ride
    # auto-promoted through the real rollout path under open-loop
    # traffic with zero lost requests and the census intact
    fw = report["flywheel"]
    assert fw["ok"] is True
    assert fw["shadow_not_routable"] is True
    assert fw["shadow_never_routed"] is True
    assert fw["losing"]["action"] == "demote"
    assert fw["losing"]["reason"] == "trailing"
    assert fw["losing"]["swaps"] == 0
    assert fw["drift_halt"]["reason"] == "rollout_halted"
    assert fw["drift_halt"]["halted"] is True
    assert fw["drift_halt"]["r0_restored"] is True
    assert fw["drift_halt"]["r1_refused"] is True
    assert fw["winning"]["rollout_ok"] is True
    assert fw["winning"]["promoted_everywhere"] is True
    assert fw["winning"]["census_ok"] is True
    assert fw["winning"]["lost"] == 0
    assert fw["winning"]["requests"] > 0
    assert fw["zero_recompiles"] is True
    assert fw["fleet_log"]["ok"] is True
    assert fw["fleet_log"]["shadow"] >= 3
    assert fw["fleet_log"]["promotions"] >= 1
    assert fw["fleet_log"]["demotions"] >= 2

    # -- the router's log validates in-process AND through the script
    assert report["fleet_log"]["ok"] is True
    assert report["fleet_log"]["requests"] > 0
    assert report["fleet_log"]["events"] > 0
    log_path = Path(report["fleet_log"]["path"])
    assert log_path.exists()
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_schema.py"),
         "--fleet-log", str(log_path)],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu"),
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    result = json.loads(proc.stdout.splitlines()[0])
    assert result["ok"] is True and result["undeclared"] == []

    # -- diag renders the fleet section from the same log
    run_dir = Path(report["run_dir"])
    diag = run_cli(tmp_path, "diag", str(run_dir), "--json", timeout=120)
    diag_report = _last_json(diag.stdout)
    fleet = diag_report["fleet"]
    assert fleet["requests"] == report["fleet_log"]["requests"]
    assert len(fleet["replicas"]) == 2
    assert fleet["shed_rate"] > 0
    assert "deadline" in fleet["shed_reasons"]
    event_names = {ev["name"] for ev in fleet["event_log"]}
    assert {"join", "eject", "drain_observed"} <= event_names
    assert fleet["counters"]["ejects"] >= 1


def test_bench_load_smoke(tmp_path):
    """scripts/bench_load.py --smoke: open-loop overload drive against
    an in-process fleet; stamped record with the gated fleet headline
    numbers (bench.py --child-fleet consumes the same fn)."""
    out = tmp_path / "fleet_bench.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_load.py"),
         "--smoke", "--out", str(out)],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu",
                 DEEPDFA_TPU_STORAGE=str(tmp_path)),
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    record = json.loads(out.read_text())
    assert record["metric"] == "fleet_p99_overload_ms"
    assert record["value"] > 0
    assert record["fleet_p99_overload_ms"] >= record["fleet_latency_p50_ms"]
    # the generator genuinely overloads: offered rate above measured
    # warm capacity, and the admission layer shed something (the
    # best-effort tenant's tiny bucket guarantees a nonzero floor)
    assert record["fleet_offered_rate_per_sec"] > (
        record["fleet_warm_requests_per_sec"]
    )
    assert 0.0 < record["fleet_shed_rate"] < 1.0
    assert record["fleet_admitted"] + record["fleet_shed"] + (
        record["fleet_failed_other"]
    ) == record["fleet_requests_total"]
    assert record["fleet_replicas"] == 2
    # the Morphling invariant survives overload: nothing recompiled
    assert record["fleet_steady_state_recompiles"] == 0
    # provenance stamp, like every other bench record
    for k in ("schema_version", "git_sha", "jax_version"):
        assert k in record
