"""GPipe pipeline parallelism: parity vs the single-device encoder."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.core import MeshConfig
from deepdfa_tpu.models.transformer import (
    TransformerConfig,
    cls_pool,
    encode,
    init_params,
)
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.parallel.pipeline import (
    merge_stages,
    pipeline_encode,
    split_stages,
)

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig.tiny(
        vocab_size=64, num_layers=4, max_position_embeddings=40
    )
    params = init_params(cfg, jax.random.key(0))
    ids = np.array(
        jax.random.randint(jax.random.key(1), (8, 12), 5, 60), np.int32
    )
    ids[:, -3:] = cfg.pad_token_id  # a padded tail exercises the mask
    return cfg, params, jnp.asarray(ids)


def test_split_merge_roundtrip(setup):
    _, params, _ = setup
    staged = split_stages(params["layers"], 2)
    for leaf in jax.tree.leaves(staged):
        assert leaf.shape[0] == 2
    back = merge_stages(staged)
    jax.tree.map(np.testing.assert_array_equal, back, params["layers"])


def test_split_rejects_indivisible(setup):
    _, params, _ = setup
    with pytest.raises(ValueError, match="not divisible"):
        split_stages(params["layers"], 3)


@pytest.mark.parametrize("pp,microbatches", [(2, 4), (4, 8), (2, 2)])
def test_pipeline_matches_single_device(setup, pp, microbatches):
    cfg, params, ids = setup
    mesh = make_mesh(MeshConfig(dp=1, pp=pp), devices=jax.devices()[:pp])
    want = np.asarray(encode(cfg, params, ids))
    got = np.asarray(
        jax.jit(
            lambda p, x: pipeline_encode(
                cfg, p, x, mesh, microbatches=microbatches
            )
        )(params, ids)
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match(setup):
    """Autodiff through ppermute yields the mirrored backward pipeline:
    gradients must match the single-device encoder's."""
    cfg, params, ids = setup
    mesh = make_mesh(MeshConfig(dp=1, pp=2), devices=jax.devices()[:2])

    def loss_single(p):
        h = encode(cfg, p, ids)
        return jnp.sum(cls_pool(cfg, p, h) ** 2)

    def loss_pp(p):
        h = pipeline_encode(cfg, p, ids, mesh, microbatches=4)
        return jnp.sum(cls_pool(cfg, p, h) ** 2)

    g1 = jax.jit(jax.grad(loss_single))(params)
    g2 = jax.jit(jax.grad(loss_pp))(params)
    flat1, flat2 = jax.tree.leaves(g1), jax.tree.leaves(g2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
        )


def test_pipeline_uneven_final_microbatch(setup):
    """B=8 with microbatches=3: the schedule pads the final microbatch
    with replicated rows and slices them off — forward parity AND grad
    parity must hold exactly as in the divisible case (the VERDICT-r4
    uneven-microbatch gap)."""
    cfg, params, ids = setup
    mesh = make_mesh(MeshConfig(dp=1, pp=2), devices=jax.devices()[:2])
    want = np.asarray(encode(cfg, params, ids))
    got = np.asarray(
        jax.jit(
            lambda p, i: pipeline_encode(cfg, p, i, mesh, microbatches=3)
        )(params, ids)
    )
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=1e-4)

    def loss_pp(p):
        h = pipeline_encode(cfg, p, ids, mesh, microbatches=3)
        return jnp.sum(cls_pool(cfg, p, h) ** 2)

    def loss_1(p):
        return jnp.sum(cls_pool(cfg, p, encode(cfg, p, ids)) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_1 = jax.jit(jax.grad(loss_1))(params)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_1)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-3
        )


def test_pipeline_dropout_runs_and_differs_across_stages(setup):
    """With dropout active the pipeline must still run (keys fold by
    microbatch AND stage so stage masks decorrelate); smoke finiteness
    and that dropout actually perturbs the no-dropout output."""
    cfg, params, ids = setup
    mesh = make_mesh(MeshConfig(dp=1, pp=2), devices=jax.devices()[:2])
    clean = pipeline_encode(cfg, params, ids, mesh, microbatches=4)
    noisy = pipeline_encode(
        cfg, params, ids, mesh, microbatches=4,
        dropout_key=jax.random.key(9),
    )
    assert np.isfinite(np.asarray(noisy)).all()
    assert np.abs(np.asarray(noisy) - np.asarray(clean)).max() > 1e-4


# ---------------------------------------------------------------------------
# T5 pipeline (round 3: pp x t5 composition)


@pytest.fixture(scope="module")
def t5_setup():
    from deepdfa_tpu.models import t5 as t5m

    cfg = t5m.T5Config.tiny(vocab_size=64, dropout_rate=0.0, remat=False)
    params = t5m.init_params(cfg, jax.random.key(2))
    ids = np.array(
        jax.random.randint(jax.random.key(3), (8, 12), 5, 60), np.int32
    )
    ids[:, -2:] = cfg.pad_token_id
    return cfg, params, jnp.asarray(ids)


@pytest.mark.parametrize("pp,microbatches", [(2, 4), (2, 2)])
def test_t5_pipeline_matches_single_device(t5_setup, pp, microbatches):
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.parallel.pipeline import t5_pipeline_encode

    cfg, params, ids = t5_setup
    mesh = make_mesh(MeshConfig(dp=1, pp=pp), devices=jax.devices()[:pp])
    want = np.asarray(t5m.encode(cfg, params, ids))
    got = np.asarray(
        jax.jit(
            lambda p, x: t5_pipeline_encode(
                cfg, p, x, mesh, microbatches=microbatches
            )
        )(params, ids)
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_t5_pipeline_gradients_match(t5_setup):
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.parallel.pipeline import t5_pipeline_encode

    cfg, params, ids = t5_setup
    mesh = make_mesh(MeshConfig(dp=1, pp=2), devices=jax.devices()[:2])

    def loss_single(p):
        h = t5m.encode(cfg, p, ids)
        return jnp.sum(h[:, 0, :] ** 2)

    def loss_pp(p):
        h = t5_pipeline_encode(cfg, p, ids, mesh, microbatches=4)
        return jnp.sum(h[:, 0, :] ** 2)

    g1 = jax.jit(jax.grad(loss_single))(params)
    g2 = jax.jit(jax.grad(loss_pp))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5
        )
