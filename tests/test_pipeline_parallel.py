"""GPipe pipeline parallelism: parity vs the single-device encoder."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.core import MeshConfig
from deepdfa_tpu.models.transformer import (
    TransformerConfig,
    cls_pool,
    encode,
    init_params,
)
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.parallel.pipeline import (
    merge_stages,
    pipeline_encode,
    split_stages,
)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig.tiny(
        vocab_size=64, num_layers=4, max_position_embeddings=40
    )
    params = init_params(cfg, jax.random.key(0))
    ids = np.array(
        jax.random.randint(jax.random.key(1), (8, 12), 5, 60), np.int32
    )
    ids[:, -3:] = cfg.pad_token_id  # a padded tail exercises the mask
    return cfg, params, jnp.asarray(ids)


def test_split_merge_roundtrip(setup):
    _, params, _ = setup
    staged = split_stages(params["layers"], 2)
    for leaf in jax.tree.leaves(staged):
        assert leaf.shape[0] == 2
    back = merge_stages(staged)
    jax.tree.map(np.testing.assert_array_equal, back, params["layers"])


def test_split_rejects_indivisible(setup):
    _, params, _ = setup
    with pytest.raises(ValueError, match="not divisible"):
        split_stages(params["layers"], 3)


@pytest.mark.parametrize("pp,microbatches", [(2, 4), (4, 8), (2, 2)])
def test_pipeline_matches_single_device(setup, pp, microbatches):
    cfg, params, ids = setup
    mesh = make_mesh(MeshConfig(dp=1, pp=pp), devices=jax.devices()[:pp])
    want = np.asarray(encode(cfg, params, ids))
    got = np.asarray(
        jax.jit(
            lambda p, x: pipeline_encode(
                cfg, p, x, mesh, microbatches=microbatches
            )
        )(params, ids)
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match(setup):
    """Autodiff through ppermute yields the mirrored backward pipeline:
    gradients must match the single-device encoder's."""
    cfg, params, ids = setup
    mesh = make_mesh(MeshConfig(dp=1, pp=2), devices=jax.devices()[:2])

    def loss_single(p):
        h = encode(cfg, p, ids)
        return jnp.sum(cls_pool(cfg, p, h) ** 2)

    def loss_pp(p):
        h = pipeline_encode(cfg, p, ids, mesh, microbatches=4)
        return jnp.sum(cls_pool(cfg, p, h) ** 2)

    g1 = jax.jit(jax.grad(loss_single))(params)
    g2 = jax.jit(jax.grad(loss_pp))(params)
    flat1, flat2 = jax.tree.leaves(g1), jax.tree.leaves(g2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
        )


def test_pipeline_batch_divisibility_checked(setup):
    cfg, params, ids = setup
    mesh = make_mesh(MeshConfig(dp=1, pp=2), devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_encode(cfg, params, ids, mesh, microbatches=3)


def test_pipeline_dropout_runs_and_differs_across_stages(setup):
    """With dropout active the pipeline must still run (keys fold by
    microbatch AND stage so stage masks decorrelate); smoke finiteness
    and that dropout actually perturbs the no-dropout output."""
    cfg, params, ids = setup
    mesh = make_mesh(MeshConfig(dp=1, pp=2), devices=jax.devices()[:2])
    clean = pipeline_encode(cfg, params, ids, mesh, microbatches=4)
    noisy = pipeline_encode(
        cfg, params, ids, mesh, microbatches=4,
        dropout_key=jax.random.key(9),
    )
    assert np.isfinite(np.asarray(noisy)).all()
    assert np.abs(np.asarray(noisy) - np.asarray(clean)).max() > 1e-4
