"""Joern session driver: protocol + timeout, exercised via a stub binary.

The real JVM is absent from CI images (as it was for the reference, which
only tested against a locally installed joern); the interaction protocol
— marker framing, queue-pumped reads, per-command deadline, EOF
detection — is fully exercised against a stub process, and a
skipif-gated test drives the real binary when one is on PATH.
"""

import os
import stat
import sys
import textwrap

import pytest

from deepdfa_tpu.frontend import joern_session
from deepdfa_tpu.frontend.joern_session import JoernSession, JoernTimeout

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def _stub(tmp_path, body: str) -> str:
    """A marker-echoing stand-in for the joern REPL."""
    path = tmp_path / "joern-stub"
    path.write_text(
        "#!" + sys.executable + "\n" + textwrap.dedent(body)
    )
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


ECHO_STUB = """
import sys
for line in sys.stdin:
    line = line.strip()
    if line.startswith('println("'):
        print(line.split('"')[1], flush=True)
    else:
        print("echo: " + line, flush=True)
"""

WEDGE_STUB = """
import sys, time
n = 0
for line in sys.stdin:
    line = line.strip()
    if line.startswith('println("'):
        n += 1
        if n > 1:
            time.sleep(3600)  # wedge after the readiness handshake
        print(line.split('"')[1], flush=True)
    else:
        print("echo: " + line, flush=True)
"""


def test_protocol_roundtrip(tmp_path):
    s = JoernSession(binary=_stub(tmp_path, ECHO_STUB), timeout=10)
    try:
        out = s.run_command("cpg.method.name.l")
        assert "echo: cpg.method.name.l" in out
        # multiple commands on one session
        assert "echo: 2 + 2" in s.run_command("2 + 2")
    finally:
        s.close()


def test_timeout_raises_and_kills(tmp_path):
    # generous session timeout (interpreter startup can take seconds when
    # sitecustomize is heavy); the per-command bound is what's under test
    s = JoernSession(binary=_stub(tmp_path, WEDGE_STUB), timeout=60)
    with pytest.raises(JoernTimeout):
        s.run_command("anything", timeout=2)
    assert s.proc.poll() is not None  # wedged JVM was killed
    s.close()


def test_eof_detected(tmp_path):
    stub = _stub(tmp_path, ECHO_STUB)
    s = JoernSession(binary=stub, timeout=10)
    s.proc.stdin.close()
    s.proc.wait(timeout=10)
    with pytest.raises((RuntimeError, ValueError)):
        s.run_command("after eof")
    s.close()


@pytest.mark.skipif(not joern_session.available(), reason="no joern binary")
def test_real_joern_export(tmp_path):
    """End-to-end against a real joern install: import + export + load."""
    from deepdfa_tpu.frontend.joern_io import load_joern_cpg

    src = tmp_path / "f.c"
    src.write_text("int f(int a) {\n  int x = a + 1;\n  return x;\n}\n")
    with JoernSession() as s:
        s.import_code(src)
        nodes, edges = s.export_cpg_json(src)
        assert nodes.exists() and edges.exists()
        cpg = load_joern_cpg(src)
        assert cpg.cfg_nodes()


def test_export_dataflow_sends_solver_script(tmp_path):
    """Protocol-level: the dataflow export issues one command that writes
    the expected output path via Joern's reaching-def solver API."""
    s = JoernSession(binary=_stub(tmp_path, ECHO_STUB), timeout=60)
    try:
        out = s.export_dataflow_json(tmp_path / "f.c")
        assert str(out).endswith("f.c.dataflow.json")
    finally:
        s.close()


def test_export_cpg_bin_copies_workspace_artifact(tmp_path):
    s = JoernSession(binary=_stub(tmp_path, ECHO_STUB), timeout=60)
    try:
        proj = s.workspace / "workspace" / "f.c"
        proj.mkdir(parents=True)
        (proj / "cpg.bin").write_bytes(b"\x00CPGB")
        dest = s.export_cpg_bin(tmp_path / "f.c")
        assert dest.read_bytes() == b"\x00CPGB"
    finally:
        s.close()


def test_export_cpg_bin_without_import_raises(tmp_path):
    s = JoernSession(binary=_stub(tmp_path, ECHO_STUB), timeout=60)
    try:
        with pytest.raises(RuntimeError, match="cpg.bin"):
            s.export_cpg_bin(tmp_path / "f.c")
    finally:
        s.close()


def test_export_cpg_bin_prefers_matching_project(tmp_path):
    import time

    s = JoernSession(binary=_stub(tmp_path, ECHO_STUB), timeout=60)
    try:
        for name in ("a.c", "z.c"):
            proj = s.workspace / "workspace" / name
            proj.mkdir(parents=True)
            (proj / "cpg.bin").write_bytes(name.encode())
            time.sleep(0.01)
        # z.c written last (newest mtime, lexicographically greatest) but
        # the export must pick the project matching the requested source
        dest = s.export_cpg_bin(tmp_path / "a.c")
        assert dest.read_bytes() == b"a.c"
    finally:
        s.close()


# -- bounded auto-restart after a hung JVM (ISSUE 3 satellite) -------------

RESTART_STUB = """
import sys, time, pathlib
flag = pathlib.Path(r'%s')
first = not flag.exists()
if first:
    flag.touch()
n = 0
for line in sys.stdin:
    line = line.strip()
    if line.startswith('println("'):
        n += 1
        if first and n > 1:
            time.sleep(3600)  # first JVM wedges after its handshake
        print(line.split('"')[1], flush=True)
    else:
        print("echo: " + line, flush=True)
"""


def test_timeout_restarts_fresh_jvm_and_retries_once(tmp_path):
    """First JVM wedges on the real command; the session spawns a fresh
    one and the retried command succeeds — one hung JVM no longer fails
    the whole extraction batch."""
    flag = tmp_path / "first-run-marker"
    s = JoernSession(
        binary=_stub(tmp_path, RESTART_STUB % str(flag)), timeout=60
    )
    try:
        out = s.run_command("cpg.method.name.l", timeout=3)
        assert "echo: cpg.method.name.l" in out
        assert s.restarts == 1
    finally:
        s.close()


def test_timeout_with_restarts_disabled_keeps_failfast(tmp_path):
    s = JoernSession(
        binary=_stub(tmp_path, WEDGE_STUB), timeout=60, max_restarts=0
    )
    try:
        with pytest.raises(JoernTimeout):
            s.run_command("anything", timeout=2)
        assert s.restarts == 0
    finally:
        s.close()
