"""Operational observability for serving (ISSUE 6): rolling SLO
windows, Prometheus exposition, backend-health probes, the bench
regression gate, and request-scoped trace linkage.

The heavyweight end-to-end halves (HTTP /metrics scrape validated by
check_obs_schema, flow-linked spans in a real `serve --smoke`, diag SLO
section) live in tests/test_serve_cli.py; these are the unit-level
contracts.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from deepdfa_tpu.obs import health as obs_health, slo as obs_slo, trace
from deepdfa_tpu.obs.slo import SloEngine, WindowedSamples, percentile

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# rolling windows


def test_windowed_percentiles_match_brute_force():
    """Property: for random observation times/values and random query
    times, the engine's windowed percentile equals a brute-force filter
    over the full (time, value) history."""
    rng = np.random.default_rng(7)
    horizon = 60.0
    ring = WindowedSamples(horizon, max_samples=10_000)
    history: list[tuple[float, float]] = []
    t = 1000.0
    for _ in range(400):
        t += float(rng.exponential(2.0))
        v = float(rng.lognormal(0.0, 1.0))
        ring.observe(v, t)
        history.append((t, v))
        if rng.random() < 0.25:
            # query at the current clock: eviction is destructive, so
            # (like a wall clock) query times never run backwards
            now = t
            got = sorted(ring.values(now))
            want = sorted(
                v for (tv, v) in history if tv >= now - horizon
            )
            assert got == want
            for q in (0.5, 0.95, 0.99):
                assert percentile(got, q) == percentile(want, q)


def test_windowed_samples_bounded():
    ring = WindowedSamples(1e9, max_samples=16)
    for i in range(100):
        ring.observe(float(i), now=float(i))
    vals = ring.values(now=100.0)
    assert len(vals) == 16
    assert vals == [float(i) for i in range(84, 100)]  # newest survive


def test_slo_engine_windows_and_error_rate():
    clock = {"t": 1000.0}
    eng = SloEngine(windows=(60, 300), clock=lambda: clock["t"])
    for i in range(10):
        eng.observe_request(
            200, 0.010 * (i + 1), frontend_s=0.001, queue_s=0.002,
            device_s=0.004,
        )
    eng.observe_request(429, None)
    eng.observe_request(500, 0.5)
    snap = eng.snapshot()
    v60 = snap["60s"]
    assert v60["status"] == {"200": 10, "429": 1, "500": 1}
    assert v60["error_rate"] == pytest.approx(2 / 12, abs=1e-4)
    # latency stages all present, p50 over the 11 finite totals
    assert v60["latency_ms"]["total"]["count"] == 11
    assert v60["latency_ms"]["frontend"]["p50"] == 1.0
    # 4 minutes later the 60s window is empty, the 300s one is not
    clock["t"] += 240
    snap = eng.snapshot()
    assert "latency_ms" not in snap["60s"]
    assert snap["300s"]["latency_ms"]["total"]["count"] == 11
    # lifetime totals never age out
    assert snap["requests_total"] == 12


def test_windowed_status_counts_exact_beyond_sample_cap():
    """Status counts have COUNTER semantics: a busy status past the
    latency-sample cap must not distort the windowed error rate (a
    sample-ring would truncate the 200s first and overstate errors)."""
    clock = {"t": 1000.0}
    eng = SloEngine(windows=(60,), max_samples=4, clock=lambda: clock["t"])
    for _ in range(40):
        eng.observe_request(200, 0.01)
    eng.observe_request(500, 0.01)
    view = eng.snapshot()["60s"]
    assert view["status"] == {"200": 40, "500": 1}
    assert view["error_rate"] == pytest.approx(1 / 41, abs=1e-4)
    # latency quantiles DO degrade to the newest max_samples — that cap
    # is the documented memory bound
    assert view["latency_ms"]["total"]["count"] == 4


def test_windowed_counts_evict_on_write():
    """A ring nobody reads must not grow a bucket per active second
    forever — eviction happens on observe() too."""
    ring = obs_slo.WindowedCounts(horizon_s=10.0)
    for sec in range(1000):
        ring.observe(float(sec))
    assert len(ring._buckets) <= 11
    assert ring.total(999.0) == 11  # seconds 989..999 inclusive


def test_exposition_max_gauge_binds_to_own_family():
    """A summary's sibling `<base>_max` gauge declares its own family;
    its sample must not fold into the base summary's samples."""
    from deepdfa_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("serve/latency_seconds")
    h.observe(0.05)
    h.observe(0.20)
    fams = obs_slo.parse_exposition(obs_slo.registry_exposition(reg))
    base = fams["deepdfa_serve_latency_seconds"]
    assert len(base["samples"]) == 2  # _count + _sum only
    mx = fams["deepdfa_serve_latency_seconds_max"]
    assert mx["type"] == "gauge"
    assert mx["samples"] == [("", 0.2)]


# ---------------------------------------------------------------------------
# Prometheus exposition


def test_slo_exposition_parses_and_labels():
    clock = {"t": 50.0}
    eng = SloEngine(windows=(60,), clock=lambda: clock["t"])
    eng.observe_request(200, 0.05, queue_s=0.01, device_s=0.03)
    eng.observe_request(404, 0.01)
    eng.set_queue_depth(3)
    eng.observe_hot_swap()
    families = obs_slo.parse_exposition(eng.exposition())
    lat = families["deepdfa_serve_slo_latency_ms"]
    assert lat["type"] == "gauge" and lat["tag"] == "serve_slo/latency_ms"
    assert any('quantile="0.99"' in lbl for lbl, _ in lat["samples"])
    status = families["deepdfa_serve_requests_by_status_total"]
    assert status["type"] == "counter"
    assert (
        sorted(status["samples"])
        == [('{status="200"}', 1.0), ('{status="404"}', 1.0)]
    )
    assert (
        families["deepdfa_serve_slo_queue_depth"]["samples"][0][1] == 3.0
    )
    assert (
        families["deepdfa_serve_slo_hot_swaps_total"]["samples"][0][1]
        == 1.0
    )


def test_registry_exposition_counters_monotone_and_declared():
    """Scrape twice with traffic in between: every counter sample is
    non-decreasing, every family parses, and every family's tag is
    schema-declared (the check_obs_schema --metrics contract)."""
    from deepdfa_tpu.obs import metrics as obs_metrics

    sys.path.insert(0, str(REPO / "scripts"))
    import check_obs_schema

    reg = obs_metrics.MetricsRegistry()
    reg.counter("serve/requests").inc(3)
    reg.gauge("serve/queue_depth").set(2)
    reg.histogram("serve/latency_seconds").observe(0.05)

    def counters(text):
        out = {}
        for name, fam in obs_slo.parse_exposition(text).items():
            if fam["type"] == "counter":
                for lbl, v in fam["samples"]:
                    out[name + lbl] = v
        return out

    scrape1 = obs_slo.registry_exposition(reg)
    reg.counter("serve/requests").inc(2)
    reg.histogram("serve/latency_seconds").observe(0.07)
    scrape2 = obs_slo.registry_exposition(reg)
    c1, c2 = counters(scrape1), counters(scrape2)
    assert c1 and all(c2[k] >= v for k, v in c1.items())

    result = check_obs_schema.check_metrics_scrape(scrape2)
    assert result["ok"], result
    assert result["families"] >= 3

    # an undeclared registry tag fails the scrape validation
    reg.counter("totally/new_metric").inc()
    bad = check_obs_schema.check_metrics_scrape(
        obs_slo.registry_exposition(reg)
    )
    assert not bad["ok"]
    assert any("totally/new_metric" in u for u in bad["undeclared"])

    # malformed exposition text is a parse error, not a pass
    assert not check_obs_schema.check_metrics_scrape(
        "deepdfa_x{unclosed 1\n"
    )["ok"]


# ---------------------------------------------------------------------------
# backend health


def test_backend_health_probe_timeout_path():
    """A probe that times out is a WEDGE (service hung), retried the
    configured number of times, and lands in the backend/* metrics —
    the /healthz?deep=1 failure path without a real 60s subprocess."""
    from deepdfa_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    calls = []

    def fake_probe(timeout_s):
        calls.append(timeout_s)
        return False, (
            f"backend probe timed out after {timeout_s:.0f}s "
            "(compile service wedged?)"
        )

    h = obs_health.BackendHealth(probe_fn=fake_probe, registry=reg)
    report = h.probe(timeout_s=5.0, retries=2)
    assert calls == [5.0, 5.0, 5.0]
    assert report["ok"] is False
    assert report["wedged"] is True
    assert report["attempts"] == 3
    snap = reg.snapshot()
    assert snap["backend/probes"] == 3
    assert snap["backend/probe_failures"] == 3
    assert snap["backend/probe_retries"] == 2
    assert snap["backend/wedges"] == 3
    assert snap["backend/healthy"] == 0.0
    assert snap["backend/probe_seconds/count"] == 3
    assert h.last()["wedged"] is True

    h.record_fallback("wedged; falling back to cpu")
    assert reg.snapshot()["backend/fallbacks"] == 1
    assert h.last()["fallback"] is True


def test_backend_health_probe_recovery_and_fast_failure():
    from deepdfa_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    outcomes = [(False, "backend probe rc=1: tunnel down"), (True, "tpu")]
    h = obs_health.BackendHealth(
        probe_fn=lambda t: outcomes.pop(0), registry=reg
    )
    report = h.probe(timeout_s=1.0, retries=3)
    assert report["ok"] and report["platform"] == "tpu"
    assert report["attempts"] == 2
    snap = reg.snapshot()
    # rc!=0 is a fast failure, NOT a wedge (different operator action)
    assert snap["backend/wedges"] == 0
    assert snap["backend/probe_failures"] == 1
    assert snap["backend/healthy"] == 1.0


# ---------------------------------------------------------------------------
# bench regression gate


def _trajectory():
    return [{
        "source": "BENCH_r01.json", "round": 1,
        "record": {
            "metric": "deepdfa_infer_graphs_per_sec", "value": 4000.0,
            "platform": "tpu", "train_graphs_per_sec": 3000.0,
            "serve_latency_p99_ms": 10.0,
        },
    }]


def test_bench_gate_pass_regression_fallback():
    from deepdfa_tpu.obs import bench_gate as bg

    traj = _trajectory()
    ok = bg.gate(
        {"value": 3900.0, "platform": "tpu",
         "train_graphs_per_sec": 2950.0, "serve_latency_p99_ms": 11.0},
        traj,
    )
    assert ok["verdict"] == "pass" and not ok["failure_classes"]
    assert {c["metric"] for c in ok["checks"]} == {
        "value", "train_graphs_per_sec", "serve_latency_p99_ms"
    }

    slow = bg.gate({"value": 3000.0, "platform": "tpu"}, traj)
    assert slow["verdict"] == "fail"
    assert slow["failure_classes"] == ["regression"]
    bad = [c for c in slow["checks"] if not c["ok"]]
    assert [c["metric"] for c in bad] == ["value"]

    # lower-is-better metric regresses UPWARD
    lat = bg.gate(
        {"value": 4000.0, "platform": "tpu",
         "serve_latency_p99_ms": 20.0}, traj,
    )
    assert "regression" in lat["failure_classes"]

    fb = bg.gate(
        {"value": 300.0, "platform": "cpu",
         "fallback_from": "probe: backend probe timed out"},
        traj,
    )
    assert fb["verdict"] == "fail"
    assert fb["failure_classes"] == ["cpu_fallback"]
    assert not fb["checks"]  # never judged against the tpu baseline

    wrong = bg.gate(
        {"value": 500.0, "platform": "cpu"}, traj,
        expect_platform="tpu",
    )
    assert "cpu_fallback" in wrong["failure_classes"]

    md = bg.render_markdown(slow, {"metric": "m", "value": 3000.0})
    assert "FAIL" in md and "regression" in md and "| value |" in md


def test_bench_gate_reference_skips_fallback_records():
    """A fallback record in the trajectory must never become the
    baseline (the silent-rebaseline bug class), and an embedded
    last_healthy_tpu capture wins for tpu candidates."""
    from deepdfa_tpu.obs import bench_gate as bg

    traj = _trajectory() + [{
        "source": "BENCH_r02.json", "round": 2,
        "record": {
            "value": 100.0, "platform": "cpu",
            "fallback_from": "wedged",
            "last_healthy_tpu": {
                "artifact": "BENCH_TPU_X.json",
                "bench": {"value": 4500.0, "platform": "tpu"},
            },
        },
    }]
    ref = bg.reference_for(traj, "tpu")
    assert ref["record"]["value"] == 4500.0
    assert "last_healthy_tpu" in ref["source"]
    assert bg.reference_for(traj, "cpu") is None  # fallback != baseline

    # a committed candidate must not be judged against itself: with r01
    # excluded there is no earlier tpu reference at all, and a regressed
    # r01 re-gated WITHOUT exclusion would pass vacuously (ratio 1.0)
    assert bg.reference_for(
        _trajectory(), "tpu", exclude_source="BENCH_r01.json"
    ) is None
    self_cmp = bg.gate(
        _trajectory()[0]["record"], _trajectory(),
        exclude_source="BENCH_r01.json",
    )
    assert not self_cmp["checks"]
    assert any("no healthy" in n for n in self_cmp["notes"])


def test_bench_gate_loads_real_trajectory_and_smoke():
    """The committed BENCH_r*/BENCH_TPU_* artifacts parse (r1's failed
    round and r5's truncated tail degrade to notes, not crashes), and
    the script smoke self-check passes — the tier-1 wiring."""
    from deepdfa_tpu.obs import bench_gate as bg

    traj = bg.load_trajectory(REPO)
    by_source = {e["source"]: e for e in traj}
    assert by_source["BENCH_r01.json"]["record"] is None
    assert by_source["BENCH_r02.json"]["record"]["platform"] == "cpu"
    assert (
        bg.classify(by_source["BENCH_r02.json"]["record"])
        == "cpu_fallback"
    )
    assert any(e["source"].startswith("BENCH_TPU_") for e in traj)

    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_gate.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    assert "bench_gate smoke OK" in proc.stdout


def test_bench_gate_cli_fallback_exit_code(tmp_path):
    """Gating a CPU-fallback record exits 2 — the class the driver
    pages on differently (sick backend, not slow code)."""
    rec = tmp_path / "rec.json"
    rec.write_text(json.dumps({
        "metric": "deepdfa_infer_graphs_per_sec", "value": 370.0,
        "platform": "cpu", "fallback_from": "probe timed out",
    }))
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_gate.py"),
         "--record", str(rec), "--out", str(tmp_path / "verdict.json")],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 2, (proc.stdout + proc.stderr)[-1500:]
    verdict = json.loads((tmp_path / "verdict.json").read_text())
    assert verdict["failure_classes"] == ["cpu_fallback"]


# ---------------------------------------------------------------------------
# request-scoped trace linkage (batcher level; the full HTTP path is
# asserted by the serve --smoke CLI test)


def test_request_flow_linkage_in_merged_trace(tmp_path):
    """With tracing on, a scored request's queue-wait and device spans
    in the merged trace both carry its request_id, and its flow chain
    (s at the frontend span, t in the queue window, f in the device
    span) shares that id — one request, one linked arrow chain."""
    jax = pytest.importorskip("jax")
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.serve.batcher import DynamicBatcher, GgnnExecutor

    synth = generate(6, seed=5)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(6), limit_all=50,
        limit_subkeys=50,
    )
    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
    ])
    model = DeepDFA.from_config(
        cfg.model, input_dim=cfg.data.feat.input_dim
    )
    params = model.init(jax.random.key(0), pack([], 1, 2048, 8192))
    executor = GgnnExecutor(
        model, lambda: params, node_budget=2048, edge_budget=8192,
        max_batch_graphs=4,
    )
    executor.warmup()

    tdir = tmp_path / "trace"
    rids = [f"test-{i}" for i in range(len(specs))]
    trace.enable(tdir, process_name="test")
    try:
        for rid in rids:
            # what ScoringService.submit_code emits around the frontend
            with trace.span("frontend", cat="serve", request_id=rid):
                trace.flow("request", rid, "s", cat="serve")
        batcher = DynamicBatcher(executor, queue_limit=64)
        reqs = batcher.score_all(specs, request_ids=rids)
        assert all(r.error is None for r in reqs)
        # stage attribution landed on every request
        assert all(
            r.queue_wait_s is not None and r.device_s is not None
            and r.batch_size >= 1
            for r in reqs
        )
    finally:
        trace.disable()

    events = trace.merge(tdir)
    rid = rids[0]
    frontend = [
        e for e in events if e.get("ph") == "X"
        and e.get("name") == "frontend"
        and (e.get("args") or {}).get("request_id") == rid
    ]
    queue = [
        e for e in events if e.get("ph") == "X"
        and e.get("name") == "queue_wait"
        and (e.get("args") or {}).get("request_id") == rid
    ]
    device = [
        e for e in events if e.get("ph") == "X"
        and e.get("name") == "device_execute"
        and rid in ((e.get("args") or {}).get("request_ids") or [])
    ]
    assert frontend and queue and device
    flows = {
        e["ph"] for e in events
        if e.get("id") == rid and e.get("ph") in ("s", "t", "f")
    }
    assert flows == {"s", "t", "f"}
    # the device span records the batch signature it executed
    assert device[0]["args"]["batch_size"] >= 1
    assert "signature" in device[0]["args"]
