"""Tier-1 wiring for scripts/check_obs_schema.py: a smoke train run's
emitted metric tags must all be declared in the obs schema
(deepdfa_tpu/obs/metrics.py:SCHEMA) — adding a record key without
declaring it fails here instead of silently growing an undocumented
TensorBoard tag (ISSUE 4 satellite)."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run(args, tmp_path, timeout=420):
    env = dict(
        os.environ,
        DEEPDFA_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
        DEEPDFA_TPU_STORAGE=str(tmp_path),
    )
    return subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_schema.py"),
         *args],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout,
    )


def test_check_obs_schema_smoke(tmp_path):
    out = tmp_path / "schema.json"
    proc = _run(["--smoke", "--out", str(out)], tmp_path)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    record = json.loads(out.read_text())
    assert record["ok"] is True
    assert record["undeclared"] == []
    assert record["records"] >= 2  # step records + the epoch record
    assert record["tags"] >= 10


def test_check_obs_schema_flags_drift(tmp_path):
    """An undeclared tag in an existing log is reported and fails."""
    log = tmp_path / "train_log.jsonl"
    log.write_text(
        json.dumps({"epoch": 0, "train_loss": 0.5,
                    "sneaky_new_metric": 1.0}) + "\n"
    )
    proc = _run(["--log", str(log)], tmp_path, timeout=120)
    assert proc.returncode == 1
    record = json.loads(proc.stdout.splitlines()[0])
    assert record["ok"] is False
    assert "sneaky_new_metric" in record["undeclared"]
