"""Frontend fidelity vs hand-specified Joern exports (VERDICT r1 item 7)."""

import json

import pytest

from deepdfa_tpu.frontend.fidelity import (
    agreement_report,
    compare_cpgs,
    fidelity_against_joern,
)
from tests.joern_fixtures import BUILDERS, SOURCES


def test_identical_cpgs_score_one():
    from deepdfa_tpu.frontend.parser import parse_function

    cpg = parse_function(SOURCES["if_else"])
    m = compare_cpgs(cpg, cpg)
    assert m["stmt_line_jaccard"] == 1.0
    assert m["cfg_edge_jaccard"] == 1.0
    assert m["def_line_jaccard"] == 1.0
    assert m["hash_agreement"] == 1.0


@pytest.mark.parametrize("name", sorted(SOURCES))
def test_parser_agrees_with_joern_fixture(tmp_path, name):
    prefix = BUILDERS[name](tmp_path)
    report = fidelity_against_joern(
        {name: SOURCES[name]}, joern_prefixes={name: prefix}
    )
    m = report["per_example"][name]
    # measured 1.0 on every fixture (docs/FIDELITY.md); floors at 0.95
    # so a real regression in branch/loop/switch plumbing cannot hide
    assert m["stmt_line_jaccard"] >= 0.95, m
    assert m["def_line_jaccard"] >= 0.99, m
    assert m["cfg_edge_jaccard"] >= 0.95, m
    assert m["hash_agreement"] >= 0.99, m


def test_agreement_report_aggregates(tmp_path):
    from deepdfa_tpu.frontend.joern_io import load_joern_cpg
    from deepdfa_tpu.frontend.parser import parse_function

    pairs = []
    for name, builder in BUILDERS.items():
        prefix = builder(tmp_path)
        pairs.append(
            (name, parse_function(SOURCES[name]), load_joern_cpg(prefix))
        )
    report = agreement_report(pairs)
    assert report["n_examples"] == len(BUILDERS)
    assert set(report["mean"]) == {
        "stmt_line_jaccard", "cfg_edge_jaccard", "def_line_jaccard",
        "hash_agreement", "rd_in_jaccard",
    }
    assert json.dumps(report)  # serializable


def test_rd_in_jaccard_detects_edge_divergence():
    """The reaching-defs agreement metric is 1.0 on identical CPGs and
    drops when a CFG edge changes the flow of a definition."""
    from deepdfa_tpu.frontend.cpg import CFG
    from deepdfa_tpu.frontend.parser import parse_function

    cpg = parse_function(SOURCES["if_else"])
    assert compare_cpgs(cpg, cpg)["rd_in_jaccard"] == 1.0

    import copy

    mutated = copy.deepcopy(cpg)
    # sever the control flow entirely: no definition reaches anything, so
    # the line-keyed IN sets must diverge from the intact CPG's
    mutated.edges[:] = [e for e in mutated.edges if e[2] != CFG]
    m = compare_cpgs(cpg, mutated)
    assert m["rd_in_jaccard"] < 1.0
