"""End-to-end pipeline test: synthetic corpus -> graphs -> training -> F1.

This is the framework's analog of the reference's sample-mode smoke path
(SURVEY.md §4: 200-example stratified sample as de-facto integration test),
but it goes further: the model must actually learn to separate the injected
vulnerability patterns.
"""

import pytest

import numpy as np

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, split_ids, to_examples
from deepdfa_tpu.data.diffs import diff_lines
from deepdfa_tpu.graphs import pack_shards
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train import GraphTrainer, undersample_epoch


def test_diff_lines():
    before = "a\nb\nc\nd\n"
    after = "a\nB\nc\nd\ne\n"
    removed, added = diff_lines(before, after)
    assert removed == {2}
    assert added == {2, 5}


def test_synthetic_corpus_properties():
    synth = generate(200, vuln_rate=0.3, seed=1)
    assert len(synth) == 200
    pos = [s for s in synth if s.label]
    assert 30 <= len(pos) <= 90
    for s in pos:
        assert s.vuln_lines, "vulnerable example must have changed lines"
        assert s.before != s.after


def test_pipeline_extracts_most_graphs():
    synth = generate(100, vuln_rate=0.2, seed=2)
    specs, vocabs = build_dataset(
        to_examples(synth), train_ids=range(100), limit_all=100, limit_subkeys=100
    )
    assert len(specs) >= 95  # parser should handle all generated code
    # vuln node labels only on positive graphs
    by_label = {int(s.label): 0 for s in specs}
    for s in specs:
        if s.label == 0:
            assert s.node_vuln.sum() == 0
        else:
            assert s.node_vuln.sum() > 0, s.graph_id
    # def features present: some nodes have nonzero vocab indices
    assert any((s.node_feats > 0).any() for s in specs)


@pytest.mark.slow  # e2e training: slow lane
def test_end_to_end_training_beats_chance():
    n = 400
    synth = generate(n, vuln_rate=0.25, seed=3)
    train_ids, val_ids, test_ids = split_ids(n, seed=0)
    specs, vocabs = build_dataset(
        to_examples(synth), train_ids=train_ids, limit_all=200, limit_subkeys=200
    )
    by_id = {s.graph_id: s for s in specs}
    train = [by_id[i] for i in train_ids if i in by_id]
    test = [by_id[i] for i in test_ids if i in by_id]

    cfg = config_mod.apply_overrides(
        Config(),
        [
            "model.hidden_dim=16",
            "train.max_epochs=18",
            "train.optim.learning_rate=0.005",
        ],
    )
    mesh = make_mesh(MeshConfig(dp=8))
    model = DeepDFA.from_config(cfg.model, input_dim=202)
    trainer = GraphTrainer(model, cfg, mesh=mesh)

    labels = np.array([s.label for s in train])
    BS = 32  # graphs per global batch (4 per dp shard)

    def epoch_batches(epoch):
        idx = undersample_epoch(labels, epoch, seed=0)
        sel = [train[i] for i in idx]
        return [
            pack_shards(sel[k : k + BS], 8, BS // 8, 1024, 4096)
            for k in range(0, len(sel) - len(sel) % BS, BS)
        ]

    def eval_batches():
        sel = test + test[: (-len(test)) % BS]
        return [
            pack_shards(sel[k : k + BS], 8, BS // 8, 1024, 4096)
            for k in range(0, len(sel), BS)
        ]

    state = trainer.init_state(epoch_batches(0)[0])
    state = trainer.fit(state, epoch_batches)
    metrics, _ = trainer.evaluate(state, eval_batches())
    # injected patterns are cleanly separable; require strong recovery
    assert metrics["f1"] > 0.9, metrics


def test_cfg_dep_gtype_emits_typed_edges():
    """gtype="cfg+dep" adds data/control-dependence relations as typed
    edges (reference gtype/rdg experiment axis, joern.py:419-441)."""
    code = """
int f(int a) {
  int x = a + 1;
  int y = 0;
  if (x > 2) {
    y = x * 3;
  }
  return y;
}
"""
    from deepdfa_tpu.data.pipeline import extract_graph

    cfg_only = extract_graph(code, 0, gtype="cfg")
    typed = extract_graph(code, 0, gtype="cfg+dep")
    assert cfg_only.edge_type is None
    assert typed.edge_type is not None
    kinds = set(np.asarray(typed.edge_type).tolist())
    assert 0 in kinds and (1 in kinds or 2 in kinds), kinds
    # cfg relation is preserved verbatim as type 0
    cfg_edges = {
        (int(s), int(d))
        for s, d, t in zip(typed.edge_src, typed.edge_dst, typed.edge_type)
        if t == 0
    }
    assert cfg_edges == {
        (int(s), int(d))
        for s, d in zip(cfg_only.edge_src, cfg_only.edge_dst)
    }


@pytest.mark.slow  # e2e training: slow lane
def test_end_to_end_training_cfg_dep_n_etypes():
    """The typed-edge pipeline trains end to end with an n_etypes=3 GGNN."""
    import jax

    synth = generate(24, vuln_rate=0.4, seed=11)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(24), limit_all=64,
        limit_subkeys=64, gtype="cfg+dep",
    )
    assert specs and all(s.edge_type is not None for s in specs)
    cfg = config_mod.apply_overrides(
        Config(), ["model.hidden_dim=8", "model.n_etypes=3"]
    )
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
    model = DeepDFA.from_config(cfg.model, input_dim=66, hidden_dim=8)
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    batch = pack_shards(specs, 2, 24, 1024, 8192)
    state = trainer.init_state(batch)
    losses = []
    for _ in range(8):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(jax.device_get(loss)))
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses


def test_pdg_gtype_single_relation():
    """gtype="pdg": dependence edges merged into one untyped relation
    (reference rdg("pdg"), joern.py:419-441)."""
    code = """
int f(int a) {
  int x = a + 1;
  int y = 0;
  if (x > 2) {
    y = x * 3;
  }
  return y;
}
"""
    from deepdfa_tpu.data.pipeline import extract_graph

    pdg = extract_graph(code, 0, gtype="pdg")
    typed = extract_graph(code, 0, gtype="cfg+dep")
    assert pdg.edge_type is None  # single relation
    # pdg edge set == the dependence (type 1/2) edges of cfg+dep
    dep_edges = {
        (int(s), int(d))
        for s, d, t in zip(typed.edge_src, typed.edge_dst, typed.edge_type)
        if t != 0
    }
    got = set(zip(pdg.edge_src.tolist(), pdg.edge_dst.tolist()))
    assert got == dep_edges and got
