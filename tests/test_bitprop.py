"""Differentiable RD propagation == the exact worklist solver at fixpoint."""

import numpy as np
import pytest

from deepdfa_tpu.frontend import parse_function
from deepdfa_tpu.nn.bitprop import BitvectorPropagation, rd_bit_problem

PROGRAMS = [
    """
int f(int a) {
    int x = 1;
    if (a) { x = 2; } else { a = 3; }
    while (a--) { x += 1; }
    return x + a;
}
""",
    """
int g(int n) {
    int i = 0, s = 0;
    for (i = 0; i < n; i++) { s += i; }
    if (s > 10) { s = 10; }
    return s;
}
""",
    """
int h(int a) {
    int r = 0;
    switch (a) { case 1: r = 1; break; default: r = 2; }
    goto out;
out:
    return r;
}
""",
]


@pytest.mark.parametrize("union_type", ["simple", "relu"])
@pytest.mark.parametrize("code", PROGRAMS, ids=range(len(PROGRAMS)))
def test_matches_exact_solver(code, union_type):
    import jax

    cpg = parse_function(code)
    prob = rd_bit_problem(cpg, max_defs=64)
    assert prob is not None
    n = prob["n_nodes"]
    model = BitvectorPropagation(n_steps=n + 1, union_type=union_type)
    mask = np.ones_like(prob["edge_src"], bool)
    params = model.init(
        jax.random.key(0),
        prob["gen"], prob["kill"], prob["edge_src"], prob["edge_dst"], mask,
    )
    in_, out = model.apply(
        params,
        prob["gen"], prob["kill"], prob["edge_src"], prob["edge_dst"], mask,
    )
    np.testing.assert_allclose(np.asarray(in_), prob["labels_in"], atol=1e-5)
    np.testing.assert_allclose(np.asarray(out), prob["labels_out"], atol=1e-5)


def test_learned_gate_is_differentiable():
    import jax
    import jax.numpy as jnp

    cpg = parse_function(PROGRAMS[0])
    prob = rd_bit_problem(cpg, max_defs=64)
    model = BitvectorPropagation(n_steps=6, learned_gate=True)
    mask = np.ones_like(prob["edge_src"], bool)
    params = model.init(
        jax.random.key(0),
        prob["gen"], prob["kill"], prob["edge_src"], prob["edge_dst"], mask,
    )

    def loss(p):
        in_, out = model.apply(
            p, prob["gen"], prob["kill"], prob["edge_src"],
            prob["edge_dst"], mask,
        )
        return jnp.mean((in_ - prob["labels_in"]) ** 2)

    g = jax.grad(loss)(params)
    leaves = jax.tree.leaves(g)
    assert leaves and all(np.isfinite(np.asarray(l)).all() for l in leaves)
    # gate gradient is non-trivial
    assert any(float(np.abs(np.asarray(l)).max()) > 0 for l in leaves)


def test_too_many_defs_returns_none():
    body = "".join(f"x{i} = {i};\n" for i in range(70))
    cpg = parse_function("int f(void) {\nint " + ",".join(f"x{i}" for i in range(70)) + ";\n" + body + "return x0;\n}")
    assert rd_bit_problem(cpg, max_defs=64) is None
    assert rd_bit_problem(cpg, max_defs=128) is not None
