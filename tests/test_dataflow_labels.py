"""End-to-end `dataflow_solution_{in,out}` label styles.

The reference's experimental supervision mode (DDFA/code_gnn/models/
base_module.py:83-95): instead of vulnerability labels, the GGNN is
supervised to predict the exact reaching-definitions solution as per-node
bitvectors. Here the labels come from the worklist solver
(frontend/reaching.py) via nn/bitprop.rd_bit_problem, flow through
extraction -> GraphStore -> packing -> GraphTrainer with static [N, B]
shapes, and the model mixes differentiable bitvector propagation
(nn/bitprop.py) into its features.
"""

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, to_examples
from deepdfa_tpu.graphs import (
    GraphStore,
    pack,
    pack_shards,
    shard_bucket_batches,
)
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train import GraphTrainer

MAX_DEFS = 16


@pytest.fixture(scope="module")
def corpus():
    synth = generate(48, vuln_rate=0.25, seed=11)
    specs, vocabs = build_dataset(
        to_examples(synth),
        train_ids=range(48),
        limit_all=64,
        limit_subkeys=64,
        max_defs=MAX_DEFS,
    )
    return specs


def test_specs_carry_bit_labels(corpus):
    assert corpus
    for s in corpus:
        assert s.node_gen is not None and s.node_gen.shape == (
            s.num_nodes, MAX_DEFS,
        )
        assert s.node_kill.shape == (s.num_nodes, MAX_DEFS)
        assert s.node_bits_in.shape == (s.num_nodes, MAX_DEFS)
        assert s.node_bits_out.shape == (s.num_nodes, MAX_DEFS)
        # OUT ⊇ gen, and bits are 0/1
        assert set(np.unique(s.node_bits_out)) <= {0.0, 1.0}
        assert np.all(s.node_bits_out >= s.node_gen)


def test_store_roundtrips_bits(tmp_path, corpus):
    store = GraphStore(tmp_path / "g")
    store.write(corpus)
    back = store.load_all()
    for s in corpus:
        r = back[s.graph_id]
        np.testing.assert_array_equal(r.node_gen, s.node_gen)
        np.testing.assert_array_equal(r.node_kill, s.node_kill)
        np.testing.assert_array_equal(r.node_bits_in, s.node_bits_in)
        np.testing.assert_array_equal(r.node_bits_out, s.node_bits_out)


def test_pack_carries_bits(corpus):
    b = pack(corpus[:4], num_graphs=4, node_budget=256, edge_budget=1024)
    assert b.node_gen.shape == (256, MAX_DEFS)
    n0 = corpus[0].num_nodes
    np.testing.assert_array_equal(
        np.asarray(b.node_bits_in)[:n0], corpus[0].node_bits_in
    )
    # padding rows are zero
    total = sum(g.num_nodes for g in corpus[:4])
    assert np.asarray(b.node_gen)[total:].sum() == 0


def test_pack_rejects_mixed_bit_presence(corpus):
    import dataclasses

    from deepdfa_tpu.graphs.batch import GraphSpec  # noqa: F401

    stripped = dataclasses.replace(
        corpus[0], node_gen=None, node_kill=None, node_bits_in=None,
        node_bits_out=None,
    )
    with pytest.raises(ValueError):
        pack(
            [stripped, corpus[1]], num_graphs=2, node_budget=256,
            edge_budget=1024,
        )


@pytest.mark.slow  # e2e training: slow lane
@pytest.mark.parametrize("style", ["dataflow_solution_in", "dataflow_solution_out"])
def test_dataflow_style_trains_and_beats_random(corpus, style):
    """VERDICT round-1 item 4: the style must train end to end to finite
    loss and beat chance. Bit labels are highly structured (OUT ⊇ gen), so
    the bar is masked-bit accuracy well above the all-zeros/chance rate
    AND improvement over the untrained model."""
    cfg = config_mod.apply_overrides(
        Config(),
        [
            "model.hidden_dim=8",
            f"model.label_style={style}",
            "train.max_epochs=8",
            "train.optim.learning_rate=0.01",
        ],
    )
    mesh = make_mesh(MeshConfig(dp=8))
    model = DeepDFA.from_config(cfg.model, input_dim=66)
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    batches = list(
        shard_bucket_batches(
            corpus, num_shards=8, num_graphs=8, node_budget=256,
            edge_budget=1024, oversized="raise",
        )
    )
    state = trainer.init_state(batches[0])
    m0, _ = trainer.evaluate(state, batches)
    state = trainer.fit(state, lambda epoch: batches)
    m1, _ = trainer.evaluate(state, batches)
    assert np.isfinite(m1["loss"]), m1
    assert m1["loss"] < m0["loss"] * 0.7, (m0["loss"], m1["loss"])
    assert m1["f1"] > 0.5, m1  # all-zeros predictor scores f1 = 0
