"""Aux component tests: set-union ops, IVDetect tokenise, localization."""

import numpy as np
import pytest

from deepdfa_tpu.frontend.tokenise import tokenise, tokenise_lines
from deepdfa_tpu.nn.setops import relu_union, segment_union, simple_union

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def test_union_semantics(rng):
    import jax.numpy as jnp

    a = jnp.array([0.0, 0.0, 1.0, 1.0, 0.3])
    b = jnp.array([0.0, 1.0, 0.0, 1.0, 0.4])
    np.testing.assert_allclose(simple_union(a, b), [0, 1, 1, 1, 0.58])
    np.testing.assert_allclose(relu_union(a, b), [0, 1, 1, 1, 0.7])
    # relu union == min(a+b, 1) (reference test_smoothness algebra)
    x = rng.uniform(-2, 2, 50).astype(np.float32)
    y = rng.uniform(-2, 2, 50).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(relu_union(jnp.array(x), jnp.array(y))),
        np.minimum(x + y, 1.0),
        rtol=1e-6,
    )


def test_segment_union_matches_fold(rng):
    import jax.numpy as jnp

    n, e, d = 4, 10, 6
    init = rng.uniform(0, 1, (n, d)).astype(np.float32)
    msgs = rng.uniform(0, 1, (e, d)).astype(np.float32)
    seg = rng.integers(0, n, (e,))
    mask = rng.random(e) > 0.3
    for union_type, op in [("simple", simple_union), ("relu", relu_union)]:
        got = np.asarray(
            segment_union(
                jnp.array(msgs), jnp.array(init), jnp.array(seg),
                jnp.array(mask), union_type,
            )
        )
        want = init.copy()
        for i in range(e):
            if mask[i]:
                want[seg[i]] = np.asarray(
                    op(jnp.array(want[seg[i]]), jnp.array(msgs[i]))
                )
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_tokenise_ivdetect():
    # the reference docstring example
    out = tokenise("FooBar fooBar foo bar_blub23/x~y'z")
    assert "Foo" in out and "Bar" in out
    assert "foo" in out and "blub23" in out
    # single chars dropped
    assert " x" not in f" {out} "
    lines = tokenise_lines("line1a line1b\nline2a asdf\nf f f f f\na")
    assert len(lines) == 2  # single-char-only lines vanish


def test_localization_end_to_end(rng):
    """Saliency + attention scores flow through line aggregation into the
    statement metrics."""
    import jax

    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.eval.localize import (
        aggregate_line_scores,
        attention_token_scores,
        combined_saliency_scores,
    )
    from deepdfa_tpu.eval.statements import RankedExample, statement_report
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig

    code = "int f(int a) {\n  int x = a;\n  strcpy(b, c);\n  return x;\n}"
    tok = HashTokenizer(vocab_size=256)
    ids, tok_lines = tok.encode_with_lines(code, max_length=32)
    ids = ids[None]

    mcfg = cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(vocab_size=256, dropout_rate=0.0),
        graph_hidden_dim=8,
        graph_input_dim=52,
        head_dropout=0.0,
        use_graph=False,
    )
    params = cmb.init_params(mcfg, jax.random.key(0))

    att = attention_token_scores(mcfg.encoder, params["encoder"], ids)
    assert att.shape == ids.shape
    assert np.isfinite(att).all()

    sal = combined_saliency_scores(mcfg, params, ids)
    assert sal.shape == ids.shape
    assert np.isfinite(sal).all()
    assert sal.max() > 0

    n_lines = 5
    line_scores = aggregate_line_scores(sal[0], tok_lines, n_lines)
    assert line_scores.shape == (n_lines,)
    flagged = np.zeros(n_lines, bool)
    flagged[2] = True  # the strcpy line
    rep = statement_report([RankedExample(line_scores, flagged)])
    assert 0 <= rep["top_10_acc"] <= 1


def test_tokenizer_line_maps():
    from deepdfa_tpu.data.tokenizer import HashTokenizer

    tok = HashTokenizer(vocab_size=256)
    ids, lines = tok.encode_with_lines("aa bb\ncc\n\ndd", max_length=16)
    # specials have line 0; tokens map to 1,1,2,4
    toks = [int(l) for l, i in zip(lines, ids) if l > 0]
    assert toks == [1, 1, 2, 4]


def test_bpe_line_map_matches_reference_assets():
    from pathlib import Path

    ref = Path("/root/reference/LineVul/linevul/bpe_tokenizer")
    if not ref.exists():
        pytest.skip("no local BPE assets")
    from deepdfa_tpu.data.tokenizer import BpeTokenizer

    tok = BpeTokenizer(
        ref / "bpe_tokenizer-vocab.json", ref / "bpe_tokenizer-merges.txt"
    )
    code = "int f() {\n  return g(x);\n}"
    ids, lines = tok.encode_with_lines(code, max_length=32)
    ids2 = tok.encode(code, max_length=32)
    np.testing.assert_array_equal(ids, ids2)
    body = [int(l) for l in lines if l > 0]
    assert min(body) == 1 and max(body) == 3


def test_explanation_method_family(rng):
    """All gradient methods produce finite, non-degenerate token scores on
    both combined architectures (reference reasoning_method family,
    unixcoder/linevul_main.py:513-516)."""
    import jax
    import pytest as _pytest

    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.eval.localize import GRADIENT_METHODS, token_scores
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.models.transformer import TransformerConfig

    code = "int f(int a) {\n  int x = a;\n  strcpy(b, c);\n  return x;\n}"

    # roberta-combined (no graph)
    tok = HashTokenizer(vocab_size=256)
    ids = tok.encode(code, max_length=24)[None]
    mcfg = cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(vocab_size=256, dropout_rate=0.0),
        graph_hidden_dim=8, graph_input_dim=52, head_dropout=0.0,
        use_graph=False,
    )
    params = cmb.init_params(mcfg, jax.random.key(0))
    for method in GRADIENT_METHODS:
        s = token_scores(method, "roberta", mcfg, params, ids, n_steps=4,
                         n_samples=2)
        assert s.shape == ids.shape, method
        assert np.isfinite(s).all(), method
        assert np.abs(s).max() > 0, method

    # t5-defect (eos pooling), attention must be rejected
    tok5 = HashTokenizer(vocab_size=256, t5_frame=True)
    ids5 = tok5.encode(code, max_length=24)[None]
    dcfg = t5m.DefectConfig(
        encoder=t5m.T5Config.tiny(dropout_rate=0.0, remat=False),
        use_graph=False,
    )
    dparams = t5m.init_defect_params(dcfg, jax.random.key(1))
    for method in ("saliency", "lig", "deeplift"):
        s = token_scores(method, "t5", dcfg, dparams, ids5, n_steps=4)
        assert s.shape == ids5.shape and np.isfinite(s).all(), method
    with _pytest.raises(ValueError):
        token_scores("attention", "t5", dcfg, dparams, ids5)


def test_deeplift_multistep_rescale_exact_and_complete():
    """VERDICT r3 item 7: deeplift is now the n-step rescale. On a linear
    target it is EXACT at every step count (1 step == 32 steps == LIG's
    closed form delta x weight); on a nonlinear target it satisfies
    completeness: sum(attr) -> f(input) - f(baseline) as steps grow."""
    import jax
    import jax.numpy as jnp

    from deepdfa_tpu.eval.localize import _path_attribution

    rng = np.random.default_rng(3)
    rows = jnp.asarray(rng.normal(size=(2, 5, 4)), jnp.float32)
    base = jnp.zeros_like(rows)
    w = jnp.asarray(rng.normal(size=(5, 4)), jnp.float32)

    def linear(r):
        return (r * w).sum()

    g = jax.grad(linear)
    a1 = _path_attribution(g, rows, base, 1)
    a32 = _path_attribution(g, rows, base, 32)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a32), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(a32), np.asarray((rows - base) * w), atol=1e-6
    )

    def mlp(r):
        h = jnp.tanh(r.reshape(2, -1) @ jnp.ones((20, 3), jnp.float32))
        return (h * jnp.asarray([0.5, -1.0, 2.0])).sum()

    g2 = jax.grad(mlp)
    a = _path_attribution(g2, rows, base, 64)
    np.testing.assert_allclose(
        float(a.sum()), float(mlp(rows) - mlp(base)), rtol=1e-3
    )
    # and more steps strictly tightens a coarse approximation
    a_coarse = _path_attribution(g2, rows, base, 1)
    err64 = abs(float(a.sum()) - float(mlp(rows) - mlp(base)))
    err1 = abs(float(a_coarse.sum()) - float(mlp(rows) - mlp(base)))
    assert err64 <= err1 + 1e-6


def test_aggregate_line_scores_signed():
    """Signed attributions must keep their ordering: no zero clamp, and
    token-less lines rank strictly last."""
    from deepdfa_tpu.eval.localize import aggregate_line_scores

    scores = np.array([-0.5, -0.1, 0.3, -0.9])
    lines = np.array([1, 1, 2, 3])
    out = aggregate_line_scores(scores, lines, n_lines=4)
    assert out[0] == -0.1  # max of the signed values, not clamped to 0
    assert out[1] == 0.3
    assert out[2] == -0.9
    assert out[3] < out[2]  # no tokens -> below every tokenized line
