"""Dataset reader tests against reference-shaped inputs."""

import json

import numpy as np
import pandas as pd
import pytest

from deepdfa_tpu.data import readers


def _bigvul_csv(tmp_path, rows):
    df = pd.DataFrame(rows)
    p = tmp_path / "MSR_data_cleaned.csv"
    df.to_csv(p, index=True)
    return p


GOOD_VULN = (
    "int f(char *s) {\n"
    "    char buf[8];\n"
    "    int n = strlen(s);\n"
    "    strcpy(buf, s);\n"
    "    n += 1;\n"
    "    return n;\n"
    "}"
)
GOOD_FIXED = (
    "int f(char *s) {\n"
    "    char buf[8];\n"
    "    int n = strlen(s);\n"
    "    strncpy(buf, s, 7);\n"
    "    n += 1;\n"
    "    return n;\n"
    "}"
)


def test_read_bigvul_filters(tmp_path):
    rows = [
        # clean negative
        {"func_before": "int a(void) { return 1; }", "func_after": "int a(void) { return 1; }", "vul": 0},
        # good vulnerable example
        {"func_before": GOOD_VULN, "func_after": GOOD_FIXED, "vul": 1},
        # vulnerable but no change -> dropped
        {"func_before": GOOD_VULN, "func_after": GOOD_VULN, "vul": 1},
        # vulnerable but truncated (no closing brace) -> dropped
        {"func_before": "int b(void) { return 1;", "func_after": "int b(void) { return 2;", "vul": 1},
        # vulnerable but too short -> dropped
        {"func_before": "int c(void)\n{\nreturn 1;\n}", "func_after": "int c(void)\n{\nreturn 2;\n}", "vul": 1},
    ]
    p = _bigvul_csv(tmp_path, rows)
    exs = readers.read_bigvul(p)
    by_id = {e.id: e for e in exs}
    assert 0 in by_id and by_id[0].label == 0.0
    assert 1 in by_id and by_id[1].label == 1.0
    assert by_id[1].vuln_lines == frozenset({4})  # the strcpy line
    assert 2 not in by_id and 3 not in by_id and 4 not in by_id
    # comments are stripped
    assert "/*" not in by_id[1].code


def test_read_bigvul_sample_stratified(tmp_path):
    """sample=N draws ~N/2 seeded rows PER CLASS (sample_MSR_data.py:6-16)
    — a head() cut of a ~6%-vul corpus would contain almost no positives."""
    rows = [
        {"func_before": f"int f{i}(void)\n{{\nint x = {i};\nreturn x;\n}}",
         "func_after": f"int f{i}(void)\n{{\nint x = {i};\nreturn x;\n}}",
         "vul": 0}
        for i in range(40)
    ]
    # positives at the TAIL so head() would miss them entirely
    rows += [
        {"func_before": GOOD_VULN.replace("n += 1", f"n += {i}"),
         "func_after": GOOD_FIXED.replace("n += 1", f"n += {i}"),
         "vul": 1}
        for i in range(10)
    ]
    p = _bigvul_csv(tmp_path, rows)
    exs = readers.read_bigvul(p, sample=8)
    labels = [e.label for e in exs]
    assert len(exs) == 8
    assert labels.count(1.0) == 4 and labels.count(0.0) == 4
    # seeded: same draw every time
    assert [e.id for e in readers.read_bigvul(p, sample=8)] == [
        e.id for e in exs
    ]


def test_read_devign(tmp_path):
    p = tmp_path / "function.json"
    p.write_text(
        json.dumps(
            [
                {"func": "int x(void) { return 0; } // c", "target": 0},
                {"func": "int y(int a) { return a; }", "target": 1},
            ]
        )
    )
    exs = readers.read_devign(p)
    assert len(exs) == 2
    assert exs[1].label == 1.0
    assert exs[1].vuln_lines == frozenset()
    assert "//" not in exs[0].code


def test_splits_roundtrip(tmp_path):
    df = pd.DataFrame({"id": [0, 1, 2, 3], "split": ["train", "valid", "test", "train"]})
    p = tmp_path / "splits.csv"
    df.to_csv(p, index=False)
    m = readers.read_splits_csv(p)
    assert m == {0: "train", 1: "val", 2: "test", 3: "train"}

    rs = readers.random_splits(range(100), seed=0)
    counts = {s: sum(1 for v in rs.values() if v == s) for s in ("train", "val", "test")}
    assert counts["train"] == 80 and counts["val"] == 10 and counts["test"] == 10
    assert readers.random_splits(range(100), seed=0) == rs


_REF_SPLITS = "/root/reference/DDFA/storage/external/bigvul_rand_splits.csv"


@pytest.mark.skipif(
    not __import__("pathlib").Path(_REF_SPLITS).exists(),
    reason="reference checkout not mounted",
)
def test_reference_rand_splits_artifact_parses():
    """read_splits_csv consumes the reference's REAL committed split
    artifact (bigvul_rand_splits.csv, header `id,label`): all 187,093
    assignments load with the expected 80/10/10 proportions."""
    s = readers.read_splits_csv(_REF_SPLITS)
    assert len(s) == 187_093
    counts = {k: 0 for k in ("train", "val", "test")}
    for v in s.values():
        counts[v] += 1
    assert abs(counts["train"] / len(s) - 0.8) < 0.01
    assert abs(counts["val"] / len(s) - 0.1) < 0.01
    assert abs(counts["test"] / len(s) - 0.1) < 0.01
    # spot-pin a few concrete assignments from the artifact
    assert s[0] == "train" and s[1] == "test" and s[3] == "val"


def test_partition_disjoint():
    from deepdfa_tpu.data.pipeline import Example

    exs = [Example(id=i, code="", label=0.0) for i in range(10)]
    splits = readers.random_splits(range(10), seed=1)
    parts = readers.partition(exs, splits)
    all_ids = [e.id for part in parts.values() for e in part]
    assert sorted(all_ids) == list(range(10))
    assert len(set(all_ids)) == 10


def test_read_mutated_join(tmp_path):
    from deepdfa_tpu.data.pipeline import Example

    base = [
        Example(id=0, code="int a(void) { return 1; }", label=0.0),
        Example(id=5, code="int b(void) { return 2; }", label=1.0,
                vuln_lines=frozenset({1})),
    ]
    p = tmp_path / "c_mutated.jsonl"
    rows = [
        {"idx": 5, "source": "int b_src(void) { return 9; }",
         "target": "int b_tgt(void) { return 9; }"},
        {"idx": 99, "source": "x", "target": "y"},  # not in base -> dropped
    ]
    p.write_text("\n".join(json.dumps(r) for r in rows))

    out = readers.read_mutated(p, base)
    assert len(out) == 1  # inner join
    assert out[0].id == 5 and "b_tgt" in out[0].code
    assert out[0].label == 1.0 and out[0].vuln_lines == frozenset({1})

    flipped = readers.read_mutated(p, base, flip=True)
    assert "b_src" in flipped[0].code


def test_read_dbgbench(tmp_path):
    df = pd.DataFrame(
        {
            "code": ["int f() { return 1; }", "int f() { return 2; }"],
            "c": ["find-1234-buggy.c", "find-1234-patched.c"],
        }
    )
    p = tmp_path / "dbgbench_data_code.csv"
    df.to_csv(p, index=False)
    exs = readers.read_dbgbench(p)
    assert [e.label for e in exs] == [1.0, 0.0]
    assert len({e.id for e in exs}) == 2


def test_mutated_corpus_end_to_end(tmp_path):
    """Mutated-variant flow (reference datasets.py:104-126): base corpus ->
    mutated jsonl join -> features -> eval batches. The cross-dataset
    contract is that mutated examples keep base ids/labels so reference
    vocab + splits apply unchanged."""
    from deepdfa_tpu.data import build_dataset
    from deepdfa_tpu.data.synthetic import generate, to_examples
    from deepdfa_tpu.graphs import bucket_batches

    base = to_examples(generate(20, vuln_rate=0.3, seed=3))
    # mutation: rename a variable everywhere (code changes, labels persist)
    rows = [
        {"idx": e.id, "source": e.code,
         "target": e.code.replace("v0", "mut_v0")}
        for e in base
        if e.id % 2 == 0
    ]
    p = tmp_path / "c_mutated.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))

    mutated = readers.read_mutated(p, base)
    assert len(mutated) == len(rows)
    specs, vocab = build_dataset(
        mutated, train_ids=[e.id for e in mutated], limit_all=100,
        limit_subkeys=100,
    )
    assert len(specs) == len(mutated)
    batches = list(bucket_batches(specs, 8, 1024, 4096, drop_oversized=False))
    total = sum(int(b.graph_mask.sum()) for b in batches)
    assert total == len(specs)
