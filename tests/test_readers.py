"""Dataset reader tests against reference-shaped inputs."""

import json

import numpy as np
import pandas as pd
import pytest

from deepdfa_tpu.data import readers


def _bigvul_csv(tmp_path, rows):
    df = pd.DataFrame(rows)
    p = tmp_path / "MSR_data_cleaned.csv"
    df.to_csv(p, index=True)
    return p


GOOD_VULN = (
    "int f(char *s) {\n"
    "    char buf[8];\n"
    "    int n = strlen(s);\n"
    "    strcpy(buf, s);\n"
    "    n += 1;\n"
    "    return n;\n"
    "}"
)
GOOD_FIXED = (
    "int f(char *s) {\n"
    "    char buf[8];\n"
    "    int n = strlen(s);\n"
    "    strncpy(buf, s, 7);\n"
    "    n += 1;\n"
    "    return n;\n"
    "}"
)


def test_read_bigvul_filters(tmp_path):
    rows = [
        # clean negative
        {"func_before": "int a(void) { return 1; }", "func_after": "int a(void) { return 1; }", "vul": 0},
        # good vulnerable example
        {"func_before": GOOD_VULN, "func_after": GOOD_FIXED, "vul": 1},
        # vulnerable but no change -> dropped
        {"func_before": GOOD_VULN, "func_after": GOOD_VULN, "vul": 1},
        # vulnerable but truncated (no closing brace) -> dropped
        {"func_before": "int b(void) { return 1;", "func_after": "int b(void) { return 2;", "vul": 1},
        # vulnerable but too short -> dropped
        {"func_before": "int c(void)\n{\nreturn 1;\n}", "func_after": "int c(void)\n{\nreturn 2;\n}", "vul": 1},
    ]
    p = _bigvul_csv(tmp_path, rows)
    exs = readers.read_bigvul(p)
    by_id = {e.id: e for e in exs}
    assert 0 in by_id and by_id[0].label == 0.0
    assert 1 in by_id and by_id[1].label == 1.0
    assert by_id[1].vuln_lines == frozenset({4})  # the strcpy line
    assert 2 not in by_id and 3 not in by_id and 4 not in by_id
    # comments are stripped
    assert "/*" not in by_id[1].code


def test_read_devign(tmp_path):
    p = tmp_path / "function.json"
    p.write_text(
        json.dumps(
            [
                {"func": "int x(void) { return 0; } // c", "target": 0},
                {"func": "int y(int a) { return a; }", "target": 1},
            ]
        )
    )
    exs = readers.read_devign(p)
    assert len(exs) == 2
    assert exs[1].label == 1.0
    assert exs[1].vuln_lines == frozenset()
    assert "//" not in exs[0].code


def test_splits_roundtrip(tmp_path):
    df = pd.DataFrame({"id": [0, 1, 2, 3], "split": ["train", "valid", "test", "train"]})
    p = tmp_path / "splits.csv"
    df.to_csv(p, index=False)
    m = readers.read_splits_csv(p)
    assert m == {0: "train", 1: "val", 2: "test", 3: "train"}

    rs = readers.random_splits(range(100), seed=0)
    counts = {s: sum(1 for v in rs.values() if v == s) for s in ("train", "val", "test")}
    assert counts["train"] == 80 and counts["val"] == 10 and counts["test"] == 10
    assert readers.random_splits(range(100), seed=0) == rs


def test_partition_disjoint():
    from deepdfa_tpu.data.pipeline import Example

    exs = [Example(id=i, code="", label=0.0) for i in range(10)]
    splits = readers.random_splits(range(10), seed=1)
    parts = readers.partition(exs, splits)
    all_ids = [e.id for part in parts.values() for e in part]
    assert sorted(all_ids) == list(range(10))
    assert len(set(all_ids)) == 10
