"""NNI protocol bridge: no-op without runtime, full protocol with a stub."""

import sys
import types

from deepdfa_tpu.train import nni_bridge


def test_inactive_without_platform(monkeypatch):
    monkeypatch.delenv("NNI_PLATFORM", raising=False)
    assert not nni_bridge.active()
    assert nni_bridge.get_next_parameters() == {}
    # reports are silent no-ops
    nni_bridge.report_intermediate(0.5)
    nni_bridge.report_final(0.9)


def test_bridge_with_stubbed_nni(monkeypatch):
    calls = {"intermediate": [], "final": []}
    stub = types.ModuleType("nni")
    stub.get_next_parameter = lambda: {
        "train.optim.learning_rate": 0.01,
        "model.hidden_dim": 16,
    }
    stub.report_intermediate_result = calls["intermediate"].append
    stub.report_final_result = calls["final"].append
    monkeypatch.setitem(sys.modules, "nni", stub)
    monkeypatch.setenv("NNI_PLATFORM", "local")

    assert nni_bridge.active()
    ov = sorted(nni_bridge.nni_overrides())
    assert ov == ["model.hidden_dim=16", "train.optim.learning_rate=0.01"]

    # overrides round-trip through the typed config
    from deepdfa_tpu.core import Config, config as config_mod

    cfg = config_mod.apply_overrides(Config(), ov)
    assert cfg.model.hidden_dim == 16
    assert cfg.train.optim.learning_rate == 0.01

    log_fn = nni_bridge.intermediate_log_fn("val_loss")
    log_fn({"epoch": 0, "val_loss": 0.7})
    log_fn({"epoch": 1})  # no monitor key -> no report
    nni_bridge.report_final(0.42)
    assert calls["intermediate"] == [0.7]
    assert calls["final"] == [0.42]


def test_bool_and_none_params_roundtrip(monkeypatch):
    import sys
    import types

    stub = types.ModuleType("nni")
    stub.get_next_parameter = lambda: {"train.debug_nans": True}
    monkeypatch.setitem(sys.modules, "nni", stub)
    monkeypatch.setenv("NNI_PLATFORM", "local")
    ov = nni_bridge.nni_overrides()
    assert ov == ["train.debug_nans=true"]

    from deepdfa_tpu.core import Config, config as config_mod

    cfg = config_mod.apply_overrides(Config(), ov)
    assert cfg.train.debug_nans is True
