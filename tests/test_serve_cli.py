"""CLI `score`/`serve` smoke paths via real subprocesses (the argparse
wiring can't rot silently), plus the serve halves of the schema checker
and bench script — ISSUE 5 satellites.

Subprocess-only by design (tests/conftest.py:run_cli): the CLI
normalizes to a 1-device CPU platform, which must never leak into this
8-virtual-device pytest process.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

from tests.conftest import run_cli

REPO = Path(__file__).resolve().parent.parent


def _last_json(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line in output: {stdout[-800:]}"
    return json.loads(lines[-1])


def test_score_smoke_end_to_end(tmp_path):
    """`score --smoke`: train a tiny checkpoint, restore it through the
    registry, score its corpus with ZERO steady-state recompiles, and
    leave a schema-clean serve_log.jsonl behind."""
    res = run_cli(tmp_path, "score", "--smoke", timeout=420)
    summary = _last_json(res.stdout)
    assert summary["serve_scored"] > 0
    assert summary["serve_failed_requests"] == 0
    assert summary["serve_steady_state_recompiles"] == 0
    assert summary["serve_requests_per_sec"] > 0

    run_dir = tmp_path / "runs" / "serve-smoke"
    scores = [
        json.loads(ln)
        for ln in (run_dir / "scores.jsonl").read_text().splitlines()
    ]
    assert len(scores) == summary["serve_scored"]
    assert all(0.0 <= s["prob"] <= 1.0 for s in scores if s["ok"])

    # the serve metric tags are all declared in the obs SCHEMA
    # (scripts/check_obs_schema.py --serve-log: the serve half of the
    # schema drift guard, without a second smoke train)
    serve_log = run_dir / "serve_log.jsonl"
    assert serve_log.exists()
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_schema.py"),
         "--serve-log", str(serve_log)],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu"),
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    record = json.loads(proc.stdout.splitlines()[0])
    assert record["ok"] is True and record["undeclared"] == []


def test_serve_smoke_http_round_trip(tmp_path):
    """`serve --smoke`: real HTTP against an ephemeral port — scores
    return 200, junk returns 422, /healthz and /stats answer, and the
    device never recompiles after warmup. Plus the ISSUE 6 acceptance
    surface: every response carries a request_id, the opt-in trace echo
    returns per-stage latency, /metrics scrapes clean and validates
    against the registry schema, the merged trace flow-links one
    request's frontend/queue/device spans under its request_id, the
    deep healthz ran a bounded backend probe, per-request entries (with
    request_id + status) land in serve_log.jsonl, and diag renders an
    SLO section from them."""
    res = run_cli(tmp_path, "serve", "--smoke", timeout=420)
    report = _last_json(res.stdout)
    assert report["scored"] and all(
        s["status"] == 200 and 0.0 <= s["prob"] <= 1.0
        for s in report["scored"]
    )
    assert report["reject_status"] == 422
    assert report["healthz_status"] == 200
    assert report["healthz"]["warmed_signatures"]
    assert report["healthz"]["checkpoint_step"] >= 0
    assert report["stats_status"] == 200
    assert report["stats"]["serve"]["batches"] >= 1
    assert report["steady_state_recompiles"] == 0

    # -- request-scoped tracing
    assert all(s["request_id"] for s in report["scored"])
    echoed = report["scored"][0]  # the first request opted into trace
    assert "stages" in echoed and "device_ms" in echoed["stages"]
    assert report["trace_flow_phases"] == ["f", "s", "t"]
    linked = set(report["trace_linked_spans"])
    assert linked >= {"frontend", "queue_wait"}
    # the device half of the chain: the smoke pins pipeline_depth=2, so
    # the request links through the dispatch+fetch pair (a serial run
    # would link one inline device_execute span instead)
    assert "device_execute" in linked or {"dispatch", "fetch"} <= linked
    run_dir = Path(report["run_dir"])
    assert (run_dir / "trace" / "trace.json").exists()

    # -- SLO windows reached /stats
    slo = report["stats"]["slo"]
    assert slo["requests_total"] >= len(report["scored"])
    assert "latency_ms" in slo["60s"]

    # -- ISSUE 12: the cascade round trip rode the smoke — per-request
    # stage verdicts, escalation accounting consistent, cascade stages
    # windowed, zero stage-2 recompiles, schema-valid cascade log
    casc = report["cascade"]
    assert casc["ok"], casc
    assert all(s.get("stage") in (1, 2) for s in report["scored"])
    assert casc["log"]["ok"]

    # -- deep healthz ran the bounded backend probe
    assert report["deep_healthz_status"] == 200
    backend = report["deep_healthz_backend"]
    assert backend["ok"] is True and backend["attempts"] >= 1

    # -- /metrics scrape validates against the declared registry schema
    assert report["metrics_status"] == 200
    scrape = Path(report["metrics_path"])
    assert scrape.exists()
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_schema.py"),
         "--metrics", str(scrape)],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu"),
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    result = json.loads(proc.stdout.splitlines()[0])
    assert result["ok"] and result["families"] > 10

    # -- per-request serve_log entries: request_id + status on every one
    entries = [
        json.loads(ln)["request"]
        for ln in (run_dir / "serve_log.jsonl").read_text().splitlines()
        if '"request"' in ln and "id" in json.loads(ln).get("request", {})
    ]
    assert len(entries) >= len(report["scored"]) + 1  # + the 422
    assert all("id" in e and "status" in e for e in entries)
    assert {e["status"] for e in entries} >= {200, 422}
    # and the whole log (request entries + summary record) is declared
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_schema.py"),
         "--serve-log", str(run_dir / "serve_log.jsonl")],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu"),
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]

    # -- diag renders the SLO section from the same log
    diag = run_cli(tmp_path, "diag", str(run_dir), "--json", timeout=120)
    diag_report = _last_json(diag.stdout)
    assert diag_report["slo"]["all"]["requests"] >= len(entries)
    assert "latency_ms" in diag_report["slo"]["all"]
    assert diag_report["slo"]["engine"]["requests_total"] >= 1
    assert diag_report["bench"]["trajectory"]  # committed BENCH_* parse


def test_bench_serve_smoke(tmp_path):
    """scripts/bench_serve.py --smoke: stamped record with the serving
    headline numbers (bench.py --child-serve consumes the same fn)."""
    out = tmp_path / "serve_bench.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_serve.py"),
         "--smoke", "--out", str(out)],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu",
                 DEEPDFA_TPU_STORAGE=str(tmp_path)),
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    record = json.loads(out.read_text())
    assert record["metric"] == "serve_requests_per_sec"
    assert record["value"] > 0
    assert record["serve_latency_p50_ms"] is not None
    assert record["serve_latency_p99_ms"] >= record["serve_latency_p50_ms"]
    assert 0.0 < record["serve_batch_occupancy_mean"] <= 1.0
    assert record["serve_steady_state_recompiles"] == 0
    # SLO+tracing warm-path tax, measured with interleaved reps
    # (docs/slo.md documents the <=2% bound; the value itself is noisy
    # on this box, so the smoke asserts presence and sanity, not the
    # bound)
    assert 0.0 <= record["serve_obs_overhead_fraction"] < 1.0
    assert record["serve_instrumented_requests_per_sec"] > 0
    # provenance stamp, like every other bench record
    for k in ("schema_version", "git_sha", "jax_version"):
        assert k in record
