"""Multi-task generation training (run_multi_gen role, train/multi_gen.py).

One GenTrainer state trains over a two-task mixture (copy + reverse);
both tasks' dev perplexity must improve, the mixture must visit both
tasks, and the per-task dual-counter early stop must end the run.
"""

import pytest

import numpy as np

from deepdfa_tpu.core import Config, MeshConfig
from deepdfa_tpu.core.config import apply_overrides
from deepdfa_tpu.data import gen_data
from deepdfa_tpu.models import t5 as t5m
from deepdfa_tpu.models import t5_gen as gen
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train.gen_loop import GenTrainer
from deepdfa_tpu.train.multi_gen import (
    GenTask,
    TASK_PATIENCE,
    fit_multi,
    mixture_probs,
)

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow

EOS, PAD = 2, 0


def _task(rng, n, reverse, src_len=10, tgt_len=8):
    src = np.zeros((n, src_len), np.int32)
    tgt = np.zeros((n, tgt_len), np.int32)
    for i in range(n):
        L = rng.integers(3, tgt_len - 1)
        toks = rng.integers(3, 20, L)
        src[i, :L] = toks
        src[i, L] = EOS
        out = toks[::-1] if reverse else toks
        tgt[i, :L] = out
        tgt[i, L] = EOS
    return src, tgt


def test_mixture_probs_tempering():
    p = mixture_probs([100, 1])
    # alpha=0.7 tempering lifts the small task above its raw share
    assert p[1] > 1 / 101
    assert abs(p.sum() - 1.0) < 1e-12
    assert p[0] > p[1]


def test_patience_table_matches_reference():
    # run_multi_gen.py:253-266
    assert TASK_PATIENCE == {
        "summarize": 2, "translate": 5, "refine": 5, "concode": 3,
        "defect": 2,
    }
    assert GenTask("summarize_python", lambda e: [], 1).resolved_patience() == 2
    assert GenTask("translate_java-cs", lambda e: [], 1).resolved_patience() == 5
    assert GenTask("unknown", lambda e: [], 1, patience=7).resolved_patience() == 7


def test_two_task_mixture_trains_and_early_stops():
    import jax

    rng = np.random.default_rng(0)
    copy_src, copy_tgt = _task(rng, 24, reverse=False)
    rev_src, rev_tgt = _task(rng, 12, reverse=True)
    cfg = apply_overrides(
        Config(),
        ["train.optim.name=adamw", "train.optim.learning_rate=0.01",
         "train.optim.warmup_frac=0.0"],
    )
    gcfg = gen.GenConfig(
        encoder=t5m.T5Config.tiny(vocab_size=32, remat=False, dropout_rate=0.0),
        max_target_length=8,
        beam_size=2,
    )
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
    trainer = GenTrainer(cfg, gcfg, mesh=mesh)
    state = trainer.init_state(seed=0)

    def batches(src, tgt):
        def factory(epoch):
            return gen_data.batches_of(
                src, tgt, num_shards=2, rows_per_shard=12,
                shuffle_seed=epoch,
            )

        return factory

    visits: list[str] = []

    def spying(factory, name):
        def wrapped(epoch):
            visits.append(name)
            return factory(epoch)

        return wrapped

    copy_val = gen_data.batches_of(copy_src, copy_tgt, 2, 12)
    rev_val = gen_data.batches_of(rev_src, rev_tgt, 2, 6)
    tasks = [
        GenTask(
            "copy", spying(batches(copy_src, copy_tgt), "copy"), size=24,
            val_batches=lambda: copy_val, patience=1,
        ),
        GenTask(
            "reverse", spying(batches(rev_src, rev_tgt), "reverse"), size=12,
            val_batches=lambda: rev_val, patience=1,
        ),
    ]
    ppl0 = {
        "copy": trainer.eval_ppl(state, copy_val),
        "reverse": trainer.eval_ppl(state, rev_val),
    }
    records: list[dict] = []
    state, summary = fit_multi(
        trainer, state, tasks, max_steps=400, eval_every=25, seed=0,
        log_fn=records.append,
    )
    # both tasks were sampled (24:12 sizes -> both have real mass)
    assert set(visits) == {"copy", "reverse"}
    # both improved on their own dev sets from one shared model
    for name in ("copy", "reverse"):
        assert summary[name]["best_ppl"] < ppl0[name] / 2, (
            name, summary[name], ppl0[name],
        )
    # with patience=1 on an overfittable task the dual-counter stop fires
    # well before max_steps (400 draws), ending the whole run
    assert all(s["stopped_at"] is not None for s in summary.values()), summary
    assert records and records[-1]["step"] < 400
