"""Tier-1 smoke for scripts/fault_inject.py --smoke: every documented
failure mode (SIGTERM preemption -> resume, truncated cache shard ->
quarantine+repack, NaN batch -> guard skip) must be survived end-to-end
through the real runtime, with the crash/resume loss trajectory
bit-identical — so resilience breakage fails tests instead of only
showing up as lost training runs (ISSUE 3)."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_fault_inject_smoke(tmp_path):
    out = tmp_path / "record.json"
    env = dict(
        os.environ,
        DEEPDFA_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "fault_inject.py"),
            "--smoke",
            "--out", str(out),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    record = json.loads(out.read_text())
    assert record["ok"] is True
    scen = record["scenarios"]
    assert scen["sigterm"]["trajectory_identical"] is True
    assert scen["sigterm"]["resumed_from_step"] > 0
    assert scen["corrupt-shard"]["stream_identical_after_repack"] is True
    assert scen["corrupt-shard"]["quarantined_entries"] >= 1
    assert scen["nan"]["skipped_steps"] == 2
    assert scen["nan"]["params_finite"] is True
    # flight-recorder coverage (ISSUE 10): sigterm/nan/stall each leave
    # a schema-valid postmortem.json naming its trigger
    flight = scen["flight"]
    assert flight["sigterm"]["trigger"] == "sigterm"
    assert flight["nan"]["trigger"] == "nan_rollback"
    assert flight["stall"]["trigger"] == "watchdog_abort"
    for name in ("sigterm", "nan", "stall"):
        assert flight[name]["valid"] is True
        assert flight[name]["steps"] > 0
    # unified sharding layer (ISSUE 13, docs/sharding.md): SIGTERM on
    # the 8-device mesh still writes exactly ONE (process-0) postmortem,
    # and a simulated non-primary host's obs.session installs nothing
    mesh = scen["mesh-sigterm"]
    assert mesh["valid"] is True
    assert mesh["trigger"] == "sigterm"
    assert mesh["postmortems"] == 1
    assert mesh["secondary_install"] is False
    assert mesh["mesh"]["axes"] == {"dp": 8}


def test_fault_inject_fleet_smoke(tmp_path):
    """The tier-1 fleet chaos tier (ISSUE 14, docs/fleet.md): the
    in-process kill-router + wedge-backend drills — a wedged backend is
    ejected off the forward timeout and readmitted on recovery with
    every request answered bit-identically from the survivor, and an
    abruptly-dead active router fails over to the standby within the
    documented bound with admission token-bucket levels re-seeded from
    the last fleet_log summary record."""
    out = tmp_path / "record.json"
    env = dict(
        os.environ,
        DEEPDFA_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "fault_inject.py"),
            "--smoke", "--fleet",
            "--out", str(out),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    record = json.loads(out.read_text())
    assert record["ok"] is True
    scen = record["scenarios"]
    wedge = scen["wedge-backend"]
    assert wedge["ejected"] is True
    assert wedge["readmitted"] is True
    assert wedge["steady_state_recompiles"] == 0
    kill = scen["kill-router"]
    assert kill["within_bound"] is True
    assert kill["epoch"] >= 2
    # the failover must not hand the drill tenant a fresh burst: the
    # re-seeded level reflects the 10 requests the dead active admitted
    assert kill["reseeded_drill_tokens"] <= 45.0
