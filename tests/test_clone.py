"""Clone-detection path: model parity shape, trainer overfit, readers."""

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig
from deepdfa_tpu.core.config import apply_overrides
from deepdfa_tpu.models import t5 as t5m
from deepdfa_tpu.models import t5_gen as gen
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train.clone_loop import (
    CloneTrainer,
    clone_batches_of,
)

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow

EOS, PAD = 2, 0


def test_clone_vec_matches_hf(rng):
    """clone_vec == HF decoder_hidden_states[-1] pooled at last eos
    (reference get_t5_vec, CodeT5/models.py:72-84)."""
    torch = pytest.importorskip("torch")
    from transformers import T5Config as HFT5Config, T5ForConditionalGeneration

    hf_cfg = HFT5Config(
        vocab_size=256, d_model=64, num_layers=2, num_decoder_layers=2,
        num_heads=4, d_kv=16, d_ff=128, dropout_rate=0.0,
        feed_forward_proj="relu", decoder_start_token_id=0,
        eos_token_id=2, pad_token_id=0,
    )
    tm = T5ForConditionalGeneration(hf_cfg).eval()
    ccfg = gen.CloneConfig(
        encoder=t5m.T5Config.tiny(dropout_rate=0.0, remat=False)
    )
    params = gen.init_clone_params(ccfg, __import__("jax").random.key(0))
    params["seq2seq"] = gen.gen_params_from_hf_torch(
        gen.GenConfig(encoder=ccfg.encoder), tm.state_dict()
    )

    ids = rng.integers(3, 256, (2, 10))
    ids[:, -3:] = 0
    ids[:, -4] = 2
    ids = ids.astype(np.int32)
    mask = torch.tensor((ids != 0).astype(np.int64))
    with torch.no_grad():
        out = tm(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=mask,
            labels=torch.tensor(ids, dtype=torch.long),
            decoder_attention_mask=mask,
            output_hidden_states=True,
        )
        hidden = out.decoder_hidden_states[-1].numpy()
    eos_pos = 6  # last eos index per construction
    want = hidden[:, eos_pos, :]
    got = np.asarray(gen.clone_vec(ccfg, params, ids))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.fixture(scope="module")
def clone_task():
    """Pairs are clones iff their (random) token bags are identical."""
    rng = np.random.default_rng(1)
    n, T = 32, 8
    pairs = np.zeros((n, 2, T), np.int32)
    labels = np.zeros((n,), np.int32)
    for i in range(n):
        a = rng.integers(4, 20, T - 2)
        if i % 2 == 0:
            b = a.copy()
            labels[i] = 1
        else:
            b = rng.integers(4, 20, T - 2)
        pairs[i, 0, : T - 2] = a
        pairs[i, 1, : T - 2] = b
        pairs[i, :, T - 2] = EOS
    return pairs, labels


def test_clone_trainer_overfits(clone_task):
    import jax

    pairs, labels = clone_task
    cfg = apply_overrides(
        Config(),
        ["train.optim.name=adamw", "train.optim.learning_rate=0.005",
         "train.optim.warmup_frac=0.0"],
    )
    ccfg = gen.CloneConfig(
        encoder=t5m.T5Config.tiny(vocab_size=32, remat=False, dropout_rate=0.0)
    )
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
    trainer = CloneTrainer(cfg, ccfg, mesh=mesh)
    state = trainer.init_state(seed=0)
    batches = clone_batches_of(pairs, labels, num_shards=2, rows_per_shard=16)
    m0, _ = trainer.evaluate(state, batches)
    for step in range(50):
        state, loss = trainer.train_step(state, batches[0], jax.random.key(step))
    m1, _ = trainer.evaluate(state, batches)
    assert np.isfinite(m1["loss"])
    assert m1["loss"] < m0["loss"]
    assert m1["f1"] > 0.9, m1


def test_clone_fit_checkpoints(tmp_path, clone_task):
    import jax

    pairs, labels = clone_task
    cfg = apply_overrides(Config(), ["train.optim.warmup_frac=0.0"])
    ccfg = gen.CloneConfig(
        encoder=t5m.T5Config.tiny(vocab_size=32, remat=False, dropout_rate=0.0)
    )
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
    trainer = CloneTrainer(cfg, ccfg, mesh=mesh)
    state = trainer.init_state(seed=0)
    batches = clone_batches_of(pairs, labels, num_shards=2, rows_per_shard=16)
    ckpt = trainer.make_checkpoints(tmp_path / "clone")
    seen = []
    trainer.fit(
        state,
        lambda _e: batches,
        val_batches=lambda: batches,
        checkpoints=ckpt,
        max_epochs=2,
        patience=5,
        log_fn=seen.append,
    )
    assert len(seen) == 2
    assert all("val_f1" in r for r in seen)
    assert ckpt.best_metrics() is not None
