"""Online inference subsystem (deepdfa_tpu/serve/, docs/serving.md).

The load-bearing invariants, in-process (the CLI surface is covered by
tests/test_serve_cli.py subprocesses):

- batching is a pure throughput decision: any interleaving of request
  arrivals scores BIT-IDENTICALLY to scoring each request alone
  (padding/bucketing must not leak across requests);
- the flush timer bounds a lone request's wait;
- admission control rejects at queue_limit (backpressure, not buffering);
- AOT warmup means zero steady-state lowerings;
- the registry restores params-only, names mismatches, and hot-swaps
  between batches without recompiling.
"""

import dataclasses
import json

import numpy as np
import pytest

from deepdfa_tpu.core import Config, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, to_examples
from deepdfa_tpu.serve.batcher import (
    DynamicBatcher,
    GgnnExecutor,
    QueueFull,
    RequestTooLarge,
)

NODE_BUDGET, EDGE_BUDGET = 2048, 8192


@pytest.fixture(scope="module")
def corpus():
    synth = generate(16, seed=3)
    examples = to_examples(synth)
    specs, vocabs = build_dataset(
        examples, train_ids=range(16), limit_all=50, limit_subkeys=50
    )
    return examples, specs, vocabs


@pytest.fixture(scope="module")
def served_model(corpus):
    import jax

    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA

    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=cfg.data.feat.input_dim)
    params = model.init(
        jax.random.key(0), pack([], 1, NODE_BUDGET, EDGE_BUDGET)
    )
    return cfg, model, params


def make_executor(model, params, max_batch=4) -> GgnnExecutor:
    return GgnnExecutor(
        model, lambda: params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=max_batch,
    )


def test_batcher_bit_identical_any_interleaving(corpus, served_model):
    """Property: for random arrival orders and batch compositions, every
    request's score equals its singleton score EXACTLY."""
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params, max_batch=4)
    executor.warmup()

    # ground truth: each spec scored alone through the same machinery
    alone = {}
    for s in specs:
        solo = DynamicBatcher(executor, queue_limit=8)
        [req] = solo.score_all([s])
        alone[s.graph_id] = req.result

    rng = np.random.default_rng(0)
    for round_ in range(4):
        order = rng.permutation(len(specs))
        batcher = DynamicBatcher(executor, queue_limit=64)
        reqs = batcher.score_all([specs[i] for i in order])
        for i, req in zip(order, reqs):
            gid = specs[i].graph_id
            assert req.result == alone[gid], (
                f"round {round_}: graph {gid} scored {req.result} "
                f"batched vs {alone[gid]} alone"
            )


def test_zero_steady_state_lowerings(corpus, served_model):
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params, max_batch=4)
    executor.warmup()
    n0 = executor.jit_lowerings()
    assert n0 == len(executor.sizes)
    assert executor.warmup() == {}  # idempotent
    rng = np.random.default_rng(1)
    for _ in range(3):
        sel = rng.choice(len(specs), size=rng.integers(1, 9), replace=False)
        batcher = DynamicBatcher(executor, queue_limit=64)
        batcher.score_all([specs[i] for i in sel])
    assert executor.jit_lowerings() == n0


def test_flush_timer_lone_request(corpus, served_model):
    """A lone request must flush after max_batch_delay, not wait for
    co-arrivals."""
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params, max_batch=4)
    executor.warmup()
    batcher = DynamicBatcher(
        executor, queue_limit=8, max_batch_delay_s=0.02
    )
    batcher.start()
    try:
        req = batcher.submit(specs[0])
        prob = req.wait(timeout=10.0)  # >> delay; generous for CI
        assert 0.0 <= prob <= 1.0
        assert req.latency_s < 5.0
    finally:
        batcher.close()


def test_backpressure_rejects_at_queue_limit(corpus, served_model):
    from deepdfa_tpu.obs import metrics as obs_metrics

    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params, max_batch=4)
    batcher = DynamicBatcher(executor, queue_limit=2)
    rejected0 = obs_metrics.REGISTRY.counter("serve/rejected").value
    batcher.submit(specs[0])
    batcher.submit(specs[1])
    with pytest.raises(QueueFull):
        batcher.submit(specs[2])
    assert (
        obs_metrics.REGISTRY.counter("serve/rejected").value == rejected0 + 1
    )
    # draining frees capacity and admission recovers
    batcher.drain()
    batcher.submit(specs[2])
    batcher.drain()


def test_oversized_request_rejected(corpus, served_model):
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params)
    big = dataclasses.replace(
        specs[0],
        node_feats=np.zeros((NODE_BUDGET + 1, 4), np.int32),
        node_vuln=np.zeros((NODE_BUDGET + 1,), np.int32),
    )
    batcher = DynamicBatcher(executor, queue_limit=8)
    with pytest.raises(RequestTooLarge):
        batcher.submit(big)


def test_oversized_request_isolated_in_offline_drive(corpus, served_model):
    """score_all: one over-budget graph becomes a failed row; every
    other request still scores (per-row fault isolation, never a
    crashed job)."""
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params)
    executor.warmup()
    big = dataclasses.replace(
        specs[0],
        node_feats=np.zeros((NODE_BUDGET + 1, 4), np.int32),
        node_vuln=np.zeros((NODE_BUDGET + 1,), np.int32),
    )
    batcher = DynamicBatcher(executor, queue_limit=8)
    reqs = batcher.score_all([specs[0], big, specs[1]])
    assert reqs[0].error is None and reqs[2].error is None
    assert isinstance(reqs[1].error, RequestTooLarge)
    with pytest.raises(RequestTooLarge):
        reqs[1].wait(0.1)


def test_feature_cache_and_frontend(corpus, served_model):
    from deepdfa_tpu.obs import metrics as obs_metrics
    from deepdfa_tpu.serve.frontend import (
        FrontendError,
        RequestPreprocessor,
    )

    examples, _, vocabs = corpus
    cfg, _, _ = served_model
    pre = RequestPreprocessor(cfg, vocabs, cache_entries=8)
    hits = obs_metrics.REGISTRY.counter("serve/cache_hits")
    h0 = hits.value
    code = examples[0].code
    s1 = pre.features(code)
    s2 = pre.features(code)
    assert hits.value == h0 + 1
    assert s1 is s2  # the cache returns the SAME extraction
    np.testing.assert_array_equal(s1.node_feats, s2.node_feats)
    # failures are cached too
    with pytest.raises(FrontendError):
        pre.features("@@@ not C at all")
    with pytest.raises(FrontendError, match="cached"):
        pre.features("@@@ not C at all")
    # bounded: the LRU never exceeds its configured entries
    for e in examples:
        try:
            pre.features(e.code)
        except FrontendError:
            pass
    assert len(pre.cache) <= 8


def test_session_pool_replaces_dead_sessions():
    from deepdfa_tpu.serve.frontend import SessionPool

    created = []

    class FakeSession:
        def __init__(self, i):
            self.i = i
            self.closed = False

        def close(self):
            self.closed = True

    pool = SessionPool(lambda i: created.append(FakeSession(i)) or created[-1],
                       size=2)
    with pool.session() as a:
        pass
    with pool.session() as b:
        assert b is a  # healthy sessions are reused
    with pytest.raises(RuntimeError):
        with pool.session() as c:
            raise RuntimeError("jvm died")
    assert created[0].closed  # dead session left the pool
    assert pool.replaced == 1
    with pool.session() as d:
        assert d is not created[0]
    pool.close()
    assert all(s.closed for s in created)


def test_session_pool_discard_wakes_waiter():
    """A waiter blocked on an exhausted pool must wake when a discard
    frees CREATION capacity (not just when a session is returned)."""
    import threading
    import time

    from deepdfa_tpu.serve.frontend import SessionPool

    class FakeSession:
        def close(self):
            pass

    pool = SessionPool(lambda i: FakeSession(), size=1)
    lease = pool.session()
    held = lease.__enter__()
    got = []

    def waiter():
        with pool.session() as s:
            got.append(s)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.1)
    assert not got  # blocked: pool exhausted
    lease.__exit__(RuntimeError, RuntimeError("jvm died"), None)
    t.join(timeout=5)
    assert got and got[0] is not held  # woken, served a FRESH session
    pool.close()


def _write_run(tmp_path, cfg, model, params, metrics, step=1):
    """Real run-dir artifacts (config.json + checkpoints/best) without a
    training loop."""
    import jax

    from deepdfa_tpu.train.checkpoint import CheckpointManager

    run_dir = tmp_path / "runs" / cfg.run_name
    run_dir.mkdir(parents=True, exist_ok=True)
    config_mod.to_json(cfg, run_dir / "config.json")
    mgr = CheckpointManager(run_dir / "checkpoints", monitor="val_loss")
    mgr.save(
        f"epoch-{step:04d}", jax.device_get(params), metrics, step=step
    )
    return run_dir


def test_registry_restore_and_hot_swap(tmp_path, monkeypatch, corpus,
                                       served_model):
    import jax

    from deepdfa_tpu.core import paths
    from deepdfa_tpu.serve.registry import ModelRegistry

    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    examples, specs, vocabs = corpus
    cfg, model, params = served_model
    cfg = config_mod.apply_overrides(
        cfg, ['run_name="serve-reg"', 'data.dataset="serve-reg"']
    )
    (paths.processed_dir("serve-reg") / f"vocab{cfg.data.feat.name}.json"
     ).write_text(json.dumps({k: v.to_json() for k, v in vocabs.items()}))
    run_dir = _write_run(tmp_path, cfg, model, params, {"val_loss": 1.0})

    registry = ModelRegistry(run_dir, family="deepdfa", cfg=cfg)
    executor = GgnnExecutor(
        registry.model, registry.params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=2,
    )
    executor.warmup()
    n0 = executor.jit_lowerings()
    batcher = DynamicBatcher(
        executor, queue_limit=8, on_batch=registry.maybe_reload
    )
    [r1] = batcher.score_all([specs[0]])

    # a newer, better checkpoint appears -> hot swap between batches
    params2 = jax.tree.map(lambda a: a + 0.05, jax.device_get(params))
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    CheckpointManager(run_dir / "checkpoints", monitor="val_loss").save(
        "epoch-0002", params2, {"val_loss": 0.5}, step=2
    )
    [r2] = batcher.score_all([specs[0]])
    assert registry.reloads == 1
    assert registry.info()["checkpoint_step"] == 2
    assert r2.result != r1.result  # new weights actually serve
    assert executor.jit_lowerings() == n0  # swap never recompiles


def test_hot_reload_discarded_when_swap_lands_mid_restore(
        tmp_path, monkeypatch, corpus, served_model):
    """The swap-generation fence: maybe_reload restores OUTSIDE the
    registry lock, so an operator swap_checkpoint/rollback landing in
    that window must win — the poller discards its now-stale params
    instead of silently reverting the swap (fleet rollout contract)."""
    import jax

    from deepdfa_tpu.core import paths
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    examples, specs, vocabs = corpus
    cfg, model, params = served_model
    cfg = config_mod.apply_overrides(
        cfg, ['run_name="serve-race"', 'data.dataset="serve-race"']
    )
    (paths.processed_dir("serve-race") / f"vocab{cfg.data.feat.name}.json"
     ).write_text(json.dumps({k: v.to_json() for k, v in vocabs.items()}))
    run_dir = _write_run(tmp_path, cfg, model, params, {"val_loss": 1.0})
    registry = ModelRegistry(run_dir, family="deepdfa", cfg=cfg)
    params2 = jax.tree.map(lambda a: a + 0.05, jax.device_get(params))
    CheckpointManager(run_dir / "checkpoints", monitor="val_loss").save(
        "epoch-0002", params2, {"val_loss": 0.5}, step=2
    )

    # the manifest moved, so a reload is due — but an operator swap
    # lands while the poller's restore runs outside the lock
    orig_restore = registry._restore

    def racing_restore(base=None):
        out = orig_restore(base)
        with registry._lock:
            registry._swap_generation += 1
        return out

    served_before = registry.params()
    monkeypatch.setattr(registry, "_restore", racing_restore)
    assert registry.maybe_reload() is False  # discarded, not committed
    assert registry.params() is served_before  # swap's params untouched
    assert registry.reloads == 0

    # with no concurrent swap the same reload lands on the next poll
    monkeypatch.setattr(registry, "_restore", orig_restore)
    assert registry.maybe_reload() is True
    assert registry.info()["checkpoint_step"] == 2


def test_restore_for_inference_errors(tmp_path, served_model):
    import jax

    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train.checkpoint import (
        CheckpointManager,
        CheckpointMismatch,
    )

    cfg, model, params = served_model
    mgr = CheckpointManager(tmp_path / "ckpts", monitor="val_loss")
    mgr.save("epoch-0001", jax.device_get(params), {"val_loss": 1.0}, step=1)

    # happy path: params-only restore round-trips
    got = mgr.restore_for_inference(
        "epoch-0001", jax.device_get(params)
    )
    chk = jax.tree.leaves(got)[0]
    np.testing.assert_array_equal(chk, jax.tree.leaves(jax.device_get(params))[0])

    # a differently-sized model names the mismatched paths, not a pytree
    # structure error
    wide = DeepDFA.from_config(
        config_mod.apply_overrides(cfg, ["model.hidden_dim=16"]).model,
        input_dim=cfg.data.feat.input_dim,
    )
    wide_params = wide.init(
        jax.random.key(0), pack([], 1, NODE_BUDGET, EDGE_BUDGET)
    )
    with pytest.raises(CheckpointMismatch) as ei:
        mgr.restore_for_inference("epoch-0001", jax.device_get(wide_params))
    assert ei.value.shape_mismatches
    assert "hidden_dim" in str(ei.value)  # the config hint names knobs

    # unknown tag: a clear listing, not an orbax stack trace
    with pytest.raises(FileNotFoundError, match="epoch-0001"):
        mgr.restore_for_inference("nope", jax.device_get(params))


def test_restore_for_inference_skips_optimizer_state(tmp_path, served_model):
    """A full-TrainState checkpoint (resilience layout) restores
    params-only."""
    import jax
    import orbax.checkpoint as ocp

    _, _, params = served_model
    host = jax.device_get(params)
    full = {
        "params": host,
        "opt_state": {"mu": jax.tree.map(np.zeros_like, host)},
        "step": np.zeros((), np.int32),
    }
    ckpt = ocp.StandardCheckpointer()
    path = tmp_path / "ckpts" / "step-5"
    ckpt.save(path, full, force=True)
    ckpt.wait_until_finished()

    from deepdfa_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path / "ckpts")
    got = mgr.restore_for_inference("step-5", host)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(host)):
        np.testing.assert_array_equal(a, b)


def test_combined_executor_buckets(corpus):
    """Text requests group by their PR-2 bucket edge and score through
    AOT signature executables with zero steady-state lowerings."""
    import jax

    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.serve.batcher import CombinedExecutor

    examples, specs, _ = corpus
    tok = HashTokenizer(vocab_size=256)
    enc = TransformerConfig.tiny(
        vocab_size=tok.vocab_size, max_position_embeddings=68,
        num_layers=1, num_heads=2, hidden_size=8, intermediate_size=16,
    )
    mcfg = cmb.CombinedConfig(
        encoder=enc, graph_hidden_dim=8, graph_input_dim=52,
        use_graph=False,
    )
    params = cmb.init_params(mcfg, jax.random.key(0))
    executor = CombinedExecutor(
        mcfg, lambda: params, tok, seq_buckets=(32, 64),
        token_budget=256, node_budget=256, edge_budget=1024,
    )
    executor.warmup()
    n0 = executor.jit_lowerings()
    assert n0 == 2

    payloads = [
        (tok.encode(e.code, max_length=64), None) for e in examples[:6]
    ]
    keys = {executor.bucket_key(p) for p in payloads}
    assert keys <= {32, 64}
    batcher = DynamicBatcher(executor, queue_limit=16)
    reqs = batcher.score_all(payloads)
    assert all(0.0 <= r.result <= 1.0 for r in reqs)
    # singleton equivalence on the text path (same bucket -> same padded
    # shape -> identical row computation)
    solo = DynamicBatcher(executor, queue_limit=4)
    [alone] = solo.score_all([payloads[0]])
    assert alone.result == reqs[0].result
    assert executor.jit_lowerings() == n0


def test_combined_executor_graphs_never_degrade(corpus):
    """With graphs attached, the budget accounting must mirror collate()
    exactly: an admitted chunk degrades NO row to has_graph=False, so
    batched scores stay bit-identical to singleton scores (a degraded
    row would score text-only batched but with its graph alone)."""
    import jax

    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.serve.batcher import CombinedExecutor

    examples, specs, _ = corpus
    tok = HashTokenizer(vocab_size=256)
    enc = TransformerConfig.tiny(
        vocab_size=tok.vocab_size, max_position_embeddings=68,
        num_layers=1, num_heads=2, hidden_size=8, intermediate_size=16,
    )
    mcfg = cmb.CombinedConfig(
        encoder=enc, graph_hidden_dim=8, graph_input_dim=52,
        use_graph=True,
    )
    params = cmb.init_params(mcfg, jax.random.key(0))
    # budgets tight enough that sloppy accounting would admit chunks
    # collate() then degrades (specs here run ~3-40 nodes each)
    executor = CombinedExecutor(
        mcfg, lambda: params, tok, seq_buckets=(64,),
        token_budget=512, node_budget=64, edge_budget=256,
    )
    executor.warmup()
    by_id = {e.id: e for e in examples}
    payloads = [
        (tok.encode(by_id[s.graph_id].code, max_length=64), s)
        for s in specs[:8]
    ]
    alone = {}
    for p in payloads:
        solo = DynamicBatcher(executor, queue_limit=4)
        [req] = solo.score_all([p])
        alone[id(p)] = req.result
    batcher = DynamicBatcher(executor, queue_limit=32)
    reqs = batcher.score_all(payloads)
    for p, req in zip(payloads, reqs):
        assert req.result == alone[id(p)], (
            f"graph {p[1].graph_id} ({p[1].num_nodes} nodes): "
            f"{req.result} batched vs {alone[id(p)]} alone"
        )


# -- pipelined execution (ISSUE 17, docs/serving.md "Pipelined execution") --


def test_pipelined_bit_identical_any_interleaving(corpus, served_model):
    """Property: with pipeline_depth >= 2, every request's score equals
    the serial path's AND the singleton score EXACTLY, under arbitrary
    request mixes — pipelining moves the sync point, never the
    numerics."""
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params, max_batch=4)
    executor.warmup()

    alone = {}
    for s in specs:
        solo = DynamicBatcher(executor, queue_limit=8)
        [req] = solo.score_all([s])
        alone[s.graph_id] = req.result

    rng = np.random.default_rng(7)
    for round_ in range(4):
        order = rng.permutation(len(specs))
        serial = DynamicBatcher(executor, queue_limit=64)
        pipelined = DynamicBatcher(
            executor, queue_limit=64, pipeline_depth=2
        )
        sreqs = serial.score_all([specs[i] for i in order])
        preqs = pipelined.score_all([specs[i] for i in order])
        pipelined.close()
        for i, sr, pr in zip(order, sreqs, preqs):
            gid = specs[i].graph_id
            assert pr.result == sr.result == alone[gid], (
                f"round {round_}: graph {gid} scored {pr.result} "
                f"pipelined vs {sr.result} serial vs {alone[gid]} alone"
            )
            # fetch-side attribution landed on every request
            assert pr.device_s is not None and pr.device_s >= 0.0
            assert pr.queue_wait_s is not None


class _InflightProbe:
    """Executor wrapper counting concurrently dispatched-but-unsynced
    batches (the in-flight window the depth bound promises)."""

    def __init__(self, inner):
        self._inner = inner
        self.now = 0
        self.peak = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def dispatch(self, key, packed):
        self.now += 1
        self.peak = max(self.peak, self.now)
        return self._inner.dispatch(key, packed)

    def fetch(self, handle, n):
        import time as _time

        # stretch the sync so the dispatcher has every chance to race
        # past the bound if the window were leaky
        _time.sleep(0.005)
        out = self._inner.fetch(handle, n)
        self.now -= 1
        return out


def test_pipelined_inflight_never_exceeds_depth(corpus, served_model):
    """Backpressure: dispatched-but-unsynced batches never exceed
    pipeline_depth in either drive mode, and the queue-depth accounting
    stays truthful (drains to zero once resolved)."""
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params, max_batch=2)
    executor.warmup()
    depth = 2

    # offline drive
    probe = _InflightProbe(executor)
    batcher = DynamicBatcher(probe, queue_limit=64, pipeline_depth=depth)
    reqs = batcher.score_all(list(specs))
    assert all(r.error is None for r in reqs)
    assert probe.peak <= depth
    assert probe.peak >= 2  # the window actually filled
    assert batcher.stats()["queue_depth"] == 0
    assert batcher.stats()["pipeline_in_flight"] == 0
    batcher.close()

    # online drive (scheduler + fetch thread)
    probe = _InflightProbe(executor)
    batcher = DynamicBatcher(
        probe, queue_limit=64, max_batch_delay_s=0.002,
        pipeline_depth=depth,
    )
    batcher.start()
    try:
        reqs = [batcher.submit(s) for s in specs]
        probs = [r.wait(timeout=30.0) for r in reqs]
        assert all(0.0 <= p <= 1.0 for p in probs)
        assert probe.peak <= depth
        assert batcher.stats()["queue_depth"] == 0
    finally:
        batcher.close()
    assert batcher.stats()["pipeline_in_flight"] == 0


def test_pipelined_zero_steady_state_lowerings(corpus, served_model):
    """The pipelined path reuses the SAME warmed ladder executables —
    no request mix may trigger a lowering after warmup."""
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params, max_batch=4)
    executor.warmup()
    n0 = executor.jit_lowerings()
    rng = np.random.default_rng(11)
    for _ in range(3):
        sel = rng.choice(len(specs), size=rng.integers(1, 9), replace=False)
        batcher = DynamicBatcher(
            executor, queue_limit=64, pipeline_depth=2
        )
        batcher.score_all([specs[i] for i in sel])
        batcher.close()
    assert executor.jit_lowerings() == n0


def test_pipelined_dispatch_error_isolated(corpus, served_model):
    """A batch whose dispatch dies must fail ONLY its own requests,
    release its in-flight slot, and leave the batcher serviceable."""
    _, specs, _ = corpus
    _, model, params = served_model
    executor = make_executor(model, params, max_batch=2)
    executor.warmup()

    class _Bomb(_InflightProbe):
        def __init__(self, inner):
            super().__init__(inner)
            self.armed = True

        def dispatch(self, key, packed):
            if self.armed:
                self.armed = False
                raise RuntimeError("boom")
            return super().dispatch(key, packed)

    probe = _Bomb(executor)
    batcher = DynamicBatcher(probe, queue_limit=64, pipeline_depth=2)
    reqs = batcher.score_all(list(specs[:4]))
    failed = [r for r in reqs if r.error is not None]
    ok = [r for r in reqs if r.error is None]
    assert failed and ok  # first batch died, the rest scored
    assert all(isinstance(r.error, RuntimeError) for r in failed)
    assert batcher.stats()["pipeline_in_flight"] == 0
    batcher.close()
