import json

from deepdfa_tpu.core import Config, FeatureSpec, config, prng


def test_feature_spec_roundtrip():
    fs = FeatureSpec(limit_all=1000, limit_subkeys=1000)
    assert fs.input_dim == 1002
    name = fs.name
    parsed = FeatureSpec.parse(name)
    assert parsed.limit_all == 1000
    assert parsed.limit_subkeys == 1000
    assert set(parsed.subkeys) == {"api", "datatype", "literal", "operator"}


def test_feature_spec_parse_reference_string():
    # the exact feat string from the reference config
    # (DDFA/configs/config_bigvul.yaml:3)
    feat = "_ABS_DATAFLOW_datatype_all_limitall_1000_limitsubkeys_1000"
    fs = FeatureSpec.parse(feat)
    assert fs.subkeys == ("datatype",)
    assert fs.limit_all == 1000
    assert fs.input_dim == 1002


def test_config_json_roundtrip(tmp_path):
    cfg = Config()
    p = tmp_path / "cfg.json"
    config.to_json(cfg, p)
    cfg2 = config.load(p)
    assert cfg2 == cfg


def test_config_overrides():
    cfg = Config()
    cfg2 = config.apply_overrides(
        cfg, ["model.hidden_dim=64", "train.optim.learning_rate=0.01", "run_name=x"]
    )
    assert cfg2.model.hidden_dim == 64
    assert cfg2.train.optim.learning_rate == 0.01
    assert cfg2.run_name == "x"
    # unknown keys are rejected
    try:
        config.apply_overrides(cfg, ["model.nope=1"])
        raise AssertionError("should have raised")
    except KeyError:
        pass


def test_prng_determinism():
    import jax

    k1 = prng.fold_name(prng.root_key(0), "train")
    k2 = prng.fold_name(prng.root_key(0), "train")
    k3 = prng.fold_name(prng.root_key(0), "eval")
    assert (jax.random.key_data(k1) == jax.random.key_data(k2)).all()
    assert not (jax.random.key_data(k1) == jax.random.key_data(k3)).all()
    g = prng.host_rng(0, "sampler")
    g2 = prng.host_rng(0, "sampler")
    assert g.integers(0, 1 << 30) == g2.integers(0, 1 << 30)


def test_apply_sanitizers_debug_nans():
    """train.debug_nans=true -> NaN under jit raises (the detect_anomaly
    analog, config_default.yaml:40)."""
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    from deepdfa_tpu.core import Config, config as config_mod

    cfg = config_mod.apply_overrides(Config(), ["train.debug_nans=true"])
    assert cfg.train.debug_nans is True
    config_mod.apply_sanitizers(cfg)
    try:
        with _pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(-1.0).block_until_ready()
    finally:
        jax.config.update("jax_debug_nans", False)

    # off by default: no raise
    config_mod.apply_sanitizers(Config())
    assert bool(jnp.isnan(jax.jit(lambda x: jnp.log(x))(-1.0)))
