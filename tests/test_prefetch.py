"""Async input pipeline (data/prefetch.py): ordering, errors, and the
numerics/step-count guarantee — training through the prefetch queue must
be bit-identical to training without it (VERDICT r2 item 6)."""

import time

import jax
import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data.prefetch import device_placer, prefetch
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train import GraphTrainer

from tests.test_train import _batches, synthetic_dataset


def test_same_elements_same_order():
    src = list(range(57))
    assert list(prefetch(iter(src), size=3)) == src


def test_place_runs_in_producer():
    out = list(prefetch(iter([1, 2, 3]), size=2, place=lambda x: x * 10))
    assert out == [10, 20, 30]


def test_size_zero_is_inline():
    calls = []

    def gen():
        for i in range(3):
            calls.append(i)
            yield i

    it = prefetch(gen(), size=0)
    assert calls == []
    assert next(it) == 0
    assert calls == [0]  # strictly lazy: nothing ran ahead


def test_source_exception_propagates():
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = prefetch(gen(), size=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_place_exception_propagates():
    def bad(x):
        raise ValueError("bad place")

    with pytest.raises(ValueError, match="bad place"):
        list(prefetch(iter([1]), size=2, place=bad))


def test_producer_runs_ahead():
    produced = []

    def gen():
        for i in range(5):
            produced.append(i)
            yield i

    it = prefetch(gen(), size=2)
    assert next(it) == 0
    deadline = time.time() + 5.0
    # queue depth 2 => producer should have built items 1 and 2 (and
    # usually pulled 3) before the consumer asks for them
    while len(produced) < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert len(produced) >= 3


def test_same_order_with_many_producers():
    src = list(range(200))
    for producers in (2, 5):
        assert list(prefetch(iter(src), size=4, producers=producers)) == src


def test_place_exception_order_with_many_producers():
    # the failure is re-raised at its position in SOURCE order: every
    # earlier element is still delivered, later ones never are
    def bad(x):
        if x == 7:
            raise ValueError("bad place")
        return x

    got = []
    with pytest.raises(ValueError, match="bad place"):
        for x in prefetch(iter(range(20)), size=3, producers=4, place=bad):
            got.append(x)
    assert got == list(range(7))


def test_stats_counters_accumulate():
    from deepdfa_tpu.data.prefetch import PipelineStats

    stats = PipelineStats()
    out = list(
        prefetch(
            iter(range(10)), size=2, producers=2,
            place=lambda x: x, stats=stats,
        )
    )
    assert out == list(range(10))
    assert stats.produced == 10 and stats.consumed == 10
    assert stats.pack_seconds >= 0 and stats.load_seconds == 0
    rec = stats.record()
    assert set(rec) >= {"pack_seconds", "place_seconds", "wait_seconds"}
    assert stats.wait_fraction(0.0) == 0.0


def test_abandon_joins_producer_threads():
    import threading

    before = {
        t.name for t in threading.enumerate()
        if t.name.startswith("batch-prefetch")
    }
    it = prefetch(iter(range(10_000)), size=1, producers=3)
    assert next(it) == 0
    it.close()
    alive = {
        t.name for t in threading.enumerate()
        if t.name.startswith("batch-prefetch")
    }
    # close() joined the producers (with timeout): none left beyond any
    # that predate this test
    assert alive <= before


def test_abandoned_consumer_stops_producer():
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield i

    it = prefetch(gen(), size=1)
    assert next(it) == 0
    it.close()  # generator finalizer sets the stop event
    time.sleep(0.3)
    n = len(produced)
    time.sleep(0.3)
    assert len(produced) == n  # no further production after close


def _fit(prefetch_batches: int):
    graphs = synthetic_dataset(np.random.default_rng(3), n_graphs=32)
    cfg = config_mod.apply_overrides(
        Config(),
        [
            "model.hidden_dim=8",
            "train.max_epochs=2",
            f"train.prefetch_batches={prefetch_batches}",
        ],
    )
    mesh = make_mesh(MeshConfig(dp=4), devices=jax.devices()[:4])
    model = DeepDFA.from_config(cfg.model, input_dim=24, hidden_dim=8)
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    batches = _batches(graphs, 4)
    state = trainer.init_state(batches[0])
    state = trainer.fit(state, lambda epoch: batches)
    return jax.device_get(state.params), int(jax.device_get(state.step))


@pytest.mark.slow  # e2e training: slow lane
def test_training_numerics_and_step_count_unchanged():
    params_off, steps_off = _fit(0)
    params_on, steps_on = _fit(2)
    assert steps_on == steps_off
    for a, b in zip(jax.tree.leaves(params_off), jax.tree.leaves(params_on)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_device_placer_rejects_indivisible_leading_axis():
    """Satellite: a batch whose leading axis can't split over the dp mesh
    axis raises a clear ValueError naming the leaf, not XLA's opaque
    sharding failure."""
    graphs = synthetic_dataset(np.random.default_rng(7), n_graphs=6)
    mesh = make_mesh(MeshConfig(dp=4), devices=jax.devices()[:4])
    batch = _batches(graphs, 3)[0]  # leading axis 3, mesh dp=4
    with pytest.raises(ValueError, match="not divisible by mesh axes"):
        device_placer(mesh)(batch)


def test_device_placer_preserves_static_metadata():
    graphs = synthetic_dataset(np.random.default_rng(5), n_graphs=8)
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
    batch = _batches(graphs, 2)[0]
    placed = device_placer(mesh)(batch)
    assert placed.num_graphs == batch.num_graphs
    assert isinstance(placed.num_graphs, int)
    np.testing.assert_array_equal(
        np.asarray(placed.node_feats), np.asarray(batch.node_feats)
    )
