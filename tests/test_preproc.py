"""Preprocessor conditional evaluation (frontend/preproc.py).

The reference's Joern sees function text after real preprocessing with an
empty predefined-macro table; these tests pin the ISO-C conditional
semantics (unknown id = 0, defined(), file-local #define table) and the
line-structure guarantee the CPG's line numbers depend on.
"""

from deepdfa_tpu.frontend.preproc import evaluate_conditionals


def lines(code):
    return evaluate_conditionals(code).split("\n")


def test_line_count_always_preserved():
    code = "a\n#if 0\nb\n#else\nc\n#endif\nd\n"
    out = evaluate_conditionals(code)
    assert len(out.split("\n")) == len(code.split("\n"))


def test_if0_drops_then_keeps_else():
    out = lines("#if 0\nX;\n#else\nY;\n#endif\n")
    assert out[1] == "" and out[3] == "Y;"


def test_if1_keeps_then_drops_else():
    out = lines("#if 1\nX;\n#else\nY;\n#endif\n")
    assert out[1] == "X;" and out[3] == ""


def test_ifdef_unknown_macro_is_inactive():
    out = lines("#ifdef NOPE\nX;\n#else\nY;\n#endif\n")
    assert out[1] == "" and out[3] == "Y;"


def test_ifndef_unknown_macro_is_active():
    out = lines("#ifndef NOPE\nX;\n#endif\n")
    assert out[1] == "X;"


def test_define_makes_ifdef_active():
    out = lines("#define HAVE_FOO\n#ifdef HAVE_FOO\nX;\n#endif\n")
    assert out[2] == "X;"


def test_undef_deactivates():
    out = lines(
        "#define A\n#undef A\n#ifdef A\nX;\n#endif\n"
    )
    assert out[3] == ""


def test_unknown_identifier_evaluates_to_zero():
    # ISO C 6.10.1p4: remaining identifiers become 0
    out = lines("#if CONFIG_THING\nX;\n#endif\n")
    assert out[1] == ""


def test_defined_operator():
    out = lines(
        "#define W 1\n#if defined(W) && !defined(Z)\nX;\n#endif\n"
    )
    assert out[2] == "X;"


def test_elif_chain_takes_first_true():
    code = "#if 0\na;\n#elif 1\nb;\n#elif 1\nc;\n#else\nd;\n#endif\n"
    out = lines(code)
    assert out[1] == "" and out[3] == "b;" and out[5] == "" and out[7] == ""


def test_nested_conditionals():
    code = (
        "#if 1\n"
        "a;\n"
        "#if 0\n"
        "b;\n"
        "#endif\n"
        "c;\n"
        "#endif\n"
    )
    out = lines(code)
    assert out[1] == "a;" and out[3] == "" and out[5] == "c;"


def test_object_macro_expansion_outside_strings():
    code = '#define N 16\nint a[N];\nchar *s = "N";\n'
    out = lines(code)
    assert out[1] == "int a[16];"
    assert out[2] == 'char *s = "N";'


def test_function_like_macros_not_expanded():
    code = "#define SQ(x) ((x)*(x))\nint y = SQ(3);\n"
    out = lines(code)
    assert out[1] == "int y = SQ(3);"


def test_undecidable_expression_stays_active():
    out = lines("#if FOO(1)\nX;\n#endif\n")
    assert out[1] == "X;"


def test_macro_value_drives_if():
    out = lines("#define LEVEL 2\n#if LEVEL > 1\nX;\n#endif\n")
    assert out[2] == "X;"


def test_continued_directive_lines_blanked():
    code = "#define LONG \\\n  1\n#if LONG\nX;\n#endif\n"
    out = lines(code)
    assert out[0] == "" and out[1] == ""
    assert out[3] == "X;"
