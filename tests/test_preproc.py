"""Preprocessor conditional evaluation (frontend/preproc.py).

The reference's Joern sees function text after real preprocessing with an
empty predefined-macro table; these tests pin the ISO-C conditional
semantics (unknown id = 0, defined(), file-local #define table) and the
line-structure guarantee the CPG's line numbers depend on.
"""

from deepdfa_tpu.frontend.preproc import evaluate_conditionals


def lines(code):
    return evaluate_conditionals(code).split("\n")


def test_line_count_always_preserved():
    code = "a\n#if 0\nb\n#else\nc\n#endif\nd\n"
    out = evaluate_conditionals(code)
    assert len(out.split("\n")) == len(code.split("\n"))


def test_if0_drops_then_keeps_else():
    out = lines("#if 0\nX;\n#else\nY;\n#endif\n")
    assert out[1] == "" and out[3] == "Y;"


def test_if1_keeps_then_drops_else():
    out = lines("#if 1\nX;\n#else\nY;\n#endif\n")
    assert out[1] == "X;" and out[3] == ""


def test_ifdef_unknown_macro_is_inactive():
    out = lines("#ifdef NOPE\nX;\n#else\nY;\n#endif\n")
    assert out[1] == "" and out[3] == "Y;"


def test_ifndef_unknown_macro_is_active():
    out = lines("#ifndef NOPE\nX;\n#endif\n")
    assert out[1] == "X;"


def test_define_makes_ifdef_active():
    out = lines("#define HAVE_FOO\n#ifdef HAVE_FOO\nX;\n#endif\n")
    assert out[2] == "X;"


def test_undef_deactivates():
    out = lines(
        "#define A\n#undef A\n#ifdef A\nX;\n#endif\n"
    )
    assert out[3] == ""


def test_unknown_identifier_evaluates_to_zero():
    # ISO C 6.10.1p4: remaining identifiers become 0
    out = lines("#if CONFIG_THING\nX;\n#endif\n")
    assert out[1] == ""


def test_defined_operator():
    out = lines(
        "#define W 1\n#if defined(W) && !defined(Z)\nX;\n#endif\n"
    )
    assert out[2] == "X;"


def test_elif_chain_takes_first_true():
    code = "#if 0\na;\n#elif 1\nb;\n#elif 1\nc;\n#else\nd;\n#endif\n"
    out = lines(code)
    assert out[1] == "" and out[3] == "b;" and out[5] == "" and out[7] == ""


def test_nested_conditionals():
    code = (
        "#if 1\n"
        "a;\n"
        "#if 0\n"
        "b;\n"
        "#endif\n"
        "c;\n"
        "#endif\n"
    )
    out = lines(code)
    assert out[1] == "a;" and out[3] == "" and out[5] == "c;"


def test_object_macro_expansion_outside_strings():
    code = '#define N 16\nint a[N];\nchar *s = "N";\n'
    out = lines(code)
    assert out[1] == "int a[16];"
    assert out[2] == 'char *s = "N";'


def test_function_like_macros_not_expanded():
    code = "#define SQ(x) ((x)*(x))\nint y = SQ(3);\n"
    out = lines(code)
    assert out[1] == "int y = SQ(3);"


def test_undecidable_expression_stays_active():
    out = lines("#if FOO(1)\nX;\n#endif\n")
    assert out[1] == "X;"


def test_macro_value_drives_if():
    out = lines("#define LEVEL 2\n#if LEVEL > 1\nX;\n#endif\n")
    assert out[2] == "X;"


def test_continued_directive_lines_blanked():
    code = "#define LONG \\\n  1\n#if LONG\nX;\n#endif\n"
    out = lines(code)
    assert out[0] == "" and out[1] == ""
    assert out[3] == "X;"


def test_directive_inside_block_comment_is_text():
    """A `#if` inside /* */ is not a directive (phase 3 removes comments
    before phase 4 executes directives, ISO C 5.1.1.2) — previously it
    pushed a conditional frame with no #endif and blanked all remaining
    code (ADVICE r3)."""
    code = "/*\n#if FOO\n*/\nint x = 1;\n"
    out = lines(code)
    assert out[3] == "int x = 1;"


def test_directive_after_comment_close_still_directive():
    code = "/* c\n*/ #if 0\nX;\n#endif\nY;\n"
    out = lines(code)
    assert out[2] == ""  # #if 0 took effect
    assert out[4] == "Y;"


def test_comment_stripped_from_directive_body():
    code = "#define N 16 /* width */\nint a[N];\n"
    assert lines(code)[1] == "int a[16];"


def test_exponentiation_rejected_not_evaluated():
    """`**` is not C; eval()ing it would compute an astronomically large
    integer on hostile corpora (ADVICE r3). Undecidable -> active."""
    code = "#if 9**9**9**9\nX;\n#endif\n"
    assert lines(code)[1] == "X;"


def test_valueless_macro_removed_from_token_stream():
    """`#define UNUSED` annotation macros vanish under a real
    preprocessor; leaving them in diverges the CPG (ADVICE r3)."""
    code = "#define UNUSED\nUNUSED int x;\n"
    assert lines(code)[1] == " int x;"


def test_complex_body_macro_left_intact_but_defined():
    code = "#define GUARD if (p) return\n#ifdef GUARD\nGUARD;\n#endif\n"
    assert lines(code)[2] == "GUARD;"


def test_hostile_shift_and_power_bounded():
    """The evaluator must never materialize astronomical integers: `**`
    is not C (tokenizes as * *, a parse error) and shift counts/magnitudes
    are capped. All undecidable -> branch stays active."""
    for expr in ("9**9**9**9", "1<<1000000000", "1<<(1<<40)",
                 "0xffffffffffffffff * 0xffffffffffffffff * 0xffffffffffffffff"):
        out = lines(f"#if {expr}\nX;\n#endif\n")
        assert out[1] == "X;", expr


def test_cond_parser_c_semantics():
    code = (
        "#if (3/2 == 1) && (7%3 == 1) && (-7/2 == -3) && (1 ? 2 : 0) "
        "&& (0x10 == 16) && (010 == 8) && (1 << 4 == 16) && !0 && (~0 != 0)\n"
        "X;\n#endif\n"
    )
    assert lines(code)[1] == "X;"


def test_unselected_arm_errors_do_not_poison():
    """Real preprocessors accept `0 && 1/0` and ternaries whose
    UNselected arm is erroneous (code-review r4): only the evaluated
    operand's failure may make the directive undecidable."""
    assert lines("#if 0 && 1/0\nX;\n#endif\n")[1] == ""  # decidably false
    assert lines("#if 1 || 1/0\nX;\n#endif\n")[1] == "X;"
    assert lines("#if FOO ? 100/FOO : 0\nX;\n#endif\n")[1] == ""  # FOO=0
    assert lines("#if 1 ? 1 : 1/0\nX;\n#endif\n")[1] == "X;"
    # but an error in the EVALUATED position stays undecidable -> active
    assert lines("#if 1/0\nX;\n#endif\n")[1] == "X;"
    assert lines("#if (1/0) || 1\nX;\n#endif\n")[1] == "X;"


import pytest as _pytest


@_pytest.mark.slow
def test_fuzz_vs_real_gcc_preprocessor():
    """Floor on the gcc -E differential fuzz (scripts/fuzz_preproc_vs_gcc
    .py, full report docs/preproc_fuzz_report.json: 300/300 exact):
    random well-formed directive programs must keep exactly the markers
    the real preprocessor keeps."""
    import shutil

    if shutil.which("gcc") is None:
        _pytest.skip("no gcc binary")
    from tests.conftest import load_script_module

    fz = load_script_module("fuzz_preproc_vs_gcc")
    rec = fz.run(n=80, seed=20260730)
    assert rec["n"] >= 60, rec
    # floor below the measured 100% (docs/preproc_fuzz_report.json):
    # a gcc upgrade changing a #if corner case must not flake the lane
    assert rec["exact"] / rec["n"] >= 0.97, rec
