"""Fleet layer unit tests (deepdfa_tpu/fleet/, docs/fleet.md) — the
router/admission halves against STUB HTTP replicas, no model, no
subprocess: failover retry, eject/readmit, drain observation, tenant
token buckets, deadline shedding, co-serving arbitration, and fleet-log
schema validation. The full-stack 2-replica drive (real checkpoints,
SIGKILL, SIGTERM drain) lives in tests/test_fleet_cli.py via
`fleet --smoke`."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from deepdfa_tpu.fleet import admission as fleet_admission, heartbeat
from deepdfa_tpu.fleet.router import (
    FleetLog,
    NoReplicaAvailable,
    Router,
    validate_fleet_log,
)
from deepdfa_tpu.obs import metrics as obs_metrics


# ---------------------------------------------------------------------------
# stub replica: a real HTTP server scoring with a deterministic function


class _StubHandler(BaseHTTPRequestHandler):
    replica_id = "stub"
    delay_s = 0.0

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):  # noqa: N802
        body = json.dumps({"ok": True, "replica": self.replica_id}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(n) or b"{}")
        if self.delay_s:
            time.sleep(self.delay_s)
        # deterministic score: the same code gives the same prob on any
        # replica (the bit-parity property the real fleet pins)
        code = payload.get("code", "")
        prob = (sum(map(ord, code)) % 1000) / 1000.0
        body = json.dumps({
            "ok": True,
            "prob": prob,
            "request_id": self.headers.get("X-Request-Id"),
            "replica": self.replica_id,
        }).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class StubReplica:
    """One stub replica: HTTP server + its heartbeat file."""

    def __init__(self, fleet_dir, replica_id: str, port: int = 0):
        self.fleet_dir = fleet_dir
        self.replica_id = replica_id
        handler = type(
            f"Stub_{replica_id}", (_StubHandler,),
            {"replica_id": replica_id},
        )
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        self.beat()

    def beat(self, state: str = heartbeat.READY, **info) -> None:
        heartbeat.write_heartbeat(
            self.fleet_dir, self.replica_id, "127.0.0.1", self.port,
            state=state, info=info,
        )

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)


def make_router(fleet_dir, log_path=None, **kw) -> Router:
    kw.setdefault("heartbeat_timeout_s", 5.0)
    kw.setdefault("poll_interval_s", 0.0)  # every poll() call rescans
    kw.setdefault("retries", 2)
    kw.setdefault("request_timeout_s", 10.0)
    return Router(
        fleet_dir,
        log=FleetLog(log_path) if log_path else None,
        **kw,
    )


def counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot().get(name, 0.0)


# ---------------------------------------------------------------------------
# heartbeat protocol


def test_heartbeat_round_trip_and_staleness(tmp_path):
    path = heartbeat.write_heartbeat(
        tmp_path, "r0", "127.0.0.1", 1234,
        info={"checkpoint_step": 3, "ledger_params": {"m": 100.0}},
    )
    hb = heartbeat.read_heartbeat(path)
    assert hb["replica_id"] == "r0" and hb["port"] == 1234
    assert hb["state"] == "ready"
    assert hb["ledger_params"] == {"m": 100.0}
    assert heartbeat.is_fresh(hb, timeout_s=5.0)
    assert not heartbeat.is_fresh(hb, 5.0, now=hb["t_unix"] + 6.0)
    assert heartbeat.scan_heartbeats(tmp_path) == {"r0": hb}
    with pytest.raises(ValueError):
        heartbeat.write_heartbeat(tmp_path, "r0", "h", 1, state="zombie")
    # a torn/garbage file is skipped, never fatal
    (tmp_path / "replica-bad.json").write_text("{truncat")
    assert set(heartbeat.scan_heartbeats(tmp_path)) == {"r0"}


# ---------------------------------------------------------------------------
# routing


def test_router_spreads_and_propagates_request_id(tmp_path):
    stubs = [StubReplica(tmp_path, f"r{i}") for i in range(2)]
    log_path = tmp_path / "fleet_log.jsonl"
    router = make_router(tmp_path, log_path)
    try:
        served = set()
        for i in range(4):
            rid = f"test-{i}"
            status, data, replica, retries = router.forward(
                json.dumps({"code": f"int f{i};"}).encode(), rid
            )
            assert status == 200 and retries == 0
            resp = json.loads(data)
            # the ingress id travelled to the replica and back
            assert resp["request_id"] == rid
            served.add(resp["replica"])
            router.log_request(
                rid, status, 0.01, tenant="default", priority=1,
                replica=replica,
            )
        # least-outstanding with forwarded tie-break: sequential
        # traffic round-robins across both replicas
        assert served == {"r0", "r1"}
    finally:
        router.close()
        for s in stubs:
            s.stop()
    result = validate_fleet_log(log_path)
    assert result["ok"], result["problems"]
    assert result["requests"] == 4
    assert result["events"] >= 2  # two joins
    assert result["summaries"] == 1  # appended by close()


def test_router_failover_no_request_lost(tmp_path):
    """Kill one stub replica; every request still answers 200 with the
    same deterministic score, the dead replica is ejected, and the
    retries counter shows the failover actually happened."""
    stubs = [StubReplica(tmp_path, f"r{i}") for i in range(2)]
    log_path = tmp_path / "fleet_log.jsonl"
    router = make_router(tmp_path, log_path)
    ejects0, retries0 = counter("fleet/ejects"), counter("fleet/retries")
    try:
        codes = [f"int g{i}(void);" for i in range(6)]
        expect = {
            c: (sum(map(ord, c)) % 1000) / 1000.0 for c in codes
        }
        # r0 dies; its heartbeat file stays fresh (the crash just
        # happened) so the router WILL route to it and must recover
        stubs[0].stop()
        for i, code in enumerate(codes):
            status, data, replica, _ = router.forward(
                json.dumps({"code": code}).encode(), f"fo-{i}"
            )
            assert status == 200
            resp = json.loads(data)
            assert resp["replica"] == "r1"
            assert resp["prob"] == expect[code]
        assert counter("fleet/ejects") - ejects0 == 1
        assert counter("fleet/retries") - retries0 >= 1
        with router._lock:
            assert router._replicas["r0"].ejected
            assert not router._replicas["r1"].ejected
    finally:
        router.close()
        stubs[1].stop()
    result = validate_fleet_log(log_path)
    assert result["ok"], result["problems"]
    assert any(
        json.loads(ln).get("fleet_event", {}).get("name") == "eject"
        for ln in log_path.read_text().splitlines()
    )


def test_router_retries_request_reset_mid_response(tmp_path):
    """The hard failover case: the replica READS the request, then the
    connection dies before any response bytes (process killed
    mid-batch). The router must classify it as a transport failure and
    retry on the survivor — deterministically exercised here by a stub
    that aborts every accepted connection after consuming the body."""

    class _AbortHandler(_StubHandler):
        replica_id = "dead"

        def do_POST(self):  # noqa: N802
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)  # the request was genuinely in flight
            # abort without a status line: the router's getresponse()
            # sees ConnectionReset/BadStatusLine, not an HTTP error
            self.connection.close()

    aborter = StubReplica.__new__(StubReplica)
    aborter.fleet_dir = tmp_path
    aborter.replica_id = "r0"
    aborter.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _AbortHandler)
    aborter.port = aborter.httpd.server_address[1]
    aborter._thread = threading.Thread(
        target=aborter.httpd.serve_forever, daemon=True
    )
    aborter._thread.start()
    aborter.beat()
    survivor = StubReplica(tmp_path, "r1")
    router = make_router(tmp_path)
    retries0 = counter("fleet/retries")
    try:
        # r0 wins the first pick (id order at equal load); every
        # request that lands there dies mid-flight and must come back
        # from r1 with the right score
        for i in range(4):
            code = f"int mid{i};"
            status, data, _, _ = router.forward(
                json.dumps({"code": code}).encode(), f"mid-{i}"
            )
            assert status == 200
            resp = json.loads(data)
            assert resp["replica"] == "r1"
            assert resp["prob"] == (sum(map(ord, code)) % 1000) / 1000.0
        assert counter("fleet/retries") - retries0 >= 1
        with router._lock:
            assert router._replicas["r0"].ejected
    finally:
        router.close()
        aborter.stop()
        survivor.stop()


def test_router_readmits_recovered_replica(tmp_path):
    stubs = [StubReplica(tmp_path, f"r{i}") for i in range(2)]
    router = make_router(tmp_path)
    readmits0 = counter("fleet/readmits")
    try:
        port0 = stubs[0].port
        stubs[0].stop()
        # fail onto r1 -> r0 ejected
        router.forward(b'{"code": "x"}', "rid-0")
        with router._lock:
            assert router._replicas["r0"].ejected
        # r0 comes back on the same port with a fresh heartbeat
        stubs[0] = StubReplica(tmp_path, "r0", port=port0)
        router.probe_ejected()
        with router._lock:
            assert not router._replicas["r0"].ejected
        assert counter("fleet/readmits") - readmits0 == 1
        # and it takes traffic again
        served = set()
        for i in range(4):
            _, data, _, _ = router.forward(
                b'{"code": "y"}', f"rid-{i + 1}"
            )
            served.add(json.loads(data)["replica"])
        assert "r0" in served
    finally:
        router.close()
        for s in stubs:
            s.stop()


def test_router_observes_drain_and_gone(tmp_path):
    stubs = [StubReplica(tmp_path, f"r{i}") for i in range(2)]
    log_path = tmp_path / "fleet_log.jsonl"
    router = make_router(tmp_path, log_path)
    try:
        # r0 announces draining: still known, never routed
        stubs[0].beat(state="draining")
        router.poll(force=True)
        for i in range(4):
            _, data, _, _ = router.forward(b'{"code": "z"}', f"d-{i}")
            assert json.loads(data)["replica"] == "r1"
        # drained -> gone from the table entirely
        stubs[0].beat(state="drained")
        router.poll(force=True)
        with router._lock:
            assert "r0" not in router._replicas
    finally:
        router.close()
        for s in stubs:
            s.stop()
    events = [
        json.loads(ln)["fleet_event"]["name"]
        for ln in log_path.read_text().splitlines()
        if "fleet_event" in json.loads(ln)
    ]
    assert "drain_observed" in events and "gone" in events
    result = validate_fleet_log(log_path)
    assert result["ok"], result["problems"]


def test_router_ignores_lingering_dead_heartbeats(tmp_path):
    """A drained or stale heartbeat FILE stays on disk by design (crash
    evidence) — it must not churn join+gone event pairs on every poll
    of a router that never knew the replica."""
    heartbeat.write_heartbeat(tmp_path, "r9", "127.0.0.1", 1, state="drained")
    import json as _json

    stale_path = heartbeat.heartbeat_path(tmp_path, "r8")
    doc = {"heartbeat": {
        "replica_id": "r8", "host": "127.0.0.1", "port": 2,
        "state": "ready", "t_unix": time.time() - 3600,
    }}
    stale_path.write_text(_json.dumps(doc))
    log_path = tmp_path / "fleet_log.jsonl"
    router = make_router(tmp_path, log_path)
    try:
        for _ in range(3):
            router.poll(force=True)
        with router._lock:
            assert router._replicas == {}
    finally:
        router.close()
    events = [
        _json.loads(ln)["fleet_event"]["name"]
        for ln in log_path.read_text().splitlines()
        if "fleet_event" in _json.loads(ln)
    ]
    assert events == []


def test_router_no_replica_available(tmp_path):
    router = make_router(tmp_path)
    try:
        with pytest.raises(NoReplicaAvailable):
            router.forward(b"{}", "none-0")
    finally:
        router.close()


# ---------------------------------------------------------------------------
# admission


def test_token_bucket_rate_and_burst():
    b = fleet_admission.TokenBucket(rate=2.0, burst=3.0, now=0.0)
    assert [b.try_take(0.0) for _ in range(4)] == [
        True, True, True, False
    ]  # burst capacity, then empty
    assert b.try_take(0.5)  # refilled 1 token at rate 2/s
    assert not b.try_take(0.5)
    assert b.try_take(10.0) and b.try_take(10.0) and b.try_take(10.0)
    assert not b.try_take(10.0)  # capped at burst, not rate*elapsed


def test_admission_decisions():
    clock = [100.0]
    c = fleet_admission.AdmissionController(
        tenants=fleet_admission.parse_tenants(
            '{"vip": {"rate": 10, "burst": 10, "priority": 0},'
            ' "tiny": {"rate": 0.001, "burst": 1, "priority": 2}}'
        ),
        default_rate=100.0, default_burst=100.0,
        replica_capacity=4, shed_fraction=1.0,
        service_time_init_ms=50.0, clock=lambda: clock[0],
    )
    # healthy path
    d = c.decide("vip", outstanding=0, healthy=2)
    assert d.admit and d.priority == 0
    # no replicas
    d = c.decide("vip", outstanding=0, healthy=0)
    assert (d.status, d.reason) == (503, "no_replicas")
    # per-tenant bucket: tiny gets one, then 429
    assert c.decide("tiny", 0, 2).admit
    d = c.decide("tiny", 0, 2)
    assert (d.status, d.reason) == (429, "rate_limit")
    # deadline shed: estimate (outstanding/healthy + 1) * 50ms = 150ms
    d = c.decide("vip", outstanding=4, healthy=2, deadline_ms=100)
    assert (d.status, d.reason) == (503, "deadline")
    assert d.estimated_wait_ms == pytest.approx(150.0)
    d = c.decide("vip", outstanding=4, healthy=2, deadline_ms=200)
    assert d.admit
    # overload shed spares interactive (priority 0), sheds batch
    d = c.decide("default", outstanding=8, healthy=2)
    assert (d.status, d.reason) == (503, "overload")
    assert c.decide("vip", outstanding=8, healthy=2).admit
    # EWMA calibration moves the estimate
    for _ in range(50):
        c.observe_service(0.01)
    assert c.service_ewma_s == pytest.approx(0.01, rel=0.2)
    assert c.decide("vip", outstanding=4, healthy=2, deadline_ms=100).admit


def test_admission_fairness_between_equal_tenants():
    """Two tenants with identical policies flooding together each get
    their own bucket's worth — one noisy tenant cannot starve the
    other."""
    clock = [0.0]
    c = fleet_admission.AdmissionController(
        tenants=fleet_admission.parse_tenants(
            '{"a": {"rate": 10, "burst": 10, "priority": 1},'
            ' "b": {"rate": 10, "burst": 10, "priority": 1}}'
        ),
        replica_capacity=10_000, clock=lambda: clock[0],
    )
    admitted = {"a": 0, "b": 0}
    # 10 seconds of interleaved flooding, 40 req/s/tenant offered
    for step in range(400):
        clock[0] = step * 0.025
        for tenant in ("a", "b"):
            if c.decide(tenant, outstanding=0, healthy=2).admit:
                admitted[tenant] += 1
    # each gets burst (10) + ~10/s * 10s = ~110; equal within 2%
    assert admitted["a"] == admitted["b"]
    assert 90 <= admitted["a"] <= 130


def test_parse_tenants_rejects_bad_specs():
    assert fleet_admission.parse_tenants("") == {}
    with pytest.raises(ValueError):
        fleet_admission.parse_tenants('["not", "an", "object"]')
    with pytest.raises(ValueError):
        fleet_admission.parse_tenants(
            '{"t": {"rate": -1, "burst": 1}}'
        )


# ---------------------------------------------------------------------------
# co-serving arbitration (the PR-10 param-bytes capacity signal)


def test_parse_model_spec():
    from deepdfa_tpu.fleet.replica import parse_model_spec

    assert parse_model_spec("ggnn=/runs/a") == (
        "ggnn", "deepdfa", "/runs/a", "best"
    )
    assert parse_model_spec("ggnn=/runs/a:last") == (
        "ggnn", "deepdfa", "/runs/a", "last"
    )
    # a path colon only splits when the tail looks like a checkpoint
    # tag (no slash)
    assert parse_model_spec("m=runs/x") == ("m", "deepdfa", "runs/x", "best")
    for bad in ("noequals", "=x", "name="):
        with pytest.raises(ValueError):
            parse_model_spec(bad)


def test_plan_coserving():
    plan = fleet_admission.plan_coserving
    # unbudgeted: everything fits
    assert plan({"a": 1e9, "b": 2e9}, 0) == (["a", "b"], [])
    # greedy in declaration order, refusing what would overflow
    assert plan({"a": 10.0, "b": 20.0, "c": 5.0}, 16.0) == (
        ["a", "c"], ["b"]
    )
    # exact fit is a fit
    assert plan({"a": 10.0, "b": 6.0}, 16.0) == (["a", "b"], [])
    assert plan({}, 100.0) == ([], [])


# ---------------------------------------------------------------------------
# fleet log validation


def test_validate_fleet_log_rejects_bad_shapes(tmp_path):
    path = tmp_path / "fleet_log.jsonl"
    path.write_text("\n".join([
        json.dumps({"request": {"id": "a", "status": 200,
                                "latency_ms": 1.0, "shed": 0,
                                "priority": 1, "retries": 0}}),
        json.dumps({"fleet_event": {"name": "eject", "t_unix": 1.0}}),
        json.dumps({"fleet_event": {"name": "exploded", "t_unix": 1.0}}),
        json.dumps({"request": {"status": 200}}),  # missing id
        json.dumps({"mystery": 1}),
        "not json at all",
    ]) + "\n")
    result = validate_fleet_log(path)
    assert not result["ok"]
    joined = "\n".join(result["problems"])
    assert "exploded" in joined
    assert "missing id/status" in joined
    assert "unknown record shape" in joined
    assert "not JSON" in joined


def test_validate_fleet_log_catches_undeclared_tags(tmp_path):
    path = tmp_path / "fleet_log.jsonl"
    path.write_text(json.dumps({
        "request": {"id": "a", "status": 200,
                    "made_up_scalar_tag": 1.0},
    }) + "\n")
    result = validate_fleet_log(path)
    assert not result["ok"]
    assert any("made_up_scalar_tag" in p for p in result["problems"])
