"""Reference-schema `prepare` goldens (VERDICT r4 #7).

The readers implement the cleaning semantics of the reference's
`DDFA/sastvd/helpers/datasets.py:139-292` (comment stripping, the four
vulnerable-row post-filters, split maps), but until this file no
committed fixture exercised the REAL `MSR_data_cleaned.csv` column set
end-to-end. `tests/fixtures/msr_golden.csv` carries all 36 columns of
the reference schema (the dtype dict at datasets.py:160-196, including
"Unnamed: 0" as the id column) over 19 rows designed to hit every
filter exactly once:

  ids 0-7   benign (vul=0)            -> kept unconditionally
  ids 8-12  vulnerable, real fix      -> kept, vuln line = 3 (1-based)
  id  13    benign with comments      -> kept, comments stripped
  id  14    vulnerable, no change     -> dropped (no added/removed)
  id  15    vulnerable, abnormal end  -> dropped (no trailing } or ;)
  id  16    vulnerable, ends ");"     -> dropped (declaration artifact)
  id  17    vulnerable, mod_prop>=0.7 -> dropped (mostly-rewritten)
  id  18    vulnerable, <=5 lines     -> dropped (too short)

`tests/fixtures/linevul_splits_golden.csv` mirrors the reference's
linevul_splits.csv / bigvul_rand_splits.csv shape (id,label).
"""

import json
import pickle

import pytest

pytestmark = pytest.mark.slow

FIXTURE = "tests/fixtures/msr_golden.csv"
SPLITS = "tests/fixtures/linevul_splits_golden.csv"


@pytest.fixture
def storage(tmp_path, monkeypatch):
    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    return tmp_path


def _prepare(argv):
    from deepdfa_tpu.cli.main import main
    from deepdfa_tpu.core import paths

    main(argv)
    out = paths.processed_dir("bigvul")
    with (out / "examples.pkl").open("rb") as f:
        examples = pickle.load(f)
    splits = {
        int(k): v
        for k, v in json.loads((out / "splits.json").read_text()).items()
    }
    return examples, splits


def test_fixture_has_reference_columns():
    """The fixture must stay byte-compatible with the reference schema:
    every column of datasets.py:160-196's dtype dict, id as the unnamed
    leading index column."""
    import pandas as pd

    df = pd.read_csv(FIXTURE)
    want = {
        "Unnamed: 0", "Access Gained", "Attack Origin",
        "Authentication Required", "Availability", "CVE ID", "CVE Page",
        "CWE ID", "Complexity", "Confidentiality", "Integrity",
        "Known Exploits", "Publish Date", "Score", "Summary",
        "Update Date", "Vulnerability Classification", "add_lines",
        "codeLink", "commit_id", "commit_message", "del_lines",
        "file_name", "files_changed", "func_after", "func_before",
        "lang", "lines_after", "lines_before", "parentID", "patch",
        "project", "project_after", "project_before", "vul",
        "vul_func_with_fix",
    }
    assert set(df.columns) == want
    assert len(df) == 19


def test_prepare_end_to_end_golden(storage):
    examples, splits = _prepare(
        ["prepare", "--source", FIXTURE, "--splits", SPLITS]
    )

    # filter counts: 14 kept (8 benign + 5 vuln + comment probe), the
    # five designed-to-drop vulnerable rows gone
    assert sorted(e.id for e in examples) == list(range(14))

    # labels and line labels: every kept vulnerable row flags exactly
    # line 3 (1-based — the `a = a * 2;` statement its fix rewrites)
    by_id = {e.id: e for e in examples}
    for i in range(8):
        assert by_id[i].label == 0.0 and not by_id[i].vuln_lines
    for i in range(8, 13):
        assert by_id[i].label == 1.0
        assert sorted(by_id[i].vuln_lines) == [3], i

    # comment stripping (reference remove_comments semantics): the
    # block and line comments in row 13 are gone from the kept code
    probe = by_id[13].code
    assert "/*" not in probe and "//" not in probe
    assert "comment" not in probe  # the comment text itself
    assert "int x = 1;" in probe  # the code around it survives

    # splits: taken from the csv verbatim (including dropped ids — the
    # reference keeps the full map; consumers join on kept ids),
    # partitions disjoint by construction of a dict
    assert len(splits) == 19
    assert [splits[i] for i in (3, 11)] == ["val", "val"]
    assert [splits[i] for i in (4, 12)] == ["test", "test"]
    assert all(v in ("train", "val", "test") for v in splits.values())


def test_prepare_cross_project_splits_disjoint(storage):
    """--cross-project: the holdout is project-disjoint from train
    (reference cross-project experiment, paper Table 7)."""
    import pandas as pd

    examples, splits = _prepare(
        ["prepare", "--source", FIXTURE, "--cross-project"]
    )
    df = pd.read_csv(FIXTURE).rename(columns={"Unnamed: 0": "id"})
    project = dict(zip(df["id"], df["project"]))
    train_projects = {
        project[e.id] for e in examples if splits.get(e.id) == "train"
    }
    test_projects = {
        project[e.id] for e in examples if splits.get(e.id) == "test"
    }
    assert train_projects and test_projects
    assert not (train_projects & test_projects)
