"""Fuzz floors: diff_lines vs the real git binary (VERDICT r3 item 6).

Runs scripts/fuzz_diffs_vs_git.py's corpora in-process at a reduced size
(git subprocess per case; the full 297-case sweep lives in the script and
its committed report docs/diff_fuzz_report.json). Floors are set below
the measured 99.3/99.7/100% so seed drift can't flake the lane, but well
above the pre-xdl 58.6% adversarial baseline.
"""

import shutil

import pytest

pytestmark = pytest.mark.slow

FLOORS = {"adversarial": 0.95, "indented": 0.95, "fuzzed": 1.0}
N = 60


@pytest.mark.skipif(shutil.which("git") is None, reason="no git binary")
@pytest.mark.parametrize("corpus", sorted(FLOORS))
def test_fuzz_exactness_floor(corpus):
    import sys
    from pathlib import Path

    scripts = Path(__file__).parents[1] / "scripts"
    sys.path.insert(0, str(scripts))
    try:
        import fuzz_diffs_vs_git as fz
    finally:
        sys.path.remove(str(scripts))
    import random

    from deepdfa_tpu.data.diffs import diff_lines

    gen = {
        "adversarial": fz.corpus_adversarial,
        "indented": fz.corpus_indented,
        "fuzzed": fz.corpus_fuzzed,
    }[corpus]
    rng = random.Random(20260730)
    exact = total = 0
    for before, after in gen(rng, N):
        total += 1
        if diff_lines(before, after) == fz.git_diff_lines(before, after):
            exact += 1
    assert exact / total >= FLOORS[corpus], (corpus, exact, total)
