"""Fuzz floors: diff_lines vs the real git binary (VERDICT r3 item 6).

Runs scripts/fuzz_diffs_vs_git.py's corpora in-process at a reduced size
(git subprocess per case; the full 297-case sweep lives in the script and
its committed report docs/diff_fuzz_report.json). With the full xdiff
pipeline (split heuristics + cleanup_records + compaction) every corpus
measures 100% exact; floors keep a hair of slack for git-version drift.
"""

import shutil

import pytest

pytestmark = pytest.mark.slow

FLOORS = {"adversarial": 0.98, "indented": 0.98, "fuzzed": 1.0}
N = 60


@pytest.mark.skipif(shutil.which("git") is None, reason="no git binary")
@pytest.mark.parametrize("corpus", sorted(FLOORS))
def test_fuzz_exactness_floor(corpus):
    import random

    from tests.conftest import load_script_module

    fz = load_script_module("fuzz_diffs_vs_git")

    from deepdfa_tpu.data.diffs import diff_lines

    gen = {
        "adversarial": fz.corpus_adversarial,
        "indented": fz.corpus_indented,
        "fuzzed": fz.corpus_fuzzed,
    }[corpus]
    rng = random.Random(20260730)
    exact = total = 0
    for before, after in gen(rng, N):
        total += 1
        if diff_lines(before, after) == fz.git_diff_lines(before, after):
            exact += 1
    assert exact / total >= FLOORS[corpus], (corpus, exact, total)
