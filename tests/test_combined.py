"""Combined transformer+graph model: bridge semantics + end-to-end training."""

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, split_ids, to_examples
from deepdfa_tpu.data.text import collate, collate_shards
from deepdfa_tpu.data.tokenizer import HashTokenizer
from deepdfa_tpu.models import combined as cmb
from deepdfa_tpu.models.transformer import TransformerConfig
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train.combined_loop import CombinedTrainer

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def corpus():
    n = 240
    synth = generate(n, vuln_rate=0.3, seed=5)
    train_ids, val_ids, test_ids = split_ids(n, seed=0)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=train_ids, limit_all=100, limit_subkeys=100
    )
    tok = HashTokenizer(vocab_size=512)
    token_ids = tok.batch_encode([s.before for s in synth], max_length=64)
    labels = [s.label for s in synth]
    by_id = {s.graph_id: s for s in specs}
    return synth, token_ids, labels, by_id, train_ids, test_ids


def _model_cfg():
    return cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(dropout_rate=0.0),
        graph_hidden_dim=8,
        graph_input_dim=102,
    )


def test_collate_bridge(corpus):
    synth, token_ids, labels, by_id, train_ids, _ = corpus
    # drop some graphs to exercise has_graph
    partial_graphs = {k: v for k, v in by_id.items() if k % 3 != 0}
    b = collate(
        token_ids[:16], labels[:16], list(range(16)), partial_graphs,
        batch_rows=16, node_budget=2048, edge_budget=8192,
    )
    hg = np.asarray(b.has_graph)
    for i in range(16):
        assert hg[i] == (i % 3 != 0 and i in partial_graphs)
    # graph slot i belongs to row i
    ids = np.asarray(b.graphs.graph_ids)
    for i in range(16):
        if hg[i]:
            assert ids[i] == i
    assert b.input_ids.shape == (16, 64)


def test_forward_shapes_and_missing_graph_zeroing(corpus):
    import jax

    synth, token_ids, labels, by_id, _, _ = corpus
    cfg = _model_cfg()
    params = cmb.init_params(cfg, jax.random.key(0))
    b = collate(
        token_ids[:8], labels[:8], list(range(8)), by_id,
        batch_rows=8, node_budget=1024, edge_budget=4096,
    )
    logits = cmb.forward(cfg, params, b.input_ids, b.graphs, b.has_graph)
    assert logits.shape == (8, 2)
    # zeroing: with has_graph all False, output equals text-only path for
    # a head whose graph block sees zeros
    logits2 = cmb.forward(
        cfg, params, b.input_ids, b.graphs, np.zeros((8,), bool)
    )
    assert np.isfinite(np.asarray(logits2)).all()
    assert not np.allclose(np.asarray(logits), np.asarray(logits2))


def test_combined_trains_on_synthetic(corpus):
    synth, token_ids, labels, by_id, train_ids, test_ids = corpus
    from deepdfa_tpu.train import undersample_epoch

    cfg = config_mod.apply_overrides(
        Config(),
        [
            "train.optim.learning_rate=0.001",
            "train.optim.warmup_frac=0.1",
            "train.optim.grad_clip_norm=1.0",
            "train.max_epochs=12",
        ],
    )
    mesh = make_mesh(MeshConfig(dp=8))
    BS, RPS = 32, 4  # 32 rows per step, 4 per shard
    trainer = CombinedTrainer(cfg, _model_cfg(), mesh=mesh, total_steps=12 * 6)

    def batches(ids, drop_remainder=True):
        out = []
        end = len(ids) - len(ids) % BS if drop_remainder else len(ids)
        for k in range(0, end, BS):
            sel = ids[k : k + BS]
            out.append(
                collate_shards(
                    token_ids[sel],
                    [labels[i] for i in sel],
                    list(sel),
                    by_id,
                    num_shards=8,
                    rows_per_shard=RPS,
                    node_budget=512,
                    edge_budget=2048,
                )
            )
        return out

    train_arr = np.array(train_ids)
    train_labels = np.array([labels[i] for i in train_arr])

    def epoch_batches(epoch):
        idx = undersample_epoch(train_labels, epoch, seed=0)
        return batches(train_arr[idx])

    state = trainer.init_state()
    state = trainer.fit(state, epoch_batches)
    metrics, _ = trainer.evaluate(
        state, batches(np.array(test_ids), drop_remainder=False)
    )
    assert metrics["f1"] > 0.9, metrics


def test_combined_fit_without_val_still_checkpoints(corpus, tmp_path):
    """A run with no validation split must still persist weights (periodic +
    final-epoch fallback, mirroring GraphTrainer.fit)."""
    synth, token_ids, labels, by_id, train_ids, _ = corpus
    cfg = config_mod.apply_overrides(Config(), ["train.max_epochs=1"])
    mesh = make_mesh(MeshConfig(dp=8))
    trainer = CombinedTrainer(cfg, _model_cfg(), mesh=mesh, total_steps=2)
    b = collate_shards(
        token_ids[:16], [labels[i] for i in range(16)], list(range(16)),
        by_id, num_shards=8, rows_per_shard=2, node_budget=512,
        edge_budget=2048,
    )
    ckpts = trainer.make_checkpoints(tmp_path / "ckpts")
    state = trainer.init_state()
    trainer.fit(state, lambda epoch: [b], val_batches=None, checkpoints=ckpts)
    assert ckpts._manifest["last"] is not None, (
        "no checkpoint saved for a val-less run"
    )
