"""Train a BPE vocab on the synthetic corpus and round-trip through our
BpeTokenizer (and the HF fast tokenizer as oracle)."""

import pytest

from deepdfa_tpu.data.synthetic import generate
from deepdfa_tpu.data.tokenizer import BpeTokenizer
from deepdfa_tpu.data.tokenizer_training import train_bpe


def test_train_and_load(tmp_path):
    pytest.importorskip("tokenizers")
    synth = generate(120, vuln_rate=0.3, seed=4)
    vocab, merges = train_bpe(
        (s.before for s in synth), tmp_path, vocab_size=600, min_frequency=1
    )
    assert vocab.exists() and merges.exists()

    tok = BpeTokenizer(vocab, merges)
    ids = tok.encode("int f(char *src, int len) { strcpy(buf, src); }", 64)
    assert ids[0] == tok.cls_id
    assert tok.sep_id in ids
    assert (ids >= 0).all() and (ids < tok.vocab_size).all()

    # oracle: HF fast tokenizer over the same trained files
    pytest.importorskip("transformers")
    from transformers import RobertaTokenizerFast

    hf = RobertaTokenizerFast(vocab_file=str(vocab), merges_file=str(merges))
    sample = "for (i = 0; i < len; i++) total += src[i];"
    want = hf(sample, max_length=64, padding="max_length", truncation=True)["input_ids"]
    got = tok.encode(sample, 64)
    assert got.tolist() == want


def test_train_word_level_matches_reference_layout(tmp_path):
    """Word-level asset parity: WordLevel model, Whitespace pre-tokenizer,
    BERT specials at ids 0-4 (LineVul word_level_tokenizer/wordlevel.json)."""
    import json

    from deepdfa_tpu.data.tokenizer_training import train_word_level

    corpus = ["int main ( ) { return 0 ; }", "void f ( int a ) { a ++ ; }"]
    path = train_word_level(corpus, tmp_path / "wordlevel.json")
    d = json.loads(path.read_text())
    assert d["model"]["type"] == "WordLevel"
    assert d["pre_tokenizer"]["type"] == "Whitespace"
    vocab = d["model"]["vocab"]
    assert [vocab[t] for t in ("[UNK]", "[CLS]", "[SEP]", "[PAD]", "[MASK]")] == [
        0, 1, 2, 3, 4,
    ]
    assert "return" in vocab and "int" in vocab

    # loadable by the HF runtime
    from tokenizers import Tokenizer

    tok = Tokenizer.from_file(str(path))
    ids = tok.encode("int main ( )").ids
    assert all(i > 4 for i in ids)
