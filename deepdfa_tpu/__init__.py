"""deepdfa_tpu: a TPU-native vulnerability-detection framework.

A ground-up JAX/XLA/Pallas/pjit re-design of the capabilities of the DeepDFA
reproduction package (ICSE'24, "Dataflow Analysis-Inspired Deep Learning for
Efficient Vulnerability Detection"): abstract-dataflow GGNN models over C/C++
control-flow graphs, combined transformer+graph classifiers, and the full
host-side preprocessing pipeline (CPG extraction, reaching definitions,
abstract dataflow features).

Layering (bottom-up):
  core/      paths, typed config, PRNG discipline, registry
  graphs/    static-shape padded GraphBatch pytree + bucketed batching + storage
  frontend/  host-side C -> CPG -> dataflow features pipeline
  nn/        Flax modules (GGNN message passing, pooling, embeddings)
  models/    DeepDFA classifier, combined transformer+graph models
  parallel/  mesh / sharding / collectives / ring attention
  train/     jit-compiled train loops, samplers, metrics, checkpoints
  eval/      statement-level eval, coverage analysis, profiling
  data/      dataset readers, synthetic corpus generator
  cli/       command-line entry points mirroring the reference pipeline
"""

__version__ = "0.1.0"
