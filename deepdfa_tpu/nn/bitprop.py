"""Differentiable reaching-definitions propagation (bitvector GGNN variant).

The reference's experimental direction behind clipper.py and the
`dataflow_solution_{in,out}` label styles (base_module.py:83-95): make the
network's message passing literally simulate the reaching-definitions
fixpoint over soft bitvectors, supervised by the exact solver's solution.

State: per node, a (0..1)-valued membership vector over definition sites.
Step (mirroring OUT = gen U (IN - kill) with IN = U over preds of OUT):

    in_v   = segment_union of out_u over incoming edges (nn/setops.py)
    out_v  = union(gen_v, in_v * (1 - kill_v))

Iterated n_steps times from out = gen; with hard 0/1 gen/kill and
n_steps >= n_nodes + 1 this EQUALS the worklist solver's fixpoint (a
definition may need to travel the longest def-clear simple path, which
can exceed the CFG diameter, and the returned IN lags OUT by one
iteration — hence the +1). Tested against frontend/reaching.py; stays
differentiable for learned gen/kill parameterizations (learned_gate=True
blends a learned per-node gate into kill, the research knob the
reference was reaching for).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from deepdfa_tpu.frontend.cpg import Cpg
from deepdfa_tpu.frontend.reaching import ReachingDefinitions
from deepdfa_tpu.nn.setops import relu_union, segment_union, simple_union


def rd_bit_problem(cpg: Cpg, max_defs: int, clip: bool = False):
    """Host-side: CFG arrays + gen/kill bit matrices + exact IN/OUT labels.

    Returns None when the graph has no definitions, or (unless `clip`) more
    than max_defs of them; with clip=True only the first max_defs
    definition sites (in node order) carry bits — corpus-label semantics,
    where every graph must produce fixed-width arrays. Dense node order
    follows cfg_nodes(); bit d corresponds to the d-th definition site in
    node order; the returned dict includes that node order under "nodes".
    """
    rd = ReachingDefinitions(cpg)
    nodes, dense, src, dst = rd.dense_cfg()
    sites = [n for n in nodes if rd.gen_set[n]]
    if not sites or (len(sites) > max_defs and not clip):
        return None
    sites = sites[:max_defs]
    site_idx = {n: i for i, n in enumerate(sites)}

    n_nodes = len(nodes)
    gen = np.zeros((n_nodes, max_defs), np.float32)
    kill = np.zeros((n_nodes, max_defs), np.float32)
    var_of_site = {}
    for s in sites:
        (d,) = rd.gen_set[s]
        var_of_site[s] = d.var
    for n in nodes:
        if not rd.gen_set[n]:
            continue
        (d,) = rd.gen_set[n]
        if n in site_idx:  # clipped sites own no bit...
            gen[dense[n], site_idx[n]] = 1.0
        for s in sites:  # ...but still kill tracked sites of their var
            if var_of_site[s] == d.var and s != n:
                kill[dense[n], site_idx[s]] = 1.0

    in_sets = rd.solve()
    labels_in = np.zeros((n_nodes, max_defs), np.float32)
    for n, defs in in_sets.items():
        for d in defs:
            if d.node in site_idx:
                labels_in[dense[n], site_idx[d.node]] = 1.0
    # OUT derives from IN in one pass (no second fixpoint solve)
    labels_out = np.zeros((n_nodes, max_defs), np.float32)
    for n in nodes:
        out_defs = set(rd.gen(n)) | (
            in_sets[n] - rd.kill(n, in_sets[n])
        )
        for d in out_defs:
            if d.node in site_idx:
                labels_out[dense[n], site_idx[d.node]] = 1.0
    return {
        "gen": gen,
        "kill": kill,
        "edge_src": np.array(src, np.int32),
        "edge_dst": np.array(dst, np.int32),
        "labels_in": labels_in,
        "labels_out": labels_out,
        "n_nodes": n_nodes,
        "nodes": nodes,
    }


class BitvectorPropagation(nn.Module):
    """n_steps of differentiable OUT = gen U (IN - kill) over a batch.

    With learned_gate=False this is a parameter-free exact simulator (the
    parity test vs the worklist solver); with learned_gate=True a sigmoid
    gate per node modulates kill — the learnable meet-operator knob.
    """

    n_steps: int
    union_type: str = "simple"  # simple | relu (nn/setops.py)
    learned_gate: bool = False
    #: graph-dimension sharding (parallel/graph_shard.py): with edge
    #: arrays sharded over this mesh axis, each device's segment union
    #: covers only its local edges; the cross-shard combine is the union
    #: monoid REDUCED VIA PSUM IN TRANSFORMED SPACE — relu union is a
    #: clipped sum (clip after psum of the >=0 partials is exact: any
    #: local clip implies the global sum exceeds 1), simple union
    #: reduces over log(1-x) (the same trick segment_union itself uses,
    #: nn/setops.py). One collective, no [P, N, B] gather.
    axis_name: str | None = None

    @nn.compact
    def __call__(
        self,
        gen: jax.Array,  # [N, B]
        kill: jax.Array,  # [N, B]
        edge_src: jax.Array,
        edge_dst: jax.Array,
        edge_mask: jax.Array,
        node_feats: jax.Array | None = None,  # for the learned gate
    ) -> tuple[jax.Array, jax.Array]:
        """Returns (in_state, out_state), each [N, B]."""
        if self.learned_gate:
            gate_in = node_feats if node_feats is not None else gen
            gate = nn.sigmoid(nn.Dense(1, name="kill_gate")(gate_in))
            kill = kill * gate

        union = simple_union if self.union_type == "simple" else relu_union

        out = gen
        in_ = jnp.zeros_like(gen)
        for _ in range(self.n_steps):
            msgs = out[edge_src]
            in_ = segment_union(
                msgs,
                jnp.zeros_like(gen),
                edge_dst,
                edge_mask,
                self.union_type,
            )
            if self.axis_name is not None:
                if self.union_type == "relu":
                    # clipped sum: un-clip is impossible, but a local
                    # clip implies the global sum >= 1, so clipping the
                    # psum of the clipped partials is still exact
                    in_ = 1.0 - jax.nn.relu(
                        1.0 - jax.lax.psum(in_, self.axis_name)
                    )
                else:
                    # simple union over shards = 1 - prod(1 - partial),
                    # reduced in log space (setops.py's own trick)
                    log_keep = jnp.log(jnp.clip(1.0 - in_, 1e-30, 1.0))
                    in_ = 1.0 - jnp.exp(
                        jax.lax.psum(log_keep, self.axis_name)
                    )
            survived = in_ * (1.0 - kill)
            out = union(gen, survived)
        return in_, out
