"""Abstract-dataflow embedding tables.

Each CFG node carries up to four vocab indices — one per abstract-dataflow
subkey (api, datatype, literal, operator). Index scheme (reference:
DDFA/sastvd/scripts/dbize_absdf.py:35-42): 0 = node is not a definition,
1 = UNKNOWN hash, 2.. = train-split hash buckets; table size = limit_all + 2.

`concat_all` mirrors the reference's `concat_all_absdf=True` flagship config
(DDFA/code_gnn/models/flow_gnn/ggnn.py:47-52): one table per subkey,
embeddings concatenated to 4 * hidden_dim.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

SUBKEY_ORDER = ("api", "datatype", "literal", "operator")


class AbstractDataflowEmbedding(nn.Module):
    input_dim: int  # vocab size per table (limit_all + 2)
    embedding_dim: int  # per-table width (reference hidden_dim = 32)
    concat_all: bool = True
    param_dtype: jnp.dtype = jnp.float32
    #: fixed vocab sizes for the family-invariant structural channels
    #: appended after the 4 subkey columns (frontend/structfeat.py);
    #: () = flagship-parity behavior (4 columns only)
    struct_vocab: tuple[int, ...] = ()

    @property
    def out_dim(self) -> int:
        base = self.embedding_dim * (
            len(SUBKEY_ORDER) if self.concat_all else 1
        )
        return base + self.embedding_dim * len(self.struct_vocab)

    @nn.compact
    def __call__(self, node_feats: jax.Array) -> jax.Array:
        """node_feats: [N, 4 (+S)] int32 -> [N, out_dim] embeddings."""
        # extraction ALWAYS writes the 4 subkey columns before any
        # struct columns (data/pipeline.py to_graph_spec), regardless of
        # how many the model embeds — struct offsets are fixed
        struct_off = len(SUBKEY_ORDER)
        if self.struct_vocab:
            want = struct_off + len(self.struct_vocab)
            if node_feats.shape[1] < want:
                raise ValueError(
                    f"struct_vocab={self.struct_vocab} needs "
                    f"{want} feature columns, batch has "
                    f"{node_feats.shape[1]} — extract the corpus with "
                    "struct_feats=True"
                )
        if self.concat_all:
            outs = []
            for i, name in enumerate(SUBKEY_ORDER):
                emb = nn.Embed(
                    self.input_dim,
                    self.embedding_dim,
                    name=f"embed_{name}",
                    param_dtype=self.param_dtype,
                )
                outs.append(emb(node_feats[:, i]))
        else:
            emb = nn.Embed(
                self.input_dim,
                self.embedding_dim,
                name="embed",
                param_dtype=self.param_dtype,
            )
            outs = [emb(node_feats[:, 0])]
        for j, vocab in enumerate(self.struct_vocab):
            emb = nn.Embed(
                vocab,
                self.embedding_dim,
                name=f"embed_struct_{j}",
                param_dtype=self.param_dtype,
            )
            outs.append(emb(node_feats[:, struct_off + j]))
        if len(outs) == 1:
            return outs[0]
        return jnp.concatenate(outs, axis=-1)
