"""Abstract-dataflow embedding tables.

Each CFG node carries up to four vocab indices — one per abstract-dataflow
subkey (api, datatype, literal, operator). Index scheme (reference:
DDFA/sastvd/scripts/dbize_absdf.py:35-42): 0 = node is not a definition,
1 = UNKNOWN hash, 2.. = train-split hash buckets; table size = limit_all + 2.

`concat_all` mirrors the reference's `concat_all_absdf=True` flagship config
(DDFA/code_gnn/models/flow_gnn/ggnn.py:47-52): one table per subkey,
embeddings concatenated to 4 * hidden_dim.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

SUBKEY_ORDER = ("api", "datatype", "literal", "operator")


class AbstractDataflowEmbedding(nn.Module):
    input_dim: int  # vocab size per table (limit_all + 2)
    embedding_dim: int  # per-table width (reference hidden_dim = 32)
    concat_all: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @property
    def out_dim(self) -> int:
        return self.embedding_dim * (len(SUBKEY_ORDER) if self.concat_all else 1)

    @nn.compact
    def __call__(self, node_feats: jax.Array) -> jax.Array:
        """node_feats: [N, 4] int32 -> [N, out_dim] embeddings."""
        if self.concat_all:
            outs = []
            for i, name in enumerate(SUBKEY_ORDER):
                emb = nn.Embed(
                    self.input_dim,
                    self.embedding_dim,
                    name=f"embed_{name}",
                    param_dtype=self.param_dtype,
                )
                outs.append(emb(node_feats[:, i]))
            return jnp.concatenate(outs, axis=-1)
        emb = nn.Embed(
            self.input_dim,
            self.embedding_dim,
            name="embed",
            param_dtype=self.param_dtype,
        )
        return emb(node_feats[:, 0])
