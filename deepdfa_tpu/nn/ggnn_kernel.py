"""Pallas-fused GGNN message-passing step for the TPU train/score/serve
hot path (ROADMAP item 1, docs/ggnn_kernel.md).

Why this exists: the lax GGNN step in `nn/gnn.py` is an XLA-scheduled
chain of dense transform -> masked edge gather -> `segment_sum` scatter
-> GRU, and on TPU the chain is memory-bound, not matmul-bound — train
MFU ~0.007 / infer ~0.003 (BENCH_r05 `last_healthy_tpu`;
docs/roofline.md models the byte traffic). This kernel fuses one FULL
GGNN step — edge-source gather, per-edge-type message transform,
dst-sorted segment-sum aggregation, and the GRU cell update — into one
HBM-resident pass per node block: the message-side node table is staged
into VMEM once, each output node block walks only the edge blocks whose
(sorted) destination range overlaps it, and the aggregate never round-
trips to HBM before the GRU consumes it. The design follows "Fast
Training of Sparse GNNs on Dense Hardware" (PAPERS.md): the scatter is
reformulated as a block-diagonal one-hot matmul so the MXU, not the
scalar core, does the aggregation — the dst-sorted padded layout
`graphs/batch.py` already produces is exactly the block-diagonal
structure that makes the sweep skip ~all off-diagonal blocks.

Two scatter modes (static kernel parameter, both compiled from the same
body structure):

- ``"mxu"`` — one-hot [Eb, Nb] x message [Eb, D] matmul per live
  (node-block, edge-block) pair. The fast path for hardware; its f32
  numerics differ from the lax path only by reduction-order
  reassociation inside the dot (documented tolerance).
- ``"fold"`` — a sequential left fold over the block's edges in edge
  order, which is EXACTLY the accumulation order XLA's sorted
  `segment_sum` scatter applies (verified empirically; pinned in
  tests/test_ggnn_kernel.py). In fp32 this makes the kernel output
  BIT-IDENTICAL to the lax path — the interpret-mode parity contract
  tier-1 enforces across the whole serve warmup ladder.

``scatter="auto"`` resolves to mxu on TPU hardware and fold elsewhere
(interpret), so CPU tier-1 exercises the bit-exact mode and the chip
gets the MXU mode.

bf16 accumulation policy (``accum="bf16"``): the message-side node
table and the per-etype transform weights are cast to bfloat16 — the
gather traffic, the dominant HBM bytes of the step (docs/roofline.md),
halves — while every dot accumulates in f32 (`preferred_element_type`)
and the GRU state/update stays f32. Tolerance vs the f32 path is pinned
in tests (the bound tracks bf16's ~3 decimal digits through one
matmul + masked sum, NOT compounding across steps, because the GRU
re-anchors the state in f32 each step).

Backward (custom_vjp, per step): the transposed problem is a gather by
dst (sorted — cheap) followed by a scatter by src (unsorted — the slow
path XLA's autodiff would take through an unsorted scatter-add,
measured 7.3x slower than sorted in scripts/bench_scatter.py). Instead:

- `_gru_bwd_kernel` fuses the whole GRU backward per node block —
  gates recomputed from the saved (h, a) residuals (the remat choice:
  ~2 small matmuls instead of 3 x [N, 3D] of saved activations),
  elementwise chain, `da`/`dh` products, and the four GRU param
  cotangents accumulated across the sequential grid directly in the
  output refs (the flash_attention `_dbias_kernel` pattern);
- `_dmsg_kernel` fuses the dst-gather with the transposed message
  transform, emitting per-edge `dh`-cotangent rows ALREADY PERMUTED
  into src-sorted order (the permutation is composed into the index
  arrays on the host side of the call, so the kernel's gather does the
  reorder for free);
- the final scatter-by-src then rides `segment_sum(...,
  indices_are_sorted=True)` over the src-sorted layout — the same
  sorted fast path the forward's dst scatter uses, i.e. the backward
  pays sorted-scatter prices in both directions.

The per-etype transform weight cotangents are two thin einsums over
arrays the step already gathered; XLA handles them (25k-param model —
they are noise next to the edge traffic).

Like `nn/flash_attention.py`, every kernel takes an ``interpret`` mode
("legacy" = the generic Pallas interpreter, the CPU tier-1 default;
"tpu" = the TPU-semantics interpreter; False = compile via Mosaic) so
the whole contract is executable and pinned on CPU. Hardware tiling
constraints (D % 128, block divisibility) are checked by
`kernel_shape_ok`; interpret mode relaxes them the way
`flash_shape_ok(lax_alignment=True)` does.

Zero-steady-state-recompile invariant: the kernel is traced inside the
SAME jitted/AOT programs the lax path uses, keyed by the same
`(num_graphs, node_budget, edge_budget)` signatures — it adds no new
program signatures. Trace-time lowering counters per signature land in
the obs registry (`ggnn_kernel/*`, declared in obs/metrics.py:SCHEMA)
so epoch records and serve logs carry the compile census the same way
the PR-2 step cache does.
"""

from __future__ import annotations

import dataclasses
import functools
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


@dataclasses.dataclass(frozen=True)
class _Params:
    """Static kernel parameters (hashable: the custom_vjp nondiff arg)."""

    n: int  # node budget (divisible by block_n)
    e: int  # edge budget (divisible by block_e)
    d: int  # feature width (4*hidden at the flagship: 128)
    block_n: int
    block_e: int
    n_etypes: int
    accum: str  # "fp32" | "bf16" — message-side dtype policy
    scatter: str  # "fold" (order-exact) | "mxu" (one-hot matmul)
    interpret: str | bool  # False | "legacy" | "tpu"

    @property
    def n_nb(self) -> int:
        return self.n // self.block_n

    @property
    def n_eb(self) -> int:
        return self.e // self.block_e

    @property
    def msg_dtype(self):
        return jnp.bfloat16 if self.accum == "bf16" else jnp.float32

    @property
    def interpret_arg(self):
        if self.interpret == "tpu":
            return pltpu.InterpretParams()
        return bool(self.interpret)


def _pick_block(total: int, target: int) -> int:
    """Largest divisor of `total` that is <= target, preferring the
    target itself (budgets are powers of two in every shipped config, so
    this is almost always `target`). A budget whose only divisors near
    the target are tiny (prime/odd budgets) falls back to `total` — ONE
    block — rather than a degenerate 1-wide tiling (a 1-edge block would
    mean `total` grid sweeps; one big block merely costs VMEM, which
    interpret mode does not care about and hardware rejects loudly)."""
    total = int(total)
    if total <= target:
        return max(total, 1)
    for cand in range(target, max(target // 8, 1), -1):
        if total % cand == 0:
            return cand
    return total


def block_sizes(
    node_budget: int, edge_budget: int,
    block_nodes: int = 0, block_edges: int = 0,
) -> tuple[int, int]:
    """Block/tile sizing keyed off the fixed batch budgets: 256-node /
    512-edge tiles at the flagship shape (VMEM: the full [16384, 128]
    message table ~8 MB f32 / 4 MB bf16 + per-tile temporaries ~2 MB),
    shrunk to the largest dividing block for small test budgets."""
    bn = block_nodes or 256
    be = block_edges or 512
    return _pick_block(node_budget, bn), _pick_block(edge_budget, be)


def kernel_shape_ok(
    node_budget: int, edge_budget: int, d: int, *,
    lax_alignment: bool = False,
) -> bool:
    """Can the kernel tile this problem on hardware? Single source of
    truth for dispatch sites (mirrors `flash_shape_ok`). Mosaic needs
    the lane dim (D) to be a multiple of 128; interpret mode
    (``lax_alignment=True``) relaxes that — CPU tests run tiny widths."""
    if d <= 0 or node_budget <= 0 or edge_budget <= 0:
        return False
    if not lax_alignment and d % 128:
        return False
    return True


def resolve_scatter(scatter: str) -> str:
    """"auto" -> "mxu" on TPU hardware (MXU aggregation), "fold"
    elsewhere (the bit-exact interpret parity mode)."""
    if scatter in ("fold", "mxu"):
        return scatter
    if scatter != "auto":
        raise ValueError(f"unknown ggnn_kernel scatter {scatter!r}")
    return "mxu" if jax.default_backend() == "tpu" else "fold"


def resolve_interpret(interpret: str | bool) -> str | bool:
    """"auto" -> compiled on TPU hardware, the (faster) generic
    interpreter elsewhere; explicit values pass through."""
    if interpret != "auto":
        return interpret
    return False if jax.default_backend() == "tpu" else "legacy"


# ---------------------------------------------------------------------------
# trace-time signature census (the PR-2 step-cache convention)

_SIG_LOCK = threading.Lock()
_SIGNATURES: dict[str, int] = {}


def _note_lowering(p: _Params) -> None:
    """Called once per trace of the fused step: counts kernel lowerings
    per batch signature into the process-wide obs registry. Steady state
    (AOT-warmed executors, signature-cached train steps) never re-traces,
    so a growing census IS a recompile — the same guard semantics as
    `jit_lowerings()` on the serve executors."""
    from deepdfa_tpu.obs import metrics as obs_metrics

    sig = f"{p.n}x{p.e}x{p.d}"
    with _SIG_LOCK:
        _SIGNATURES[sig] = _SIGNATURES.get(sig, 0) + 1
        count = _SIGNATURES[sig]
    r = obs_metrics.REGISTRY
    r.counter("ggnn_kernel/lowerings").inc()
    r.gauge(f"ggnn_kernel/signatures/{sig}").set(count)


def signature_stats() -> dict[str, int]:
    """{signature: trace count} for every fused-step lowering this
    process performed (a copy; safe to mutate)."""
    with _SIG_LOCK:
        return dict(_SIGNATURES)


def reset_signature_stats() -> None:
    with _SIG_LOCK:
        _SIGNATURES.clear()


def epoch_record(steps: int | None = None) -> dict:
    """The epoch-record blob train loops embed when the kernel is
    enabled (flattens to `ggnn_kernel/*` tags, declared in SCHEMA)."""
    stats = signature_stats()
    rec: dict = {"lowerings": float(sum(stats.values()))}
    if steps is not None:
        rec["device_steps"] = float(steps)
    for sig, count in sorted(stats.items()):
        rec[f"signatures/{sig}"] = float(count)
    return rec


# ---------------------------------------------------------------------------
# forward kernel


def _aggregate(p: _Params, acc, msg, dst_local):
    """Scatter one edge block's messages into the node-block accumulator.

    msg: [block_e, d] f32 (already masked by the edge weight);
    dst_local: [block_e] i32 destination indices relative to the block
    (out-of-block values are outside [0, block_n) and contribute 0).
    """
    if p.scatter == "mxu":
        # block-diagonal dense scatter: the one-hot rows select the
        # in-block destinations, the MXU does the accumulation. f32
        # one-hot x f32 msg with f32 accumulation — reassociation-only
        # deviation from the sequential fold.
        onehot = (
            dst_local[:, None]
            == jax.lax.broadcasted_iota(
                jnp.int32, (p.block_e, p.block_n), 1
            )
        ).astype(jnp.float32)
        return acc + jax.lax.dot_general(
            onehot, msg, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # "fold": sequential left fold in edge order — bit-identical to the
    # order XLA's sorted segment_sum scatter applies its updates in
    # (the interpret-mode parity contract; see module docstring).
    def body(k, acc):
        idx = dst_local[k]
        ok = (idx >= 0) & (idx < p.block_n)
        idxc = jnp.clip(idx, 0, p.block_n - 1)
        row = jax.lax.dynamic_slice(acc, (idxc, 0), (1, p.d))
        row = row + jnp.where(ok, msg[k][None, :], 0.0)
        return jax.lax.dynamic_update_slice(acc, row, (idxc, 0))

    return jax.lax.fori_loop(0, p.block_e, body, acc)


def _gru(p: _Params, a, h, wih, whh, bih, bhh):
    """torch-convention GRU update, f32, same expression as
    `nn/gnn.py:GRUCell.__call__` (row-blocked matmuls are bit-identical
    to the full-table ones — pinned in tests)."""
    gx = jax.lax.dot_general(
        a, wih, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bih
    gh = jax.lax.dot_general(
        h, whh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bhh
    xr, xz, xn = jnp.split(gx, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1.0 - z) * n + z * h


def _fwd_kernel(p: _Params, bounds_ref, hm_ref, hb_ref, src_ref, dst_ref,
                w_ref, wm_ref, bm_ref, wih_ref, whh_ref, bih_ref, bhh_ref,
                hout_ref, aout_ref):
    i = pl.program_id(0)
    n0 = i * p.block_n
    hm = hm_ref[...]  # [n, d] message-side table (f32 or bf16)
    acc = jnp.zeros((p.block_n, p.d), jnp.float32)

    for t in range(p.n_etypes):
        # per-type partial in its own accumulator, added once at the end
        # — matches the lax path's `a = a + segment_sum(msg_t)` fold
        # association exactly (bit-parity requirement)
        acc_t = jnp.zeros((p.block_n, p.d), jnp.float32)
        for j in range(p.n_eb):

            def live(acc_t, t=t, j=j):
                src = src_ref[j]  # [block_e]
                dst_local = dst_ref[j] - n0
                w = w_ref[t, j].astype(jnp.float32)  # [block_e]
                hg = jnp.take(hm, src, axis=0)  # [block_e, d] gather
                msg = jax.lax.dot_general(
                    hg, wm_ref[t], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) + bm_ref[t].astype(jnp.float32)
                msg = msg * w[:, None]
                return _aggregate(p, acc_t, msg, dst_local)

            # dst-sorted edges: skip blocks whose destination range
            # misses this node block entirely (block-diagonal sweep)
            acc_t = jax.lax.cond(
                (bounds_ref[j, 1] >= n0)
                & (bounds_ref[j, 0] < n0 + p.block_n),
                live, lambda a: a, acc_t,
            )
        acc = acc + acc_t

    h = hb_ref[...]  # [block_n, d] f32 GRU state
    hout_ref[...] = _gru(
        p, acc, h, wih_ref[...], whh_ref[...], bih_ref[...], bhh_ref[...]
    )
    aout_ref[...] = acc


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _full(shape_len: int):
    """Constant-index full-array VMEM spec (staged once, revisited by
    every sequential grid step)."""
    zeros = (0,) * shape_len
    return pl.BlockSpec(memory_space=pltpu.VMEM, index_map=lambda i: zeros)


def _fwd_call(p: _Params, hm, h, src2, dst2, w2, bounds, wm, bm, wih, whh,
              bih, bhh):
    block = pl.BlockSpec(
        (p.block_n, p.d), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    h_out, a_out = pl.pallas_call(
        functools.partial(_fwd_kernel, p),
        grid=(p.n_nb,),
        in_specs=[
            _smem_spec(),  # bounds [n_eb, 2]
            pl.BlockSpec(
                (p.n, p.d), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),  # hm (full message table)
            block,  # h (GRU-state block)
            _full(2),  # src [n_eb, block_e]
            _full(2),  # dst
            _full(3),  # w [T, n_eb, block_e]
            _full(3),  # wm [T, d, d]
            _full(2),  # bm [T, d]
            _full(2),  # wih [d, 3d]
            _full(2),  # whh
            _full(2),  # bih [1, 3d]
            _full(2),  # bhh
        ],
        out_specs=[block, block],
        out_shape=[
            jax.ShapeDtypeStruct((p.n, p.d), jnp.float32),
            jax.ShapeDtypeStruct((p.n, p.d), jnp.float32),
        ],
        interpret=p.interpret_arg,
    )(bounds, hm, h, src2, dst2, w2, wm, bm, wih, whh, bih, bhh)
    return h_out, a_out


# ---------------------------------------------------------------------------
# backward kernels


def _gru_bwd_kernel(p: _Params, h_ref, a_ref, wih_ref, whh_ref, bih_ref,
                    bhh_ref, g_ref, da_ref, dh_ref, dwih_ref, dwhh_ref,
                    dbih_ref, dbhh_ref):
    """Fused GRU backward per node block; gates recomputed from the
    (h, a) residuals (the remat choice — see module docstring). The four
    param cotangents accumulate across the sequential grid directly in
    their output refs (constant index maps; zero-init at program 0)."""
    i = pl.program_id(0)
    h = h_ref[...]
    a = a_ref[...]
    g = g_ref[...]
    wih = wih_ref[...]
    whh = whh_ref[...]

    gx = jax.lax.dot_general(
        a, wih, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bih_ref[...]
    gh = jax.lax.dot_general(
        h, whh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bhh_ref[...]
    xr, xz, xn = jnp.split(gx, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)

    dz = g * (h - n)
    dn = g * (1.0 - z)
    dt = dn * (1.0 - n * n)
    dhn = dt * r
    dr = dt * hn
    dsr = dr * r * (1.0 - r)
    dsz = dz * z * (1.0 - z)
    dgx = jnp.concatenate([dsr, dsz, dt], axis=-1)  # [block_n, 3d]
    dgh = jnp.concatenate([dsr, dsz, dhn], axis=-1)

    da_ref[...] = jax.lax.dot_general(
        dgx, wih, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dh_ref[...] = jax.lax.dot_general(
        dgh, whh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + g * z

    @pl.when(i == 0)
    def _():
        dwih_ref[...] = jnp.zeros_like(dwih_ref)
        dwhh_ref[...] = jnp.zeros_like(dwhh_ref)
        dbih_ref[...] = jnp.zeros_like(dbih_ref)
        dbhh_ref[...] = jnp.zeros_like(dbhh_ref)

    dwih_ref[...] += jax.lax.dot_general(
        a, dgx, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dwhh_ref[...] += jax.lax.dot_general(
        h, dgh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dbih_ref[...] += jnp.sum(dgx, axis=0, keepdims=True)
    dbhh_ref[...] += jnp.sum(dgh, axis=0, keepdims=True)


def _gru_bwd_call(p: _Params, h, a, wih, whh, bih, bhh, g):
    block = pl.BlockSpec(
        (p.block_n, p.d), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    const = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda i: (0,) * len(shape), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        functools.partial(_gru_bwd_kernel, p),
        grid=(p.n_nb,),
        in_specs=[
            block,  # h
            block,  # a
            _full(2), _full(2), _full(2), _full(2),  # gru params
            block,  # g
        ],
        out_specs=[
            block,  # da
            block,  # dh_gru
            const((p.d, 3 * p.d)),
            const((p.d, 3 * p.d)),
            const((1, 3 * p.d)),
            const((1, 3 * p.d)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((p.n, p.d), jnp.float32),
            jax.ShapeDtypeStruct((p.n, p.d), jnp.float32),
            jax.ShapeDtypeStruct((p.d, 3 * p.d), jnp.float32),
            jax.ShapeDtypeStruct((p.d, 3 * p.d), jnp.float32),
            jax.ShapeDtypeStruct((1, 3 * p.d), jnp.float32),
            jax.ShapeDtypeStruct((1, 3 * p.d), jnp.float32),
        ],
        interpret=p.interpret_arg,
    )(h, a, wih, whh, bih, bhh, g)


def _dmsg_kernel(p: _Params, da_ref, dstp_ref, wp_ref, wm_ref, dmsg_ref):
    """Transposed gather: per src-sorted edge block, gather the upstream
    aggregate cotangent by (permuted) destination and push it through
    the transposed message transform — the per-edge `dh` cotangent rows,
    emitted already in src-sorted order for the sorted final scatter."""
    j = pl.program_id(0)
    da = da_ref[...]  # [n, d]
    dag = jnp.take(da, dstp_ref[j], axis=0)  # [block_e, d]
    acc = jnp.zeros((p.block_e, p.d), jnp.float32)
    for t in range(p.n_etypes):
        w = wp_ref[t, j].astype(jnp.float32)
        acc = acc + jax.lax.dot_general(
            dag * w[:, None], wm_ref[t].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    dmsg_ref[...] = acc


def _dmsg_call(p: _Params, da, dstp2, wp2, wm):
    return pl.pallas_call(
        functools.partial(_dmsg_kernel, p),
        grid=(p.n_eb,),
        in_specs=[
            pl.BlockSpec(
                (p.n, p.d), lambda j: (0, 0), memory_space=pltpu.VMEM
            ),  # da table
            _full(2),  # dstp [n_eb, block_e]
            _full(3),  # wp [T, n_eb, block_e]
            _full(3),  # wm [T, d, d]
        ],
        out_specs=pl.BlockSpec(
            (p.block_e, p.d), lambda j: (j, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((p.e, p.d), jnp.float32),
        interpret=p.interpret_arg,
    )(da, dstp2, wp2, wm)


# ---------------------------------------------------------------------------
# the custom_vjp'd fused step


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _step(p: _Params, wm, bm, wih, whh, bih, bhh, h, src2, dst2, w2,
          bounds, src_sorted, dstp2, wp2):
    h_out, _ = _step_fwd_call(p, wm, bm, wih, whh, bih, bhh, h, src2,
                              dst2, w2, bounds)
    return h_out


def _step_fwd_call(p, wm, bm, wih, whh, bih, bhh, h, src2, dst2, w2,
                   bounds):
    hm = h.astype(p.msg_dtype)
    wm_msg = wm.astype(p.msg_dtype)
    return _fwd_call(
        p, hm, h, src2, dst2, w2, bounds, wm_msg, bm, wih, whh, bih, bhh
    )


def _step_fwd(p, wm, bm, wih, whh, bih, bhh, h, src2, dst2, w2, bounds,
              src_sorted, dstp2, wp2):
    h_out, a = _step_fwd_call(p, wm, bm, wih, whh, bih, bhh, h, src2,
                              dst2, w2, bounds)
    # residuals: (h, a) per step — gates are recomputed in the backward
    # kernel (the remat choice), everything else is step-invariant
    res = (wm, bm, wih, whh, bih, bhh, h, a, src2, dst2, w2, src_sorted,
           dstp2, wp2)
    return h_out, res


def _step_bwd(p: _Params, res, g):
    (wm, bm, wih, whh, bih, bhh, h, a, src2, dst2, w2, src_sorted, dstp2,
     wp2) = res
    da, dh_gru, dwih, dwhh, dbih, dbhh = _gru_bwd_call(
        p, h, a, wih, whh, bih, bhh, g
    )
    # transposed gather (by dst, fused in-kernel, emitted src-sorted) ...
    dmsg = _dmsg_call(p, da, dstp2, wp2, wm)
    # ... then the transposed scatter (by src) on the SORTED fast path
    dh_msg = jax.ops.segment_sum(
        dmsg, src_sorted, num_segments=p.n, indices_are_sorted=True
    )
    dh = dh_gru + dh_msg

    # message transform cotangents: thin einsums over arrays the step
    # already indexes; original edge order (sums are order-free here)
    src = src2.reshape(-1)
    dst = dst2.reshape(-1)
    hg = jnp.take(h, src, axis=0)  # [e, d] f32
    dag = jnp.take(da, dst, axis=0)
    w_flat = w2.reshape(p.n_etypes, -1)  # [T, e]
    dwm = jnp.einsum("ed,te,ef->tdf", hg, w_flat, dag)
    dbm = jnp.einsum("te,ef->tf", w_flat, dag)
    return (dwm, dbm, dwih, dwhh, dbih, dbhh, dh,
            None, None, None, None, None, None, None)


_step.defvjp(_step_fwd, _step_bwd)


# ---------------------------------------------------------------------------
# public entry point


def ggnn_propagate(
    wm: jax.Array,  # [T, d, d] per-etype message kernels
    bm: jax.Array,  # [T, d] per-etype message biases
    wih: jax.Array,  # [d, 3d] GRU input projection
    whh: jax.Array,  # [d, 3d] GRU hidden projection
    bih: jax.Array,  # [3d]
    bhh: jax.Array,  # [3d]
    feat: jax.Array,  # [N, d] f32 initial node state
    edge_src: jax.Array,  # [E] i32
    edge_dst: jax.Array,  # [E] i32, non-decreasing (GraphBatch invariant)
    edge_mask: jax.Array,  # [E] bool
    edge_type: jax.Array | None,  # [E] i32 or None
    *,
    n_steps: int,
    n_etypes: int = 1,
    scan_steps: bool = False,
    scatter: str = "auto",
    accum: str = "fp32",
    block_nodes: int = 0,
    block_edges: int = 0,
    interpret: str | bool = "auto",
) -> jax.Array:
    """Run `n_steps` fused GGNN steps; drop-in for the lax step loop in
    `GatedGraphConv.__call__` (same semantics, same [N, d] result).

    The edge preprocessing — per-type masked weights, block reshapes,
    per-edge-block destination bounds, and the src-sorted permutation
    the backward's sorted scatter rides — is pure integer work traced
    once per batch signature and shared by all steps AND by the
    backward pass.
    """
    if accum not in ("fp32", "bf16"):
        raise ValueError(f"unknown ggnn_kernel accum {accum!r}")
    n, d = feat.shape
    e = edge_src.shape[0]
    block_n, block_e = block_sizes(n, e, block_nodes, block_edges)
    interp = resolve_interpret(interpret)
    if not interp and not kernel_shape_ok(n, e, d):
        # fail with the documented guard, not an opaque Mosaic tiling
        # error from deep inside the lowering (the flash_shape_ok
        # dispatch convention)
        raise ValueError(
            f"ggnn_kernel cannot tile d={d} for hardware compilation "
            f"(the lane dim must be a multiple of 128, i.e. "
            f"hidden_dim % 32 == 0 with concat_all_absdf); interpret "
            f"modes relax this — set model.ggnn_kernel=false or use a "
            f"128-aligned feature width"
        )
    p = _Params(
        n=n, e=e, d=d, block_n=block_n, block_e=block_e,
        n_etypes=n_etypes, accum=accum,
        scatter=resolve_scatter(scatter),
        interpret=interp,
    )
    _note_lowering(p)

    feat = feat.astype(jnp.float32)
    w = edge_mask.astype(jnp.float32)
    if n_etypes == 1:
        w2 = w[None]
    else:
        w2 = jnp.stack(
            [w * (edge_type == t).astype(jnp.float32)
             for t in range(n_etypes)]
        )
    src2 = edge_src.reshape(p.n_eb, p.block_e)
    dst2 = edge_dst.reshape(p.n_eb, p.block_e)
    w2 = w2.reshape(p.n_etypes, p.n_eb, p.block_e)
    # dst is sorted, so each block's range is (first, last) — exact ints
    bounds = jnp.stack([dst2[:, 0], dst2[:, -1]], axis=1)
    # src-sorted layout for the backward's sorted scatter (stable sort:
    # deterministic; shared across steps and fwd/bwd)
    perm = jnp.argsort(edge_src, stable=True)
    src_sorted = jnp.take(edge_src, perm)
    dstp2 = jnp.take(edge_dst, perm).reshape(p.n_eb, p.block_e)
    wp2 = jnp.take(w2.reshape(p.n_etypes, -1), perm, axis=1).reshape(
        p.n_etypes, p.n_eb, p.block_e
    )

    bih2 = bih.astype(jnp.float32)[None, :]
    bhh2 = bhh.astype(jnp.float32)[None, :]
    args = (wm.astype(jnp.float32), bm.astype(jnp.float32),
            wih.astype(jnp.float32), whh.astype(jnp.float32), bih2, bhh2)

    def step(h):
        return _step(p, *args, h, src2, dst2, w2, bounds, src_sorted,
                     dstp2, wp2)

    if n_steps == 0:
        return feat
    h = step(feat)
    if scan_steps and n_steps > 1:
        h, _ = jax.lax.scan(
            lambda c, _: (step(c), None), h, None, length=n_steps - 1
        )
    else:
        for _ in range(n_steps - 1):
            h = step(h)
    return h
