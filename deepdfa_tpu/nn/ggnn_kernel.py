"""Pallas-fused GGNN message-passing step for the TPU train/score/serve
hot path (ROADMAP item 1, docs/ggnn_kernel.md).

Why this exists: the lax GGNN step in `nn/gnn.py` is an XLA-scheduled
chain of dense transform -> masked edge gather -> `segment_sum` scatter
-> GRU, and on TPU the chain is memory-bound, not matmul-bound — train
MFU ~0.007 / infer ~0.003 (BENCH_r05 `last_healthy_tpu`;
docs/roofline.md models the byte traffic). This kernel fuses one FULL
GGNN step — edge-source gather, per-edge-type message transform,
dst-sorted segment-sum aggregation, and the GRU cell update — into one
HBM-resident pass per node block: the message-side node table is staged
into VMEM once, each output node block walks only the edge blocks whose
(sorted) destination range overlaps it, and the aggregate never round-
trips to HBM before the GRU consumes it. The design follows "Fast
Training of Sparse GNNs on Dense Hardware" (PAPERS.md): the scatter is
reformulated as a block-diagonal one-hot matmul so the MXU, not the
scalar core, does the aggregation — the dst-sorted padded layout
`graphs/batch.py` already produces is exactly the block-diagonal
structure that makes the sweep skip ~all off-diagonal blocks.

Two scatter modes (static kernel parameter, both compiled from the same
body structure):

- ``"mxu"`` — one-hot [Eb, Nb] x message [Eb, D] matmul per live
  (node-block, edge-block) pair. The fast path for hardware; its f32
  numerics differ from the lax path only by reduction-order
  reassociation inside the dot (documented tolerance).
- ``"fold"`` — a sequential left fold over the block's edges in edge
  order, which is EXACTLY the accumulation order XLA's sorted
  `segment_sum` scatter applies (verified empirically; pinned in
  tests/test_ggnn_kernel.py). In fp32 this makes the kernel output
  BIT-IDENTICAL to the lax path — the interpret-mode parity contract
  tier-1 enforces across the whole serve warmup ladder.

``scatter="auto"`` resolves to mxu on TPU hardware and fold elsewhere
(interpret), so CPU tier-1 exercises the bit-exact mode and the chip
gets the MXU mode.

bf16 accumulation policy (``accum="bf16"``): the message-side node
table and the per-etype transform weights are cast to bfloat16 — the
gather traffic, the dominant HBM bytes of the step (docs/roofline.md),
halves — while every dot accumulates in f32 (`preferred_element_type`)
and the GRU state/update stays f32. Tolerance vs the f32 path is pinned
in tests (the bound tracks bf16's ~3 decimal digits through one
matmul + masked sum, NOT compounding across steps, because the GRU
re-anchors the state in f32 each step).

int8 accumulation policy (``accum="int8"``): true int8 MXU operands —
the message-side node table quantizes to per-ROW (per-node) symmetric
int8 (`_quant_rows`) and the per-etype transform weights to
per-OUTPUT-CHANNEL symmetric int8 (`_quant_wm`); the edge transform
then runs int8 x int8 with int32 accumulation and dequantizes with the
rank-1 outer product of the two scale vectors (exact — the row scale
factors out of the contraction, the column scale out of the output
channel). Under ``scatter="mxu"`` the one-hot scatter ALSO runs on
int8: messages requantize per-COLUMN inside each edge block (the
column scale factors out of the edge sum; the one-hot operand is exact
0/1), int32 accumulation, dequant into the f32 node accumulator. The
GRU state/update stays f32, so like bf16 the error does not compound
across steps; the drift bound vs the f32 lax path is
`INT8_DRIFT_BOUND` (asserted in tests, in `tune/kernel.py`'s
per-candidate numerics verdict, and as an absolute bench-gate bound —
the PR-12 admission-contract idiom).

Whole-unroll fusion (``unroll="fused"``): one `pallas_call` runs ALL
`n_steps` steps on a `(n_steps, n_node_blocks)` grid with the node
state resident in VMEM across steps — a double-buffered `(2, N, D)`
f32 scratch ping-pongs the inter-step GRU chain (TPU grid programs run
sequentially, so every block's step-`s` write lands before step
`s+1`'s gathers read it), and `h` is written back to HBM exactly once,
from a constant-index full-table output buffer flushed at grid end.
`resolve_unroll` admits the mode only when the resident working set
(`fused_residency_bytes`) fits the per-core VMEM budget and the caller
is not under `scan_steps` (whose point is a bounded trace the unrolled
backward would defeat); both fallbacks are LOUD — a warning plus the
`ggnn_kernel/fused_fallbacks` counter. The backward (custom_vjp on the
whole unroll) saves only the step-input `h` chain — streamed to HBM by
a chain-emitting forward variant — and recomputes each step's gates
from it, sweeping the existing per-step backward kernels in reverse.

Backward (custom_vjp, per step): the transposed problem is a gather by
dst (sorted — cheap) followed by a scatter by src (unsorted — the slow
path XLA's autodiff would take through an unsorted scatter-add,
measured 7.3x slower than sorted in scripts/bench_scatter.py). Instead:

- `_gru_bwd_kernel` fuses the whole GRU backward per node block —
  gates recomputed from the saved (h, a) residuals (the remat choice:
  ~2 small matmuls instead of 3 x [N, 3D] of saved activations),
  elementwise chain, `da`/`dh` products, and the four GRU param
  cotangents accumulated across the sequential grid directly in the
  output refs (the flash_attention `_dbias_kernel` pattern);
- `_dmsg_kernel` fuses the dst-gather with the transposed message
  transform, emitting per-edge `dh`-cotangent rows ALREADY PERMUTED
  into src-sorted order (the permutation is composed into the index
  arrays on the host side of the call, so the kernel's gather does the
  reorder for free);
- the final scatter-by-src then rides `segment_sum(...,
  indices_are_sorted=True)` over the src-sorted layout — the same
  sorted fast path the forward's dst scatter uses, i.e. the backward
  pays sorted-scatter prices in both directions.

The per-etype transform weight cotangents are two thin einsums over
arrays the step already gathered; XLA handles them (25k-param model —
they are noise next to the edge traffic).

Like `nn/flash_attention.py`, every kernel takes an ``interpret`` mode
("legacy" = the generic Pallas interpreter, the CPU tier-1 default;
"tpu" = the TPU-semantics interpreter; False = compile via Mosaic) so
the whole contract is executable and pinned on CPU. Hardware tiling
constraints (D % 128, block divisibility) are checked by
`kernel_shape_ok`; interpret mode relaxes them the way
`flash_shape_ok(lax_alignment=True)` does.

Zero-steady-state-recompile invariant: the kernel is traced inside the
SAME jitted/AOT programs the lax path uses, keyed by the same
`(num_graphs, node_budget, edge_budget)` signatures — it adds no new
program signatures. Trace-time lowering counters per signature land in
the obs registry (`ggnn_kernel/*`, declared in obs/metrics.py:SCHEMA)
so epoch records and serve logs carry the compile census the same way
the PR-2 step cache does.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger(__name__)

# relative-error admission bound for accum="int8" vs the f32 lax path —
# analogous to bf16's 5e-2 rung on the PR-8 numerics ladder and to
# serve.quant_drift_bound's default (the PR-12 admission contract).
# Single declaration; tune/kernel.py keys its tolerance table off it and
# obs/bench_gate.py mirrors it as an absolute bound (pinned equal in
# tests).
INT8_DRIFT_BOUND = 5e-2

# per-core VMEM budget the fused-unroll residency check admits against
# (mirrors tune/kernel.py:DEFAULT_VMEM_LIMIT_BYTES; pinned equal in
# tests — declared here too so the nn layer never imports tune/)
VMEM_LIMIT_BYTES = 16 * 2**20


@dataclasses.dataclass(frozen=True)
class _Params:
    """Static kernel parameters (hashable: the custom_vjp nondiff arg)."""

    n: int  # node budget (divisible by block_n)
    e: int  # edge budget (divisible by block_e)
    d: int  # feature width (4*hidden at the flagship: 128)
    block_n: int
    block_e: int
    n_etypes: int
    accum: str  # "fp32" | "bf16" | "int8" — message-side dtype policy
    scatter: str  # "fold" (order-exact) | "mxu" (one-hot matmul)
    interpret: str | bool  # False | "legacy" | "tpu"
    unroll: str = "per_step"  # "per_step" | "fused" (whole-unroll kernel)
    n_steps: int = 1  # step count the fused kernel grids over

    @property
    def n_nb(self) -> int:
        return self.n // self.block_n

    @property
    def n_eb(self) -> int:
        return self.e // self.block_e

    @property
    def msg_dtype(self):
        return jnp.bfloat16 if self.accum == "bf16" else jnp.float32

    @property
    def interpret_arg(self):
        if self.interpret == "tpu":
            return pltpu.InterpretParams()
        return bool(self.interpret)


def _pick_block(total: int, target: int) -> int:
    """Largest divisor of `total` that is <= target, preferring the
    target itself (budgets are powers of two in every shipped config, so
    this is almost always `target`). A budget whose only divisors near
    the target are tiny (prime/odd budgets) falls back to `total` — ONE
    block — rather than a degenerate 1-wide tiling (a 1-edge block would
    mean `total` grid sweeps; one big block merely costs VMEM, which
    interpret mode does not care about and hardware rejects loudly)."""
    total = int(total)
    if total <= target:
        return max(total, 1)
    for cand in range(target, max(target // 8, 1), -1):
        if total % cand == 0:
            return cand
    return total


def block_sizes(
    node_budget: int, edge_budget: int,
    block_nodes: int = 0, block_edges: int = 0,
) -> tuple[int, int]:
    """Block/tile sizing keyed off the fixed batch budgets: 256-node /
    512-edge tiles at the flagship shape (VMEM: the full [16384, 128]
    message table ~8 MB f32 / 4 MB bf16 + per-tile temporaries ~2 MB),
    shrunk to the largest dividing block for small test budgets."""
    bn = block_nodes or 256
    be = block_edges or 512
    return _pick_block(node_budget, bn), _pick_block(edge_budget, be)


def kernel_shape_ok(
    node_budget: int, edge_budget: int, d: int, *,
    lax_alignment: bool = False,
) -> bool:
    """Can the kernel tile this problem on hardware? Single source of
    truth for dispatch sites (mirrors `flash_shape_ok`). Mosaic needs
    the lane dim (D) to be a multiple of 128; interpret mode
    (``lax_alignment=True``) relaxes that — CPU tests run tiny widths."""
    if d <= 0 or node_budget <= 0 or edge_budget <= 0:
        return False
    if not lax_alignment and d % 128:
        return False
    return True


def resolve_scatter(scatter: str) -> str:
    """"auto" -> "mxu" on TPU hardware (MXU aggregation), "fold"
    elsewhere (the bit-exact interpret parity mode)."""
    if scatter in ("fold", "mxu"):
        return scatter
    if scatter != "auto":
        raise ValueError(f"unknown ggnn_kernel scatter {scatter!r}")
    return "mxu" if jax.default_backend() == "tpu" else "fold"


def resolve_interpret(interpret: str | bool) -> str | bool:
    """"auto" -> compiled on TPU hardware, the (faster) generic
    interpreter elsewhere; explicit values pass through."""
    if interpret != "auto":
        return interpret
    return False if jax.default_backend() == "tpu" else "legacy"


def fused_residency_bytes(
    n: int, d: int, accum: str, n_steps: int = 1
) -> int:
    """VMEM the fused unroll keeps resident ON TOP of the per-step
    kernel's staged inputs: the inter-step state chain plus the
    full-table output buffer. The naive chain is ×n_steps node tables;
    the ping-pong scratch caps the resident copies at
    `min(n_steps + 1, 2)` (each step reads one parity and writes the
    other), and the constant-index output buffer adds one more. int8
    adds the quantized shadow table and its per-row scales,
    re-quantized in-kernel each step."""
    resident_states = min(int(n_steps) + 1, 2)
    total = (resident_states + 1) * n * d * 4
    if accum == "int8":
        total += n * d + n * 4
    return total


def _note_fused_fallback(reason: str) -> None:
    """The LOUD half of the fused-unroll fallback contract: a warning
    naming the reason plus the `ggnn_kernel/fused_fallbacks` counter
    (declared under the `ggnn_kernel/*` SCHEMA wildcard), so a config
    that asks for `fused` and silently serves per-step is visible in
    logs, epoch records, and serve diagnostics alike."""
    from deepdfa_tpu.obs import metrics as obs_metrics

    logger.warning("ggnn_kernel: fused unroll unavailable — %s; "
                   "falling back to the per-step kernel", reason)
    obs_metrics.REGISTRY.counter("ggnn_kernel/fused_fallbacks").inc()


def resolve_unroll(
    unroll: str, *, n: int, d: int, n_steps: int, accum: str,
    scan_steps: bool, vmem_limit_bytes: int | None = None,
) -> tuple[str, str]:
    """Admission check for ``unroll="fused"``: returns the effective
    unroll mode and, when it downgrades, the reason (empty string
    otherwise). Two downgrade rules, both documented in
    docs/ggnn_kernel.md:

    - ``scan_steps`` training asked for a bounded trace; the fused
      backward unrolls n_steps per-step backward sweeps at trace time,
      which is exactly what scan exists to avoid — per-step + lax.scan
      is the honest lowering there.
    - the resident working set must fit the per-core VMEM budget
      (`fused_residency_bytes`); over budget falls back rather than
      letting Mosaic (or silent VMEM spilling) decide.
    """
    if unroll not in ("per_step", "fused"):
        raise ValueError(f"unknown ggnn_kernel unroll {unroll!r}")
    if unroll != "fused":
        return "per_step", ""
    if vmem_limit_bytes is None:
        # resolved at call time (not def time) so tests can shrink the
        # module-level budget and watch the fallback fire end-to-end
        vmem_limit_bytes = VMEM_LIMIT_BYTES
    if scan_steps and n_steps > 1:
        return ("per_step",
                "scan_steps requested a bounded trace; the fused "
                "unroll's backward re-unrolls every step")
    need = fused_residency_bytes(n, d, accum, n_steps)
    if need > vmem_limit_bytes:
        return ("per_step",
                f"fused unroll residency {need} B exceeds the VMEM "
                f"budget {vmem_limit_bytes} B at {n}x{d}")
    return "fused", ""


# ---------------------------------------------------------------------------
# int8 quantization (per-channel symmetric; host- OR kernel-side)


def _quant_rows(x):
    """Per-row symmetric int8: scale = max|row|/127 (all-zero rows get
    scale 1.0 so padding quantizes to exact zeros). Returns (q, s) with
    q int8 [n, d] and s f32 [n, 1]; x ~= q * s."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                keepdims=True) / 127.0
    s = jnp.where(s > 0.0, s, 1.0)
    q = jnp.clip(jnp.round(x / s), -127.0, 127.0).astype(jnp.int8)
    return q, s.astype(jnp.float32)


def _quant_wm(wm):
    """Per-output-channel symmetric int8 for the [T, d, d] per-etype
    message transforms (channel = the non-contracted output dim, so the
    scale factors out of the int32 accumulation exactly). Returns
    (q [T, d, d] int8, s [T, d] f32)."""
    s = jnp.max(jnp.abs(wm.astype(jnp.float32)), axis=1,
                keepdims=True) / 127.0  # [T, 1, d]
    s = jnp.where(s > 0.0, s, 1.0)
    q = jnp.clip(jnp.round(wm / s), -127.0, 127.0).astype(jnp.int8)
    return q, s[:, 0, :].astype(jnp.float32)


# ---------------------------------------------------------------------------
# trace-time signature census (the PR-2 step-cache convention)

_SIG_LOCK = threading.Lock()
_SIGNATURES: dict[str, int] = {}


def _note_lowering(p: _Params) -> None:
    """Called once per trace of the fused step: counts kernel lowerings
    per batch signature into the process-wide obs registry. Steady state
    (AOT-warmed executors, signature-cached train steps) never re-traces,
    so a growing census IS a recompile — the same guard semantics as
    `jit_lowerings()` on the serve executors."""
    from deepdfa_tpu.obs import metrics as obs_metrics

    sig = f"{p.n}x{p.e}x{p.d}"
    with _SIG_LOCK:
        _SIGNATURES[sig] = _SIGNATURES.get(sig, 0) + 1
        count = _SIGNATURES[sig]
    r = obs_metrics.REGISTRY
    r.counter("ggnn_kernel/lowerings").inc()
    r.gauge(f"ggnn_kernel/signatures/{sig}").set(count)


def signature_stats() -> dict[str, int]:
    """{signature: trace count} for every fused-step lowering this
    process performed (a copy; safe to mutate)."""
    with _SIG_LOCK:
        return dict(_SIGNATURES)


def reset_signature_stats() -> None:
    with _SIG_LOCK:
        _SIGNATURES.clear()


def epoch_record(steps: int | None = None) -> dict:
    """The epoch-record blob train loops embed when the kernel is
    enabled (flattens to `ggnn_kernel/*` tags, declared in SCHEMA)."""
    stats = signature_stats()
    rec: dict = {"lowerings": float(sum(stats.values()))}
    if steps is not None:
        rec["device_steps"] = float(steps)
    for sig, count in sorted(stats.items()):
        rec[f"signatures/{sig}"] = float(count)
    return rec


# ---------------------------------------------------------------------------
# forward kernel


def _aggregate(p: _Params, acc, msg, dst_local):
    """Scatter one edge block's messages into the node-block accumulator.

    msg: [block_e, d] f32 (already masked by the edge weight);
    dst_local: [block_e] i32 destination indices relative to the block
    (out-of-block values are outside [0, block_n) and contribute 0).
    """
    if p.scatter == "mxu":
        # block-diagonal dense scatter: the one-hot rows select the
        # in-block destinations, the MXU does the accumulation. f32
        # one-hot x f32 msg with f32 accumulation — reassociation-only
        # deviation from the sequential fold.
        onehot_bool = (
            dst_local[:, None]
            == jax.lax.broadcasted_iota(
                jnp.int32, (p.block_e, p.block_n), 1
            )
        )
        if p.accum == "int8":
            # int8 scatter on the MXU: requantize the block's messages
            # per COLUMN (the column scale factors out of the edge sum;
            # the one-hot operand is exact 0/1), accumulate in int32,
            # dequantize into the f32 node accumulator.
            ms = jnp.max(jnp.abs(msg), axis=0, keepdims=True) / 127.0
            ms = jnp.where(ms > 0.0, ms, 1.0)
            msg_q = jnp.clip(
                jnp.round(msg / ms), -127.0, 127.0
            ).astype(jnp.int8)
            part = jax.lax.dot_general(
                onehot_bool.astype(jnp.int8), msg_q,
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            return acc + part.astype(jnp.float32) * ms
        return acc + jax.lax.dot_general(
            onehot_bool.astype(jnp.float32), msg,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # "fold": sequential left fold in edge order — bit-identical to the
    # order XLA's sorted segment_sum scatter applies its updates in
    # (the interpret-mode parity contract; see module docstring).
    def body(k, acc):
        idx = dst_local[k]
        ok = (idx >= 0) & (idx < p.block_n)
        idxc = jnp.clip(idx, 0, p.block_n - 1)
        row = jax.lax.dynamic_slice(acc, (idxc, 0), (1, p.d))
        row = row + jnp.where(ok, msg[k][None, :], 0.0)
        return jax.lax.dynamic_update_slice(acc, row, (idxc, 0))

    return jax.lax.fori_loop(0, p.block_e, body, acc)


def _edge_messages(p: _Params, hm, hs, src, w, wm_t, ws_t, bm_t):
    """One edge block's masked messages [block_e, d] f32 for one etype —
    the body shared verbatim by the per-step and fused kernels (the
    bit-parity contract between them rides on this sharing).

    hm: [n, d] message-side node table (f32, bf16, or int8);
    hs: [n, 1] f32 per-row scales (int8 only; unused otherwise);
    wm_t/ws_t/bm_t: this etype's transform (+ per-channel scales)."""
    hg = jnp.take(hm, src, axis=0)  # [block_e, d] gather
    if p.accum == "int8":
        mm = jax.lax.dot_general(
            hg, wm_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        sg = jnp.take(hs, src, axis=0)  # [block_e, 1]
        msg = (mm.astype(jnp.float32) * sg * ws_t[None, :]
               + bm_t.astype(jnp.float32))
    else:
        msg = jax.lax.dot_general(
            hg, wm_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) + bm_t.astype(jnp.float32)
    return msg * w[:, None]


def _gru(p: _Params, a, h, wih, whh, bih, bhh):
    """torch-convention GRU update, f32, same expression as
    `nn/gnn.py:GRUCell.__call__` (row-blocked matmuls are bit-identical
    to the full-table ones — pinned in tests)."""
    gx = jax.lax.dot_general(
        a, wih, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bih
    gh = jax.lax.dot_general(
        h, whh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bhh
    xr, xz, xn = jnp.split(gx, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1.0 - z) * n + z * h


def _block_aggregate(p: _Params, n0, hm, hs, bounds_ref, src_ref,
                     dst_ref, w_ref, wm_ref, ws_ref, bm_ref):
    """The full message/aggregate sweep for the node block at `n0`:
    per-etype partials over the live edge blocks (block-diagonal skip
    on the dst-sorted bounds), added once at the end — matches the lax
    path's `a = a + segment_sum(msg_t)` fold association exactly (the
    bit-parity requirement). Shared by the per-step and fused kernels."""
    acc = jnp.zeros((p.block_n, p.d), jnp.float32)
    for t in range(p.n_etypes):
        acc_t = jnp.zeros((p.block_n, p.d), jnp.float32)
        for j in range(p.n_eb):

            def live(acc_t, t=t, j=j):
                src = src_ref[j]  # [block_e]
                dst_local = dst_ref[j] - n0
                w = w_ref[t, j].astype(jnp.float32)  # [block_e]
                msg = _edge_messages(
                    p, hm, hs, src, w, wm_ref[t], ws_ref[t], bm_ref[t]
                )
                return _aggregate(p, acc_t, msg, dst_local)

            # dst-sorted edges: skip blocks whose destination range
            # misses this node block entirely (block-diagonal sweep)
            acc_t = jax.lax.cond(
                (bounds_ref[j, 1] >= n0)
                & (bounds_ref[j, 0] < n0 + p.block_n),
                live, lambda a: a, acc_t,
            )
        acc = acc + acc_t
    return acc


def _fwd_kernel(p: _Params, bounds_ref, hm_ref, hs_ref, hb_ref, src_ref,
                dst_ref, w_ref, wm_ref, ws_ref, bm_ref, wih_ref, whh_ref,
                bih_ref, bhh_ref, hout_ref, aout_ref):
    i = pl.program_id(0)
    n0 = i * p.block_n
    hm = hm_ref[...]  # [n, d] message-side table (f32, bf16, or int8)
    hs = hs_ref[...]  # [n, 1] per-row scales (int8; ones otherwise)
    acc = _block_aggregate(p, n0, hm, hs, bounds_ref, src_ref, dst_ref,
                           w_ref, wm_ref, ws_ref, bm_ref)

    h = hb_ref[...]  # [block_n, d] f32 GRU state
    hout_ref[...] = _gru(
        p, acc, h, wih_ref[...], whh_ref[...], bih_ref[...], bhh_ref[...]
    )
    aout_ref[...] = acc


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _full(shape_len: int):
    """Constant-index full-array VMEM spec (staged once, revisited by
    every sequential grid step; grid-rank agnostic)."""
    zeros = (0,) * shape_len
    return pl.BlockSpec(memory_space=pltpu.VMEM, index_map=lambda *_: zeros)


def _fwd_call(p: _Params, hm, hs, h, src2, dst2, w2, bounds, wm, ws, bm,
              wih, whh, bih, bhh):
    block = pl.BlockSpec(
        (p.block_n, p.d), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    h_out, a_out = pl.pallas_call(
        functools.partial(_fwd_kernel, p),
        grid=(p.n_nb,),
        in_specs=[
            _smem_spec(),  # bounds [n_eb, 2]
            pl.BlockSpec(
                (p.n, p.d), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),  # hm (full message table)
            _full(2),  # hs [n, 1] per-row scales (int8; ones otherwise)
            block,  # h (GRU-state block)
            _full(2),  # src [n_eb, block_e]
            _full(2),  # dst
            _full(3),  # w [T, n_eb, block_e]
            _full(3),  # wm [T, d, d]
            _full(2),  # ws [T, d] per-channel scales
            _full(2),  # bm [T, d]
            _full(2),  # wih [d, 3d]
            _full(2),  # whh
            _full(2),  # bih [1, 3d]
            _full(2),  # bhh
        ],
        out_specs=[block, block],
        out_shape=[
            jax.ShapeDtypeStruct((p.n, p.d), jnp.float32),
            jax.ShapeDtypeStruct((p.n, p.d), jnp.float32),
        ],
        interpret=p.interpret_arg,
    )(bounds, hm, hs, h, src2, dst2, w2, wm, ws, bm, wih, whh, bih, bhh)
    return h_out, a_out


# ---------------------------------------------------------------------------
# whole-unroll fused kernel (unroll="fused")


def _fused_kernel(p: _Params, with_chain: bool, *refs):
    """All `n_steps` GGNN steps in one kernel, grid = (step, node
    block) with the step axis slowest: TPU grid programs run
    sequentially, so every node block's step-`s` state write lands in
    the ping-pong scratch before any step-`s+1` program gathers from
    it. `h` reaches HBM once — the constant-index full-table output
    buffer, flushed at grid end (`with_chain` additionally streams each
    step's INPUT state out per block: the backward's only residual)."""
    (bounds_ref, feat_ref, src_ref, dst_ref, w_ref, wm_ref, ws_ref,
     bm_ref, wih_ref, whh_ref, bih_ref, bhh_ref) = refs[:12]
    k = 12
    hout_ref = refs[k]
    k += 1
    chain_ref = None
    if with_chain:
        chain_ref = refs[k]
        k += 1
    hbuf_ref = refs[k]  # VMEM (2, n, d) f32 ping-pong state chain
    k += 1
    hq_ref = hs_ref = None
    if p.accum == "int8":
        hq_ref, hs_ref = refs[k], refs[k + 1]

    s = pl.program_id(0)  # step (slow axis)
    i = pl.program_id(1)  # node block (fast axis)
    n0 = i * p.block_n
    rb = jax.lax.rem(s, 2)  # read parity; writes go to 1 - rb

    @pl.when((s == 0) & (i == 0))
    def _():
        hbuf_ref[0] = feat_ref[...]

    full = (pl.ds(rb, 1), pl.ds(0, p.n), pl.ds(0, p.d))
    if p.accum == "int8":
        # requantize the step's message table once per step (block 0's
        # program; later blocks of the step reuse it — the sequential
        # grid order makes the write-before-read exact)
        @pl.when(i == 0)
        def _():
            q, sc = _quant_rows(pl.load(hbuf_ref, full)[0])
            hq_ref[...] = q
            hs_ref[...] = sc

        hm = hq_ref[...]
        hs = hs_ref[...]
    else:
        hm = pl.load(hbuf_ref, full)[0].astype(p.msg_dtype)
        hs = None

    acc = _block_aggregate(p, n0, hm, hs, bounds_ref, src_ref, dst_ref,
                           w_ref, wm_ref, ws_ref, bm_ref)

    blk = (pl.ds(rb, 1), pl.ds(n0, p.block_n), pl.ds(0, p.d))
    h = pl.load(hbuf_ref, blk)[0]  # [block_n, d] f32 GRU state
    if chain_ref is not None:
        chain_ref[...] = h[None]
    new_h = _gru(
        p, acc, h, wih_ref[...], whh_ref[...], bih_ref[...], bhh_ref[...]
    )
    pl.store(
        hbuf_ref,
        (pl.ds(1 - rb, 1), pl.ds(n0, p.block_n), pl.ds(0, p.d)),
        new_h[None],
    )

    @pl.when(s == p.n_steps - 1)
    def _():
        pl.store(hout_ref, (pl.ds(n0, p.block_n), pl.ds(0, p.d)), new_h)


def _fused_kernel_interp(p: _Params, with_chain: bool, *refs):
    """The fused unroll for emulation: ONE grid program per step
    (grid = (n_steps,)), the node-block sweep unrolled statically
    inside the body. Grid emulation copies every staged block on every
    program, so riding the node blocks on a second grid axis would
    re-copy the full input tables n_nb times per step; here they are
    sliced once per step and the pre-step state table is read once.
    Arithmetic per block is exactly `_fused_kernel`'s — the outputs are
    bitwise equal, so the numerics contract is mode-independent.
    Hardware keeps the 2-D grid (VMEM admission priced the per-block
    layout, and the one-flush h_out needs the constant-index spec)."""
    (bounds_ref, feat_ref, src_ref, dst_ref, w_ref, wm_ref, ws_ref,
     bm_ref, wih_ref, whh_ref, bih_ref, bhh_ref) = refs[:12]
    k = 12
    hout_ref = refs[k]
    k += 1
    chain_ref = None
    if with_chain:
        chain_ref = refs[k]
        k += 1
    # no scratch: the state carries in hout_ref itself (emulation
    # threads out blocks through the grid loop exactly like scratch,
    # and the whole pre-step table is read as a VALUE before any
    # write, so overwriting the carry in place is hazard-free)

    s = pl.program_id(0)
    h_tab = jax.lax.select(  # whole pre-step state, once
        s == 0, feat_ref[...], hout_ref[...]
    )
    if chain_ref is not None:
        chain_ref[...] = h_tab[None]  # the step's INPUT state plane
    if p.accum == "int8":
        hm, hs = _quant_rows(h_tab)
    else:
        hm = h_tab.astype(p.msg_dtype)
        hs = None

    new_blocks = []
    for i in range(p.n_nb):  # static unroll: every node block
        n0 = i * p.block_n
        acc = _block_aggregate(p, n0, hm, hs, bounds_ref, src_ref,
                               dst_ref, w_ref, wm_ref, ws_ref, bm_ref)
        h = h_tab[n0:n0 + p.block_n]
        new_blocks.append(_gru(
            p, acc, h, wih_ref[...], whh_ref[...], bih_ref[...],
            bhh_ref[...]
        ))
    new_tab = (new_blocks[0] if p.n_nb == 1
               else jnp.concatenate(new_blocks, axis=0))
    hout_ref[...] = new_tab  # carry; the final program's write IS h_out


def _fused_call(p: _Params, feat, src2, dst2, w2, bounds, wm, ws, bm,
                wih, whh, bih, bhh, *, with_chain: bool):
    if p.interpret:
        # emulation copies every staged block on every grid program —
        # the interp body collapses the node-block axis into the step
        # program (see _fused_kernel_interp), so specs lose the i axis
        grid = (p.n_steps,)
        kernel = functools.partial(_fused_kernel_interp, p, with_chain)
        out_specs = [pl.BlockSpec(
            (p.n, p.d), lambda s: (0, 0), memory_space=pltpu.VMEM
        )]
        chain_spec = pl.BlockSpec(
            (1, p.n, p.d), lambda s: (s, 0, 0), memory_space=pltpu.VMEM
        )
    else:
        grid = (p.n_steps, p.n_nb)
        kernel = functools.partial(_fused_kernel, p, with_chain)
        out_specs = [pl.BlockSpec(
            (p.n, p.d), lambda *_: (0, 0), memory_space=pltpu.VMEM
        )]  # h_out: full table, constant index -> one flush at grid end
        chain_spec = pl.BlockSpec(
            (1, p.block_n, p.d), lambda s, i: (s, i, 0),
            memory_space=pltpu.VMEM,
        )
    out_shape = [jax.ShapeDtypeStruct((p.n, p.d), jnp.float32)]
    if with_chain:
        out_specs.append(chain_spec)
        out_shape.append(
            jax.ShapeDtypeStruct((p.n_steps, p.n, p.d), jnp.float32)
        )
    if p.interpret:
        # no scratch: state carries in h_out, quantization happens
        # in-register (see _fused_kernel_interp)
        scratch = []
    else:
        scratch = [pltpu.VMEM((2, p.n, p.d), jnp.float32)]
        if p.accum == "int8":
            scratch += [
                pltpu.VMEM((p.n, p.d), jnp.int8),
                pltpu.VMEM((p.n, 1), jnp.float32),
            ]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _smem_spec(),  # bounds [n_eb, 2]
            _full(2),  # feat [n, d] f32 (staged once)
            _full(2),  # src [n_eb, block_e]
            _full(2),  # dst
            _full(3),  # w [T, n_eb, block_e]
            _full(3),  # wm [T, d, d]
            _full(2),  # ws [T, d] per-channel scales
            _full(2),  # bm [T, d]
            _full(2),  # wih [d, 3d]
            _full(2),  # whh
            _full(2),  # bih [1, 3d]
            _full(2),  # bhh
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=p.interpret_arg,
    )(bounds, feat, src2, dst2, w2, wm, ws, bm, wih, whh, bih, bhh)


# ---------------------------------------------------------------------------
# backward kernels


def _gru_bwd_kernel(p: _Params, h_ref, a_ref, wih_ref, whh_ref, bih_ref,
                    bhh_ref, g_ref, da_ref, dh_ref, dwih_ref, dwhh_ref,
                    dbih_ref, dbhh_ref):
    """Fused GRU backward per node block; gates recomputed from the
    (h, a) residuals (the remat choice — see module docstring). The four
    param cotangents accumulate across the sequential grid directly in
    their output refs (constant index maps; zero-init at program 0)."""
    i = pl.program_id(0)
    h = h_ref[...]
    a = a_ref[...]
    g = g_ref[...]
    wih = wih_ref[...]
    whh = whh_ref[...]

    gx = jax.lax.dot_general(
        a, wih, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bih_ref[...]
    gh = jax.lax.dot_general(
        h, whh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bhh_ref[...]
    xr, xz, xn = jnp.split(gx, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)

    dz = g * (h - n)
    dn = g * (1.0 - z)
    dt = dn * (1.0 - n * n)
    dhn = dt * r
    dr = dt * hn
    dsr = dr * r * (1.0 - r)
    dsz = dz * z * (1.0 - z)
    dgx = jnp.concatenate([dsr, dsz, dt], axis=-1)  # [block_n, 3d]
    dgh = jnp.concatenate([dsr, dsz, dhn], axis=-1)

    da_ref[...] = jax.lax.dot_general(
        dgx, wih, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dh_ref[...] = jax.lax.dot_general(
        dgh, whh, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + g * z

    @pl.when(i == 0)
    def _():
        dwih_ref[...] = jnp.zeros_like(dwih_ref)
        dwhh_ref[...] = jnp.zeros_like(dwhh_ref)
        dbih_ref[...] = jnp.zeros_like(dbih_ref)
        dbhh_ref[...] = jnp.zeros_like(dbhh_ref)

    dwih_ref[...] += jax.lax.dot_general(
        a, dgx, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dwhh_ref[...] += jax.lax.dot_general(
        h, dgh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dbih_ref[...] += jnp.sum(dgx, axis=0, keepdims=True)
    dbhh_ref[...] += jnp.sum(dgh, axis=0, keepdims=True)


def _gru_bwd_call(p: _Params, h, a, wih, whh, bih, bhh, g):
    block = pl.BlockSpec(
        (p.block_n, p.d), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    const = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda i: (0,) * len(shape), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        functools.partial(_gru_bwd_kernel, p),
        grid=(p.n_nb,),
        in_specs=[
            block,  # h
            block,  # a
            _full(2), _full(2), _full(2), _full(2),  # gru params
            block,  # g
        ],
        out_specs=[
            block,  # da
            block,  # dh_gru
            const((p.d, 3 * p.d)),
            const((p.d, 3 * p.d)),
            const((1, 3 * p.d)),
            const((1, 3 * p.d)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((p.n, p.d), jnp.float32),
            jax.ShapeDtypeStruct((p.n, p.d), jnp.float32),
            jax.ShapeDtypeStruct((p.d, 3 * p.d), jnp.float32),
            jax.ShapeDtypeStruct((p.d, 3 * p.d), jnp.float32),
            jax.ShapeDtypeStruct((1, 3 * p.d), jnp.float32),
            jax.ShapeDtypeStruct((1, 3 * p.d), jnp.float32),
        ],
        interpret=p.interpret_arg,
    )(h, a, wih, whh, bih, bhh, g)


def _dmsg_kernel(p: _Params, da_ref, dstp_ref, wp_ref, wm_ref, dmsg_ref):
    """Transposed gather: per src-sorted edge block, gather the upstream
    aggregate cotangent by (permuted) destination and push it through
    the transposed message transform — the per-edge `dh` cotangent rows,
    emitted already in src-sorted order for the sorted final scatter."""
    j = pl.program_id(0)
    da = da_ref[...]  # [n, d]
    dag = jnp.take(da, dstp_ref[j], axis=0)  # [block_e, d]
    acc = jnp.zeros((p.block_e, p.d), jnp.float32)
    for t in range(p.n_etypes):
        w = wp_ref[t, j].astype(jnp.float32)
        acc = acc + jax.lax.dot_general(
            dag * w[:, None], wm_ref[t].astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    dmsg_ref[...] = acc


def _dmsg_call(p: _Params, da, dstp2, wp2, wm):
    return pl.pallas_call(
        functools.partial(_dmsg_kernel, p),
        grid=(p.n_eb,),
        in_specs=[
            pl.BlockSpec(
                (p.n, p.d), lambda j: (0, 0), memory_space=pltpu.VMEM
            ),  # da table
            _full(2),  # dstp [n_eb, block_e]
            _full(3),  # wp [T, n_eb, block_e]
            _full(3),  # wm [T, d, d]
        ],
        out_specs=pl.BlockSpec(
            (p.block_e, p.d), lambda j: (j, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((p.e, p.d), jnp.float32),
        interpret=p.interpret_arg,
    )(da, dstp2, wp2, wm)


# ---------------------------------------------------------------------------
# the custom_vjp'd fused step


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _step(p: _Params, wm, bm, wih, whh, bih, bhh, h, src2, dst2, w2,
          bounds, src_sorted, dstp2, wp2):
    h_out, _ = _step_fwd_call(p, wm, bm, wih, whh, bih, bhh, h, src2,
                              dst2, w2, bounds)
    return h_out


def _msg_weight_operands(p: _Params, wm):
    """The message-transform operand pair the kernels consume: the
    (possibly quantized) kernel plus its per-channel scales (exact ones
    outside int8 — loaded but algebraically inert)."""
    if p.accum == "int8":
        return _quant_wm(wm)
    return (wm.astype(p.msg_dtype),
            jnp.ones((p.n_etypes, p.d), jnp.float32))


def _step_fwd_call(p, wm, bm, wih, whh, bih, bhh, h, src2, dst2, w2,
                   bounds):
    if p.accum == "int8":
        hm, hs = _quant_rows(h)
    else:
        hm = h.astype(p.msg_dtype)
        hs = jnp.ones((p.n, 1), jnp.float32)
    wm_msg, ws = _msg_weight_operands(p, wm)
    return _fwd_call(
        p, hm, hs, h, src2, dst2, w2, bounds, wm_msg, ws, bm, wih, whh,
        bih, bhh
    )


def _step_fwd(p, wm, bm, wih, whh, bih, bhh, h, src2, dst2, w2, bounds,
              src_sorted, dstp2, wp2):
    h_out, a = _step_fwd_call(p, wm, bm, wih, whh, bih, bhh, h, src2,
                              dst2, w2, bounds)
    # residuals: (h, a) per step — gates are recomputed in the backward
    # kernel (the remat choice), everything else is step-invariant
    res = (wm, bm, wih, whh, bih, bhh, h, a, src2, dst2, w2, src_sorted,
           dstp2, wp2)
    return h_out, res


def _step_bwd(p: _Params, res, g):
    (wm, bm, wih, whh, bih, bhh, h, a, src2, dst2, w2, src_sorted, dstp2,
     wp2) = res
    da, dh_gru, dwih, dwhh, dbih, dbhh = _gru_bwd_call(
        p, h, a, wih, whh, bih, bhh, g
    )
    # transposed gather (by dst, fused in-kernel, emitted src-sorted) ...
    dmsg = _dmsg_call(p, da, dstp2, wp2, wm)
    # ... then the transposed scatter (by src) on the SORTED fast path
    dh_msg = jax.ops.segment_sum(
        dmsg, src_sorted, num_segments=p.n, indices_are_sorted=True
    )
    dh = dh_gru + dh_msg

    # message transform cotangents: thin einsums over arrays the step
    # already indexes; original edge order (sums are order-free here)
    src = src2.reshape(-1)
    dst = dst2.reshape(-1)
    hg = jnp.take(h, src, axis=0)  # [e, d] f32
    dag = jnp.take(da, dst, axis=0)
    w_flat = w2.reshape(p.n_etypes, -1)  # [T, e]
    dwm = jnp.einsum("ed,te,ef->tdf", hg, w_flat, dag)
    dbm = jnp.einsum("te,ef->tf", w_flat, dag)
    return (dwm, dbm, dwih, dwhh, dbih, dbhh, dh,
            None, None, None, None, None, None, None)


_step.defvjp(_step_fwd, _step_bwd)


# ---------------------------------------------------------------------------
# the custom_vjp'd whole unroll (unroll="fused")


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _unroll(p: _Params, wm, bm, wih, whh, bih, bhh, feat, src2, dst2,
            w2, bounds, src_sorted, dstp2, wp2):
    wm_msg, ws = _msg_weight_operands(p, wm)
    (h_out,) = _fused_call(p, feat, src2, dst2, w2, bounds, wm_msg, ws,
                           bm, wih, whh, bih, bhh, with_chain=False)
    return h_out


def _unroll_fwd(p, wm, bm, wih, whh, bih, bhh, feat, src2, dst2, w2,
                bounds, src_sorted, dstp2, wp2):
    wm_msg, ws = _msg_weight_operands(p, wm)
    h_out, chain = _fused_call(p, feat, src2, dst2, w2, bounds, wm_msg,
                               ws, bm, wih, whh, bih, bhh,
                               with_chain=True)
    # the SINGLE residual set of the whole unroll: each step's input
    # state (chain[s]), streamed from the VMEM-resident ping-pong by
    # the chain-emitting forward variant; gates and aggregates are
    # recomputed per step in the backward (the per-step remat choice,
    # applied across the unroll)
    res = (wm, bm, wih, whh, bih, bhh, chain, src2, dst2, w2, bounds,
           src_sorted, dstp2, wp2)
    return h_out, res


def _unroll_bwd(p: _Params, res, g):
    (wm, bm, wih, whh, bih, bhh, chain, src2, dst2, w2, bounds,
     src_sorted, dstp2, wp2) = res
    dwm = jnp.zeros_like(wm)
    dbm = jnp.zeros_like(bm)
    dwih = jnp.zeros_like(wih)
    dwhh = jnp.zeros_like(whh)
    dbih = jnp.zeros_like(bih)
    dbhh = jnp.zeros_like(bhh)
    dh = g
    # reverse sweep over the chain: recompute step s's aggregate with
    # the per-step forward kernel (its GRU output is dead code), then
    # ride the whole per-step backward — the param cotangents sum
    # across steps, the state cotangent chains backwards
    for s in reversed(range(p.n_steps)):
        h_s = chain[s]
        _, a_s = _step_fwd_call(p, wm, bm, wih, whh, bih, bhh, h_s,
                                src2, dst2, w2, bounds)
        res_s = (wm, bm, wih, whh, bih, bhh, h_s, a_s, src2, dst2, w2,
                 src_sorted, dstp2, wp2)
        grads = _step_bwd(p, res_s, dh)
        dwm = dwm + grads[0]
        dbm = dbm + grads[1]
        dwih = dwih + grads[2]
        dwhh = dwhh + grads[3]
        dbih = dbih + grads[4]
        dbhh = dbhh + grads[5]
        dh = grads[6]
    return (dwm, dbm, dwih, dwhh, dbih, dbhh, dh,
            None, None, None, None, None, None, None)


_unroll.defvjp(_unroll_fwd, _unroll_bwd)


# ---------------------------------------------------------------------------
# public entry point


def ggnn_propagate(
    wm: jax.Array,  # [T, d, d] per-etype message kernels
    bm: jax.Array,  # [T, d] per-etype message biases
    wih: jax.Array,  # [d, 3d] GRU input projection
    whh: jax.Array,  # [d, 3d] GRU hidden projection
    bih: jax.Array,  # [3d]
    bhh: jax.Array,  # [3d]
    feat: jax.Array,  # [N, d] f32 initial node state
    edge_src: jax.Array,  # [E] i32
    edge_dst: jax.Array,  # [E] i32, non-decreasing (GraphBatch invariant)
    edge_mask: jax.Array,  # [E] bool
    edge_type: jax.Array | None,  # [E] i32 or None
    *,
    n_steps: int,
    n_etypes: int = 1,
    scan_steps: bool = False,
    scatter: str = "auto",
    accum: str = "fp32",
    unroll: str = "per_step",
    block_nodes: int = 0,
    block_edges: int = 0,
    interpret: str | bool = "auto",
) -> jax.Array:
    """Run `n_steps` fused GGNN steps; drop-in for the lax step loop in
    `GatedGraphConv.__call__` (same semantics, same [N, d] result).

    The edge preprocessing — per-type masked weights, block reshapes,
    per-edge-block destination bounds, and the src-sorted permutation
    the backward's sorted scatter rides — is pure integer work traced
    once per batch signature and shared by all steps AND by the
    backward pass.

    ``unroll="fused"`` runs the whole step loop inside ONE kernel with
    the state chain VMEM-resident (module docstring), admitted by
    `resolve_unroll`'s residency/scan checks and falling back to
    per-step LOUDLY otherwise.
    """
    if accum not in ("fp32", "bf16", "int8"):
        raise ValueError(f"unknown ggnn_kernel accum {accum!r}")
    n, d = feat.shape
    e = edge_src.shape[0]
    block_n, block_e = block_sizes(n, e, block_nodes, block_edges)
    interp = resolve_interpret(interpret)
    if not interp and not kernel_shape_ok(n, e, d):
        # fail with the documented guard, not an opaque Mosaic tiling
        # error from deep inside the lowering (the flash_shape_ok
        # dispatch convention)
        raise ValueError(
            f"ggnn_kernel cannot tile d={d} for hardware compilation "
            f"(the lane dim must be a multiple of 128, i.e. "
            f"hidden_dim % 32 == 0 with concat_all_absdf); interpret "
            f"modes relax this — set model.ggnn_kernel=false or use a "
            f"128-aligned feature width"
        )
    unroll_eff, fallback_why = resolve_unroll(
        unroll, n=n, d=d, n_steps=n_steps, accum=accum,
        scan_steps=scan_steps,
    )
    if unroll == "fused" and unroll_eff != "fused":
        _note_fused_fallback(fallback_why)
    p = _Params(
        n=n, e=e, d=d, block_n=block_n, block_e=block_e,
        n_etypes=n_etypes, accum=accum,
        scatter=resolve_scatter(scatter),
        interpret=interp,
        unroll=unroll_eff, n_steps=n_steps,
    )
    _note_lowering(p)

    feat = feat.astype(jnp.float32)
    w = edge_mask.astype(jnp.float32)
    if n_etypes == 1:
        w2 = w[None]
    else:
        w2 = jnp.stack(
            [w * (edge_type == t).astype(jnp.float32)
             for t in range(n_etypes)]
        )
    src2 = edge_src.reshape(p.n_eb, p.block_e)
    dst2 = edge_dst.reshape(p.n_eb, p.block_e)
    w2 = w2.reshape(p.n_etypes, p.n_eb, p.block_e)
    # dst is sorted, so each block's range is (first, last) — exact ints
    bounds = jnp.stack([dst2[:, 0], dst2[:, -1]], axis=1)
    # src-sorted layout for the backward's sorted scatter (stable sort:
    # deterministic; shared across steps and fwd/bwd)
    perm = jnp.argsort(edge_src, stable=True)
    src_sorted = jnp.take(edge_src, perm)
    dstp2 = jnp.take(edge_dst, perm).reshape(p.n_eb, p.block_e)
    wp2 = jnp.take(w2.reshape(p.n_etypes, -1), perm, axis=1).reshape(
        p.n_etypes, p.n_eb, p.block_e
    )

    bih2 = bih.astype(jnp.float32)[None, :]
    bhh2 = bhh.astype(jnp.float32)[None, :]
    args = (wm.astype(jnp.float32), bm.astype(jnp.float32),
            wih.astype(jnp.float32), whh.astype(jnp.float32), bih2, bhh2)

    def step(h):
        return _step(p, *args, h, src2, dst2, w2, bounds, src_sorted,
                     dstp2, wp2)

    if n_steps == 0:
        return feat
    if p.unroll == "fused":
        return _unroll(p, *args, feat, src2, dst2, w2, bounds,
                       src_sorted, dstp2, wp2)
    h = step(feat)
    if scan_steps and n_steps > 1:
        h, _ = jax.lax.scan(
            lambda c, _: (step(c), None), h, None, length=n_steps - 1
        )
    else:
        for _ in range(n_steps - 1):
            h = step(h)
    return h
