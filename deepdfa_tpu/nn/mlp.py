"""Output head: stacked Dense+ReLU ending in a single logit.

Mirrors the reference head (DDFA/code_gnn/models/flow_gnn/ggnn.py:70-80):
num_output_layers Linear layers with ReLU between, hidden width equal to
the input width, final layer size 1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class OutputHead(nn.Module):
    num_layers: int
    out_features: int = 1
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        width = x.shape[-1]
        for i in range(self.num_layers):
            last = i == self.num_layers - 1
            x = nn.Dense(
                self.out_features if last else width,
                name=f"dense_{i}",
                param_dtype=self.param_dtype,
            )(x)
            if not last:
                x = jax.nn.relu(x)
        return x
