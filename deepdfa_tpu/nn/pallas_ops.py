"""Pallas TPU kernel for GGNN message passing (gather + segment scatter).

The GGNN hot loop is, per step: msg = (W h)[edge_src]; a = scatter-add of
msg into edge_dst (DGL's C++/CUDA update_all in the reference,
SURVEY.md §2.4). XLA lowers the gather + segment_sum as separate HBM
passes over an [E, D] intermediate; this kernel fuses them — transformed
node states and the accumulator live in VMEM, edges stream through in
blocks, and no [E, D] message tensor ever exists.

Padding contract: callers remap masked edge slots to a dummy row at index
N (the kernel operates on [N+1, D] arrays whose last row is zero), so no
per-edge masking is needed in the inner loop.

VMEM budget: (N+1) * D * 4B * 2 (input + accumulator); with the default
node budget 16384 and D=128 that is ~16MB, so the pallas path is gated on
fitting half of VMEM and falls back to jax.ops.segment_sum otherwise —
same numerics either way (tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

EDGE_BLOCK = 2048


def _scatter_kernel(src_ref, dst_ref, m_ref, out_ref):
    """One edge block: out[dst[e]] += m[src[e]] sequentially.

    Grid steps run sequentially on a TPU core, so accumulating into the
    same full-array output block across steps is safe (revisiting
    pattern); the first step zeroes the accumulator.
    """
    import jax.experimental.pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    def body(e, _):
        s = src_ref[e]
        d = dst_ref[e]
        row = m_ref[pl.ds(s, 1), :]
        out_ref[pl.ds(d, 1), :] += row
        return 0

    jax.lax.fori_loop(0, src_ref.shape[0], body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_edge_scatter(
    m: jax.Array,  # [N, D] transformed node states
    edge_src: jax.Array,  # [E] int32
    edge_dst: jax.Array,  # [E] int32
    edge_mask: jax.Array,  # [E] bool
    interpret: bool = False,
) -> jax.Array:
    """a[v] = sum_{(u,v) in E} m[u]; returns [N, D]."""
    import jax.experimental.pallas as pl

    n, d = m.shape
    e = edge_src.shape[0]
    # dummy zero rows from index n absorb masked edges; row count padded to
    # the float32 sublane tile (8) so VMEM blocks are aligned
    n_rows = ((n + 1 + 7) // 8) * 8
    m_pad = jnp.concatenate(
        [m, jnp.zeros((n_rows - n, d), m.dtype)], axis=0
    )
    src = jnp.where(edge_mask, edge_src, n).astype(jnp.int32)
    dst = jnp.where(edge_mask, edge_dst, n).astype(jnp.int32)
    # pad edges to a block multiple (extra slots hit the dummy row)
    e_pad = ((e + EDGE_BLOCK - 1) // EDGE_BLOCK) * EDGE_BLOCK
    if e_pad != e:
        pad = jnp.full((e_pad - e,), n, jnp.int32)
        src = jnp.concatenate([src, pad])
        dst = jnp.concatenate([dst, pad])

    from jax.experimental.pallas import tpu as pltpu

    grid = (e_pad // EDGE_BLOCK,)
    # edge indices go to SMEM (scalar reads); node states/accumulator in VMEM
    idx_spec = (
        pl.BlockSpec((EDGE_BLOCK,), lambda i: (i,), memory_space=pltpu.SMEM)
        if not interpret
        else pl.BlockSpec((EDGE_BLOCK,), lambda i: (i,))
    )
    out = pl.pallas_call(
        _scatter_kernel,
        grid=grid,
        in_specs=[
            idx_spec,
            idx_spec,
            pl.BlockSpec((n_rows, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((n_rows, d), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_rows, d), m.dtype),
        interpret=interpret,
    )(src, dst, m_pad)
    return out[:n]


def edge_scatter_reference(m, edge_src, edge_dst, edge_mask):
    """The XLA fallback / executable spec."""
    w = edge_mask.astype(m.dtype)[:, None]
    return jax.ops.segment_sum(
        m[edge_src] * w, edge_dst, num_segments=m.shape[0]
    )


def fits_vmem(n: int, d: int, dtype_bytes: int = 4, budget: int = 8 * 2**20) -> bool:
    return (n + 1) * d * dtype_bytes * 2 <= budget
