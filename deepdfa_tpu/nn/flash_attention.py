"""Fused (flash) attention Pallas kernel for the TPU combined-model path.

Why this exists: the reference fine-tunes its transformer encoders with
attention-probs dropout (HF ``attention_probs_dropout_prob=0.1`` — the
LineVul recipe, ``LineVul/linevul/linevul_main.py:150-162``). On the XLA
path that training step materializes, per layer, a ``[B, H, T, T]`` score
tensor AND an equally large Bernoulli dropout mask in HBM (at the
flagship shape — B=64, H=12, T=512, bf16 — ~400 MB of probs plus the
threefry bits per layer, several times per step with rematerialization).
HBM bandwidth is the combined step's bottleneck, not MXU FLOPs
(SURVEY.md §3.3: RoBERTa self-attention dominates the step).

This kernel computes attention blockwise in VMEM with the streaming
log-sum-exp softmax, so the ``T x T`` probabilities never leave the chip,
and generates the dropout mask *inside* the kernel with the TPU PRNG
(`pltpu.prng_seed` / `prng_random_bits`), so the mask is never
materialized either. The backward pass (custom VJP, two more kernels)
recomputes probabilities from the saved log-sum-exp and *regenerates the
identical dropout bits* by reseeding per ``(batch, head, q-block,
k-block)`` — the standard FlashAttention recipe, with dropout handled as
in the repo's streaming formulation (`parallel/ring_attention.py
_block_attn`: dropout scales the numerator only; the softmax denominator
is the undropped sum, matching ``dropout(softmax(s)) @ v``).

Semantics vs the XLA path (`parallel/ring_attention.py:full_attention`):
identical math, different dropout RNG *stream* (TPU PRNG here, threefry
there) — same Bernoulli(1-rate) distribution, which is what training
semantics require (the reference's torch RNG differs from both anyway).

Dropout convention: ``keep = bits < keep_prob * 2**32`` on uint32 bits.
Chosen deliberately: Pallas interpret mode implements `prng_random_bits`
as zeros, so on CPU the PRNG path degrades to keep-everything (a no-op
dropout) instead of drop-everything. Exact dropout math is still fully
testable on CPU by injecting explicit bits via ``debug_bits`` (the
kernels then read bits from HBM instead of the PRNG — used by
tests/test_flash_attention.py to pin fwd AND custom-vjp math against a
pure-jnp oracle given the same mask).

VMEM envelope: per program the kernel holds q/out blocks, the full k/v
strips ([Tk, D]), and (when biased) a [block_q, Tk] bias strip — fine
through Tk ~4k in bf16; beyond that a biased call should fall back to
the XLA path (the un-biased roberta path streams to ~32k tokens).
Ulysses sequence parallelism routes its post-all-to-all local attention
through this kernel too (`parallel/ulysses.py` — the local problem is
exactly the single-device one), CPU-tested inside shard_map via the
interpreter. Ring keeps its XLA blockwise attention: each rotation step
is already streaming O(T_local^2), and folding the kernel in would mean
threading the ring's cross-step (m, l, acc) state through the kernel's
lse — a redesign with nothing left to save.

Kernel decision history: the GGNN scatter Pallas kernel measurably LOST
to XLA's sorted-segment path and was deleted (docs/DESIGN.md §3). This
kernel targets the opposite regime — not a gather/scatter but a fused
softmax chain whose XLA lowering is HBM-traffic-bound — and its win is
verified the same way, by A/B measurement on the real chip
(scripts/bench_combined.py records both paths; docs/bench_history.json).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30  # additive mask value; exp(_NEG_BIG - max) == 0 in f32


@dataclasses.dataclass(frozen=True)
class _Params:
    """Static kernel parameters (hashable: the custom_vjp nondiff arg)."""

    scale: float
    dropout_rate: float
    block_q: int
    block_k: int
    n_q: int
    n_k: int
    use_prng: bool  # False: bits come from the debug_bits input
    has_bias: bool  # additive [H, Tq, Tk] score bias (T5 relative pos)
    causal: bool  # autoregressive mask (decoder self-attention)
    interpret: str | bool  # False | "legacy" | "tpu"

    @property
    def interpret_arg(self):
        # "tpu" = the TPU-semantics interpreter; "legacy" = the generic
        # interpreter (faster). Either way the PRNG path degrades to
        # keep-all on CPU: _bits_for_block short-circuits to zero bits
        # under any interpreter (matching what the TPU-semantics
        # interpreter's prng_random_bits-as-zeros would produce), so on
        # jax builds without InterpretParams "tpu" falls back to the
        # legacy interpreter with identical semantics.
        if self.interpret == "tpu":
            ip = getattr(pltpu, "InterpretParams", None)
            if ip is not None:
                return ip()
            return True
        return bool(self.interpret)

    @property
    def keep_prob(self) -> float:
        return 1.0 - self.dropout_rate

    @property
    def keep_threshold(self) -> int:
        # uint32 threshold: keep = bits < threshold, P(keep) = keep_prob
        return min(int(round(self.keep_prob * 2.0**32)), 2**32 - 1)


def _keep_mask(p: _Params, bits):
    return pltpu.bitcast(bits, jnp.uint32) < jnp.uint32(p.keep_threshold)


def _bits_for_block(p: _Params, seed_ref, bits_ref, b, h, qi, kj, qsl, ksl,
                    num_h):
    """uint32 bits for the (qi, kj) block — PRNG or the debug input.

    The seed is (user seed, flat (b, h, qi, kj) index): any kernel that
    reseeds with the same coordinates regenerates the identical mask,
    which is what makes the fwd and the two bwd kernels agree without
    storing it. Mosaic accepts at most 2 seed values, hence the flat
    block coordinate rather than one value per axis.
    """
    if p.use_prng:
        if p.interpret:
            # no CPU interpreter runs the real TPU PRNG: the TPU-
            # semantics one implements prng_random_bits as zeros and
            # the legacy one has no lowering at all — emit the zeros
            # directly so both give the documented keep-all degrade
            return jnp.zeros((p.block_q, p.block_k), jnp.uint32)
        flat = ((b * num_h + h) * p.n_q + qi) * p.n_k + kj
        pltpu.prng_seed(seed_ref[0], flat)
        return pltpu.prng_random_bits((p.block_q, p.block_k))
    return bits_ref[0, 0, qsl, ksl]


def _scores(q, k_blk, kv_ok, scale, bias_blk=None):
    """Masked scaled scores for one block pair, f32. q:[bq,D] k:[bk,D];
    bias_blk: optional additive [bq, bk] (added unscaled, T5 style)."""
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if bias_blk is not None:
        s = s + bias_blk.astype(jnp.float32)
    return jnp.where(kv_ok, s, _NEG_BIG)


def _block_dead(p: _Params, qi, kj):
    """Causal: True when block (qi, kj) lies entirely above the diagonal
    (every col > every row) — its probs are all zero, so the dots can be
    skipped at runtime. qi/kj may be traced (grid ids)."""
    return kj * p.block_k > qi * p.block_q + (p.block_q - 1)


def _block_ok(p: _Params, kv_ok, qi, kj):
    """Combine the kv padding mask with the causal block mask.

    kv_ok: [1, bk]. Returns [1, bk] or (causal) [bq, bk] — every
    consumer broadcasts. qi/kj are the global block coordinates (grid
    ids or loop indices), so the iota comparison uses global positions.
    """
    if not p.causal:
        return kv_ok
    rows = jax.lax.broadcasted_iota(
        jnp.int32, (p.block_q, p.block_k), 0) + qi * p.block_q
    cols = jax.lax.broadcasted_iota(
        jnp.int32, (p.block_q, p.block_k), 1) + kj * p.block_k
    return kv_ok & (cols <= rows)


def _fwd_kernel(p: _Params, seed_ref, q_ref, k_ref, v_ref, m_ref, bits_ref,
                bias_ref, o_ref, lse_ref):
    b, h, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    q = q_ref[0, 0]  # [bq, D], input dtype
    qsl = pl.ds(0, p.block_q)  # debug_bits rows: block-relative (see spec)

    m_run = jnp.full((p.block_q, 1), _NEG_BIG, jnp.float32)
    l_run = jnp.zeros((p.block_q, 1), jnp.float32)
    acc = jnp.zeros((p.block_q, q.shape[-1]), jnp.float32)

    for kj in range(p.n_k):
        ksl = pl.ds(kj * p.block_k, p.block_k)

        def live(carry, ksl=ksl, kj=kj):
            m_run, l_run, acc = carry
            k_blk = k_ref[0, 0, ksl]  # [bk, D]
            v_blk = v_ref[0, 0, ksl]
            kv_ok = _block_ok(p, (m_ref[0, 0, ksl] != 0)[None, :], qi, kj)
            bias_blk = bias_ref[0, :, ksl] if p.has_bias else None
            s = _scores(q, k_blk, kv_ok, p.scale, bias_blk)
            m_new = jnp.maximum(m_run, jnp.max(s, axis=-1, keepdims=True))
            pr = jnp.where(kv_ok, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m_run - m_new)
            l_run = l_run * alpha + jnp.sum(pr, axis=-1, keepdims=True)
            pv = pr
            if p.dropout_rate > 0.0:
                keep = _keep_mask(
                    p, _bits_for_block(p, seed_ref, bits_ref, b, h, qi, kj,
                                       qsl, ksl, pl.num_programs(1)))
                pv = jnp.where(keep, pr * (1.0 / p.keep_prob), 0.0)
            acc2 = acc * alpha + jax.lax.dot_general(
                pv.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_run, acc2

        carry = (m_run, l_run, acc)
        if p.causal:
            # skip above-diagonal blocks entirely at runtime (roughly
            # half the block pairs) — they contribute zero probability
            m_run, l_run, acc = jax.lax.cond(
                _block_dead(p, qi, kj), lambda c: c, live, carry)
        else:
            m_run, l_run, acc = live(carry)

    l_safe = jnp.maximum(l_run, jnp.finfo(jnp.float32).tiny)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = m_run + jnp.log(l_safe)  # [bq, 1]


def _dq_kernel(p: _Params, seed_ref, q_ref, k_ref, v_ref, m_ref, lse_ref,
               delta_ref, do_ref, bits_ref, bias_ref, dq_ref):
    b, h, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]  # [bq, 1]
    delta = delta_ref[0, 0]
    qsl = pl.ds(0, p.block_q)
    dq = jnp.zeros((p.block_q, q.shape[-1]), jnp.float32)

    for kj in range(p.n_k):
        ksl = pl.ds(kj * p.block_k, p.block_k)

        def live(dq, ksl=ksl, kj=kj):
            k_blk = k_ref[0, 0, ksl]
            v_blk = v_ref[0, 0, ksl]
            kv_ok = _block_ok(p, (m_ref[0, 0, ksl] != 0)[None, :], qi, kj)
            bias_blk = bias_ref[0, :, ksl] if p.has_bias else None
            s = _scores(q, k_blk, kv_ok, p.scale, bias_blk)
            pr = jnp.where(kv_ok, jnp.exp(s - lse), 0.0)  # softmax probs
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk]
            if p.dropout_rate > 0.0:
                keep = _keep_mask(
                    p, _bits_for_block(p, seed_ref, bits_ref, b, h, qi, kj,
                                       qsl, ksl, pl.num_programs(1)))
                dp = jnp.where(keep, dp * (1.0 / p.keep_prob), 0.0)
            ds = pr * (dp - delta)  # softmax vjp; delta = rowsum(do * o)
            return dq + jax.lax.dot_general(
                ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        if p.causal:
            dq = jax.lax.cond(_block_dead(p, qi, kj), lambda d: d, live, dq)
        else:
            dq = live(dq)
    dq_ref[0, 0] = (dq * p.scale).astype(dq_ref.dtype)


def _dkv_kernel(p: _Params, seed_ref, q_ref, k_ref, v_ref, m_ref, lse_ref,
                delta_ref, do_ref, bits_ref, bias_ref, dk_ref, dv_ref):
    b, h, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    k_blk = k_ref[0, 0]  # [bk, D] (this program's k/v block)
    v_blk = v_ref[0, 0]
    kv_pad_ok = (m_ref[0, 0] != 0)[None, :]  # [1, bk]
    ksl = pl.ds(0, p.block_k)  # debug_bits cols: block-relative (see spec)
    dk = jnp.zeros((p.block_k, k_blk.shape[-1]), jnp.float32)
    dv = jnp.zeros((p.block_k, v_blk.shape[-1]), jnp.float32)

    for qi in range(p.n_q):
        qsl = pl.ds(qi * p.block_q, p.block_q)

        def live(carry, qsl=qsl, qi=qi):
            dk, dv = carry
            q = q_ref[0, 0, qsl]  # [bq, D]
            do = do_ref[0, 0, qsl]
            lse = lse_ref[0, 0, qsl]  # [bq, 1]
            delta = delta_ref[0, 0, qsl]
            kv_ok = _block_ok(p, kv_pad_ok, qi, kj)
            bias_blk = bias_ref[0, qsl, :] if p.has_bias else None
            s = _scores(q, k_blk, kv_ok, p.scale, bias_blk)
            pr = jnp.where(kv_ok, jnp.exp(s - lse), 0.0)  # [bq, bk]
            pv = pr
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if p.dropout_rate > 0.0:
                keep = _keep_mask(
                    p, _bits_for_block(p, seed_ref, bits_ref, b, h, qi, kj,
                                       qsl, ksl, pl.num_programs(1)))
                inv = 1.0 / p.keep_prob
                pv = jnp.where(keep, pr * inv, 0.0)
                dp = jnp.where(keep, dp * inv, 0.0)
            dv2 = dv + jax.lax.dot_general(
                pv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bk, D]
            ds = pr * (dp - delta)
            dk2 = dk + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return dk2, dv2

        if p.causal:
            dk, dv = jax.lax.cond(
                _block_dead(p, qi, kj), lambda c: c, live, (dk, dv))
        else:
            dk, dv = live((dk, dv))
    dk_ref[0, 0] = (dk * p.scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _dbias_kernel(p: _Params, seed_ref, q_ref, k_ref, v_ref, m_ref, lse_ref,
                  delta_ref, do_ref, bits_ref, bias_ref, dbias_ref):
    """Accumulate dbias[h, qi-block] = sum over batch of ds.

    Grid is (H, n_q, B) with batch INNERMOST so consecutive programs
    revisit the same (h, qi) output block — the TPU grid is sequential,
    which makes zero-init-at-b==0 + accumulate correct. The bias
    cotangent is only [H, T, T] (batch-summed), so it is the one piece
    of the backward that is cheap to hand to XLA afterwards (T5 buckets
    it into the relative-position embedding via its own scatter).
    """
    h, qi, b = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    q = q_ref[0, 0]  # [bq, D]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]  # [bq, 1]
    delta = delta_ref[0, 0]

    @pl.when(b == 0)
    def _():
        dbias_ref[0] = jnp.zeros_like(dbias_ref[0])

    for kj in range(p.n_k):
        ksl = pl.ds(kj * p.block_k, p.block_k)

        def live(ksl=ksl, kj=kj):
            k_blk = k_ref[0, 0, ksl]
            v_blk = v_ref[0, 0, ksl]
            kv_ok = _block_ok(p, (m_ref[0, 0, ksl] != 0)[None, :], qi, kj)
            bias_blk = bias_ref[0, :, ksl]
            s = _scores(q, k_blk, kv_ok, p.scale, bias_blk)
            pr = jnp.where(kv_ok, jnp.exp(s - lse), 0.0)
            dp = jax.lax.dot_general(
                do, v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if p.dropout_rate > 0.0:
                keep = _keep_mask(
                    p, _bits_for_block(p, seed_ref, bits_ref, b, h, qi, kj,
                                       pl.ds(0, p.block_q), ksl,
                                       pl.num_programs(0)))
                dp = jnp.where(keep, dp * (1.0 / p.keep_prob), 0.0)
            ds = pr * (dp - delta)
            dbias_ref[0, :, ksl] += ds

        if p.causal:
            # above-diagonal blocks contribute zero ds: predicate out
            pl.when(jnp.logical_not(_block_dead(p, qi, kj)))(live)
        else:
            live()


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _bits_specs(p: _Params, T: int, for_dkv: bool, grid: str = "bhi"):
    """BlockSpec for the debug_bits input (dummy [1,1,1,1] when PRNG).

    fwd/dq read a [bq, T] row-block (rows block-relative, cols global);
    dkv reads a [T, bk] col-block (rows global, cols block-relative).
    """
    if p.use_prng:
        return pl.BlockSpec((1, 1, 1, 1), lambda *_: (0, 0, 0, 0),
                            memory_space=pl.ANY)
    if for_dkv:
        return pl.BlockSpec((1, 1, T, p.block_k),
                            lambda b, h, j: (b, h, 0, j),
                            memory_space=pltpu.VMEM)
    if grid == "hib":  # the dbias grid order (h, qi, b)
        return pl.BlockSpec((1, 1, p.block_q, T),
                            lambda h, i, b: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, 1, p.block_q, T),
                        lambda b, h, i: (b, h, i, 0),
                        memory_space=pltpu.VMEM)


def _dummy_bits():
    return jnp.zeros((1, 1, 1, 1), jnp.uint32)


def _bias_spec(p: _Params, T: int, layout: str):
    """BlockSpec for the bias input (dummy [1,1,1] when absent).

    layout "rows": [bq, T] block per (h, qi) — fwd/dq/dbias;
    layout "cols": [T, bk] block per (h, kj) — dkv;
    "rows_hib": same as rows but for the dbias grid order (h, qi, b).
    """
    if not p.has_bias:
        return pl.BlockSpec((1, 1, 1), lambda *_: (0, 0, 0),
                            memory_space=pl.ANY)
    if layout == "cols":
        return pl.BlockSpec((1, T, p.block_k), lambda b, h, j: (h, 0, j),
                            memory_space=pltpu.VMEM)
    if layout == "rows_hib":
        return pl.BlockSpec((1, p.block_q, T), lambda h, i, b: (h, i, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, p.block_q, T), lambda b, h, i: (h, i, 0),
                        memory_space=pltpu.VMEM)


def _dummy_bias():
    return jnp.zeros((1, 1, 1), jnp.float32)


def _fwd_call(p: _Params, q, k, v, mask_i32, seed, bits, bias):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, p),
        grid=(B, H, p.n_q),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, 1, p.block_q, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Tk, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Tk, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Tk), lambda b, h, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            _bits_specs(p, Tk, for_dkv=False),
            _bias_spec(p, Tk, "rows"),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, p.block_q, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, p.block_q, 1), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32),
        ],
        interpret=p.interpret_arg,
    )(seed, q, k, v, mask_i32, bits, bias)
    return out, lse


def _bwd_call(p: _Params, q, k, v, mask_i32, seed, bits, bias, lse, delta,
              do):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    common = [
        _smem_spec(),
        pl.BlockSpec((1, 1, Tq, D), lambda b, h, i: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),  # q (full; dq re-blocks)
        pl.BlockSpec((1, 1, Tk, D), lambda b, h, i: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),  # k
        pl.BlockSpec((1, 1, Tk, D), lambda b, h, i: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),  # v
        pl.BlockSpec((1, 1, Tk), lambda b, h, i: (b, 0, 0),
                     memory_space=pltpu.VMEM),  # mask
        pl.BlockSpec((1, 1, Tq, 1), lambda b, h, i: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),  # lse
        pl.BlockSpec((1, 1, Tq, 1), lambda b, h, i: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),  # delta
        pl.BlockSpec((1, 1, Tq, D), lambda b, h, i: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),  # do
    ]
    dq_specs = list(common)
    dq_specs[1] = pl.BlockSpec((1, 1, p.block_q, D),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM)
    dq_specs[5] = pl.BlockSpec((1, 1, p.block_q, 1),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM)
    dq_specs[6] = pl.BlockSpec((1, 1, p.block_q, 1),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM)
    dq_specs[7] = pl.BlockSpec((1, 1, p.block_q, D),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, p),
        grid=(B, H, p.n_q),
        in_specs=dq_specs + [_bits_specs(p, Tk, for_dkv=False),
                             _bias_spec(p, Tk, "rows")],
        out_specs=pl.BlockSpec((1, 1, p.block_q, D),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
        interpret=p.interpret_arg,
    )(seed, q, k, v, mask_i32, lse, delta, do, bits, bias)

    dkv_specs = list(common)
    dkv_specs[2] = pl.BlockSpec((1, 1, p.block_k, D),
                                lambda b, h, j: (b, h, j, 0),
                                memory_space=pltpu.VMEM)
    dkv_specs[3] = pl.BlockSpec((1, 1, p.block_k, D),
                                lambda b, h, j: (b, h, j, 0),
                                memory_space=pltpu.VMEM)
    dkv_specs[4] = pl.BlockSpec((1, 1, p.block_k), lambda b, h, j: (b, 0, j),
                                memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, p),
        grid=(B, H, p.n_k),
        in_specs=dkv_specs + [_bits_specs(p, Tq, for_dkv=True),
                              _bias_spec(p, Tq, "cols")],
        out_specs=[
            pl.BlockSpec((1, 1, p.block_k, D), lambda b, h, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, p.block_k, D), lambda b, h, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tk, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tk, D), q.dtype),
        ],
        interpret=p.interpret_arg,
    )(seed, q, k, v, mask_i32, lse, delta, do, bits, bias)

    dbias = None
    if p.has_bias:
        dbias_specs = [
            _smem_spec(),
            pl.BlockSpec((1, 1, p.block_q, D),
                         lambda h, i, b: (b, h, i, 0),
                         memory_space=pltpu.VMEM),  # q
            pl.BlockSpec((1, 1, Tk, D), lambda h, i, b: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),  # k
            pl.BlockSpec((1, 1, Tk, D), lambda h, i, b: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),  # v
            pl.BlockSpec((1, 1, Tk), lambda h, i, b: (b, 0, 0),
                         memory_space=pltpu.VMEM),  # mask
            pl.BlockSpec((1, 1, p.block_q, 1),
                         lambda h, i, b: (b, h, i, 0),
                         memory_space=pltpu.VMEM),  # lse
            pl.BlockSpec((1, 1, p.block_q, 1),
                         lambda h, i, b: (b, h, i, 0),
                         memory_space=pltpu.VMEM),  # delta
            pl.BlockSpec((1, 1, p.block_q, D),
                         lambda h, i, b: (b, h, i, 0),
                         memory_space=pltpu.VMEM),  # do
            _bits_specs(p, Tk, for_dkv=False, grid="hib"),
            _bias_spec(p, Tk, "rows_hib"),
        ]
        dbias = pl.pallas_call(
            functools.partial(_dbias_kernel, p),
            grid=(H, p.n_q, B),  # batch innermost: see kernel doc
            in_specs=dbias_specs,
            out_specs=pl.BlockSpec((1, p.block_q, Tk),
                                   lambda h, i, b: (h, i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((H, Tq, Tk), jnp.float32),
            interpret=p.interpret_arg,
        )(seed, q, k, v, mask_i32, lse, delta, do, bits, bias)
    return dq, dk, dv, dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(p: _Params, q, k, v, mask_i32, seed, bits, bias):
    out, _ = _fwd_call(p, q, k, v, mask_i32, seed, bits, bias)
    return out


def _flash_fwd(p: _Params, q, k, v, mask_i32, seed, bits, bias):
    out, lse = _fwd_call(p, q, k, v, mask_i32, seed, bits, bias)
    # named for selective rematerialization: when the enclosing layer is
    # checkpointed with save_only_these_names("attn_ctx", "attn_lse"),
    # the backward replay reuses these instead of re-running the fwd
    # kernel (the custom-vjp residuals below are then assembled from
    # saved/cheap values only) — TransformerConfig.remat_policy
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "attn_ctx")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, mask_i32, seed, bits, bias, out, lse)


def _flash_bwd(p: _Params, res, do):
    q, k, v, mask_i32, seed, bits, bias, out, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    dq, dk, dv, dbias = _bwd_call(
        p, q, k, v, mask_i32, seed, bits, bias, lse, delta, do
    )
    if dbias is not None:
        dbias = dbias.astype(bias.dtype)
    return dq, dk, dv, None, None, None, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_shape_ok(Tq: int, head_dim: int, Tk: int | None = None,
                   biased: bool = False, *,
                   lax_alignment: bool = False) -> bool:
    """Can the kernel tile this problem? Single source of truth for every
    dispatch site (encoder/decoder/ulysses). Kernel blocks are
    min(512, T) per axis: any 128-aligned T <= 512 divides, larger T
    must tile evenly. On hardware T must be a multiple of 128 (the TPU
    lane width): Mosaic's block-shape rules are only validated on-chip
    at aligned lengths (scripts/flash_tpu_check.py runs T=512), so an
    unaligned T that the interpreter happily accepts could be a
    compile-time crash on hardware — "auto" must never select a tiling
    the chip hasn't been proven to take. ``lax_alignment=True`` (the
    interpreter test hook, resolve_impl's interpret_hint) drops the
    128-alignment requirement only — the interpreter doesn't enforce
    Mosaic's rules and CPU tests run tiny unaligned shapes. head_dim is
    capped so q/k/v blocks stay VMEM-sized; biased calls additionally
    cap the sequence (the per-program [block_q, Tk] bias strip — see
    the VMEM envelope note in the module docstring)."""
    def _axis_ok(T):
        if not lax_alignment and T % 128:
            return False
        return T <= 512 or T % 512 == 0

    if Tk is None:
        Tk = Tq
    if biased and max(Tq, Tk) > 4096:
        return False
    return _axis_ok(Tq) and _axis_ok(Tk) and head_dim <= 128


def derive_seed(key: jax.Array) -> jax.Array:
    """int32 [1] kernel seed from a jax PRNG key (the dropout key the
    XLA path would have consumed)."""
    return jax.lax.bitcast_convert_type(
        jax.random.bits(key, (1,), "uint32"), "int32")


def resolve_impl(attn_impl: str, Tq: int, head_dim: int, *,
                 Tk: int | None = None, biased: bool = False,
                 interpret_hint: bool = False) -> str:
    """Resolve "auto"/"xla"/"flash" to a concrete lowering for a given
    problem shape. Forced "flash" on an untileable shape raises; "auto"
    falls back quietly. interpret_hint: the CPU-interpreter test hook is
    active, so flash is eligible off-TPU."""
    if attn_impl == "xla":
        return "xla"
    ok = flash_shape_ok(Tq, head_dim, Tk, biased,
                        lax_alignment=interpret_hint)
    if attn_impl == "flash":
        if not ok:
            raise ValueError(
                f"attn_impl='flash' cannot tile Tq={Tq}, Tk={Tk or Tq}, "
                f"head_dim={head_dim}, biased={biased} (each T needs "
                f"%128==0 on hardware, and <=512 or %512==0; biased "
                f"caps T at 4096)")
        return "flash"
    if attn_impl != "auto":
        raise ValueError(f"unknown attn_impl {attn_impl!r}")
    if not ok:
        return "xla"
    if interpret_hint:
        return "flash"
    return "flash" if jax.default_backend() == "tpu" else "xla"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array,
    *,
    scale: float | None = None,
    dropout_rate: float = 0.0,
    seed: jax.Array | None = None,
    block_q: int = 512,
    block_k: int = 512,
    bias: jax.Array | None = None,
    causal: bool = False,
    debug_bits: jax.Array | None = None,
    interpret: bool | str = False,
) -> jax.Array:
    """Fused attention with in-kernel probs-dropout (drop-in for
    `parallel/ring_attention.full_attention`).

    q: [B, H, Tq, D]; k, v: [B, H, Tk, D] (Tq != Tk is the decoder
    cross-attention case); kv_mask: [B, Tk] (False/0 = padding).
    causal=True applies the autoregressive mask (requires Tq == Tk).
    seed: int32 [1] array seeding the in-kernel PRNG (required when
    dropout_rate > 0 and debug_bits is None). debug_bits: optional
    uint32 [B, H, Tq, Tk] explicit dropout bits — testing hook; replaces
    the PRNG so CPU (interpret) runs can pin the exact dropout math.
    bias: optional additive [H, Tq, Tk] score bias, broadcast over batch
    (T5's relative-position bias; added unscaled, like the reference's
    ``scores + position_bias``). Differentiable in q, k, v, and bias
    (custom VJP, flash backward; dbias via a batch-accumulating kernel).
    """
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if Tq % block_q or Tk % block_k:
        raise ValueError(
            f"flash_attention: Tq={Tq} must divide by block_q={block_q} "
            f"and Tk={Tk} by block_k={block_k}")
    if causal and Tq != Tk:
        raise ValueError(
            f"flash_attention: causal needs Tq == Tk (got {Tq} vs {Tk})")
    if dropout_rate > 0.0 and seed is None and debug_bits is None:
        raise ValueError("flash_attention: dropout needs a seed")
    if bias is not None and bias.shape != (H, Tq, Tk):
        raise ValueError(
            f"flash_attention: bias must be [H={H}, Tq={Tq}, Tk={Tk}] "
            f"(batch-broadcast), got {bias.shape}")
    p = _Params(
        scale=float(scale) if scale is not None else float(D) ** -0.5,
        dropout_rate=float(dropout_rate),
        block_q=block_q,
        block_k=block_k,
        n_q=Tq // block_q,
        n_k=Tk // block_k,
        use_prng=debug_bits is None,
        has_bias=bias is not None,
        causal=causal,
        interpret=interpret,
    )
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    bits = _dummy_bits() if debug_bits is None else debug_bits
    if bias is None:
        bias = _dummy_bias()
    mask_i32 = kv_mask.astype(jnp.int32)[:, None, :]  # [B,1,T]: TPU
    # block specs need the (sub)lane dims of every operand to tile cleanly
    return _flash(p, q, k, v, mask_i32, seed, bits, bias)
