"""Differentiable set operations for bitvector dataflow propagation.

JAX port of the reference's experimental "meet operator" toolkit
(DDFA/code_gnn/models/clipper.py:6-77): union of soft bitvectors used by
the bitvector-propagation GGNN variant, where each node state is a
(0..1)-valued membership vector and message aggregation is set union
rather than sum.

  simple_union(a, b) = a + b - a*b   (probabilistic OR)
  relu_union(a, b)   = 1 - relu(1 - (a + b))  (= min(a + b, 1), piecewise
                       linear; reference test_smoothness semantics)

`segment_union` is the GraphBatch aggregation counterpart of the
reference's DGL mailbox UDF (dgl_union_factory): a fold of the chosen
union over each destination node's incoming messages.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def simple_union(a: jax.Array, b: jax.Array) -> jax.Array:
    return a + b - a * b


def relu_union(a: jax.Array, b: jax.Array) -> jax.Array:
    return 1.0 - jax.nn.relu(1.0 - (a + b))


def segment_union(
    messages: jax.Array,
    init: jax.Array,
    segment_ids: jax.Array,
    mask: jax.Array,
    union_type: str = "simple",
) -> jax.Array:
    """Fold a union over each segment's messages.

    messages: [E, D] soft bitvectors; init: [N, D] starting state per
    node; segment_ids: [E] destination node per message; mask: [E].

    simple union is associative-and-commutative over products:
    U_i x_i = 1 - prod_i (1 - x_i), so it reduces with one segment
    product. relu_union (= clipped sum) reduces with a clipped
    segment-sum. Both match a sequential fold of the pairwise op.
    """
    n = init.shape[0]
    m = mask.astype(messages.dtype)[:, None]
    if union_type == "simple":
        # fold into log-space-free closed form: 1 - (1-init) * prod(1-msg)
        one_minus = 1.0 - messages * m  # masked slots contribute 1
        log_terms = jnp.log(jnp.clip(one_minus, 1e-30, 1.0))
        prod = jnp.exp(
            jax.ops.segment_sum(log_terms, segment_ids, num_segments=n)
        )
        return 1.0 - (1.0 - init) * prod
    if union_type == "relu":
        s = jax.ops.segment_sum(messages * m, segment_ids, num_segments=n)
        return 1.0 - jax.nn.relu(1.0 - (init + s))
    raise ValueError(f"unknown union_type {union_type}")
