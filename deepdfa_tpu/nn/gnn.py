"""Graph neural network primitives over padded `GraphBatch`es.

TPU-native re-design of the reference model's DGL ops
(DDFA/code_gnn/models/flow_gnn/ggnn.py:5 — `GatedGraphConv`,
`GlobalAttentionPooling`, both backed by DGL C++/CUDA kernels):

- message passing = dense transform + masked edge gather + segment-sum
  scatter, which XLA fuses and tiles onto the MXU/VPU; no dynamic shapes.
- the GRU update matches torch.nn.GRUCell equations exactly (DGL uses
  torch's GRUCell), so numerical parity with the reference holds for
  identical weights — see tests/test_nn_parity.py.
- pooling = numerically-stable masked segment softmax; padded node slots
  belong to a dummy segment that is sliced off.

Everything is a pure function of (params, batch) under `flax.linen`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from deepdfa_tpu.graphs.batch import GraphBatch


def segment_sum(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    return jax.ops.segment_sum(
        data,
        segment_ids,
        num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def segment_max(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    return jax.ops.segment_max(
        data,
        segment_ids,
        num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def segment_softmax(
    scores: jax.Array,
    segment_ids: jax.Array,
    mask: jax.Array,
    num_segments: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """Masked softmax within segments; masked slots get weight 0."""
    neg = jnp.finfo(scores.dtype).min
    scores = jnp.where(mask, scores, neg)
    smax = segment_max(scores, segment_ids, num_segments, indices_are_sorted)
    smax = jnp.maximum(smax, neg)  # empty segments
    ex = jnp.exp(scores - smax[segment_ids])
    ex = jnp.where(mask, ex, 0.0)
    denom = segment_sum(ex, segment_ids, num_segments, indices_are_sorted)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    return ex / denom[segment_ids]


def attention_pool(
    gate: jax.Array,
    feat: jax.Array,
    node_graph: jax.Array,
    node_mask: jax.Array,
    num_graphs: int,
) -> tuple[jax.Array, jax.Array]:
    """Gated-attention readout core: ([G, D] pooled, [N] attention).

    The ONE implementation of the masked-segment-softmax pooling shared
    by `GlobalAttentionPooling.__call__` (the model path) and
    `eval/localize.py:ggnn_forward` (the attribution path, which needs
    the per-node attention weights the module used to discard). A
    single body means a kernel swap or numerics change in either
    consumer cannot silently diverge the two — the bit-parity test in
    tests/test_scan.py pins them equal.

    `gate`: [N] pre-softmax gate scores; padding slots must map to
    segment `num_graphs` (the batcher invariant) and are masked out.
    """
    attn = segment_softmax(
        gate, node_graph, node_mask, num_graphs + 1,
        indices_are_sorted=True,
    )
    pooled = segment_sum(
        attn[:, None] * feat, node_graph, num_graphs + 1,
        indices_are_sorted=True,
    )
    return pooled[:num_graphs], attn


class _DenseParams(nn.Module):
    """Parameter-only twin of `nn.Dense`: creates the identical
    {kernel, bias} subtree (same shapes, same initializers, same
    path-derived RNG folding) WITHOUT computing `x @ W + b` — the
    fused-kernel path reads the raw arrays and does the math inside the
    Pallas kernel. A checkpoint trained on either path restores into
    the other bit-for-bit."""

    features: int
    in_features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self) -> tuple[jax.Array, jax.Array]:
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (self.in_features, self.features), self.param_dtype,
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(),
            (self.features,), self.param_dtype,
        )
        return kernel, bias


class _GRUParams(nn.Module):
    """Parameter-only twin of `GRUCell` (input_proj/hidden_proj Dense
    subtrees under the same names)."""

    features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self):
        wih, bih = _DenseParams(
            3 * self.features, self.features, self.param_dtype,
            name="input_proj",
        )()
        whh, bhh = _DenseParams(
            3 * self.features, self.features, self.param_dtype,
            name="hidden_proj",
        )()
        return wih, bih, whh, bhh


class GRUCell(nn.Module):
    """torch.nn.GRUCell-compatible gated update (reset-before-candidate).

    r = sigmoid(W_ir x + b_ir + W_hr h + b_hr)
    z = sigmoid(W_iz x + b_iz + W_hz h + b_hz)
    n = tanh(W_in x + b_in + r * (W_hn h + b_hn))
    h' = (1 - z) * n + z * h

    The three input/hidden projections are fused into two matmuls so the MXU
    sees [N, D] @ [D, 3D].
    """

    features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, h: jax.Array) -> jax.Array:
        dense = lambda name: nn.Dense(
            3 * self.features, name=name, param_dtype=self.param_dtype
        )
        gx = dense("input_proj")(x)
        gh = dense("hidden_proj")(h)
        xr, xz, xn = jnp.split(gx, 3, axis=-1)
        hr, hz, hn = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        return (1.0 - z) * n + z * h


class GatedGraphConv(nn.Module):
    """Gated Graph Convolution (Li et al. 2016) with DGL-parity semantics.

    Per step: a_v = sum_{(u,v) in E} W h_u ; h_v = GRU(a_v, h_v).
    Input features narrower than `out_features` are zero-padded, matching
    DGL's GatedGraphConv.

    Step weights are shared (DGL semantics), so the loop can compile two
    ways: unrolled (default — XLA pipelines the gather/matmul chain) or
    `scan_steps=True`, which runs step 1 eagerly (binding the params in
    this scope) and lax.scan's the rest — a knob for compile-time-
    constrained environments (the remote TPU compile service wedged on
    the unrolled flagship train step; measured on CPU the scan trims
    the train-step StableHLO 156->135 KiB, so program size is a minor
    factor there, but the loop form is the one structural lever the
    model has). Same param tree either way; numerics equal to float32
    fusion tolerance (tests/test_nn_parity.py pins scan == unroll on
    forward and grads).
    """

    out_features: int
    n_steps: int
    n_etypes: int = 1
    param_dtype: jnp.dtype = jnp.float32
    scan_steps: bool = False
    #: graph-dimension sharding (SURVEY §2.5b): inside shard_map with the
    #: batch's EDGE arrays sharded over this mesh axis (nodes replicated),
    #: each device segment-sums its local edges' messages and one psum
    #: makes the aggregate exact — shards the O(E·D) gather/scatter work
    #: for graph batches whose edges exceed one chip. No param change.
    axis_name: str | None = None
    #: Pallas-fused step (nn/ggnn_kernel.py): gather + transform +
    #: dst-sorted scatter + GRU in one HBM-resident pass. Identical
    #: param tree (parameter-only twin modules), so checkpoints move
    #: freely between paths; fp32 + fold scatter is bit-identical to
    #: the lax path under jit (docs/ggnn_kernel.md numerics contract).
    use_kernel: bool = False
    kernel_scatter: str = "auto"  # auto | fold | mxu
    kernel_accum: str = "fp32"  # fp32 | bf16 | int8 message-side policy
    kernel_unroll: str = "per_step"  # per_step | fused (whole unroll)
    kernel_block_nodes: int = 0  # 0 = auto from the node budget
    kernel_block_edges: int = 0  # 0 = auto from the edge budget
    kernel_interpret: str | bool = "auto"  # auto | False | legacy | tpu

    @nn.compact
    def __call__(self, batch: GraphBatch, feat: jax.Array) -> jax.Array:
        if self.n_etypes != 1 and batch.edge_type is None:
            # silently mixing all relations through every transform would
            # be wrong; pack GraphSpecs carrying edge_type arrays
            raise ValueError(
                f"n_etypes={self.n_etypes} needs edge-type ids on the "
                "batch (GraphSpec.edge_type)"
            )
        if self.n_etypes == 1 and batch.edge_type is not None:
            # the mirror-image config error: a typed store (gtype=cfg+dep)
            # fed to a single-relation model would sum dependence edges
            # through the cfg transform with no signal anything is off
            raise ValueError(
                "batch carries edge-type ids but n_etypes=1; set "
                "model.n_etypes to the relation count (cfg+dep: 3)"
            )
        n = feat.shape[0]
        if feat.shape[-1] > self.out_features:
            raise ValueError(
                f"input dim {feat.shape[-1]} > out_features {self.out_features}"
            )
        if feat.shape[-1] < self.out_features:
            feat = jnp.pad(feat, ((0, 0), (0, self.out_features - feat.shape[-1])))

        if self.use_kernel:
            if self.axis_name is not None:
                raise ValueError(
                    "ggnn_kernel does not compose with edge-sharded "
                    "message passing (axis_name); run the kernel "
                    "un-sharded or keep the lax path for graph_shard"
                )
            if self.n_steps == 0:
                # the lax branch never calls its submodules for 0 steps
                # (no params materialize); match that tree exactly
                return feat
            # parameter-only twins under the SAME names/paths as the
            # lax branch below — identical init and checkpoint layout
            etype_params = [
                _DenseParams(
                    self.out_features, self.out_features,
                    self.param_dtype, name=f"etype_{i}",
                )()
                for i in range(self.n_etypes)
            ]
            wih, bih, whh, bhh = _GRUParams(
                self.out_features, self.param_dtype, name="GRUCell_0"
            )()
            from deepdfa_tpu.nn import ggnn_kernel as _gk

            return _gk.ggnn_propagate(
                jnp.stack([k for k, _ in etype_params]),
                jnp.stack([b for _, b in etype_params]),
                wih, whh, bih, bhh, feat,
                batch.edge_src, batch.edge_dst, batch.edge_mask,
                batch.edge_type,
                n_steps=self.n_steps,
                n_etypes=self.n_etypes,
                scan_steps=self.scan_steps,
                scatter=self.kernel_scatter,
                accum=self.kernel_accum,
                unroll=self.kernel_unroll,
                block_nodes=self.kernel_block_nodes,
                block_edges=self.kernel_block_edges,
                interpret=self.kernel_interpret,
            )

        edge_w = batch.edge_mask.astype(feat.dtype)[:, None]

        def _etype_w(i):
            if self.n_etypes == 1:
                return edge_w
            # relation-restricted messages: each type's transform sees
            # only its own edges (DGL GatedGraphConv etypes semantics),
            # as one extra mask on the same fast path
            return edge_w * (batch.edge_type == i).astype(feat.dtype)[
                :, None
            ]

        if self.n_steps == 0:
            return feat

        if self.scan_steps and self.n_steps > 1:
            # Flax module calls can't appear inside lax.scan's traced
            # body (the scope is no longer bound there), so the scan
            # form binds the SAME param tree through the parameter-only
            # twins — identical names/shapes/init to the module path
            # below — and does the Dense/GRU math inline
            etype_params = [
                _DenseParams(
                    self.out_features, self.out_features,
                    self.param_dtype, name=f"etype_{i}",
                )()
                for i in range(self.n_etypes)
            ]
            wih, bih, whh, bhh = _GRUParams(
                self.out_features, self.param_dtype, name="GRUCell_0"
            )()

            def raw_step(h):
                a = jnp.zeros((n, self.out_features), feat.dtype)
                for i, (k, b) in enumerate(etype_params):
                    m = h @ k + b  # [N, D] on the MXU
                    msg = m[batch.edge_src] * _etype_w(i)
                    a = a + segment_sum(
                        msg, batch.edge_dst, n, indices_are_sorted=True
                    )
                if self.axis_name is not None:
                    a = jax.lax.psum(a, self.axis_name)
                gx = a @ wih + bih
                gh = h @ whh + bhh
                xr, xz, xn = jnp.split(gx, 3, axis=-1)
                hr, hz, hn = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                cand = jnp.tanh(xn + r * hn)
                return (1.0 - z) * cand + z * h

            h = raw_step(feat)
            h, _ = jax.lax.scan(
                lambda c, _: (raw_step(c), None), h, None,
                length=self.n_steps - 1,
            )
            return h

        # one message transform per edge type (CFG graphs use a single type)
        linears = [
            nn.Dense(self.out_features, name=f"etype_{i}", param_dtype=self.param_dtype)
            for i in range(self.n_etypes)
        ]
        gru = GRUCell(self.out_features, param_dtype=self.param_dtype)

        def step(h):
            a = jnp.zeros((n, self.out_features), feat.dtype)
            for i, linear in enumerate(linears):
                m = linear(h)  # [N, D] on the MXU
                msg = m[batch.edge_src] * _etype_w(i)  # masked gather
                # the batcher emits dst-sorted edges (padding carries
                # the max segment id), enabling the sorted fast path —
                # measured 12.6x faster than a fused Pallas VMEM kernel
                # at the flagship shape (scripts/bench_scatter.py)
                a = a + segment_sum(
                    msg, batch.edge_dst, n, indices_are_sorted=True
                )
            if self.axis_name is not None:
                # exact cross-shard aggregate (each shard summed only its
                # own edge slice; contiguous slices of the dst-sorted
                # edge list stay sorted, so the fast path above holds)
                a = jax.lax.psum(a, self.axis_name)
            return gru(a, h)

        h = step(feat)  # eager first step also binds every param
        for _ in range(self.n_steps - 1):
            h = step(h)
        return h


class GlobalAttentionPooling(nn.Module):
    """Gated attention readout (Li et al. 2016), masked-segment version.

    gate = softmax_over_graph(gate_nn(h)); out_g = sum_v gate_v * h_v.
    Matches DGL's GlobalAttentionPooling with identity feat_nn.
    """

    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, batch: GraphBatch, feat: jax.Array) -> jax.Array:
        gate = nn.Dense(1, name="gate_nn", param_dtype=self.param_dtype)(feat)
        # node_graph is non-decreasing by the batcher's construction;
        # the readout body is shared with the attribution path
        # (eval/localize.py) via `attention_pool`
        pooled, _ = attention_pool(
            gate[:, 0], feat, batch.node_graph, batch.node_mask,
            batch.num_graphs,
        )
        return pooled
