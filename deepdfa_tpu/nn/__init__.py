from deepdfa_tpu.nn.embedding import SUBKEY_ORDER, AbstractDataflowEmbedding
from deepdfa_tpu.nn.gnn import (
    GatedGraphConv,
    GlobalAttentionPooling,
    GRUCell,
    attention_pool,
    segment_softmax,
    segment_sum,
)
from deepdfa_tpu.nn.mlp import OutputHead

__all__ = [
    "SUBKEY_ORDER",
    "AbstractDataflowEmbedding",
    "GatedGraphConv",
    "GlobalAttentionPooling",
    "GRUCell",
    "attention_pool",
    "segment_softmax",
    "segment_sum",
    "OutputHead",
]
