"""Dataset coverage analysis: abstract-dataflow feature statistics.

Replaces the reference's --analyze_dataset audit
(DDFA/code_gnn/main_cli.py:192-313 get_coverage): per split, how many
nodes are definitions, how many map to known vs UNKNOWN hashes, and the
resulting known-def coverage percentage that the paper reports to justify
the vocab limits.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from deepdfa_tpu.frontend.vocab import NOT_A_DEF, UNKNOWN_IDX
from deepdfa_tpu.graphs.batch import GraphSpec


@dataclass
class CoverageStats:
    n_graphs: int
    n_nodes: int
    n_def_nodes: int
    n_known: int
    n_unknown: int

    @property
    def def_rate(self) -> float:
        return self.n_def_nodes / max(self.n_nodes, 1)

    @property
    def known_coverage(self) -> float:
        """Fraction of definition nodes with an in-vocab hash."""
        return self.n_known / max(self.n_def_nodes, 1)

    def as_dict(self) -> dict:
        return {
            "n_graphs": self.n_graphs,
            "n_nodes": self.n_nodes,
            "n_def_nodes": self.n_def_nodes,
            "n_known": self.n_known,
            "n_unknown": self.n_unknown,
            "def_rate": self.def_rate,
            "known_coverage": self.known_coverage,
        }


def coverage(specs: list[GraphSpec], feat_column: int = 1) -> CoverageStats:
    """Audit one split. feat_column picks the subkey column (default:
    datatype, the reference flagship feature)."""
    n_nodes = n_def = n_known = n_unknown = 0
    for s in specs:
        col = np.asarray(s.node_feats[:, feat_column])
        n_nodes += col.shape[0]
        is_def = col != NOT_A_DEF
        n_def += int(is_def.sum())
        n_unknown += int((col == UNKNOWN_IDX).sum())
        n_known += int((col > UNKNOWN_IDX).sum())
    return CoverageStats(
        n_graphs=len(specs),
        n_nodes=n_nodes,
        n_def_nodes=n_def,
        n_known=n_known,
        n_unknown=n_unknown,
    )


def coverage_report(split_specs: dict[str, list[GraphSpec]]) -> dict[str, dict]:
    return {split: coverage(specs).as_dict() for split, specs in split_specs.items()}
