"""Statement-level vulnerability localization metrics.

Reimplements the reference's line-level evaluation suite:
- top-k accuracy over ranked statements
  (DDFA/sastvd/helpers/evaluate.py:262-322 eval_statements*)
- IFA (initial false alarm), top-k localization accuracy, effort@20%
  recall and recall@1%LOC (LineVul/unixcoder/linevul_main.py:886-1316).

All functions take per-example (scores, true_line_flags) pairs; scoring
models (attention rollout, gradient saliency, GGNN node scores) plug in
above this layer.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class RankedExample:
    """Per-statement scores + binary ground truth for one function."""

    scores: np.ndarray  # [n_statements] float
    flagged: np.ndarray  # [n_statements] bool (true vulnerable lines)

    def ranking(self) -> np.ndarray:
        return np.argsort(-np.asarray(self.scores), kind="stable")


def top_k_accuracy(examples: list[RankedExample], k: int = 10) -> float:
    """Fraction of positive examples with a true line in the top k."""
    hits, total = 0, 0
    for ex in examples:
        if not ex.flagged.any():
            continue
        total += 1
        top = ex.ranking()[:k]
        if ex.flagged[top].any():
            hits += 1
    return hits / total if total else 0.0


def per_example_ifa(examples: list[RankedExample]) -> list[int]:
    """Per-positive-example Initial False Alarm values (clean lines ranked
    above the first truly vulnerable one) — the rows of the reference's
    ifa_records/ifa_<method>.txt dumps."""
    vals = []
    for ex in examples:
        if not ex.flagged.any():
            continue
        order = ex.ranking()
        vals.append(int(np.argmax(ex.flagged[order])))
    return vals


def ifa(examples: list[RankedExample]) -> float:
    """Mean Initial False Alarm: false positives ranked above the first
    true positive (per positive example)."""
    vals = per_example_ifa(examples)
    return float(np.mean(vals)) if vals else 0.0


def effort_at_recall(
    examples: list[RankedExample], recall_frac: float = 0.2
) -> float:
    """Fraction of all statements inspected (global ranking) to reach
    `recall_frac` of all true vulnerable statements (Effort@20%Recall)."""
    if not examples:
        return 0.0
    scores = np.concatenate([np.asarray(e.scores) for e in examples])
    flags = np.concatenate([np.asarray(e.flagged) for e in examples])
    if not flags.any():
        return 0.0
    order = np.argsort(-scores, kind="stable")
    cum = np.cumsum(flags[order])
    target = recall_frac * flags.sum()
    idx = int(np.argmax(cum >= target))
    return (idx + 1) / len(flags)


def recall_at_effort(
    examples: list[RankedExample], effort_frac: float = 0.01
) -> float:
    """Recall of true statements within the top `effort_frac` of the
    global statement ranking (Recall@1%LOC)."""
    if not examples:
        return 0.0
    scores = np.concatenate([np.asarray(e.scores) for e in examples])
    flags = np.concatenate([np.asarray(e.flagged) for e in examples])
    if not flags.any():
        return 0.0
    order = np.argsort(-scores, kind="stable")
    budget = max(1, int(len(flags) * effort_frac))
    return float(flags[order[:budget]].sum() / flags.sum())


def statement_report(examples: list[RankedExample], ks=(1, 3, 5, 10)) -> dict:
    rep = {f"top_{k}_acc": top_k_accuracy(examples, k) for k in ks}
    rep["ifa"] = ifa(examples)
    rep["effort_at_20_recall"] = effort_at_recall(examples, 0.2)
    rep["recall_at_1_loc"] = recall_at_effort(examples, 0.01)
    return rep
