"""CodeBLEU: ngram + weighted-ngram + syntax + dataflow match.

Role parity with the reference evaluator
(CodeT5/evaluator/CodeBLEU/calc_code_bleu.py:11-63):

    CodeBLEU = alpha * BLEU + beta * BLEU_weighted
             + gamma * Match_ast + theta * Match_df

- BLEU: corpus BLEU (Papineni 2002) — micro-averaged modified n-gram
  precision with clipping, closest-reference brevity penalty, and
  epsilon smoothing on zero counts (the reference defaults to NLTK's
  SmoothingFunction().method1, bleu.py:475-484). Implemented here from the
  published formula; validated against the doctest values the reference
  ships (corpus_bleu == 0.5920..., see tests).
- weighted BLEU: same skeleton but per-reference modified *recall* with
  keyword-weighted unigram counts (weight 1.0 for language keywords, 0.2
  otherwise — weighted_ngram_match.py:modified_recall, calc_code_bleu.py:41-42).
- syntax match: fraction of reference AST subtrees (as s-expressions of
  node labels) found in the candidate AST (syntax_match.py:49-74). The
  reference uses tree-sitter grammars; here the AST comes from this
  repo's hermetic frontend in the matching dialect (LANG_DIALECT:
  "c"/"cpp" via the C grammar; "java"/"c_sharp"/"javascript"/"php"/
  "go"/"ruby" via dialect-gated extensions of it) or the python stdlib
  `ast` module (lang "python"). java+c_sharp alone already matches the
  RUNNABLE surface of the reference evaluator (its keywords/ dir ships
  only those two files; any other lang crashes at calc_code_bleu.py:39
  opening the keywords list); javascript/php/go/ruby here go beyond
  what the reference could execute — every language in its DFG.py
  grammar set is covered (docs/PARITY.md).
- dataflow match: fraction of the reference's normalized def-use triples
  (var_i, relation, [var_j...]) found in the candidate
  (dataflow_match.py:28-66, variable names alpha-renamed in order of
  appearance :132-148). For java + c_sharp — the reference evaluator's
  entire runnable surface — the triples come from eval/dfg_parity.py, a
  faithful port of DFG_java/DFG_csharp + the dataflow_match.py pipeline
  over a tree-sitter-shaped mini-AST: DIGIT-EXACT with the reference
  (golden-pinned, tests/test_dfg_parity.py; the only caveat is the
  reference's own str-hash-dependent merged-parent-list ordering). The
  remaining languages keep the reaching-definitions approximation —
  same relation vocabulary, different extractor, comparable within this
  framework.

Both structural scores degenerate to 0 with the reference's own warning
semantics when nothing parses (dataflow_match.py:61-64).
"""

from __future__ import annotations

import functools
import logging
import math
from collections import Counter
from typing import Iterable, Sequence

logger = logging.getLogger(__name__)

_EPSILON = 0.1  # NLTK SmoothingFunction default, used by the reference

# language keyword tables for the weighted-ngram match
# (role of CodeBLEU/keywords/<lang>.txt; standard-defined keyword sets)
KEYWORDS: dict[str, frozenset[str]] = {
    "c": frozenset(
        """auto break case char const continue default do double else enum
        extern float for goto if inline int long register restrict return
        short signed sizeof static struct switch typedef union unsigned void
        volatile while _Bool _Complex _Imaginary""".split()
    ),
    "java": frozenset(
        """abstract assert boolean break byte case catch char class const
        continue default do double else enum extends final finally float for
        goto if implements import instanceof int interface long native new
        package private protected public return short static strictfp super
        switch synchronized this throw throws transient try void volatile
        while""".split()
    ),
    "python": frozenset(
        """False None True and as assert async await break class continue def
        del elif else except finally for from global if import in is lambda
        nonlocal not or pass raise return try while with yield""".split()
    ),
}
KEYWORDS["cpp"] = KEYWORDS["c"] | frozenset(
    """alignas alignof bool catch class constexpr const_cast decltype delete
    dynamic_cast explicit export false friend mutable namespace new noexcept
    nullptr operator private protected public reinterpret_cast static_assert
    static_cast template this thread_local throw true try typeid typename
    using virtual wchar_t""".split()
)
# C# keyword + contextual-keyword set (standard-defined; same contents as
# the reference's keywords/c_sharp.txt — the only keyword file besides
# java.txt the reference actually ships, so java+c_sharp is the complete
# runnable surface of its evaluator)
KEYWORDS["c_sharp"] = frozenset(
    """abstract as base bool break byte case catch char checked class const
    continue decimal default delegate do double else enum event explicit
    extern false finally fixed float for foreach goto if implicit in int
    interface internal is lock long namespace new null object operator out
    override params private protected public readonly ref return sbyte
    sealed short sizeof stackalloc static string struct switch this throw
    true try typeof uint ulong unchecked unsafe ushort using virtual void
    volatile while add alias ascending async await by descending dynamic
    equals from get global group into join let nameof notnull on orderby
    partial remove select set unmanaged value var when where yield""".split()
)

# ECMAScript reserved words + strict-mode/contextual additions
# (standard-defined set; role of a keywords/javascript.txt the reference
# does not ship — its evaluator cannot actually run js, see _check_lang)
KEYWORDS["javascript"] = frozenset(
    """await break case catch class const continue debugger default delete
    do else enum export extends false finally for function if implements
    import in instanceof interface let new null of package private
    protected public return static super switch this throw true try
    typeof var void while with yield async get set""".split()
)

# PHP reserved words + compile-time constants (standard-defined set;
# role of the keywords/php.txt the reference does not ship)
KEYWORDS["php"] = frozenset(
    """abstract and array as break callable case catch class clone const
    continue declare default die do echo else elseif empty enddeclare
    endfor endforeach endif endswitch endwhile eval exit extends final
    finally fn for foreach function global goto if implements include
    include_once instanceof insteadof interface isset list match
    namespace new or print private protected public readonly require
    require_once return static switch throw trait try unset use var
    while xor yield true false null""".split()
)

# Go spec keyword set + predeclared constants (standard-defined; role of
# the keywords/go.txt the reference does not ship)
KEYWORDS["go"] = frozenset(
    """break case chan const continue default defer else fallthrough for
    func go goto if import interface map package range return select
    struct switch type var true false nil iota""".split()
)
# Ruby keyword set (standard-defined; role of the keywords/ruby.txt the
# reference does not ship)
KEYWORDS["ruby"] = frozenset(
    """BEGIN END alias and begin break case class def defined? do else
    elsif end ensure false for if in module next nil not or redo rescue
    retry return self super then true undef unless until when while
    yield""".split()
)

#: CodeBLEU lang -> frontend parser dialect (frontend/parser.py); python
#: goes through the stdlib-ast backend instead
LANG_DIALECT: dict[str, str] = {
    "c": "c",
    "cpp": "c",
    "java": "java",
    "c_sharp": "cs",
    "javascript": "js",
    "php": "php",
    "go": "go",
    "ruby": "ruby",
}

#: snippet wrapper per dialect for bare statement sequences
_WRAPPERS = {
    "js": "function __snippet__() {\n%s\n}",
    "php": "function __snippet__() {\n%s\n}",
    "go": "func __snippet__() {\n%s\n}",
    "ruby": "def __snippet__\n%s\nend",
}


# ---------------------------------------------------------------------------
# n-gram matches
# ---------------------------------------------------------------------------


def _ngrams(tokens: Sequence[str], n: int) -> Counter:
    return Counter(
        tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)
    )


def _closest_ref_length(references: Sequence[Sequence[str]], hyp_len: int) -> int:
    return min(
        (len(r) for r in references),
        key=lambda rl: (abs(rl - hyp_len), rl),
    )


def _brevity_penalty(ref_len: int, hyp_len: int) -> float:
    if hyp_len > ref_len:
        return 1.0
    if hyp_len == 0:
        return 0.0
    return math.exp(1 - ref_len / hyp_len)


def _combine(p_n: list[tuple[float, int]], weights, bp: float) -> float:
    """exp(sum w_i log p_i) with epsilon smoothing on zero numerators."""
    if p_n[0][0] == 0:
        return 0.0
    s = 0.0
    for w, (num, den) in zip(weights, p_n):
        num = num if num != 0 else _EPSILON
        s += w * math.log(num / max(den, 1))
    return bp * math.exp(s)


def corpus_bleu(
    list_of_references: Sequence[Sequence[Sequence[str]]],
    hypotheses: Sequence[Sequence[str]],
    weights: Sequence[float] = (0.25, 0.25, 0.25, 0.25),
) -> float:
    """Corpus BLEU with clipped micro-averaged precision (bleu.py role)."""
    assert len(list_of_references) == len(hypotheses)
    numer = Counter()
    denom = Counter()
    hyp_lengths = 0
    ref_lengths = 0
    for references, hyp in zip(list_of_references, hypotheses):
        for n, _ in enumerate(weights, start=1):
            hyp_counts = _ngrams(hyp, n)
            max_ref = Counter()
            for ref in references:
                for g, c in _ngrams(ref, n).items():
                    max_ref[g] = max(max_ref[g], c)
            clipped = {g: min(c, max_ref[g]) for g, c in hyp_counts.items()}
            numer[n] += sum(clipped.values())
            denom[n] += max(1, sum(hyp_counts.values()))
        hyp_lengths += len(hyp)
        ref_lengths += _closest_ref_length(references, len(hyp))
    bp = _brevity_penalty(ref_lengths, hyp_lengths)
    p_n = [(numer[n], denom[n]) for n, _ in enumerate(weights, start=1)]
    return _combine(p_n, weights, bp)


def weighted_corpus_bleu(
    list_of_references: Sequence[Sequence[Sequence[str]]],
    hypotheses: Sequence[Sequence[str]],
    keywords: frozenset[str],
    weights: Sequence[float] = (0.25, 0.25, 0.25, 0.25),
    keyword_weight: float = 1.0,
    other_weight: float = 0.2,
) -> float:
    """Keyword-weighted variant (weighted_ngram_match.py role): modified
    n-gram *recall* accumulated per reference, with unigram counts scaled
    by token weights (keywords count 5x as much as other tokens)."""
    assert len(list_of_references) == len(hypotheses)
    numer = Counter()
    denom = Counter()
    hyp_lengths = 0
    ref_lengths = 0

    def w(tok: str) -> float:
        return keyword_weight if tok in keywords else other_weight

    for references, hyp in zip(list_of_references, hypotheses):
        for n, _ in enumerate(weights, start=1):
            hyp_counts = _ngrams(hyp, n)
            for ref in references:
                ref_counts = _ngrams(ref, n)
                clipped = {
                    g: min(c, hyp_counts[g]) for g, c in ref_counts.items()
                }
                if n == 1:
                    numer[n] += sum(c * w(g[0]) for g, c in clipped.items())
                    denom[n] += max(
                        1, sum(c * w(g[0]) for g, c in ref_counts.items())
                    )
                else:
                    numer[n] += sum(clipped.values())
                    denom[n] += max(1, sum(ref_counts.values()))
        hyp_lengths += len(hyp)
        ref_lengths += _closest_ref_length(references, len(hyp))
    bp = _brevity_penalty(ref_lengths, hyp_lengths)
    p_n = [(numer[n], denom[n]) for n, _ in enumerate(weights, start=1)]
    return _combine(p_n, weights, bp)


# ---------------------------------------------------------------------------
# syntax match (AST subtrees via the hermetic frontend)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=4096)
def _parse(code: str, dialect: str = "c"):
    """Parse a snippet with the hermetic frontend; None on failure.

    Generated snippets are frequently bare statement sequences, so a
    function wrapper is tried when direct parsing fails (the reference
    swallows parse failures the same way, syntax_match.py:36-43). Cached:
    the syntax and dataflow matchers score the same snippets, and CPG
    construction dominates CodeBLEU runtime.
    """
    from deepdfa_tpu.frontend.parser import parse_function

    wrapper = _WRAPPERS.get(dialect, "void __snippet__() {\n%s\n}") % code
    for candidate in (code, wrapper):
        try:
            return parse_function(candidate, dialect=dialect)
        except Exception:
            continue
    return None


def _subtree_sexps(cpg) -> list[str]:
    """S-expressions (node labels only, like tree-sitter's sexp) for every
    AST node that has children, plus the root (syntax_match.py:49-61)."""
    from deepdfa_tpu.frontend.cpg import AST

    children: dict[int, list[int]] = {}
    has_parent: set[int] = set()
    for s, d, t in cpg.edges:
        if t == AST:
            children.setdefault(s, []).append(d)
            has_parent.add(d)

    def sexp(nid: int) -> str:
        kids = sorted(
            children.get(nid, []),
            key=lambda k: (cpg.nodes[k].order or 0, k),
        )
        label = cpg.nodes[nid].label
        if not kids:
            return f"({label})"
        return f"({label} " + " ".join(sexp(k) for k in kids) + ")"

    roots = [n.id for n in cpg.nodes if n.id not in has_parent]
    out: list[str] = []
    stack = list(roots)
    while stack:
        nid = stack.pop()
        kids = children.get(nid, [])
        if kids or nid in roots:
            out.append(sexp(nid))
        stack.extend(kids)
    return out


# ---------------------------------------------------------------------------
# python structural backends (stdlib ast replaces tree-sitter's grammar;
# reference: CodeT5/evaluator/CodeBLEU/parser/DFG.py DFG_python)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=4096)
def _parse_py(code: str):
    import ast
    import textwrap

    for candidate in (code, textwrap.dedent(code)):
        try:
            return ast.parse(candidate)
        except SyntaxError:
            continue
    return None


def _py_sexps(tree) -> list[str]:
    """S-expressions of python AST node type names for every node with
    children (same shape as the tree-sitter sexps used on C)."""
    import ast

    out: list[str] = []

    def sexp(node) -> str:
        kids = list(ast.iter_child_nodes(node))
        label = type(node).__name__
        if not kids:
            return f"({label})"
        return f"({label} " + " ".join(sexp(k) for k in kids) + ")"

    def walk(node, is_root=False):
        kids = list(ast.iter_child_nodes(node))
        if kids or is_root:
            out.append(sexp(node))
        for k in kids:
            walk(k)

    walk(tree, is_root=True)
    return out


def _py_dataflow_triples(tree) -> list[tuple[str, str, tuple[str, ...]]]:
    """Def-use triples from a python AST, in source order:

    - assignment/aug-assignment/for-target/with-as/arg: ("x",
      "computedFrom", (rhs names...))
    - a Load of a name with a prior definition: ("x", "comesFrom", ("x",))

    Same triple vocabulary as the C extractor above and the reference DFG
    functions; like the reference's DFG_python it is a linear (source
    -order) approximation, not a full-CFG solution.
    """
    import ast

    triples: list[tuple[str, str, tuple[str, ...]]] = []
    defined: set[str] = set()

    def names_in(node) -> tuple[str, ...]:
        return tuple(
            sorted(
                {
                    n.id
                    for n in ast.walk(node)
                    if isinstance(n, ast.Name)
                }
            )
        )

    def define(target, rhs: tuple[str, ...]):
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                triples.append((n.id, "computedFrom", rhs))
                defined.add(n.id)

    def visit(node):
        if isinstance(node, ast.Assign):
            visit_children(node.value)
            rhs = names_in(node.value)
            for t in node.targets:
                define(t, rhs)
            return
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                visit_children(node.value)
                define(node.target, names_in(node.value))
            return
        if isinstance(node, ast.For):
            visit_children(node.iter)
            define(node.target, names_in(node.iter))
            for b in node.body + node.orelse:
                visit(b)
            return
        if isinstance(node, ast.withitem) and node.optional_vars is not None:
            visit_children(node.context_expr)
            define(node.optional_vars, names_in(node.context_expr))
            return
        if isinstance(node, ast.arg):
            defined.add(node.arg)
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in defined:
                triples.append((node.id, "comesFrom", (node.id,)))
            return
        visit_children(node)

    def visit_children(node):
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit_children(tree)
    return triples


def corpus_syntax_match(
    list_of_references: Sequence[Sequence[str]],
    candidates: Sequence[str],
    lang: str = "c",
) -> float:
    _check_lang(lang)
    if lang == "python":
        parse, sexps = _parse_py, _py_sexps
    else:
        parse = functools.partial(_parse, dialect=LANG_DIALECT[lang])
        sexps = _subtree_sexps
    match = 0
    total = 0
    for references, cand in zip(list_of_references, candidates):
        cand_cpg = parse(cand)
        cand_sexps = sexps(cand_cpg) if cand_cpg else []
        for ref in references:
            ref_cpg = parse(ref)
            if ref_cpg is None:
                continue
            ref_sexps = sexps(ref_cpg)
            match += sum(1 for s in ref_sexps if s in cand_sexps)
            total += len(ref_sexps)
    if total == 0:
        logger.warning(
            "no reference ASTs parsed; syntax match degenerates to 0"
        )
        return 0.0
    return match / total


# ---------------------------------------------------------------------------
# dataflow match (def-use triples via the reaching-definitions solver)
# ---------------------------------------------------------------------------


def _dataflow_triples(cpg) -> list[tuple[str, str, tuple[str, ...]]]:
    """(var, relation, parent-vars) triples:

    - ("x", "computedFrom", (rhs vars...)) for every definition x = expr
    - ("x", "comesFrom", (defining vars...)) for every use of x reached by
      at least one definition (from the worklist solver)
    Triple vocabulary mirrors the reference DFG functions (parser/DFG.py).
    """
    from deepdfa_tpu.frontend.reaching import ReachingDefinitions

    rd = ReachingDefinitions(cpg)

    def identifiers(root: int) -> list[str]:
        ids = []
        for nid in [root, *cpg.ast_descendants(root)]:
            node = cpg.nodes[nid]
            if node.label == "IDENTIFIER":
                ids.append(node.code)
        return ids

    triples: list[tuple[str, str, tuple[str, ...]]] = []
    in_sets = rd.solve()
    for n in rd.cfg_nodes:
        reaching = in_sets.get(n, set())
        uses = sorted(set(identifiers(n)))
        for d in rd.gen_set[n]:
            args = cpg.arguments(n)
            rhs_roots = args[1:] if len(args) > 1 else args[:1]
            rhs = sorted(
                {i for r in rhs_roots for i in identifiers(r)}
            )
            triples.append((d.var, "computedFrom", tuple(rhs)))
        for u in uses:
            if any(dd.var == u for dd in reaching):
                triples.append((u, "comesFrom", (u,)))
    return triples


def _normalize_dataflow(
    triples: Iterable[tuple[str, str, tuple[str, ...]]]
) -> list[tuple[str, str, tuple[str, ...]]]:
    """Alpha-rename variables in order of appearance
    (dataflow_match.py:132-148): parents first, then the target var."""
    var_map: dict[str, str] = {}

    def norm(v: str) -> str:
        if v not in var_map:
            var_map[v] = f"var_{len(var_map)}"
        return var_map[v]

    out = []
    for var, rel, parents in triples:
        normed_parents = tuple(norm(p) for p in parents)
        out.append((norm(var), rel, normed_parents))
    return out


def corpus_dataflow_match(
    list_of_references: Sequence[Sequence[str]],
    candidates: Sequence[str],
    lang: str = "c",
) -> float:
    _check_lang(lang)
    if lang in ("java", "c_sharp"):
        # digit-exact path: a faithful port of the reference's
        # DFG_java/DFG_csharp recursion + dataflow_match.py pipeline
        # over a tree-sitter-shaped mini-AST (eval/dfg_parity.py;
        # golden-pinned in tests/test_dfg_parity.py). The remaining
        # languages keep the reaching-defs approximation below.
        from deepdfa_tpu.eval import dfg_parity

        return dfg_parity.corpus_dataflow_match(
            list_of_references, candidates, lang
        )
    if lang == "python":
        parse, triples_fn = _parse_py, _py_dataflow_triples
    else:
        parse = functools.partial(_parse, dialect=LANG_DIALECT[lang])
        triples_fn = _dataflow_triples
    match = 0
    total = 0
    for references, cand in zip(list_of_references, candidates):
        cand_cpg = parse(cand)
        cand_dfg = (
            _normalize_dataflow(triples_fn(cand_cpg))
            if cand_cpg
            else []
        )
        for ref in references:
            ref_cpg = parse(ref)
            if ref_cpg is None:
                continue
            ref_dfg = _normalize_dataflow(triples_fn(ref_cpg))
            if not ref_dfg:
                continue
            remaining = list(cand_dfg)
            total += len(ref_dfg)
            for t in ref_dfg:
                if t in remaining:
                    match += 1
                    remaining.remove(t)
    if total == 0:
        logger.warning(
            "no reference data-flows extracted; dataflow match degenerates "
            "to 0 (reference emits the same warning, dataflow_match.py:61-64)"
        )
        return 0.0
    return match / total


# ---------------------------------------------------------------------------
# the composite score
# ---------------------------------------------------------------------------


def _check_lang(lang: str) -> None:
    if lang not in set(LANG_DIALECT) | {"python"}:
        raise ValueError(
            f"lang={lang!r}: structural matches need a parser; supported "
            f"langs are {sorted(set(LANG_DIALECT) | {'python'})} (hermetic "
            "frontend dialects + stdlib ast for python) — already beyond "
            "the reference evaluator's runnable surface (java+c_sharp, "
            "the only keyword lists it ships, calc_code_bleu.py:39). "
            "Anything else is descoped — see docs/PARITY.md."
        )


def get_codebleu(
    references: Sequence[str] | Sequence[Sequence[str]],
    hypotheses: Sequence[str],
    lang: str = "c",
    params: Sequence[float] = (0.25, 0.25, 0.25, 0.25),
) -> dict[str, float]:
    """Composite CodeBLEU over parallel corpora (calc_code_bleu.py:11-63).

    `references` is either one string per hypothesis or a list of
    reference variants per hypothesis. Returns all four components plus
    the weighted composite under "codebleu".
    """
    _check_lang(lang)  # before KEYWORDS[lang] can KeyError on e.g. "swift"
    refs: list[list[str]] = [
        [r] if isinstance(r, str) else list(r) for r in references
    ]
    if len(refs) != len(hypotheses):
        raise ValueError(
            f"{len(refs)} references vs {len(hypotheses)} hypotheses"
        )
    if len(params) != 4:
        raise ValueError(
            f"params needs 4 weights (alpha,beta,gamma,theta), got {params}"
        )
    alpha, beta, gamma, theta = params

    tokenized_hyps = [h.split() for h in hypotheses]
    tokenized_refs = [[r.split() for r in rr] for rr in refs]

    ngram = corpus_bleu(tokenized_refs, tokenized_hyps)
    weighted = weighted_corpus_bleu(
        tokenized_refs, tokenized_hyps, KEYWORDS[lang]
    )
    syntax = corpus_syntax_match(refs, hypotheses, lang)
    dataflow = corpus_dataflow_match(refs, hypotheses, lang)
    return {
        "ngram_match": ngram,
        "weighted_ngram_match": weighted,
        "syntax_match": syntax,
        "dataflow_match": dataflow,
        "codebleu": alpha * ngram
        + beta * weighted
        + gamma * syntax
        + theta * dataflow,
    }
