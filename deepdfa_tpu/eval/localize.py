"""Line-level vulnerability localization scoring for transformer models.

The reference's UniXcoder evaluation ranks lines by explanation scores
computed from the fine-tuned model (LineVul/unixcoder/linevul_main.py:
955-1398): attention aggregation plus captum gradient methods (Saliency,
InputXGradient/DeepLift-style). TPU-native equivalents:

- `attention_token_scores`: attention mass received by each token from
  [CLS], averaged over heads and layers (the linevul attention method);
- `saliency_token_scores`: |d logit_vuln / d embedding . embedding|
  per token (gradient x input — the first-order common core of the captum
  family);
- `aggregate_line_scores`: token scores -> per-line scores through the
  tokenizer's token->line map (max aggregation like the reference).

Outputs feed eval/statements.py (top-k, IFA, effort metrics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models import transformer as tfm


def attention_token_scores(
    cfg: tfm.TransformerConfig, params: dict, input_ids: jax.Array
) -> np.ndarray:
    """[B, T] attention-from-CLS scores averaged over layers and heads."""
    mask = input_ids != cfg.pad_token_id
    x = tfm.embed(cfg, params, input_ids)
    layers = params["layers"]
    n_layers = layers["wq"].shape[0]
    acc = jnp.zeros(input_ids.shape, jnp.float32)
    neg = jnp.finfo(jnp.float32).min
    for i in range(n_layers):
        lp = jax.tree.map(lambda a: a[i], layers)
        q = jnp.einsum("btd,dhk->bhtk", x, lp["wq"]) + lp["bq"][:, None, :]
        k = jnp.einsum("btd,dhk->bhtk", x, lp["wk"]) + lp["bk"][:, None, :]
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        s = jnp.where(mask[:, None, None, :], s, neg)
        p = jax.nn.softmax(s, axis=-1)
        acc = acc + p[:, :, 0, :].mean(axis=1)  # CLS row, head-averaged
        x = tfm.encoder_layer(cfg, lp, x, mask, None)
    return np.asarray(acc / n_layers)


def combined_saliency_scores(
    model_cfg, params, input_ids, graph_batch=None, has_graph=None
) -> np.ndarray:
    """Gradient-x-input token scores for the combined classifier's
    vulnerable-class logit."""
    from deepdfa_tpu.models import combined as cmb

    ecfg = model_cfg.encoder
    word = params["encoder"]["embeddings"]["word"]
    rows = word[input_ids]

    def fn(rows):
        # patched embed: replace the word-gather with the provided rows
        e = params["encoder"]["embeddings"]
        mask = (input_ids != ecfg.pad_token_id).astype(jnp.int32)
        pos = jnp.cumsum(mask, axis=-1) * mask + ecfg.pad_token_id
        x = rows + e["position"][pos] + e["token_type"][jnp.zeros_like(input_ids)]
        x = tfm._layer_norm(x, e["ln_scale"], e["ln_bias"], ecfg.layer_norm_eps)
        x = x.astype(jnp.dtype(ecfg.dtype))
        attn_mask = input_ids != ecfg.pad_token_id
        layers = params["encoder"]["layers"]
        x, _ = jax.lax.scan(
            lambda x, lp: (tfm.encoder_layer(ecfg, lp, x, attn_mask, None), None),
            x,
            layers,
        )
        cls_vec = x[:, 0, :]
        gvec = None
        if model_cfg.use_graph and graph_batch is not None:
            enc = cmb.make_graph_encoder(model_cfg)
            gvec = enc.apply(params["graph"], graph_batch)
            if has_graph is not None:
                gvec = gvec * has_graph[:, None].astype(gvec.dtype)
        logits = cmb.head_logits(model_cfg, params["head"], cls_vec, gvec)
        return logits[:, 1].sum()

    grads = jax.grad(fn)(rows)
    return np.asarray(jnp.linalg.norm(grads * rows, axis=-1))


def aggregate_line_scores(
    token_scores: np.ndarray,
    token_lines: np.ndarray,
    n_lines: int,
    reduce: str = "max",
) -> np.ndarray:
    """[T] token scores + [T] 1-based line ids (0 = no line) -> [n_lines]."""
    out = np.zeros((n_lines,), np.float64)
    for s, ln in zip(np.asarray(token_scores), np.asarray(token_lines)):
        if 1 <= ln <= n_lines:
            i = int(ln) - 1
            out[i] = max(out[i], float(s)) if reduce == "max" else out[i] + float(s)
    return out
