"""Line-level vulnerability localization scoring for transformer models.

The reference's UniXcoder evaluation ranks lines by explanation scores
computed from the fine-tuned model (LineVul/unixcoder/linevul_main.py:
955-1398) with captum: attention, LayerIntegratedGradients ("lig"),
Saliency, DeepLift, DeepLiftShap, GradientShap. TPU-native equivalents of
the whole family, as jax.grad over an embedding-injected forward:

- `attention`: attention mass received by each token from [CLS],
  averaged over heads and layers (roberta-family only);
- `saliency`: |d logit_vuln / d embedding| (captum Saliency);
- `input_x_gradient`: gradient x embedding (first-order common core);
- `lig`: integrated gradients along the straight path from a reference
  embedding (pad everywhere, cls/sep kept — create_ref_input_ids,
  linevul_main.py:932-945) with an m-step Riemann midpoint sum;
- `deeplift`: the rescale-rule first-order form grad(x) * (x - baseline)
  with a zero baseline (the reference's baselines, :1055);
- `deeplift_shap` / `gradient_shap`: the same attributions averaged over
  a small set of noisy baselines / noisy path samples (captum's sampling
  semantics with the reference's zero-baseline choice).

Every gradient method is summarized captum-tutorial style: sum over the
embedding dim, normalized by the L2 norm of the summed vector.

Both combined architectures are supported: the RoBERTa-family combined
classifier (models/combined.py) and the CodeT5-style DefectConfig
(models/t5.py, eos pooling). Outputs feed eval/statements.py (top-k,
IFA, effort metrics).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models import transformer as tfm

GRADIENT_METHODS = (
    "saliency",
    "input_x_gradient",
    "lig",
    "deeplift",
    "deeplift_shap",
    "gradient_shap",
)
METHODS = ("attention",) + GRADIENT_METHODS


def attention_token_scores(
    cfg: tfm.TransformerConfig, params: dict, input_ids: jax.Array
) -> np.ndarray:
    """[B, T] attention-from-CLS scores averaged over layers and heads."""
    mask = input_ids != cfg.pad_token_id
    x = tfm.embed(cfg, params, input_ids)
    layers = params["layers"]
    n_layers = layers["wq"].shape[0]
    acc = jnp.zeros(input_ids.shape, jnp.float32)
    neg = jnp.finfo(jnp.float32).min
    for i in range(n_layers):
        lp = jax.tree.map(lambda a: a[i], layers)
        q = jnp.einsum("btd,dhk->bhtk", x, lp["wq"]) + lp["bq"][:, None, :]
        k = jnp.einsum("btd,dhk->bhtk", x, lp["wk"]) + lp["bk"][:, None, :]
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        s = jnp.where(mask[:, None, None, :], s, neg)
        p = jax.nn.softmax(s, axis=-1)
        acc = acc + p[:, :, 0, :].mean(axis=1)  # CLS row, head-averaged
        x = tfm.encoder_layer(cfg, lp, x, mask, None)
    return np.asarray(acc / n_layers)


# ---------------------------------------------------------------------------
# embedding-injected forwards (the jax.grad hook per architecture)


def _roberta_forward(model_cfg, params, input_ids, graph_batch, has_graph):
    """(fn(rows) -> scalar vuln-logit sum, rows [B, T, D])."""
    from deepdfa_tpu.models import combined as cmb

    ecfg = model_cfg.encoder
    word = params["encoder"]["embeddings"]["word"]
    rows = word[input_ids]

    def fn(rows):
        # patched embed: replace the word-gather with the provided rows
        e = params["encoder"]["embeddings"]
        mask = (input_ids != ecfg.pad_token_id).astype(jnp.int32)
        pos = jnp.cumsum(mask, axis=-1) * mask + ecfg.pad_token_id
        x = rows + e["position"][pos] + e["token_type"][jnp.zeros_like(input_ids)]
        x = tfm._layer_norm(x, e["ln_scale"], e["ln_bias"], ecfg.layer_norm_eps)
        x = x.astype(jnp.dtype(ecfg.dtype))
        attn_mask = input_ids != ecfg.pad_token_id
        layers = params["encoder"]["layers"]
        x, _ = jax.lax.scan(
            lambda x, lp: (tfm.encoder_layer(ecfg, lp, x, attn_mask, None), None),
            x,
            layers,
        )
        cls_vec = x[:, 0, :]
        gvec = None
        if model_cfg.use_graph and graph_batch is not None:
            enc = cmb.make_graph_encoder(model_cfg)
            gvec = enc.apply(params["graph"], graph_batch)
            if has_graph is not None:
                gvec = gvec * has_graph[:, None].astype(gvec.dtype)
        logits = cmb.head_logits(model_cfg, params["head"], cls_vec, gvec)
        return logits[:, 1].sum()

    return fn, rows


def _t5_forward(model_cfg, params, input_ids, graph_batch, has_graph):
    """Same contract for the CodeT5-style DefectConfig (eos pooling) —
    delegates to the training forward via its inputs_embeds hook so the
    attribution target can never drift from what was trained."""
    from deepdfa_tpu.models import t5 as t5m

    rows = params["encoder"]["word"][input_ids]

    def fn(rows):
        logits = t5m.defect_forward(
            model_cfg, params, input_ids,
            graph_batch=graph_batch if model_cfg.use_graph else None,
            has_graph=has_graph,
            inputs_embeds=rows,
        )
        return logits[:, 1].sum()

    return fn, rows


def _forward_builder(arch: str) -> Callable:
    return {"roberta": _roberta_forward, "t5": _t5_forward}[arch]


# ---------------------------------------------------------------------------
# attribution methods


def _summarize(attr: jax.Array) -> np.ndarray:
    """captum-tutorial summarization: sum over the embedding dim, L2
    normalized per example (summarize_attributions role)."""
    s = attr.sum(axis=-1)
    norm = jnp.linalg.norm(s, axis=-1, keepdims=True)
    return np.asarray(s / jnp.maximum(norm, 1e-12))


def _path_attribution(grad, rows, base, steps: int):
    """n-step rescale: Riemann midpoint sum of grads along the straight
    baseline->input path, times delta — shared by lig / deeplift /
    deeplift_shap. For linear targets the rule is EXACT at any step
    count and equals captum's layer-wise rescale (both reduce to
    delta x weight); elsewhere it converges to the path integral with
    the completeness property sum(attr) -> f(input) - f(baseline)
    (pinned in tests/test_aux_components.py)."""
    delta = rows - base
    acc = jnp.zeros_like(rows)
    for k in range(steps):
        alpha = (k + 0.5) / steps
        acc = acc + grad(base + alpha * delta)
    return delta * acc / steps


def _lig_baseline_rows(word, input_ids, pad_id, cls_id, sep_id):
    """Reference create_ref_input_ids: pad everywhere, cls/sep preserved."""
    ref_ids = jnp.where(
        (input_ids == cls_id) | (input_ids == sep_id), input_ids, pad_id
    )
    return word[ref_ids]


def token_scores(
    method: str,
    arch: str,
    model_cfg,
    params,
    input_ids,
    graph_batch=None,
    has_graph=None,
    *,
    n_steps: int = 20,
    n_samples: int = 8,
    seed: int = 0,
) -> np.ndarray:
    """[B, T] token attribution scores for the vulnerable-class logit."""
    if method == "attention":
        if arch != "roberta":
            raise ValueError(
                "the attention method reads RoBERTa-shaped encoder layers; "
                "use a gradient method for --arch t5"
            )
        return attention_token_scores(
            model_cfg.encoder, params["encoder"], input_ids
        )
    if method not in GRADIENT_METHODS:
        raise ValueError(f"unknown method {method!r} (choose from {METHODS})")

    # checkpoint restores hand back numpy leaves; the jitted grad traces
    # through fancy indexing on them, which numpy rejects — normalize once
    params = jax.tree.map(jnp.asarray, params)
    input_ids = jnp.asarray(input_ids)
    fn, rows = _forward_builder(arch)(
        model_cfg, params, input_ids, graph_batch, has_graph
    )
    # jit the gradient: the path methods evaluate it n_steps/n_samples
    # times at identical shapes — compile once, replay the rest
    grad = jax.jit(jax.grad(fn))

    if method == "saliency":
        return _summarize(jnp.abs(grad(rows)))
    if method == "input_x_gradient":
        return _summarize(grad(rows) * rows)

    ecfg = model_cfg.encoder
    if arch == "roberta":
        word = params["encoder"]["embeddings"]["word"]
        cls_id, sep_id = 0, 2  # RoBERTa frame
    else:
        word = params["encoder"]["word"]
        cls_id, sep_id = ecfg.eos_token_id, ecfg.eos_token_id

    def path_attr(base, steps):
        return _path_attribution(grad, rows, base, steps)

    if method == "lig":
        base = _lig_baseline_rows(
            word, input_ids, ecfg.pad_token_id, cls_id, sep_id
        )
        return _summarize(path_attr(base, n_steps))

    if method == "deeplift":
        # n-step rescale against the zero baseline (reference :1055 runs
        # captum's layer-wise rescale rule; the multi-step input-level
        # rescale converges to the same path attribution and is exact
        # where the rescale rule is — linear models, pinned in tests)
        return _summarize(path_attr(jnp.zeros_like(rows), n_steps))

    key = jax.random.key(seed)
    if method == "deeplift_shap":
        # rescale-rule attributions averaged over noisy zero-mean
        # baselines; a smaller inner step count keeps the total grad
        # evaluations at ~n_samples * n_steps / 4
        inner = max(2, n_steps // 4)
        acc = jnp.zeros_like(rows)
        for k in jax.random.split(key, n_samples):
            base = 0.01 * jax.random.normal(k, rows.shape, rows.dtype)
            acc = acc + path_attr(base, inner)
        return _summarize(acc / n_samples)

    # gradient_shap: expectation of grad at noisy interpolation points
    acc = jnp.zeros_like(rows)
    for k in jax.random.split(key, n_samples):
        k1, k2 = jax.random.split(k)
        alpha = jax.random.uniform(k1)
        noisy = rows + 0.01 * jax.random.normal(k2, rows.shape, rows.dtype)
        acc = acc + grad(alpha * noisy)  # zero baseline
    return _summarize((acc / n_samples) * rows)


def combined_saliency_scores(
    model_cfg, params, input_ids, graph_batch=None, has_graph=None
) -> np.ndarray:
    """Gradient-x-input token scores (kept for backward compatibility;
    the general entry point is token_scores)."""
    fn, rows = _roberta_forward(
        model_cfg, params, input_ids, graph_batch, has_graph
    )
    grads = jax.grad(fn)(rows)
    return np.asarray(jnp.linalg.norm(grads * rows, axis=-1))


# ---------------------------------------------------------------------------
# GGNN node-level attribution (the flagship family's localization path)
#
# The transformer family above attributes the vuln logit to token
# embedding rows; the GGNN analog attributes it to per-node embedding
# rows of a packed `GraphBatch`, which map straight back to source lines
# (every CFG node carries one). Both the offline eval below and the
# served AOT executables (serve/localize.py) call `ggnn_score_fn`, so
# the two paths cannot drift — tests pin them bit-identical.

GGNN_METHODS = (
    "attention",
    "saliency",
    "input_x_gradient",
    "deeplift",
    "lig",
)


def _ggnn_embedding(model):
    """The model's own AbstractDataflowEmbedding, reconstructed with the
    hyperparameters DeepDFA.__call__ uses."""
    from deepdfa_tpu.nn import AbstractDataflowEmbedding

    struct_vocab: tuple[int, ...] = ()
    if model.struct_feats:
        from deepdfa_tpu.frontend.structfeat import STRUCT_VOCAB

        struct_vocab = STRUCT_VOCAB
    return AbstractDataflowEmbedding(
        input_dim=model.input_dim,
        embedding_dim=model.hidden_dim,
        concat_all=model.concat_all_absdf,
        param_dtype=model.param_dtype,
        struct_vocab=struct_vocab,
    )


def _unwrap(params):
    return params["params"] if "params" in params else params


def ggnn_forward(model, params, batch):
    """(fn(rows) -> ([G] vuln logits, [N] pooling attention), rows) —
    embedding-injected forward for the graph-level DeepDFA classifier
    (models/deepdfa.py, label_style="graph").

    Recomposed from the model's own submodules ("embedding"/"ggnn"/
    "pooling"/"head" param subtrees) so jax.grad can reach the per-node
    embedding rows; the pooling readout calls the SAME
    `nn/gnn.py:attention_pool` body `GlobalAttentionPooling` uses (it
    additionally returns the per-node attention weights, which ARE the
    "GGNN node scores" method — the shared helper is what keeps a
    kernel swap or numerics change from diverging this path from the
    model path). The GGNN conv inherits every kernel knob from the
    model, so `model.ggnn_kernel` switches attribution too. Logit
    parity with `model.apply` is pinned bit-identical in
    tests/test_scan.py — the drift guard for this recomposition."""
    from deepdfa_tpu.nn import GatedGraphConv, OutputHead
    from deepdfa_tpu.nn.gnn import attention_pool

    if model.label_style != "graph":
        raise ValueError(
            f"GGNN localization attributes the graph-level logit; "
            f"label_style={model.label_style!r} has no single logit to "
            f"attribute"
        )
    p = _unwrap(params)
    rows = _ggnn_embedding(model).apply(
        {"params": p["embedding"]}, batch.node_feats
    )

    def fn(rows):
        width = rows.shape[-1]
        ggnn_out = GatedGraphConv(
            out_features=width,
            n_steps=model.n_steps,
            n_etypes=model.n_etypes,
            scan_steps=model.scan_steps,
            param_dtype=model.param_dtype,
            use_kernel=getattr(model, "ggnn_kernel", False),
            kernel_scatter=getattr(model, "ggnn_kernel_scatter", "auto"),
            kernel_accum=getattr(model, "ggnn_kernel_accum", "fp32"),
            kernel_unroll=getattr(
                model, "ggnn_kernel_unroll", "per_step"
            ),
            kernel_block_nodes=getattr(
                model, "ggnn_kernel_block_nodes", 0
            ),
            kernel_block_edges=getattr(
                model, "ggnn_kernel_block_edges", 0
            ),
        ).apply({"params": p["ggnn"]}, batch, rows)
        out = jnp.concatenate([ggnn_out, rows], axis=-1)
        gp = p["pooling"]["gate_nn"]
        gate = out @ gp["kernel"] + gp["bias"]
        pooled, attn = attention_pool(
            gate[:, 0], out, batch.node_graph, batch.node_mask,
            batch.num_graphs,
        )
        logits = OutputHead(
            num_layers=model.num_output_layers,
            param_dtype=model.param_dtype,
        ).apply({"params": p["head"]}, pooled)
        return logits[..., 0], attn

    return fn, rows


def _summarize_nodes(attr: jax.Array, batch) -> jax.Array:
    """[N, D] node attributions -> [N] scores: sum over the embedding
    dim, L2-normalized WITHIN each graph segment (the captum-tutorial
    summarization of `_summarize`, per graph instead of per row);
    padding slots are zeroed."""
    from deepdfa_tpu.nn.gnn import segment_sum

    s = attr.sum(axis=-1)
    s = jnp.where(batch.node_mask, s, 0.0)
    norm = jnp.sqrt(
        segment_sum(
            s * s, batch.node_graph, batch.num_graphs + 1,
            indices_are_sorted=True,
        )
    )
    return s / jnp.maximum(norm[batch.node_graph], 1e-12)


def ggnn_score_fn(method: str, model, n_steps: int = 8) -> Callable:
    """Pure jittable (params, batch) -> (probs [G], node_scores [N]).

    One function serves both drives: the offline eval path jits it
    directly; serve/localize.py AOT-lowers it per batch signature
    (shared warmup ladder with the scoring executor). Methods mirror the
    transformer family where they transfer:

    - `attention`: the GlobalAttentionPooling gate weights — what the
      trained readout already attends to, gradient-free;
    - `saliency` / `input_x_gradient`: first-order grads of the vuln
      logit wrt the node embedding rows;
    - `deeplift`: n-step rescale against the zero baseline;
    - `lig`: integrated gradients against the model's own "node is not
      a definition" baseline (vocab index 0 in every subkey table — the
      GGNN analog of the reference's pad-everywhere ref input).

    Per-graph independence (masked segment ops, no cross-graph edges)
    keeps node scores independent of co-batched neighbors up to float32
    reduction order; at a FIXED batch signature the function is
    deterministic, which is what pins served-vs-offline bit-identity
    (tests/test_scan.py)."""
    if method not in GGNN_METHODS:
        raise ValueError(
            f"unknown GGNN method {method!r} (choose from {GGNN_METHODS})"
        )

    def run(params, batch):
        params = jax.tree.map(jnp.asarray, params)
        fn, rows = ggnn_forward(model, params, batch)
        logits, attn = fn(rows)
        probs = jax.nn.sigmoid(logits)
        if method == "attention":
            return probs, jnp.where(batch.node_mask, attn, 0.0)
        grad = jax.grad(lambda r: fn(r)[0].sum())
        if method == "saliency":
            attr = jnp.abs(grad(rows))
        elif method == "input_x_gradient":
            attr = grad(rows) * rows
        elif method == "deeplift":
            attr = _path_attribution(
                grad, rows, jnp.zeros_like(rows), n_steps
            )
        else:  # lig
            base = _ggnn_embedding(model).apply(
                {"params": _unwrap(params)["embedding"]},
                jnp.zeros_like(batch.node_feats),
            )
            attr = _path_attribution(grad, rows, base, n_steps)
        return probs, _summarize_nodes(attr, batch)

    return run


def node_line_attributions(
    node_scores, node_lines, top_k: int = 0
) -> list[dict]:
    """[n] per-node scores + [n] 1-based source lines (the function's
    own coordinates) -> ranked [{"line", "score"}], max-reduced per line
    (the `aggregate_line_scores` rule), truncated to `top_k` when > 0.

    No rounding: the served payload must stay bit-identical to the
    offline eval on the same checkpoint (tests/test_scan.py)."""
    by_line: dict[int, float] = {}
    for s, ln in zip(np.asarray(node_scores), np.asarray(node_lines)):
        ln = int(ln)
        if ln < 1:
            continue
        s = float(s)
        if ln not in by_line or s > by_line[ln]:
            by_line[ln] = s
    ranked = sorted(by_line.items(), key=lambda kv: (-kv[1], kv[0]))
    if top_k:
        ranked = ranked[:top_k]
    return [{"line": ln, "score": s} for ln, s in ranked]


def aggregate_line_scores(
    token_scores: np.ndarray,
    token_lines: np.ndarray,
    n_lines: int,
    reduce: str = "max",
) -> np.ndarray:
    """[T] token scores + [T] 1-based line ids (0 = no line) -> [n_lines].

    Attribution scores may be SIGNED (lig/deeplift/...): lines are
    max- or sum-reduced over their own tokens only (no zero clamp), and
    lines with no tokens rank strictly below every tokenized line — the
    reference scores only tokenized lines at all (get_all_lines_score)."""
    out = np.full((n_lines,), -np.inf)
    for s, ln in zip(np.asarray(token_scores), np.asarray(token_lines)):
        if 1 <= ln <= n_lines:
            i = int(ln) - 1
            if reduce == "max":
                out[i] = max(out[i], float(s))
            else:
                out[i] = float(s) if np.isinf(out[i]) else out[i] + float(s)
    present = np.isfinite(out)
    floor = (out[present].min() - 1.0) if present.any() else 0.0
    out[~present] = floor
    return out
