"""Digit-exact CodeBLEU dataflow match for java + c_sharp.

The reference evaluator's dataflow subscore is defined by
`CodeT5/evaluator/CodeBLEU/parser/DFG.py` (DFG_java:180-355,
DFG_csharp:356-538) running over tree-sitter parse trees, plus the
filter/merge/normalize pipeline in `dataflow_match.py:70-150`. Round 4
approximated those triples with the repo's reaching-definitions solver
— comparable, not digit-exact (VERDICT r4 missing #3). This module is
the digit-exact path: a purpose-built mini-parser produces trees whose
node types, child order, and field layout mirror the tree-sitter java /
c_sharp grammars *for exactly the constructs the DFG rules inspect*,
and a faithful reimplementation of the DFG recursion + the
dataflow_match pipeline runs over them.

What the DFG semantics actually depend on (everything else in the
grammars is irrelevant — unknown constructs fall into the generic
visit-children-in-order branch, whose only observable effect is the
ordered leaf stream):

- the ordered LEAF stream = the token stream (token index is the triple
  identity);
- leaf typing: anonymous tokens (keywords/punctuation, type == text)
  are invisible to the variable logic; `identifier` leaves update the
  def state; literal leaves participate as parents but never define
  (tree-sitter quirk faithfully kept: `true`/`false` are anonymous in
  both grammars and thus invisible, while `null` lifts to a
  `null_literal` token whose type != text, so it DOES participate);
- the special node shapes: variable_declarator (java: name/value
  fields; c#: [name, equals_value_clause] children — the len==2 check
  at DFG.py:377), assignment_expression (left/right),
  update_expression (java) / postfix_unary_expression (c# — prefix
  ++x is NOT an increment in c#, DFG.py:359), if/else, for,
  enhanced_for (java name/value/body) / for_each (c# left/right/body),
  while;
- the for-statement second pass triggers on a child typed exactly
  "local_variable_declaration" (DFG.py:294/470) — c#'s for initializer
  is `variable_declaration` in its grammar, so the c# second pass NEVER
  fires; this quirk is replicated, not fixed.

Validated by tests/test_dfg_parity.py: a golden corpus of snippets
whose normalized triples were hand-derived by executing DFG.py's logic
on paper (tree-sitter itself is not installed in this image — the
goldens cite the DFG.py lines they trace).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

# ---------------------------------------------------------------------------
# comment stripping (dataflow_match.py applies the 'java' branch of
# remove_comments_and_docstrings to BOTH candidate and reference for
# every language — replicated byte-for-byte including the
# blank-line removal)
# ---------------------------------------------------------------------------

_COMMENT_RE = re.compile(
    r'//.*?$|/\*.*?\*/|\'(?:\\.|[^\\\'])*\'|"(?:\\.|[^\\"])*"',
    re.DOTALL | re.MULTILINE,
)


def remove_comments(source: str) -> str:
    def replacer(match):
        s = match.group(0)
        if s.startswith("/"):
            return " "  # a space, not an empty string (utils.py:55-57)
        return s

    out = _COMMENT_RE.sub(replacer, source)
    return "\n".join(x for x in out.split("\n") if x.strip() != "")


# ---------------------------------------------------------------------------
# mini-AST
# ---------------------------------------------------------------------------


@dataclass
class Node:
    """Tree-sitter-shaped node: ordered children (anonymous tokens
    included, as tree-sitter's .children does), optional named fields,
    and for leaves the token (idx, text)."""

    type: str
    children: list["Node"] = field(default_factory=list)
    fields: dict[str, "Node"] = field(default_factory=dict)
    idx: int | None = None
    text: str | None = None

    @property
    def is_leaf(self) -> bool:
        return not self.children

    def child_by_field_name(self, name: str):
        return self.fields.get(name)


def _leaves(node: Node, out: list[Node]) -> None:
    if node.is_leaf:
        out.append(node)
        return
    for c in node.children:
        _leaves(c, out)


def tree_to_variable_index(node: Node) -> list[Node]:
    """Reference utils.tree_to_variable_index: leaves whose type differs
    from their text (named tokens: identifiers + literals)."""
    leaves: list[Node] = []
    _leaves(node, leaves)
    return [lf for lf in leaves if lf.type != lf.text]


# ---------------------------------------------------------------------------
# tokenizer adapter: hermetic tokens -> typed leaves
# ---------------------------------------------------------------------------

#: tree-sitter-java anonymous keywords (grammar.js terminals). true/false
#: are anonymous token rules there; null lifts to null_literal.
JAVA_KEYWORDS = frozenset(
    """abstract assert boolean break byte case catch char class const
    continue default do double else enum extends final finally float for
    goto if implements import instanceof int interface long native new
    package private protected public return short static strictfp super
    switch synchronized this throw throws transient try void volatile
    while true false""".split()
)

#: tree-sitter-c-sharp anonymous terminals (the subset a method body can
#: meet; `var` is the anonymous implicit_type token, `in` the foreach
#: separator; true/false anonymous as in java)
CSHARP_KEYWORDS = frozenset(
    """abstract as base bool break byte case catch char checked class
    const continue decimal default delegate do double else enum event
    explicit extern finally fixed float for foreach goto if implicit in
    int interface internal is lock long namespace new object operator
    out override params private protected public readonly ref return
    sbyte sealed short sizeof stackalloc static string struct switch
    this throw try typeof uint ulong unchecked unsafe ushort using
    var virtual void volatile while true false""".split()
)

_PRIMITIVES = {
    "java": frozenset(
        "boolean byte char double float int long short void".split()
    ),
    "cs": frozenset(
        """bool byte char decimal double float int long object sbyte
        short string uint ulong ushort var void""".split()
    ),
}


def _lex(code: str, dialect: str) -> list[Node]:
    """Token stream as typed leaves, tree-sitter leaf-typing rules."""
    from deepdfa_tpu.frontend.tokens import tokenize

    kws = JAVA_KEYWORDS if dialect == "java" else CSHARP_KEYWORDS
    leaves: list[Node] = []
    for t in tokenize(code, backend="python", dialect=dialect):
        if t.kind == "eof":
            break
        if t.kind in ("op",) or t.text in kws:
            ty = t.text  # anonymous: invisible to the variable logic
        elif t.text == "null":
            ty = "null_literal"
        elif t.kind == "id" or t.kind == "kw":
            ty = "identifier"
        elif t.kind == "num":
            ty = "decimal_integer_literal"
        elif t.kind == "str":
            ty = "string_literal"
        elif t.kind == "char":
            ty = "character_literal"
        else:
            ty = t.text
        leaves.append(Node(ty, idx=len(leaves), text=t.text))
    return leaves


# ---------------------------------------------------------------------------
# mini-parser (recursive descent over the typed leaves)
# ---------------------------------------------------------------------------

_ASSIGN_OPS = {
    "=", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "<<=", ">>=",
    ">>>=", "??=",
}
#: binary operator precedence (only relative order matters; the DFG
#: treats every binary_expression generically)
_BIN_PREC = {
    "||": 1, "&&": 2, "??": 2, "|": 3, "^": 4, "&": 5,
    "==": 6, "!=": 6, "<": 7, ">": 7, "<=": 7, ">=": 7,
    "<<": 8, ">>": 8, ">>>": 8, "+": 9, "-": 9,
    "*": 10, "/": 10, "%": 10,
}
_MODIFIERS = frozenset(
    """public private protected static final abstract native synchronized
    transient volatile strictfp readonly sealed virtual override internal
    extern unsafe const async partial""".split()
)


class _MiniParser:
    """Builds the tree-sitter-shaped tree the DFG rules need. Loose by
    design everywhere the DFG is insensitive (expression internals,
    modifiers, generics) and exact where it is not (the special node
    types, field layouts, and child order)."""

    def __init__(self, leaves: list[Node], dialect: str):
        self.toks = leaves
        self.i = 0
        self.d = dialect

    # -- cursor helpers --
    def peek(self, k: int = 0) -> Node | None:
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else None

    def at(self, text: str, k: int = 0) -> bool:
        t = self.peek(k)
        return t is not None and t.text == text

    def eat(self) -> Node:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> Node:
        if not self.at(text):
            got = self.peek()
            raise ValueError(
                f"dfg_parity parse: expected {text!r}, got "
                f"{got.text if got else 'EOF'!r} at {self.i}"
            )
        return self.eat()

    # -- entry --
    def parse_program(self) -> Node:
        items = []
        while self.peek() is not None:
            items.append(self.parse_statement())
        return Node("program", items)

    # -- types --
    def _looks_like_type_start(self) -> bool:
        t = self.peek()
        if t is None:
            return False
        if t.text in _PRIMITIVES[self.d]:
            return True
        return t.type == "identifier"

    def _scan_type_end(self, j: int) -> int | None:
        """Index just past a type starting at j, or None. Handles
        qualified names, one balanced <...> group, and [] suffixes."""
        t = self.toks[j] if j < len(self.toks) else None
        if t is None:
            return None
        if not (t.text in _PRIMITIVES[self.d] or t.type == "identifier"):
            return None
        j += 1
        while (
            j + 1 < len(self.toks)
            and self.toks[j].text == "."
            and self.toks[j + 1].type == "identifier"
        ):
            j += 2
        if j < len(self.toks) and self.toks[j].text == "<":
            depth = 0
            k = j
            while k < len(self.toks):
                tx = self.toks[k].text
                if tx == "<":
                    depth += 1
                elif tx == ">":
                    depth -= 1
                    if depth == 0:
                        k += 1
                        break
                elif tx in (";", "{", "}", ")", "=") or (
                    tx in _BIN_PREC and tx not in ("<", ">")
                ):
                    return None  # not a generic argument list
                k += 1
            else:
                return None
            j = k
        while (
            j + 1 < len(self.toks)
            and self.toks[j].text == "["
            and self.toks[j + 1].text == "]"
        ):
            j += 2
        return j

    def parse_type(self) -> Node:
        end = self._scan_type_end(self.i)
        if end is None:
            raise ValueError("dfg_parity parse: expected a type")
        kids = [self.eat() for _ in range(end - self.i)]
        return Node("type", kids)

    def _decl_lookahead(self) -> bool:
        """True when the cursor starts `Type name [=,;(]...` — a
        declaration (or method, resolved later)."""
        j = self.i
        while j < len(self.toks) and self.toks[j].text in _MODIFIERS:
            j += 1
        end = self._scan_type_end(j)
        if end is None or end >= len(self.toks):
            return False
        if self.toks[end].type != "identifier":
            return False
        nxt = self.toks[end + 1] if end + 1 < len(self.toks) else None
        return nxt is not None and nxt.text in ("=", ",", ";", "(")

    # -- statements --
    def parse_statement(self) -> Node:
        t = self.peek()
        if t is None:
            raise ValueError("dfg_parity parse: unexpected EOF")
        tx = t.text
        if tx == "{":
            return self.parse_block()
        if tx == ";":
            return Node("empty_statement", [self.eat()])
        if tx == "if":
            return self.parse_if()
        if tx == "while":
            return self.parse_while()
        if tx == "do":
            return self.parse_do()
        if tx == "for":
            return self.parse_for()
        if tx == "foreach" and self.d == "cs":
            return self.parse_foreach_cs()
        if tx in ("return", "throw"):
            kids = [self.eat()]
            if not self.at(";"):
                kids.append(self.parse_expression())
            kids.append(self.expect(";"))
            return Node(f"{tx}_statement", kids)
        if tx in ("break", "continue"):
            kids = [self.eat()]
            if self.peek() is not None and self.peek().type == "identifier":
                kids.append(self.eat())  # label
            kids.append(self.expect(";"))
            return Node(f"{tx}_statement", kids)
        if tx == "switch":
            return self.parse_switch()
        if tx == "try":
            return self.parse_try()
        if tx in ("class", "interface", "enum", "struct", "namespace"):
            return self.parse_class_like()
        if tx == "using" and self.d == "cs":
            kids = [self.eat()]
            while self.peek() is not None and not self.at(";"):
                kids.append(self.eat())
            kids.append(self.expect(";"))
            return Node("using_directive", kids)
        if self._decl_lookahead():
            return self.parse_declaration_or_method()
        # expression statement
        kids = [self.parse_expression()]
        kids.append(self.expect(";"))
        return Node("expression_statement", kids)

    def parse_block(self) -> Node:
        kids = [self.expect("{")]
        while not self.at("}"):
            kids.append(self.parse_statement())
        kids.append(self.expect("}"))
        return Node("block", kids)

    def parse_if(self) -> Node:
        kids = [self.expect("if"), self.parse_paren_expr(),
                self.parse_statement()]
        if self.at("else"):
            kids.append(self.eat())  # the 'else' LEAF the DFG rule keys on
            kids.append(self.parse_statement())
        return Node("if_statement", kids)

    def parse_while(self) -> Node:
        return Node("while_statement", [
            self.expect("while"), self.parse_paren_expr(),
            self.parse_statement(),
        ])

    def parse_do(self) -> Node:
        kids = [self.expect("do"), self.parse_statement(),
                self.expect("while"), self.parse_paren_expr(),
                self.expect(";")]
        return Node("do_statement", kids)

    def parse_for(self) -> Node:
        # java enhanced for: `for (Type name : expr) body`
        if self.d == "java":
            j = self.i + 2  # past `for (`
            depth = 1
            k = j
            colon = None
            while k < len(self.toks) and depth > 0:
                tx = self.toks[k].text
                if tx == "(":
                    depth += 1
                elif tx == ")":
                    depth -= 1
                elif tx == ";" and depth == 1:
                    break
                elif tx == ":" and depth == 1:
                    colon = k
                    break
                k += 1
            if colon is not None:
                kids = [self.expect("for"), self.expect("(")]
                ty = self.parse_type()
                name = self.eat()
                kids += [ty, name, self.expect(":")]
                value = self.parse_expression()
                kids += [value, self.expect(")")]
                body = self.parse_statement()
                kids.append(body)
                return Node(
                    "enhanced_for_statement", kids,
                    fields={"name": name, "value": value, "body": body},
                )
        kids = [self.expect("for"), self.expect("(")]
        decl_type = (
            "local_variable_declaration" if self.d == "java"
            else "variable_declaration"  # the c# grammar name — the
            # DFG's second-pass check never matches it (DFG.py:470)
        )
        if self.at(";"):
            kids.append(Node("empty_statement", [self.eat()]))
        elif self._decl_lookahead():
            kids.append(self.parse_declaration_or_method(
                node_type=decl_type, terminator=";"))
        else:
            kids.append(self.parse_expression())
            kids.append(self.expect(";"))
        if not self.at(";"):
            kids.append(self.parse_expression())
        kids.append(self.expect(";"))
        if not self.at(")"):
            kids.append(self.parse_expression())
            while self.at(","):
                kids.append(self.eat())
                kids.append(self.parse_expression())
        kids.append(self.expect(")"))
        kids.append(self.parse_statement())
        return Node("for_statement", kids)

    def parse_foreach_cs(self) -> Node:
        kids = [self.expect("foreach"), self.expect("(")]
        ty = self.parse_type()
        name = self.eat()
        kids += [ty, name, self.expect("in")]
        value = self.parse_expression()
        kids += [value, self.expect(")")]
        body = self.parse_statement()
        kids.append(body)
        return Node(
            "for_each_statement", kids,
            fields={"left": name, "right": value, "body": body},
        )

    def parse_switch(self) -> Node:
        kids = [self.expect("switch"), self.parse_paren_expr(),
                self.expect("{")]
        while not self.at("}"):
            if self.at("case"):
                kids.append(self.eat())
                kids.append(self.parse_expression())
                kids.append(self.expect(":"))
            elif self.at("default"):
                kids.append(self.eat())
                kids.append(self.expect(":"))
            else:
                kids.append(self.parse_statement())
        kids.append(self.expect("}"))
        return Node("switch_statement", kids)

    def parse_try(self) -> Node:
        kids = [self.expect("try"), self.parse_block()]
        while self.at("catch"):
            kids.append(self.eat())
            if self.at("("):
                kids.append(self.expect("("))
                kids.append(self.parse_type())
                if self.peek().type == "identifier":
                    kids.append(self.eat())
                kids.append(self.expect(")"))
            kids.append(self.parse_block())
        if self.at("finally"):
            kids.append(self.eat())
            kids.append(self.parse_block())
        return Node("try_statement", kids)

    def parse_class_like(self) -> Node:
        kids = [self.eat()]  # class/struct/... keyword
        while not self.at("{"):
            kids.append(self.eat())  # name, extends, generics — generic
        kids.append(self.expect("{"))
        while not self.at("}"):
            kids.append(self.parse_statement())
        kids.append(self.expect("}"))
        return Node("class_declaration", kids)

    def parse_declaration_or_method(
        self, node_type: str | None = None, terminator: str = ";"
    ) -> Node:
        kids: list[Node] = []
        while self.peek() is not None and self.peek().text in _MODIFIERS:
            kids.append(self.eat())
        ty = self.parse_type()
        kids.append(ty)
        if (
            self.peek() is not None
            and self.peek().type == "identifier"
            and self.at("(", 1)
        ):
            return self._parse_method(kids)
        decl_node_type = node_type or (
            "local_variable_declaration" if self.d == "java"
            else "variable_declaration"
        )
        while True:
            kids.append(self.parse_declarator())
            if self.at(","):
                kids.append(self.eat())
                continue
            break
        kids.append(self.expect(terminator))
        return Node(decl_node_type, kids)

    def parse_declarator(self) -> Node:
        name = self.eat()
        if name.type != "identifier":
            raise ValueError(
                f"dfg_parity parse: declarator name, got {name.text!r}"
            )
        if not self.at("="):
            return Node("variable_declarator", [name],
                        fields={"name": name})
        eq = self.eat()
        value = self.parse_expression(no_comma=True)
        if self.d == "java":
            # java grammar: declarator children include '=', value FIELD
            # is the expression itself
            return Node(
                "variable_declarator", [name, eq, value],
                fields={"name": name, "value": value},
            )
        # c# grammar: [identifier, equals_value_clause] — the len==2
        # shape DFG_csharp's def_statement branch checks (DFG.py:377)
        evc = Node("equals_value_clause", [eq, value])
        return Node("variable_declarator", [name, evc],
                    fields={"name": name})

    def _parse_method(self, kids: list[Node]) -> Node:
        kids.append(self.eat())  # method name (identifier leaf)
        params = [self.expect("(")]
        while not self.at(")"):
            pk: list[Node] = []
            while self.peek().text in _MODIFIERS | {"ref", "out", "final"}:
                pk.append(self.eat())
            pk.append(self.parse_type())
            pk.append(self.eat())  # param name
            params.append(Node("formal_parameter", pk))
            if self.at(","):
                params.append(self.eat())
        params.append(self.expect(")"))
        kids.append(Node("formal_parameters", params))
        if self.at("{"):
            kids.append(self.parse_block())
        else:
            kids.append(self.expect(";"))
        return Node("method_declaration", kids)

    # -- expressions --
    def parse_paren_expr(self) -> Node:
        return Node("parenthesized_expression", [
            self.expect("("), self.parse_expression(), self.expect(")"),
        ])

    def parse_expression(self, no_comma: bool = False) -> Node:
        return self._assignment(no_comma)

    def _assignment(self, no_comma: bool) -> Node:
        left = self._ternary(no_comma)
        t = self.peek()
        if t is not None and t.text in _ASSIGN_OPS:
            op = self.eat()
            right = self._assignment(no_comma)  # right-assoc
            return Node(
                "assignment_expression", [left, op, right],
                fields={"left": left, "right": right},
            )
        return left

    def _ternary(self, no_comma: bool) -> Node:
        cond = self._binary(0, no_comma)
        if self.at("?"):
            q = self.eat()
            then = self._assignment(no_comma)
            c = self.expect(":")
            els = self._assignment(no_comma)
            return Node("ternary_expression", [cond, q, then, c, els])
        return cond

    def _binary(self, min_prec: int, no_comma: bool) -> Node:
        left = self._unary(no_comma)
        while True:
            t = self.peek()
            if t is None or t.text not in _BIN_PREC:
                break
            prec = _BIN_PREC[t.text]
            if prec < min_prec:
                break
            op = self.eat()
            right = self._binary(prec + 1, no_comma)
            left = Node("binary_expression", [left, op, right])
        return left

    def _unary(self, no_comma: bool) -> Node:
        t = self.peek()
        if t is not None and t.text in ("++", "--"):
            op = self.eat()
            operand = self._unary(no_comma)
            ty = ("update_expression" if self.d == "java"
                  else "prefix_unary_expression")  # c# prefix is NOT an
            # increment statement for the DFG (DFG.py:359)
            return Node(ty, [op, operand])
        if t is not None and t.text in ("!", "~", "+", "-"):
            op = self.eat()
            return Node("unary_expression", [op, self._unary(no_comma)])
        if t is not None and t.text == "new":
            kids = [self.eat(), self.parse_type()]
            if self.at("("):
                kids.append(self._argument_list())
            elif self.at("{"):
                kids.append(self._array_initializer())
            return self._postfix(
                Node("object_creation_expression", kids), no_comma
            )
        if (
            t is not None and t.text == "("
            and self._cast_lookahead()
        ):
            kids = [self.eat(), self.parse_type(), self.expect(")")]
            kids.append(self._unary(no_comma))
            return Node("cast_expression", kids)
        return self._postfix(self._primary(), no_comma)

    def _cast_lookahead(self) -> bool:
        """`( Type )` followed by an operand — a cast, not parens."""
        end = self._scan_type_end(self.i + 1)
        if end is None or end >= len(self.toks):
            return False
        if self.toks[end].text != ")":
            return False
        nxt = self.toks[end + 1] if end + 1 < len(self.toks) else None
        if nxt is None:
            return False
        return (
            nxt.type in ("identifier", "decimal_integer_literal",
                         "string_literal", "character_literal",
                         "null_literal")
            or nxt.text in ("(", "!", "~", "new")
        )

    def _postfix(self, node: Node, no_comma: bool) -> Node:
        while True:
            if self.at("("):
                node = Node("method_invocation",
                            [node, self._argument_list()])
            elif self.at("["):
                lb = self.eat()
                idx = self.parse_expression()
                rb = self.expect("]")
                node = Node("array_access", [node, lb, idx, rb])
            elif self.at(".") or (self.d == "cs" and self.at("?.")):
                dot = self.eat()
                member = self.eat()
                node = Node("field_access", [node, dot, member])
            elif self.at("++") or self.at("--"):
                op = self.eat()
                ty = ("update_expression" if self.d == "java"
                      else "postfix_unary_expression")
                node = Node(ty, [node, op])
            else:
                return node

    def _argument_list(self) -> Node:
        kids = [self.expect("(")]
        while not self.at(")"):
            kids.append(self.parse_expression(no_comma=True))
            if self.at(","):
                kids.append(self.eat())
        kids.append(self.expect(")"))
        return Node("argument_list", kids)

    def _array_initializer(self) -> Node:
        kids = [self.expect("{")]
        while not self.at("}"):
            if self.at("{"):
                kids.append(self._array_initializer())
            else:
                kids.append(self.parse_expression(no_comma=True))
            if self.at(","):
                kids.append(self.eat())
        kids.append(self.expect("}"))
        return Node("array_initializer", kids)

    def _primary(self) -> Node:
        t = self.peek()
        if t is None:
            raise ValueError("dfg_parity parse: unexpected EOF in expr")
        if t.text == "(":
            return self.parse_paren_expr()
        return self.eat()  # identifier / literal / anonymous keyword


def parse_snippet(code: str, lang: str) -> Node:
    dialect = "java" if lang == "java" else "cs"
    leaves = _lex(code, dialect)
    return _MiniParser(leaves, dialect).parse_program()


# ---------------------------------------------------------------------------
# the DFG recursion (faithful port of DFG_java / DFG_csharp)
# ---------------------------------------------------------------------------


def _var_idx_code(nodes: list[Node]) -> list[tuple[int, str]]:
    return [(n.idx, n.text) for n in nodes]


def _merge_rounds(DFG):
    """The dedup-merge the reference applies after for/while/foreach
    double passes (DFG.py:293-302 et al.)."""
    dic = {}
    for x in DFG:
        key = (x[0], x[1], x[2])
        if key not in dic:
            dic[key] = [x[3], x[4]]
        else:
            dic[key][0] = list(set(dic[key][0] + x[3]))
            dic[key][1] = sorted(set(dic[key][1] + x[4]))
    return [
        (k[0], k[1], k[2], v[0], v[1])
        for k, v in sorted(dic.items(), key=lambda t: t[0][1])
    ]


def dfg_extract(root: Node, lang: str, states: dict) -> tuple[list, dict]:
    """(DFG, states) — the recursion of DFG_java (DFG.py:180) /
    DFG_csharp (DFG.py:356), structure preserved branch-for-branch."""
    java = lang == "java"
    assignment = ["assignment_expression"]
    def_statement = ["variable_declarator"]
    increment_statement = (
        ["update_expression"] if java else ["postfix_unary_expression"]
    )
    if_statement = ["if_statement", "else"]
    for_statement = ["for_statement"]
    enhanced_for = (
        ["enhanced_for_statement"] if java else ["for_each_statement"]
    )
    while_statement = ["while_statement"]
    states = states.copy()
    rec = dfg_extract

    if root.is_leaf or root.type in (
        "string_literal", "string", "character_literal"
    ):
        if not root.is_leaf:  # string node with internal children
            idx, code = root.idx, root.text
        else:
            idx, code = root.idx, root.text
        if root.type == code:
            return [], states
        elif code in states:
            return (
                [(code, idx, "comesFrom", [code], states[code].copy())],
                states,
            )
        else:
            if root.type == "identifier":
                states[code] = [idx]
            return [(code, idx, "comesFrom", [], [])], states

    if root.type in def_statement:
        if java:
            name = root.child_by_field_name("name")
            value = root.child_by_field_name("value")
        else:
            if len(root.children) == 2:
                name, value = root.children[0], root.children[1]
            else:
                name, value = root.children[0], None
        DFG = []
        if value is None:
            for idx, code in _var_idx_code(tree_to_variable_index(name)):
                DFG.append((code, idx, "comesFrom", [], []))
                states[code] = [idx]
            return sorted(DFG, key=lambda x: x[1]), states
        name_iv = _var_idx_code(tree_to_variable_index(name))
        value_iv = _var_idx_code(tree_to_variable_index(value))
        temp, states = rec(value, lang, states)
        DFG += temp
        for idx1, code1 in name_iv:
            for idx2, code2 in value_iv:
                DFG.append((code1, idx1, "comesFrom", [code2], [idx2]))
            states[code1] = [idx1]
        return sorted(DFG, key=lambda x: x[1]), states

    if root.type in assignment:
        left = root.child_by_field_name("left")
        right = root.child_by_field_name("right")
        DFG = []
        temp, states = rec(right, lang, states)
        DFG += temp
        name_iv = _var_idx_code(tree_to_variable_index(left))
        value_iv = _var_idx_code(tree_to_variable_index(right))
        for idx1, code1 in name_iv:
            for idx2, code2 in value_iv:
                DFG.append((code1, idx1, "computedFrom", [code2], [idx2]))
            states[code1] = [idx1]
        return sorted(DFG, key=lambda x: x[1]), states

    if root.type in increment_statement:
        DFG = []
        iv = _var_idx_code(tree_to_variable_index(root))
        for idx1, code1 in iv:
            for idx2, code2 in iv:
                DFG.append((code1, idx1, "computedFrom", [code2], [idx2]))
            states[code1] = [idx1]
        return sorted(DFG, key=lambda x: x[1]), states

    if root.type in if_statement:
        DFG = []
        current_states = states.copy()
        others_states = []
        flag = False
        tag = False
        if "else" in root.type:
            tag = True
        for child in root.children:
            if "else" in child.type:
                tag = True
            if child.type not in if_statement and flag is False:
                temp, current_states = rec(child, lang, current_states)
                DFG += temp
            else:
                flag = True
                temp, new_states = rec(child, lang, states)
                DFG += temp
                others_states.append(new_states)
        others_states.append(current_states)
        if tag is False:
            others_states.append(states)
        new_states = {}
        for dic in others_states:
            for key in dic:
                if key not in new_states:
                    new_states[key] = dic[key].copy()
                else:
                    new_states[key] += dic[key]
        for key in new_states:
            new_states[key] = sorted(set(new_states[key]))
        return sorted(DFG, key=lambda x: x[1]), new_states

    if root.type in for_statement:
        DFG = []
        for child in root.children:
            temp, states = rec(child, lang, states)
            DFG += temp
        flag = False
        for child in root.children:
            if flag:
                temp, states = rec(child, lang, states)
                DFG += temp
            elif child.type == "local_variable_declaration":
                flag = True
        return _merge_rounds(DFG), states

    if root.type in enhanced_for:
        if java:
            name = root.child_by_field_name("name")
            value = root.child_by_field_name("value")
        else:
            name = root.child_by_field_name("left")
            value = root.child_by_field_name("right")
        body = root.child_by_field_name("body")
        DFG = []
        for _ in range(2):
            temp, states = rec(value, lang, states)
            DFG += temp
            name_iv = _var_idx_code(tree_to_variable_index(name))
            value_iv = _var_idx_code(tree_to_variable_index(value))
            for idx1, code1 in name_iv:
                for idx2, code2 in value_iv:
                    DFG.append(
                        (code1, idx1, "computedFrom", [code2], [idx2])
                    )
                states[code1] = [idx1]
            temp, states = rec(body, lang, states)
            DFG += temp
        return _merge_rounds(DFG), states

    if root.type in while_statement:
        DFG = []
        for _ in range(2):
            for child in root.children:
                temp, states = rec(child, lang, states)
                DFG += temp
        return _merge_rounds(DFG), states

    DFG = []
    for child in root.children:
        temp, states = rec(child, lang, states)
        DFG += temp
    return sorted(DFG, key=lambda x: x[1]), states


# ---------------------------------------------------------------------------
# dataflow_match.py pipeline (get_data_flow filter/merge + normalize +
# corpus match), replicated exactly
# ---------------------------------------------------------------------------


def get_data_flow(code: str, lang: str) -> list:
    try:
        root = parse_snippet(code, lang)
        try:
            DFG, _ = dfg_extract(root, lang, {})
        except Exception:
            DFG = []
        DFG = sorted(DFG, key=lambda x: x[1])
        indexs = set()
        for d in DFG:
            if len(d[-1]) != 0:
                indexs.add(d[1])
            for x in d[-1]:
                indexs.add(x)
        dfg = [d for d in DFG if d[1] in indexs]
    except Exception:
        dfg = []
    # merge nodes (dataflow_match.py:100-110)
    dic = {}
    for d in dfg:
        if d[1] not in dic:
            dic[d[1]] = d
        else:
            dic[d[1]] = (
                d[0], d[1], d[2],
                list(set(dic[d[1]][3] + d[3])),
                list(set(dic[d[1]][4] + d[4])),
            )
    return [dic[d] for d in dic]


def normalize_dataflow(dataflow: list) -> list:
    """dataflow_match.py:129-145: sequential alpha-renaming, parents
    before the target var within each item."""
    var_dict: dict[str, str] = {}
    i = 0
    out = []
    for item in dataflow:
        var_name = item[0]
        relationship = item[2]
        par_vars = item[3]
        for name in par_vars:
            if name not in var_dict:
                var_dict[name] = "var_" + str(i)
                i += 1
        if var_name not in var_dict:
            var_dict[var_name] = "var_" + str(i)
            i += 1
        out.append(
            (var_dict[var_name], relationship,
             [var_dict[x] for x in par_vars])
        )
    return out


def corpus_dataflow_match(
    list_of_references, candidates, lang: str
) -> float:
    """Reference corpus_dataflow_match (dataflow_match.py:28-67) with
    the same comment-stripping, triple matching, and degenerate-0
    semantics."""
    match_count = 0
    total_count = 0
    for references_sample, candidate in zip(list_of_references, candidates):
        for reference in references_sample:
            try:
                candidate = remove_comments(candidate)
            except Exception:
                pass
            try:
                reference = remove_comments(reference)
            except Exception:
                pass
            cand_dfg = normalize_dataflow(get_data_flow(candidate, lang))
            ref_dfg = normalize_dataflow(get_data_flow(reference, lang))
            if len(ref_dfg) > 0:
                total_count += len(ref_dfg)
                for dataflow in ref_dfg:
                    if dataflow in cand_dfg:
                        match_count += 1
                        cand_dfg.remove(dataflow)
    if total_count == 0:
        return 0.0
    return match_count / total_count
