"""IVDetect per-line code-representation features.

The reference's `feature_extraction` (DDFA/sastvd/helpers/evaluate.py:
19-191) dumps, per statement line of a function, the five IVDetect
feature families consumed by its line-level baselines:

1. **subseq** — the line's code (longest code string among the line's
   nodes, prefixed with the local declaration type when present),
   tokenised with the IVDetect subtoken splitter (tokenise.py);
2. **ast** — the intra-line AST as `[parent_idx, child_idx, token_lists]`
   with per-line node indices, lone/parent nodes re-rooted onto index 0
   (evaluate.py:69-103);
3. **nametypes** — tokenised "type name" pairs for identifiers whose
   declared type is known on that line (the reference walks Joern's
   REF/EVAL_TYPE component, evaluate.py:106-124; the hermetic CPG carries
   declared types directly on IDENTIFIER/LOCAL nodes);
4. **data** — line-level DDG neighbours (reaching-def use-def edges,
   undirected, evaluate.py:127-168);
5. **control** — line-level CDG neighbours (Ferrante-Ottenstein-Warren
   control dependence, same treatment).

Output mirrors the reference's `[pdg_nodes, pdg_edges]` cache record:
a per-line feature table plus line-level PDG edge lists.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

from deepdfa_tpu.frontend.cpg import AST, Cpg
from deepdfa_tpu.frontend.deps import control_dependences, data_dependences
from deepdfa_tpu.frontend.tokenise import tokenise


@dataclasses.dataclass
class LineFeatures:
    line: int
    subseq: str
    ast: tuple[list[int], list[int], list[str]]
    nametypes: str
    data: list[int]
    control: list[int]


def _line_nodes(cpg: Cpg) -> dict[int, list[int]]:
    by_line: dict[int, list[int]] = {}
    for node in cpg.nodes:
        if node.line is None or node.label in ("METHOD", "METHOD_RETURN"):
            continue
        by_line.setdefault(int(node.line), []).append(node.id)
    return by_line


def _subseq(cpg: Cpg, nids: list[int]) -> str:
    """Longest code string on the line; LOCAL declarations contribute
    their type as a prefix (reference: local_type + " " + code)."""
    best = max(nids, key=lambda n: len(cpg.nodes[n].code or ""))
    code = cpg.nodes[best].code or ""
    local_types = [
        cpg.nodes[n].type_full_name
        for n in nids
        if cpg.nodes[n].label == "LOCAL"
        and cpg.nodes[n].type_full_name not in (None, "", "ANY")
    ]
    if local_types:
        code = f"{local_types[0]} {code}"
    return tokenise(code)


def _line_ast(
    cpg: Cpg, line: int, nids: list[int]
) -> tuple[list[int], list[int], list[str]]:
    """Intra-line AST with per-line indices; lone/parent nodes re-rooted
    under index 0 (evaluate.py:93-103)."""
    idx = {nid: i for i, nid in enumerate(sorted(nids))}
    parents: list[int] = []
    children: list[int] = []
    for src, dst, t in cpg.edges:
        if t != AST:
            continue
        if src in idx and dst in idx:
            parents.append(idx[src])
            children.append(idx[dst])
    all_idx = set(idx.values())
    lone = all_idx - set(parents) - set(children)
    roots = set(parents) - set(children)
    for n in sorted((lone | roots) - {0}):
        parents.append(0)
        children.append(n)
    codes = [tokenise(cpg.nodes[nid].code or "") for nid in sorted(nids)]
    return parents, children, codes


def _nametypes(cpg: Cpg, nids: list[int]) -> str:
    pairs: list[tuple[str, str]] = []
    seen = set()
    for nid in sorted(nids):
        node = cpg.nodes[nid]
        if node.label not in ("IDENTIFIER", "LOCAL"):
            continue
        typ = node.type_full_name
        if not typ or typ == "ANY" or not node.name:
            continue
        key = (typ, node.name)
        if key in seen:
            continue
        seen.add(key)
        pairs.append(key)
    return " ".join(f"{tokenise(t)} {tokenise(n)}" for t, n in pairs)


def _line_edges(cpg: Cpg, pairs: set[tuple[int, int]]) -> set[tuple[int, int]]:
    out: set[tuple[int, int]] = set()
    for a, b in pairs:
        la, lb = cpg.nodes[a].line, cpg.nodes[b].line
        if la is None or lb is None or la == lb:
            continue
        out.add((int(la), int(lb)))
    return out


def feature_extraction(
    cpg: Cpg,
) -> tuple[list[LineFeatures], tuple[list[int], list[int]]]:
    """Per-line IVDetect features + line-level PDG edges.

    Returns (rows sorted by line, (pdg_src_lines, pdg_dst_lines)) — the
    same record shape the reference caches per file
    (evaluate.py:173-191).
    """
    by_line = _line_nodes(cpg)
    ddg = _line_edges(cpg, data_dependences(cpg))
    cdg = _line_edges(cpg, control_dependences(cpg))

    data_adj: dict[int, set[int]] = {}
    control_adj: dict[int, set[int]] = {}
    for adj, pairs in ((data_adj, ddg), (control_adj, cdg)):
        for a, b in pairs:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set()).add(a)  # reference symmetrizes

    rows = [
        LineFeatures(
            line=line,
            subseq=_subseq(cpg, nids),
            ast=_line_ast(cpg, line, nids),
            nametypes=_nametypes(cpg, nids),
            data=sorted(data_adj.get(line, ())),
            control=sorted(control_adj.get(line, ())),
        )
        for line, nids in sorted(by_line.items())
    ]
    pdg = sorted(ddg | cdg)
    return rows, ([a for a, _ in pdg], [b for _, b in pdg])


def feature_extraction_code(code: str):
    from deepdfa_tpu.frontend.parser import parse_function

    return feature_extraction(parse_function(code))


def dump_features(code: str, out_path: str | Path) -> None:
    """JSON dump (the reference pickles; JSON keeps the artifact
    inspectable and language-neutral)."""
    rows, pdg = feature_extraction_code(code)
    Path(out_path).write_text(
        json.dumps(
            {
                "lines": [dataclasses.asdict(r) for r in rows],
                "pdg_edges": pdg,
            },
            indent=1,
        )
    )
