from deepdfa_tpu.eval.codebleu import get_codebleu
from deepdfa_tpu.eval.coverage import CoverageStats, coverage, coverage_report
from deepdfa_tpu.eval.profiling import (
    ProfileWriter,
    aggregate_report,
    compiled_cost,
    profile_model,
    time_fn,
    xprof_trace,
)
from deepdfa_tpu.eval.statements import (
    RankedExample,
    effort_at_recall,
    ifa,
    recall_at_effort,
    statement_report,
    top_k_accuracy,
)

__all__ = [
    "get_codebleu",
    "CoverageStats",
    "coverage",
    "coverage_report",
    "ProfileWriter",
    "aggregate_report",
    "compiled_cost",
    "profile_model",
    "time_fn",
    "xprof_trace",
    "RankedExample",
    "effort_at_recall",
    "ifa",
    "recall_at_effort",
    "statement_report",
    "top_k_accuracy",
]
