"""Trivial-baseline control: logistic regression over subkey histograms.

The effectiveness evidence on the synthetic corpus only means something
if the task is not linearly separable from bag-of-feature counts
(VERDICT r3: round-3's corpus hit test precision 1.000, consistent with
template counting rather than learned dataflow). This control fits an
L2-regularized logistic regression on each graph's histogram of
abstract-dataflow vocab indices — exactly the information a
token/feature counter has, with all graph structure discarded — and is
reported next to the GGNN in docs/convergence_run.json. The reference
bar is paper Table 3's dynamics: DeepDFA's wins come from dataflow, so
the GGNN must beat this control by a clear margin on corpus v2's
order-sensitive families (data/synthetic.py:generate_v2), where the
buggy and fixed forms have IDENTICAL histograms.

Pure numpy on purpose: the control must be too simple to hide capacity.
"""

from __future__ import annotations

import numpy as np


def subkey_histograms(specs, input_dim: int) -> np.ndarray:
    """[n_specs, n_feats * input_dim] log1p counts of each (feature
    column, vocab index) pair over the graph's nodes."""
    if not specs:
        return np.zeros((0, 0), np.float32)
    n_feats = specs[0].node_feats.shape[1]
    X = np.zeros((len(specs), n_feats * input_dim), np.float32)
    for r, s in enumerate(specs):
        feats = np.asarray(s.node_feats)
        for c in range(n_feats):
            np.add.at(X[r], c * input_dim + feats[:, c], 1.0)
    return np.log1p(X)


def train_logistic(
    X: np.ndarray,
    y: np.ndarray,
    l2: float = 1e-3,
    lr: float = 0.5,
    epochs: int = 400,
    seed: int = 0,
) -> tuple[np.ndarray, float]:
    """Full-batch gradient descent with balanced class weights (the
    corpus keeps Big-Vul's ~6% positive rate); returns (w, b)."""
    rng = np.random.default_rng(seed)
    n, d = X.shape
    w = rng.normal(0, 0.01, size=d).astype(np.float64)
    b = 0.0
    y = np.asarray(y, np.float64)
    pos = max(y.sum(), 1.0)
    neg = max(n - y.sum(), 1.0)
    sample_w = np.where(y == 1.0, n / (2.0 * pos), n / (2.0 * neg))
    Xd = np.asarray(X, np.float64)
    for _ in range(epochs):
        z = Xd @ w + b
        p = 1.0 / (1.0 + np.exp(-np.clip(z, -30, 30)))
        g = sample_w * (p - y)
        w -= lr * (Xd.T @ g / n + l2 * w)
        b -= lr * float(g.mean())
    return w, b


def predict_proba(X: np.ndarray, w: np.ndarray, b: float) -> np.ndarray:
    z = np.asarray(X, np.float64) @ w + b
    return 1.0 / (1.0 + np.exp(-np.clip(z, -30, 30)))


def binary_metrics(probs: np.ndarray, y: np.ndarray) -> dict[str, float]:
    pred = (np.asarray(probs) >= 0.5).astype(np.int64)
    y = np.asarray(y, np.int64)
    tp = int(((pred == 1) & (y == 1)).sum())
    fp = int(((pred == 1) & (y == 0)).sum())
    fn = int(((pred == 0) & (y == 1)).sum())
    precision = tp / (tp + fp) if tp + fp else 0.0
    recall = tp / (tp + fn) if tp + fn else 0.0
    f1 = (
        2 * precision * recall / (precision + recall)
        if precision + recall
        else 0.0
    )
    return {
        "acc": float((pred == y).mean()) if len(y) else 0.0,
        "precision": precision,
        "recall": recall,
        "f1": f1,
    }


def logistic_control(
    train_specs, eval_splits: dict[str, list], input_dim: int, seed: int = 0
) -> dict[str, dict[str, float]]:
    """Fit on the train split, evaluate on every split in `eval_splits`;
    returns {split: metrics}."""
    Xtr = subkey_histograms(train_specs, input_dim)
    ytr = np.array([s.label for s in train_specs])
    w, b = train_logistic(Xtr, ytr, seed=seed)
    out = {}
    for name, specs in eval_splits.items():
        X = subkey_histograms(specs, input_dim)
        y = np.array([s.label for s in specs])
        out[name] = binary_metrics(predict_proba(X, w, b), y)
    return out
