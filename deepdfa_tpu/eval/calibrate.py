"""Temperature-scaling calibration + cascade band fitting
(docs/cascade.md).

The two-stage cascade (serve/cascade.py) escalates requests whose
stage-1 probability is *uncertain* — but a raw GGNN sigmoid is not a
calibrated probability, so "uncertain" must be defined after a
calibration map. This module is the small utility that fits both halves
from a labeled dev set:

- `fit_temperature(probs, labels)` — classic temperature scaling
  (Guo et al. 2017): one scalar T minimizing NLL of
  sigmoid(logit(p) / T). Golden-section search over log T; numpy only,
  deterministic.
- `fit_band(probs, labels, temperature, target_escalation)` — the
  uncertainty band (lo, hi) around 0.5 of the CALIBRATED probabilities
  such that approximately `target_escalation` of the dev set falls
  inside it. The band is the symmetric |p - 0.5| quantile: the requests
  the calibrated stage 1 is least sure about are exactly the ones worth
  a stage-2 transformer pass.
- `auc(probs, labels)` — rank AUC (ties averaged), the accuracy metric
  the cascade bench's drift gate compares on.

The fitted (temperature, band) pair feeds `serve.cascade_temperature` /
`serve.cascade_band`; the `cascade-calibrate` CLI command wraps this
module for operators.
"""

from __future__ import annotations

import numpy as np

_EPS = 1e-7


def _logit(p: np.ndarray) -> np.ndarray:
    p = np.clip(np.asarray(p, dtype=np.float64), _EPS, 1.0 - _EPS)
    return np.log(p / (1.0 - p))


def temperature_scale(probs, temperature: float) -> np.ndarray:
    """sigmoid(logit(p) / T): T > 1 softens (towards 0.5), T < 1
    sharpens. T=1 is the identity up to float round-trip."""
    z = _logit(probs) / max(float(temperature), _EPS)
    return (1.0 / (1.0 + np.exp(-z))).astype(np.float64)


def nll(probs, labels, temperature: float = 1.0) -> float:
    """Mean negative log likelihood of the (temperature-scaled) probs."""
    p = np.clip(
        temperature_scale(probs, temperature), _EPS, 1.0 - _EPS
    )
    y = np.asarray(labels, dtype=np.float64)
    return float(-np.mean(y * np.log(p) + (1.0 - y) * np.log(1.0 - p)))


def fit_temperature(
    probs, labels, lo: float = -3.0, hi: float = 3.0, iters: int = 60
) -> float:
    """Golden-section minimization of NLL over log T in [lo, hi].

    Needs both classes present (a one-class dev set has a degenerate
    optimum at T -> inf); raises ValueError otherwise."""
    y = np.asarray(labels)
    if y.size == 0 or y.min() == y.max():
        raise ValueError(
            "fit_temperature needs a labeled dev set with BOTH classes "
            f"present (got labels {sorted(set(np.asarray(y).tolist()))})"
        )
    phi = (np.sqrt(5.0) - 1.0) / 2.0
    a, b = float(lo), float(hi)
    c, d = b - phi * (b - a), a + phi * (b - a)
    fc, fd = nll(probs, y, np.exp(c)), nll(probs, y, np.exp(d))
    for _ in range(int(iters)):
        if fc <= fd:
            b, d, fd = d, c, fc
            c = b - phi * (b - a)
            fc = nll(probs, y, np.exp(c))
        else:
            a, c, fc = c, d, fd
            d = a + phi * (b - a)
            fd = nll(probs, y, np.exp(d))
    return float(np.exp((a + b) / 2.0))


def fit_band(
    probs,
    labels=None,
    temperature: float = 1.0,
    target_escalation: float = 0.3,
) -> tuple[float, float]:
    """The uncertainty band (lo, hi): symmetric around 0.5 in calibrated
    probability space, sized so ~`target_escalation` of the dev set
    falls inside. `labels` is accepted (the calibration recipe passes
    the same arrays to both fits) but the band itself is a quantile of
    the score distribution, not of the labels."""
    del labels  # recipe symmetry; see docstring
    t = float(np.clip(target_escalation, 0.0, 1.0))
    if t <= 0.0:
        return (0.5, 0.5)  # empty band: nothing escalates
    cal = temperature_scale(probs, temperature)
    d = np.sort(np.abs(cal - 0.5))
    r = float(d[min(len(d) - 1, max(0, int(np.ceil(t * len(d))) - 1))])
    # half-open band [lo, hi): nudge hi so the boundary sample escalates
    r = min(r + 1e-9, 0.5)
    return (0.5 - r, 0.5 + r)


def in_band(prob: float, band: tuple[float, float]) -> bool:
    """The one escalation predicate (serve/cascade.py imports it): the
    band is half-open [lo, hi) so a degenerate (x, x) band is empty."""
    lo, hi = band
    return float(lo) <= float(prob) < float(hi)


def auc(probs, labels) -> float | None:
    """Rank AUC with tied-score averaging; None when one class is
    missing (AUC undefined)."""
    p = np.asarray(probs, dtype=np.float64)
    y = np.asarray(labels)
    n_pos = int(np.sum(y == 1))
    n_neg = int(np.sum(y == 0))
    if n_pos == 0 or n_neg == 0:
        return None
    order = np.argsort(p, kind="mergesort")
    ranks = np.empty(len(p), dtype=np.float64)
    sorted_p = p[order]
    i = 0
    while i < len(p):
        j = i
        while j + 1 < len(p) and sorted_p[j + 1] == sorted_p[i]:
            j += 1
        ranks[order[i:j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    return float(
        (np.sum(ranks[y == 1]) - n_pos * (n_pos + 1) / 2.0)
        / (n_pos * n_neg)
    )


def calibrate(
    probs, labels, target_escalation: float = 0.3
) -> dict:
    """The one-call recipe: fit T, fit the band, report the dev-set
    escalation rate and AUC — what `cascade-calibrate` prints and the
    cascade bench embeds."""
    temperature = fit_temperature(probs, labels)
    band = fit_band(
        probs, labels, temperature=temperature,
        target_escalation=target_escalation,
    )
    cal = temperature_scale(probs, temperature)
    esc = float(np.mean([in_band(p, band) for p in cal]))
    return {
        "temperature": round(temperature, 6),
        "band": [round(band[0], 6), round(band[1], 6)],
        "dev_escalation_rate": round(esc, 4),
        "dev_auc": auc(probs, labels),
        "dev_nll": round(nll(probs, labels, temperature), 6),
        "n": int(np.asarray(probs).size),
    }
