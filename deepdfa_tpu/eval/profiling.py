"""FLOPs + latency profiling of compiled models.

The TPU-native replacement for the reference's DeepSpeed FlopsProfiler +
torch.cuda.Event harness (DDFA/code_gnn/models/base_module.py:238-323,
profiledata.jsonl/timedata.jsonl, aggregated by scripts/report_profiling.py
into the paper's Table 5):

- FLOPs come from XLA's compiled-HLO cost analysis (exact for the compiled
  program, no module-hook estimation),
- latency from wall-clock around block_until_ready after warmup,
- records append to jsonl files with the same role as the reference's, and
  `aggregate_report` reproduces the GFLOPs / ms-per-example summary.
"""

from __future__ import annotations

import contextlib as _contextlib
import json
import time
from pathlib import Path

import numpy as np


def compiled_cost(
    fn, *args, ledger_tag: str | None = None,
    ledger_signature: str | None = None,
) -> dict:
    """Compile `fn(*args)` and return XLA cost analysis (flops, bytes).

    Thin client of the ONE cost-analysis reader
    (obs/ledger.py:read_cost_analysis — the jax list-vs-dict shim lives
    there now), so Table-5 profiling and the runtime efficiency ledger
    cannot drift. With `ledger_tag` set and the ledger enabled, the
    compile is also booked as a ledger site (flops/bytes/live-bytes +
    this call's compile wall time)."""
    import jax

    from deepdfa_tpu.obs import ledger as obs_ledger

    t0 = time.perf_counter()
    compiled = jax.jit(fn).lower(*args).compile()
    dt = time.perf_counter() - t0
    if ledger_tag is not None:
        obs_ledger.record_compile(
            ledger_tag, ledger_signature or "default", compiled, dt
        )
    return obs_ledger.read_cost_analysis(compiled)


def time_fn(fn, *args, warmup: int = 3, iters: int = 20) -> dict:
    """Steady-state wall-clock stats (seconds) for jitted `fn(*args)`."""
    import jax

    jfn = jax.jit(fn)
    for _ in range(warmup):
        out = jfn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = jfn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    t = np.array(times)
    return {
        "mean_s": float(t.mean()),
        "p50_s": float(np.percentile(t, 50)),
        "p95_s": float(np.percentile(t, 95)),
        "iters": iters,
    }


class ProfileWriter:
    """Append profiling records to a jsonl file (reference: profiledata
    .jsonl / timedata.jsonl)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def write(self, record: dict) -> None:
        with self.path.open("a") as f:
            f.write(json.dumps(record) + "\n")


def profile_model(fn, args, examples_per_call: int, out_path=None) -> dict:
    """One-stop profile: FLOPs + latency, normalized per example."""
    cost = compiled_cost(fn, *args)
    timing = time_fn(fn, *args)
    record = {
        "examples_per_call": examples_per_call,
        "gflops_per_call": cost["flops"] / 1e9,
        "gflops_per_example": cost["flops"] / 1e9 / examples_per_call,
        "ms_per_call": timing["mean_s"] * 1e3,
        "ms_per_example": timing["mean_s"] * 1e3 / examples_per_call,
        "p95_ms_per_call": timing["p95_s"] * 1e3,
        "bytes_accessed": cost["bytes_accessed"],
    }
    if out_path is not None:
        ProfileWriter(out_path).write(record)
    return record


def aggregate_report(jsonl_path: str | Path) -> dict:
    """Aggregate a profile jsonl into the Table-5-style summary."""
    records = [
        json.loads(line)
        for line in Path(jsonl_path).read_text().splitlines()
        if line.strip()
    ]
    if not records:
        return {}
    n = sum(r["examples_per_call"] for r in records)
    return {
        "records": len(records),
        "total_examples": n,
        "total_gflops": sum(r["gflops_per_call"] for r in records),
        "avg_gflops_per_example": float(
            np.mean([r["gflops_per_example"] for r in records])
        ),
        "avg_ms_per_example": float(
            np.mean([r["ms_per_example"] for r in records])
        ),
    }


@_contextlib.contextmanager
def xprof_trace(log_dir: str | Path):
    """jax.profiler trace context: dumps a TensorBoard/xprof-viewable
    device trace (compute + infeed timeline) under `log_dir`.

    The deep-dive complement to time_fn's wall-clock numbers — the
    TPU-native analog of the reference's paired torch.cuda.Event
    instrumentation (base_module.py:246-281): where the reference stamps
    events around each test step, XLA's profiler records every executed
    op on-device; view with TensorBoard's profile plugin."""
    import jax

    Path(log_dir).mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def measure_matmul_ceiling(
    n: int = 4096, chain: int = 8, reps: int = 3, dtype=None
) -> dict:
    """Measured dense-matmul FLOP/s on the CURRENT device — the achievable
    ceiling MFU should be read against.

    Public chip specs (v5e: 197 TFLOP/s bf16) assume exclusive, unthrottled
    access; a shared or tunneled chip delivers a fraction of that, AND the
    fraction moves minute to minute (observed 1.6-7.5 TFLOP/s in adjacent
    windows through the axon tunnel on 2026-07-31 — the chip is
    time-shared). A chained [n,n]@[n,n] product with one host fetch at the
    end is the densest work XLA can schedule, so its rate samples the
    currently-achievable ceiling; treat it as a CONTEMPORANEOUS POINT
    SAMPLE, not a bound — a workload timed in a faster window than the
    probe can legitimately exceed it.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    dtype = dtype or jnp.bfloat16
    a = jnp.ones((n, n), dtype)
    b = jnp.ones((n, n), dtype)
    inv = 1.0 / n

    @jax.jit
    def chained(a, b):
        x = a
        for _ in range(chain):
            x = (x @ b) * inv
        return x

    np.asarray(chained(a, b))  # compile + warmup
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(chained(a, b))
        dt = time.perf_counter() - t0
        best = max(best, chain * 2 * n**3 / dt)
    return _roofline_gauge({
        "matmul_tflops_measured": round(best / 1e12, 2),
        "matmul_probe": f"{chain}x({n}x{n}@{n}x{n}) {jnp.dtype(dtype).name}",
    })


def _roofline_gauge(fields: dict) -> dict:
    """Mirror a probe's scalar ceilings into the obs registry as
    `roofline/<name>` gauges (declared in obs/metrics.py:SCHEMA) so
    obs-enabled runs that measure a ceiling carry it in their run log
    next to the throughput it defends — not only in bench stdout."""
    from deepdfa_tpu.obs import metrics as obs_metrics

    for k, v in fields.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            obs_metrics.REGISTRY.gauge(f"roofline/{k}").set(v)
    return fields


def measure_hbm_bandwidth(
    mb: int = 256, chain: int = 8, reps: int = 3
) -> dict:
    """Measured streaming HBM bandwidth on the CURRENT device (GB/s).

    Same contemporaneous-point-sample caveat as measure_matmul_ceiling:
    the public v5e spec (819 GB/s) assumes exclusive access; the
    tunneled chip delivers a moving fraction. A chained x = x * c + 1
    over a large f32 array is the densest streaming traffic XLA can
    schedule (each link reads + writes the full array, and the data
    dependency serializes links); one host fetch bounds the window.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    n = mb * (1 << 20) // 4  # f32 elements
    x = jnp.ones((n,), jnp.float32)

    @jax.jit
    def chained(x):
        for _ in range(chain):
            x = x * 0.999 + 1.0
        return x

    np.asarray(chained(x)[:8])  # compile + warmup (device-side slice:
    # fetching the full 256 MiB through the tunnel would burn the
    # bounded child's budget before timing starts)
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        y = chained(x)
        np.asarray(y[:8])  # tiny fetch still orders after the full chain
        dt = time.perf_counter() - t0
        best = max(best, chain * 2 * n * 4 / dt)
    return _roofline_gauge({
        "hbm_gbps_measured": round(best / 1e9, 1),
        "hbm_probe": f"{chain}x stream-rw {mb}MiB f32",
    })


def roofline_fields(model_bytes_per_sec: float) -> dict:
    """Bandwidth-side analog of ceiling_fields: run both HBM probes and
    report the model's achieved bytes/s against them (never raises —
    failures land in roofline_error). The GGNN's MFU defense lives
    here: its step is gather/scatter traffic, so its honest ceiling is
    the measured gather bandwidth x arithmetic intensity, not the
    matmul peak (docs/roofline.md)."""
    out: dict = {}
    try:
        out.update(measure_hbm_bandwidth())
        out.update(measure_gather_bandwidth())
        stream = out["hbm_gbps_measured"] * 1e9
        gather = out["gather_gbps_measured"] * 1e9
        if model_bytes_per_sec > 0 and stream > 0:
            out["bytes_vs_stream_ceiling"] = round(
                model_bytes_per_sec / stream, 4)
        if model_bytes_per_sec > 0 and gather > 0:
            out["bytes_vs_gather_ceiling"] = round(
                model_bytes_per_sec / gather, 4)
    except Exception as e:  # noqa: BLE001 — probe must not cost the bench
        out["roofline_error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def measure_gather_bandwidth(
    rows: int = 16384, dim: int = 128, idx_len: int = 65536,
    chain: int = 8, reps: int = 3
) -> dict:
    """Measured gather+segment-sum bandwidth at the GGNN's access shape.

    The GGNN step's byte traffic is NOT streaming: it gathers dim-wide
    rows by edge-source index and segment-sums them by (sorted) edge
    destination — exactly this probe's access pattern, at the flagship
    batch shape by default ([16384, 128] f32 table, 65536 edges). Its
    measured GB/s is the fair roofline ceiling for the message-passing
    bytes; the streaming probe above bounds everything else.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    key = jax.random.key(0)
    table = jnp.ones((rows, dim), jnp.float32)
    src = jax.random.randint(key, (idx_len,), 0, rows, jnp.int32)
    dst = jnp.sort(jax.random.randint(
        jax.random.key(1), (idx_len,), 0, rows, jnp.int32))

    @jax.jit
    def chained(t):
        for _ in range(chain):
            msg = t[src]
            t = jax.ops.segment_sum(
                msg, dst, num_segments=rows, indices_are_sorted=True
            ) * (1.0 / idx_len) + t * 0.5
        return t

    np.asarray(chained(table)[:1])  # device-side slice (see above)
    # bytes per link: gather reads idx_len rows + writes them, segment
    # sum reads them back + writes `rows` rows, plus the residual
    # read/write of the table — the same accounting docs/roofline.md
    # applies to the model step
    link_bytes = (3 * idx_len + 3 * rows) * dim * 4
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        y = chained(table)
        np.asarray(y[:1])
        dt = time.perf_counter() - t0
        best = max(best, chain * link_bytes / dt)
    return _roofline_gauge({
        "gather_gbps_measured": round(best / 1e9, 1),
        "gather_probe": (
            f"{chain}x gather+sorted-segsum [{rows},{dim}]f32 "
            f"idx={idx_len}"
        ),
    })


def ceiling_fields(model_flops_per_sec: float) -> dict:
    """measure_matmul_ceiling + the ratio/caveat fields bench emitters
    attach next to spec-peak MFU (one implementation for bench.py and
    scripts/bench_combined.py; never raises — a probe failure is
    isolated to its own error key)."""
    try:
        out = measure_matmul_ceiling()
        meas = out["matmul_tflops_measured"] * 1e12
        if meas > 0:
            ratio = round(model_flops_per_sec / meas, 6)
            out["mfu_vs_measured_ceiling"] = ratio
            if ratio > 1.0:
                out["ceiling_note"] = (
                    "ratio>1: the probe sampled a slower tunnel window "
                    "than the workload (chip is time-shared); treat the "
                    "ceiling as indicative, not a bound"
                )
        return out
    except Exception as e:
        return {"matmul_ceiling_error": f"{type(e).__name__}: {e}"[:200]}
