"""Static-shape padded graph batches — the TPU replacement for `dgl.batch`.

The reference batches variable-size CFGs dynamically with DGL
(DDFA/sastvd/linevd/datamodule.py GraphDataLoader -> dgl.batch, backed by
DGL's C++/CUDA kernels). XLA wants static shapes, so a batch here is a fixed
budget of graphs/nodes/edges with padding masks:

- `node_graph` maps every node slot to its graph segment; padding slots map
  to segment `num_graphs` (one dummy segment sliced off after pooling) —
  non-decreasing by construction.
- edge arrays are sorted by destination; padded edge slots carry the
  maximum node index (node_budget - 1) with a False mask so `edge_dst`
  stays non-decreasing end to end (segment ops use the
  indices_are_sorted fast path; messages are masked to zero).
- self-loop edges are added for every real node, matching the reference's
  graph construction (DDFA/sastvd/scripts/dbize_graphs.py:25 add_self_loop).

All arrays are numpy on the host and become device arrays when a batch is
put on the mesh; the pytree is jit/pjit-transparent.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import jax
import numpy as np

NUM_SUBKEY_FEATS = 4  # api, datatype, literal, operator


@dataclasses.dataclass(frozen=True)
class GraphSpec:
    """One host-side graph: ragged arrays, pre-batching.

    The optional bit-label block carries reaching-definitions supervision
    for the `dataflow_solution_{in,out}` label styles (reference
    base_module.py:83-95): per-node gen/kill bitvectors plus the exact
    solver's IN/OUT fixpoint, all [n, B] float32 with a corpus-wide B.
    Either all four are present or none.
    """

    graph_id: int
    node_feats: np.ndarray  # [n, NUM_SUBKEY_FEATS] int32 vocab indices
    node_vuln: np.ndarray  # [n] int32 per-statement vulnerability label
    edge_src: np.ndarray  # [e] int32 (CFG edges, no self loops)
    edge_dst: np.ndarray  # [e] int32
    label: float  # graph-level label (max over node_vuln in reference)
    node_gen: np.ndarray | None = None  # [n, B] float32
    node_kill: np.ndarray | None = None  # [n, B]
    node_bits_in: np.ndarray | None = None  # [n, B] solver IN fixpoint
    node_bits_out: np.ndarray | None = None  # [n, B] solver OUT fixpoint
    #: per-edge relation ids for n_etypes > 1 message passing (the role of
    #: DGL GatedGraphConv's `etypes` argument); None = single-type graph
    edge_type: np.ndarray | None = None  # [e] int32 in [0, n_etypes)

    @property
    def num_nodes(self) -> int:
        return int(self.node_feats.shape[0])

    @property
    def num_edges(self) -> int:
        return int(self.edge_src.shape[0])


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GraphBatch:
    """Fixed-budget batched graphs (padded; device-ready pytree).

    Invariant (maintained by `pack`, REQUIRED by consumers): `edge_dst` is
    non-decreasing, with padded slots carrying the maximum node index —
    message passing uses the indices_are_sorted segment fast path."""

    node_feats: jax.Array  # [N, K] int32
    node_vuln: jax.Array  # [N] int32
    node_graph: jax.Array  # [N] int32 segment ids; padding -> num_graphs
    node_mask: jax.Array  # [N] bool
    edge_src: jax.Array  # [E] int32
    edge_dst: jax.Array  # [E] int32
    edge_mask: jax.Array  # [E] bool
    graph_label: jax.Array  # [G] float32
    graph_mask: jax.Array  # [G] bool
    graph_ids: jax.Array  # [G] int32 original example ids (-1 padding)
    num_graphs: int = dataclasses.field(metadata=dict(static=True))
    # optional bit-label block ([N, B] each, or all None) for the
    # dataflow_solution_{in,out} label styles
    node_gen: jax.Array | None = None
    node_kill: jax.Array | None = None
    node_bits_in: jax.Array | None = None
    node_bits_out: jax.Array | None = None
    # optional per-edge relation ids (padding/self-loop slots carry 0)
    edge_type: jax.Array | None = None

    @property
    def node_budget(self) -> int:
        return self.node_feats.shape[0]

    @property
    def edge_budget(self) -> int:
        return self.edge_src.shape[0]


#: GraphBatch's array leaves (everything but the static num_graphs) — the
#: serialization order shared by the packed-batch cache and the
#: shared-memory packer (data/packed_cache.py, data/mp_pack.py)
ARRAY_FIELDS = tuple(
    f.name for f in dataclasses.fields(GraphBatch) if f.name != "num_graphs"
)


class BudgetExceeded(ValueError):
    pass


_BIT_FIELDS = ("node_gen", "node_kill", "node_bits_in", "node_bits_out")


def bit_width(graphs: Sequence[GraphSpec]) -> int | None:
    """Corpus-wide bit-label width B, or None when graphs carry no bits.

    Raises ValueError on mixed presence or inconsistent widths — a batch
    must be homogeneous for static shapes.
    """
    widths = set()
    for g in graphs:
        present = [getattr(g, f) is not None for f in _BIT_FIELDS]
        if any(present) != all(present):
            raise ValueError(f"graph {g.graph_id}: partial bit-label block")
        widths.add(g.node_gen.shape[1] if g.node_gen is not None else None)
    if not widths or widths == {None}:
        return None
    if None in widths or len(widths) > 1:
        raise ValueError(f"inconsistent bit-label widths: {widths}")
    return widths.pop()


def edge_typed(graphs: Sequence[GraphSpec]) -> bool:
    """Whether the graphs carry per-edge type ids; raises on a mix (a batch
    must be homogeneous for static pytree structure)."""
    present = {g.edge_type is not None for g in graphs}
    if present == {True, False}:
        raise ValueError("mixed edge_type presence across graphs")
    return present == {True}


def pack(
    graphs: Sequence[GraphSpec],
    num_graphs: int,
    node_budget: int,
    edge_budget: int,
    add_self_loops: bool = True,
    bits: int | None = None,
    etypes: bool | None = None,
    feat_width: int | None = None,
) -> GraphBatch:
    """Pack host graphs into one padded batch (numpy arrays).

    Raises BudgetExceeded when the graphs do not fit; callers either bucket
    by size or drop oversized examples before packing. `bits` forces the
    bit-label width (so empty shards match sibling shards); by default it
    is inferred from the graphs. `etypes` likewise forces presence of the
    per-edge type array; self-loop and padding slots carry type 0 (the
    reference's dbize_graphs adds untyped self-loops the same way).
    """
    if len(graphs) > num_graphs:
        raise BudgetExceeded(f"{len(graphs)} graphs > budget {num_graphs}")
    n_tot = sum(g.num_nodes for g in graphs)
    e_tot = sum(g.num_edges for g in graphs) + (n_tot if add_self_loops else 0)
    if n_tot > node_budget:
        raise BudgetExceeded(f"{n_tot} nodes > budget {node_budget}")
    if e_tot > edge_budget:
        raise BudgetExceeded(f"{e_tot} edges > budget {edge_budget}")

    if bits is None:
        bits = bit_width(graphs)
    elif graphs and bit_width(graphs) not in (None, bits):
        raise ValueError(
            f"bits={bits} does not match graphs' width {bit_width(graphs)}"
        )
    if etypes is None:
        etypes = edge_typed(graphs) if graphs else False
    elif graphs and edge_typed(graphs) != etypes:
        raise ValueError(
            f"etypes={etypes} does not match graphs' edge_type presence"
        )
    bit_arrays = (
        {f: np.zeros((node_budget, bits), np.float32) for f in _BIT_FIELDS}
        if bits is not None
        else {f: None for f in _BIT_FIELDS}
    )
    # feature width follows the specs (struct_feats extraction appends
    # fixed-vocab structural columns after the 4 subkey columns); the
    # explicit `feat_width` override exists so an EMPTY shard can match
    # its non-empty siblings (same pattern as `bits`/`etypes` above)
    if feat_width is None:
        feat_width = (
            graphs[0].node_feats.shape[1] if graphs else NUM_SUBKEY_FEATS
        )
    elif graphs and graphs[0].node_feats.shape[1] != feat_width:
        raise ValueError(
            f"feat_width={feat_width} does not match graphs' width "
            f"{graphs[0].node_feats.shape[1]}"
        )
    node_feats = np.zeros((node_budget, feat_width), np.int32)
    node_vuln = np.zeros((node_budget,), np.int32)
    node_graph = np.full((node_budget,), num_graphs, np.int32)
    node_mask = np.zeros((node_budget,), bool)
    edge_src = np.zeros((edge_budget,), np.int32)
    edge_dst = np.zeros((edge_budget,), np.int32)
    edge_mask = np.zeros((edge_budget,), bool)
    edge_type = np.zeros((edge_budget,), np.int32) if etypes else None
    graph_label = np.zeros((num_graphs,), np.float32)
    graph_mask = np.zeros((num_graphs,), bool)
    graph_ids = np.full((num_graphs,), -1, np.int32)

    n_off = 0
    e_off = 0
    for gi, g in enumerate(graphs):
        n, e = g.num_nodes, g.num_edges
        node_feats[n_off : n_off + n] = g.node_feats
        node_vuln[n_off : n_off + n] = g.node_vuln
        node_graph[n_off : n_off + n] = gi
        node_mask[n_off : n_off + n] = True
        if bits is not None and g.node_gen is not None:
            for f in _BIT_FIELDS:
                bit_arrays[f][n_off : n_off + n] = getattr(g, f)
        # graph edges + self loops, sorted by destination: graphs occupy
        # increasing node ranges, so per-graph sorting makes the whole
        # batch dst-sorted and segment reductions can use the
        # indices_are_sorted fast path
        g_src = g.edge_src + n_off
        g_dst = g.edge_dst + n_off
        g_type = (
            g.edge_type
            if g.edge_type is not None
            else np.zeros((e,), np.int32)
        )
        if add_self_loops:
            loop = np.arange(n_off, n_off + n, dtype=np.int32)
            g_src = np.concatenate([g_src, loop])
            g_dst = np.concatenate([g_dst, loop])
            g_type = np.concatenate([g_type, np.zeros((n,), np.int32)])
        order = np.argsort(g_dst, kind="stable")
        ne = len(order)
        edge_src[e_off : e_off + ne] = g_src[order]
        edge_dst[e_off : e_off + ne] = g_dst[order]
        edge_mask[e_off : e_off + ne] = True
        if edge_type is not None:
            edge_type[e_off : e_off + ne] = g_type[order]
        e_off += ne
        graph_label[gi] = g.label
        graph_mask[gi] = True
        graph_ids[gi] = g.graph_id
        n_off += n
    # padded edge slots carry the largest segment id so dst stays sorted
    edge_src[e_off:] = max(node_budget - 1, 0)
    edge_dst[e_off:] = max(node_budget - 1, 0)

    return GraphBatch(
        node_feats=node_feats,
        node_vuln=node_vuln,
        node_graph=node_graph,
        node_mask=node_mask,
        edge_src=edge_src,
        edge_dst=edge_dst,
        edge_mask=edge_mask,
        graph_label=graph_label,
        graph_mask=graph_mask,
        graph_ids=graph_ids,
        num_graphs=num_graphs,
        edge_type=edge_type,
        **bit_arrays,
    )


def _stack_shards(
    per_shard: Sequence[Sequence[GraphSpec]],
    num_graphs: int,
    node_budget: int,
    edge_budget: int,
    add_self_loops: bool = True,
) -> GraphBatch:
    # bit width / etype presence decided over ALL shards so empty shards
    # still produce matching zero arrays (a pytree-structure mismatch
    # would break stack)
    flat = [g for sg in per_shard for g in sg]
    bits = bit_width(flat)
    etypes = edge_typed(flat) if flat else False
    feat_width = flat[0].node_feats.shape[1] if flat else None
    shards = [
        pack(
            sg, num_graphs, node_budget, edge_budget, add_self_loops, bits,
            etypes, feat_width,
        )
        for sg in per_shard
    ]
    stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *shards)
    return dataclasses.replace(stacked, num_graphs=num_graphs)


def pack_shards(
    graphs: Sequence[GraphSpec],
    num_shards: int,
    num_graphs: int,
    node_budget: int,
    edge_budget: int,
    add_self_loops: bool = True,
) -> GraphBatch:
    """Pack into `num_shards` equal static-shape shards, stacked on axis 0.

    The leading axis is the data-parallel axis: shard i holds whole graphs,
    so segment reductions never cross shard boundaries and XLA only inserts
    collectives for the gradient all-reduce. Graphs are dealt round-robin by
    descending node count (greedy balance).
    """
    per_shard: list[list[GraphSpec]] = [[] for _ in range(num_shards)]
    loads = np.zeros(num_shards, np.int64)
    counts = np.zeros(num_shards, np.int64)
    for g in sorted(graphs, key=lambda g: -g.num_nodes):
        order = np.argsort(loads, kind="stable")
        placed = False
        for s in order:
            if counts[s] < num_graphs:
                per_shard[int(s)].append(g)
                loads[int(s)] += g.num_nodes
                counts[int(s)] += 1
                placed = True
                break
        if not placed:
            raise BudgetExceeded(
                f"{len(graphs)} graphs > {num_shards} shards x {num_graphs}"
            )
    return _stack_shards(
        per_shard, num_graphs, node_budget, edge_budget, add_self_loops
    )


def _pow2_ceil(x: int) -> int:
    return 1 << max(0, int(x) - 1).bit_length() if x > 1 else 1


@dataclasses.dataclass(frozen=True)
class BatchPlan:
    """Packing recipe for one batch: per-shard indices into the source
    graph sequence plus the static budgets.

    Planning (this object's construction) is pure bookkeeping over
    node/edge counts — cheap and inherently sequential. Packing (turning a
    plan into padded numpy arrays) is the host-side hot loop and is
    embarrassingly parallel across plans; `pack_plan` below is the single
    packing entry point shared by the inline batcher, the process-pool
    packer (data/mp_pack.py) and the packed-batch cache builder
    (data/packed_cache.py), so every path is bit-identical by construction.
    """

    shard_indices: tuple[tuple[int, ...], ...]
    num_graphs: int
    node_budget: int
    edge_budget: int


def pack_plan(
    graphs: Sequence[GraphSpec],
    plan: BatchPlan,
    add_self_loops: bool = True,
) -> GraphBatch:
    """Materialize one planned batch (the numpy-heavy packing step)."""
    per_shard = [[graphs[i] for i in idxs] for idxs in plan.shard_indices]
    return _stack_shards(
        per_shard, plan.num_graphs, plan.node_budget, plan.edge_budget,
        add_self_loops,
    )


def plan_shard_bucket_batches(
    graphs: Sequence[GraphSpec],
    num_shards: int,
    num_graphs: int,
    node_budget: int,
    edge_budget: int,
    add_self_loops: bool = True,
    oversized: str = "drop",
    stats: dict | None = None,
) -> Iterable[BatchPlan]:
    """Greedy budget-aware planning of dp-sharded fixed-budget batches.

    Yields `BatchPlan`s; `shard_bucket_batches` packs them inline and
    documents the placement/oversized semantics. Stats keys ("batches",
    "dropped", "oversized", "overflow_signatures") fill as the generator
    advances and are final once it is exhausted.
    """
    if oversized not in ("drop", "raise", "singleton"):
        raise ValueError(f"oversized={oversized!r}")
    if stats is None:
        stats = {}
    stats.update(batches=0, dropped=0, oversized=0, overflow_signatures=0)

    overflow: dict[tuple[int, int], list[int]] = {}
    per_shard: list[list[int]] = [[] for _ in range(num_shards)]
    counts = np.zeros(num_shards, np.int64)
    n_used = np.zeros(num_shards, np.int64)
    e_used = np.zeros(num_shards, np.int64)

    def flush():
        nonlocal per_shard, counts, n_used, e_used
        if counts.sum():
            stats["batches"] += 1
            plan = BatchPlan(
                tuple(tuple(s) for s in per_shard),
                num_graphs, node_budget, edge_budget,
            )
            per_shard = [[] for _ in range(num_shards)]
            counts = np.zeros(num_shards, np.int64)
            n_used = np.zeros(num_shards, np.int64)
            e_used = np.zeros(num_shards, np.int64)
            return plan
        return None

    for gi, g in enumerate(graphs):
        e_need = g.num_edges + (g.num_nodes if add_self_loops else 0)
        if g.num_nodes > node_budget or e_need > edge_budget:
            stats["oversized"] += 1
            if oversized == "raise":
                raise BudgetExceeded(
                    f"graph {g.graph_id}: {g.num_nodes} nodes / {e_need} "
                    f"edges exceed budgets ({node_budget}/{edge_budget})"
                )
            if oversized == "drop":
                stats["dropped"] += 1
                continue
            sig = (_pow2_ceil(g.num_nodes), _pow2_ceil(e_need))
            overflow.setdefault(sig, []).append(gi)
            continue
        # least-loaded shard (by nodes) with room in every budget
        order = np.argsort(n_used, kind="stable")
        placed = False
        for s in order:
            s = int(s)
            if (
                counts[s] < num_graphs
                and n_used[s] + g.num_nodes <= node_budget
                and e_used[s] + e_need <= edge_budget
            ):
                per_shard[s].append(gi)
                counts[s] += 1
                n_used[s] += g.num_nodes
                e_used[s] += e_need
                placed = True
                break
        if not placed:
            plan = flush()
            if plan is not None:
                yield plan
            per_shard[0].append(gi)
            counts[0] += 1
            n_used[0] += g.num_nodes
            e_used[0] += e_need
    plan = flush()
    if plan is not None:
        yield plan

    stats["overflow_signatures"] = len(overflow)
    for (nb, eb), gis in sorted(overflow.items()):
        for k in range(0, len(gis), num_shards):
            stats["batches"] += 1
            yield BatchPlan(
                tuple(
                    tuple(gis[k + s : k + s + 1])
                    for s in range(num_shards)
                ),
                1, nb, eb,
            )


def shard_bucket_batches(
    graphs: Iterable[GraphSpec],
    num_shards: int,
    num_graphs: int,
    node_budget: int,
    edge_budget: int,
    add_self_loops: bool = True,
    oversized: str = "drop",
    stats: dict | None = None,
) -> Iterable[GraphBatch]:
    """Greedy budget-aware packing into dp-sharded fixed-budget batches.

    Unlike count-only chunking + pack_shards, a new batch starts whenever
    the incoming graph fits no shard of the current one — heavy-tail
    corpora never raise BudgetExceeded mid-stream.

    `oversized` controls graphs exceeding the per-shard budgets outright:
    - "drop": skip them — training semantics; count reported via `stats`
      (reference analog: the reference tolerates skipping only in training,
      DDFA/sastvd/linevd/datamodule.py evaluates every graph by shrinking
      test batches to 16).
    - "raise": BudgetExceeded.
    - "singleton": emit dedicated trailing batches whose budgets are the
      graph's needs rounded up to powers of two — eval semantics: EVERY
      example is scored, and pow2 rounding bounds the extra XLA
      compilations to O(log max_size) signatures. Graphs sharing a rounded
      signature ride the dp axis together (one per shard).

    `stats` (optional dict) receives: "batches", "dropped" (only under
    "drop"), "oversized", "overflow_signatures".

    Implementation: `plan_shard_bucket_batches` (sequential bookkeeping)
    + `pack_plan` (numpy packing) — the same two stages the multiprocess
    packer (data/mp_pack.py) distributes across cores.
    """
    graphs = graphs if isinstance(graphs, Sequence) else list(graphs)
    for plan in plan_shard_bucket_batches(
        graphs, num_shards, num_graphs, node_budget, edge_budget,
        add_self_loops, oversized, stats,
    ):
        yield pack_plan(graphs, plan, add_self_loops)


def bucket_batches(
    graphs: Iterable[GraphSpec],
    num_graphs: int,
    node_budget: int,
    edge_budget: int,
    drop_oversized: bool = True,
    add_self_loops: bool = True,
    stats: dict | None = None,
) -> Iterable[GraphBatch]:
    """Greedy first-fit packing of a graph stream into fixed-budget batches.

    One (num_graphs, node_budget, edge_budget) signature means one XLA
    compilation for the whole stream. Dropping is training-only semantics;
    eval paths use `shard_bucket_batches(..., oversized="singleton")` so
    every example is scored. `stats` receives the "dropped" count.
    """
    if stats is None:
        stats = {}
    stats.setdefault("dropped", 0)
    cur: list[GraphSpec] = []
    n_used = 0
    e_used = 0
    for g in graphs:
        e_need = g.num_edges + (g.num_nodes if add_self_loops else 0)
        if g.num_nodes > node_budget or e_need > edge_budget:
            if drop_oversized:
                stats["dropped"] += 1
                continue
            raise BudgetExceeded(
                f"graph {g.graph_id}: {g.num_nodes} nodes / {e_need} edges "
                f"exceed budgets ({node_budget}/{edge_budget})"
            )
        if (
            len(cur) == num_graphs
            or n_used + g.num_nodes > node_budget
            or e_used + e_need > edge_budget
        ):
            yield pack(cur, num_graphs, node_budget, edge_budget, add_self_loops)
            cur, n_used, e_used = [], 0, 0
        cur.append(g)
        n_used += g.num_nodes
        e_used += e_need
    if cur:
        yield pack(cur, num_graphs, node_budget, edge_budget, add_self_loops)
