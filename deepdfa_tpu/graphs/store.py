"""On-disk graph storage: flat npz shards replacing DGL's `graphs.bin`.

The reference serializes every CFG into one DGL binary file
(DDFA/sastvd/scripts/dbize_graphs.py:20-33, loaded via
DDFA/sastvd/linevd/graphmogrifier.py:51-56). Here each dataset split is a
set of npz shards holding ragged graphs in concatenated form with offset
tables — memory-mappable, language-neutral, trivially shardable across
preprocessing workers.
"""

from __future__ import annotations

import hashlib
import zipfile
from pathlib import Path
from typing import Iterator, Sequence

import numpy as np

from deepdfa_tpu.graphs.batch import (
    _BIT_FIELDS,
    NUM_SUBKEY_FEATS,
    GraphSpec,
    bit_width,
    edge_typed,
)

_VERSION = 1


def save_shard(
    path: str | Path, graphs: Sequence[GraphSpec], compressed: bool = True
) -> None:
    """Write one shard. `compressed=False` stores the npz members raw
    (zip STORED), which makes the shard memory-mappable via
    `load_shard(..., mmap=True)` — larger on disk, but loads become
    page-cache-speed views instead of per-epoch inflate+copy."""
    node_counts = np.array([g.num_nodes for g in graphs], np.int64)
    edge_counts = np.array([g.num_edges for g in graphs], np.int64)
    bits = bit_width(graphs)
    bit_arrays = {}
    if bits is not None:
        for f in _BIT_FIELDS:
            bit_arrays[f] = np.concatenate(
                [getattr(g, f) for g in graphs]
            ).astype(np.float32)
    if graphs and edge_typed(graphs):
        bit_arrays["edge_type"] = np.concatenate(
            [g.edge_type for g in graphs]
        ).astype(np.int32)
    (np.savez_compressed if compressed else np.savez)(
        path,
        version=np.int64(_VERSION),
        **bit_arrays,
        graph_ids=np.array([g.graph_id for g in graphs], np.int64),
        labels=np.array([g.label for g in graphs], np.float32),
        node_offsets=np.concatenate([[0], np.cumsum(node_counts)]),
        edge_offsets=np.concatenate([[0], np.cumsum(edge_counts)]),
        node_feats=(
            np.concatenate([g.node_feats for g in graphs])
            if graphs
            else np.zeros((0, NUM_SUBKEY_FEATS), np.int32)
        ),
        node_vuln=(
            np.concatenate([g.node_vuln for g in graphs])
            if graphs
            else np.zeros((0,), np.int32)
        ),
        edge_src=(
            np.concatenate([g.edge_src for g in graphs])
            if graphs
            else np.zeros((0,), np.int32)
        ),
        edge_dst=(
            np.concatenate([g.edge_dst for g in graphs])
            if graphs
            else np.zeros((0,), np.int32)
        ),
    )


def _mmap_npz(path: str | Path) -> dict[str, np.ndarray]:
    """Memory-map every member of an UNCOMPRESSED .npz.

    np.load silently ignores mmap_mode for zip archives, so this resolves
    each stored member's absolute data offset (zip local header + npy
    header) and hands it to np.memmap — the OS page cache then backs every
    epoch's reads instead of a per-epoch inflate+copy. Raises ValueError on
    deflated members (shards written with compressed=True)."""
    out: dict[str, np.ndarray] = {}
    with zipfile.ZipFile(path) as zf:
        for info in zf.infolist():
            name = info.filename
            key = name[:-4] if name.endswith(".npy") else name
            if info.compress_type != zipfile.ZIP_STORED:
                raise ValueError(
                    f"{path}: member {name} is deflated — mmap needs a "
                    "shard written with save_shard(compressed=False)"
                )
            with zf.open(info) as fp:
                version = np.lib.format.read_magic(fp)
                if version == (1, 0):
                    shape, fortran, dtype = (
                        np.lib.format.read_array_header_1_0(fp)
                    )
                else:
                    shape, fortran, dtype = (
                        np.lib.format.read_array_header_2_0(fp)
                    )
                header_len = fp.tell()
            if int(np.prod(shape)) == 0 or shape == ():
                # np.memmap rejects zero-length maps; scalars aren't worth
                # a page each — read those members eagerly
                with zf.open(info) as fp:
                    out[key] = np.lib.format.read_array(fp)
                continue
            with open(path, "rb") as f:
                # zip local file header: 30 fixed bytes + name + extra
                # (the central directory's lengths can differ, so read
                # the local copy)
                f.seek(info.header_offset)
                local = f.read(30)
                name_len = int.from_bytes(local[26:28], "little")
                extra_len = int.from_bytes(local[28:30], "little")
            data_start = info.header_offset + 30 + name_len + extra_len
            out[key] = np.memmap(
                path,
                dtype=dtype,
                mode="r",
                offset=data_start + header_len,
                shape=shape,
                order="F" if fortran else "C",
            )
    return out


def load_shard(path: str | Path, mmap: bool = False) -> list[GraphSpec]:
    """Load one shard. `mmap=True` (uncompressed shards only) returns
    GraphSpecs whose arrays are read-only views into the page-cache-backed
    file mapping — zero-copy until a consumer writes or re-dtypes."""
    if mmap:
        return _specs_from_arrays(_mmap_npz(path), path)
    with np.load(path) as z:
        return _specs_from_arrays({k: z[k] for k in z.files}, path)


def _specs_from_arrays(z: dict[str, np.ndarray], path) -> list[GraphSpec]:
    if int(z["version"]) != _VERSION:
        raise ValueError(f"unsupported shard version {z['version']} at {path}")
    no, eo = z["node_offsets"], z["edge_offsets"]
    has_bits = _BIT_FIELDS[0] in z
    has_etypes = "edge_type" in z

    def _as(a: np.ndarray, dtype) -> np.ndarray:
        # no-copy when the stored dtype already matches (the save path
        # writes int32/float32 natively, so mmap views stay views)
        return np.asarray(a, dtype)

    out = []
    for i in range(len(z["graph_ids"])):
        bit_kw = (
            {f: _as(z[f][no[i] : no[i + 1]], np.float32) for f in _BIT_FIELDS}
            if has_bits
            else {}
        )
        if has_etypes:
            bit_kw["edge_type"] = _as(
                z["edge_type"][eo[i] : eo[i + 1]], np.int32
            )
        out.append(
            GraphSpec(
                graph_id=int(z["graph_ids"][i]),
                node_feats=_as(z["node_feats"][no[i] : no[i + 1]], np.int32),
                node_vuln=_as(z["node_vuln"][no[i] : no[i + 1]], np.int32),
                edge_src=_as(z["edge_src"][eo[i] : eo[i + 1]], np.int32),
                edge_dst=_as(z["edge_dst"][eo[i] : eo[i + 1]], np.int32),
                label=float(z["labels"][i]),
                **bit_kw,
            )
        )
    return out


def file_digest(path: str | Path, chunk: int = 1 << 20) -> str:
    """sha256 of a file's bytes (packed-cache key component)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


class GraphStore:
    """A directory of npz shards addressable by graph_id."""

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)

    def shard_paths(self) -> list[Path]:
        return sorted(self.directory.glob("graphs-*.npz"))

    def write(
        self,
        graphs: Sequence[GraphSpec],
        shard_size: int = 4096,
        tag: str | None = None,
        compressed: bool = True,
    ) -> int:
        """Write npz shards. Concurrent writer jobs MUST pass distinct
        `tag`s (e.g. the job-array shard id): untagged numbering counts
        existing files at start time and would collide across processes.
        `compressed=False` writes mmap-able shards (see save_shard)."""
        prefix = f"graphs-{tag}-" if tag else "graphs-"
        existing = len(list(self.directory.glob(f"{prefix}*.npz")))
        n = 0
        for i in range(0, len(graphs), shard_size):
            save_shard(
                self.directory / f"{prefix}{existing + n:05d}.npz",
                graphs[i : i + shard_size],
                compressed=compressed,
            )
            n += 1
        return n

    def iter_graphs(self, mmap: bool = False) -> Iterator[GraphSpec]:
        for p in self.shard_paths():
            yield from load_shard(p, mmap=mmap)

    def load_all(self, mmap: bool = False) -> dict[int, GraphSpec]:
        return {g.graph_id: g for g in self.iter_graphs(mmap=mmap)}

    def digest(self) -> str:
        """Content hash over every shard (name + bytes) — the packed-batch
        cache's source-invalidation key (data/packed_cache.py): any
        re-extraction or added shard changes it."""
        h = hashlib.sha256()
        for p in self.shard_paths():
            h.update(p.name.encode())
            h.update(file_digest(p).encode())
        return h.hexdigest()
