"""On-disk graph storage: flat npz shards replacing DGL's `graphs.bin`.

The reference serializes every CFG into one DGL binary file
(DDFA/sastvd/scripts/dbize_graphs.py:20-33, loaded via
DDFA/sastvd/linevd/graphmogrifier.py:51-56). Here each dataset split is a
set of npz shards holding ragged graphs in concatenated form with offset
tables — memory-mappable, language-neutral, trivially shardable across
preprocessing workers.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator, Sequence

import numpy as np

from deepdfa_tpu.graphs.batch import (
    _BIT_FIELDS,
    NUM_SUBKEY_FEATS,
    GraphSpec,
    bit_width,
    edge_typed,
)

_VERSION = 1


def save_shard(path: str | Path, graphs: Sequence[GraphSpec]) -> None:
    node_counts = np.array([g.num_nodes for g in graphs], np.int64)
    edge_counts = np.array([g.num_edges for g in graphs], np.int64)
    bits = bit_width(graphs)
    bit_arrays = {}
    if bits is not None:
        for f in _BIT_FIELDS:
            bit_arrays[f] = np.concatenate(
                [getattr(g, f) for g in graphs]
            ).astype(np.float32)
    if graphs and edge_typed(graphs):
        bit_arrays["edge_type"] = np.concatenate(
            [g.edge_type for g in graphs]
        ).astype(np.int32)
    np.savez_compressed(
        path,
        version=np.int64(_VERSION),
        **bit_arrays,
        graph_ids=np.array([g.graph_id for g in graphs], np.int64),
        labels=np.array([g.label for g in graphs], np.float32),
        node_offsets=np.concatenate([[0], np.cumsum(node_counts)]),
        edge_offsets=np.concatenate([[0], np.cumsum(edge_counts)]),
        node_feats=(
            np.concatenate([g.node_feats for g in graphs])
            if graphs
            else np.zeros((0, NUM_SUBKEY_FEATS), np.int32)
        ),
        node_vuln=(
            np.concatenate([g.node_vuln for g in graphs])
            if graphs
            else np.zeros((0,), np.int32)
        ),
        edge_src=(
            np.concatenate([g.edge_src for g in graphs])
            if graphs
            else np.zeros((0,), np.int32)
        ),
        edge_dst=(
            np.concatenate([g.edge_dst for g in graphs])
            if graphs
            else np.zeros((0,), np.int32)
        ),
    )


def load_shard(path: str | Path) -> list[GraphSpec]:
    with np.load(path) as z:
        if int(z["version"]) != _VERSION:
            raise ValueError(f"unsupported shard version {z['version']} at {path}")
        no, eo = z["node_offsets"], z["edge_offsets"]
        has_bits = _BIT_FIELDS[0] in z
        has_etypes = "edge_type" in z
        out = []
        for i in range(len(z["graph_ids"])):
            bit_kw = (
                {
                    f: z[f][no[i] : no[i + 1]].astype(np.float32)
                    for f in _BIT_FIELDS
                }
                if has_bits
                else {}
            )
            if has_etypes:
                bit_kw["edge_type"] = z["edge_type"][eo[i] : eo[i + 1]].astype(
                    np.int32
                )
            out.append(
                GraphSpec(
                    graph_id=int(z["graph_ids"][i]),
                    node_feats=z["node_feats"][no[i] : no[i + 1]].astype(np.int32),
                    node_vuln=z["node_vuln"][no[i] : no[i + 1]].astype(np.int32),
                    edge_src=z["edge_src"][eo[i] : eo[i + 1]].astype(np.int32),
                    edge_dst=z["edge_dst"][eo[i] : eo[i + 1]].astype(np.int32),
                    label=float(z["labels"][i]),
                    **bit_kw,
                )
            )
        return out


class GraphStore:
    """A directory of npz shards addressable by graph_id."""

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)

    def shard_paths(self) -> list[Path]:
        return sorted(self.directory.glob("graphs-*.npz"))

    def write(
        self,
        graphs: Sequence[GraphSpec],
        shard_size: int = 4096,
        tag: str | None = None,
    ) -> int:
        """Write npz shards. Concurrent writer jobs MUST pass distinct
        `tag`s (e.g. the job-array shard id): untagged numbering counts
        existing files at start time and would collide across processes."""
        prefix = f"graphs-{tag}-" if tag else "graphs-"
        existing = len(list(self.directory.glob(f"{prefix}*.npz")))
        n = 0
        for i in range(0, len(graphs), shard_size):
            save_shard(
                self.directory / f"{prefix}{existing + n:05d}.npz",
                graphs[i : i + shard_size],
            )
            n += 1
        return n

    def iter_graphs(self) -> Iterator[GraphSpec]:
        for p in self.shard_paths():
            yield from load_shard(p)

    def load_all(self) -> dict[int, GraphSpec]:
        return {g.graph_id: g for g in self.iter_graphs()}
