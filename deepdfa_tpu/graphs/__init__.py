from deepdfa_tpu.graphs.batch import (
    NUM_SUBKEY_FEATS,
    BudgetExceeded,
    GraphBatch,
    GraphSpec,
    bucket_batches,
    pack,
    pack_shards,
    shard_bucket_batches,
)
from deepdfa_tpu.graphs.store import GraphStore, load_shard, save_shard

__all__ = [
    "NUM_SUBKEY_FEATS",
    "BudgetExceeded",
    "GraphBatch",
    "GraphSpec",
    "bucket_batches",
    "pack",
    "pack_shards",
    "shard_bucket_batches",
    "GraphStore",
    "load_shard",
    "save_shard",
]
