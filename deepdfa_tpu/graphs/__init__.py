from deepdfa_tpu.graphs.batch import (
    NUM_SUBKEY_FEATS,
    BatchPlan,
    BudgetExceeded,
    GraphBatch,
    GraphSpec,
    bucket_batches,
    pack,
    pack_plan,
    pack_shards,
    plan_shard_bucket_batches,
    shard_bucket_batches,
)
from deepdfa_tpu.graphs.store import (
    GraphStore,
    file_digest,
    load_shard,
    save_shard,
)

__all__ = [
    "NUM_SUBKEY_FEATS",
    "BatchPlan",
    "BudgetExceeded",
    "GraphBatch",
    "GraphSpec",
    "bucket_batches",
    "pack",
    "pack_plan",
    "pack_shards",
    "plan_shard_bucket_batches",
    "shard_bucket_batches",
    "GraphStore",
    "file_digest",
    "load_shard",
    "save_shard",
]
