"""Graph-dimension sharding: edge-sharded GGNN message passing.

The long-context analog for GRAPHS (SURVEY §2.5b): where sequence
parallelism shards a transformer's token axis, this shards a graph
batch's EDGE axis over a mesh axis — for mega-batches (or single huge
CFGs) whose edge arrays exceed one chip. Node states replicate; each
device gathers/scatters only its contiguous edge slice and one `psum`
per propagation step makes the aggregate exact (nn/gnn.py
GatedGraphConv.axis_name). Contiguous slices of the batcher's dst-sorted
edge list stay sorted, so the indices_are_sorted segment fast path holds
per shard.

The reference has no counterpart (DGL batches whole graphs on one GPU,
dropping test batch size to fit — datamodule.py:135-141); this is
TPU-first headroom in the same sense as ring attention.

Cost model: shards the O(E·D) edge work and edge storage; the O(N·D)
node transform and GRU stay replicated. Wins when E >> N (dense CFG
mega-batches); for ordinary batches prefer dp over whole graphs.
"""

from __future__ import annotations

import dataclasses

import jax
from jax.sharding import PartitionSpec as P

from deepdfa_tpu.graphs.batch import GraphBatch
from deepdfa_tpu.parallel.compat import shard_map

#: the GraphBatch fields carried per edge
EDGE_FIELDS = ("edge_src", "edge_dst", "edge_mask", "edge_type")


def edge_batch_specs(batch: GraphBatch, axis: str = "dp") -> GraphBatch:
    """A GraphBatch-shaped pytree of PartitionSpecs: edge arrays shard
    their leading axis over `axis`, everything else replicates."""
    fields = {}
    for f in dataclasses.fields(GraphBatch):
        if f.name == "num_graphs":
            continue
        v = getattr(batch, f.name)
        if v is None:
            fields[f.name] = None
        elif f.name in EDGE_FIELDS:
            fields[f.name] = P(axis)
        else:
            fields[f.name] = P()
    return GraphBatch(**fields, num_graphs=batch.num_graphs)


def edge_sharded_apply(
    model, params, batch: GraphBatch, mesh, axis: str = "dp"
):
    """Run `model.apply(params, batch)` with message passing edge-sharded
    over `axis`. Numerically equal to the unsharded apply (same params —
    the axis knob adds no parameters); the axis size must divide the
    edge budget. Both propagation paths are axis-aware: the GGNN
    aggregates with a per-step psum (nn/gnn.py), the bitvector
    reaching-definitions fixpoint with a cross-shard union fold
    (nn/bitprop.py — union is the monoid there, not addition).
    """
    n_shards = mesh.shape[axis]
    if batch.edge_src.shape[0] % n_shards:
        raise ValueError(
            f"edge budget {batch.edge_src.shape[0]} not divisible by "
            f"{n_shards} shards on axis {axis!r}"
        )
    sharded_model = model.clone(edge_axis=axis)

    def body(p, local: GraphBatch):
        return sharded_model.apply(p, local)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), edge_batch_specs(batch, axis)),
        out_specs=P(),
        check_vma=False,
    )(params, batch)
