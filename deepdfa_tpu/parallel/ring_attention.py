"""Ring attention: exact sequence-parallel attention over the `sp` mesh axis.

The reference caps sequences at 512 tokens and has no sequence parallelism
(SURVEY.md §5.7); this framework makes long-context first-class. Queries
stay resident per device; key/value blocks rotate around the ring via
`ppermute` over ICI while a numerically-stable blockwise softmax
accumulates output (the log-sum-exp streaming trick), so attention over a
sequence of length S sharded across P devices needs O(S/P) memory per chip
and never materializes the full S x S score matrix.

Works inside `shard_map` with the sequence axis sharded on `sp`. With
sp=1 it degenerates to one local block — the same code path single- and
multi-chip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _block_attn(
    q, k, v, kv_mask, scale, dropout_rate=0.0, dropout_key=None, bias=None
):
    """One block's scores + stable-softmax partials.

    q: [B, H, Tq, D]; k,v: [B, H, Tk, D]; kv_mask: [B, Tk] bool;
    bias: optional additive [H, Tq, Tk] (T5 relative-position bias).
    Returns (numer [B,H,Tq,D], denom [B,H,Tq], runmax [B,H,Tq]).

    Attention-probs dropout (HF attention_probs_dropout_prob) drops terms
    from the numerator only: dropout(softmax(s)) @ v == (dropout-masked p
    @ v) / (undropped sum p), since dropout's 1/keep scaling commutes with
    the normalization — this keeps the streaming form exact.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias[None]
    neg = jnp.finfo(s.dtype).min
    s = jnp.where(kv_mask[:, None, None, :], s, neg)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(kv_mask[:, None, None, :], p, 0.0)
    p_v = p
    if dropout_key is not None and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate, p.shape)
        p_v = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    numer = jnp.einsum("bhqk,bhkd->bhqd", p_v, v)
    denom = jnp.sum(p, axis=-1)
    return numer, denom, m


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array,
    axis_name: str = "sp",
    dropout_rate: float = 0.0,
    dropout_key: jax.Array | None = None,
    scale: float | None = None,
    bias_fn=None,
) -> jax.Array:
    """Exact attention with k/v rotating around the `axis_name` ring.

    Shapes (per device, inside shard_map): q,k,v [B, H, T_local, D],
    kv_mask [B, T_local] (False = padding). Returns [B, H, T_local, D].

    scale: score multiplier (default 1/sqrt(D); T5 passes 1.0).
    bias_fn: optional rotation-step -> [H, T_local, T_local] additive
    bias for the block whose k/v arrived at that step (T5's relative
    position bias, computed per block from global positions — the step
    index is traced, so the callback must be built from jnp ops).
    """
    if scale is None:
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    n_dev = jax.lax.psum(1, axis_name)
    if dropout_key is not None:
        # independent masks per (device, rotation step)
        dropout_key = jax.random.fold_in(
            dropout_key, jax.lax.axis_index(axis_name)
        )

    def block_key(i):
        return (
            None if dropout_key is None else jax.random.fold_in(dropout_key, i)
        )

    def block_bias(i):
        return None if bias_fn is None else bias_fn(i)

    numer, denom, m = _block_attn(
        q, k, v, kv_mask, scale, dropout_rate, block_key(0), block_bias(0)
    )

    def body(i, carry):
        numer, denom, m, k, v, kv_mask = carry
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        kv_mask = jax.lax.ppermute(kv_mask, axis_name, perm)
        bn, bd, bm = _block_attn(
            q, k, v, kv_mask, scale, dropout_rate, block_key(i), block_bias(i)
        )
        new_m = jnp.maximum(m, bm)
        alpha = jnp.exp(m - new_m)
        beta = jnp.exp(bm - new_m)
        numer = numer * alpha[..., None] + bn * beta[..., None]
        denom = denom * alpha + bd * beta
        return numer, denom, new_m, k, v, kv_mask

    numer, denom, m, *_ = jax.lax.fori_loop(
        1, n_dev, body, (numer, denom, m, k, v, kv_mask)
    )
    denom = jnp.maximum(denom, jnp.finfo(denom.dtype).tiny)
    return numer / denom[..., None]


def full_attention(
    q, k, v, kv_mask, dropout_rate: float = 0.0, dropout_key=None,
    scale: float | None = None, bias=None,
):
    """Reference single-device attention (for parity tests)."""
    if scale is None:
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    numer, denom, _ = _block_attn(
        q, k, v, kv_mask, scale, dropout_rate, dropout_key, bias
    )
    denom = jnp.maximum(denom, jnp.finfo(denom.dtype).tiny)
    return numer / denom[..., None]
