"""Device mesh construction and axis conventions.

The communication backend of this framework is the XLA compiler: we declare
a logical mesh with named axes and annotate shardings; XLA inserts the
collectives (all-reduce / all-gather / reduce-scatter) over ICI within a
slice and DCN across slices. This replaces the reference's explicit NCCL
process groups (CodeT5/run_defect.py:143-147) and torch DataParallel
scatter/gather (LineVul/linevul/linevul_main.py:165-166).

Axis conventions (any can be size 1 and collapse away):
  dp — data parallel: batches of whole graphs / examples
  tp — tensor parallel: transformer heads / MLP shards
  sp — sequence parallel: ring attention over sequence chunks
  pp — pipeline parallel: encoder layer stages (GPipe microbatch schedule,
       parallel/pipeline.py; activations ride ppermute between stages)
  ep — expert parallel: MoE experts (parallel/moe.py; experts shard over
       ep, tokens stay replicated, one psum assembles the outputs)
  fsdp — weight sharding for the declarative per-param sharding maps
       (parallel/sharding.py; `tp`/`fsdp` path-pattern rules, consumed
       by the GSPMD serve path)
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepdfa_tpu.core.config import MeshConfig

AXES = ("dp", "tp", "sp", "pp", "ep", "fsdp")


def maybe_init_distributed() -> bool:
    """Initialize multi-host JAX when launched under a multi-process
    runtime (TPU pods / DCN-connected slices).

    Uses jax.distributed.initialize(), which auto-discovers coordinator,
    process count, and process id from the TPU metadata or the standard
    env vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID). After this, jax.devices() spans every host and the
    same mesh/shard_map code scales across DCN — the multi-host analog of
    the reference's torch.distributed NCCL init (run_defect.py:143-147).

    No-ops (returns False) in single-process settings.
    """
    import os

    global _DISTRIBUTED_INITIALIZED
    if _DISTRIBUTED_INITIALIZED:
        return True
    if not (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
        or os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") > 0
    ):
        return False
    jax.distributed.initialize()
    _DISTRIBUTED_INITIALIZED = True
    return True


_DISTRIBUTED_INITIALIZED = False


def make_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    if devices is None:
        # multi-host runtimes must initialize before jax.devices() so the
        # mesh spans every host's chips (no-op in single-process settings)
        maybe_init_distributed()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(
        dp=cfg.dp if cfg else -1,
        tp=cfg.tp if cfg else 1,
        sp=cfg.sp if cfg else 1,
        pp=getattr(cfg, "pp", 1) if cfg else 1,
        ep=getattr(cfg, "ep", 1) if cfg else 1,
        fsdp=getattr(cfg, "fsdp", 1) if cfg else 1,
    )
    free = [ax for ax, s in sizes.items() if s == -1]
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    if n % fixed != 0:
        raise ValueError(f"{n} devices not divisible by fixed axes {sizes}")
    if len(free) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {free}")
    if free:
        sizes[free[0]] = n // fixed
    elif fixed != n:
        raise ValueError(f"mesh {sizes} does not use all {n} devices")
    shape = tuple(sizes[ax] for ax in AXES)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading axis across dp (graph shards / example batches)."""
    return NamedSharding(mesh, P("dp"))


def put_replicated(tree, mesh: Mesh):
    return jax.device_put(tree, replicated(mesh))


def put_dp(tree, mesh: Mesh):
    return jax.device_put(tree, dp_sharding(mesh))
