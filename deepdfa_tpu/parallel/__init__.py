from deepdfa_tpu.parallel.megatron import region_end, region_start
from deepdfa_tpu.parallel.mesh import (
    AXES,
    dp_sharding,
    make_mesh,
    maybe_init_distributed,
    put_dp,
    put_replicated,
    replicated,
)
from deepdfa_tpu.parallel.ring_attention import full_attention, ring_attention

__all__ = [
    "AXES",
    "dp_sharding",
    "make_mesh",
    "maybe_init_distributed",
    "put_dp",
    "put_replicated",
    "replicated",
    "region_end",
    "region_start",
    "full_attention",
    "ring_attention",
]
