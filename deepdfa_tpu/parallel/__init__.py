from deepdfa_tpu.parallel.mesh import (
    AXES,
    dp_sharding,
    make_mesh,
    put_dp,
    put_replicated,
    replicated,
)

__all__ = [
    "AXES",
    "dp_sharding",
    "make_mesh",
    "put_dp",
    "put_replicated",
    "replicated",
]
