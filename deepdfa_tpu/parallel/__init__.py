from deepdfa_tpu.parallel.graph_shard import (
    edge_batch_specs,
    edge_sharded_apply,
)
from deepdfa_tpu.parallel.megatron import region_end, region_start
from deepdfa_tpu.parallel.mesh import (
    AXES,
    dp_sharding,
    make_mesh,
    maybe_init_distributed,
    put_dp,
    put_replicated,
    replicated,
)
from deepdfa_tpu.parallel.moe import (
    MoEConfig,
    init_moe_params,
    moe_ffn,
    moe_ffn_ep,
)
from deepdfa_tpu.parallel.pipeline import (
    merge_stages,
    pipeline_encode,
    split_stages,
)
from deepdfa_tpu.parallel.sharding import (
    Rule,
    ShardingMap,
    init_runtime,
    is_primary,
    parse_rules,
    sharding_map_for,
)
from deepdfa_tpu.parallel.ring_attention import full_attention, ring_attention
from deepdfa_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "AXES",
    "dp_sharding",
    "make_mesh",
    "maybe_init_distributed",
    "put_dp",
    "put_replicated",
    "replicated",
    "region_end",
    "region_start",
    "full_attention",
    "ring_attention",
    "ulysses_attention",
    "MoEConfig",
    "init_moe_params",
    "moe_ffn",
    "edge_batch_specs",
    "edge_sharded_apply",
    "moe_ffn_ep",
    "merge_stages",
    "pipeline_encode",
    "split_stages",
    "Rule",
    "ShardingMap",
    "init_runtime",
    "is_primary",
    "parse_rules",
    "sharding_map_for",
]
