"""Ulysses-style sequence parallelism: all-to-all head sharding, exact.

The second of the two canonical sequence-parallel attention schemes (the
task's "ring attention or all-to-all" pair; see parallel/ring_attention.py
for the first). Where the ring keeps queries resident and ROTATES k/v
blocks P-1 times over ICI, Ulysses REDISTRIBUTES once: an all-to-all
converts the layout from (all heads, local sequence chunk) to (local head
slice, full sequence), plain full attention runs locally, and a second
all-to-all restores the sequence-sharded layout. Exact — no approximation;
both schemes compute identical attention.

Trade-off (the reason both exist): the ring moves k/v (2 tensors) P-1
times but needs P sequential steps whose latency hides only if each block
is compute-heavy; Ulysses moves q/k/v + output once each as two balanced
all-to-alls, which XLA lowers to single ICI collectives — typically the
faster choice at moderate sequence lengths, while the ring wins when the
head count is too small to split or sequence blocks are huge (all-to-all
materializes the full-S axis per device: O(S) memory vs the ring's
O(S/P)). Requires heads % sp == 0 (after any tp head-sharding).

No reference counterpart (SURVEY §5.7: the reference caps sequences at
512 tokens with no sequence parallelism) — this is TPU-first long-context
capability, selected per encoder via TransformerConfig.sp_variant.
"""

from __future__ import annotations

import jax

from deepdfa_tpu.parallel.ring_attention import full_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array,
    axis_name: str = "sp",
    dropout_rate: float = 0.0,
    dropout_key: jax.Array | None = None,
    scale: float | None = None,
    bias: jax.Array | None = None,
    attn_impl: str = "xla",
    flash_interpret: bool = False,
) -> jax.Array:
    """Exact attention via two all-to-alls over `axis_name`.

    Shapes (per device, inside shard_map): q,k,v [B, H, T_local, D] with
    the sequence sharded over the axis; kv_mask [B, T_local] (False =
    padding). `bias` is an additive score bias for THIS DEVICE's head
    slice over the full sequence ([H/P, S, S], broadcast over batch) —
    the all-to-all gives rank r heads [r*H/P, (r+1)*H/P), so callers
    slice their global bias the same way (T5's relative position bias,
    models/t5.py encoder_rel_bias). Returns [B, H, T_local, D], same
    layout as ring_attention.
    """
    n_dev = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    if h % n_dev:
        raise ValueError(
            f"{h} attention heads not divisible by sequence-parallel "
            f"size {n_dev} (ulysses shards heads; use sp_variant='ring')"
        )

    def to_heads(x):  # [B, H, T_local, D] -> [B, H/P, S, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = to_heads(q), to_heads(k), to_heads(v)
    # the full-sequence padding mask, assembled from the shards
    mask_full = jax.lax.all_gather(
        kv_mask, axis_name, axis=1, tiled=True
    )  # [B, S]
    if dropout_key is not None:
        # heads are disjoint across devices after the all-to-all, so
        # per-device masks are independent by construction
        dropout_key = jax.random.fold_in(
            dropout_key, jax.lax.axis_index(axis_name)
        )
    # resolve the lowering HERE, at the full-sequence shape the kernel
    # actually runs at (callers pass cfg.attn_impl raw — the local chunk
    # length they see would gate the wrong shape): forced "flash" raises
    # on untileable shapes, "auto" falls back quietly, and the biased
    # form carries the kernel's VMEM sequence cap
    from deepdfa_tpu.nn.flash_attention import (
        derive_seed,
        flash_attention,
        resolve_impl,
    )

    impl = resolve_impl(
        attn_impl, qg.shape[2], qg.shape[3], biased=bias is not None,
        interpret_hint=flash_interpret)
    if impl == "flash":
        # the local problem after the all-to-all is exactly the
        # single-device one (full sequence, head slice), so the fused
        # Pallas kernel applies unchanged: kv mask + optional head-slice
        # bias + in-kernel probs-dropout (seed derived from the
        # per-device folded key)
        seed = None
        if dropout_key is not None and dropout_rate > 0.0:
            seed = derive_seed(dropout_key)
        ctx = flash_attention(
            qg, kg, vg, mask_full, scale=scale, dropout_rate=(
                dropout_rate if dropout_key is not None else 0.0),
            seed=seed, bias=bias,
            interpret="tpu" if flash_interpret else False,
        )
    else:
        ctx = full_attention(
            qg, kg, vg, mask_full,
            dropout_rate=dropout_rate, dropout_key=dropout_key, scale=scale,
            bias=bias,
        )
    # [B, H/P, S, D] -> [B, H, T_local, D]
    return jax.lax.all_to_all(
        ctx, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
