"""Unified declarative sharding layer: one mesh config drives pod-scale
training AND serving for all three model families (docs/sharding.md).

Before this module, the `NamedSharding`/`PartitionSpec`/`shard_map`
plumbing lived as per-trainer copies (train/loop.py,
train/combined_loop.py, train/gen_loop.py, train/clone_loop.py) and the
serve executors placed params with a bare `device_put` — nothing in the
stack could span a pod. This module centralizes:

- **Path-pattern sharding maps** (`ShardingMap`): ordered glob rules
  over `/`-joined parameter paths resolving to `PartitionSpec`s — the
  `sharding_map` idiom of jetstream-style serving stacks (SNIPPETS [2]:
  `tp`/`fsdp` axes keyed by param-path globs) — plus "stacked" rules
  that shard a leading stacked-layer axis across `pp` (the GPipe stage
  layout). `sharding_map_for(family, ...)` builds the family defaults;
  `MeshConfig.rules` prepends operator overrides, so ONE config drives
  every family in train and serve.

- **Logical data shards** decoupled from the dp mesh size: a batch's
  leading axis carries `num_shards` LOGICAL shards (a fixed data
  layout); any mesh whose dp divides it consumes the same batches. Per
  logical shard compute runs under `jax.vmap` inside the `shard_map`
  block and reductions ride `gather_logical` — an ordered `all_gather`
  to the fixed `[num_shards, ...]` layout followed by one fixed-shape
  sum — so the loss/grad arithmetic has ONE reduction tree regardless
  of dp. That is what makes the step-loss trajectory BIT-IDENTICAL
  across dp topologies on the same device kind (pinned on the 8-virtual-
  device CPU mesh, tests/test_sharding.py), which in turn makes elastic
  resume exact: a `TrainState` checkpoint written at dp=8 restores onto
  dp=4 or dp=1 and the merged trajectory is the uninterrupted one.
  Cost: gradients transit as `[num_shards, ...]` (an `all_gather`
  instead of a `psum`), i.e. num_shards x grad bytes of collective
  traffic — negligible for the GGNN family this path serves; the
  combined/t5 trainers keep their psum reductions (their tp/sp/pp grad
  bookkeeping is documented in train/combined_loop.py).

- **Multi-host bring-up**: `init_runtime()` (jax.distributed via
  parallel/mesh.py:maybe_init_distributed) wired into the CLI train and
  serve entry points, and `is_primary()` gating so obs/checkpoint
  coordination (RunLogger, efficiency ledger, flight recorder, step
  checkpoints) runs on process 0 only — N hosts write ONE run log, ONE
  postmortem, ONE checkpoint tree.

- **Elastic placement**: a `ShardingMap` resolves concrete
  `NamedSharding`s for any mesh; `StepCheckpointer` resume re-places
  restored host pytrees with the live trainer's shardings
  (train/resilience.py:place_like), and `restore_for_inference` /
  `ModelRegistry` commit restored params straight under the serving
  map — a sharded checkpoint serves without a reshape step.
"""

from __future__ import annotations

import fnmatch
import dataclasses
import logging
from typing import Any, Callable, Iterable, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepdfa_tpu.parallel.mesh import AXES, maybe_init_distributed

logger = logging.getLogger(__name__)

__all__ = [
    "ShardingMap",
    "Rule",
    "parse_rules",
    "sharding_map_for",
    "flat_path",
    "param_paths",
    "batch_shardings",
    "place_batch",
    "place_params",
    "gather_logical",
    "split_logical",
    "check_logical_shards",
    "logical_shards",
    "init_runtime",
    "is_primary",
    "process_index",
    "process_count",
    "if_primary",
    "mesh_record",
    "publish_mesh",
]


# ---------------------------------------------------------------------------
# path-pattern rules


@dataclasses.dataclass(frozen=True)
class Rule:
    """`pattern` is an fnmatch glob over the `/`-joined parameter path
    (`*` spans path separators, the SCHEMA convention); `spec` is the
    PartitionSpec a matching leaf gets. First matching rule wins.
    `final` rules (operator overrides from `MeshConfig.rules`) also
    suppress any later `stacked` transform — a pinned path stays
    pinned."""

    pattern: str
    spec: P
    final: bool = False

    def matches(self, path: str) -> bool:
        return fnmatch.fnmatchcase(path, self.pattern)


def flat_path(key_path) -> str:
    """jax key path -> the `/`-joined coordinate the rules match (same
    spelling as train/checkpoint.py CheckpointMismatch reports)."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in key_path)


def param_paths(tree: Any) -> list[str]:
    """Every leaf path of a params pytree in rule coordinates."""
    return [
        flat_path(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]


def _spec_axes(spec: P) -> list[str]:
    out: list[str] = []
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.extend(str(e) for e in entry)
        else:
            out.append(str(entry))
    return out


@dataclasses.dataclass(frozen=True)
class ShardingMap:
    """Ordered path-pattern rules resolving a params pytree to
    PartitionSpecs (and NamedShardings on a concrete mesh).

    `stacked` rules fire AFTER the base spec resolves: a matching leaf's
    leading dimension (the stacked-layer axis of the scan-stacked
    encoder params) is resharded over `axis` — `P(axis, *spec[1:])` —
    which is exactly the GPipe stage layout (train/combined_loop.py
    class docstring)."""

    rules: tuple[Rule, ...] = ()
    default: P = P()
    #: (pattern, axis): shard dim 0 of matching leaves over `axis`
    stacked: tuple[tuple[str, str], ...] = ()

    def spec_for(self, path: str) -> P:
        spec = self.default
        final = False
        for rule in self.rules:
            if rule.matches(path):
                spec = rule.spec
                final = rule.final
                break
        if final:
            return spec
        for pattern, axis in self.stacked:
            if fnmatch.fnmatchcase(path, pattern):
                spec = P(axis, *tuple(spec)[1:]) if len(spec) else P(axis)
                break
        return spec

    def param_specs(self, tree: Any, mesh_shape: dict | None = None) -> Any:
        """A pytree of PartitionSpecs matching `tree`'s structure.

        With `mesh_shape` ({axis: size}), each resolved spec is FITTED
        to its leaf: a dimension the rule shards but the leaf's size
        does not divide falls back to replicated for that dim (and a
        spec longer than the leaf's rank is trimmed) — glob rules like
        `*/kernel` then shard every kernel that CAN shard instead of
        dying on the one [64, 1] output head."""
        if mesh_shape is None:
            return jax.tree_util.tree_map_with_path(
                lambda kp, _: self.spec_for(flat_path(kp)), tree
            )
        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: _fit_spec(
                self.spec_for(flat_path(kp)),
                tuple(getattr(leaf, "shape", ()) or ()),
                mesh_shape,
            ),
            tree,
        )

    def shardings(self, mesh: Mesh, tree: Any) -> Any:
        self.validate(mesh)
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            self.param_specs(tree, mesh_shape=dict(mesh.shape)),
            is_leaf=lambda x: isinstance(x, P),
        )

    def place(self, mesh: Mesh, tree: Any) -> Any:
        """Commit a (host or device) params pytree under this map."""
        return jax.device_put(tree, self.shardings(mesh, tree))

    def validate(self, mesh: Mesh | None = None) -> None:
        """Every referenced axis must be a declared mesh axis — a typo'd
        rule fails at map build, not as an opaque XLA error mid-run."""
        names = tuple(mesh.axis_names) if mesh is not None else AXES
        for rule in self.rules:
            for ax in _spec_axes(rule.spec):
                if ax not in names:
                    raise ValueError(
                        f"sharding rule {rule.pattern!r}: unknown mesh "
                        f"axis {ax!r} (axes: {names})"
                    )
        for pattern, axis in self.stacked:
            if axis not in names:
                raise ValueError(
                    f"stacked rule {pattern!r}: unknown mesh axis "
                    f"{axis!r} (axes: {names})"
                )

    def describe(self) -> dict:
        """Loggable/healthz-able summary of the map."""
        return {
            "rules": [
                {"pattern": r.pattern, "spec": str(r.spec)}
                for r in self.rules
            ],
            "stacked": [
                {"pattern": p, "axis": a} for p, a in self.stacked
            ],
            "default": str(self.default),
        }


def _fit_spec(spec: P, shape: tuple, mesh_shape: dict) -> P:
    """Fit a rule spec to a concrete leaf: trim to rank, replicate any
    dim whose size the spec's mesh-axis product does not divide."""
    if not len(spec):
        return spec
    dims: list[Any] = []
    for i, entry in enumerate(tuple(spec)[: len(shape)]):
        if entry is None:
            dims.append(None)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        size = 1
        for ax in axes:
            size *= int(mesh_shape.get(str(ax), 1))
        dims.append(entry if size and shape[i] % size == 0 else None)
    return P(*dims)


def _parse_spec(text: str) -> P:
    """`"tp,fsdp"` -> P("tp","fsdp"); `-` = None dim; `a+b` = a grouped
    dim; empty -> replicated P()."""
    text = text.strip()
    if not text:
        return P()
    dims: list[Any] = []
    for tok in text.split(","):
        tok = tok.strip()
        if tok in ("-", "None", ""):
            dims.append(None)
        elif "+" in tok:
            dims.append(tuple(t.strip() for t in tok.split("+")))
        else:
            dims.append(tok)
    return P(*dims)


def parse_rules(rule_strings: Iterable[str]) -> tuple[Rule, ...]:
    """The config spelling (`MeshConfig.rules`): each entry is
    `pattern=spec` with spec per `_parse_spec` — e.g.
    `encoder/embeddings/word/embedding=fsdp,-` or `*/kernel=-,tp`.
    An empty spec (`pattern=`) pins a path replicated ahead of any
    later rule."""
    rules = []
    for s in rule_strings:
        if "=" not in s:
            raise ValueError(
                f"sharding rule must be 'pattern=axes', got {s!r}"
            )
        pattern, _, spec = s.partition("=")
        rules.append(Rule(pattern.strip(), _parse_spec(spec)))
    return tuple(rules)


# ---------------------------------------------------------------------------
# family defaults: the ONE map per model family


def _flat_rules(prefix: str, spec_tree: Any) -> list[Rule]:
    """Flatten a pytree of PartitionSpecs into exact-path rules."""
    return [
        Rule(f"{prefix}{flat_path(kp)}", spec)
        for kp, spec in jax.tree_util.tree_flatten_with_path(
            spec_tree, is_leaf=lambda x: isinstance(x, P)
        )[0]
    ]


def sharding_map_for(
    family: str,
    model_cfg: Any = None,
    mesh_shape: dict | None = None,
    extra_rules: Sequence[str] = (),
) -> ShardingMap:
    """The family's default sharding map on a mesh of `mesh_shape`
    ({axis: size}; size-1 axes collapse their rules away so a 1-device
    mesh resolves everything replicated — single-chip and pod share one
    code path).

    - "deepdfa" / "gen" / "clone": replicated params (the GGNN/seq2seq
      trees are small); with `fsdp` > 1 the embedding tables and dense
      kernels shard their trailing dim over `fsdp` (the SNIPPETS [2]
      layout) — consumed by the GSPMD serve path, where XLA inserts the
      gathers (the shard_map train step keeps params replicated).
    - "combined" / "t5": the Megatron layer table
      (models/transformer.py:tp_layer_specs / models/t5.py) over `tp`,
      the T5 rel_bias heads over `tp`, MoE experts over `ep`, and the
      stacked encoder layer axis over `pp` via a stacked rule.

    `extra_rules` (from `MeshConfig.rules`) PREPEND, so an operator
    override beats any family default."""
    shape = dict(mesh_shape or {})
    tp = shape.get("tp", 1) > 1
    pp = shape.get("pp", 1) > 1
    ep = shape.get("ep", 1) > 1
    fsdp = shape.get("fsdp", 1) > 1
    # operator rules are FINAL: they beat family defaults AND the pp
    # stacked transform, so `pattern=` genuinely pins a path
    rules: list[Rule] = [
        dataclasses.replace(r, final=True) for r in parse_rules(extra_rules)
    ]
    stacked: list[tuple[str, str]] = []
    if family in ("deepdfa", "gen", "clone"):
        if fsdp:
            rules += [
                Rule("*/embedding", P(None, "fsdp")),
                Rule("*/kernel", P(None, "fsdp")),
            ]
    elif family in ("combined", "t5"):
        if tp:
            if family == "t5":
                from deepdfa_tpu.models import t5 as t5m

                rules += _flat_rules("encoder/layers/", t5m.tp_layer_specs())
                rules.append(Rule("encoder/rel_bias", P(None, "tp")))
            else:
                from deepdfa_tpu.models import transformer as tfm

                rules += _flat_rules("encoder/layers/", tfm.tp_layer_specs())
        if ep:
            from deepdfa_tpu.parallel.moe import moe_param_specs

            rules += _flat_rules("moe/", moe_param_specs())
        if pp:
            stacked.append(("encoder/layers/*", "pp"))
    else:
        raise ValueError(
            f"unknown model family {family!r}; known: deepdfa, gen, "
            f"clone, combined, t5"
        )
    return ShardingMap(rules=tuple(rules), stacked=tuple(stacked))


# ---------------------------------------------------------------------------
# sharded H2D placement (the ONE device_put helper)


def batch_shardings(mesh: Mesh, spec_tree: Any) -> Any:
    """PartitionSpec pytree -> NamedSharding pytree on `mesh`."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def place_batch(mesh: Mesh, batch: Any, specs: Any = None) -> Any:
    """Sharded H2D copy with the exact specs the step consumes — the one
    helper behind CombinedTrainer.place_batch and the prefetch
    pipeline's device_placer. `specs` is a single PartitionSpec /
    NamedSharding applied to every leaf (the common hot path — built
    ONCE by the caller, zero per-batch pytree work) or a per-leaf spec
    pytree; default: leading axis over dp (the logical-shard layout).
    Static pytree metadata is untouched so jit cache keys are stable."""
    if specs is None:
        specs = P(("dp",))
    if isinstance(specs, P):
        specs = NamedSharding(mesh, specs)
    if isinstance(specs, NamedSharding):
        return jax.device_put(batch, specs)
    return jax.device_put(batch, batch_shardings(mesh, specs))


def place_params(
    mesh: Mesh, tree: Any, sharding_map: ShardingMap | None = None
) -> Any:
    """Commit a params pytree under a map's resolved shardings
    (replicated default) — the registry/restore-time half of elastic
    placement."""
    smap = sharding_map if sharding_map is not None else ShardingMap()
    return smap.place(mesh, tree)


# ---------------------------------------------------------------------------
# logical shards: a data layout fixed across dp topologies


def check_logical_shards(num_shards: int, mesh: Mesh) -> int:
    """Validate the [num_shards, ...] layout against the mesh's dp size;
    returns shards-per-device. The clear error here replaces XLA's
    opaque non-divisible-sharding failure."""
    dp = mesh.shape.get("dp", 1)
    if num_shards % dp:
        raise ValueError(
            f"{num_shards} logical shards not divisible by mesh dp={dp} "
            f"— elastic topologies must keep num_shards fixed and pick "
            f"dp from its divisors (docs/sharding.md)"
        )
    return num_shards // dp


def logical_shards(mesh_cfg, mesh: Mesh) -> int:
    """The run's logical shard count: `MeshConfig.num_shards`, or the
    mesh's dp size when unset (the historical layout, one shard per
    device). Elastic runs SET num_shards so every topology consumes
    identical batches."""
    n = int(getattr(mesh_cfg, "num_shards", 0) or 0)
    return n if n > 0 else mesh.shape.get("dp", 1)


def split_logical(batch: Any, index) -> Any:
    """Leaf-wise select of one logical shard from a [k, ...] local
    block (static pytree metadata untouched)."""
    return jax.tree.map(lambda x: x[index], batch)


def gather_logical(x, axis_name: str = "dp"):
    """Ordered all_gather of per-logical-shard values to the FIXED
    [num_shards, ...] layout — the same array regardless of how many
    devices contributed, so the downstream sum has one reduction tree
    on every topology (the bit-identity mechanism; module docstring)."""
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)


# ---------------------------------------------------------------------------
# multi-host bring-up + process-0 coordination


def init_runtime() -> bool:
    """Multi-host JAX init for the CLI entry points (train AND serve):
    no-op single-process, `jax.distributed.initialize()` under a
    multi-process runtime (parallel/mesh.py:maybe_init_distributed).
    Must run before the first `jax.devices()` probe so the mesh spans
    every host."""
    return maybe_init_distributed()


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """True on the obs/checkpoint coordinator (process 0). Everything
    with a single-writer contract — RunLogger, checkpoint manifests,
    step checkpoints, the efficiency ledger, the flight recorder,
    heartbeat files — is gated on this, so an N-host run writes one of
    each instead of N racing copies."""
    return jax.process_index() == 0


def if_primary(make: Callable[[], Any], fallback: Any = None) -> Any:
    """Build a single-writer resource on process 0 only."""
    return make() if is_primary() else fallback


# ---------------------------------------------------------------------------
# observability


def mesh_record(mesh: Mesh, num_shards: int | None = None) -> dict:
    """The topology stamp manifests and MULTICHIP records carry:
    non-collapsed axis sizes, device/process counts, logical shards."""
    out = {
        "axes": {
            ax: int(size) for ax, size in mesh.shape.items() if size > 1
        },
        "devices": int(mesh.devices.size),
        "processes": int(jax.process_count()),
    }
    if num_shards is not None:
        out["num_shards"] = int(num_shards)
    return out


def validate_multichip(doc: dict) -> dict:
    """Validate a MULTICHIP record (the `{"multichip": ...}` JSON line
    `__graft_entry__.py:dryrun_multichip` prints — found either raw or
    under a driver artifact's `parsed` field). The record is the
    multi-chip BENCH gate's input, so its shape is contract-checked
    like every other emitted artifact (`scripts/check_obs_schema.py
    --multichip`): topology stamp per mesh shape, per-shard ledger
    fields, the serve ladder's zero-recompile pin, and every flattened
    scalar tag declared in obs/metrics.py:SCHEMA under `mesh/*` /
    `shard/*`."""
    problems: list[str] = []
    rec = doc
    if isinstance(rec, dict) and "parsed" in rec:
        rec = rec.get("parsed") or {}
    if isinstance(rec, dict) and "multichip" in rec:
        rec = rec["multichip"]
    if not isinstance(rec, dict):
        return {"ok": False, "problems": ["no multichip record found"]}
    for key, typ in (
        ("n_devices", int), ("num_shards", int),
        ("mesh_shapes", dict), ("shard", dict), ("hbm", dict),
        ("compile_seconds_total", (int, float)),
    ):
        if not isinstance(rec.get(key), typ):
            problems.append(f"missing/mistyped field: {key}")
    shapes = rec.get("mesh_shapes") or {}
    if isinstance(shapes, dict) and not shapes:
        problems.append("mesh_shapes is empty")
    for name, stamp in (shapes or {}).items():
        for key in ("axes", "devices", "processes", "num_shards"):
            if key not in (stamp or {}):
                problems.append(f"mesh_shapes/{name} missing {key}")
    shard = rec.get("shard") or {}
    if isinstance(shard, dict) and not shard:
        problems.append("shard section is empty (ledger off?)")
    for label, site in (shard or {}).items():
        for key in ("compile_seconds", "executions"):
            if key not in (site or {}):
                problems.append(f"shard/{label} missing {key}")
    serve = rec.get("serve")
    if not isinstance(serve, dict):
        problems.append("missing serve section")
    else:
        if serve.get("steady_state_recompiles") != 0:
            problems.append(
                "serve.steady_state_recompiles != 0 — the warmed "
                "sharded ladder recompiled"
            )
        if not serve.get("ladder"):
            problems.append("serve.ladder is empty")
    # every scalar tag the record would flatten to must be declared
    from deepdfa_tpu.obs import metrics as obs_metrics

    undeclared = obs_metrics.undeclared_tags([{
        "mesh": shapes,
        "shard": {**(shard or {}), "hbm": rec.get("hbm") or {}},
    }])
    problems.extend(f"undeclared tag: {t}" for t in undeclared)
    return {
        "ok": not problems,
        "problems": problems,
        "n_devices": rec.get("n_devices"),
        "mesh_shapes": sorted(shapes or ()),
        "shard_sites": len(shard or ()),
    }


def publish_mesh(mesh: Mesh, num_shards: int | None = None) -> None:
    """Mirror the topology into `mesh/*` gauges (SCHEMA-declared) so
    obs-enabled runs carry it in the run log."""
    from deepdfa_tpu.obs import metrics as obs_metrics

    r = obs_metrics.REGISTRY
    for ax, size in mesh.shape.items():
        if size > 1:
            r.gauge(f"mesh/{ax}").set(size)
    r.gauge("mesh/devices").set(mesh.devices.size)
    r.gauge("mesh/processes").set(jax.process_count())
    if num_shards is not None:
        r.gauge("mesh/num_shards").set(num_shards)
