"""Version-compat shims for jax APIs the framework uses everywhere."""

from __future__ import annotations

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

__all__ = ["shard_map"]
