"""Version-compat shims for jax APIs the framework uses everywhere."""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters


def shard_map(*args, **kwargs):
    """jax.shard_map with the replication-check kwarg normalized.

    The check was renamed check_rep -> check_vma across jax releases;
    callers here use the new name, and this maps it back (or drops it)
    for older installs so one call site works on every supported jax.
    """
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        check = kwargs.pop("check_vma")
        if "check_rep" in _PARAMS:
            kwargs["check_rep"] = check
    return _shard_map(*args, **kwargs)


__all__ = ["shard_map"]
