"""Mixture-of-experts FFN with expert parallelism over the `ep` mesh axis.

No reference counterpart (SURVEY §2.5: the reference has no TP/PP/SP/EP) —
this is TPU-first scale headroom: swapping an encoder's dense FFN for a
sparse expert layer multiplies parameters without multiplying per-token
FLOPs, and the experts shard across devices.

Static-shape formulation (the Mesh-TensorFlow / Switch style — XLA needs
fixed shapes, so routing is expressed as dense dispatch/combine tensors
bounded by a per-expert capacity):

- router: logits [N, E] -> top-k experts per token, softmax-renormalized
  gate weights over the chosen k;
- capacity C = ceil(k * N / E * capacity_factor); within one expert,
  tokens claim slots in arrival order (cumsum over the token axis) and
  overflow tokens are DROPPED for that expert (gate contributes 0 — the
  residual path carries them, standard Switch behavior);
- dispatch [N, E, C] one-hot gathers expert inputs as one einsum on the
  MXU; combine = dispatch * gate scatters expert outputs back.

Expert parallelism: experts shard over `ep` (each device holds E/ep
expert FFNs); tokens stay replicated across `ep` (the batch is already
dp-sharded), every device routes+computes only its local experts, and one
`psum` assembles the output — expert disjointness makes the sum exact.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    intermediate_size: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25


def init_moe_params(cfg: MoEConfig, key: jax.Array) -> dict:
    kr, k1, k2 = jax.random.split(key, 3)
    d, f, e = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    std = 0.02
    return {
        "router": jax.random.normal(kr, (d, e)) * std,
        "w1": jax.random.normal(k1, (e, d, f)) * std,
        "b1": jnp.zeros((e, f)),
        "w2": jax.random.normal(k2, (e, f, d)) * std,
        "b2": jnp.zeros((e, d)),
    }


def capacity(cfg: MoEConfig, n_tokens: int) -> int:
    return max(1, math.ceil(cfg.top_k * n_tokens / cfg.num_experts
                            * cfg.capacity_factor))


def _route(cfg: MoEConfig, router_w: jax.Array, x: jax.Array, cap: int):
    """dispatch [N, E, C] {0,1}, combine [N, E, C] float, aux loss."""
    n = x.shape[0]
    e = cfg.num_experts
    logits = x @ router_w  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    _, top_idx = jax.lax.top_k(logits, cfg.top_k)  # [N, k]
    # mask of chosen experts per token, and gates renormalized over them
    chosen = jax.nn.one_hot(top_idx, e, dtype=x.dtype).sum(1)  # [N, E]
    gates = probs * chosen
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    # slot assignment per expert: arrival-order position among its tokens
    position = jnp.cumsum(chosen, axis=0) * chosen - chosen  # [N, E] 0-based
    keep = chosen * (position < cap)
    slot = jax.nn.one_hot(position.astype(jnp.int32), cap, dtype=x.dtype)
    dispatch = keep[:, :, None] * slot  # [N, E, C]
    combine = dispatch * gates[:, :, None]
    # switch-style load-balancing auxiliary loss: fraction of tokens per
    # expert x mean router prob per expert, scaled by E
    frac = chosen.mean(0)
    aux = e * jnp.sum(frac * probs.mean(0))
    return dispatch, combine, aux


def _expert_compute(w1, b1, w2, b2, dispatch, combine, x):
    """Gather -> per-expert FFN -> scatter, for any expert block size."""
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, w1) + b1[:, None, :]
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]
    return jnp.einsum("ecd,nec->nd", expert_out, combine)


def moe_ffn(cfg: MoEConfig, params: dict, x: jax.Array,
            cap: int | None = None):
    """Dense-math MoE forward on one device. x: [N, D] -> ([N, D], aux)."""
    if cap is None:
        cap = capacity(cfg, x.shape[0])
    dispatch, combine, aux = _route(cfg, params["router"], x, cap)
    out = _expert_compute(
        params["w1"], params["b1"], params["w2"], params["b2"],
        dispatch, combine, x,
    )
    return out, aux


def moe_stage_forward(
    cfg: MoEConfig,
    local_params: dict,
    x: jax.Array,
    n_dev: int,
    ep_axis: str = "ep",
    broadcast: str = "psum",
):
    """One device's share of the expert-parallel MoE, INSIDE shard_map.

    local_params holds this device's expert block ([E/ep, ...]) plus the
    replicated router; x is the full local token batch (replicated across
    `ep_axis`). Routing is computed identically on every device (one
    [N,D]x[D,E] matmul — cheap), each device evaluates only its expert
    slice, and the output broadcast assembles the disjoint contributions:
    "psum" when the loss lives outside the shard_map, "region_end"
    (psum-forward/identity-backward) when every rank computes its own
    loss copy inside it (see parallel/pipeline.py for the same trap).
    """
    rank = jax.lax.axis_index(ep_axis)
    e_local = cfg.num_experts // n_dev
    cap = capacity(cfg, x.shape[0])
    if broadcast == "region_end":
        # Megatron f/g pairing: under per-rank loss copies each rank's
        # backward only carries its own expert slice's contribution to
        # d(loss)/dx, so the x entering the region must psum its
        # cotangent over ep (identity forward). Without this the
        # encoder upstream receives a per-rank partial gradient that no
        # dp/sp reduction ever fixes.
        from deepdfa_tpu.parallel.megatron import region_start

        x = region_start(x, ep_axis)
    dispatch, combine, aux = _route(cfg, local_params["router"], x, cap)
    lo = rank * e_local
    disp_l = jax.lax.dynamic_slice_in_dim(dispatch, lo, e_local, 1)
    comb_l = jax.lax.dynamic_slice_in_dim(combine, lo, e_local, 1)
    out = _expert_compute(
        local_params["w1"], local_params["b1"],
        local_params["w2"], local_params["b2"],
        disp_l, comb_l, x,
    )
    if broadcast == "psum":
        out = jax.lax.psum(out, ep_axis)
    elif broadcast == "region_end":
        from deepdfa_tpu.parallel.megatron import region_end

        out = region_end(out, ep_axis)
        # router grad bookkeeping under per-rank loss copies: the main
        # path's router cotangent is PARTIAL per rank (each rank only
        # differentiates through its own expert block), so the trainer
        # psums the router over ep — but the aux term's router cotangent
        # is full on every rank and would double-count. Routing aux
        # through a rank-0 region_end keeps every rank's loss copy
        # identical (psum forward) while exactly one cotangent flows
        # back (identity backward), making the ep psum exact for both.
        aux = region_end(
            jnp.where(rank == 0, aux, jnp.zeros_like(aux)), ep_axis
        )
    else:
        raise ValueError(f"broadcast={broadcast!r}")
    return out, aux


def moe_param_specs(ep_axis: str = "ep") -> dict:
    """PartitionSpecs for an MoE param tree: experts shard their leading
    axis over `ep_axis`, the router replicates."""
    return {
        "router": P(),
        "w1": P(ep_axis), "b1": P(ep_axis),
        "w2": P(ep_axis), "b2": P(ep_axis),
    }


def moe_ffn_ep(cfg: MoEConfig, params: dict, x: jax.Array, mesh,
               ep_axis: str = "ep"):
    """Expert-parallel MoE: experts shard over `ep_axis`, tokens stay
    replicated, outputs psum — numerically identical to moe_ffn (the
    routing is computed identically everywhere; each device keeps only
    its expert block's contribution). x: [N, D] -> ([N, D], aux).

    Replication contract: x is declared with in_specs P(), i.e. the FULL
    token batch is replicated across every mesh axis including dp. This
    is only safe as the standalone parity/dry-run path it serves; inside
    a dp-sharded training step it would silently compute the global
    batch on every device — callers embedding MoE in their own shard_map
    must use moe_stage_forward on their per-shard tokens instead (as
    CombinedTrainer does). Asserted below."""
    from deepdfa_tpu.parallel.compat import shard_map

    n_dev = mesh.shape[ep_axis]
    if cfg.num_experts % n_dev:
        raise ValueError(
            f"{cfg.num_experts} experts not divisible by ep={n_dev}"
        )
    oversized = {
        ax: n for ax, n in mesh.shape.items() if ax != ep_axis and n > 1
    }
    if oversized:
        raise ValueError(
            f"moe_ffn_ep replicates the full token batch over every mesh "
            f"axis; axes {oversized} would silently recompute the global "
            "batch per device — embed moe_stage_forward in your own "
            "shard_map instead"
        )

    def body(pr, x_rep):
        return moe_stage_forward(
            cfg, pr, x_rep, n_dev, ep_axis, broadcast="psum"
        )

    specs = moe_param_specs(ep_axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=({k: specs[k] for k in params}, P()),
        out_specs=(P(), P()),
        check_vma=False,
    )(params, x)
