"""Pipeline parallelism: GPipe microbatch schedule over the `pp` mesh axis.

No reference counterpart (the reference is single-GPU; SURVEY §2.5) — this
is TPU-first scale headroom for encoders too deep for one chip's HBM. The
design follows the classic GPipe schedule expressed the XLA way:

- the encoder's stacked layer parameters [L, ...] reshape to [P, L/P, ...]
  and shard their leading (stage) axis over `pp` — each device holds a
  contiguous block of layers;
- a `lax.scan` runs M + P - 1 ticks; each tick every stage runs its layer
  block on the microbatch currently resident and hands the activation to
  the next stage with a single `ppermute` hop (neighbor traffic on the
  ICI ring, never an all-to-all);
- stage 0 feeds a fresh microbatch per tick (embedding lives there
  logically; physically every stage computes the embed and a `where`
  keeps stage 0's — a few flops traded for branch-free code XLA can
  pipeline); the last stage collects finished microbatches, and one
  `psum` at the end replicates the output across stages;
- backward needs no hand-written schedule: `ppermute` transposes to the
  reverse permutation, so autodiff yields the mirrored backward pipeline,
  and `jax.checkpoint` around the stage body keeps only per-stage
  activations live (the GPipe rematerialization strategy).

The schedule itself (`_gpipe_schedule`) is architecture-agnostic — it
takes embed/layer-block callbacks. `pipeline_stage_forward` wires the
RoBERTa-family encoder (absolute positions; composes with sp by embedding
each sequence shard at its global offset and running ring/Ulysses
attention inside the stage body), `t5_pipeline_stage_forward` the T5
encoder (shared relative-position bias computed on every stage — the
bias table is replicated and cheap — with per-rotation bias blocks under
sp). pp x sp composes because the sp collectives inside a tick are
orthogonal to the pp ppermute between ticks.

The bubble fraction is (P-1)/(M+P-1): pick microbatches >= 4x stages.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def split_stages(layers: dict, n_stages: int) -> dict:
    """Reshape stacked layer params [L, ...] -> [P, L/P, ...]."""
    def reshape(x):
        n_layers = x.shape[0]
        if n_layers % n_stages:
            raise ValueError(
                f"{n_layers} layers not divisible by {n_stages} stages"
            )
        return x.reshape(n_stages, n_layers // n_stages, *x.shape[1:])

    return jax.tree.map(reshape, layers)


def merge_stages(staged: dict) -> dict:
    """Inverse of split_stages: [P, L/P, ...] -> [L, ...]."""
    return jax.tree.map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]), staged
    )


def _gpipe_schedule(
    ids: jax.Array,
    mask: jax.Array,
    embed_fn,
    block_fn,
    microbatches: int,
    n_stages: int,
    pp_axis: str,
    hidden_size: int,
    dtype,
    broadcast: str,
):
    """The arch-agnostic GPipe scan, running INSIDE shard_map on one stage.

    ids/mask: full local batch [B, T] (replicated across `pp_axis`).
    embed_fn(ids_t, microbatch_index) -> [B/M, T, D]: input embedding
    (every stage computes it; a `where` keeps stage 0's).
    block_fn(x, mask_m, microbatch_index, stage_index) -> x: this stage's
    layer block.
    Returns hidden [B, T, D] replicated across stages.

    `broadcast` picks how the last stage's outputs reach every stage:
    - "psum": plain psum — correct when the LOSS is computed outside the
      shard_map (the cotangent enters once);
    - "region_end": psum-forward / identity-backward (megatron region op)
      — required when every stage computes its own loss copy inside the
      same shard_map (a raw psum would transpose to psum and multiply
      encoder cotangents by the stage count; same trap as the sp [CLS]
      broadcast, docs/DESIGN.md section 4).
    """
    b_total, seq = ids.shape
    m = microbatches
    # uneven batches pad up to the next multiple of m by REPLICATING the
    # last real row (ids and mask together, so the padded rows are
    # numerically ordinary — no degenerate all-masked attention rows),
    # then slice the pad back off after the schedule. All static-shape
    # Python: XLA sees one fixed program. Backward is automatically
    # right: the slice's VJP zero-fills the padded rows' cotangents, so
    # they contribute nothing to parameter gradients.
    b_pad = -(-b_total // m) * m
    if b_pad != b_total:
        n_pad = b_pad - b_total
        ids = jnp.concatenate(
            [ids, jnp.broadcast_to(ids[-1:], (n_pad, seq))], axis=0
        )
        mask = jnp.concatenate(
            [mask, jnp.broadcast_to(mask[-1:], (n_pad, seq))], axis=0
        )
    ids_m = ids.reshape(m, b_pad // m, seq)
    mask_m_all = mask.reshape(m, b_pad // m, seq)

    stage = jax.lax.axis_index(pp_axis)
    steps = m + n_stages - 1
    dt = jnp.dtype(dtype)
    state0 = jnp.zeros((b_pad // m, seq, hidden_size), dt)
    out0 = jnp.zeros((m, b_pad // m, seq, hidden_size), dt)

    def step(carry, t):
        state, outputs = carry
        # microbatch index resident at this stage this tick
        mi = jnp.clip(t - stage, 0, m - 1)
        ti = jnp.clip(t, 0, m - 1)
        ids_t = jax.lax.dynamic_index_in_dim(ids_m, ti, keepdims=False)
        # stage 0's tick input is a fresh embed; later stages take the
        # activation handed over by ppermute last tick
        x0 = embed_fn(ids_t, ti)
        xin = jnp.where(stage == 0, x0, state)
        mask_m = jax.lax.dynamic_index_in_dim(mask_m_all, mi, keepdims=False)
        out = block_fn(xin, mask_m, mi, stage)
        widx = t - (n_stages - 1)
        write = (stage == n_stages - 1) & (widx >= 0)
        wi = jnp.clip(widx, 0, m - 1)
        prev = jax.lax.dynamic_index_in_dim(outputs, wi, keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, prev), wi, 0
        )
        nxt = jax.lax.ppermute(
            out, pp_axis,
            perm=[(i, (i + 1) % n_stages) for i in range(n_stages)],
        )
        return (nxt, outputs), None

    (_, outputs), _ = jax.lax.scan(step, (state0, out0), jnp.arange(steps))
    # only the last stage wrote real values; the broadcast replicates them
    if broadcast == "psum":
        outputs = jax.lax.psum(outputs, pp_axis)
    elif broadcast == "region_end":
        from deepdfa_tpu.parallel.megatron import region_end

        outputs = region_end(outputs, pp_axis)
    else:
        raise ValueError(f"broadcast={broadcast!r}")
    return outputs.reshape(b_pad, seq, -1)[:b_total]


def _stage_block_fn(layers_local: dict, dropout_key, cfg, layer_call):
    """The per-stage layer-block runner shared by both encoder families:
    microbatch/stage dropout-key decorrelation (each stage holds
    different global layers; an identical key would draw identical masks
    on every stage), per-layer key split, cfg-driven remat (incl.
    remat_policy — models/transformer.py remat_wrap), lax.scan over
    this stage's layer block. layer_call(lp, x, mask_m, key) -> x."""
    n_local = jax.tree.leaves(layers_local)[0].shape[0]

    def block_fn(x, mask_m, mi, stage):
        skey = (
            jax.random.fold_in(
                jax.random.fold_in(
                    jax.random.fold_in(dropout_key, 7919), mi
                ),
                stage,
            )
            if dropout_key is not None
            else None
        )
        keys = (
            jax.random.split(skey, n_local)
            if skey is not None
            else jnp.zeros((n_local, 2), jnp.uint32)
        )

        def layer_fn(h, inp):
            lp, k = inp
            return (
                layer_call(
                    lp, h, mask_m, k if dropout_key is not None else None
                ),
                None,
            )

        from deepdfa_tpu.models.transformer import remat_wrap

        fn = remat_wrap(cfg, layer_fn)
        x, _ = jax.lax.scan(fn, x, (layers_local, keys))
        return x

    return block_fn


def pipeline_stage_forward(
    cfg,
    layers_local: dict,
    rest_p: dict,
    input_ids: jax.Array,
    attn_mask: jax.Array,
    dropout_key,
    microbatches: int,
    n_stages: int,
    pp_axis: str = "pp",
    broadcast: str = "psum",
    tp_axis: str | None = None,
    sp_axis: str | None = None,
):
    """RoBERTa-family GPipe stage forward (INSIDE shard_map).

    layers_local: this stage's layer block [L/P, ...]; rest_p: replicated
    non-layer params; input_ids/attn_mask: the full local batch [B, T]
    (replicated across `pp_axis`; with `sp_axis`, T is the LOCAL sequence
    chunk — embedding applies the shard's global position offset and the
    layer blocks run ring/Ulysses attention over `sp_axis`).
    Returns hidden [B, T, D] replicated across stages.
    """
    from deepdfa_tpu.models.transformer import embed, encoder_layer

    position_offset = 0
    if sp_axis is not None:
        position_offset = jax.lax.axis_index(sp_axis) * input_ids.shape[1]
        if dropout_key is not None:
            # every sp shard holds different tokens: decorrelate masks
            dropout_key = jax.random.fold_in(
                dropout_key, jax.lax.axis_index(sp_axis)
            )

    def embed_fn(ids_t, ti):
        ekey = (
            jax.random.fold_in(dropout_key, ti)
            if dropout_key is not None
            else None
        )
        return embed(cfg, rest_p, ids_t, position_offset, ekey)

    block_fn = _stage_block_fn(
        layers_local, dropout_key, cfg,
        lambda lp, h, mask_m, k: encoder_layer(
            cfg, lp, h, mask_m, k, sp_axis=sp_axis, tp_axis=tp_axis
        ),
    )
    return _gpipe_schedule(
        input_ids, attn_mask, embed_fn, block_fn, microbatches, n_stages,
        pp_axis, cfg.hidden_size, cfg.dtype, broadcast,
    )


def t5_pipeline_stage_forward(
    cfg,
    layers_local: dict,
    rest_p: dict,
    input_ids: jax.Array,
    attn_mask: jax.Array,
    dropout_key,
    microbatches: int,
    n_stages: int,
    pp_axis: str = "pp",
    broadcast: str = "psum",
    tp_axis: str | None = None,
    sp_axis: str | None = None,
):
    """T5 encoder GPipe stage forward (INSIDE shard_map).

    Same contract as models.t5.encode ([B, T] -> [B, T, D] post
    final-RMSNorm): layers_local is this stage's [L/P, ...] block; rest_p
    holds the replicated word/rel_bias/final_ln params. The shared
    relative-position bias is computed on every stage (the bias table is
    tiny and replicated; its gradient is a per-stage partial that the
    trainer psums over pp). With `sp_axis`, T is the local chunk and
    per-rotation-step bias blocks feed ring attention.
    """
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.models.transformer import _dropout

    dt = jnp.dtype(cfg.dtype)
    if dropout_key is not None and sp_axis is not None:
        dropout_key = jax.random.fold_in(
            dropout_key, jax.lax.axis_index(sp_axis)
        )
    bias, bias_fn = t5m.encoder_rel_bias(
        cfg, rest_p["rel_bias"], input_ids.shape[1], dt, sp_axis
    )

    def embed_fn(ids_t, ti):
        x = rest_p["word"][ids_t].astype(dt)
        ekey = (
            jax.random.fold_in(dropout_key, ti)
            if dropout_key is not None and cfg.dropout_rate > 0.0
            else None
        )
        return _dropout(x, cfg.dropout_rate, ekey)

    block_fn = _stage_block_fn(
        layers_local, dropout_key, cfg,
        lambda lp, h, mask_m, k: t5m.encoder_layer(
            cfg, lp, h, mask_m, k, bias, bias_fn,
            tp_axis=tp_axis, sp_axis=sp_axis,
        ),
    )
    hidden = _gpipe_schedule(
        input_ids, attn_mask, embed_fn, block_fn, microbatches, n_stages,
        pp_axis, cfg.hidden_size, cfg.dtype, broadcast,
    )
    # final RMSNorm + dropout run replicated on the broadcast output
    # (replicated-true across pp: identical cotangents on every stage)
    hidden = t5m._rms_norm(hidden, rest_p["final_ln"], cfg.layer_norm_eps)
    k_final = (
        jax.random.fold_in(dropout_key, 104729)
        if dropout_key is not None and cfg.dropout_rate > 0.0
        else None
    )
    return _dropout(hidden, cfg.dropout_rate, k_final)


def pipeline_encode(
    cfg,
    params: dict,
    input_ids: jax.Array,
    mesh,
    microbatches: int = 4,
    attn_mask: jax.Array | None = None,
    dropout_key: jax.Array | None = None,
    pp_axis: str = "pp",
):
    """RoBERTa-family encoder forward, layer-pipelined over `pp_axis`.

    Same contract as models.transformer.encode ([B, T] ids -> [B, T, D]),
    numerically identical to the single-device path (parity-tested).
    `params` is the standard (unstaged) param tree; staging happens here.
    Uneven batches are handled: the final microbatch is padded with
    replicated rows inside the schedule and sliced off after.
    """
    from deepdfa_tpu.parallel.compat import shard_map

    n_stages = mesh.shape[pp_axis]
    if attn_mask is None:
        attn_mask = input_ids != cfg.pad_token_id

    staged_layers = split_stages(params["layers"], n_stages)
    rest = {k: v for k, v in params.items() if k != "layers"}

    def body(staged_local, rest_p, ids, mask, key):
        layers_local = jax.tree.map(lambda x: x[0], staged_local)
        return pipeline_stage_forward(
            cfg, layers_local, rest_p, ids, mask, key,
            microbatches, n_stages, pp_axis, broadcast="psum",
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(pp_axis), staged_layers),
            jax.tree.map(lambda _: P(), rest),
            P(), P(), P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(staged_layers, rest, input_ids, attn_mask, dropout_key)


def t5_pipeline_encode(
    cfg,
    params: dict,
    input_ids: jax.Array,
    mesh,
    microbatches: int = 4,
    attn_mask: jax.Array | None = None,
    dropout_key: jax.Array | None = None,
    pp_axis: str = "pp",
):
    """T5 encoder forward, layer-pipelined over `pp_axis` (same contract
    as models.t5.encode; parity-tested against it)."""
    from deepdfa_tpu.parallel.compat import shard_map

    n_stages = mesh.shape[pp_axis]
    if attn_mask is None:
        attn_mask = input_ids != cfg.pad_token_id

    staged_layers = split_stages(params["layers"], n_stages)
    rest = {k: v for k, v in params.items() if k != "layers"}

    def body(staged_local, rest_p, ids, mask, key):
        layers_local = jax.tree.map(lambda x: x[0], staged_local)
        return t5_pipeline_stage_forward(
            cfg, layers_local, rest_p, ids, mask, key,
            microbatches, n_stages, pp_axis, broadcast="psum",
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(pp_axis), staged_layers),
            jax.tree.map(lambda _: P(), rest),
            P(), P(), P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(staged_layers, rest, input_ids, attn_mask, dropout_key)
