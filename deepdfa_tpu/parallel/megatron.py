"""Megatron-style tensor-parallel region boundaries.

Inside a shard_map with replicated activations and tp-sharded weights, a
parallel region (attention QKV..out-proj, or FFN up..down) computes partial
sums that must be all-reduced forward, while the *backward* pass needs the
mirrored treatment so every parameter gradient comes out either
local-shard-true (sharded weights) or replicated-true (everything else):

  region_start (Megatron "f"): identity forward, psum backward —
      the region's input cotangent is partial per tp shard and must sum.
  region_end   (Megatron "g"): psum forward, identity backward —
      the full-activation cotangent arriving from above is already
      replicated-true on every shard.

With both in place, no per-parameter gradient psum over tp is needed at
all; only the data axes (dp, sp) reduce explicitly. See Shoeybi et al.
2019 §3 — this is the standard TPU recipe (scaling-book) expressed as two
custom_vjp ops usable inside shard_map.
"""

from __future__ import annotations

from functools import partial

import jax


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def region_start(x, axis_name: str):
    return x


def _rs_fwd(x, axis_name):
    return x, None


def _rs_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


region_start.defvjp(_rs_fwd, _rs_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def region_end(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


def _re_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _re_bwd(axis_name, _, g):
    return (g,)


region_end.defvjp(_re_fwd, _re_bwd)
