"""Fleet front door: health-gated least-outstanding routing with
eject/readmit and in-flight retry (docs/fleet.md).

One stdlib-HTTP process in front of N replica workers:

  POST /score    admission (fleet/admission.py) -> pick the routable
                 replica with the fewest outstanding forwards -> proxy.
                 A transport failure (connection refused/reset/timeout —
                 the replica died or wedged mid-request) ejects the
                 replica and retries the SAME request on a survivor:
                 scores are bit-identical regardless of which replica
                 batches them (tests/test_serve.py property), so a retry
                 can never return a different answer, only a later one.
  GET  /healthz  fleet topology: per-replica state/outstanding/eject
                 status + the admission snapshot
  GET  /stats    the same plus the router's rolling SLO windows
  GET  /metrics  Prometheus text: fleet/* registry + SLO families

Request identity: the router assigns the request id at ingress and
propagates it via `X-Request-Id`; the replica's serving spans adopt it
(serve/server.py), so one request's Perfetto flow chain spans
router -> replica frontend -> queue -> device across process traces.

Replica lifecycle, from heartbeats (fleet/heartbeat.py): `ready` +
fresh => routable; `draining` => observed but not routed (the drain
contract); stale or `drained` => gone. Ejected replicas are probed
(`GET /healthz`, bounded) on the poll cadence and readmitted on success
+ a fresh heartbeat — a replica that recovered rejoins without operator
action. Every eject/readmit/drain/gone transition is a `fleet_event`
line in fleet_log.jsonl next to the per-request entries; the log is
validated by `scripts/check_obs_schema.py --fleet-log`.
"""

from __future__ import annotations

import http.client
import json
import logging
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from deepdfa_tpu.fleet import admission as fleet_admission, coord, heartbeat
from deepdfa_tpu.obs import metrics as obs_metrics, trace as obs_trace
from deepdfa_tpu.obs.slo import SloEngine, registry_exposition
from deepdfa_tpu.serve.batcher import new_request_id

logger = logging.getLogger(__name__)

#: the declared fleet_event vocabulary (validate_fleet_log enforces it);
#: quarantine = malformed announcement file (fleet/heartbeat.py), and
#: takeover/stepdown are the router-HA transitions (fleet/ha.py)
EVENTS = (
    "join", "eject", "readmit", "drain_observed", "gone",
    "quarantine", "takeover", "stepdown",
)

#: the declared rollout-record vocabulary (fleet/rollout.py appends
#: {"rollout": {...}} lines to the same fleet_log; validate_fleet_log
#: enforces the names here)
ROLLOUT_EVENTS = (
    "start", "swap", "refused", "halt", "rollback", "complete",
)

#: the declared autoscale-decision vocabulary (fleet/autoscale.py
#: appends {"autoscale": {...}} lines to the same fleet_log; the
#: degradation ladder escalates shed_stage2 -> tighten_admission ->
#: scale_up, and `relax`/`scale_down` unwind it)
AUTOSCALE_ACTIONS = (
    "hold", "shed_stage2", "tighten_admission", "scale_up",
    "scale_down", "relax",
)

#: the declared shadow-ride vocabulary (deepdfa_tpu/flywheel/shadow.py
#: appends {"shadow": {...}} windowed candidate-vs-incumbent comparison
#: records to the same fleet_log; docs/flywheel.md)
SHADOW_EVENTS = ("ride_start", "window", "ride_end")

#: the declared demotion-reason vocabulary ({"demotion": {...}} records,
#: deepdfa_tpu/flywheel/promote.py): a losing or drifting candidate is
#: demoted on the record, never promoted to traffic
DEMOTION_REASONS = (
    "trailing", "drift", "alert", "unlabeled", "insufficient_samples",
    "rollout_halted", "manual",
)

#: nominal in-flight forwards one routable replica absorbs before the
#: router's queue_ratio alert signal reads saturated (replicas don't
#: advertise a queue bound in their heartbeat, so the saturation gauge
#: is outstanding / (routable * this))
REPLICA_INFLIGHT_BUDGET = 8

#: transport-level failures that mean "the replica, not the request"
TRANSPORT_ERRORS = (
    ConnectionError,
    socket.timeout,
    TimeoutError,
    http.client.HTTPException,
    OSError,
)


class FleetLog:
    """Thread-safe appender to fleet_log.jsonl (the serve RequestLog
    rule: one handle, flushed per entry, tail-able while serving). The
    handle comes from the coordination backend (fleet/coord.py); the
    default LocalDirBackend's handle is today's append-and-flush file,
    byte-identical."""

    def __init__(
        self,
        path: str | Path,
        backend: coord.CoordinationBackend | None = None,
    ):
        self.path = Path(path)
        self._lock = threading.Lock()
        self._file = (backend or coord.LOCAL).open_log(self.path)

    def append(self, entry: dict) -> None:
        line = json.dumps(entry)
        with self._lock:
            if not self._file.closed:
                self._file.write_line(line)

    def close(self) -> None:
        with self._lock:
            if not self._file.closed:
                self._file.close()


class ReplicaView:
    """Router-side state for one replica (heartbeat + routing health)."""

    __slots__ = (
        "id", "host", "port", "state", "t_heartbeat", "info",
        "outstanding", "ejected", "consecutive_failures", "forwarded",
        "drain_logged", "quarantined", "shadow",
    )

    def __init__(self, hb: dict):
        self.id = str(hb["replica_id"])
        self.outstanding = 0
        self.ejected = False
        self.consecutive_failures = 0
        self.forwarded = 0
        self.drain_logged = False
        self.quarantined = False
        self.update(hb)

    def update(self, hb: dict) -> None:
        self.host = str(hb["host"])
        self.port = int(hb["port"])
        self.state = str(hb["state"])
        self.t_heartbeat = float(hb["t_unix"])
        self.info = {
            k: v for k, v in hb.items()
            if k not in ("replica_id", "host", "port", "state", "t_unix")
        }
        # a shadow-role replica (docs/flywheel.md) announces itself via
        # the `shadow` heartbeat info field — not a new lifecycle state,
        # so every pre-flywheel reader keeps validating the heartbeat
        self.shadow = bool(self.info.get("shadow"))

    def routable(self, timeout_s: float, now: float) -> bool:
        return (
            not self.ejected
            and not self.quarantined
            and not self.shadow
            and self.state == heartbeat.READY
            and (now - self.t_heartbeat) <= timeout_s
        )

    def view(self, timeout_s: float, now: float) -> dict:
        return {
            "id": self.id,
            "addr": f"{self.host}:{self.port}",
            "state": self.state,
            "outstanding": self.outstanding,
            "forwarded": self.forwarded,
            "ejected": self.ejected,
            "quarantined": self.quarantined,
            "routable": self.routable(timeout_s, now),
            "shadow": self.shadow,
            "heartbeat_age_s": round(now - self.t_heartbeat, 3),
            "steady_state_recompiles": self.info.get(
                "steady_state_recompiles"
            ),
            "ledger_params": self.info.get("ledger_params"),
        }


class NoReplicaAvailable(RuntimeError):
    """Every routable replica failed (or none exists) for one request."""


class Router:
    """Routing + admission + fleet bookkeeping for one router process.

    Transport-only retry policy: `forward()` tries up to 1 + `retries`
    DISTINCT replicas; a replica that fails at the transport level is
    ejected at `eject_threshold` consecutive failures and the request
    moves on. HTTP responses (any status) pass through — a 4xx/5xx from
    a live replica is the request's verdict, not the replica's."""

    def __init__(
        self,
        fleet_dir: str | Path,
        heartbeat_timeout_s: float = 10.0,
        poll_interval_s: float = 0.5,
        eject_threshold: int = 1,
        retries: int = 2,
        request_timeout_s: float = 60.0,
        admission: fleet_admission.AdmissionController | None = None,
        log: FleetLog | None = None,
        slo: SloEngine | None = None,
        probe_timeout_s: float = 5.0,
        summary_interval_s: float = 0.0,
        backend: coord.CoordinationBackend | None = None,
    ):
        self.fleet_dir = Path(fleet_dir)
        self.backend = backend or coord.LOCAL
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.eject_threshold = max(1, int(eject_threshold))
        self.retries = max(0, int(retries))
        self.request_timeout_s = float(request_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.summary_interval_s = float(summary_interval_s)
        self.admission = admission or fleet_admission.AdmissionController()
        self.log = log
        self.slo = slo or SloEngine()
        self._lock = threading.Lock()
        self._replicas: dict[str, ReplicaView] = {}
        #: replica ids currently behind a malformed announcement file,
        #: with the reason — quarantine is logged once per transition,
        #: not once per poll tick
        self._quarantine_reasons: dict[str, str] = {}
        #: injectable transport fault in the router's HTTP client (the
        #: `partition` chaos scenario, scripts/fault_inject.py): a
        #: callable (replica_id) -> falsy (healthy) | reason string; a
        #: faulted forward/probe raises ConnectionError exactly where a
        #: dropped network path would
        self.transport_fault = None
        #: fleet telemetry plane (obs/aggregate.py) — wired on by
        #: router_from_config when fleet.telemetry is set; None keeps
        #: the default path byte-identical
        self.aggregator = None
        self.publisher = None
        self.trace_shipper = None
        #: alert engine (obs/alerts.py) — wired when fleet.alerts is on
        self.alerts = None
        #: shadow-ride sampler (flywheel/shadow.py:ShadowSampler) —
        #: wired on by router_from_config when fleet.flywheel is set;
        #: None keeps the default path byte-identical
        self.flywheel = None
        self.alert_interval_s = 1.0
        self._last_alert = 0.0
        self._last_summary = time.monotonic()
        self._last_poll = 0.0
        self._closed = threading.Event()
        self._poll_thread: threading.Thread | None = None
        r = obs_metrics.REGISTRY
        self._m_requests = r.counter("fleet/requests")
        self._m_forwarded = r.counter("fleet/forwarded")
        self._m_retries = r.counter("fleet/retries")
        self._m_ejects = r.counter("fleet/ejects")
        self._m_readmits = r.counter("fleet/readmits")
        self._m_unroutable = r.counter("fleet/unroutable")
        self._m_quarantines = r.counter("fleet/quarantines")
        self._m_healthy = r.gauge("fleet/replicas_routable")
        self._m_known = r.gauge("fleet/replicas_known")
        self.poll(force=True)

    # -- fleet view ----------------------------------------------------------

    def _event(self, name: str, **args) -> None:
        if name not in EVENTS:
            raise ValueError(f"unknown fleet event {name!r}; in {EVENTS}")
        obs_trace.instant(f"fleet_{name}", cat="fleet", **args)
        if self.log is not None:
            self.log.append({"fleet_event": {
                "name": name, "t_unix": round(time.time(), 3), **args,
            }})

    def poll(self, force: bool = False, now: float | None = None) -> None:
        """Refresh the replica table from the heartbeat dir (rate-
        limited to the poll cadence unless forced)."""
        now = time.time() if now is None else now
        with self._lock:
            if not force and (now - self._last_poll) < self.poll_interval_s:
                return
            self._last_poll = now
        beats, invalid = heartbeat.scan_heartbeats_verbose(
            self.fleet_dir, backend=self.backend
        )
        # malformed announcement files QUARANTINE the replica behind
        # them (docs/fleet.md failure matrix): the replica's state is
        # unknowable, so it must not be routed — but a corrupt file is
        # never allowed to crash the router or churn events every tick
        quarantine_events: list[tuple[str, str]] = []
        with self._lock:
            for rid, reason in invalid.items():
                if self._quarantine_reasons.get(rid) != reason:
                    self._quarantine_reasons[rid] = reason
                    quarantine_events.append((rid, reason))
                rep = self._replicas.get(rid)
                if rep is not None:
                    rep.quarantined = True
            for rid in list(self._quarantine_reasons):
                if rid not in invalid and rid in beats:
                    # the replica's own next atomic rewrite healed the
                    # file: the quarantine lifts and the replica is
                    # routable again off its fresh, valid heartbeat
                    del self._quarantine_reasons[rid]
                    rep = self._replicas.get(rid)
                    if rep is not None:
                        rep.quarantined = False
        for rid, reason in quarantine_events:
            self._m_quarantines.inc()
            self._event("quarantine", replica=rid, reason=reason[:200])
        with self._lock:
            for rid, hb in beats.items():
                rep = self._replicas.get(rid)
                if rep is None:
                    # a drained/stale heartbeat FILE lingers on disk by
                    # design (crash evidence); it must not churn a
                    # join+gone event pair every poll tick
                    if hb["state"] == "drained" or not heartbeat.is_fresh(
                        hb, self.heartbeat_timeout_s, now=now
                    ):
                        continue
                    self._replicas[rid] = rep = ReplicaView(hb)
                    self._event(
                        "join", replica=rid,
                        addr=f"{rep.host}:{rep.port}",
                    )
                else:
                    # a fresh heartbeat alone never readmits an ejected
                    # replica — the probe loop must also reach it
                    # (probe_ejected)
                    rep.update(hb)
                if rep.state == "draining" and not rep.drain_logged:
                    rep.drain_logged = True
                    self._event("drain_observed", replica=rid)
            gone = [
                rid for rid, rep in self._replicas.items()
                if rep.state == "drained"
                or (now - rep.t_heartbeat) > self.heartbeat_timeout_s
            ]
            for rid in gone:
                rep = self._replicas.pop(rid)
                self._event(
                    "gone", replica=rid, state=rep.state,
                    heartbeat_age_s=round(now - rep.t_heartbeat, 3),
                )
            routable = sum(
                1 for r in self._replicas.values()
                if r.routable(self.heartbeat_timeout_s, now)
            )
            self._m_known.set(len(self._replicas))
            self._m_healthy.set(routable)

    def probe_ejected(self) -> None:
        """Bounded GET /healthz against every ejected replica; success +
        a fresh heartbeat readmits it (the recover-without-operator
        path)."""
        now = time.time()
        with self._lock:
            targets = [
                (rep.id, rep.host, rep.port)
                for rep in self._replicas.values()
                if rep.ejected
                and (now - rep.t_heartbeat) <= self.heartbeat_timeout_s
            ]
        for rid, host, port in targets:
            try:
                self._maybe_inject_fault(rid)
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.probe_timeout_s
                )
                try:
                    conn.request("GET", "/healthz")
                    ok = conn.getresponse().status == 200
                finally:
                    conn.close()
            except TRANSPORT_ERRORS:
                continue
            if ok:
                with self._lock:
                    rep = self._replicas.get(rid)
                    if rep is not None and rep.ejected:
                        rep.ejected = False
                        rep.consecutive_failures = 0
                        self._m_readmits.inc()
                        self._event("readmit", replica=rid)

    def start_polling(self) -> None:
        if self._poll_thread is not None:
            return
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fleet-router-poll", daemon=True
        )
        self._poll_thread.start()

    def _poll_loop(self) -> None:
        while not self._closed.wait(self.poll_interval_s):
            try:
                self.poll(force=True)
                self.probe_ejected()
                self._maybe_summarize()
                self._maybe_telemetry()
                self._maybe_alert()
            except Exception:
                logger.exception("fleet poll failed")

    def _maybe_summarize(self) -> None:
        """Periodic fleet_log summary record (fleet.summary_interval_s):
        each one embeds the admission snapshot, so a router that dies is
        at most one cadence behind on the token-bucket levels its
        successor re-seeds from (fleet/ha.py takeover, or a plain
        restart)."""
        if self.log is None or self.summary_interval_s <= 0:
            return
        now = time.monotonic()
        if (now - self._last_summary) < self.summary_interval_s:
            return
        self._last_summary = now
        self.log.append(self.summary_record())

    def _maybe_telemetry(self) -> None:
        """Telemetry-plane housekeeping on the poll cadence: publish the
        router's OWN snapshot (so the fleet scrape includes the front
        door) and ship its trace segments when tracing is on."""
        if self.publisher is not None:
            self.publisher.maybe_publish()
        if self.trace_shipper is not None:
            self.trace_shipper.maybe_ship()

    def _alert_signals(self) -> dict:
        """The snapshot-level signals the alert engine evaluates against
        (request-level signals flow in via log_request)."""
        counters = obs_metrics.REGISTRY.snapshot()
        now = time.time()
        with self._lock:
            routable = sum(
                1 for r in self._replicas.values()
                if r.routable(self.heartbeat_timeout_s, now)
            )
            outstanding = sum(
                r.outstanding for r in self._replicas.values()
            )
        # replicas don't advertise a queue bound in their heartbeat, so
        # saturation is outstanding forwards per routable replica
        # against a nominal in-flight budget — the same shape the
        # serve_queue_saturated starter rule watches
        capacity = routable * REPLICA_INFLIGHT_BUDGET
        gauges = {
            "replicas_routable": float(routable),
            "queue_ratio": (
                outstanding / capacity if capacity else 0.0
            ),
        }
        return {
            "slo": self.slo.snapshot(),
            "counters": counters,
            "gauges": gauges,
        }

    def _maybe_alert(self) -> None:
        """Evaluate the alert rule catalog on its own cadence; every
        transition lands in the fleet_log as an {"alert": ...} record
        (the engine's sink is wired to self.log at construction)."""
        if self.alerts is None:
            return
        now = time.monotonic()
        if (now - self._last_alert) < self.alert_interval_s:
            return
        self._last_alert = now
        self.alerts.evaluate(self._alert_signals())

    def _maybe_inject_fault(self, replica_id: str) -> None:
        """The injectable transport fault (the `partition` chaos
        scenario): raise the same error class a dropped router->replica
        network path produces, at the same point in the client."""
        fault = self.transport_fault
        if fault is not None:
            reason = fault(replica_id)
            if reason:
                raise ConnectionError(
                    f"injected transport fault to {replica_id}: {reason}"
                )

    # -- routing -------------------------------------------------------------

    def _pick(self, exclude: set[str], now: float) -> ReplicaView | None:
        """Least-outstanding routable replica; ties break to the least
        forwarded-so-far (sequential traffic round-robins instead of
        pinning the first id), then stable id order (deterministic)."""
        with self._lock:
            candidates = [
                rep for rid, rep in sorted(self._replicas.items())
                if rid not in exclude
                and rep.routable(self.heartbeat_timeout_s, now)
            ]
            if not candidates:
                return None
            rep = min(
                candidates,
                key=lambda r: (r.outstanding, r.forwarded, r.id),
            )
            rep.outstanding += 1
            return rep

    def _release(self, rep: ReplicaView, failed: bool) -> None:
        with self._lock:
            rep.outstanding = max(0, rep.outstanding - 1)
            if failed:
                rep.consecutive_failures += 1
                if (
                    not rep.ejected
                    and rep.consecutive_failures >= self.eject_threshold
                ):
                    rep.ejected = True
                    self._m_ejects.inc()
                    self._event(
                        "eject", replica=rep.id,
                        failures=rep.consecutive_failures,
                    )
            else:
                rep.consecutive_failures = 0
                rep.forwarded += 1

    def outstanding_total(self) -> int:
        with self._lock:
            return sum(r.outstanding for r in self._replicas.values())

    def routable_count(self, now: float | None = None) -> int:
        now = time.time() if now is None else now
        with self._lock:
            return sum(
                1 for r in self._replicas.values()
                if r.routable(self.heartbeat_timeout_s, now)
            )

    def forward(
        self, body: bytes, request_id: str, path: str = "/score"
    ) -> tuple[int, bytes, str, int]:
        """Proxy one request; (status, body, replica_id, retries).

        Raises NoReplicaAvailable when every attempt exhausted a
        distinct replica (or none was routable to begin with)."""
        tried: set[str] = set()
        attempts = 0
        last_error: Exception | None = None
        while attempts <= self.retries:
            self.poll()
            rep = self._pick(tried, time.time())
            if rep is None:
                break
            tried.add(rep.id)
            attempts += 1
            if attempts > 1:
                self._m_retries.inc()
            try:
                with obs_trace.span(
                    "router_forward", cat="fleet", request_id=request_id,
                    replica=rep.id,
                ):
                    obs_trace.flow("request", request_id, "s", cat="fleet")
                    self._maybe_inject_fault(rep.id)
                    conn = http.client.HTTPConnection(
                        rep.host, rep.port, timeout=self.request_timeout_s
                    )
                    try:
                        conn.request(
                            "POST", path, body=body,
                            headers={
                                "Content-Type": "application/json",
                                "X-Request-Id": request_id,
                            },
                        )
                        resp = conn.getresponse()
                        data = resp.read()
                        status = resp.status
                    finally:
                        conn.close()
            except TRANSPORT_ERRORS as e:
                # the replica, not the request: eject-count and retry on
                # a survivor — this is the no-request-lost path
                last_error = e
                self._release(rep, failed=True)
                obs_trace.instant(
                    "fleet_forward_failed", cat="fleet",
                    request_id=request_id, replica=rep.id,
                    error=str(e)[:200],
                )
                continue
            self._release(rep, failed=False)
            self._m_forwarded.inc()
            return status, data, rep.id, attempts - 1
        self._m_unroutable.inc()
        raise NoReplicaAvailable(
            f"no routable replica for request {request_id} "
            f"(tried {sorted(tried)}; last error: {last_error})"
        )

    # -- records -------------------------------------------------------------

    def log_request(
        self,
        request_id: str,
        status: int,
        latency_s: float,
        tenant: str,
        priority: int,
        replica: str | None = None,
        retries: int = 0,
        deadline_ms: float | None = None,
        shed_reason: str | None = None,
        prob: float | None = None,
    ) -> None:
        """The router's per-request epilogue: SLO ingest + one
        {"request": {...}} fleet_log line (admitted AND shed — the shed
        population is exactly the one overload analysis needs). `prob`
        is the replica's calibrated score, present only when the alert
        engine is on — it feeds the per-tenant drift watch live and is
        echoed into the log so `deepdfa-tpu alerts` can replay it."""
        self._m_requests.inc()
        self.slo.observe_request(status, latency_s)
        if status == 200:
            self.admission.observe_service(latency_s)
        if self.alerts is not None:
            self.alerts.observe_request(status, tenant=tenant, prob=prob)
        if self.log is None:
            return
        entry: dict = {
            "id": request_id, "status": int(status),
            "latency_ms": round(latency_s * 1e3, 3),
            "t_unix": round(time.time(), 3),
            "tenant": tenant, "priority": int(priority),
            "retries": int(retries),
            "shed": 0 if shed_reason is None else 1,
        }
        if replica is not None:
            entry["replica"] = replica
        if deadline_ms is not None:
            entry["deadline_ms"] = float(deadline_ms)
        if shed_reason is not None:
            entry["reason"] = shed_reason
        if prob is not None:
            entry["prob"] = round(float(prob), 6)
        self.log.append({"request": entry})

    def topology(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        self.poll(now=now)
        with self._lock:
            replicas = [
                rep.view(self.heartbeat_timeout_s, now)
                for _, rep in sorted(self._replicas.items())
            ]
        return {
            "fleet": True,
            "fleet_dir": str(self.fleet_dir),
            "replicas": replicas,
            "routable": sum(1 for r in replicas if r["routable"]),
            "admission": self.admission.snapshot(),
        }

    def summary_record(self) -> dict:
        """One fleet_log summary record (the run-log shape the schema
        checker validates): the fleet/* registry snapshot, the SLO
        windows, the topology scalars, and the admission snapshot (the
        token-bucket levels + service EWMA a restarted or failed-over
        router re-seeds from — `reseed_from_log`)."""
        snap = obs_metrics.REGISTRY.snapshot()
        return {
            "fleet": {
                k[len("fleet/"):]: v
                for k, v in snap.items() if k.startswith("fleet/")
            },
            "fleet_slo": self.slo.snapshot(),
            "fleet_replicas": self.routable_count(),
            "fleet_admission": self.admission.snapshot(),
        }

    #: how much log tail the re-seed scans for the last summary record;
    #: summaries land every summary_interval_s between request lines,
    #: so a few hundred KB always covers several cadences — and the
    #: read sits on the TAKEOVER critical path, where scanning a
    #: multi-GB request log would blow the documented failover bound
    RESEED_TAIL_BYTES = 4 << 20

    def reseed_from_log(self, path: str | Path) -> int:
        """Restore admission state from the LAST summary record in a
        fleet_log.jsonl — the router-restart/HA-takeover half of the
        no-lost-state contract (docs/fleet.md). An absent, empty, or
        corrupt log re-seeds nothing: fresh buckets, never a crash.
        The read is the backend's bounded `tail_records`
        (RESEED_TAIL_BYTES): the first line may be torn by the seek
        and the FINAL line by the previous active crashing mid-append
        — both are skipped per the tail contract, so a torn tail
        costs one record, never the whole re-seed. Returns the number
        of re-seeded buckets."""
        try:
            records = self.backend.tail_records(
                path, self.RESEED_TAIL_BYTES
            )
        except OSError:
            return 0
        for rec in reversed(records):
            if isinstance(rec.get("fleet_admission"), dict):
                n = self.admission.reseed(rec["fleet_admission"])
                if n:
                    logger.info(
                        "re-seeded %d admission bucket(s) from the last "
                        "summary record in %s", n, path,
                    )
                return n
        return 0

    def close(self) -> None:
        self._closed.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
            self._poll_thread = None
        if self.flywheel is not None:
            try:
                self.flywheel.close()
            except Exception:
                logger.exception("shadow sampler close failed")
            self.flywheel = None
        if self.trace_shipper is not None:
            try:
                self.trace_shipper.close()
            except Exception:
                logger.exception("trace shipper close failed")
            self.trace_shipper = None
        if self.log is not None:
            self.log.append(self.summary_record())
            self.log.close()
            self.log = None

    def kill(self) -> None:
        """Abrupt-death test hook (the in-process kill-router drill):
        stop the poll loop and drop the log handle WITHOUT the final
        summary record — a SIGKILLed router writes nothing more. Without
        this, a 'dead' in-process active would keep appending summaries
        (frozen admission snapshots) to the shared fleet_log, and a
        later takeover could re-seed from the zombie's stale record."""
        self._closed.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
            self._poll_thread = None
        if self.log is not None:
            self.log.close()
            self.log = None


def router_from_config(
    cfg,
    fleet_dir: str | Path,
    log_path: str | Path | None = None,
    reseed: bool = True,
    backend: coord.CoordinationBackend | None = None,
) -> Router:
    """One configured Router (admission policies, cadences, SLO windows,
    fleet log) from a Config — the `fleet` CLI's and the smoke's shared
    construction path. `reseed` restores token-bucket levels from the
    log's last summary record BEFORE the log handle is (re)opened for
    append — a no-op on a fresh log, the restart contract otherwise.
    Every coordination op (heartbeat scans, the log, the re-seed tail)
    rides `fleet.coord_backend` unless a backend is passed in."""
    fcfg = cfg.fleet
    if backend is None:
        backend = coord.backend_from_config(cfg)
    admission = fleet_admission.AdmissionController(
        tenants=fleet_admission.parse_tenants(fcfg.tenants),
        default_rate=fcfg.default_rate,
        default_burst=fcfg.default_burst,
        default_priority=fcfg.default_priority,
        replica_capacity=fcfg.replica_capacity,
        shed_fraction=fcfg.shed_fraction,
        service_time_init_ms=fcfg.service_time_init_ms,
        cascade_shed_fraction=fcfg.cascade_shed_fraction,
    )
    router = Router(
        fleet_dir,
        heartbeat_timeout_s=fcfg.heartbeat_timeout_s,
        poll_interval_s=fcfg.poll_interval_s,
        eject_threshold=fcfg.eject_threshold,
        retries=fcfg.retries,
        request_timeout_s=fcfg.request_timeout_s,
        admission=admission,
        log=(
            FleetLog(log_path, backend=backend)
            if log_path is not None else None
        ),
        slo=SloEngine(
            windows=cfg.serve.slo_windows,
            max_samples=cfg.serve.slo_window_samples,
        ),
        summary_interval_s=fcfg.summary_interval_s,
        backend=backend,
    )
    if reseed and log_path is not None:
        router.reseed_from_log(log_path)
    if fcfg.telemetry:
        # the fleet telemetry plane (obs/aggregate.py): aggregate the
        # replicas' published snapshots for /metrics + /stats, publish
        # the router's OWN snapshot, and ship its trace segments when
        # tracing is on — all rides the same coord backend. Imported
        # lazily so the default (telemetry off) path never loads it.
        from deepdfa_tpu.obs import aggregate as obs_agg

        router.aggregator = obs_agg.FleetAggregator(
            fleet_dir, backend=backend,
            stale_after_s=fcfg.heartbeat_timeout_s,
        )
        router.publisher = obs_agg.SnapshotPublisher(
            fleet_dir, "router",
            slo_engines=lambda: {"router": router.slo},
            backend=backend,
            interval_s=fcfg.telemetry_interval_s,
        )
        if obs_trace.enabled():
            router.trace_shipper = obs_agg.TraceShipper(
                fleet_dir, "router", backend=backend,
                interval_s=fcfg.telemetry_interval_s,
            )
    if fcfg.alerts:
        from deepdfa_tpu.obs import alerts as obs_alerts

        router.alert_interval_s = float(fcfg.alert_interval_s)
        engine = obs_alerts.AlertEngine(
            obs_alerts.rules_from_config(cfg),
            sink=(router.log.append if router.log is not None else None),
        )
        router.alerts = engine
    if fcfg.flywheel:
        # the data flywheel's shadow sampler (flywheel/shadow.py,
        # docs/flywheel.md): mirror a bounded sample of admitted
        # requests through the coord backend for the shadow candidate.
        # Imported lazily so the default (flywheel off) path never
        # loads the subsystem.
        from deepdfa_tpu.flywheel import shadow as flywheel_shadow

        router.flywheel = flywheel_shadow.ShadowSampler(
            fleet_dir,
            sample_rate=fcfg.flywheel_sample_rate,
            max_inflight=fcfg.flywheel_max_inflight,
            backend=backend,
        )
    return router


class _RouterHandler(BaseHTTPRequestHandler):
    router: Router = None  # bound by make_router_server

    def log_message(self, fmt, *args):
        logger.debug("router http: " + fmt, *args)

    def _reply(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_raw(
        self, status: int, body: bytes,
        content_type: str = "application/json",
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        url = urllib.parse.urlsplit(self.path)
        if url.path == "/healthz":
            self._reply(200, self.router.topology())
        elif url.path == "/stats":
            out = self.router.topology()
            out["slo"] = self.router.slo.snapshot()
            snap = obs_metrics.REGISTRY.snapshot()
            out["fleet"] = {
                k[len("fleet/"):]: v
                for k, v in snap.items() if k.startswith("fleet/")
            }
            if self.router.aggregator is not None:
                out["fleet_telemetry"] = (
                    self.router.aggregator.stats_section()
                )
            if self.router.alerts is not None:
                out["alerts"] = self.router.alerts.snapshot()
            self._reply(200, out)
        elif url.path == "/metrics":
            text = registry_exposition() + self.router.slo.exposition()
            if self.router.aggregator is not None:
                # the fleet half: per-replica families labeled
                # replica="<id>" plus the exactly-merged replica="fleet"
                # series from the published snapshots
                text += self.router.aggregator.exposition()
            self._reply_raw(
                200, text.encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802
        if self.path != "/score":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        router = self.router
        rid = self.headers.get("X-Request-Id") or new_request_id()
        t0 = time.monotonic()
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n) or b"{}"
            payload = json.loads(body)
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, KeyError) as e:
            router.log_request(
                rid, 400, time.monotonic() - t0, tenant="unknown",
                priority=fleet_admission.BATCH, shed_reason="bad_request",
            )
            self._reply(400, {
                "error": f"bad request: {e}", "request_id": rid,
            })
            return
        tenant = (
            self.headers.get("X-Tenant")
            or payload.get("tenant") or "default"
        )
        deadline_ms = self.headers.get("X-Deadline-Ms")
        if deadline_ms is None:
            deadline_ms = payload.get("deadline_ms")
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
            except (TypeError, ValueError):
                deadline_ms = None
        priority = payload.get("priority")
        if priority is not None:
            try:
                priority = int(priority)
            except (TypeError, ValueError):
                priority = None
        # stage-2 escalations mark themselves so the admission layer
        # can shed them before stage-1 screens (docs/cascade.md)
        cascade_stage = payload.get("cascade_stage")
        if cascade_stage is not None:
            try:
                cascade_stage = int(cascade_stage)
            except (TypeError, ValueError):
                cascade_stage = None
        router.poll()
        decision = router.admission.decide(
            str(tenant),
            outstanding=router.outstanding_total(),
            healthy=router.routable_count(),
            deadline_ms=deadline_ms,
            priority=priority,
            cascade_stage=cascade_stage,
        )
        if not decision.admit:
            # shed BEFORE any forward: no frontend or device time spent
            router.log_request(
                rid, decision.status, time.monotonic() - t0,
                tenant=decision.tenant, priority=decision.priority,
                deadline_ms=deadline_ms, shed_reason=decision.reason,
            )
            self._reply(decision.status, {
                "error": f"shed: {decision.reason}",
                "reason": decision.reason,
                "request_id": rid,
                "estimated_wait_ms": decision.estimated_wait_ms,
            })
            return
        try:
            status, data, replica, retries = router.forward(body, rid)
        except NoReplicaAvailable as e:
            router.log_request(
                rid, 503, time.monotonic() - t0,
                tenant=decision.tenant, priority=decision.priority,
                deadline_ms=deadline_ms, shed_reason="no_replicas",
            )
            self._reply(503, {"error": str(e), "request_id": rid})
            return
        prob = None
        if (
            router.alerts is not None or router.flywheel is not None
        ) and status == 200:
            # the drift watch and the shadow sampler need the replica's
            # calibrated score; the parse is gated on both consumers so
            # the default path never decodes response bodies it would
            # otherwise just relay
            try:
                scored = json.loads(data)
                if isinstance(scored, dict):
                    p = scored.get("calibrated_prob", scored.get("prob"))
                    if isinstance(p, (int, float)):
                        prob = float(p)
            except (ValueError, UnicodeDecodeError):
                pass
        router.log_request(
            rid, status, time.monotonic() - t0,
            tenant=decision.tenant, priority=decision.priority,
            replica=replica, retries=retries, deadline_ms=deadline_ms,
            prob=prob,
        )
        if router.flywheel is not None and status == 200:
            # mirror-sample the request for the shadow candidate
            # (flywheel/shadow.py): deterministic every-kth, bounded by
            # the scorer's acknowledged progress — never blocks, never
            # changes the reply
            router.flywheel.observe(
                rid, payload, prob, tenant=decision.tenant,
            )
        self._reply_raw(status, data)


def make_router_server(
    router: Router, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """Bound (not yet serving) router HTTP server; port 0 = ephemeral
    (server.server_address[1] has the real one)."""
    handler = type("BoundRouterHandler", (_RouterHandler,), {
        "router": router,
    })
    return ThreadingHTTPServer((host, port), handler)


class BackgroundRouter:
    """In-process router on an ephemeral port (smoke mode + tests)."""

    def __init__(self, router: Router, host: str = "127.0.0.1"):
        self.router = router
        router.start_polling()
        self.httpd = make_router_server(router, host, 0)
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def request(
        self, method: str, path: str, payload: dict | None = None,
        headers: dict | None = None,
    ):
        status, raw = self.request_text(method, path, payload, headers)
        return status, json.loads(raw or "{}")

    def request_text(
        self, method: str, path: str, payload: dict | None = None,
        headers: dict | None = None,
    ):
        conn = http.client.HTTPConnection(self.host, self.port, timeout=120)
        body = json.dumps(payload) if payload is not None else None
        hdrs = dict(headers or {})
        if body:
            hdrs.setdefault("Content-Type", "application/json")
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read().decode("utf-8", "replace")
        conn.close()
        return resp.status, data

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=10)
        self.router.close()


# ---------------------------------------------------------------------------
# fleet log validation (scripts/check_obs_schema.py --fleet-log)


def validate_fleet_log(path: str | Path) -> dict:
    """Structural + schema validation of a router fleet_log.jsonl.

    The legal line shapes: {"request": {...}} per-request entries
    (id + status required), {"fleet_event": {...}} lifecycle events
    (declared name + t_unix required, incl. the HA takeover/stepdown and
    quarantine transitions), {"rollout": {...}} rollout records
    (fleet/rollout.py; declared event + t_unix + checkpoint required),
    {"autoscale": {...}} autoscaling decisions (fleet/autoscale.py;
    declared action + t_unix required), the data-flywheel records
    (docs/flywheel.md): {"shadow": {...}} windowed candidate-vs-
    incumbent comparisons (declared event + t_unix + candidate
    required), {"promotion": {...}} auto-promotions (candidate +
    t_unix required), {"demotion": {...}} refused candidates (declared
    reason + candidate + t_unix required), and summary records
    embedding the fleet/* registry snapshot + fleet_slo windows + the
    admission re-seed snapshot. Every flattened scalar tag must be
    declared in obs/metrics.py:SCHEMA — the same drift guard the
    train/serve/scan logs get."""
    path = Path(path)
    problems: list[str] = []
    records: list[dict] = []
    try:
        lines = path.read_text().splitlines()
    except OSError as e:
        return {"ok": False, "problems": [f"unreadable: {e}"]}
    n_requests = n_events = n_summaries = n_rollouts = n_autoscale = 0
    n_alerts = n_shadow = n_promotions = n_demotions = 0
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            problems.append(f"line {lineno}: not JSON ({e})")
            continue
        if not isinstance(rec, dict):
            problems.append(f"line {lineno}: not an object")
            continue
        records.append(rec)
        if "request" in rec:
            n_requests += 1
            req = rec["request"]
            if not isinstance(req, dict) or not all(
                k in req for k in ("id", "status")
            ):
                problems.append(
                    f"line {lineno}: request entry missing id/status"
                )
        elif "fleet_event" in rec:
            n_events += 1
            ev = rec["fleet_event"]
            if not isinstance(ev, dict):
                problems.append(f"line {lineno}: fleet_event not an object")
            elif ev.get("name") not in EVENTS:
                problems.append(
                    f"line {lineno}: fleet_event name {ev.get('name')!r} "
                    f"not in declared set {EVENTS}"
                )
            elif "t_unix" not in ev:
                problems.append(
                    f"line {lineno}: fleet_event missing t_unix"
                )
        elif "rollout" in rec:
            n_rollouts += 1
            ro = rec["rollout"]
            if not isinstance(ro, dict):
                problems.append(f"line {lineno}: rollout not an object")
            elif ro.get("event") not in ROLLOUT_EVENTS:
                problems.append(
                    f"line {lineno}: rollout event {ro.get('event')!r} "
                    f"not in declared set {ROLLOUT_EVENTS}"
                )
            elif "t_unix" not in ro or "checkpoint" not in ro:
                problems.append(
                    f"line {lineno}: rollout record missing "
                    f"t_unix/checkpoint"
                )
        elif "autoscale" in rec:
            n_autoscale += 1
            a = rec["autoscale"]
            if not isinstance(a, dict):
                problems.append(f"line {lineno}: autoscale not an object")
            elif a.get("action") not in AUTOSCALE_ACTIONS:
                problems.append(
                    f"line {lineno}: autoscale action {a.get('action')!r} "
                    f"not in declared set {AUTOSCALE_ACTIONS}"
                )
            elif "t_unix" not in a:
                problems.append(
                    f"line {lineno}: autoscale record missing t_unix"
                )
        elif "alert" in rec:
            n_alerts += 1
            from deepdfa_tpu.obs.alerts import validate_alert_record

            for p in validate_alert_record(rec):
                problems.append(f"line {lineno}: {p}")
        elif "shadow" in rec:
            n_shadow += 1
            sh = rec["shadow"]
            if not isinstance(sh, dict):
                problems.append(f"line {lineno}: shadow not an object")
            elif sh.get("event") not in SHADOW_EVENTS:
                problems.append(
                    f"line {lineno}: shadow event {sh.get('event')!r} "
                    f"not in declared set {SHADOW_EVENTS}"
                )
            elif "t_unix" not in sh or "candidate" not in sh:
                problems.append(
                    f"line {lineno}: shadow record missing "
                    f"t_unix/candidate"
                )
        elif "promotion" in rec:
            n_promotions += 1
            pr = rec["promotion"]
            if not isinstance(pr, dict):
                problems.append(f"line {lineno}: promotion not an object")
            elif "t_unix" not in pr or "candidate" not in pr:
                problems.append(
                    f"line {lineno}: promotion record missing "
                    f"t_unix/candidate"
                )
        elif "demotion" in rec:
            n_demotions += 1
            dm = rec["demotion"]
            if not isinstance(dm, dict):
                problems.append(f"line {lineno}: demotion not an object")
            elif dm.get("reason") not in DEMOTION_REASONS:
                problems.append(
                    f"line {lineno}: demotion reason {dm.get('reason')!r} "
                    f"not in declared set {DEMOTION_REASONS}"
                )
            elif "t_unix" not in dm or "candidate" not in dm:
                problems.append(
                    f"line {lineno}: demotion record missing "
                    f"t_unix/candidate"
                )
        elif "fleet" in rec or "fleet_slo" in rec:
            n_summaries += 1
        else:
            problems.append(
                f"line {lineno}: unknown record shape "
                f"(keys {sorted(rec)[:5]})"
            )
    undeclared = obs_metrics.undeclared_tags(records)
    for tag in undeclared:
        problems.append(f"undeclared metrics tag: {tag}")
    return {
        "ok": not problems,
        "records": len(records),
        "requests": n_requests,
        "events": n_events,
        "summaries": n_summaries,
        "rollouts": n_rollouts,
        "autoscale": n_autoscale,
        "alerts": n_alerts,
        "shadow": n_shadow,
        "promotions": n_promotions,
        "demotions": n_demotions,
        "undeclared": undeclared,
        "problems": problems,
    }
