"""Predictive fleet autoscaling (docs/fleet.md).

The fleet already has every signal a scaler needs — the router's
fleet_log records one `{"request": ...}` line per ingress arrival, the
admission controller knows per-replica capacity, and `plan_replicas`
knows how many serving stacks the host's HBM budget fits. This module
closes the loop PREDICTIVELY: replay the log's arrival process into
per-bucket offered rates (the `tune/ladder.py` replay idiom: parse
lines, skip what does not parse), forecast the near-term rate by
extrapolating the recent trend, and drive a hysteresis/cooldown
controller whose degradation ladder acts AHEAD of the predicted load:

  stage 1  shed_stage2        tighten `cascade_shed_fraction` — stage-2
                              cascade escalations shed first (they
                              already hold a stage-1 answer)
  stage 2  tighten_admission  tighten `shed_fraction` — priority>0
                              traffic sheds earlier
  stage 3  scale_up           one more replica (cooldown-gated, capped
                              by `fleet.autoscale_max_replicas` AND the
                              `plan_replicas` HBM-budget cap)

and symmetrically `relax` then `scale_down` when the forecast falls
below the low-water fraction. Every decision — including holds — is a
`{"autoscale": {...}}` record in the shared fleet_log, validated by
`validate_fleet_log` against the declared action vocabulary
(`fleet/router.py:AUTOSCALE_ACTIONS`).
"""

from __future__ import annotations

import logging
import math
import time
from pathlib import Path

from deepdfa_tpu.fleet import admission as fleet_admission
from deepdfa_tpu.fleet.router import AUTOSCALE_ACTIONS
from deepdfa_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

#: how much log tail the arrival replay scans (the reseed convention)
REPLAY_TAIL_BYTES = 4 << 20


# ---------------------------------------------------------------------------
# arrival replay + forecast


def arrival_rates_from_log(
    path: str | Path,
    bucket_s: float = 1.0,
    backend=None,
    max_bytes: int = REPLAY_TAIL_BYTES,
) -> list[tuple[float, float]]:
    """The log's offered-rate series: [(bucket_start_unix, req/s)],
    bucketed over every `{"request": ...}` record's `t_unix`, gaps
    filled with 0.0 (an idle minute is a real observation, not missing
    data). Rides the backend's torn-tolerant tail — a truncated final
    line costs one arrival, never the replay."""
    from deepdfa_tpu.fleet import coord

    bucket_s = max(1e-6, float(bucket_s))
    try:
        records = (backend or coord.LOCAL).tail_records(path, max_bytes)
    except OSError:
        return []
    counts: dict[int, int] = {}
    for rec in records:
        req = rec.get("request")
        if not isinstance(req, dict):
            continue
        t = req.get("t_unix")
        if not isinstance(t, (int, float)):
            continue
        counts[int(math.floor(float(t) / bucket_s))] = counts.get(
            int(math.floor(float(t) / bucket_s)), 0
        ) + 1
    if not counts:
        return []
    lo, hi = min(counts), max(counts)
    return [
        (k * bucket_s, counts.get(k, 0) / bucket_s)
        for k in range(lo, hi + 1)
    ]


def forecast_rate(
    history: list[tuple[float, float]],
    horizon_s: float,
    window: int = 8,
) -> float:
    """The offered rate `horizon_s` from the last observation: a
    least-squares trend over the last `window` buckets, extrapolated
    forward and clamped at zero. With one bucket (or a degenerate
    window) the forecast IS the last rate — no trend, no extrapolation."""
    if not history:
        return 0.0
    pts = history[-max(2, int(window)):]
    t_last, r_last = pts[-1]
    if len(pts) < 2:
        return max(0.0, float(r_last))
    ts = [t for t, _ in pts]
    rs = [r for _, r in pts]
    t_mean = sum(ts) / len(ts)
    r_mean = sum(rs) / len(rs)
    var = sum((t - t_mean) ** 2 for t in ts)
    if var <= 0:
        return max(0.0, float(r_last))
    slope = sum(
        (t - t_mean) * (r - r_mean) for t, r in zip(ts, rs)
    ) / var
    return max(0.0, float(r_last) + slope * float(horizon_s))


# ---------------------------------------------------------------------------
# the controller


def max_replicas_from_ledger(
    cfg_max: int,
    entry_bytes: dict[str, float] | None,
    hbm_budget_bytes: float,
) -> tuple[int, dict]:
    """The effective scale-up ceiling: the configured max, capped by how
    many full serving stacks the HBM budget actually fits
    (`plan_replicas` over the per-entry param-bytes ledger signal).
    Unbudgeted or unmeasured hosts keep the configured max."""
    n, plan = fleet_admission.plan_replicas(
        entry_bytes or {}, hbm_budget_bytes, default=int(cfg_max)
    )
    return max(1, min(int(cfg_max), n)), plan


class AutoscaleController:
    """Hysteresis/cooldown controller over the forecast-to-capacity
    ratio. One `decide()` per arrival bucket:

      ratio >= up_fraction    escalate ONE rung per bucket —
                              shed_stage2, then tighten_admission, then
                              scale_up (cooldown-gated, bounded by
                              max_replicas)
      ratio <= down_fraction  de-escalate — relax the admission ladder
                              first, then scale_down (cooldown-gated,
                              bounded by min_replicas)
      in between              hold (hysteresis: the band between the
                              fractions is deliberately dead)

    The one-rung-per-bucket ladder is the point: under a rising
    forecast the fleet degrades REVERSIBLY (shed escalations, tighten
    admission) before it pays for a replica, and the forecast's lead
    time (`horizon_s` ahead) means the replica lands before the load
    does. `clock` is injectable; the replay passes bucket timestamps so
    cooldown behaves identically live and in tests."""

    def __init__(
        self,
        capacity_rps: float,
        up_fraction: float = 0.8,
        down_fraction: float = 0.3,
        cooldown_s: float = 10.0,
        min_replicas: int = 1,
        max_replicas: int = 4,
        horizon_s: float = 5.0,
        bucket_s: float = 1.0,
        clock=time.monotonic,
    ):
        if capacity_rps <= 0:
            raise ValueError(f"capacity_rps must be >0, got {capacity_rps}")
        if not 0.0 <= down_fraction < up_fraction:
            raise ValueError(
                f"need 0 <= down_fraction < up_fraction, got "
                f"{down_fraction} / {up_fraction}"
            )
        self.capacity_rps = float(capacity_rps)
        self.up_fraction = float(up_fraction)
        self.down_fraction = float(down_fraction)
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.horizon_s = float(horizon_s)
        self.bucket_s = float(bucket_s)
        self.clock = clock
        #: admission-ladder stage: 0 none, 1 shed_stage2 applied,
        #: 2 tighten_admission applied
        self.stage = 0
        self._last_scale_t: float | None = None
        self._orig: tuple[float, float] | None = None

    @classmethod
    def from_config(
        cls,
        cfg,
        capacity_rps: float,
        entry_bytes: dict[str, float] | None = None,
        hbm_budget_bytes: float = 0.0,
    ) -> "AutoscaleController":
        fcfg = cfg.fleet
        cap, _ = max_replicas_from_ledger(
            fcfg.autoscale_max_replicas, entry_bytes, hbm_budget_bytes
        )
        return cls(
            capacity_rps=capacity_rps,
            up_fraction=fcfg.autoscale_up_fraction,
            down_fraction=fcfg.autoscale_down_fraction,
            cooldown_s=fcfg.autoscale_cooldown_s,
            min_replicas=fcfg.autoscale_min_replicas,
            max_replicas=cap,
            horizon_s=fcfg.autoscale_horizon_s,
            bucket_s=fcfg.autoscale_bucket_s,
        )

    def _cooldown_ok(self, now: float) -> bool:
        return (
            self._last_scale_t is None
            or now - self._last_scale_t >= self.cooldown_s
        )

    def decide(
        self, forecast_rps: float, replicas: int, now: float | None = None
    ) -> dict:
        """One ladder step against the forecast; returns the decision
        the caller applies (`apply_to` for admission rungs, its own
        spawn/retire for the scale rungs) and logs verbatim."""
        now = self.clock() if now is None else float(now)
        replicas = max(1, int(replicas))
        capacity = self.capacity_rps * replicas
        ratio = float(forecast_rps) / capacity
        action, reason, target = "hold", "in_band", replicas
        if ratio >= self.up_fraction:
            if self.stage == 0:
                action, reason = "shed_stage2", "ladder_stage_1"
                self.stage = 1
            elif self.stage == 1:
                action, reason = "tighten_admission", "ladder_stage_2"
                self.stage = 2
            elif replicas >= self.max_replicas:
                reason = "at_max_replicas"
            elif not self._cooldown_ok(now):
                reason = "cooldown"
            else:
                action, reason = "scale_up", "forecast_over_high_water"
                target = replicas + 1
                self._last_scale_t = now
        elif ratio <= self.down_fraction:
            if self.stage > 0:
                action, reason = "relax", "ladder_unwind"
                self.stage = 0
            elif replicas <= self.min_replicas:
                reason = "at_min_replicas"
            elif not self._cooldown_ok(now):
                reason = "cooldown"
            else:
                action, reason = "scale_down", "forecast_under_low_water"
                target = replicas - 1
                self._last_scale_t = now
        assert action in AUTOSCALE_ACTIONS, action
        obs_metrics.REGISTRY.counter("autoscale/decisions").inc()
        obs_metrics.REGISTRY.counter(f"autoscale/{action}").inc()
        return {
            "action": action,
            "reason": reason,
            "t_unix": round(time.time(), 3),
            "decided_at": round(now, 3),
            "forecast_rps": round(float(forecast_rps), 3),
            "capacity_rps": round(capacity, 3),
            "ratio": round(ratio, 4),
            "replicas": replicas,
            "target_replicas": target,
            "stage": self.stage,
        }

    def apply_to(self, admission, decision: dict) -> None:
        """Apply an admission-ladder rung to a live
        `AdmissionController` by mutating its shed fractions; `relax`
        restores the values observed on first application. The scale
        rungs are the CALLER's to execute (spawn/retire a replica) —
        this method only ever touches admission policy."""
        if self._orig is None:
            self._orig = (
                float(admission.shed_fraction),
                float(admission.cascade_shed_fraction),
            )
        action = decision["action"]
        if action == "shed_stage2":
            admission.cascade_shed_fraction = min(
                self._orig[1], 0.5 * self._orig[1]
            )
        elif action == "tighten_admission":
            admission.shed_fraction = min(
                self._orig[0], 0.8 * self._orig[0]
            )
        elif action == "relax":
            admission.shed_fraction = self._orig[0]
            admission.cascade_shed_fraction = self._orig[1]

    @staticmethod
    def log_record(decision: dict) -> dict:
        """The fleet_log line for one decision (the shape
        `validate_fleet_log`'s autoscale branch checks)."""
        return {"autoscale": dict(decision)}


def replay(
    rates: list[tuple[float, float]],
    controller: AutoscaleController,
    replicas: int = 1,
    on_decision=None,
) -> list[dict]:
    """Drive the controller over an offered-rate series (the
    `arrival_rates_from_log` output): one forecast + one decision per
    bucket, the replica count tracking the controller's own scale
    decisions. `on_decision(decision)` fires for every bucket — the
    smoke uses it to spawn the real second replica the moment the
    controller asks, the CLI to append log records."""
    decisions: list[dict] = []
    history: list[tuple[float, float]] = []
    for t, rate in rates:
        history.append((float(t), float(rate)))
        forecast = forecast_rate(history, controller.horizon_s)
        decision = controller.decide(forecast, replicas, now=float(t))
        decision["bucket_t"] = float(t)
        decision["offered_rps"] = round(float(rate), 3)
        if decision["action"] in ("scale_up", "scale_down"):
            replicas = int(decision["target_replicas"])
        if on_decision is not None:
            on_decision(decision)
        decisions.append(decision)
    return decisions


# ---------------------------------------------------------------------------
# the smoke: scale 1 -> 2 AHEAD of a replayed ramp, zero requests lost


def run_smoke_autoscale(tmp: str | Path, parts=None) -> dict:
    """The `fleet --smoke` autoscale phase (<60 s, in-process):

    1. bring up ONE stub replica behind a real router and MEASURE its
       capacity from observed service latency;
    2. synthesize a ramp fleet_log whose offered rate climbs from
       0.2x to 1.3x that capacity;
    3. replay it through the controller — the ladder must escalate
       shed_stage2 -> tighten_admission -> scale_up, with the scale_up
       landing while the offered rate is still BELOW capacity (the
       forecast's lead time is the whole point);
    4. spawn the second stub replica the moment the controller asks,
       then drive a real burst through the router with ZERO requests
       lost;
    5. append every decision to the router's fleet_log and validate it.

    `parts` is an optional pre-built `chaos.build_stub_parts` tuple so
    a caller running several smoke phases pays for the stub model
    once.
    """
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.fleet import chaos as fleet_chaos, coord
    from deepdfa_tpu.fleet.router import (
        BackgroundRouter,
        FleetLog,
        router_from_config,
        validate_fleet_log,
    )

    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
        "serve.max_batch_graphs=1",
        "serve.node_budget=2048", "serve.edge_budget=8192",
        "serve.slo_windows=[5, 60]",
        "fleet.heartbeat_timeout_s=3600.0",
        "fleet.poll_interval_s=0.1",
        "fleet.request_timeout_s=5.0",
        "fleet.summary_interval_s=0.2",
        "fleet.autoscale=true",
    ])
    fcfg = cfg.fleet
    model, params, vocabs, codes = (
        parts if parts is not None else fleet_chaos.build_stub_parts(cfg)
    )
    fleet_dir = Path(tmp) / "autoscale"
    log_path = fleet_dir / "fleet_log.jsonl"
    replicas = [
        fleet_chaos.StubReplicaServer(
            cfg, fleet_dir, "r0",
            fleet_chaos.stub_service(
                cfg, fleet_dir, "r0", model, params, vocabs
            ),
        )
    ]
    router = router_from_config(cfg, fleet_dir, log_path=log_path)
    server = BackgroundRouter(router)
    out: dict = {}
    try:
        # -- measure per-replica capacity from real service latency
        lat: list[float] = []
        for code in (codes * 2)[:6]:
            t0 = time.monotonic()
            status, resp = server.request(
                "POST", "/score", {"code": code}
            )
            assert status == 200, (status, resp)
            lat.append(time.monotonic() - t0)
        measured_rps = 1.0 / max(1e-3, sum(lat) / len(lat))
        # clamp the ramp's capacity so the synthetic log stays small on
        # fast hosts; the controller and the ramp share the SAME number,
        # so "scaled ahead of capacity" means what it says
        capacity_rps = min(50.0, measured_rps)
        out["measured_rps"] = round(measured_rps, 2)
        out["capacity_rps"] = round(capacity_rps, 2)

        # -- synthesize the ramp: 0.2x -> 1.3x capacity, one bucket per
        # step, timestamps safely in the past so the replay window is
        # disjoint from live traffic
        bucket_s = float(fcfg.autoscale_bucket_s)
        fractions = [0.2 + 0.1 * i for i in range(12)]
        base = math.floor(time.time() - 120.0)
        ramp_path = fleet_dir / "ramp_log.jsonl"
        ramp_log = FleetLog(ramp_path)
        try:
            for k, frac in enumerate(fractions):
                n = max(1, round(frac * capacity_rps * bucket_s))
                for j in range(n):
                    ramp_log.append({"request": {
                        "id": f"ramp-{k}-{j}", "status": 200,
                        "latency_ms": round(1e3 / measured_rps, 3),
                        "t_unix": round(
                            base + k * bucket_s + j * bucket_s / n, 3
                        ),
                        "tenant": "ramp", "priority": 1,
                        "retries": 0, "shed": 0,
                    }})
        finally:
            ramp_log.close()
        rates = arrival_rates_from_log(ramp_path, bucket_s)
        assert len(rates) == len(fractions), (len(rates), len(fractions))

        # -- replay through the controller; the second REAL replica
        # spawns the moment the controller decides scale_up
        cap_n, plan = max_replicas_from_ledger(
            fcfg.autoscale_max_replicas,
            {"deepdfa": 1.0}, 0.0,  # unbudgeted stub host: cfg max rules
        )
        controller = AutoscaleController(
            capacity_rps=capacity_rps,
            up_fraction=fcfg.autoscale_up_fraction,
            down_fraction=fcfg.autoscale_down_fraction,
            cooldown_s=fcfg.autoscale_cooldown_s,
            min_replicas=fcfg.autoscale_min_replicas,
            max_replicas=cap_n,
            horizon_s=fcfg.autoscale_horizon_s,
            bucket_s=bucket_s,
        )
        out["max_replicas"] = cap_n
        out["plan_reason"] = plan.get("reason")

        def _on_decision(decision: dict) -> None:
            controller.apply_to(router.admission, decision)
            router.log.append(AutoscaleController.log_record(decision))
            if decision["action"] == "scale_up" and len(replicas) == 1:
                replicas.append(fleet_chaos.StubReplicaServer(
                    cfg, fleet_dir, "r1",
                    fleet_chaos.stub_service(
                        cfg, fleet_dir, "r1", model, params, vocabs
                    ),
                ))

        decisions = replay(
            rates, controller, replicas=1, on_decision=_on_decision
        )
        actions = [d["action"] for d in decisions]
        out["actions"] = actions
        scale_idx = actions.index("scale_up") if "scale_up" in actions else None
        out["scaled"] = scale_idx is not None
        if scale_idx is not None:
            rate_at_scale = decisions[scale_idx]["offered_rps"]
            peak = max(r for _, r in rates)
            out["rate_at_scale_rps"] = rate_at_scale
            out["peak_rps"] = round(peak, 2)
            out["scaled_ahead"] = (
                rate_at_scale < capacity_rps < peak
            )
            out["ladder_before_scale"] = [
                a for a in actions[:scale_idx]
                if a in ("shed_stage2", "tighten_admission")
            ] == ["shed_stage2", "tighten_admission"]
        else:
            out["scaled_ahead"] = False
            out["ladder_before_scale"] = False

        # -- the scaled fleet serves a real burst, nothing lost
        assert len(replicas) == 2, "second replica never spawned"
        routable = coord.poll_until(
            lambda: (router.topology()["routable"] >= 2) or None,
            20.0, interval_s=0.1, max_interval_s=0.5,
            what="autoscaled replica routable",
        )
        burst = []
        for code in (codes * 4)[:20]:
            status, _ = server.request("POST", "/score", {"code": code})
            burst.append(status)
        out["burst"] = {
            "total": len(burst),
            "lost": sum(1 for s in burst if s != 200),
            "routable_replicas": router.topology()["routable"],
            "second_replica_routable": bool(routable),
        }

        server.close()  # appends the final summary record
        server = None
        out["fleet_log"] = {
            k: v for k, v in validate_fleet_log(log_path).items()
            if k in ("ok", "records", "autoscale", "problems")
        }
        out["ramp_log_ok"] = validate_fleet_log(ramp_path)["ok"]
        out["ok"] = bool(
            out["scaled"]
            and out["scaled_ahead"]
            and out["ladder_before_scale"]
            and out["burst"]["lost"] == 0
            and out["fleet_log"]["ok"]
            and out["fleet_log"].get("autoscale", 0) >= len(decisions)
            and out["ramp_log_ok"]
        )
    finally:
        if server is not None:
            server.close()
        for r in replicas:
            r.close()
    return out
