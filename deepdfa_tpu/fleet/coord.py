"""Pluggable fleet coordination backend (docs/fleet.md).

Every piece of shared fleet state — replica heartbeats, the router
rendezvous, the append-only fleet_log — used to reach the filesystem
through ad-hoc `atomic_write_text` / `read_text` / `glob` calls inlined
across `fleet/heartbeat.py`, `fleet/ha.py`, `fleet/router.py`, and
`fleet/rollout.py`. This module extracts that protocol behind one
interface so the HA pair, the chaos drills, and a future off-box
control plane all speak the same contract:

  CoordinationBackend   the interface: atomic document write/read +
                        directory scan (heartbeats), rendezvous publish
                        with EPOCH FENCING (router.json), append/tail
                        (fleet_log). Fencing lives HERE, not in the
                        caller: `publish_rendezvous` refuses a publish
                        superseded by a higher epoch (or an equal-epoch
                        lexically-smaller router id) and hands back the
                        winning record, so the active/standby pair works
                        unchanged over any backend that honors the
                        contract.
  LocalDirBackend       the default: today's byte-identical atomic-file
                        protocol (core/ioutil.py tmp+fsync+rename), with
                        every op behind the shared bounded retry.
  FaultableBackend      a wrapper injecting per-path latency, stale
                        reads, torn/lost writes, and partitions — the
                        chaos drills' storage-level fault surface. The
                        faults are observable ONLY through this wrapper;
                        the inner backend's files stay whatever the
                        surviving writes made them.

`poll_until` is the one shared bounded poll/retry helper (deadline-
aware, exponential backoff with jitter, logged + counted on
exhaustion) replacing the ad-hoc `time.sleep` loops that used to live
in `ha.resolve_router`, `replica.wait_for_ready`,
`replica._wait_queue_drain`, and the smoke's drain wait.
"""

from __future__ import annotations

import fnmatch
import json
import logging
import random
import threading
import time
from pathlib import Path

from deepdfa_tpu.core import ioutil
from deepdfa_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

#: the rendezvous document name under a fleet dir (fleet/ha.py re-exports)
ROUTER_FILE = "router.json"


# ---------------------------------------------------------------------------
# the one shared bounded poll helper


def poll_until(
    predicate,
    timeout_s: float,
    *,
    interval_s: float = 0.05,
    max_interval_s: float = 0.5,
    jitter: float = 0.25,
    what: str = "condition",
    clock=time.monotonic,
    sleep=time.sleep,
):
    """Poll `predicate` until it returns a truthy value (returned) or
    `timeout_s` elapses (returns None, logged + counted — exhaustion is
    never silent).

    The wait between attempts starts at `interval_s` and doubles up to
    `max_interval_s`, each sleep randomized by ±`jitter` so N pollers
    watching one file do not synchronize into a thundering herd. The
    predicate always runs at least once (timeout_s=0 is "check now"),
    and exceptions it raises propagate — a predicate that can tell the
    waited-for thing DIED should raise rather than keep polling."""
    deadline = clock() + max(0.0, float(timeout_s))
    attempt = 0
    while True:
        value = predicate()
        if value:
            return value
        now = clock()
        if now >= deadline:
            obs_metrics.REGISTRY.counter("coord/poll_exhausted").inc()
            logger.warning(
                "poll for %s exhausted after %.3fs (%d attempt(s))",
                what, float(timeout_s), attempt + 1,
            )
            return None
        delay = min(interval_s * (2 ** attempt), max_interval_s)
        if jitter > 0:
            delay *= 1.0 + random.uniform(-jitter, jitter)
        sleep(max(0.0, min(delay, deadline - now)))
        attempt += 1


def _retry(fn, what: str):
    """Every coordination op rides the one bounded retry (transient
    host I/O blips must not look like a dead peer); deterministic
    absence (FileNotFoundError) propagates immediately."""
    return ioutil.with_retries(fn, retries=2, backoff_s=0.05, what=what)


# ---------------------------------------------------------------------------
# the backend contract


class CoordinationBackend:
    """Atomic write/read/scan for heartbeats, fenced rendezvous publish
    for router.json, append/tail for the fleet_log. Subclasses provide
    the storage primitives; the rendezvous protocol (including epoch
    fencing) and torn-line-tolerant tailing are shared here so every
    backend honors the same contract."""

    # -- storage primitives (subclass responsibility) ------------------------

    def write_doc(self, path: str | Path, text: str) -> None:
        """Atomically replace `path` with `text` (readers see the old or
        the new complete content, never a truncation)."""
        raise NotImplementedError

    def read_doc(self, path: str | Path) -> str:
        """The document's current content; raises OSError when absent."""
        raise NotImplementedError

    def scan(self, directory: str | Path, pattern: str) -> list[Path]:
        """Sorted paths under `directory` matching `pattern` ([] when
        the directory does not exist)."""
        raise NotImplementedError

    def open_log(self, path: str | Path):
        """An append handle (`write_line(text)`, `close()`, `.closed`)
        for a line-oriented log; each written line is flushed so the
        log is tail-able while being written."""
        raise NotImplementedError

    def tail(self, path: str | Path, max_bytes: int) -> list[str]:
        """The last <= `max_bytes` of the log, split into lines; raises
        OSError when absent. The first line may be torn by the seek and
        the last by a concurrent append — `tail_records` absorbs both."""
        raise NotImplementedError

    # -- shared protocol -----------------------------------------------------

    def tail_records(self, path: str | Path, max_bytes: int) -> list[dict]:
        """Parsed JSON records from the log tail, in file order. Torn or
        otherwise unparseable lines (the seek-split first line, a
        truncated final line from a crashed writer) are skipped, never
        fatal — a torn tail must cost one record, not the whole read."""
        records: list[dict] = []
        for line in self.tail(path, max_bytes):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
        return records

    def read_rendezvous(self, path: str | Path) -> dict | None:
        """The parsed rendezvous record, or None when absent, unreadable,
        or malformed (a torn or foreign file is never a crash)."""
        try:
            doc = json.loads(self.read_doc(path))
        except (OSError, json.JSONDecodeError):
            return None
        rv = doc.get("router") if isinstance(doc, dict) else None
        if not isinstance(rv, dict):
            return None
        required = ("router_id", "host", "port", "epoch", "t_unix")
        if any(k not in rv for k in required):
            return None
        return rv

    def publish_rendezvous(
        self,
        path: str | Path,
        router_id: str,
        host: str,
        port: int,
        epoch: int,
        force: bool = True,
    ) -> dict | None:
        """Publish the active router's rendezvous; returns None on
        success or the FENCING record when refused.

        The epoch-fence contract: with `force=False` (the active's
        periodic refresh) the publish is refused when the current record
        belongs to another router at a higher epoch, or at an equal
        epoch with a lexically smaller router id (the deterministic
        equal-epoch tie-break) — the superseded router must step down,
        never fight. `force=True` (a takeover publishing epoch+1, or a
        fresh bring-up) writes unconditionally; epochs only grow because
        every takeover derives its epoch from the record it replaces."""
        if not force:
            rv = self.read_rendezvous(path)
            if rv is not None and str(rv["router_id"]) != str(router_id) and (
                int(rv["epoch"]) > int(epoch)
                or (int(rv["epoch"]) == int(epoch)
                    and str(rv["router_id"]) < str(router_id))
            ):
                obs_metrics.REGISTRY.counter("coord/fenced_publishes").inc()
                return rv
        self.write_doc(path, json.dumps({"router": {
            "router_id": str(router_id),
            "host": str(host),
            "port": int(port),
            "epoch": int(epoch),
            "t_unix": round(time.time(), 3),
        }}))
        return None


# ---------------------------------------------------------------------------
# default backend: today's atomic-file protocol, byte-identical


class _LocalLogHandle:
    """One append handle over a real file (the FleetLog rule: one
    handle, flushed per line, tail-able while serving)."""

    def __init__(self, path: Path):
        path.parent.mkdir(parents=True, exist_ok=True)
        self._file = path.open("a")

    @property
    def closed(self) -> bool:
        return self._file.closed

    def write_line(self, text: str) -> None:
        self._file.write(text + "\n")
        self._file.flush()

    def close(self) -> None:
        if not self._file.closed:
            self._file.close()


class LocalDirBackend(CoordinationBackend):
    """The default backend: the PR-11 atomic-file protocol over one
    shared directory, unchanged — same tmp+fsync+rename writes
    (core/ioutil.py), same glob scans, same append-and-flush log. The
    default fleet path's file layout stays byte-identical."""

    def write_doc(self, path: str | Path, text: str) -> None:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        _retry(
            lambda: ioutil.atomic_write_text(path, text),
            what=f"coord write {path.name}",
        )

    def read_doc(self, path: str | Path) -> str:
        path = Path(path)
        return _retry(path.read_text, what=f"coord read {path.name}")

    def scan(self, directory: str | Path, pattern: str) -> list[Path]:
        directory = Path(directory)
        if not directory.is_dir():
            return []
        return sorted(directory.glob(pattern))

    def open_log(self, path: str | Path):
        return _retry(
            lambda: _LocalLogHandle(Path(path)),
            what=f"coord open log {Path(path).name}",
        )

    def tail(self, path: str | Path, max_bytes: int) -> list[str]:
        def _read() -> list[str]:
            with Path(path).open("rb") as f:
                f.seek(0, 2)
                size = f.tell()
                f.seek(max(0, size - int(max_bytes)))
                return f.read().decode("utf-8", "replace").splitlines()

        return _retry(_read, what=f"coord tail {Path(path).name}")


# ---------------------------------------------------------------------------
# chaos wrapper: the drills' storage-level fault surface


class _FaultableLogHandle:
    def __init__(self, backend: "FaultableBackend", path: Path, inner):
        self._backend = backend
        self._path = path
        self._inner = inner

    @property
    def closed(self) -> bool:
        return self._inner.closed

    def write_line(self, text: str) -> None:
        fault = self._backend._check(self._path, "append")
        if fault is not None:
            if fault.take("lose_writes"):
                self._backend._count("lost_write")
                return
            if fault.take("torn_writes"):
                self._backend._count("torn_write")
                # a torn append: the line's prefix lands without the
                # newline — exactly a writer crashing mid-append. The
                # tail-record contract (skip unparseable lines) is what
                # keeps this survivable.
                self._inner.write_line(text[: max(1, len(text) // 2)])
                # the torn fragment has its newline from write_line; a
                # truncated final line without one needs the raw file
                return
        self._inner.write_line(text)

    def close(self) -> None:
        self._inner.close()


class _Fault:
    """One per-path-pattern fault spec with consumable counters."""

    def __init__(
        self,
        pattern: str,
        latency_s: float = 0.0,
        stale_reads: int = 0,
        lose_writes: int = 0,
        torn_writes: int = 0,
        partitioned: bool = False,
    ):
        self.pattern = str(pattern)
        self.latency_s = float(latency_s)
        self.stale_reads = int(stale_reads)
        self.lose_writes = int(lose_writes)
        self.torn_writes = int(torn_writes)
        self.partitioned = bool(partitioned)
        self._lock = threading.Lock()

    def matches(self, path: Path) -> bool:
        return fnmatch.fnmatch(path.name, self.pattern) or fnmatch.fnmatch(
            str(path), self.pattern
        )

    def take(self, counter: str) -> bool:
        """Consume one unit of a bounded fault (`stale_reads` etc.);
        False once exhausted."""
        with self._lock:
            n = getattr(self, counter)
            if n <= 0:
                return False
            setattr(self, counter, n - 1)
            return True


class FaultableBackend(CoordinationBackend):
    """A CoordinationBackend wrapper injecting storage faults per path
    pattern — the chaos drills' way of exercising the fleet against a
    misbehaving coordination substrate without touching the protocol
    code. Faults:

      latency_s     every matching op sleeps first (a slow store)
      stale_reads   the next N matching reads return the PREVIOUS
                    version this backend overwrote (a lagging replica
                    of the store)
      lose_writes   the next N matching writes are silently dropped
      torn_writes   the next N matching writes land NON-atomically
                    truncated (what `atomic_write_text` exists to
                    prevent — readers must survive it anyway)
      partitioned   matching ops raise OSError until cleared

    Every injection is counted under coord/* so a drill can assert the
    fault actually fired; none of them is observable through the plain
    LocalDirBackend."""

    def __init__(self, inner: CoordinationBackend | None = None):
        self.inner = inner or LocalDirBackend()
        self._faults: list[_Fault] = []
        self._prev: dict[str, str] = {}
        self._lock = threading.Lock()

    # -- fault programming ---------------------------------------------------

    def set_fault(self, pattern: str, **spec) -> _Fault:
        """Install one fault for paths matching `pattern` (fnmatch on
        the file name or the full path); later faults win ties."""
        fault = _Fault(pattern, **spec)
        with self._lock:
            self._faults.insert(0, fault)
        return fault

    def clear_faults(self) -> None:
        with self._lock:
            self._faults.clear()

    def _fault_for(self, path: Path) -> _Fault | None:
        with self._lock:
            for fault in self._faults:
                if fault.matches(path):
                    return fault
        return None

    def _count(self, kind: str) -> None:
        obs_metrics.REGISTRY.counter(f"coord/faults/{kind}").inc()

    def _check(self, path: Path, op: str) -> _Fault | None:
        """Latency + partition (the faults every op shares); returns the
        matched fault for op-specific injections."""
        fault = self._fault_for(path)
        if fault is None:
            return None
        if fault.latency_s > 0:
            self._count("latency")
            time.sleep(fault.latency_s)
        if fault.partitioned:
            self._count("partition")
            raise OSError(
                f"injected partition: {op} {path.name} unreachable"
            )
        return fault

    # -- faulted primitives --------------------------------------------------

    def write_doc(self, path: str | Path, text: str) -> None:
        path = Path(path)
        fault = self._check(path, "write")
        if fault is not None and fault.take("lose_writes"):
            self._count("lost_write")
            return
        # stash the version being replaced so a stale read can serve it
        try:
            with self._lock:
                self._prev[str(path)] = self.inner.read_doc(path)
        except OSError:
            pass
        if fault is not None and fault.take("torn_writes"):
            self._count("torn_write")
            # deliberately NON-atomic truncated write: the exact damage
            # the atomic protocol exists to prevent, injected below it
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(text[: max(1, len(text) // 2)])
            return
        self.inner.write_doc(path, text)

    def read_doc(self, path: str | Path) -> str:
        path = Path(path)
        fault = self._check(path, "read")
        if fault is not None and fault.take("stale_reads"):
            with self._lock:
                prev = self._prev.get(str(path))
            if prev is not None:
                self._count("stale_read")
                return prev
        return self.inner.read_doc(path)

    def scan(self, directory: str | Path, pattern: str) -> list[Path]:
        self._check(Path(directory), "scan")
        return self.inner.scan(directory, pattern)

    def open_log(self, path: str | Path):
        path = Path(path)
        self._check(path, "open")
        return _FaultableLogHandle(self, path, self.inner.open_log(path))

    def tail(self, path: str | Path, max_bytes: int) -> list[str]:
        self._check(Path(path), "tail")
        return self.inner.tail(path, max_bytes)


# ---------------------------------------------------------------------------
# construction

#: the process-wide default backend: the byte-identical local protocol
LOCAL = LocalDirBackend()

_BACKENDS = {
    "local": LocalDirBackend,
    "faultable": FaultableBackend,
}


def make_backend(name: str) -> CoordinationBackend:
    """One CoordinationBackend by registry name (`fleet.coord_backend`).
    Unknown names fail loudly — a typo must not silently fall back to a
    different coordination substrate."""
    try:
        factory = _BACKENDS[str(name)]
    except KeyError:
        raise ValueError(
            f"unknown fleet.coord_backend {name!r}; "
            f"in {sorted(_BACKENDS)}"
        ) from None
    return factory()


def backend_from_config(cfg) -> CoordinationBackend:
    """The configured backend; `local` (the default, and the default
    for configs predating the knob) returns the shared LOCAL instance
    so the default path allocates nothing new."""
    name = str(getattr(cfg.fleet, "coord_backend", "local"))
    if name == "local":
        return LOCAL
    return make_backend(name)
