"""Injectable fleet faults + the in-process chaos fixtures
(docs/fleet.md failure matrix; scripts/fault_inject.py executes it).

Three kinds of injectable failure, each landing at the exact layer the
real failure would:

  ChaosState        per-replica fault switchboard, driven by the
                    replica's `/admin/chaos` endpoint (gated by
                    `fleet.chaos`, never on by default): `wedge_s`
                    flips the health probe to 503 and stalls /score
                    past the router's forward timeout (the PR-6
                    "backend wedge" class — process alive, work stuck);
                    `latency_s` adds fixed scoring latency (the
                    slow-replica scenario — deadline shedding must
                    engage off the rising service-time EWMA).
  Router.transport_fault  the partition fault: a callable installed on
                    the router raising ConnectionError inside its HTTP
                    client (fleet/router.py:_maybe_inject_fault) — the
                    router->replica path drops while both processes
                    stay healthy, forwards AND readmit probes fail.
  corrupt heartbeat  no code needed: the harness writes a malformed
                    announcement file and the router's quarantine path
                    (fleet/heartbeat.py:scan_heartbeats_verbose) must
                    absorb it.

`StubRegistry` + `StubReplicaServer` are the in-process fleet: a
registry-shaped stub over freshly-initialized params behind the REAL
ScoringService + HTTP handler + heartbeat protocol — everything but the
checkpoint round trip, which `fleet --smoke` and the subprocess chaos
scenarios own. scripts/bench_load.py and the tier-1 chaos smoke both
build their fleets from here.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path


class ChaosState:
    """One replica's injected-fault switchboard (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._wedge_until = 0.0
        self._latency_s = 0.0
        self._latency_until = 0.0

    def apply(self, spec: dict, now: float | None = None) -> dict:
        """One `/admin/chaos` document -> the new state. Accepts
        {"wedge_s": x}, {"latency_s": x, "duration_s": d}, and
        {"clear": true}; unknown keys are rejected loudly."""
        now = time.monotonic() if now is None else now
        known = {"wedge_s", "latency_s", "duration_s", "clear"}
        unknown = sorted(set(spec) - known)
        if unknown:
            raise ValueError(f"unknown chaos keys {unknown}; in {sorted(known)}")
        with self._lock:
            if spec.get("clear"):
                self._wedge_until = 0.0
                self._latency_s = 0.0
                self._latency_until = 0.0
            if "wedge_s" in spec:
                self._wedge_until = now + float(spec["wedge_s"])
            if "latency_s" in spec:
                self._latency_s = float(spec["latency_s"])
                self._latency_until = now + float(
                    spec.get("duration_s", 3600.0)
                )
            return self._view(now)

    def _view(self, now: float) -> dict:
        return {
            "wedge_remaining_s": round(max(0.0, self._wedge_until - now), 3),
            "latency_s": (
                self._latency_s if now < self._latency_until else 0.0
            ),
            "latency_remaining_s": round(
                max(0.0, self._latency_until - now), 3
            ),
        }

    def view(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._view(now)

    def wedged(self, now: float | None = None) -> float:
        """Remaining wedge seconds (0 = healthy)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return max(0.0, self._wedge_until - now)

    def delay(self) -> None:
        """The /score-path injection point: stall for the wedge window
        (the router's forward timeout fires first — exactly a wedged
        backend), else sleep the injected latency."""
        now = time.monotonic()
        with self._lock:
            wedge = max(0.0, self._wedge_until - now)
            lat = self._latency_s if now < self._latency_until else 0.0
        if wedge > 0:
            time.sleep(wedge)
        elif lat > 0:
            time.sleep(lat)


class StubRegistry:
    """Registry-shaped stub over freshly initialized params: the chaos
    drills and the load bench measure the fleet machinery, not
    checkpoint IO (the restore path has its own e2e coverage in
    `fleet --smoke` and the subprocess scenarios)."""

    family = "deepdfa"

    def __init__(self, cfg, model, params, vocabs, run_dir,
                 checkpoints=None, flywheel_tag: str = "incumbent"):
        self.cfg = cfg
        self._model = model
        self._params = params
        self.vocabs = vocabs
        self.run_dir = Path(run_dir)
        self.checkpoint = "init"
        #: swappable named param sets for the rollout/flywheel smokes:
        #: {name: (params, injected_drift)} — the injected drift is
        #: what swap_checkpoint reports, so a "bad candidate" stub
        #: trips the real drift gate without a real calibration stream
        self.checkpoints: dict = dict(checkpoints or {})
        self.flywheel_tag = str(flywheel_tag)
        self.hot_swaps = 0
        self._prev: tuple[str, object] | None = None

    @property
    def model(self):
        return self._model

    def params(self):
        return self._params

    def _feat_width(self) -> int:
        from deepdfa_tpu.graphs.batch import NUM_SUBKEY_FEATS

        return NUM_SUBKEY_FEATS

    def maybe_reload(self) -> bool:
        return False

    def swap_checkpoint(self, checkpoint: str, drift_bound=None) -> dict:
        """The ModelRegistry swap contract over the stub's named param
        sets (same refusal semantics: RegistryError on unknown tag or
        drift past bound, prior params stashed for rollback) — so
        run_rollout drives the stub fleet through the identical
        drain/swap/refuse/rollback protocol it drives production
        through."""
        from deepdfa_tpu.serve.registry import RegistryError

        if checkpoint not in self.checkpoints:
            raise RegistryError(
                f"unknown stub checkpoint {checkpoint!r}; "
                f"known: {sorted(self.checkpoints)}"
            )
        params, drift = self.checkpoints[checkpoint]
        if drift_bound is not None and drift > float(drift_bound):
            raise RegistryError(
                f"calibration drift {drift:.3f} exceeds bound "
                f"{float(drift_bound):.3f}; swap refused"
            )
        self._prev = (self.checkpoint, self._params)
        self.checkpoint = str(checkpoint)
        self._params = params
        self.hot_swaps += 1
        return {
            "checkpoint": self.checkpoint,
            "checkpoint_step": self.hot_swaps,
            "previous": self._prev[0],
            "drift": float(drift),
        }

    def rollback(self) -> dict | None:
        if self._prev is None:
            return None
        rolled_from = self.checkpoint
        self.checkpoint, self._params = self._prev
        self._prev = None
        return {
            "checkpoint": self.checkpoint,
            "checkpoint_step": self.hot_swaps,
            "rolled_back_from": rolled_from,
        }

    def info(self) -> dict:
        out = {
            "family": self.family,
            "run_dir": str(self.run_dir),
            "checkpoint": self.checkpoint,
            "checkpoint_step": self.hot_swaps,
            "config_digest": "stub",
            "vocab_digest": "stub",
            "hot_swaps": self.hot_swaps,
        }
        if self.flywheel_tag != "incumbent":
            out["flywheel_tag"] = self.flywheel_tag
        return out


def stub_service(cfg, fleet_dir: Path, replica_id: str, model=None,
                 params=None, vocabs=None, checkpoints=None,
                 flywheel_tag: str = "incumbent"):
    """One real ScoringService over a StubRegistry (shared model/params
    so N replicas warm N identical ladders without N model inits)."""
    from deepdfa_tpu.serve.server import ScoringService

    registry = StubRegistry(
        cfg, model, params, vocabs, Path(fleet_dir) / replica_id,
        checkpoints=checkpoints, flywheel_tag=flywheel_tag,
    )
    return ScoringService(registry, cfg)


def build_stub_parts(cfg, n_corpus: int = 32, seed: int = 0):
    """(model, params, vocabs, codes): the shared model-side parts of an
    in-process fleet, plus a scoreable corpus."""
    import jax

    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA

    synth = generate(n_corpus, seed=seed)
    examples = to_examples(synth)
    _, vocabs = build_dataset(
        examples, train_ids=range(n_corpus),
        limit_all=cfg.data.feat.limit_all,
        limit_subkeys=cfg.data.feat.limit_subkeys,
    )
    model = DeepDFA.from_config(
        cfg.model, input_dim=cfg.data.feat.input_dim
    )
    params = model.init(jax.random.key(0), pack([], 1, 2048, 8192))
    codes = [e.code for e in examples]
    return model, params, vocabs, codes


class StubReplicaServer:
    """In-process replica: real ScoringService + the real serve HTTP
    handler with the chaos injection points, announced via the real
    heartbeat protocol — the tier-1 kill-router/wedge drills run against
    these (no subprocess, no checkpoint; <60 s)."""

    def __init__(self, cfg, fleet_dir, replica_id: str, service,
                 host: str = "127.0.0.1", shadow: bool = False):
        from http.server import ThreadingHTTPServer

        from deepdfa_tpu.serve import server as serve_server

        self.cfg = cfg
        self.fleet_dir = Path(fleet_dir)
        self.replica_id = str(replica_id)
        self.service = service
        #: flywheel shadow role — mirrored into the heartbeat info so
        #: the router's routable() and run_rollout's replica selection
        #: exclude this stub exactly as they would a real shadow
        self.shadow = bool(shadow)
        self.chaos = ChaosState()
        chaos = self.chaos
        server = self

        class _ChaosHandler(serve_server._Handler):
            service = self.service

            def do_GET(handler):  # noqa: N802, N805
                if handler.path.startswith("/healthz") and chaos.wedged():
                    handler._reply(503, {
                        "error": "wedged (chaos)", "wedged": True,
                    })
                    return
                serve_server._Handler.do_GET(handler)

            def do_POST(handler):  # noqa: N802, N805
                if handler.path == "/admin/rollout":
                    server._handle_rollout(handler)
                    return
                chaos.delay()
                serve_server._Handler.do_POST(handler)

        service.start()
        self.httpd = ThreadingHTTPServer((host, 0), _ChaosHandler)
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            name=f"stub-replica-{replica_id}", daemon=True,
        )
        self._thread.start()
        self.beat()

    def beat(self, state: str = "ready") -> None:
        from deepdfa_tpu.fleet import heartbeat

        info = {
            "steady_state_recompiles": (
                self.service.steady_state_recompiles()
            ),
            "jit_lowerings": self.service._jit_lowerings(),
        }
        if self.shadow:
            info["shadow"] = True
        heartbeat.write_heartbeat(
            self.fleet_dir, self.replica_id, self.host, self.port,
            state=state, info=info,
        )

    def _handle_rollout(self, handler) -> None:
        """POST /admin/rollout against the stub: the real replica's
        response contract (200 swap report / 409 refusal / rollback)
        over StubRegistry.swap_checkpoint, with the heartbeat riding
        through draining -> ready — enough for run_rollout to drive a
        stub fleet through its full gate sequence in tier-1."""
        import json as _json

        from deepdfa_tpu.serve.registry import RegistryError

        try:
            n = int(handler.headers.get("Content-Length", 0))
            payload = _json.loads(handler.rfile.read(n) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as e:
            handler._reply(400, {"error": f"bad request: {e}"})
            return
        registry = self.service.registry
        self.beat("draining")
        try:
            if payload.get("rollback"):
                out = registry.rollback()
                if out is None:
                    raise RegistryError(
                        "nothing to roll back to on this stub"
                    )
            else:
                checkpoint = payload.get("checkpoint")
                if not checkpoint:
                    handler._reply(400, {
                        "error": "rollout needs a checkpoint tag "
                                 "(or rollback: true)",
                    })
                    return
                drift_bound = payload.get("drift_bound")
                out = registry.swap_checkpoint(
                    checkpoint,
                    drift_bound=(
                        float(drift_bound) if drift_bound is not None
                        else None
                    ),
                )
        except RegistryError as e:
            handler._reply(409, {
                "ok": False, "refused": True, "error": str(e),
                "replica_id": self.replica_id,
            })
            return
        finally:
            self.beat("ready")
        out.update(
            ok=True, drained=True, recompiles=0,
            steady_state_recompiles=(
                self.service.steady_state_recompiles()
            ),
            replica_id=self.replica_id,
        )
        handler._reply(200, out)

    def corrupt_heartbeat(self, text: str = '{"heartbeat": {"state": "zombie"') -> Path:
        """Overwrite this replica's announcement with damage (NON-atomic
        on purpose — the failure being injected is a bad file, and the
        next `beat()` heals it the way the real replica's refresh
        would)."""
        from deepdfa_tpu.fleet import heartbeat

        path = heartbeat.heartbeat_path(self.fleet_dir, self.replica_id)
        path.write_text(text)
        return path

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=10)
        self.service.close()


class OpenLoopTraffic:
    """Background open-loop Poisson traffic against a fleet router —
    the load the rollout and router-failover drills run under
    (scripts/fault_inject.py; the same arrival discipline as
    scripts/bench_load.py's `bench_load`, packaged as a start/stop
    driver next to the other shared chaos fixtures).

    `resolve_addr` is called per attempt, so a request in flight on a
    dead router follows the documented client contract: the send fails
    at the transport level, the client RE-RESOLVES (the router.json
    rendezvous, fleet/ha.py) and retries — waiting out the failover
    window for the rendezvous to answer before giving up. An addr that
    just failed is retried after `addr_cooldown_s` (not never): a
    transient reset on a healthy router, and a takeover that re-binds
    the SAME preferred port, must both land on retry. Results record
    every outcome — status 0 means every attempt inside
    `retry_window_s` failed at the transport level (a genuinely lost
    request, which the drills assert never happens)."""

    def __init__(
        self,
        resolve_addr,
        codes: list[str],
        rate_per_sec: float,
        tenant: str = "drill",
        deadline_ms: float | None = None,
        seed: int = 0,
        request_timeout_s: float = 60.0,
        retry_window_s: float = 20.0,
        addr_cooldown_s: float = 1.0,
    ):
        import random

        self.resolve_addr = resolve_addr
        self.codes = list(codes)
        self.rate = float(rate_per_sec)
        self.tenant = tenant
        self.deadline_ms = deadline_ms
        self.request_timeout_s = float(request_timeout_s)
        self.retry_window_s = float(retry_window_s)
        self.addr_cooldown_s = float(addr_cooldown_s)
        self.results: list[dict] = []
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._arrival: threading.Thread | None = None
        self._senders: list[threading.Thread] = []

    def _send(self, idx: int) -> None:
        import http.client

        payload: dict = {
            "code": self.codes[idx % len(self.codes)],
            "tenant": self.tenant,
        }
        if self.deadline_ms is not None:
            payload["deadline_ms"] = float(self.deadline_ms)
        body = json.dumps(payload)
        t0 = time.monotonic()
        deadline = t0 + self.retry_window_s
        last_error = None
        last_fail: dict = {}
        retries = 0
        while True:
            addr = self.resolve_addr()
            now = time.monotonic()
            # a just-failed addr cools down before the next attempt
            # (the rendezvous may move meanwhile — or the same front
            # door may come back, which is equally a recovery)
            if addr is not None and (
                now - last_fail.get(addr, -1e9) >= self.addr_cooldown_s
            ):
                try:
                    conn = http.client.HTTPConnection(
                        addr[0], addr[1], timeout=self.request_timeout_s
                    )
                    try:
                        conn.request(
                            "POST", "/score", body=body,
                            headers={
                                "Content-Type": "application/json",
                            },
                        )
                        resp = conn.getresponse()
                        raw = resp.read()
                        status = resp.status
                    finally:
                        conn.close()
                except OSError as e:
                    last_error = f"{type(e).__name__}: {e}"
                    last_fail[addr] = time.monotonic()
                    retries += 1
                else:
                    try:
                        doc = json.loads(raw or b"{}")
                    except json.JSONDecodeError:
                        doc = {}
                    with self._lock:
                        self.results.append({
                            "status": status,
                            "latency_s": time.monotonic() - t0,
                            "prob": doc.get("prob"),
                            "reason": doc.get("reason"),
                            "retried": retries,
                        })
                    return
            if time.monotonic() >= deadline:
                break
            time.sleep(0.25)
        with self._lock:
            self.results.append({
                "status": 0,
                "latency_s": time.monotonic() - t0,
                "error": str(last_error)[:200],
            })

    def _arrivals(self) -> None:
        idx = 0
        while not self._stop.is_set():
            gap = self._rng.expovariate(self.rate) if self.rate > 0 else 0.1
            if self._stop.wait(gap):
                break
            t = threading.Thread(
                target=self._send, args=(idx,), daemon=True,
                name=f"open-loop-{idx}",
            )
            t.start()
            self._senders.append(t)
            idx += 1

    def start(self) -> "OpenLoopTraffic":
        self._arrival = threading.Thread(
            target=self._arrivals, daemon=True, name="open-loop-arrivals"
        )
        self._arrival.start()
        return self

    def stop(self, timeout_s: float = 120.0) -> list[dict]:
        """Stop arrivals, join every sender (bounded — the thread-audit
        rule), return the recorded results."""
        self._stop.set()
        deadline = time.monotonic() + float(timeout_s)
        if self._arrival is not None:
            self._arrival.join(timeout=max(0.1, deadline - time.monotonic()))
        for t in self._senders:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        with self._lock:
            return list(self.results)


def http_json(host: str, port: int, method: str, path: str,
              payload: dict | None = None, headers: dict | None = None,
              timeout: float = 60.0):
    """One bounded HTTP round trip -> (status, parsed body). The chaos
    harness's shared client; raises the usual transport errors so
    callers can exercise the client's-retry contract themselves."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload) if payload is not None else None
        hdrs = dict(headers or {})
        if body:
            hdrs.setdefault("Content-Type", "application/json")
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        raw = resp.read().decode("utf-8", "replace")
    finally:
        conn.close()
    try:
        return resp.status, json.loads(raw or "{}")
    except json.JSONDecodeError:
        return resp.status, {"raw": raw}
