"""Multi-replica serving fleet (docs/fleet.md).

The layer above the single-process serving stack (deepdfa_tpu/serve/):
N shared-nothing replica workers — each a full `ScoringService` with its
own AOT-warmed bucket ladders — behind one stdlib-HTTP router with
per-tenant admission control and deadline-aware load shedding.

- `fleet.heartbeat` — the replica announcement protocol: one atomic
  JSON file per replica under `<run_dir>/fleet/`, carrying the cached
  `BackendHealth` report, the per-entry HBM param-bytes ledger snapshot
  (the co-serving capacity signal), and the drain state.
- `fleet.admission` — per-tenant token-bucket admission with priority
  classes, deadline-aware shedding (requests whose deadline cannot be
  met at the current queue depth are rejected BEFORE any frontend or
  device time is spent), and the param-bytes co-serving planner.
- `fleet.router` — the front door: health-gated least-outstanding
  routing, replica eject/readmit on transport failure or probe success,
  in-flight retry on a survivor (scores are bit-identical regardless of
  replica, so a retry is always safe), request-id propagation so one
  request's Perfetto flow chain spans router -> replica.
- `fleet.replica` — the worker process: ScoringService + HTTP server +
  heartbeat thread; SIGTERM drains (stop accepting, finish in-flight
  batches, final SLO snapshot + flight-recorder postmortem) instead of
  dropping work.
- `fleet.coord` — the pluggable coordination backend every fleet
  module reads/writes shared state through (heartbeats, the
  `router.json` rendezvous with epoch fencing, the fleet log): the
  default `LocalDirBackend` keeps today's shared-directory files
  byte-identical; `FaultableBackend` wraps it with injectable latency,
  stale reads, torn/lost writes, and partitions for the drills. Also
  home of `poll_until`, the one bounded-backoff poll helper.
- `fleet.drill` — scheduled chaos drills: recurring execution of the
  failure-matrix scenarios with measured failover/readmit/rollback/
  reseed times recorded into the gated `DRILL_r*.json` trajectory.
- `fleet.autoscale` — predictive autoscaling: replay fleet-log arrival
  rates, forecast near-term load, and walk the degradation ladder
  (shed_stage2 → tighten_admission → scale_up) AHEAD of predicted
  saturation, every decision a schema-valid fleet-log record.
- `fleet.smoke` — the `fleet --smoke` end-to-end drive (tier-1).

Everything here is opt-in via the `fleet`/`fleet-replica`/`fleet-drill`
CLI commands; the default single-process `serve` path never imports
this package.
"""

from __future__ import annotations

__all__ = [
    "admission",
    "autoscale",
    "coord",
    "drill",
    "heartbeat",
    "replica",
    "router",
    "smoke",
]
