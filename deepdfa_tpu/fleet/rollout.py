"""Zero-downtime checkpoint rollout across a serving fleet
(docs/fleet.md; `deepdfa-tpu fleet-rollout`).

Hot-swaps a new checkpoint tag across the fleet ONE replica at a time
while the router keeps serving — the drill the failure matrix's
"deploy" row executes under `bench_load.py` traffic:

  per replica   drain -> swap -> re-warm -> readmit, all replica-side
                (fleet/replica.py:swap_primary via POST /admin/rollout):
                the heartbeat flips to `draining`, the router stops
                routing there within its poll cadence, the swap is one
                reference assignment against the same AOT executables
                (zero recompiles), and `ready` readmits it.
  drift gate    the replica refuses a swap whose calibration score
                drift vs the serving params exceeds
                `fleet.rollout_drift_bound` (the PR-12 machinery,
                serve/registry.py:swap_checkpoint) — a bad checkpoint
                halts the rollout at the FIRST replica, before it ever
                serves a request.
  SLO guard     between swaps the controller reads the router's
                smallest SLO window; a windowed p99 past
                `fleet.rollout_p99_ms` (when set) or a SERVER-error
                rate (5xx minus 503 sheds) past
                `fleet.rollout_error_rate` HALTS the rollout and rolls
                every already-swapped replica back to the prior tag
                (registry rollback stash — no disk round trip).

Every step is a `{"rollout": {...}}` record in the shared
fleet_log.jsonl (validate_fleet_log checks the vocabulary), and the
report pins the zero-recompile census across the whole event.
"""

from __future__ import annotations

import logging
import time
from pathlib import Path

from deepdfa_tpu.fleet import chaos as fleet_chaos, coord, ha, heartbeat
from deepdfa_tpu.fleet.router import FleetLog, ROLLOUT_EVENTS
from deepdfa_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)


class SloGuard:
    """The halt condition: windowed p99 / error rate off the router's
    /stats SLO snapshot (smallest window — the one that reacts inside a
    rollout's timescale).

    The error-rate arm counts GENUINE server failures only — 5xx except
    503 (i.e. 500/502/504). 429 rate-limit and 503 deadline/overload/
    no_replicas responses are the fleet's DESIGNED shed behavior
    (fleet/admission.py): a tenant at its token-bucket limit during a
    rollout is load shedding working, not the new checkpoint failing,
    and must not halt + roll back a healthy deploy. (A checkpoint that
    wedges replicas still trips the p99 arm.) Either arm set to 0
    disables it."""

    def __init__(self, p99_ms: float = 0.0, error_rate: float = 0.25):
        self.p99_ms = float(p99_ms)
        self.error_rate = float(error_rate)

    def read(self, host: str, port: int) -> dict:
        status, stats = fleet_chaos.http_json(
            host, port, "GET", "/stats", timeout=30.0
        )
        if status != 200:
            return {"ok": False, "reason": f"router /stats -> {status}"}
        slo = stats.get("slo") or {}
        windows = sorted(
            (k for k in slo if isinstance(slo.get(k), dict)
             and k.endswith("s") and k[:-1].isdigit()),
            key=lambda k: int(k[:-1]),
        )
        if not windows:
            return {"ok": True, "reason": "no window data yet"}
        view = slo[windows[0]]
        p99 = (
            ((view.get("latency_ms") or {}).get("total") or {}).get("p99")
        )
        # genuine failures only: 5xx minus the 503 shed statuses; the
        # window's raw error_rate also counts designed 429/503 sheds
        counts = view.get("status") or {}
        n = sum(counts.values())
        err = None
        if n:
            failures = sum(
                v for k, v in counts.items()
                if str(k).startswith("5") and str(k) != "503"
            )
            err = round(failures / n, 4)
        out = {
            "ok": True,
            "window": windows[0],
            "p99_ms": p99,
            "error_rate": err,
        }
        if (
            self.p99_ms > 0
            and isinstance(p99, (int, float))
            and p99 > self.p99_ms
        ):
            out.update(ok=False, reason=(
                f"windowed p99 {p99:.1f}ms > guard {self.p99_ms:g}ms"
            ))
        elif (
            self.error_rate > 0
            and isinstance(err, (int, float))
            and err > self.error_rate
        ):
            out.update(ok=False, reason=(
                f"windowed server-error rate {err:.3f} > guard "
                f"{self.error_rate:g}"
            ))
        return out


def _record(log: FleetLog | None, event: str, checkpoint: str, **fields):
    assert event in ROLLOUT_EVENTS, event
    obs_metrics.REGISTRY.counter(f"rollout/{event}").inc()
    if log is not None:
        log.append({"rollout": {
            "event": event,
            "checkpoint": checkpoint,
            "t_unix": round(time.time(), 3),
            **fields,
        }})


def _ready_replicas(
    fleet_dir, timeout_s: float, backend=None
) -> dict[str, dict]:
    beats = heartbeat.scan_heartbeats(fleet_dir, backend=backend)
    return {
        rid: hb for rid, hb in sorted(beats.items())
        if hb.get("state") == heartbeat.READY
        and heartbeat.is_fresh(hb, timeout_s)
        # a shadow replica (flywheel ride, docs/flywheel.md) already IS
        # the candidate — swapping it would score the comparison stream
        # against itself and defeat the ride
        and not hb.get("shadow")
    }


def run_rollout(
    cfg,
    fleet_dir: str | Path,
    checkpoint: str,
    router_addr: tuple[str, int] | None = None,
    log_path: str | Path | None = None,
    swap_timeout_s: float = 300.0,
) -> dict:
    """Roll `checkpoint` across every ready replica; the report the CLI
    prints and the chaos drill asserts on. Never raises for a halted
    rollout — the halt, its reason, and the rollback outcome ARE the
    report."""
    fleet_dir = Path(fleet_dir)
    fcfg = cfg.fleet
    backend = coord.backend_from_config(cfg)
    if router_addr is None:
        # rides the shared bounded poll helper (coord.poll_until) —
        # a rollout started inside the failover window waits the
        # documented bound for the new front door, never ad hoc
        router_addr = ha.resolve_router(
            fleet_dir, timeout_s=fcfg.router_failover_timeout_s,
            backend=backend,
        )
    log = (
        FleetLog(log_path, backend=backend)
        if log_path is not None else None
    )
    guard = SloGuard(fcfg.rollout_p99_ms, fcfg.rollout_error_rate)
    replicas = _ready_replicas(
        fleet_dir, fcfg.heartbeat_timeout_s, backend=backend
    )
    report: dict = {
        "checkpoint": checkpoint,
        "drift_bound": float(fcfg.rollout_drift_bound),
        "replicas": [],
        "halted": False,
        "rolled_back": [],
        "router": (
            f"{router_addr[0]}:{router_addr[1]}" if router_addr else None
        ),
    }
    if not replicas:
        report.update(ok=False, error="no ready replicas to roll")
        if log is not None:
            log.close()
        return report

    swapped: list[tuple[str, dict]] = []

    def halt(reason: str, **fields) -> None:
        report["halted"] = True
        report["halt_reason"] = reason
        _record(log, "halt", checkpoint, reason=reason[:300], **fields)
        # roll every already-swapped replica back, NEWEST first (the
        # registry stash makes this a reference assignment per replica)
        for rid, hb in reversed(swapped):
            try:
                status, resp = fleet_chaos.http_json(
                    str(hb["host"]), int(hb["port"]),
                    "POST", "/admin/rollout", {"rollback": True},
                    timeout=swap_timeout_s,
                )
            except Exception as e:  # noqa: BLE001 - report, don't die
                status, resp = 0, {"error": str(e)}
            _record(
                log, "rollback", checkpoint, replica=rid,
                status=status,
            )
            report["rolled_back"].append({
                "replica": rid, "status": status,
                "checkpoint": resp.get("checkpoint"),
            })

    _record(
        log, "start", checkpoint, replicas=len(replicas),
        drift_bound=float(fcfg.rollout_drift_bound),
    )
    try:
        for rid, hb in replicas.items():
            if router_addr is not None:
                pre = guard.read(*router_addr)
                if not pre.get("ok"):
                    halt(f"SLO guard before {rid}: {pre.get('reason')}")
                    break
            try:
                status, resp = fleet_chaos.http_json(
                    str(hb["host"]), int(hb["port"]),
                    "POST", "/admin/rollout",
                    {
                        "checkpoint": checkpoint,
                        "drift_bound": float(fcfg.rollout_drift_bound),
                    },
                    timeout=swap_timeout_s,
                )
            except Exception as e:  # noqa: BLE001 - transport = halt
                halt(f"replica {rid} unreachable mid-swap: {e}")
                break
            entry = {
                "replica": rid, "status": status,
                "drift": resp.get("drift"),
                "checkpoint_step": resp.get("checkpoint_step"),
                "recompiles": resp.get("recompiles"),
                "steady_state_recompiles": resp.get(
                    "steady_state_recompiles"
                ),
            }
            report["replicas"].append(entry)
            if status == 409:
                _record(
                    log, "refused", checkpoint, replica=rid,
                    error=str(resp.get("error"))[:300],
                )
                halt(
                    f"replica {rid} refused the swap (score drift past "
                    f"bound): {resp.get('error')}"
                )
                break
            if status != 200 or not resp.get("ok"):
                halt(
                    f"replica {rid} swap failed "
                    f"(status {status}): {resp.get('error')}"
                )
                break
            _record(
                log, "swap", checkpoint, replica=rid,
                drift=resp.get("drift"),
                checkpoint_step=resp.get("checkpoint_step"),
                recompiles=resp.get("recompiles"),
            )
            swapped.append((rid, hb))
            # settle, then judge: the windowed guard needs post-swap
            # traffic through the readmitted replica before it means
            # anything
            time.sleep(max(0.0, float(fcfg.rollout_settle_s)))
            if router_addr is not None:
                post = guard.read(*router_addr)
                entry["guard"] = {
                    k: post.get(k) for k in ("p99_ms", "error_rate")
                }
                if not post.get("ok"):
                    halt(f"SLO guard after {rid}: {post.get('reason')}")
                    break
        else:
            _record(
                log, "complete", checkpoint, replicas=len(swapped),
            )
        report["swapped"] = [rid for rid, _ in swapped]
        report["ok"] = not report["halted"] and len(swapped) == len(
            replicas
        )
        # the zero-recompile census across the whole event, straight
        # from the replicas' own lowering counters
        census = {}
        for rid, hb in replicas.items():
            try:
                _, h = fleet_chaos.http_json(
                    str(hb["host"]), int(hb["port"]), "GET", "/healthz",
                    timeout=30.0,
                )
                census[rid] = h.get("steady_state_recompiles")
            except Exception:  # noqa: BLE001
                census[rid] = None
        report["census"] = census
        report["census_ok"] = all(v == 0 for v in census.values())
    finally:
        if log is not None:
            log.close()
    return report
