"""Replica heartbeat files — the fleet's announcement protocol
(docs/fleet.md).

One JSON file per replica under the fleet dir, written atomically
(core/ioutil.py) so the router never reads a torn document. The file is
the replica's whole public record: where it listens, what it serves
(checkpoint step + config/vocab digests, warmed signatures, recompile
census), the cached `BackendHealth` report, the per-entry HBM
param-bytes ledger snapshot (the co-serving capacity signal, PR 10),
and its lifecycle state:

    starting -> ready -> draining -> drained

The router treats `ready` with a fresh timestamp as routable,
`draining` as observe-but-don't-route (the replica is finishing its
in-flight batches), and anything stale past `heartbeat_timeout_s` as
gone. Files, not sockets, on purpose: a crashed replica leaves its last
heartbeat behind as evidence, and the smoke/failure tests can inspect
the fleet's state without a live process.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

#: lifecycle states a heartbeat may declare
STATES = ("starting", "ready", "draining", "drained")

#: routable state — the only one the router forwards to
READY = "ready"


def heartbeat_path(fleet_dir: str | Path, replica_id: str) -> Path:
    return Path(fleet_dir) / f"replica-{replica_id}.json"


def write_heartbeat(
    fleet_dir: str | Path,
    replica_id: str,
    host: str,
    port: int,
    state: str = READY,
    info: dict | None = None,
    backend=None,
) -> Path:
    """Atomically write one replica's heartbeat; returns the path.

    `info` carries the replica's serving identity + capacity signals
    (healthz-lite fields, backend report, ledger param bytes); the
    envelope adds the routing essentials and the timestamp the router
    ages against. The write rides the coordination backend
    (fleet/coord.py; the default LocalDirBackend is today's atomic
    file, byte-identical)."""
    if state not in STATES:
        raise ValueError(f"unknown heartbeat state {state!r}; in {STATES}")
    from deepdfa_tpu.fleet import coord

    doc = {
        "heartbeat": {
            "replica_id": str(replica_id),
            "pid": os.getpid(),
            "host": str(host),
            "port": int(port),
            "state": state,
            "t_unix": round(time.time(), 3),
            **(info or {}),
        }
    }
    path = heartbeat_path(fleet_dir, replica_id)
    (backend or coord.LOCAL).write_doc(path, json.dumps(doc))
    return path


def validate_heartbeat(doc) -> tuple[dict | None, str | None]:
    """(heartbeat, None) for a well-formed document, (None, reason) for
    a malformed one. A heartbeat is malformed when the envelope is not
    `{"heartbeat": {...}}`, a required field is missing, a field has an
    un-coercible type, or the state is outside the declared lifecycle —
    the router QUARANTINES the replica behind such a file instead of
    crashing on it (docs/fleet.md failure matrix; the `corrupt-heartbeat`
    chaos scenario executes this row)."""
    if not isinstance(doc, dict):
        return None, "not a JSON object"
    hb = doc.get("heartbeat")
    if not isinstance(hb, dict):
        return None, "no heartbeat object"
    required = ("replica_id", "host", "port", "state", "t_unix")
    missing = [k for k in required if k not in hb]
    if missing:
        return None, f"missing fields {missing}"
    if hb["state"] not in STATES:
        return None, f"unknown state {hb['state']!r}"
    try:
        port = int(hb["port"])
        float(hb["t_unix"])
    except (TypeError, ValueError):
        return None, "port/t_unix not numeric"
    if not (0 < port < 65536):
        return None, f"port {port} out of range"
    return hb, None


def read_heartbeat(path: str | Path, backend=None) -> dict | None:
    """One parsed heartbeat document, or None when unreadable (a replica
    mid-first-write, or a deleted file racing the scan) or malformed."""
    hb, _ = read_heartbeat_verbose(path, backend=backend)
    return hb


def read_heartbeat_verbose(
    path: str | Path, backend=None
) -> tuple[dict | None, str | None]:
    """(heartbeat, None) | (None, reason) — the quarantine-aware read."""
    from deepdfa_tpu.fleet import coord

    try:
        doc = json.loads((backend or coord.LOCAL).read_doc(path))
    except OSError:
        # a deleted file racing the scan is not evidence of anything
        return None, None
    except json.JSONDecodeError as e:
        return None, f"not JSON ({e})"
    return validate_heartbeat(doc)


def scan_heartbeats(fleet_dir: str | Path, backend=None) -> dict[str, dict]:
    """{replica_id: heartbeat} for every readable heartbeat file."""
    beats, _ = scan_heartbeats_verbose(fleet_dir, backend=backend)
    return beats


def scan_heartbeats_verbose(
    fleet_dir: str | Path, backend=None
) -> tuple[dict[str, dict], dict[str, str]]:
    """(beats, invalid): well-formed heartbeats by replica id, plus
    {replica_id: reason} for every malformed announcement file — the
    replica id derived from the `replica-<id>.json` filename so the
    router can quarantine the SPECIFIC replica behind a corrupt file."""
    from deepdfa_tpu.fleet import coord

    backend = backend or coord.LOCAL
    out: dict[str, dict] = {}
    invalid: dict[str, str] = {}
    for path in backend.scan(Path(fleet_dir), "replica-*.json"):
        hb, reason = read_heartbeat_verbose(path, backend=backend)
        if hb is not None:
            out[str(hb["replica_id"])] = hb
        elif reason is not None:
            invalid[path.stem[len("replica-"):]] = reason
    return out, invalid


def is_fresh(hb: dict, timeout_s: float, now: float | None = None) -> bool:
    """Has this heartbeat been refreshed inside the staleness window?"""
    now = time.time() if now is None else now
    return (now - float(hb.get("t_unix", 0.0))) <= float(timeout_s)
