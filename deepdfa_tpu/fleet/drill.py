"""Scheduled chaos drills as a gated trajectory (docs/fleet.md;
`deepdfa-tpu fleet-drill`).

The failure matrix (docs/fleet.md) is only evidence while someone runs
it. This module makes the running RECURRING and the evidence a
trajectory: a scheduler executes drill rounds on a cadence — the
in-process kill-router drill through a `coord.FaultableBackend` in
smoke mode, the real `scripts/fault_inject.py --fleet` failure-matrix
rows in full mode — and records the MEASURED recovery times (failover,
admission reseed, readmit, rollback) into one `DRILL_r*.json` record
per run. `obs/bench_gate.py:gate_drill` then holds the trajectory to a
round-over-round tolerance on `drill_failover_s` plus the documented
3.2 s failover bound as an ABSOLUTE ceiling — a regression in recovery
time fails the gate exactly like a throughput regression would.

The drill rounds ride the coordination backend deliberately: the smoke
round injects storage latency on the rendezvous document and asserts
the fault counters moved, proving the drill exercised the pluggable
backend seam and not a shortcut around it.
"""

from __future__ import annotations

import json
import logging
import re
import subprocess
import sys
import time
from pathlib import Path

from deepdfa_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

#: the documented router failover ceiling (docs/fleet.md: failover
#: timeout 3.0 s + probe + one rendezvous poll) — the gate's ABSOLUTE
#: bound on drill_failover_s, independent of any reference round
DRILL_BOUND_S = 3.2

#: file-name pattern of one drill round record in a run dir
DRILL_GLOB = "DRILL_r*.json"

#: what the smoke drill executes (in-process, <60 s)
SMOKE_SCENARIOS = ("kill-router",)

#: what the full drill executes by default (real subprocess fleet)
FULL_SCENARIOS = ("wedge-backend", "rollout", "kill-router")

_REPO = Path(__file__).resolve().parents[2]


# ---------------------------------------------------------------------------
# one smoke drill round: kill-router through the FaultableBackend


def run_smoke_drill(tmp: str | Path, parts=None) -> dict:
    """One in-process drill round: an active/standby HA pair over stub
    replicas, ALL coordination through a FaultableBackend with latency
    injected on the rendezvous document. Measures, in seconds:

      readmit_s    wedge a replica -> router ejects -> recovery ->
                   readmitted (the wedge-backend matrix row)
      failover_s   kill the active router -> standby serves (the
                   kill-router row; the 3.2 s bound applies HERE)
      reseed_s     a fresh router re-seeds admission state from the
                   shared fleet_log through the backend's torn-tolerant
                   tail

    rollback_s is None in smoke mode — a checkpoint rollback needs the
    real replica subprocesses (`fleet-drill --full`).

    `parts` is an optional pre-built `chaos.build_stub_parts` tuple so
    a caller running several smoke phases pays for the stub model
    once."""
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.fleet import chaos as fleet_chaos, coord, ha as fleet_ha
    from deepdfa_tpu.fleet.router import router_from_config

    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
        "serve.max_batch_graphs=1",
        "serve.node_budget=2048", "serve.edge_budget=8192",
        "serve.slo_windows=[5, 60]",
        # in-process stubs never refresh heartbeats; a large timeout
        # keeps them routable (the bench_load convention)
        "fleet.heartbeat_timeout_s=3600.0",
        "fleet.poll_interval_s=0.1",
        "fleet.request_timeout_s=1.0",
        "fleet.rendezvous_interval_s=0.1",
        "fleet.router_failover_timeout_s=0.8",
        "fleet.summary_interval_s=0.2",
        'fleet.tenants="{\\"drill\\": {\\"rate\\": 0.001, '
        '\\"burst\\": 50, \\"priority\\": 1}}"',
    ])
    backend = coord.FaultableBackend()
    # a slow coordination store on the rendezvous path: small enough to
    # stay inside the failover bound, large enough that the coord/faults
    # counters PROVE the drill's coordination rode the wrapper
    backend.set_fault(coord.ROUTER_FILE, latency_s=0.005)
    snap_start = obs_metrics.REGISTRY.snapshot()

    model, params, vocabs, codes = (
        parts if parts is not None else fleet_chaos.build_stub_parts(cfg)
    )
    fleet_dir = Path(tmp) / "drill"
    log_path = fleet_dir / "fleet_log.jsonl"
    replicas = [
        fleet_chaos.StubReplicaServer(
            cfg, fleet_dir, f"r{i}",
            fleet_chaos.stub_service(
                cfg, fleet_dir, f"r{i}", model, params, vocabs
            ),
        )
        for i in range(2)
    ]
    active = fleet_ha.HARouter(
        cfg, fleet_dir, "ra", log_path=log_path, backend=backend
    )
    standby = fleet_ha.HARouter(
        cfg, fleet_dir, "rb", log_path=log_path, backend=backend
    )
    out: dict = {"scenario": "kill-router", "rollback_s": None}
    try:
        active.start()
        assert active.wait_active(20.0), "active router never came up"
        addr = (active.host, active.port)
        # traffic under the drill tenant so the summary record carries a
        # partially-drained bucket level for the reseed leg to restore
        for i in range(6):
            status, _ = fleet_chaos.http_json(
                *addr, "POST", "/score",
                {"code": codes[i % len(codes)], "tenant": "drill"},
            )
            assert status == 200, status
        active.router._last_summary = 0.0
        active.router._maybe_summarize()

        # -- readmit leg: wedge r0; the router must eject off the
        # forward timeout, retry on the survivor, and readmit on
        # recovery (the wedge-backend matrix row, timed)
        # wedge must outlast request_timeout_s (1.0) so the forward
        # genuinely times out and ejects; 1.6 s keeps that margin while
        # holding the drill round inside the smoke budget
        replicas[0].chaos.apply({"wedge_s": 1.6})
        t0 = time.monotonic()
        for code in codes[:2]:
            status, resp = fleet_chaos.http_json(
                *addr, "POST", "/score", {"code": code}, timeout=60.0
            )
            assert status == 200, (status, resp)
        snap_w = obs_metrics.REGISTRY.snapshot()
        assert snap_w.get("fleet/ejects", 0) > snap_start.get(
            "fleet/ejects", 0
        ), "wedged replica never ejected"
        readmitted = coord.poll_until(
            lambda: (
                obs_metrics.REGISTRY.snapshot().get("fleet/readmits", 0)
                > snap_start.get("fleet/readmits", 0)
            ) or None,
            30.0, interval_s=0.05, max_interval_s=0.25,
            what="drill readmit",
        )
        assert readmitted, "wedged replica never readmitted"
        out["readmit_s"] = round(time.monotonic() - t0, 3)

        # -- failover leg: the active dies abruptly (SIGKILL residue:
        # no rendezvous handoff); the standby must fence past the stale
        # epoch and serve within the documented bound
        standby.start()
        time.sleep(0.3)
        assert standby.role == "standby", standby.role
        epoch0 = fleet_ha.read_rendezvous(fleet_dir, backend=backend)[
            "epoch"
        ]

        # the alert engine rides along (obs/alerts.py): a fast
        # router_failover burn-rate rule watches the front door during
        # the kill window, so the drill measures DETECTION time (MTTD)
        # next to recovery time, and the transitions land in the shared
        # fleet_log as schema-valid {"alert": ...} records
        from deepdfa_tpu.fleet.router import FleetLog
        from deepdfa_tpu.obs import alerts as obs_alerts

        alert_log = FleetLog(log_path, backend=backend)
        engine = obs_alerts.AlertEngine(
            [obs_alerts.AlertRule(
                name="router_failover", kind="burn_rate",
                threshold=1.0, for_s=0.0, windows=(0.4, 1.2),
                params={"budget": 0.05, "min_count": 1},
            )],
            sink=alert_log.append,
        )

        def probe_front_door() -> bool:
            addr = fleet_ha.resolve_router(fleet_dir, backend=backend)
            if addr is None:
                return False
            try:
                status, _ = fleet_chaos.http_json(
                    *addr, "GET", "/healthz", timeout=0.25
                )
            except Exception:
                # a dying front door shows up as several error classes
                # (refused, timeout, a torn mid-response close) — all of
                # them are the same alert-worthy fact
                return False
            return status == 200

        def feed(ok: bool) -> None:
            engine.observe_request(200 if ok else 503)
            for rec in engine.evaluate({}):
                state = rec["alert"]["state"]
                if state == "firing" and out.get("alert_mttd_s") is None:
                    out["alert_mttd_s"] = round(time.monotonic() - t0, 3)
                    out["alert_fired"] = True
                elif state == "resolved":
                    out["alert_resolved"] = True

        out["alert_mttd_s"] = None
        t0 = time.monotonic()
        active.kill()
        took_over = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if standby.wait_active(timeout_s=0.02):
                took_over = True
                break
            feed(probe_front_door())
        assert took_over, "no takeover"
        out["failover_s"] = round(time.monotonic() - t0, 3)
        # keep probing the (now healthy) front door until the error
        # windows drain and the alert resolves
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not out.get(
            "alert_resolved"
        ):
            feed(probe_front_door())
            time.sleep(0.05)
        alert_log.close()
        assert out.get("alert_fired"), (
            "router_failover alert never fired during the kill window"
        )
        assert out.get("alert_resolved"), (
            "router_failover alert never resolved after takeover"
        )
        rv = fleet_ha.read_rendezvous(fleet_dir, backend=backend)
        assert rv["router_id"] == "rb" and rv["epoch"] > epoch0, rv
        out["epoch"] = rv["epoch"]
        status, resp = fleet_chaos.http_json(
            *fleet_ha.resolve_router(fleet_dir, backend=backend),
            "POST", "/score", {"code": codes[0]},
        )
        assert status == 200, (status, resp)
        drill_tokens = standby.router.admission.snapshot()["tokens"].get(
            "drill"
        )
        assert drill_tokens is not None and drill_tokens <= 45.0, (
            f"takeover did not re-seed the drill bucket: {drill_tokens}"
        )

        # -- reseed leg: a restarted router restores admission state
        # from the log's last summary through the backend's
        # torn-tolerant tail (timed separately from the takeover)
        t0 = time.monotonic()
        throwaway = router_from_config(
            cfg, fleet_dir, log_path=log_path, backend=backend
        )
        out["reseed_s"] = round(time.monotonic() - t0, 3)
        reseeded = throwaway.admission.snapshot()["tokens"].get("drill")
        throwaway.close()
        assert reseeded is not None and reseeded <= 45.0, reseeded

        # the backend seam was genuinely exercised: the injected
        # latency fault fired at least once
        snap_end = obs_metrics.REGISTRY.snapshot()
        out["coord_faults"] = {
            k.rsplit("/", 1)[1]: snap_end[k] - snap_start.get(k, 0)
            for k in snap_end
            if k.startswith("coord/faults/")
            and snap_end[k] > snap_start.get(k, 0)
        }
        assert out["coord_faults"].get("latency", 0) > 0, (
            "drill coordination never rode the FaultableBackend"
        )
        out["ok"] = True
    finally:
        active.kill()
        standby.close()
        for r in replicas:
            r.close()
    return out


# ---------------------------------------------------------------------------
# one full drill round: the real failure matrix via fault_inject.py


def run_full_drill(
    scenarios=FULL_SCENARIOS, timeout_s: float = 3600.0
) -> dict:
    """One full drill round: `scripts/fault_inject.py --fleet` with the
    selected failure-matrix rows, in a subprocess (real replica
    processes, real SIGKILLs). Timings come out of the scenario record:
    kill-router's measured `failover_seconds` is the gated number;
    wedge-backend / rollout wall times stand in for readmit / rollback
    (the subprocess record does not time those legs individually)."""
    cmd = [
        sys.executable, str(_REPO / "scripts" / "fault_inject.py"),
        "--fleet",
    ]
    for name in scenarios:
        cmd += ["--fleet-scenario", str(name)]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout_s,
        cwd=str(_REPO), check=False,
    )
    out: dict = {"scenario": "+".join(scenarios), "rollback_s": None}
    try:
        record = json.loads(proc.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        out["ok"] = False
        out["error"] = (
            f"fault_inject --fleet rc={proc.returncode}, unparseable "
            f"output: {proc.stdout[-500:]!r} {proc.stderr[-500:]!r}"
        )
        return out
    scen = record.get("scenarios") or {}
    kr = scen.get("kill-router") or {}
    if isinstance(kr.get("failover_seconds"), (int, float)):
        out["failover_s"] = float(kr["failover_seconds"])
    wb = scen.get("wedge-backend") or {}
    if isinstance(wb.get("seconds"), (int, float)):
        out["readmit_s"] = float(wb["seconds"])
    ro = scen.get("rollout") or {}
    if isinstance(ro.get("seconds"), (int, float)):
        out["rollback_s"] = float(ro["seconds"])
    out["ok"] = bool(record.get("ok")) and proc.returncode == 0
    if not out["ok"]:
        out["error"] = f"fault_inject --fleet rc={proc.returncode}"
        out["record"] = {
            k: v for k, v in scen.items() if "error" in (v or {})
        }
    return out


# ---------------------------------------------------------------------------
# the scheduler: rounds on a cadence -> one DRILL record


class DrillScheduler:
    """Run `rounds` drill rounds on an `interval_s` cadence and fold the
    measurements into one DRILL record. The runner is injected (the
    smoke phase passes `run_smoke_drill` over a tempdir, the CLI's full
    mode passes `run_full_drill`) so the schedule/aggregate/gate
    machinery is identical in both modes — and trivially testable with
    a stub runner and a fake clock."""

    def __init__(
        self,
        runner,
        rounds: int = 1,
        interval_s: float = 0.0,
        scenarios=SMOKE_SCENARIOS,
        mode: str = "smoke",
        sleep=time.sleep,
        clock=time.monotonic,
    ):
        self.runner = runner
        self.rounds = max(1, int(rounds))
        self.interval_s = max(0.0, float(interval_s))
        self.scenarios = tuple(str(s) for s in scenarios)
        self.mode = str(mode)
        self._sleep = sleep
        self._clock = clock

    def run(self) -> dict:
        per_round: list[dict] = []
        t_prev: float | None = None
        for i in range(self.rounds):
            if t_prev is not None and self.interval_s > 0:
                # cadence between round STARTS; a slow round eats into
                # its own gap, never delays the schedule further
                elapsed = self._clock() - t_prev
                self._sleep(max(0.0, self.interval_s - elapsed))
            t_start = t_prev = self._clock()
            obs_metrics.REGISTRY.counter("drill/rounds").inc()
            try:
                entry = dict(self.runner(i) or {})
            except (AssertionError, RuntimeError, OSError) as e:
                entry = {
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}"[:2000],
                }
            entry.setdefault("ok", False)
            entry["round"] = i
            entry["seconds"] = round(self._clock() - t_start, 3)
            if not entry["ok"]:
                obs_metrics.REGISTRY.counter("drill/failures").inc()
                logger.warning(
                    "drill round %d failed: %s", i, entry.get("error")
                )
            per_round.append(entry)
        return drill_record(
            mode=self.mode,
            cadence_s=self.interval_s,
            scenarios=self.scenarios,
            per_round=per_round,
        )


def drill_record(
    mode: str, cadence_s: float, scenarios, per_round: list[dict]
) -> dict:
    """Fold per-round measurements into the gated DRILL record. Each
    aggregate timing is the WORST round — the gate holds the trajectory
    to worst-case recovery, not a flattering average."""

    def _worst(key: str):
        vals = [
            r.get(key) for r in per_round
            if isinstance(r.get(key), (int, float))
        ]
        return round(max(vals), 3) if vals else None

    failover = _worst("failover_s")
    ok = (
        bool(per_round)
        and all(r.get("ok") for r in per_round)
        and failover is not None
        and failover < DRILL_BOUND_S
    )
    return {
        "mode": str(mode),
        "t_unix": round(time.time(), 3),
        "cadence_s": float(cadence_s),
        "rounds": len(per_round),
        "scenarios": sorted(set(map(str, scenarios))),
        "drill_failover_s": failover,
        "drill_reseed_s": _worst("reseed_s"),
        "drill_readmit_s": _worst("readmit_s"),
        "drill_rollback_s": _worst("rollback_s"),
        "drill_alert_mttd_s": _worst("alert_mttd_s"),
        "drill_bound_s": DRILL_BOUND_S,
        "per_round": per_round,
        "ok": ok,
    }


def next_drill_path(out_dir: str | Path) -> Path:
    """The next DRILL_rNN.json slot under `out_dir` — the trajectory
    grows by round number, mirroring the BENCH_r*/TUNED_r* convention
    the gates' trajectory loaders share."""
    out_dir = Path(out_dir)
    taken = [
        int(m.group(1))
        for p in out_dir.glob(DRILL_GLOB)
        if (m := re.search(r"r(\d+)", p.stem))
    ]
    return out_dir / f"DRILL_r{max(taken, default=0) + 1:02d}.json"


def write_drill_record(record: dict, out_dir: str | Path) -> Path:
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = next_drill_path(out_dir)
    path.write_text(json.dumps(record, indent=2, sort_keys=True) + "\n")
    return path


# ---------------------------------------------------------------------------
# validation (scripts/check_obs_schema.py --drill runs this function)


def validate_drill_record(doc) -> list[str]:
    """Every problem that makes a DRILL record unusable as gate input
    (empty = valid)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["not a JSON object"]
    if doc.get("mode") not in ("smoke", "full"):
        problems.append(f"mode {doc.get('mode')!r} not smoke|full")
    for key in ("t_unix", "cadence_s", "drill_bound_s"):
        if not isinstance(doc.get(key), (int, float)):
            problems.append(f"{key} missing or not numeric")
    if not (isinstance(doc.get("rounds"), int) and doc["rounds"] >= 1):
        problems.append("rounds missing or < 1")
    scen = doc.get("scenarios")
    if not (
        isinstance(scen, list) and scen
        and all(isinstance(s, str) for s in scen)
    ):
        problems.append("scenarios missing or not a list of names")
    if not isinstance(doc.get("drill_failover_s"), (int, float)):
        problems.append("drill_failover_s missing or not numeric")
    for key in (
        "drill_reseed_s", "drill_readmit_s", "drill_rollback_s",
        "drill_alert_mttd_s",
    ):
        if key in doc and doc[key] is not None and not isinstance(
            doc[key], (int, float)
        ):
            problems.append(f"{key} not numeric or null")
    rounds = doc.get("per_round")
    if not isinstance(rounds, list) or not rounds:
        problems.append("per_round missing or empty")
    else:
        if isinstance(doc.get("rounds"), int) and len(rounds) != doc[
            "rounds"
        ]:
            problems.append(
                f"per_round has {len(rounds)} entries, rounds says "
                f"{doc['rounds']}"
            )
        for i, entry in enumerate(rounds):
            if not isinstance(entry, dict):
                problems.append(f"per_round[{i}] not an object")
            elif "ok" not in entry:
                problems.append(f"per_round[{i}] missing ok")
    if not isinstance(doc.get("ok"), bool):
        problems.append("ok missing or not a bool")
    return problems


def validate_drill_file(path: str | Path) -> dict:
    """{"ok", "problems", "path"} for one DRILL_r*.json on disk."""
    path = Path(path)
    try:
        doc = json.loads(path.read_text())
    except OSError as e:
        return {"ok": False, "problems": [f"unreadable: {e}"],
                "path": str(path)}
    except json.JSONDecodeError as e:
        return {"ok": False, "problems": [f"not JSON: {e}"],
                "path": str(path)}
    problems = validate_drill_record(doc)
    return {"ok": not problems, "problems": problems, "path": str(path)}
