"""`fleet --smoke`: a 2-replica fleet end to end on a just-trained tiny
checkpoint (tier-1).

The acceptance drive for the whole fleet layer, in five phases against
REAL replica subprocesses (the same `fleet-replica` entry production
uses) and a real in-process router:

1. **parity** — scores through the router are BIT-IDENTICAL to
   single-replica serving (the offline score path on the same
   checkpoint), both replicas took traffic, and each replica's
   `jit_lowerings()` census shows zero steady-state recompiles.
2. **shedding** — an over-deadline burst is rejected 503 at the front
   door with the replicas' request counters UNCHANGED (no frontend or
   device time spent), and a token-bucket tenant gets 429 past its
   burst.
3. **failover** — one replica is SIGKILLed with requests in flight; the
   router ejects it, retries on the survivor, and every request still
   answers 200 with the bit-identical score (no request lost).
4. **drain** — the survivor gets SIGTERM: the router observes the
   `draining` heartbeat, the replica finishes in-flight work, leaves a
   final SLO snapshot + a validated flight-recorder postmortem, and
   exits 0 with its heartbeat at `drained`.
5. **log** — the router's fleet_log.jsonl validates against the
   declared obs schema (`scripts/check_obs_schema.py --fleet-log` runs
   the same function).

Two further phases run AFTER the main fleet is torn down, on cheap
stub fleets of their own (<60 s combined, docs/fleet.md):

6. **drill** — one scheduled chaos-drill round through
   `fleet/drill.py:DrillScheduler` (active/standby HA pair on a
   FaultableBackend): measured failover must beat the documented
   3.2 s bound, readmit and log-reseed must complete.
7. **autoscale** — `fleet/autoscale.py:run_smoke_autoscale`: replayed
   ramp arrivals force the degradation ladder (shed_stage2 ->
   tighten_admission) and a scale_up BEFORE the offered rate crosses
   measured capacity, with zero requests lost and every decision a
   schema-valid `{"autoscale": ...}` fleet_log record.
8. **telemetry** — `run_telemetry_smoke`: exact federated percentiles,
   cross-host trace stitching past a torn write, and burn-rate + drift
   alerts firing/resolving as schema-valid records.
9. **flywheel** — `run_flywheel_smoke` (docs/flywheel.md): a candidate
   shadow ride end to end — losing candidate demoted without a swap,
   drifting candidate halted + rolled back by the real rollout gates,
   winning candidate auto-promoted through `run_rollout` with zero
   lost open-loop requests.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from pathlib import Path


def _replica_stats(host: str, port: int) -> dict:
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", "/stats")
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def _replica_healthz(host: str, port: int) -> dict:
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", "/healthz")
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def run_fleet_smoke(extra_overrides=None, **smoke_kw) -> dict:
    """Returns the machine-readable smoke report `cmd_fleet` asserts
    on. Every phase's evidence is a field, not a print."""
    from deepdfa_tpu.fleet import coord, ha as fleet_ha, heartbeat
    from deepdfa_tpu.fleet.replica import spawn_replicas, wait_for_ready
    from deepdfa_tpu.fleet.router import (
        BackgroundRouter,
        router_from_config,
        validate_fleet_log,
    )
    from deepdfa_tpu.obs import flight as obs_flight
    from deepdfa_tpu.serve import driver
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService, score_texts

    smoke_kw.setdefault("run_name", "fleet-smoke")
    smoke_kw.setdefault("dataset", "fleet-smoke")
    smoke_kw.setdefault("n_examples", 16)
    smoke_kw.setdefault("max_epochs", 1)
    # the co-served combined entry (ROADMAP item 2 -> done): replicas
    # restore the transformer family NEXT TO the GGNN from the same run
    # dir — the fleet-wide cascade layout. The run dir is deterministic
    # from the run name, so the fleet.models override can name it
    # before build_smoke_run creates it.
    from deepdfa_tpu.core import paths

    stage2_run_dir = paths.runs_dir(smoke_kw["run_name"])
    cfg, run_dir, sources_dir = driver.build_smoke_run(
        extra_overrides=[
            "serve.request_log=true",
            "fleet.models=" + json.dumps(
                [f"stage2=combined:{stage2_run_dir}:best"]
            ),
            # tiny stage-2 serve batches (rows_for_bucket(32, 128) = 4)
            # keep the combined warmup ladder cheap on CPU
            "data.token_budget=128",
            # ONE ladder size so every phase (baseline, sequential
            # routing, concurrent failover) runs the IDENTICAL compiled
            # executable: cross-ladder-size runs (G1 vs G4) can differ
            # by ~1 ulp on XLA CPU (fusion/tiling vary with the segment
            # count), and this smoke pins request-level bit parity
            # across REPLICAS, not across batch shapes —
            # tests/test_serve.py owns the co-batching property
            "serve.max_batch_graphs=1",
            # per-replica postmortems are the drain contract's evidence
            "obs.flight=true",
            # tight cadences so the smoke's observations are prompt
            "fleet.heartbeat_interval_s=0.2",
            "fleet.heartbeat_timeout_s=5.0",
            "fleet.poll_interval_s=0.1",
            "fleet.drain_announce_s=0.5",
            # a deliberately tiny tenant for the 429 phase (the field
            # is a JSON string, so the override is a JSON string
            # literal)
            "fleet.tenants=" + json.dumps(
                '{"burst": {"rate": 0.001, "burst": 2, "priority": 1}}'
            ),
            *(extra_overrides or []),
        ],
        **smoke_kw,
    )
    fcfg = cfg.fleet
    fleet_dir = Path(fcfg.fleet_dir or run_dir / "fleet")
    # stage-2 artifacts (checkpoints-combined/ + model_cfg.json) must
    # exist before any replica restores the co-served entry
    from deepdfa_tpu.serve import cascade as cascade_mod

    cascade_mod.build_stage2_smoke(run_dir, cfg, family="combined")

    # -- singleton baseline: the offline score path on the same
    # checkpoint IS single-replica serving (same registry restore, same
    # frontend, same AOT ladder) — the bit-parity reference
    sources = driver.collect_sources([str(sources_dir)])[:8]
    registry = ModelRegistry(
        run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
        cfg=cfg,
    )
    baseline_service = ScoringService(registry, cfg)
    try:
        baseline_rows = score_texts(baseline_service, sources)
    finally:
        baseline_service.close()
    baseline = {
        Path(r["name"]).name: r["prob"]
        for r in baseline_rows if r.get("ok")
    }
    codes = {
        Path(name).name: code
        for name, code in sources
        if Path(name).name in baseline
    }

    report: dict = {"run_dir": str(run_dir)}
    procs = spawn_replicas(run_dir, fleet_dir, 2)
    router_server = None
    try:
        beats = wait_for_ready(
            fleet_dir, [rid for rid, _ in procs],
            timeout_s=240.0, procs=procs,
        )
        replica_addr = {
            rid: (hb["host"], int(hb["port"]))
            for rid, hb in beats.items()
        }
        router = router_from_config(
            cfg, fleet_dir, log_path=run_dir / "fleet_log.jsonl"
        )
        router_server = BackgroundRouter(router)

        # -- phase 1: routed scores == singleton scores, bit for bit
        scored = []
        for name, code in codes.items():
            status, resp = router_server.request(
                "POST", "/score", {"code": code}
            )
            scored.append({
                "name": name, "status": status,
                "prob": resp.get("prob"),
                "request_id": resp.get("request_id"),
                "bit_identical": resp.get("prob") == baseline[name],
            })
        report["scored"] = scored
        report["bit_identical"] = all(
            s["status"] == 200 and s["bit_identical"] for s in scored
        )
        topo = router.topology()
        report["both_replicas_served"] = (
            sorted(r["id"] for r in topo["replicas"] if r["forwarded"])
            == sorted(replica_addr)
        )
        # zero-steady-state-recompile census, pinned PER REPLICA
        census = {
            rid: _replica_healthz(*addr)
            for rid, addr in replica_addr.items()
        }
        report["replica_census"] = {
            rid: {
                "jit_lowerings": h.get("jit_lowerings"),
                "steady_state_recompiles": h.get(
                    "steady_state_recompiles"
                ),
            }
            for rid, h in census.items()
        }
        report["zero_recompiles_per_replica"] = all(
            h.get("steady_state_recompiles") == 0
            for h in census.values()
        )

        # -- phase 1.5: multi-family co-serving — requests picking the
        # combined entry with {"model": "stage2"} answer 200 through
        # the router, every replica restored it (ROADMAP item 2), and
        # the per-entry census stays at zero recompiles
        coserve_scored = []
        for code in list(codes.values())[:2]:
            status, resp = router_server.request(
                "POST", "/score", {"code": code, "model": "stage2"}
            )
            prob = resp.get("prob")
            coserve_scored.append({
                "status": status,
                "prob": prob,
                "in_range": (
                    prob is not None and 0.0 <= float(prob) <= 1.0
                ),
            })
        # per-entry census AFTER the co-served traffic: the combined
        # ladder must not have lowered anything post-warmup either
        census2 = {
            rid: _replica_healthz(*addr)
            for rid, addr in replica_addr.items()
        }
        report["coserved_combined"] = {
            "scored": coserve_scored,
            "replicas_restored": all(
                "stage2" in (h.get("models") or {})
                for h in census2.values()
            ),
            "zero_recompiles": all(
                (h.get("models") or {}).get("stage2", {}).get(
                    "steady_state_recompiles"
                ) == 0
                for h in census2.values()
            ),
            "ok": all(
                s["status"] == 200 and s["in_range"]
                for s in coserve_scored
            ),
        }

        # -- phase 2a: over-deadline burst shed BEFORE device time.
        # Evidence: every reply is a 503 `deadline`, and the replicas'
        # own request counters do not move.
        before = {
            rid: _replica_stats(*addr)["serve"].get("requests", 0)
            for rid, addr in replica_addr.items()
        }
        shed_statuses = []
        for code in list(codes.values())[:4]:
            status, resp = router_server.request(
                "POST", "/score",
                {"code": code, "deadline_ms": 0.001},
            )
            shed_statuses.append((status, resp.get("reason")))
        after = {
            rid: _replica_stats(*addr)["serve"].get("requests", 0)
            for rid, addr in replica_addr.items()
        }
        report["deadline_shed"] = {
            "statuses": shed_statuses,
            "replica_requests_before": before,
            "replica_requests_after": after,
            "no_device_time_spent": before == after,
            "all_shed": all(
                s == 503 and r == "deadline" for s, r in shed_statuses
            ),
        }
        # -- phase 2b: the token-bucket tenant gets 429 past its burst
        rate_statuses = []
        for code in list(codes.values())[:3]:
            status, _ = router_server.request(
                "POST", "/score", {"code": code},
                headers={"X-Tenant": "burst"},
            )
            rate_statuses.append(status)
        report["rate_limit"] = {
            "statuses": rate_statuses,
            "ok": rate_statuses[:2] == [200, 200]
            and rate_statuses[2] == 429,
        }

        # -- phase 3: SIGKILL r0 with requests genuinely in flight —
        # the concurrent senders start FIRST, the kill lands while they
        # run, so the router sees the whole failure spectrum (refused
        # connections AND sockets reset mid-request) and must retry
        # every one on the survivor
        victim = procs[0]
        survivor_id = procs[1][0]
        results: list[dict] = []
        lock = threading.Lock()

        def one(name: str, code: str) -> None:
            status, resp = router_server.request(
                "POST", "/score", {"code": code}
            )
            with lock:
                results.append({
                    "name": name, "status": status,
                    "prob": resp.get("prob"),
                    "bit_identical": resp.get("prob") == baseline[name],
                })

        threads = [
            threading.Thread(target=one, args=(n, c))
            for n, c in codes.items()
        ]
        for t in threads:
            t.start()
        os.kill(victim[1].pid, signal.SIGKILL)
        for t in threads:
            t.join(timeout=120)
        victim[1].wait(timeout=30)
        topo = router.topology()
        from deepdfa_tpu.obs import metrics as obs_metrics

        snap = obs_metrics.REGISTRY.snapshot()
        report["failover"] = {
            "killed": victim[0],
            "responses": len(results),
            "all_ok": len(results) == len(codes) and all(
                r["status"] == 200 and r["bit_identical"]
                for r in results
            ),
            "ejects": snap.get("fleet/ejects", 0),
            "retries": snap.get("fleet/retries", 0),
            "survivor_routable": any(
                r["id"] == survivor_id and r["routable"]
                for r in topo["replicas"]
            ),
        }

        # -- phase 4: graceful drain of the survivor
        sproc = procs[1][1]
        sproc.send_signal(signal.SIGTERM)

        def _drain_progress() -> str | None:
            with router._lock:
                rep = router._replicas.get(survivor_id)
                if rep is not None and rep.drain_logged:
                    return "observed"
            if sproc.poll() is not None:
                return "exited"
            return None

        drain_seen = coord.poll_until(
            _drain_progress, 60.0, interval_s=0.05, max_interval_s=0.25,
            what=f"drain observation on {survivor_id}",
        ) == "observed"
        rc = sproc.wait(timeout=60)
        hb = heartbeat.read_heartbeat(
            heartbeat.heartbeat_path(fleet_dir, survivor_id)
        )
        pm_path = fleet_dir / survivor_id / "postmortem.json"
        pm = (
            obs_flight.validate_postmortem_file(pm_path)
            if pm_path.exists()
            else {"ok": False, "problems": ["no postmortem dumped"]}
        )
        final_log = fleet_dir / survivor_id / "serve_log.jsonl"
        report["drain"] = {
            "replica": survivor_id,
            "exit_code": rc,
            "router_observed": drain_seen,
            "final_heartbeat_state": hb.get("state") if hb else None,
            "postmortem": pm,
            "final_serve_log": final_log.exists(),
        }

        # -- phase: router HA restart (docs/fleet.md): the rendezvous
        # file resolves to the live front door, and a RESTARTED router
        # re-seeds its admission token-bucket levels from the log's
        # last summary record instead of handing every tenant a fresh
        # burst (the `kill-router` chaos scenario kills the process for
        # real; this phase pins the restart half in the smoke)
        fleet_ha.write_rendezvous(
            fleet_dir, "router-smoke", router_server.host,
            router_server.port, 1,
        )
        resolved = fleet_ha.resolve_router(fleet_dir)
        levels_before = router.admission.snapshot()["tokens"]
        router.log.append(router.summary_record())
        restarted = router_from_config(
            cfg, fleet_dir, log_path=run_dir / "fleet_log.jsonl"
        )
        levels_after = restarted.admission.snapshot()["tokens"]
        restarted.close()
        report["ha"] = {
            "rendezvous_resolved": resolved == (
                router_server.host, router_server.port
            ),
            "reseeded_levels_match": bool(levels_before) and all(
                abs(levels_after.get(t, -1e9) - lv) <= 1.0
                for t, lv in levels_before.items()
            ),
            "levels": levels_before,
        }

        router_server.close()  # appends the summary record
        router_server = None
        report["fleet_log"] = validate_fleet_log(
            run_dir / "fleet_log.jsonl"
        )
        report["fleet_log"]["path"] = str(run_dir / "fleet_log.jsonl")
    finally:
        if router_server is not None:
            router_server.close()
        for _, proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

    # -- phase 6: one scheduled chaos-drill round (stub fleet on a
    # FaultableBackend; the same scheduler `deepdfa-tpu fleet-drill`
    # runs on a cadence) — the DRILL record is the evidence
    import tempfile

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.fleet import autoscale as autoscale_mod
    from deepdfa_tpu.fleet import chaos as chaos_mod
    from deepdfa_tpu.fleet import drill as drill_mod

    # both phases run on the SAME tiny stub model (drill/autoscale use
    # identical data.feat/model overrides) — build it once
    stub_parts = chaos_mod.build_stub_parts(config_mod.apply_overrides(
        Config(), [
            'data.feat={"limit_all": 50, "limit_subkeys": 50}',
            "model.hidden_dim=8", "model.n_steps=2",
        ],
    ))

    with tempfile.TemporaryDirectory() as td:
        report["drill"] = drill_mod.DrillScheduler(
            runner=lambda i: drill_mod.run_smoke_drill(
                Path(td) / f"round{i}", parts=stub_parts
            ),
            rounds=1,
            interval_s=0.0,
            scenarios=drill_mod.SMOKE_SCENARIOS,
            mode="smoke",
        ).run()

    # -- phase 7: predictive autoscaling on a replayed ramp (stub
    # replica + real router; decisions land in its fleet_log)
    with tempfile.TemporaryDirectory() as td:
        report["autoscale"] = autoscale_mod.run_smoke_autoscale(
            td, parts=stub_parts
        )

    # -- phase 8: the fleet telemetry plane (obs/aggregate.py +
    # obs/alerts.py) end-to-end on a FaultableBackend: exact merged
    # percentiles through a live /metrics, cross-host trace stitching
    # with an unbroken flow chain under a torn write, and burn-rate +
    # per-tenant drift alerts firing and resolving as schema-valid
    # fleet_log records
    with tempfile.TemporaryDirectory() as td:
        report["telemetry"] = run_telemetry_smoke(td)

    # -- phase 9: the data flywheel (deepdfa_tpu/flywheel/,
    # docs/flywheel.md): a candidate rides a stub fleet as a shadow,
    # comparison windows land as schema-valid records, a losing
    # candidate is demoted without touching traffic, a drifting one is
    # halted + rolled back BY the real rollout gates, and the winning
    # one auto-promotes through run_rollout with zero lost open-loop
    # requests
    with tempfile.TemporaryDirectory() as td:
        report["flywheel"] = run_flywheel_smoke(td, parts=stub_parts)
    return report


def run_telemetry_smoke(tmp: str | Path) -> dict:
    """The telemetry smoke phase (also runnable standalone from the
    tests): two simulated replicas publish snapshots through a
    FaultableBackend (one slot write torn), a REAL router with
    `fleet.telemetry`/`fleet.alerts` on serves the aggregated /metrics,
    and the asserted facts are the ISSUE's acceptance criteria — the
    merged p99 EQUALS the brute-force percentile over the union of the
    replicas' (grid-quantized) samples, the stitched trace carries the
    router->replica flow chain unbroken past a torn segment line, and a
    burn-rate + a per-tenant drift alert fire and resolve as
    schema-valid {"alert": ...} fleet_log records."""
    import random

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.fleet import coord
    from deepdfa_tpu.fleet.router import (
        BackgroundRouter, router_from_config, validate_fleet_log,
    )
    from deepdfa_tpu.obs import (
        aggregate as obs_agg, alerts as obs_alerts,
        metrics as obs_metrics, trace as obs_trace,
    )
    from deepdfa_tpu.obs.slo import (
        SloEngine, parse_exposition, percentile,
    )

    out: dict = {}
    tmp = Path(tmp)
    fleet_dir = tmp / "fleet"
    fleet_dir.mkdir(parents=True, exist_ok=True)
    log_path = fleet_dir / "fleet_log.jsonl"
    backend = coord.FaultableBackend()
    rng = random.Random(19)

    # two simulated replicas with real SLO engines behind publishers
    engines: dict[str, SloEngine] = {}
    pubs: dict[str, object] = {}
    for rid in ("r0", "r1"):
        eng = SloEngine(windows=(60.0,))
        for _ in range(150):
            eng.observe_request(200, rng.lognormvariate(-3.0, 1.0))
        engines[rid] = eng
        pubs[rid] = obs_agg.SnapshotPublisher(
            fleet_dir, rid,
            slo_engines=lambda eng=eng: {"primary": eng},
            backend=backend,
        )
        pubs[rid].publish()

    # torn-write fault on r0's NEXT snapshot write: the two-slot scheme
    # must keep r0 visible (from the surviving slot), flagged not lost
    backend.set_fault("metrics-r0-*.json", torn_writes=1)
    for _ in range(10):
        engines["r0"].observe_request(
            200, rng.lognormvariate(-3.0, 1.0)
        )
    pubs["r0"].publish()  # lands torn
    probe = obs_agg.FleetAggregator(
        fleet_dir, backend=backend, stale_after_s=3600.0
    )
    col = probe.collect()
    out["torn_slot_survived"] = (
        "r0" in col["replicas"]
        and bool(col["problems"])
        and col["replicas"]["r0"]["snapshot"]["seq"] == 0
    )
    # heal (the torn fault is consumed): final clean snapshots
    for rid in ("r0", "r1"):
        pubs[rid].publish()

    # the live router, telemetry + alerts on, same faultable backend
    cfg = config_mod.apply_overrides(Config(), [
        "fleet.telemetry=true",
        "fleet.alerts=true",
        "fleet.telemetry_interval_s=0.2",
        "fleet.alert_interval_s=0.05",
        "fleet.heartbeat_timeout_s=3600.0",
        "fleet.poll_interval_s=0.05",
        "serve.slo_windows=[60]",
    ])
    router = router_from_config(
        cfg, fleet_dir, log_path=log_path, backend=backend
    )
    # the smoke's alert rules: fast burn-rate windows + a per-tenant
    # drift watch, swapped in over the default catalog so firing and
    # resolution both land inside the phase budget
    router.alerts = obs_alerts.AlertEngine(
        [
            obs_alerts.AlertRule(
                name="serve_high_error_rate", kind="burn_rate",
                threshold=1.0, for_s=0.0, windows=(0.5, 1.5),
                params={"budget": 0.05, "min_count": 3},
            ),
            obs_alerts.AlertRule(
                name="acme_drift", kind="drift",
                threshold=0.2, for_s=0.0, windows=(20.0,),
                params={
                    "tenant": "acme", "temperature": 1.0,
                    "band": (0.4, 0.6), "target": 0.1,
                    "min_samples": 10,
                },
            ),
        ],
        sink=router.log.append,
    )
    server = BackgroundRouter(router)
    try:
        # -- exact merged percentiles through the live scrape
        status, text = server.request_text("GET", "/metrics")
        assert status == 200, status
        fams = parse_exposition(text)
        lat = fams.get("deepdfa_fleet_agg_latency_ms") or {"samples": []}
        got = [
            v for labels, v in lat["samples"]
            if 'replica="fleet"' in labels
            and 'stage="total"' in labels
            and 'quantile="0.99"' in labels
        ]
        union: list[float] = []
        for eng in engines.values():
            h = obs_agg.FixedBucketHistogram()
            h.observe_all(eng.latency_samples()["60s"]["total"])
            union.extend(h.expand())
        want = percentile(sorted(union), 0.99) * 1e3
        out["merged_p99_ms"] = got[0] if got else None
        out["merged_p99_exact"] = got == [want]
        out["fleet_scrape"] = obs_agg.validate_fleet_scrape(text)
        status, stats = server.request("GET", "/stats")
        tele = stats.get("fleet_telemetry") or {}
        out["stats_fleet_section"] = {"r0", "r1"} <= set(
            tele.get("replicas") or {}
        )

        # -- cross-host trace stitching under a torn segment write
        tr_router = obs_trace.Tracer(
            tmp / "tr_router", process_name="router"
        )
        tr_replica = obs_trace.Tracer(
            tmp / "tr_replica", process_name="replica-r0"
        )
        flow_id = "req-telemetry-1"
        t_us = obs_trace.Tracer.now_us()
        tr_router.emit({
            "name": "router_forward", "cat": "fleet", "ph": "X",
            "ts": t_us, "dur": 500.0,
            "args": {"request_id": flow_id},
        })
        tr_router.emit({
            "name": "request", "cat": "fleet", "ph": "s",
            "id": flow_id, "ts": t_us + 10.0,
        })
        ship_router = obs_agg.TraceShipper(
            fleet_dir, "router", backend=backend, tracer=tr_router
        )
        ship_replica = obs_agg.TraceShipper(
            fleet_dir, "r0-trace", backend=backend, tracer=tr_replica
        )
        t2 = obs_trace.Tracer.now_us()
        tr_replica.emit({
            "name": "request", "cat": "fleet", "ph": "t",
            "id": flow_id, "ts": t2,
        })
        ship_router.ship()
        ship_replica.ship()  # anchor + the flow arrival, clean
        # the torn fault hits the replica's NEXT shipped line (the
        # pack span), never the anchor — the stitch must drop exactly
        # that line and keep the chain
        snap0 = obs_metrics.REGISTRY.snapshot()
        backend.set_fault("trace-seg-r0-trace.jsonl", torn_writes=1)
        for i, name in enumerate(("pack", "dispatch", "fetch")):
            tr_replica.emit({
                "name": name, "cat": "serve", "ph": "X",
                "ts": t2 + 10.0 * (i + 1), "dur": 8.0,
            })
        tr_replica.emit({
            "name": "request", "cat": "fleet", "ph": "f",
            "id": flow_id, "ts": t2 + 50.0,
        })
        ship_replica.ship()
        stitch = obs_agg.stitch_fleet_trace(
            fleet_dir, tmp / "fleet_trace.json", backend=backend
        )
        snap1 = obs_metrics.REGISTRY.snapshot()
        out["trace"] = {
            "unbroken_flow": flow_id in stitch["unbroken_flows"],
            "events": stitch["events"],
            "sources": sorted(stitch["sources"]),
            "torn_write_injected": (
                snap1.get("coord/faults/torn_write", 0)
                > snap0.get("coord/faults/torn_write", 0)
            ),
        }

        # -- alerts: error burst + calibrated-prob drift through the
        # router's own request epilogue; the poll loop evaluates and
        # sinks transitions into the fleet_log
        for i in range(40):
            router.log_request(
                f"ok-{i}", 200, 0.01, tenant="acme", priority=0,
                prob=0.9,
            )
        for i in range(40):
            router.log_request(
                f"err-{i}", 500, 0.01, tenant="acme", priority=0,
                prob=0.5,
            )
        fired = coord.poll_until(
            lambda: (
                {"serve_high_error_rate", "acme_drift"}
                <= set(router.alerts.firing())
            ) or None,
            10.0, interval_s=0.05, what="smoke alerts firing",
        )
        # recovery traffic until both resolve (the burn windows drain
        # in <= 1.5 s of clean traffic)
        def _resolved():
            for i in range(10):
                router.log_request(
                    f"heal-{i}", 200, 0.01, tenant="acme",
                    priority=0, prob=0.9,
                )
            return (not router.alerts.firing()) or None

        resolved = coord.poll_until(
            _resolved, 15.0, interval_s=0.1,
            what="smoke alerts resolving",
        )
        out["alerts"] = {
            "fired": bool(fired),
            "resolved": bool(resolved),
        }
    finally:
        server.close()

    log_report = validate_fleet_log(log_path)
    states: dict[str, set] = {}
    for rec in backend.tail_records(log_path, 16 << 20):
        if "alert" in rec:
            states.setdefault(rec["alert"]["rule"], set()).add(
                rec["alert"]["state"]
            )
    out["alerts"]["burn_fired_resolved"] = {
        "firing", "resolved"
    } <= states.get("serve_high_error_rate", set())
    out["alerts"]["drift_fired_resolved"] = {
        "firing", "resolved"
    } <= states.get("acme_drift", set())
    out["alerts"]["records_valid"] = log_report["ok"]
    out["fleet_log"] = {
        "ok": log_report["ok"],
        "alerts": log_report["alerts"],
        "problems": log_report["problems"][:5],
    }
    out["ok"] = bool(
        out["torn_slot_survived"]
        and out["merged_p99_exact"]
        and out["fleet_scrape"]["ok"]
        and out["stats_fleet_section"]
        and out["trace"]["unbroken_flow"]
        and out["trace"]["torn_write_injected"]
        and out["alerts"]["burn_fired_resolved"]
        and out["alerts"]["drift_fired_resolved"]
        and out["alerts"]["records_valid"]
    )
    return out


def run_flywheel_smoke(tmp: str | Path, parts=None) -> dict:
    """The `fleet --smoke` flywheel phase (<60 s, in-process): the full
    closed loop from ISSUE 20's acceptance criteria, against a stub
    fleet whose replicas speak the REAL /admin/rollout protocol.

    1. two incumbent replicas + one shadow replica (candidate params,
       `shadow: true` heartbeat) behind a real router with
       `fleet.flywheel` on — the router's sampler mirrors every scored
       request into the sample stream, and the shadow must never be
       routed live traffic;
    2. a LOSING ride: labels adversarial to the candidate -> the
       window verdict demotes it ("trailing") with zero swaps;
    3. a DRIFTING ride: the window verdict promotes, but the stub
       checkpoint carries injected calibration drift on r1 -> the
       real run_rollout swaps r0, gets the 409 refusal from r1, halts,
       and rolls r0 back — the PR-14 gates covering an automated
       promotion, recorded as promotion(rollout_ok=false) +
       demotion("rollout_halted");
    4. a WINNING ride: the candidate auto-promotes through run_rollout
       (drift gate + armed SLO guard) onto both incumbents while
       open-loop traffic runs — zero lost requests, zero
       steady-state recompiles;
    5. the fleet_log validates with shadow/promotion/demotion counts.

    Labels ride the request bodies (the /score contract ignores
    unknown keys); they are constructed from the two models' rank
    DISAGREEMENT — positives where the candidate ranks a code higher
    than the incumbent does — so "candidate beats incumbent" is true
    by construction for the winning ride and false for the inverted
    losing ride, deterministically.
    """
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.fleet import chaos as fleet_chaos, coord
    from deepdfa_tpu.fleet.router import (
        BackgroundRouter, router_from_config, validate_fleet_log,
    )
    from deepdfa_tpu.flywheel import promote as promote_mod
    from deepdfa_tpu.flywheel import shadow as shadow_mod

    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
        "serve.max_batch_graphs=1",
        "serve.node_budget=2048", "serve.edge_budget=8192",
        "serve.slo_windows=[5, 60]",
        "fleet.heartbeat_timeout_s=3600.0",
        "fleet.poll_interval_s=0.05",
        "fleet.request_timeout_s=10.0",
        "fleet.drain_announce_s=0.0",
        "fleet.rollout_settle_s=0.0",
        # armed SLO guard: a real p99 bound the stub traffic respects
        "fleet.rollout_p99_ms=30000.0",
        # the flywheel knobs, tightened to smoke scale: every request
        # sampled, one 12-sample window per ride decides
        "fleet.flywheel=true",
        "fleet.flywheel_sample_rate=1.0",
        "fleet.flywheel_max_inflight=256",
        "fleet.flywheel_min_samples=12",
        "fleet.flywheel_window=12",
        "fleet.flywheel_promote_margin=0.01",
        "fleet.flywheel_demote_margin=0.02",
        # the in-window drift gate stays open: the SWAP-TIME drift
        # gate (fleet.rollout_drift_bound) is the one this phase pins
        "fleet.flywheel_drift_bound=1.0",
    ])
    fcfg = cfg.fleet
    import jax

    from deepdfa_tpu.graphs.batch import pack

    model, params, vocabs, codes = (
        parts if parts is not None else fleet_chaos.build_stub_parts(cfg)
    )
    # the candidate: same architecture, decorrelated init — a genuinely
    # different scoring function for the comparison stream
    cand_params = model.init(jax.random.key(1), pack([], 1, 2048, 8192))

    fleet_dir = Path(tmp) / "flywheel"
    log_path = fleet_dir / "fleet_log.jsonl"
    out: dict = {}

    def ckpts(drift_r1: float) -> dict:
        return {
            "cand-good": (cand_params, 0.0),
            "cand-bad": (cand_params, 0.0),
            "cand-drift": (cand_params, drift_r1),
        }

    replicas = {
        rid: fleet_chaos.StubReplicaServer(
            cfg, fleet_dir, rid,
            fleet_chaos.stub_service(
                cfg, fleet_dir, rid, model, params, vocabs,
                # the injected-drift axis: r1's view of "cand-drift"
                # is past fleet.rollout_drift_bound, r0's is clean —
                # so the halt fires mid-rollout, after one real swap
                checkpoints=ckpts(0.9 if rid == "r1" else 0.0),
            ),
        )
        for rid in ("r0", "r1")
    }
    shadow_server = fleet_chaos.StubReplicaServer(
        cfg, fleet_dir, "rs",
        fleet_chaos.stub_service(
            cfg, fleet_dir, "rs", model, cand_params, vocabs,
            flywheel_tag="candidate",
        ),
        shadow=True,
    )
    router = router_from_config(cfg, fleet_dir, log_path=log_path)
    server = BackgroundRouter(router)
    traffic = None
    try:
        coord.poll_until(
            lambda: (
                router.routable_count() >= 2
                and "rs" in router._replicas
            ) or None,
            30.0, interval_s=0.05, what="flywheel stub fleet routable",
        )
        rs_view = router._replicas["rs"]
        out["shadow_not_routable"] = not rs_view.routable(
            fcfg.heartbeat_timeout_s, time.time()
        )

        # -- probe both scoring functions to build the rank-diff labels
        probe = codes[:16]
        inc_probs, cand_probs = [], []
        for code in probe:
            status, resp = server.request("POST", "/score", {"code": code})
            assert status == 200, (status, resp)
            inc_probs.append(float(resp["prob"]))
            status, resp = fleet_chaos.http_json(
                shadow_server.host, shadow_server.port,
                "POST", "/score", {"code": code},
            )
            assert status == 200, (status, resp)
            cand_probs.append(float(resp.get("calibrated_prob",
                                             resp.get("prob"))))
        out["shadow_answers_mirror"] = True

        def ranks(xs):
            order = sorted(range(len(xs)), key=lambda i: xs[i])
            r = [0] * len(xs)
            for pos, i in enumerate(order):
                r[i] = pos
            return r
        diff = [c - i for c, i in zip(ranks(cand_probs), ranks(inc_probs))]
        by_diff = sorted(range(len(probe)), key=lambda i: diff[i])
        ride_codes = [probe[i] for i in by_diff[-6:] + by_diff[:6]]
        win_labels = [1] * 6 + [0] * 6

        score_fn = shadow_mod.http_score_fn(
            shadow_server.host, shadow_server.port
        )

        def ride(name: str, labels, last_seq: int):
            scorer = shadow_mod.ShadowScorer(
                fleet_dir, name, "init", score_fn, log=router.log,
                window=fcfg.flywheel_window,
                min_samples=fcfg.flywheel_min_samples,
                promote_margin=fcfg.flywheel_promote_margin,
                demote_margin=fcfg.flywheel_demote_margin,
                drift_bound=fcfg.flywheel_drift_bound,
            )
            scorer.last_seq = last_seq
            scorer.ride_start()
            for code, y in zip(ride_codes, labels):
                status, resp = server.request(
                    "POST", "/score", {"code": code, "label": y}
                )
                assert status == 200, (status, resp)

            def _scored() -> bool | None:
                scorer.poll()
                return (
                    scorer.comparator.total >= len(ride_codes)
                ) or None

            coord.poll_until(
                _scored, 30.0, interval_s=0.05,
                what=f"shadow scoring for {name}",
            )
            scorer.ride_end()
            return scorer

        # -- losing ride: inverted labels -> demote("trailing"), no
        # swap (each scorer starts past the samples the previous phase
        # produced, so one ride = exactly one decided window)
        seq0 = router.flywheel._seq  # the warmup probes, sampled too
        scorer = ride("cand-bad", [1 - y for y in win_labels], seq0)
        rep = promote_mod.run_promotion(
            cfg, fleet_dir, "cand-bad", log_path,
            router_addr=(server.host, server.port),
        )
        out["losing"] = {
            "action": rep["action"], "reason": rep["reason"],
            "swaps": sum(
                r.service.registry.hot_swaps for r in replicas.values()
            ),
        }

        # -- drifting ride: promote verdict, but r1's 409 halts the
        # rollout and r0 is rolled back by the real halt path
        scorer = ride("cand-drift", win_labels, scorer.last_seq)
        rep = promote_mod.run_promotion(
            cfg, fleet_dir, "cand-drift", log_path,
            router_addr=(server.host, server.port),
        )
        ro = rep.get("rollout") or {}
        out["drift_halt"] = {
            "action": rep["action"], "reason": rep["reason"],
            "halted": bool(ro.get("halted")),
            "swapped": ro.get("swapped"),
            "rolled_back": [
                e.get("replica") for e in ro.get("rolled_back") or []
            ],
            "r0_restored": (
                replicas["r0"].service.registry.checkpoint == "init"
            ),
            "r1_refused": (
                replicas["r1"].service.registry.checkpoint == "init"
            ),
        }

        # -- winning ride: auto-promotion through the real rollout path
        # under open-loop traffic (zero lost requests is the bar)
        scorer = ride("cand-good", win_labels, scorer.last_seq)
        traffic = fleet_chaos.OpenLoopTraffic(
            lambda: (server.host, server.port), codes[:4],
            rate_per_sec=25.0, tenant="flywheel", seed=7,
        ).start()
        # let arrivals straddle the whole swap sequence — "zero lost"
        # must be a claim about requests that actually flew
        time.sleep(0.4)
        rep = promote_mod.run_promotion(
            cfg, fleet_dir, "cand-good", log_path,
            router_addr=(server.host, server.port),
        )
        time.sleep(0.2)
        results = traffic.stop()
        traffic = None
        ro = rep.get("rollout") or {}
        out["winning"] = {
            "action": rep["action"], "reason": rep["reason"],
            "rollout_ok": bool(ro.get("ok")),
            "swapped": ro.get("swapped"),
            "census_ok": bool(ro.get("census_ok")),
            "promoted_everywhere": all(
                r.service.registry.checkpoint == "cand-good"
                for r in replicas.values()
            ),
            "lost": sum(1 for r in results if r.get("status") == 0),
            "requests": len(results),
        }
        out["shadow_never_routed"] = router._replicas["rs"].forwarded == 0
        out["zero_recompiles"] = all(
            r.service.steady_state_recompiles() == 0
            for r in replicas.values()
        )
        out["sampler_sampled"] = router.flywheel._seq > seq0
    finally:
        if traffic is not None:
            traffic.stop()
        server.close()
        for r in replicas.values():
            r.close()
        shadow_server.close()

    log_report = validate_fleet_log(log_path)
    out["fleet_log"] = {
        "ok": log_report["ok"],
        "shadow": log_report["shadow"],
        "promotions": log_report["promotions"],
        "demotions": log_report["demotions"],
        "problems": log_report["problems"][:5],
    }
    out["ok"] = bool(
        out.get("shadow_not_routable")
        and out.get("shadow_never_routed")
        and out.get("sampler_sampled")
        and (out.get("losing") or {}).get("action") == "demote"
        and (out.get("losing") or {}).get("swaps") == 0
        and (out.get("drift_halt") or {}).get("halted")
        and (out.get("drift_halt") or {}).get("r0_restored")
        and (out.get("winning") or {}).get("rollout_ok")
        and (out.get("winning") or {}).get("promoted_everywhere")
        and (out.get("winning") or {}).get("lost") == 0
        and (out.get("winning") or {}).get("requests", 0) > 0
        and out.get("zero_recompiles")
        and out["fleet_log"]["ok"]
        and out["fleet_log"]["shadow"] >= 3
        and out["fleet_log"]["promotions"] >= 1
        and out["fleet_log"]["demotions"] >= 2
    )
    return out


def smoke_verdict(report: dict) -> list[str]:
    """The failed acceptance criteria (empty = the smoke passed) — one
    place `cmd_fleet` and the tests read the contract from."""
    bad: list[str] = []
    if not report.get("bit_identical"):
        bad.append("router scores != singleton scores (bit parity)")
    if not report.get("both_replicas_served"):
        bad.append("traffic did not spread across both replicas")
    if not report.get("zero_recompiles_per_replica"):
        bad.append("steady-state recompiles on a replica")
    co = report.get("coserved_combined") or {}
    if not co.get("ok"):
        bad.append("co-served combined entry did not answer 200")
    if not co.get("replicas_restored"):
        bad.append("a replica failed to restore the combined entry")
    if not co.get("zero_recompiles"):
        bad.append("steady-state recompiles on the combined entry")
    ds = report.get("deadline_shed") or {}
    if not (ds.get("all_shed") and ds.get("no_device_time_spent")):
        bad.append("over-deadline burst not shed before device time")
    if not (report.get("rate_limit") or {}).get("ok"):
        bad.append("token-bucket tenant not rate-limited")
    fo = report.get("failover") or {}
    if not fo.get("all_ok"):
        bad.append("failover lost or mis-scored a request")
    if not fo.get("ejects"):
        bad.append("killed replica was never ejected")
    dr = report.get("drain") or {}
    if dr.get("exit_code") != 0:
        bad.append("drained replica exited nonzero")
    if not dr.get("router_observed"):
        bad.append("router never observed the drain state")
    if dr.get("final_heartbeat_state") != "drained":
        bad.append("final heartbeat state is not 'drained'")
    if not (dr.get("postmortem") or {}).get("ok"):
        bad.append("drain postmortem missing or invalid")
    if not dr.get("final_serve_log"):
        bad.append("no final SLO snapshot in the replica serve log")
    if not (report.get("fleet_log") or {}).get("ok"):
        bad.append("fleet_log.jsonl failed schema validation")
    ha_phase = report.get("ha") or {}
    if not ha_phase.get("rendezvous_resolved"):
        bad.append("router.json rendezvous did not resolve")
    if not ha_phase.get("reseeded_levels_match"):
        bad.append(
            "restarted router did not re-seed admission levels from "
            "the last summary record"
        )
    dd = report.get("drill") or {}
    if not dd.get("ok"):
        bad.append(
            "drill round failed or failover missed the documented "
            "3.2 s bound (fleet/drill.py)"
        )
    az = report.get("autoscale") or {}
    if not (az.get("scaled") and az.get("scaled_ahead")):
        bad.append("autoscale did not scale ahead of predicted load")
    if az.get("ladder_before_scale") is not True:
        bad.append("autoscale degradation ladder out of order")
    if (az.get("burst") or {}).get("lost") != 0:
        bad.append("autoscale ramp lost requests")
    if not (
        (az.get("fleet_log") or {}).get("ok") and az.get("ramp_log_ok")
    ):
        bad.append("autoscale decision records failed validation")
    tm = report.get("telemetry") or {}
    if not tm.get("merged_p99_exact"):
        bad.append(
            "federated p99 != brute-force percentile over the union of "
            "replica samples (histogram merge must be exact)"
        )
    if not tm.get("torn_slot_survived"):
        bad.append("aggregator dropped a replica on a torn snapshot write")
    if not (tm.get("fleet_scrape") or {}).get("ok"):
        bad.append("fleet /metrics scrape failed schema validation")
    if not (tm.get("trace") or {}).get("unbroken_flow"):
        bad.append(
            "cross-host request flow chain broke in the stitched trace"
        )
    al = tm.get("alerts") or {}
    if not (al.get("burn_fired_resolved") and al.get("drift_fired_resolved")):
        bad.append("burn-rate or drift alert did not fire and resolve")
    if not al.get("records_valid"):
        bad.append("an alert record failed schema validation")
    fw = report.get("flywheel") or {}
    if not (fw.get("shadow_not_routable") and fw.get("shadow_never_routed")):
        bad.append("router routed (or would route) live traffic to the "
                   "shadow replica")
    if (fw.get("losing") or {}).get("action") != "demote" or (
        fw.get("losing") or {}
    ).get("swaps") != 0:
        bad.append("losing candidate was not refused without a swap")
    dh = fw.get("drift_halt") or {}
    if not (dh.get("halted") and dh.get("r0_restored")):
        bad.append("injected bad candidate did not halt + roll back "
                   "through the real rollout gates")
    wn = fw.get("winning") or {}
    if not (wn.get("rollout_ok") and wn.get("promoted_everywhere")):
        bad.append("winning candidate did not auto-promote via the "
                   "rollout path")
    if wn.get("lost") != 0 or not wn.get("requests"):
        bad.append("flywheel promotion lost open-loop requests (or none "
                   "flew during the swap window)")
    if not fw.get("zero_recompiles"):
        bad.append("steady-state recompiles on an incumbent during the "
                   "flywheel phase")
    if not (fw.get("fleet_log") or {}).get("ok"):
        bad.append("a shadow/promotion/demotion record failed schema "
                   "validation")
    return bad
