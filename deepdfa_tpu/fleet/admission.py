"""Per-tenant admission control + deadline-aware load shedding
(docs/fleet.md).

The router calls `AdmissionController.decide()` once per ingress
request, BEFORE any frontend or device time is spent — the whole point
of shedding at the front door is that an over-deadline or over-budget
request costs one dict lookup, not a feature extraction and a padded
batch slot. Three independent mechanisms, checked in order:

1. **capacity** — no routable replica => 503 `no_replicas`.
2. **tenant token buckets** — each tenant owns a `rate`/`burst` bucket
   (unlisted tenants share the default policy, each still getting their
   OWN bucket so one noisy unlisted tenant cannot starve another).
   An empty bucket => 429 `rate_limit` (the retry-later signal).
3. **deadline + overload shed** — the controller keeps an EWMA of
   observed service time; a request declaring `deadline_ms` that cannot
   be met at the current fleet queue depth is shed 503 `deadline`.
   Separately, past `shed_fraction` of estimated fleet capacity,
   priority>0 (non-interactive) requests are shed 503 `overload` so
   interactive traffic keeps its latency while batch traffic backs off.
4. **cascade-aware shed** (docs/cascade.md) — a request marked
   `cascade_stage=2` (a stage-2 escalation re-entering through the
   router) sheds at `cascade_shed_fraction` of the overload capacity,
   BEFORE plain traffic sheds: under overload the cascade degrades to
   stage-1-only screening first — the natural degradation mode, since
   every shed escalation still has its stage-1 answer.

Every decision lands in `fleet/*` registry metrics (admitted and shed,
by tenant and by priority class) so shed-rate is a first-class SLO
observable, and the verdict carries enough to log (tenant, priority,
reason, estimate) without re-deriving anything.

`plan_coserving` is the PR-10 capacity arbiter for multi-model
co-serving: given the per-entry param-bytes ledger signal and an HBM
budget, which registry entries fit one host. Pure function — the
replica uses it at load time, tests drive it directly.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time

from deepdfa_tpu.obs import metrics as obs_metrics

#: priority classes (lower = more important); the overload shed spares
#: class 0 (interactive) and sheds the rest first
INTERACTIVE, BATCH, BEST_EFFORT = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's admission contract."""

    name: str
    rate: float  # sustained tokens/second
    burst: float  # bucket capacity (instantaneous burst allowance)
    priority: int = BATCH

    def __post_init__(self):
        if self.rate < 0 or self.burst <= 0:
            raise ValueError(
                f"tenant {self.name!r}: rate must be >=0 and burst >0 "
                f"(got rate={self.rate}, burst={self.burst})"
            )
        if self.priority < 0:
            raise ValueError(
                f"tenant {self.name!r}: priority must be >=0"
            )


def parse_tenants(spec: str) -> dict[str, TenantPolicy]:
    """cfg.fleet.tenants JSON -> {name: TenantPolicy}; '' -> {}."""
    if not spec:
        return {}
    raw = json.loads(spec)
    if not isinstance(raw, dict):
        raise ValueError(
            f"fleet.tenants must be a JSON object, got {type(raw).__name__}"
        )
    out: dict[str, TenantPolicy] = {}
    for name, p in raw.items():
        if not isinstance(p, dict):
            raise ValueError(f"tenant {name!r} policy must be an object")
        out[name] = TenantPolicy(
            name=name,
            rate=float(p.get("rate", 1.0)),
            burst=float(p.get("burst", max(1.0, float(p.get("rate", 1.0))))),
            priority=int(p.get("priority", BATCH)),
        )
    return out


class TokenBucket:
    """Classic token bucket: `burst` capacity refilled at `rate`/s.
    Starts full (a tenant's first burst is the allowance, not a cold
    penalty). Thread-safe."""

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t = float(now)
        self._lock = threading.Lock()

    def try_take(self, now: float, n: float = 1.0) -> bool:
        with self._lock:
            dt = max(0.0, now - self._t)
            self._t = now
            self.tokens = min(self.burst, self.tokens + dt * self.rate)
            if self.tokens >= n:
                self.tokens -= n
                return True
            return False


@dataclasses.dataclass(frozen=True)
class Decision:
    """One admission verdict; `status` is the HTTP code a shed maps to
    (429 back-off vs 503 overload/deadline — different caller action)."""

    admit: bool
    tenant: str
    priority: int
    status: int = 200
    reason: str = "admitted"
    estimated_wait_ms: float | None = None


class AdmissionController:
    """The router's front-door policy engine (one per router process).

    `clock` is injectable so the bucket-refill and EWMA tests are
    deterministic; production uses time.monotonic."""

    #: cap on DISTINCT unlisted tenants tracked (own bucket + counters);
    #: past it, new unlisted tenants collapse into one shared overflow
    #: label — tenant names are client-controlled bytes, and unbounded
    #: per-tenant state in the load-shedding component is a DoS vector
    MAX_DYNAMIC_TENANTS = 1024
    OVERFLOW_TENANT = "_other"

    def __init__(
        self,
        tenants: dict[str, TenantPolicy] | None = None,
        default_rate: float = 100.0,
        default_burst: float = 200.0,
        default_priority: int = BATCH,
        replica_capacity: int = 64,
        shed_fraction: float = 1.0,
        service_time_init_ms: float = 50.0,
        cascade_shed_fraction: float = 0.75,
        clock=time.monotonic,
    ):
        self.clock = clock
        self.policies = dict(tenants or {})
        self.default_rate = float(default_rate)
        self.default_burst = float(default_burst)
        self.default_priority = int(default_priority)
        self.replica_capacity = int(replica_capacity)
        self.shed_fraction = float(shed_fraction)
        self.cascade_shed_fraction = float(cascade_shed_fraction)
        self._service_ewma_s = max(1e-6, service_time_init_ms / 1e3)
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}
        r = obs_metrics.REGISTRY
        self._m_admitted = r.counter("fleet/admitted")
        self._m_shed = r.counter("fleet/shed")

    # -- calibration ---------------------------------------------------------

    def observe_service(self, seconds: float, alpha: float = 0.2) -> None:
        """Fold one completed request's service time into the EWMA the
        deadline shed estimates against (the router calls this on every
        2xx completion)."""
        if seconds <= 0:
            return
        with self._lock:
            self._service_ewma_s = (
                (1 - alpha) * self._service_ewma_s + alpha * float(seconds)
            )

    @property
    def service_ewma_s(self) -> float:
        with self._lock:
            return self._service_ewma_s

    def estimate_wait_s(self, outstanding: int, healthy: int) -> float:
        """Expected completion time for a request admitted NOW: the
        fleet's outstanding work divided across healthy replicas, plus
        this request's own service time."""
        if healthy <= 0:
            return float("inf")
        ewma = self.service_ewma_s
        return (float(outstanding) / healthy + 1.0) * ewma

    # -- policy --------------------------------------------------------------

    def policy_for(self, tenant: str) -> TenantPolicy:
        p = self.policies.get(tenant)
        if p is not None:
            return p
        # unlisted tenants each get their own bucket (isolation) until
        # the dynamic-tenant cap; beyond it they share the overflow
        # label so a unique-tenant-per-request flood cannot grow state
        with self._lock:
            if (
                tenant not in self._buckets
                and len(self._buckets) >= self.MAX_DYNAMIC_TENANTS
            ):
                tenant = self.OVERFLOW_TENANT
        return TenantPolicy(
            name=tenant,
            rate=self.default_rate,
            burst=self.default_burst,
            priority=self.default_priority,
        )

    def _bucket_for(self, policy: TenantPolicy, now: float) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(policy.name)
            if b is None:
                b = self._buckets[policy.name] = TokenBucket(
                    policy.rate, policy.burst, now
                )
            return b

    def decide(
        self,
        tenant: str,
        outstanding: int,
        healthy: int,
        deadline_ms: float | None = None,
        priority: int | None = None,
        cascade_stage: int | None = None,
        now: float | None = None,
    ) -> Decision:
        """The one front-door verdict. A request may declare its own
        `priority`, but only to DEMOTE itself below its tenant policy's
        class — self-promotion to interactive would let any tenant
        bypass the overload shed, the exact isolation it provides.
        `cascade_stage=2` marks a stage-2 escalation, shed earlier than
        plain traffic under overload (docs/cascade.md shed order)."""
        now = self.clock() if now is None else now
        policy = self.policy_for(tenant)
        tenant = policy.name  # bounded label (dynamic-tenant overflow)
        prio = policy.priority
        if priority is not None:
            prio = max(prio, int(priority))

        def shed(status: int, reason: str, est_ms=None) -> Decision:
            self._m_shed.inc()
            r = obs_metrics.REGISTRY
            r.counter(f"fleet/shed/{reason}").inc()
            r.counter(f"fleet/tenant/{tenant}/shed").inc()
            r.counter(f"fleet/priority/{min(prio, 9)}/shed").inc()
            return Decision(
                admit=False, tenant=tenant, priority=prio,
                status=status, reason=reason, estimated_wait_ms=est_ms,
            )

        if healthy <= 0:
            return shed(503, "no_replicas")
        if not self._bucket_for(policy, now).try_take(now):
            return shed(429, "rate_limit")
        est_s = self.estimate_wait_s(outstanding, healthy)
        est_ms = round(est_s * 1e3, 3)
        if deadline_ms is not None and est_ms > float(deadline_ms):
            return shed(503, "deadline", est_ms)
        capacity = self.shed_fraction * healthy * self.replica_capacity
        # shed order under load (docs/cascade.md): stage-2 escalations
        # first (they already hold a stage-1 answer), then priority>0
        if (
            cascade_stage is not None
            and int(cascade_stage) >= 2
            and outstanding >= self.cascade_shed_fraction * capacity
        ):
            return shed(503, "cascade_overload", est_ms)
        if prio > INTERACTIVE and outstanding >= capacity:
            return shed(503, "overload", est_ms)
        self._m_admitted.inc()
        obs_metrics.REGISTRY.counter(f"fleet/tenant/{tenant}/admitted").inc()
        return Decision(
            admit=True, tenant=tenant, priority=prio,
            estimated_wait_ms=est_ms,
        )

    def snapshot(self) -> dict:
        """Live policy/bucket view for /stats and the fleet log."""
        with self._lock:
            buckets = {
                name: round(b.tokens, 3) for name, b in self._buckets.items()
            }
            ewma_ms = round(self._service_ewma_s * 1e3, 3)
        return {
            "service_ewma_ms": ewma_ms,
            "tokens": buckets,
            "tenants": {
                name: {
                    "rate": p.rate, "burst": p.burst, "priority": p.priority,
                }
                for name, p in self.policies.items()
            },
        }

    def reseed(self, snapshot: dict, now: float | None = None) -> int:
        """Restore token-bucket levels + the service-time EWMA from a
        fleet_log summary record's admission snapshot — the router
        restart / HA-takeover path (docs/fleet.md): a new router must
        not hand every tenant a full burst the moment the old one dies,
        or a failover doubles the admitted load exactly when the fleet
        is most fragile. Returns the number of re-seeded buckets.

        Tolerant by contract: a malformed snapshot re-seeds nothing
        (fresh buckets are the safe default, never a crash), and levels
        are clamped to each tenant's burst so a stale record cannot
        grant more than the policy allows."""
        if not isinstance(snapshot, dict):
            return 0
        now = self.clock() if now is None else now
        n = 0
        tokens = snapshot.get("tokens")
        if isinstance(tokens, dict):
            for tenant, level in tokens.items():
                try:
                    level = float(level)
                except (TypeError, ValueError):
                    continue
                policy = self.policy_for(str(tenant))
                bucket = self._bucket_for(policy, now)
                with bucket._lock:
                    bucket.tokens = max(
                        0.0, min(policy.burst, level)
                    )
                    bucket._t = now
                n += 1
        ewma_ms = snapshot.get("service_ewma_ms")
        if isinstance(ewma_ms, (int, float)) and ewma_ms > 0:
            with self._lock:
                self._service_ewma_s = float(ewma_ms) / 1e3
        if n:
            obs_metrics.REGISTRY.counter("fleet_ha/reseeded_buckets").inc(n)
        return n


# ---------------------------------------------------------------------------
# multi-model co-serving capacity arbitration (PR-10 ledger signal)


def plan_coserving(
    param_bytes: dict[str, float], hbm_budget_bytes: float
) -> tuple[list[str], list[str]]:
    """Which registry entries fit one host, per the per-entry param-bytes
    ledger signal (obs/ledger.py:record_params — the co-serving capacity
    signal PR 10 built). Greedy in declaration order: the operator lists
    entries most-important-first, and an entry that would push the
    running total past the budget is refused (loaded, refused).

    budget <= 0 means unbudgeted: every entry fits (the single-model
    default, and hosts whose HBM the operator hasn't characterized)."""
    loaded: list[str] = []
    refused: list[str] = []
    if hbm_budget_bytes <= 0:
        return list(param_bytes), refused
    total = 0.0
    for name, nbytes in param_bytes.items():
        nbytes = float(nbytes)
        if total + nbytes <= float(hbm_budget_bytes):
            total += nbytes
            loaded.append(name)
        else:
            refused.append(name)
    return loaded, refused


#: working-set headroom over raw param bytes one replica needs: the AOT
#: executable ladder, activation buffers at the padded batch budgets,
#: and the restore-time double-residency window all live next to the
#: params — the 4x factor matches the per-phase HBM watermarks the
#: PR-10 ledger records for the serve smoke (docs/efficiency.md)
REPLICA_HEADROOM = 4.0


def plan_replicas(
    entry_bytes: dict[str, float],
    hbm_budget_bytes: float,
    default: int = 2,
    max_replicas: int = 16,
    headroom: float = REPLICA_HEADROOM,
) -> tuple[int, dict]:
    """Default replica count from the per-entry param-bytes ledger
    signal (ROADMAP item 2 remainder): when `fleet.replicas` is unset,
    how many full serving stacks fit the host's HBM budget.

    Rides `plan_coserving` for the entry arbitration (which entries one
    replica holds), then divides the budget by the loaded set's working
    set (param bytes x `headroom`). Unbudgeted hosts (budget <= 0) or
    unmeasurable entries fall back to `default`. Returns (n, plan) where
    the plan names every input — the caller logs it loudly, the count is
    never silent."""
    entry_bytes = {k: float(v) for k, v in (entry_bytes or {}).items()}
    loaded, refused = plan_coserving(entry_bytes, hbm_budget_bytes)
    per_replica = sum(entry_bytes[name] for name in loaded) * float(headroom)
    plan = {
        "entries": entry_bytes,
        "loaded": loaded,
        "refused": refused,
        "hbm_budget_bytes": float(hbm_budget_bytes),
        "headroom": float(headroom),
        "per_replica_bytes": per_replica,
    }
    if hbm_budget_bytes <= 0 or per_replica <= 0:
        plan["reason"] = (
            "unbudgeted" if hbm_budget_bytes <= 0 else "unmeasured"
        )
        plan["replicas"] = int(default)
        return int(default), plan
    n = max(1, min(int(max_replicas), int(hbm_budget_bytes // per_replica)))
    plan["reason"] = "ledger"
    plan["replicas"] = n
    return n, plan
