"""Router high availability: an active/standby pair over one fleet dir
(docs/fleet.md).

The PR-11 router was the fleet's one unreplicated process. This module
gives it the same treatment the replicas already get — announce, watch,
fail over — built on the announcement directory both routers share:

  router.json      the RENDEZVOUS file: the active router's own
                   heartbeat (addr + monotone epoch + t_unix), written
                   atomically on `fleet.rendezvous_interval_s`. Clients
                   re-resolve the front door from it after a failover
                   (`resolve_router`), the same way the fleet smoke's
                   clients already re-read replica heartbeats.
  fleet_log.jsonl  the shared log. The ACTIVE appends; periodic summary
                   records carry the admission snapshot (token-bucket
                   levels + service EWMA), which is exactly what the
                   standby re-seeds from at takeover — a failover must
                   not hand every tenant a fresh burst at the moment
                   the fleet is most fragile.

Roles, from the rendezvous file alone (no peer protocol):

  standby   sees a fresh rendezvous owned by someone else. Keeps its
            replica table warm by polling the heartbeat dir, serves no
            traffic, appends nothing.
  active    owns the rendezvous (highest epoch). Serves the front door,
            refreshes the file, appends to the log.

Failover: a rendezvous older than `fleet.router_failover_timeout_s`
marks the active presumed-dead. The standby double-checks with one
bounded `/healthz` probe (a stalled file write on a live router must
not trigger a split brain), then takes over: re-seed admission from the
log's last summary, bind its own front-door port, publish the
rendezvous at epoch+1. The documented failover window is
`router_failover_timeout_s + probe timeout + one standby poll`; past
`2x router_failover_timeout_s` the probe is overridden (a router that
answers healthz but cannot write its heartbeat is wedged, not healthy).

Fencing: every active refresh first READS the file — a higher epoch
means another router took over while this one was presumed dead, and
the superseded active steps down (stops serving, detaches the log)
instead of fighting. Epochs only grow, so exactly one router converges
to active. In-flight requests on a dead router are the client's retry;
no replica state is lost — replicas never see the failover at all.
"""

from __future__ import annotations

import http.client
import logging
import threading
import time
from pathlib import Path

from deepdfa_tpu.fleet import coord, router as router_mod
from deepdfa_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

#: the rendezvous file name under the fleet dir (fleet/coord.py owns it)
ROUTER_FILE = coord.ROUTER_FILE


def rendezvous_path(fleet_dir: str | Path) -> Path:
    return Path(fleet_dir) / ROUTER_FILE


def write_rendezvous(
    fleet_dir: str | Path,
    router_id: str,
    host: str,
    port: int,
    epoch: int,
    backend: coord.CoordinationBackend | None = None,
) -> Path:
    """Atomically publish the active router's heartbeat (unfenced — the
    bring-up/takeover form; the active's periodic refresh goes through
    the backend's FENCED `publish_rendezvous` instead)."""
    path = rendezvous_path(fleet_dir)
    (backend or coord.LOCAL).publish_rendezvous(
        path, router_id, host, port, epoch, force=True
    )
    return path


def read_rendezvous(
    fleet_dir: str | Path,
    backend: coord.CoordinationBackend | None = None,
) -> dict | None:
    """The parsed rendezvous, or None when absent/unreadable."""
    return (backend or coord.LOCAL).read_rendezvous(
        rendezvous_path(fleet_dir)
    )


def resolve_router(
    fleet_dir: str | Path,
    timeout_s: float = 0.0,
    backend: coord.CoordinationBackend | None = None,
) -> tuple[str, int] | None:
    """The client re-resolve helper: (host, port) of the current active
    router per the rendezvous file, optionally waiting up to `timeout_s`
    for one to appear (the post-failover window). Rides the shared
    bounded poll helper (coord.poll_until) — jittered backoff, capped
    so a waiting client still sees a fresh takeover promptly."""

    def _lookup() -> tuple[str, int] | None:
        rv = read_rendezvous(fleet_dir, backend=backend)
        if rv is not None:
            return str(rv["host"]), int(rv["port"])
        return None

    return coord.poll_until(
        _lookup, timeout_s, interval_s=0.05, max_interval_s=0.25,
        what="router rendezvous",
    )


class HARouter:
    """One member of the active/standby router pair.

    Wraps a fully-constructed `Router` (replica table, admission, SLO)
    whose front-door HTTP server only exists while this member is
    active. `start()` runs the role loop in a background thread (the
    in-process form the chaos smoke drives); `run()` blocks (the
    `fleet-router` CLI form)."""

    def __init__(
        self,
        cfg,
        fleet_dir: str | Path,
        router_id: str,
        host: str = "127.0.0.1",
        port: int = 0,
        log_path: str | Path | None = None,
        backend: coord.CoordinationBackend | None = None,
    ):
        self.cfg = cfg
        self.fleet_dir = Path(fleet_dir)
        self.router_id = str(router_id)
        self.host = host
        self.port = int(port)  # preferred; ephemeral fallback on takeover
        self.log_path = (
            Path(log_path) if log_path is not None
            else self.fleet_dir / "fleet_log.jsonl"
        )
        fcfg = cfg.fleet
        self.backend = backend or coord.backend_from_config(cfg)
        self.rendezvous_interval_s = float(fcfg.rendezvous_interval_s)
        self.failover_timeout_s = float(fcfg.router_failover_timeout_s)
        self.probe_timeout_s = min(2.0, self.failover_timeout_s)
        # the standby's router carries NO log handle: only the active
        # appends (attached at takeover, after the re-seed reads the
        # previous active's last summary)
        self.router = router_mod.router_from_config(
            cfg, self.fleet_dir, log_path=None, reseed=False,
            backend=self.backend,
        )
        self.role = "standby"
        self.epoch = 0
        self.httpd = None
        self._serve_thread: threading.Thread | None = None
        self._loop_thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._became_active = threading.Event()
        r = obs_metrics.REGISTRY
        self._m_takeovers = r.counter("fleet_ha/takeovers")
        self._m_stepdowns = r.counter("fleet_ha/stepdowns")
        self._m_role = r.gauge("fleet_ha/active")
        self._m_failover = r.gauge("fleet_ha/failover_seconds")
        self._m_role.set(0)

    # -- role loop -----------------------------------------------------------

    def start(self) -> None:
        if self._loop_thread is not None:
            return
        self.step()  # one synchronous tick: a lone starter is active
        self._loop_thread = threading.Thread(
            target=self._loop, name=f"fleet-ha-{self.router_id}",
            daemon=True,
        )
        self._loop_thread.start()

    def run(self) -> None:
        """Blocking form (`fleet-router` CLI); returns when closed."""
        self.step()
        while not self._closed.wait(self.rendezvous_interval_s):
            try:
                self.step()
            except Exception:
                logger.exception("ha router step failed")

    def _loop(self) -> None:
        while not self._closed.wait(self.rendezvous_interval_s):
            try:
                self.step()
            except Exception:
                logger.exception("ha router step failed")

    def step(self, now: float | None = None) -> None:
        """One role-loop tick: refresh-or-fence when active, watch-or-
        takeover when standby."""
        now = time.time() if now is None else now
        rv = read_rendezvous(self.fleet_dir, backend=self.backend)
        with self._lock:
            role = self.role
        if role == "active":
            # the fenced refresh is the backend's epoch contract
            # (coord.publish_rendezvous): a refusal means another
            # router took over at a higher epoch (or won the equal-
            # epoch tie) while this one was presumed dead (wedge,
            # stall) — never fight the epoch
            fencer = self.backend.publish_rendezvous(
                rendezvous_path(self.fleet_dir), self.router_id,
                self.host, self.port, self.epoch, force=False,
            )
            if fencer is not None:
                self.step_down(superseded_by=str(fencer["router_id"]))
            return
        # standby: keep the replica table warm, watch the active
        self.router.poll(force=True)
        if rv is not None and rv["router_id"] == self.router_id:
            # our own stale file (e.g. restarted in place): reclaim it
            self.take_over(rv)
            return
        if rv is not None:
            age = now - float(rv["t_unix"])
            if age <= self.failover_timeout_s:
                return
            # presumed dead; one bounded probe guards against a live
            # router whose file write stalled — but past twice the
            # window a healthz-answering router that cannot write its
            # heartbeat is wedged, and the fleet needs a front door
            if age <= 2 * self.failover_timeout_s and self._probe(rv):
                return
        self.take_over(rv)

    def _probe(self, rv: dict) -> bool:
        try:
            conn = http.client.HTTPConnection(
                str(rv["host"]), int(rv["port"]),
                timeout=self.probe_timeout_s,
            )
            try:
                conn.request("GET", "/healthz")
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except router_mod.TRANSPORT_ERRORS:
            return False

    # -- transitions ---------------------------------------------------------

    def take_over(self, rv: dict | None) -> None:
        """standby -> active: re-seed admission from the log's last
        summary, bind the front door, publish the rendezvous at
        epoch+1."""
        t0 = time.perf_counter()
        stale_epoch = int(rv["epoch"]) if rv is not None else 0
        reseeded = self.router.reseed_from_log(self.log_path)
        self.router.log = router_mod.FleetLog(
            self.log_path, backend=self.backend
        )
        try:
            self.httpd = router_mod.make_router_server(
                self.router, self.host, self.port
            )
        except OSError:
            # the preferred port is still held (a wedged predecessor on
            # this host): serve on an ephemeral one — clients re-resolve
            # the new addr from the rendezvous either way
            self.httpd = router_mod.make_router_server(
                self.router, self.host, 0
            )
        self.port = self.httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name=f"fleet-ha-serve-{self.router_id}", daemon=True,
        )
        self._serve_thread.start()
        self.router.start_polling()
        with self._lock:
            self.role = "active"
            self.epoch = stale_epoch + 1
        write_rendezvous(
            self.fleet_dir, self.router_id, self.host, self.port,
            self.epoch, backend=self.backend,
        )
        took = time.perf_counter() - t0
        self._m_takeovers.inc()
        self._m_role.set(1)
        self._m_failover.set(round(took, 3))
        self.router._event(
            "takeover", router=self.router_id, epoch=self.epoch,
            addr=f"{self.host}:{self.port}",
            reseeded_buckets=reseeded,
            takeover_seconds=round(took, 3),
        )
        self._became_active.set()
        logger.warning(
            "router %s took over (epoch %d) on %s:%d in %.3fs; "
            "re-seeded %d admission bucket(s)",
            self.router_id, self.epoch, self.host, self.port, took,
            reseeded,
        )

    def step_down(self, superseded_by: str | None = None) -> None:
        """active -> standby: stop serving, detach the log. The replica
        table and admission state stay warm — a later takeover re-seeds
        from the NEW active's summaries anyway."""
        with self._lock:
            if self.role != "active":
                return
            self.role = "standby"
        self._m_stepdowns.inc()
        self._m_role.set(0)
        self.router._event(
            "stepdown", router=self.router_id, epoch=self.epoch,
            **({"superseded_by": superseded_by} if superseded_by else {}),
        )
        self._stop_serving()
        if self.router.log is not None:
            self.router.log.close()
            self.router.log = None
        self._became_active.clear()
        logger.warning(
            "router %s stepped down (superseded by %s)",
            self.router_id, superseded_by,
        )

    def _stop_serving(self) -> None:
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None
        if self._serve_thread is not None:
            # bounded join (docs/fleet.md thread audit): a wedged serve
            # thread must not hang the step-down/close path
            self._serve_thread.join(timeout=10)
            self._serve_thread = None

    def wait_active(self, timeout_s: float = 30.0) -> bool:
        return self._became_active.wait(timeout_s)

    def kill(self) -> None:
        """Abrupt-death test hook (the in-process kill-router drill):
        drop the front door and every loop WITHOUT touching the
        rendezvous — exactly what SIGKILL leaves behind. The wrapped
        Router dies too (`Router.kill`): its poll loop and log handle
        stop without the final summary record, so a 'dead' active
        cannot keep appending frozen admission snapshots the next
        takeover would wrongly re-seed from."""
        self._closed.set()
        if self.httpd is not None:
            try:
                self.httpd.shutdown()
                self.httpd.server_close()
            except Exception:
                pass
            self.httpd = None
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10)
            self._serve_thread = None
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10)
            self._loop_thread = None
        self.router.kill()

    def close(self) -> None:
        """Graceful shutdown; every background thread joined with a
        timeout (a wedged thread can delay close, never hang it)."""
        self._closed.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10)
            self._loop_thread = None
        self._stop_serving()
        self.router.close()
