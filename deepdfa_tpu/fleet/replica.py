"""Fleet replica worker: one shared-nothing serving process
(docs/fleet.md).

Each replica is a FULL single-process serving stack — ModelRegistry +
RequestPreprocessor + DynamicBatcher with its own AOT-warmed bucket
ladders (zero steady-state recompiles per replica, the Morphling
invariant the fleet must preserve while scaling out) — plus the fleet
half:

- a heartbeat thread announcing the replica via an atomic JSON file
  (fleet/heartbeat.py): address, checkpoint identity, recompile census,
  the cached `BackendHealth` report, and the per-entry param-bytes
  ledger snapshot (the PR-10 co-serving capacity signal);
- multi-model co-serving: `fleet.models` entries each restore through
  their own registry and score through their own batcher; requests pick
  one with `{"model": name}`. How many entries actually load is
  arbitrated by `plan_coserving` against `fleet.hbm_budget_bytes` using
  measured param bytes — a refused entry is announced in the heartbeat,
  never silently dropped;
- graceful drain: SIGTERM/SIGINT (train/resilience.py's
  PreemptionHandler, reused) flips the heartbeat to `draining`, stops
  accepting, finishes every in-flight batch, appends a final SLO
  snapshot record to the replica's serve log, dumps a flight-recorder
  postmortem (obs/flight.py conventions), and exits 0 with the
  heartbeat left at `drained` — the router observes every step.

Per-replica obs home: `<fleet_dir>/<replica_id>/` holds the replica's
serve_log.jsonl, trace files, and postmortem.json so N replicas sharing
one run_dir never interleave writes.
"""

from __future__ import annotations

import json
import logging
import signal
import threading
import time
from http.server import ThreadingHTTPServer
from pathlib import Path

from deepdfa_tpu.fleet import (
    admission as fleet_admission,
    chaos as fleet_chaos,
    coord,
    heartbeat,
)
from deepdfa_tpu.obs import (
    flight as obs_flight,
    ledger as obs_ledger,
    metrics as obs_metrics,
)
from deepdfa_tpu.serve import server as serve_server
from deepdfa_tpu.serve.server import (
    RequestLog,
    ScoringService,
    UnknownModel,
    write_serve_log,
)

logger = logging.getLogger(__name__)

#: the primary model's entry name (requests without {"model": ...})
PRIMARY = "default"


def param_bytes(params) -> float:
    """Total parameter bytes of one params pytree — the same accounting
    obs/ledger.py:record_params uses, computed here so the heartbeat
    carries the capacity signal whether or not the ledger is enabled."""
    import numpy as np

    total = 0.0
    try:
        import jax

        leaves = jax.tree.leaves(params)
    except Exception:
        leaves = []
    for leaf in leaves:
        try:
            total += float(
                np.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize
            )
        except Exception:
            continue
    return total


def parse_model_spec(spec: str) -> tuple[str, str, str, str]:
    """One `fleet.models` entry:
    "name=[family:]run_dir[:checkpoint]" -> (name, family, run_dir,
    checkpoint). The optional leading family (deepdfa | combined | t5,
    serve/registry.py's table) lets one replica co-serve the combined/t5
    transformer next to the GGNN — the cascade's fleet-wide layout; a
    checkpoint with the @int8 suffix co-serves the quantized entry."""
    from deepdfa_tpu.serve.registry import CKPT_DIR_BY_FAMILY

    name, sep, rest = spec.partition("=")
    if not sep or not name or not rest:
        raise ValueError(
            f"fleet.models entry {spec!r} must be "
            f"name=[family:]run_dir[:checkpoint]"
        )
    family = "deepdfa"
    head, sep, tail = rest.partition(":")
    if sep and head in CKPT_DIR_BY_FAMILY:
        family, rest = head, tail
        if not rest:
            raise ValueError(
                f"fleet.models entry {spec!r} names family {head!r} but "
                f"no run_dir"
            )
    run_dir, sep, ckpt = rest.rpartition(":")
    if not sep or "/" in ckpt or not run_dir:
        run_dir, ckpt = rest, "best"
    return name, family, run_dir, ckpt


class _DrainingServer(ThreadingHTTPServer):
    """Handler threads are joined on close so a drain never abandons an
    in-flight response — but the join is BOUNDED. socketserver's own
    block_on_close join is UNBOUNDED, so one wedged handler (a stuck
    backend, an injected chaos stall) would hang the drain forever;
    `server_close` here joins with a shared deadline instead: the drain
    waits its bounded share for stragglers, logs what it abandoned, and
    completes (docs/fleet.md thread audit). The threads are DAEMON —
    tracked in our own list, not socketserver's — so an abandoned
    wedged handler cannot re-block the process at interpreter exit
    (threading._shutdown joins every non-daemon thread unbounded,
    which would undo the bounded drain)."""

    daemon_threads = True
    block_on_close = False  # socketserver's unbounded join stays off
    #: total budget for joining in-flight handler threads at close
    join_timeout_s = 30.0

    def process_request(self, request, client_address):
        # mirror ThreadingMixIn's tracking (daemon threads are dropped
        # from socketserver's list) so the bounded join below has the
        # thread list
        t = threading.Thread(
            target=self.process_request_thread,
            args=(request, client_address),
        )
        t.daemon = True
        if not hasattr(self, "_handler_threads"):
            self._handler_threads = []
        self._handler_threads = [
            x for x in self._handler_threads if x.is_alive()
        ]
        self._handler_threads.append(t)
        t.start()

    def server_close(self):
        super().server_close()
        deadline = time.monotonic() + float(self.join_timeout_s)
        wedged = []
        for t in list(getattr(self, "_handler_threads", ())):
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                wedged.append(t.name)
        if wedged:
            logger.error(
                "drain abandoned %d wedged handler thread(s) after "
                "%.0fs: %s", len(wedged), self.join_timeout_s, wedged,
            )


class ReplicaWorker:
    """One replica process: services + HTTP server + heartbeat +
    drain."""

    def __init__(
        self,
        cfg,
        run_dir: str | Path,
        replica_id: str,
        fleet_dir: str | Path | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        family: str = "deepdfa",
        shadow: bool = False,
    ):
        self.cfg = cfg
        #: flywheel shadow role (docs/flywheel.md): advertised as an
        #: info field on every heartbeat — NOT a lifecycle state — so
        #: the router's ReplicaView excludes this replica from routing
        #: and run_rollout never swaps it, while /score still answers
        #: for the shadow scorer's mirrored sample stream
        self.shadow = bool(shadow)
        self.run_dir = Path(run_dir)
        self.replica_id = str(replica_id)
        self.fleet_dir = Path(
            fleet_dir if fleet_dir is not None
            else (cfg.fleet.fleet_dir or self.run_dir / "fleet")
        )
        self.obs_dir = self.fleet_dir / self.replica_id
        self.obs_dir.mkdir(parents=True, exist_ok=True)
        self.host = host
        self.port = int(port)
        self.family = family
        self.started_unix = time.time()
        self.services: dict[str, ScoringService] = {}
        self.coserve_refused: list[str] = []
        self.httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._state = "starting"
        self._state_lock = threading.Lock()
        #: injected-fault switchboard (fleet/chaos.py), driven by
        #: /admin/chaos when fleet.chaos is on — inert otherwise
        self.chaos = fleet_chaos.ChaosState()
        #: one swap at a time: a rollout controller retrying into a
        #: replica mid-swap must queue, not interleave drains
        self._swap_lock = threading.Lock()
        #: fleet telemetry plane (obs/aggregate.py), wired in start()
        #: when fleet.telemetry is on — None keeps the default path
        #: byte-identical
        self.telemetry_publisher = None
        self.trace_shipper = None

    # -- construction --------------------------------------------------------

    def _build_service(
        self, run_dir: Path, checkpoint: str, family: str | None = None
    ) -> tuple[ScoringService, float]:
        """(service, measured param bytes) for one registry entry; the
        restore happens first so co-serving admission decides on the
        MEASURED capacity signal before the expensive AOT warmup.
        Combined/t5 entries rebuild their tokenizer + encoder config
        from the run's model_cfg.json manifest (serve/cascade.py), so a
        replica restores ALL three families — the cascade's fleet-wide
        layout — and a @int8 checkpoint restores the quantized entry."""
        from deepdfa_tpu.serve.registry import ModelRegistry

        family = family or self.family
        cfg = (
            self.cfg
            if run_dir == self.run_dir and family == self.family
            else None
        )
        from deepdfa_tpu.serve.registry import serve_mesh

        # the serve mesh follows THIS replica's config; co-served
        # entries with cfg=None (registry loads the run's own config)
        # inherit it too — one mesh per replica process
        registry = ModelRegistry(
            run_dir, family=family, checkpoint=checkpoint, cfg=cfg,
            mesh=serve_mesh(self.cfg),
            # the role tag rides /healthz (registry.info) so operators
            # and the diag flywheel section can tell which process is
            # the candidate without cross-referencing heartbeats
            flywheel_tag="candidate" if self.shadow else "incumbent",
        )
        nbytes = param_bytes(registry.params())
        service = ScoringService(registry, registry.cfg)
        if service.request_log is not None:
            # per-replica log home: N replicas must never interleave
            # appends into the run dir's single serve_log.jsonl
            service.request_log.close()
            service.request_log = RequestLog(
                self.obs_dir / "serve_log.jsonl"
            )
        return service, nbytes

    def build(self) -> None:
        """Restore + warm every co-served entry the HBM budget admits
        (primary first — it is never refused; a budget too small for the
        primary is an operator error worth failing loudly)."""
        specs: list[tuple[str, Path, str, str]] = [
            (PRIMARY, self.run_dir, self.cfg.serve.checkpoint,
             self.family)
        ]
        for spec in self.cfg.fleet.models:
            name, family, run_dir, ckpt = parse_model_spec(spec)
            if name == PRIMARY:
                raise ValueError(
                    f"fleet.models entry {spec!r} shadows the primary "
                    f"entry name {PRIMARY!r}"
                )
            specs.append((name, Path(run_dir), ckpt, family))
        budget = float(self.cfg.fleet.hbm_budget_bytes)
        measured: dict[str, float] = {}
        for name, run_dir, ckpt, family in specs:
            service, nbytes = self._build_service(
                run_dir, ckpt, family=family
            )
            measured[name] = nbytes
            loaded, refused = fleet_admission.plan_coserving(
                measured, budget
            )
            if name in refused:
                if name == PRIMARY:
                    raise RuntimeError(
                        f"fleet.hbm_budget_bytes={budget:g} cannot fit "
                        f"even the primary entry "
                        f"({nbytes:g} param bytes)"
                    )
                # refused by the capacity arbiter: announced, not loaded
                service.close()
                measured.pop(name)
                self.coserve_refused.append(name)
                obs_metrics.REGISTRY.counter(
                    "fleet/coserve_refused"
                ).inc()
                logger.warning(
                    "co-serving entry %r refused: %g param bytes would "
                    "exceed fleet.hbm_budget_bytes=%g",
                    name, nbytes, budget,
                )
                continue
            self.services[name] = service
        self._measured_param_bytes = measured

    # -- heartbeat -----------------------------------------------------------

    def state(self) -> str:
        with self._state_lock:
            return self._state

    def set_state(self, state: str) -> None:
        with self._state_lock:
            self._state = state
        self.write_heartbeat()

    def heartbeat_info(self) -> dict:
        primary = self.services.get(PRIMARY)
        info: dict = {
            "started_unix": round(self.started_unix, 3),
            "models": sorted(self.services),
            "coserve_refused": list(self.coserve_refused),
            "hbm_budget_bytes": float(self.cfg.fleet.hbm_budget_bytes),
        }
        if self.shadow:
            # only present on shadow rides — absent from every default
            # heartbeat so the flywheel-off envelope is byte-identical
            info["shadow"] = True
        if primary is not None:
            reg = primary.registry.info()
            info.update(
                checkpoint_step=reg.get("checkpoint_step"),
                config_digest=reg.get("config_digest"),
                vocab_digest=reg.get("vocab_digest"),
                jit_lowerings=sum(
                    s._jit_lowerings() for s in self.services.values()
                ),
                steady_state_recompiles=sum(
                    s.steady_state_recompiles()
                    for s in self.services.values()
                ),
                queue_depth=sum(
                    s.batcher.stats()["queue_depth"]
                    for s in self.services.values()
                ),
                backend=primary.health.last(),
            )
        # the co-serving capacity signal: measured per-entry param
        # bytes, plus the efficiency ledger's own per-entry view when on
        ledger_params = dict(
            getattr(self, "_measured_param_bytes", {}) or {}
        )
        led = obs_ledger.snapshot_or_none()
        if led is not None and isinstance(led.get("params"), dict):
            ledger_params.update(led["params"])
        info["ledger_params"] = ledger_params
        return info

    def write_heartbeat(self) -> None:
        try:
            heartbeat.write_heartbeat(
                self.fleet_dir, self.replica_id, self.host, self.port,
                state=self.state(), info=self.heartbeat_info(),
            )
        except OSError:
            logger.exception("heartbeat write failed")

    # -- serving surface -----------------------------------------------------

    def healthz(self, deep: bool = False) -> dict:
        primary = self.services[PRIMARY]
        out = primary.healthz(deep=deep)
        out.update(
            replica_id=self.replica_id,
            state=self.state(),
            models={
                name: {
                    "jit_lowerings": svc._jit_lowerings(),
                    "steady_state_recompiles": (
                        svc.steady_state_recompiles()
                    ),
                }
                for name, svc in self.services.items()
            },
            coserve_refused=list(self.coserve_refused),
        )
        return out

    def stats(self) -> dict:
        primary = self.services[PRIMARY]
        out = primary.stats()
        out["replica_id"] = self.replica_id
        out["state"] = self.state()
        if len(self.services) > 1:
            out["models"] = {
                name: svc.batcher.stats()
                for name, svc in self.services.items()
            }
        return out

    # -- rollout swap (fleet/rollout.py drives this via /admin/rollout) -----

    def _wait_queue_drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every co-served batcher's queue is empty (the
        in-flight work the drain half of a swap must not abandon).
        Rides the shared bounded poll helper (coord.poll_until) —
        deadline-aware, jittered, logged on exhaustion."""

        def _drained() -> bool:
            return sum(
                s.batcher.stats()["queue_depth"]
                for s in self.services.values()
            ) == 0

        return coord.poll_until(
            _drained, timeout_s, interval_s=0.05, max_interval_s=0.25,
            what=f"queue drain on replica {self.replica_id}",
        ) is not None

    def swap_primary(
        self,
        checkpoint: str | None,
        drift_bound: float | None = None,
        rollback: bool = False,
    ) -> dict:
        """The per-replica rollout step (docs/fleet.md): drain -> swap
        -> re-warm -> readmit, with the replica back at `ready` whether
        the swap landed or was refused (a refused swap leaves the OLD
        weights serving — the replica never exits the fleet over it).

        drain    heartbeat flips to `draining` (the router stops routing
                 within its poll cadence), the lame-duck window passes,
                 and the batcher queues empty out.
        swap     registry.swap_checkpoint (drift-gated, rollback-capable)
                 or registry.rollback().
        re-warm  one execution through the smallest compiled ladder rung
                 with the new params — proves the AOT executables still
                 execute, and pins the zero-recompile census across the
                 swap.
        readmit  heartbeat back to `ready`; the router routes here again
                 off the normal poll.
        """
        from deepdfa_tpu.serve.registry import RegistryError

        svc = self.services[PRIMARY]
        with self._swap_lock:
            lowerings_before = svc._jit_lowerings()
            self.set_state("draining")
            try:
                time.sleep(max(0.0, float(self.cfg.fleet.drain_announce_s)))
                drained = self._wait_queue_drain()
                if rollback:
                    out = svc.registry.rollback()
                    if out is None:
                        raise RegistryError(
                            "nothing to roll back to (no prior swap "
                            "stashed on this replica)"
                        )
                else:
                    out = svc.registry.swap_checkpoint(
                        checkpoint, drift_bound=drift_bound
                    )
                # re-warm: run the smallest compiled rung once with the
                # new params (empty padded batch — zero request cost;
                # the GGNN executor's one bucket key is "graph")
                if svc.registry.family == "deepdfa":
                    svc.executor.execute("graph", [])
                out.update(
                    ok=True,
                    drained=drained,
                    recompiles=svc._jit_lowerings() - lowerings_before,
                    steady_state_recompiles=(
                        svc.steady_state_recompiles()
                    ),
                )
                obs_metrics.REGISTRY.counter("rollout/swaps").inc()
                return out
            finally:
                # readmit UNCONDITIONALLY: a refused swap still serves
                # the old weights, and a replica stuck at `draining`
                # would silently shrink the fleet
                self.set_state(heartbeat.READY)

    def _make_server(self) -> ThreadingHTTPServer:
        worker = self

        class _ReplicaHandler(serve_server._Handler):
            service = self.services[PRIMARY]

            def _service_for(self, payload):
                name = payload.get("model")
                if name is None:
                    return worker.services[PRIMARY]
                svc = worker.services.get(str(name))
                if svc is None:
                    raise UnknownModel(
                        f"no co-served model {name!r} on this replica "
                        f"(have {sorted(worker.services)})"
                    )
                return svc

            def do_GET(self):  # noqa: N802
                import urllib.parse

                url = urllib.parse.urlsplit(self.path)
                query = urllib.parse.parse_qs(url.query)
                if url.path == "/healthz" and worker.chaos.wedged():
                    # the wedge-backend failure class (docs/fleet.md):
                    # process alive, health probe flipped — the router
                    # must eject and keep probing until recovery
                    self._reply(503, {
                        "error": "wedged (chaos)", "wedged": True,
                        "replica_id": worker.replica_id,
                    })
                elif url.path == "/healthz":
                    deep = query.get("deep", ["0"])[0] not in (
                        "", "0", "false"
                    )
                    self._reply(200, worker.healthz(deep=deep))
                elif url.path == "/stats":
                    self._reply(200, worker.stats())
                else:
                    super().do_GET()

            def do_POST(self):  # noqa: N802
                if self.path.startswith("/admin/"):
                    worker._handle_admin(self)
                    return
                # injected chaos (wedge stall / added latency) lands on
                # the scoring path only — admin stays reachable so a
                # drill can always clear its own fault
                worker.chaos.delay()
                super().do_POST()

        return _DrainingServer((self.host, self.port), _ReplicaHandler)

    def _handle_admin(self, handler) -> None:
        """POST /admin/rollout | /admin/chaos on this replica.

        rollout: {"checkpoint": tag[, "drift_bound": b]} swaps the
        primary entry (drain -> swap -> re-warm -> readmit);
        {"rollback": true} undoes the last swap. A drift refusal answers
        409 with the registry's message — the rollout controller's halt
        signal. chaos: the fault switchboard, 403 unless fleet.chaos.
        """
        from deepdfa_tpu.serve.registry import RegistryError

        try:
            n = int(handler.headers.get("Content-Length", 0))
            payload = json.loads(handler.rfile.read(n) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, KeyError) as e:
            handler._reply(400, {"error": f"bad request: {e}"})
            return
        if handler.path == "/admin/rollout":
            rollback = bool(payload.get("rollback"))
            checkpoint = payload.get("checkpoint")
            if not rollback and not checkpoint:
                handler._reply(400, {
                    "error": "rollout needs a checkpoint tag "
                             "(or rollback: true)",
                })
                return
            drift_bound = payload.get("drift_bound")
            try:
                out = self.swap_primary(
                    checkpoint,
                    drift_bound=(
                        float(drift_bound) if drift_bound is not None
                        else None
                    ),
                    rollback=rollback,
                )
            except RegistryError as e:
                obs_metrics.REGISTRY.counter("rollout/refusals").inc()
                handler._reply(409, {
                    "ok": False, "refused": True, "error": str(e),
                    "replica_id": self.replica_id,
                })
                return
            except Exception as e:  # noqa: BLE001 - admin must answer
                logger.exception("rollout swap failed")
                handler._reply(500, {"ok": False, "error": str(e)})
                return
            out["replica_id"] = self.replica_id
            handler._reply(200, out)
        elif handler.path == "/admin/chaos":
            if not getattr(self.cfg.fleet, "chaos", False):
                handler._reply(403, {
                    "error": "chaos endpoints disabled (set "
                             "fleet.chaos=true to run drills)",
                })
                return
            try:
                state = self.chaos.apply(payload)
            except ValueError as e:
                handler._reply(400, {"error": str(e)})
                return
            handler._reply(200, {
                "ok": True, "replica_id": self.replica_id, **state,
            })
        else:
            handler._reply(404, {
                "error": f"no admin route {handler.path}",
            })

    def start(self) -> None:
        """Build, warm, bind, announce — returns with the replica
        routable (heartbeat `ready`)."""
        self.write_heartbeat()  # `starting`: visible while warming
        self.build()
        for svc in self.services.values():
            svc.start()
        self.httpd = self._make_server()
        self.port = self.httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name=f"fleet-replica-{self.replica_id}", daemon=True,
        )
        self._http_thread.start()
        self.set_state(heartbeat.READY)
        if self.cfg.fleet.telemetry:
            # publish this replica's metrics snapshots (and ship its
            # trace segments when tracing is on) through the coord
            # backend so the router's fleet /metrics and `diag --fleet`
            # see it without reading this process's disk. Lazy import:
            # the default (telemetry off) path never loads the plane.
            from deepdfa_tpu.fleet import coord
            from deepdfa_tpu.obs import (
                aggregate as obs_agg, trace as obs_trace,
            )

            backend = coord.backend_from_config(self.cfg)
            self.telemetry_publisher = obs_agg.SnapshotPublisher(
                self.fleet_dir, self.replica_id,
                slo_engines=lambda: {
                    name: svc.slo
                    for name, svc in self.services.items()
                },
                backend=backend,
                interval_s=self.cfg.fleet.telemetry_interval_s,
            )
            if obs_trace.enabled():
                self.trace_shipper = obs_agg.TraceShipper(
                    self.fleet_dir, self.replica_id, backend=backend,
                    interval_s=self.cfg.fleet.telemetry_interval_s,
                )

    def _tick_telemetry(self) -> None:
        """Cadenced snapshot publication + trace shipping from the main
        loop — telemetry failures log and count, never kill serving."""
        try:
            if self.telemetry_publisher is not None:
                self.telemetry_publisher.maybe_publish()
            if self.trace_shipper is not None:
                self.trace_shipper.maybe_ship()
        except Exception:
            logger.exception("telemetry tick failed")

    def drain(self, trigger: str = "sigterm") -> None:
        """The graceful exit: announce, stop accepting, finish in-flight
        work, leave the final SLO snapshot + postmortem behind."""
        self.set_state("draining")
        # lame-duck period: keep serving while the router's poll cadence
        # observes the drain and stops routing here
        time.sleep(max(0.0, float(self.cfg.fleet.drain_announce_s)))
        if self.httpd is not None:
            # stop the accept loop first; in-flight handler threads keep
            # running (the batcher scheduler is still alive to finish
            # their batches) and server_close joins them
            self.httpd.shutdown()
            self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=30)
        final_slo: dict = {}
        for name, svc in self.services.items():
            svc.batcher.close()  # force-flushes everything still queued
            record = dict(svc.serve_record())
            record["serve_steady_state_recompiles"] = (
                svc.steady_state_recompiles()
            )
            write_serve_log(self.obs_dir, [record])
            final_slo[name] = svc.slo.snapshot()
        # the drain postmortem (obs/flight.py conventions): a no-op
        # unless the flight recorder is installed for this process
        obs_flight.crash_dump(trigger, extra={
            "replica_id": self.replica_id,
            "drain": True,
            "slo": final_slo,
        })
        # the last snapshot + trace segment make it off-host before the
        # process goes away — a drained replica's final SLO windows stay
        # visible to the fleet scrape until they age into staleness
        try:
            if self.telemetry_publisher is not None:
                self.telemetry_publisher.publish()
            if self.trace_shipper is not None:
                self.trace_shipper.close()
        except Exception:
            logger.exception("final telemetry publish failed")
        for svc in self.services.values():
            svc.close()
        self.set_state("drained")

    def run(self, ready_line: bool = True) -> int:
        """The replica main loop: install the preemption handler, serve
        + heartbeat until SIGTERM/SIGINT, then drain. Returns the
        process exit code."""
        from deepdfa_tpu.train.resilience import PreemptionHandler

        handler = PreemptionHandler(
            (signal.SIGTERM, signal.SIGINT)
        ).install()
        try:
            self.start()
            if ready_line:
                print(json.dumps({
                    "replica": self.replica_id,
                    "host": self.host,
                    "port": self.port,
                    "models": sorted(self.services),
                    "heartbeat": str(heartbeat.heartbeat_path(
                        self.fleet_dir, self.replica_id
                    )),
                }), flush=True)
            interval = float(self.cfg.fleet.heartbeat_interval_s)
            next_beat = time.monotonic()
            while not handler.triggered:
                now = time.monotonic()
                if now >= next_beat:
                    self.write_heartbeat()
                    next_beat = now + interval
                self._tick_telemetry()
                # short sleeps so a drain signal is observed promptly
                time.sleep(min(0.1, interval))
            self.drain()
            return 0
        finally:
            handler.uninstall()


def estimate_param_bytes_on_disk(
    run_dir: str | Path, family: str, checkpoint: str
) -> float:
    """One entry's checkpoint bytes on disk — the pre-spawn stand-in
    for the measured param-bytes signal (the fleet parent must size the
    fleet BEFORE any replica restores anything). Conservative for @int8
    entries (the disk tree is fp32; the served tree is ~0.26x), honest
    for everything else. 0.0 when unresolvable — the planner falls back
    to the default count, never crashes the bring-up."""
    from deepdfa_tpu.serve import quant
    from deepdfa_tpu.serve.registry import CKPT_DIR_BY_FAMILY

    base, _ = quant.split_checkpoint_tag(checkpoint)
    ckpt_dir = Path(run_dir) / CKPT_DIR_BY_FAMILY.get(
        family, "checkpoints"
    )
    tag = base
    if tag == "last":
        try:
            manifest = json.loads(
                (ckpt_dir / "manifest.json").read_text()
            )
            tag = (manifest.get("last") or {}).get("tag") or tag
        except (OSError, json.JSONDecodeError):
            pass
    tag_dir = ckpt_dir / tag
    if not tag_dir.is_dir():
        return 0.0
    try:
        return float(sum(
            p.stat().st_size for p in tag_dir.rglob("*") if p.is_file()
        ))
    except OSError:
        return 0.0


def estimate_entry_bytes(cfg, run_dir: str | Path) -> dict[str, float]:
    """{entry name: on-disk checkpoint bytes} for the primary + every
    `fleet.models` co-serving spec — the `plan_replicas` input when
    `fleet.replicas` is unset (ROADMAP item 2 remainder)."""
    out = {
        PRIMARY: estimate_param_bytes_on_disk(
            run_dir, "deepdfa", cfg.serve.checkpoint
        ),
    }
    for spec in cfg.fleet.models:
        try:
            name, family, entry_dir, ckpt = parse_model_spec(spec)
        except ValueError:
            continue
        out[name] = estimate_param_bytes_on_disk(entry_dir, family, ckpt)
    return out


# ---------------------------------------------------------------------------
# replica process management (cli `fleet`, fleet/smoke.py)


def replica_command(
    run_dir: str | Path,
    replica_id: str,
    fleet_dir: str | Path,
    overrides: list[str] | None = None,
    host: str = "127.0.0.1",
    port: int = 0,
    shadow: bool = False,
) -> list[str]:
    """argv for one replica subprocess (the `fleet-replica` CLI)."""
    import sys

    cmd = [
        sys.executable, "-m", "deepdfa_tpu.cli", "fleet-replica",
        "--run-dir", str(run_dir),
        "--replica-id", str(replica_id),
        "--fleet-dir", str(fleet_dir),
        "--host", host, "--port", str(port),
    ]
    if shadow:
        cmd.append("--shadow")
    for ov in overrides or []:
        cmd += ["--override", ov]
    return cmd


def spawn_replicas(
    run_dir: str | Path,
    fleet_dir: str | Path,
    n: int,
    overrides: list[str] | None = None,
    host: str = "127.0.0.1",
):
    """Start N replica subprocesses; [(replica_id, Popen)]."""
    import subprocess

    procs = []
    for i in range(int(n)):
        rid = f"r{i}"
        procs.append((rid, subprocess.Popen(
            replica_command(
                run_dir, rid, fleet_dir, overrides=overrides, host=host
            ),
        )))
    return procs


def wait_for_ready(
    fleet_dir: str | Path,
    replica_ids: list[str],
    timeout_s: float = 300.0,
    procs=None,
    backend=None,
) -> dict[str, dict]:
    """Block until every listed replica's heartbeat says `ready`;
    returns {replica_id: heartbeat}. Raises on timeout or on a replica
    process that exited before becoming ready. The wait rides the
    shared bounded poll helper (coord.poll_until): a dead replica
    process raises out of the predicate immediately, exhaustion is
    logged, and the retry cadence is jittered."""
    want = set(map(str, replica_ids))
    seen: dict[str, dict] = {}

    def _all_ready() -> dict[str, dict] | None:
        beats = heartbeat.scan_heartbeats(fleet_dir, backend=backend)
        ready = {
            rid: hb for rid, hb in beats.items()
            if rid in want and hb.get("state") == heartbeat.READY
        }
        seen.clear()
        seen.update(ready)
        if set(ready) == want:
            return ready
        if procs is not None:
            for rid, proc in procs:
                if rid in want and proc.poll() is not None and (
                    rid not in ready
                ):
                    raise RuntimeError(
                        f"replica {rid} exited rc={proc.returncode} "
                        f"before becoming ready"
                    )
        return None

    ready = coord.poll_until(
        _all_ready, timeout_s, interval_s=0.1, max_interval_s=0.5,
        what="replica readiness",
    )
    if ready is None:
        raise TimeoutError(
            f"replicas not ready in {timeout_s}s: missing "
            f"{sorted(want - set(seen))}"
        )
    return ready
