from deepdfa_tpu.cli.main import main

main()
