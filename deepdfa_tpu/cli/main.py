"""Command-line interface mirroring the reference pipeline stages.

Reference entry points consolidated here (DDFA/scripts/*.sh -> LightningCLI
+ LineVul/CodeT5 argparse zoos):

  prepare   read + clean a dataset csv/json, compute line labels, splits
  extract   frontend pipeline: CPG -> features -> vocab -> graph shards
  train     DeepDFA GGNN training (fit + best checkpoint)
  test      evaluation with metrics report + optional profiling
  coverage  abstract-dataflow vocab coverage audit (--analyze_dataset)
  bench     the headline throughput benchmark
  diag      render a run's telemetry (docs/observability.md); --fleet
            stitches a fleet's shipped trace segments into one timeline
  alerts    replay a fleet log through the alert engine (docs/alerts.md)
  score     offline batch scoring through the serving path (docs/serving.md)
  serve     online HTTP scoring service (dynamic batcher + AOT executables)
  scan      whole-repo incremental scanning -> JSONL + SARIF findings
            with optional line attributions (docs/scanning.md)
  fleet     multi-replica serving fleet: N replica workers behind a
            health-gated router with tenant admission + deadline-aware
            load shedding (docs/fleet.md)
  fleet-replica  one fleet replica worker process (spawned by `fleet`;
            heartbeats + graceful SIGTERM drain)
  fleet-router   one HA router (active/standby via the router.json
            rendezvous; standby takes over within the failover window)
  fleet-rollout  zero-downtime checkpoint rollout across the fleet
            (drift-gated, SLO-guarded, halt + rollback on breach)
  flywheel  data-flywheel controller: watch a candidate's shadow-ride
            comparison records and auto-promote it through the
            fleet-rollout gates (or demote it); --retrain builds the
            candidate from the traffic log (docs/flywheel.md)

Config comes from --config (json) plus dotted key=value overrides, e.g.
  python -m deepdfa_tpu.cli train data.batch.graphs_per_batch=128
"""

from __future__ import annotations

import argparse
import json
import pickle
import sys
from pathlib import Path

from deepdfa_tpu.core import Config, config as config_mod, paths
from deepdfa_tpu.data.diffs import split_lines


def _load_config(args) -> Config:
    cfg = config_mod.load(args.config) if args.config else Config()
    cfg = config_mod.apply_overrides(cfg, args.overrides)
    config_mod.validate(cfg)
    config_mod.apply_sanitizers(cfg)
    return cfg


def _load_run_config(args) -> Config:
    """Config for commands that operate on an EXISTING run (test,
    localize): the run's saved config.json is the base, so model/data
    dims always match the checkpoint being restored (train saves it,
    cmd_train:332 — the reference gets this via re-passing the same
    stacked yamls, main_cli.py); explicit --config or CLI overrides
    still apply on top."""
    cfg = _load_config(args)
    if args.config is None:
        saved = paths.runs_dir(cfg.run_name) / "config.json"
        if saved.exists():
            cfg = config_mod.load(saved)
            cfg = config_mod.apply_overrides(cfg, args.overrides)
            config_mod.validate(cfg)
            config_mod.apply_sanitizers(cfg)
    return cfg


def _apply_tuned(cfg: Config, serve_side: bool = False) -> Config:
    """Fold the matching tuned.json layout into the config when
    tune.enabled (deepdfa_tpu/tune/, docs/tuning.md) — a no-op (loud,
    inside record_for_config) otherwise or on any hardware-key
    mismatch. Train-side callers also take the fitted seq-bucket edges;
    serve-side callers take only the kernel block layout (their ladder
    + bucket edges flow through ScoringService so the registry's
    hot-swap digest never sees a tuned data section) keyed at the
    resolved SERVE budgets — the signature the score programs pack at."""
    if not getattr(getattr(cfg, "tune", None), "enabled", False):
        return cfg
    from deepdfa_tpu.tune import cache as tune_cache

    if serve_side:
        cfg, _ = tune_cache.apply_to_config(
            cfg, sections=("kernel",),
            node_budget=cfg.serve.node_budget or cfg.data.batch.node_budget,
            edge_budget=cfg.serve.edge_budget or cfg.data.batch.edge_budget,
        )
    else:
        cfg, _ = tune_cache.apply_to_config(cfg)
    return cfg


def _graphs_dirname(cfg: Config) -> str:
    """Graph-store directory for the configured feat x gtype; the flagship
    cfg gtype keeps the historical name so existing artifacts stay valid."""
    suffix = "" if cfg.data.gtype == "cfg" else f"_gtype_{cfg.data.gtype}"
    return f"graphs{cfg.data.feat.name}{suffix}"


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", default=None, help="json config file")
    p.add_argument(
        "overrides", nargs="*", default=[], help="dotted key=value overrides"
    )


def cmd_prepare(args) -> None:
    from deepdfa_tpu.data import readers, synthetic

    cfg = _load_config(args)
    ds = cfg.data.dataset
    out_dir = paths.processed_dir(ds)
    fmt = args.format
    if fmt == "auto":
        if args.source == "synthetic":
            fmt = "synthetic"
        elif args.source.endswith(".json"):
            fmt = "devign"
        else:
            fmt = "bigvul"
    if fmt == "synthetic":
        if not args.synthetic_v2 and (
            args.lookalike_rate != 0.5 or args.label_noise != 0.02
        ):
            raise SystemExit(
                "--lookalike-rate/--label-noise only apply with "
                "--synthetic-v2 (the v1 generator has neither knob)"
            )
        if args.synthetic_v2:
            # the hardened corpus: order families + benign lookalikes +
            # label noise (data/synthetic.py:generate_v2, round 4)
            synth = synthetic.generate_v2(
                args.n_examples, seed=cfg.data.seed,
                lookalike_rate=args.lookalike_rate,
                label_noise=args.label_noise,
            )
        else:
            synth = synthetic.generate(args.n_examples, seed=cfg.data.seed)
        examples = synthetic.to_examples(synth)
    elif fmt == "devign":
        examples = readers.read_devign(args.source, sample=args.sample)
    elif fmt == "dbgbench":
        examples = readers.read_dbgbench(args.source, sample=args.sample)
    else:
        examples = readers.read_bigvul(args.source, sample=args.sample)
    if args.mutated_jsonl:
        # mutated subdatasets replace each example's code via id join
        # (reference datasets.py:104-126); "_flip" variants use `source`
        examples = readers.read_mutated(
            args.mutated_jsonl, examples, flip=args.mutated_flip
        )
    if args.dep_closure:
        # reference statement labeling: changed lines PLUS lines data/
        # control dependent on them (evaluate.py:194-236 dep-add closure)
        from deepdfa_tpu.frontend import parse_function
        from deepdfa_tpu.frontend.deps import dependent_lines

        import dataclasses as _dc

        enriched = []
        for e in examples:
            if e.vuln_lines:
                try:
                    cpg = parse_function(e.code)
                    extra = dependent_lines(cpg, set(e.vuln_lines))
                    e = _dc.replace(
                        e, vuln_lines=frozenset(set(e.vuln_lines) | extra)
                    )
                except ValueError:
                    pass
            enriched.append(e)
        examples = enriched

    if args.splits:
        splits = readers.read_splits_csv(args.splits)
    elif args.cross_project:
        if args.source == "synthetic" or args.source.endswith(".json"):
            raise SystemExit(
                "--cross-project requires a Big-Vul csv with a `project` column"
            )
        splits = readers.cross_project_splits(args.source, seed=cfg.data.seed)
    else:
        splits = readers.random_splits(
            [e.id for e in examples], seed=cfg.data.seed
        )
    with (out_dir / "examples.pkl").open("wb") as f:
        pickle.dump(examples, f)
    (out_dir / "splits.json").write_text(
        json.dumps({str(k): v for k, v in splits.items()})
    )
    if args.export_codet5:
        # per-split defect jsonl {"idx","code","target"} — the UniXcoder
        # CodeT5-export hook (unixcoder/linevul_main.py:1400-1424), i.e.
        # the corpus in the format data/gen_data.py's defect reader and
        # CodeT5/_utils.py:260-279 consume
        c5_dir = out_dir / "codet5"
        c5_dir.mkdir(parents=True, exist_ok=True)
        names = {"train": "train", "val": "valid", "test": "test"}
        counts = {}
        for split, fname in names.items():
            rows = [e for e in examples if splits.get(e.id) == split]
            with (c5_dir / f"{fname}.jsonl").open("w") as f:
                for e in rows:
                    f.write(json.dumps({
                        "idx": e.id, "code": e.code, "target": int(e.label),
                    }) + "\n")
            counts[fname] = len(rows)
        print(f"codet5 export -> {c5_dir}: {counts}")
    print(f"prepared {len(examples)} examples -> {out_dir}")


def cmd_extract_vocab(args) -> None:
    """Build the shared train-split vocabularies (run once before sharded
    extraction; single-process `extract` does this implicitly)."""
    from deepdfa_tpu.data.pipeline import build_corpus_vocabs

    cfg = _load_config(args)
    out_dir = paths.processed_dir(cfg.data.dataset)
    with (out_dir / "examples.pkl").open("rb") as f:
        examples = pickle.load(f)
    splits = json.loads((out_dir / "splits.json").read_text())
    train_ids = [int(k) for k, v in splits.items() if v == "train"]
    vocabs = build_corpus_vocabs(
        examples,
        train_ids=train_ids,
        limit_all=cfg.data.feat.limit_all,
        limit_subkeys=cfg.data.feat.limit_subkeys,
        workers=args.workers,
    )
    vocab_path = out_dir / f"vocab{cfg.data.feat.name}.json"
    vocab_path.write_text(
        json.dumps({k: v.to_json() for k, v in vocabs.items()})
    )
    print(f"built vocabularies -> {vocab_path}")


def _write_missing_ids(store_dir, examples, specs, tag=None):
    """Record ids the frontend could not turn into graphs (the role of the
    reference's LineVul/linevul/missing_ids.txt manifest: downstream
    combined training masks these rows via the index-join bridge). Lives
    inside the graph-store directory so each feat x gtype store keeps its
    own manifest (the failure set differs by gtype)."""
    got = {s.graph_id for s in specs}
    missing = sorted(e.id for e in examples if e.id not in got)
    name = f"missing_ids-{tag}.txt" if tag else "missing_ids.txt"
    (store_dir / name).write_text("".join(f"{i}\n" for i in missing))


def cmd_extract(args) -> None:
    from deepdfa_tpu.data.pipeline import build_dataset, encode_corpus
    from deepdfa_tpu.frontend.vocab import AbsDfVocab
    from deepdfa_tpu.graphs import GraphStore

    cfg = _load_config(args)
    ds = cfg.data.dataset
    out_dir = paths.processed_dir(ds)
    with (out_dir / "examples.pkl").open("rb") as f:
        examples = pickle.load(f)
    splits = json.loads((out_dir / "splits.json").read_text())
    train_ids = [int(k) for k, v in splits.items() if v == "train"]
    vocab_path = out_dir / f"vocab{cfg.data.feat.name}.json"
    store = GraphStore(out_dir / _graphs_dirname(cfg))

    # fixed vocabularies: either another dataset's (--vocab-from, the
    # DbgBench / unseen-project cross-dataset workflow) or this dataset's
    # own pre-built ones (sharded extraction). Both compose with
    # --num-shards; shard jobs write tagged npz files.
    fixed_vocab_src = None
    if args.vocab_from:
        fixed_vocab_src = Path(args.vocab_from)
    elif args.num_shards > 1:
        if not vocab_path.exists():
            raise SystemExit(
                f"sharded extract requires {vocab_path}; run "
                f"`deepdfa_tpu extract-vocab` first"
            )
        fixed_vocab_src = vocab_path

    if fixed_vocab_src is not None:
        vocabs = {
            k: AbsDfVocab.from_json(v)
            for k, v in json.loads(fixed_vocab_src.read_text()).items()
        }
        sel = [
            e
            for i, e in enumerate(examples)
            if i % args.num_shards == args.shard
        ]
        specs = encode_corpus(
            sel, vocabs, workers=args.workers,
            max_defs=cfg.data.feat.max_defs, gtype=cfg.data.gtype,
            struct_feats=cfg.data.feat.struct_feats,
        )
        tag = f"shard{args.shard:04d}" if args.num_shards > 1 else None
        store.write(specs, tag=tag)
        _write_missing_ids(store.directory, sel, specs, tag=tag)
        if fixed_vocab_src != vocab_path:
            vocab_path.write_text(fixed_vocab_src.read_text())
        print(
            f"extracted shard {args.shard}/{args.num_shards}: "
            f"{len(specs)}/{len(sel)} graphs (vocab: {fixed_vocab_src}) "
            f"-> {store.directory}"
        )
        return

    specs, vocabs = build_dataset(
        examples,
        train_ids=train_ids,
        limit_all=cfg.data.feat.limit_all,
        limit_subkeys=cfg.data.feat.limit_subkeys,
        workers=args.workers,
        max_defs=cfg.data.feat.max_defs,
        gtype=cfg.data.gtype,
        struct_feats=cfg.data.feat.struct_feats,
    )
    store.write(specs)
    _write_missing_ids(store.directory, examples, specs)
    vocab_path.write_text(
        json.dumps({k: v.to_json() for k, v in vocabs.items()})
    )
    print(
        f"extracted {len(specs)}/{len(examples)} graphs -> {store.directory}"
    )


def _load_graph_splits(cfg: Config):
    from deepdfa_tpu.graphs import GraphStore

    ds = cfg.data.dataset
    out_dir = paths.processed_dir(ds)
    splits = json.loads((out_dir / "splits.json").read_text())
    store = GraphStore(out_dir / _graphs_dirname(cfg))
    by_id = store.load_all()
    if not by_id:
        # an absent store silently yields empty splits and an opaque crash
        # downstream; feat-name mismatches (e.g. limit_subkeys differing
        # between extract and train) are the common cause
        raise SystemExit(
            f"no graphs in {store.directory} — run `extract` with the "
            "same data.feat.* / data.gtype settings as this command"
        )
    out = {"train": [], "val": [], "test": []}
    for gid, spec in by_id.items():
        s = splits.get(str(gid))
        if s in out:
            out[s].append(spec)
    return out


class _BatchStream:
    """Single-use lazy batch stream whose `source_stage` tells the
    prefetch pipeline where to book pull time (PipelineStats): "pack"
    for live packing, "load" for warm cache replay — so per-epoch
    records attribute host time to the stage that actually ran."""

    def __init__(self, it, source_stage: str):
        self._it = iter(it)
        self.source_stage = source_stage

    def __iter__(self):
        return self._it


def _epoch_batches(
    cfg: Config, specs, mesh, shuffle_epoch=None, phase="train",
    source_digest=None, packer=None, lazy=False,
):
    """Budget-aware dp-sharded batches for one pass over `specs`.

    phase="train": over-budget graphs are dropped (and counted loudly);
    phase="eval": they get dedicated pow2-budget overflow batches so
    every example is scored (reference evaluates every graph by shrinking
    test batches, DDFA/sastvd/linevd/datamodule.py:135-141).

    Host pipeline knobs (docs/input_pipeline.md): data.pack_workers > 1
    packs on a spawn process pool — pass a long-lived `packer`
    (MpPacker bound to `specs`) to reuse one pool across epochs instead
    of paying spawn + corpus pickle every epoch; data.packed_cache (with
    a `source_digest` of the split corpus) persists the packed stream
    and replays it zero-copy when the content key matches — the
    selection is deterministic in (epoch, seed), so the key covers it
    exactly.
    """
    import numpy as np

    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.train import undersample_epoch

    if packer is not None and packer.graphs is not specs:
        raise ValueError(
            "packer must be bound to the same corpus as `specs` — its "
            "plans index into the corpus it was constructed with"
        )
    # LOGICAL shards (parallel/sharding.py): the batch layout is keyed
    # to train.mesh.num_shards (default: the dp size), so elastic
    # topologies whose dp divides it consume identical batches
    from deepdfa_tpu.parallel import sharding as sharding_mod

    num_shards = sharding_mod.logical_shards(cfg.train.mesh, mesh)
    bcfg = cfg.data.batch
    batcher = dict(
        num_shards=num_shards,
        num_graphs=max(1, bcfg.graphs_per_batch // num_shards),
        node_budget=bcfg.node_budget,
        edge_budget=bcfg.edge_budget,
        oversized="drop" if phase == "train" else "singleton",
    )
    # per-epoch undersampling is the only reason the stream varies across
    # epochs; without it one cache entry serves every epoch and re-run
    undersampling = bool(shuffle_epoch is not None and cfg.data.undersample)

    def build():
        # selection runs here, not up front: the key derives it from
        # (epoch, seed, digest), so a warm cache hit skips it entirely
        idx = None
        if undersampling:
            labels = np.array([s.label for s in specs])
            idx = undersample_epoch(labels, shuffle_epoch, seed=cfg.data.seed)
            sel = [specs[i] for i in idx]
        else:
            sel = list(specs)
        stats: dict = {}
        if packer is not None:
            it = packer.shard_bucket_batches(
                stats=stats, select=idx, **batcher
            )
        elif cfg.data.pack_workers > 1:
            from deepdfa_tpu.data.mp_pack import mp_shard_bucket_batches

            it = mp_shard_bucket_batches(
                sel, stats=stats, workers=cfg.data.pack_workers, **batcher
            )
        else:
            it = shard_bucket_batches(sel, stats=stats, **batcher)
        yield from it
        if stats.get("dropped"):
            print(
                f"[batch] dropped {stats['dropped']}/{len(sel)} over-budget "
                f"graphs (training only; eval scores every example)"
            )

    if cfg.data.packed_cache and source_digest is not None:
        from deepdfa_tpu.data.packed_cache import PackedBatchCache, cache_key

        rcfg = cfg.train.resilience
        cache = PackedBatchCache(
            paths.cache_dir(cfg.data.dataset) / "packed",
            max_entries=cfg.data.packed_cache_max_entries,
            io_retries=rcfg.io_retries,
            io_backoff_s=rcfg.io_backoff_s,
        )
        key = cache_key(
            dict(
                batcher,
                # every packing path here leaves add_self_loops at its
                # default (True) except a packer bound with it off; it
                # changes the packed bytes, so it must enter the key
                add_self_loops=(
                    packer.add_self_loops if packer is not None else True
                ),
                phase=phase,
                # epoch only shapes the stream when undersampling
                # resamples per epoch; keying it unconditionally would
                # turn every epoch of a non-undersampled run into a cold
                # miss that writes a duplicate entry
                epoch=shuffle_epoch if undersampling else None,
                undersample=undersampling,
                data_seed=cfg.data.seed,
            ),
            source_digest,
        )
        # warmness decides the stage label up front; get_or_pack itself
        # stays lazy so a `lazy` caller's prefetch pipeline times the
        # pulls (an eager list would book the whole cost outside the
        # instrumented window and report zeros). Known limit: the label
        # is per-epoch, so if a shared entry is evicted by a concurrent
        # run mid-replay, the rebuilt remainder is still booked as
        # "load" for that epoch
        stage = "load" if cache.has(key) else "pack"
        stream = cache.get_or_pack(key, build)
    else:
        stage, stream = "pack", build()
    if lazy:
        return _BatchStream(stream, stage)
    return list(stream)


def cmd_train(args) -> None:
    import jax

    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.parallel import make_mesh, sharding as sharding_mod
    from deepdfa_tpu.train import (
        GraphTrainer,
        NullRunLogger,
        RunLogger,
        positive_weight,
    )

    cfg = _load_config(args)
    # under an NNI experiment, trial parameters override the config and
    # val metrics stream back (reference main_cli.py:110-120, :184)
    from deepdfa_tpu.train import nni_bridge

    if nni_bridge.active():
        cfg = config_mod.apply_overrides(cfg, nni_bridge.nni_overrides())
    split_specs = _load_graph_splits(cfg)
    run_dir = paths.runs_dir(cfg.run_name)
    # multi-host bring-up (docs/sharding.md): jax.distributed init must
    # precede the first device probe; single-writer artifacts (saved
    # config, run log, checkpoints, step checkpoints) are owned by
    # process 0 while every host runs the identical sharded steps
    sharding_mod.init_runtime()
    # tuned layout AFTER init_runtime: the hardware-key lookup probes
    # jax.devices(), which must see the distributed topology
    cfg = _apply_tuned(cfg)
    primary = sharding_mod.is_primary()
    if primary:
        config_mod.to_json(cfg, run_dir / "config.json")

    mesh = make_mesh(cfg.train.mesh)
    model = DeepDFA.from_config(cfg.model, input_dim=cfg.data.feat.input_dim)
    import numpy as np

    pw = None
    if cfg.train.pos_weight is None and not cfg.data.undersample:
        pw = positive_weight(np.array([s.label for s in split_specs["train"]]))
    # content digests key the packed-batch cache (computed once per run;
    # covers array bytes + ordering, so any re-extraction invalidates)
    train_digest = val_digest = None
    if cfg.data.packed_cache:
        from deepdfa_tpu.data.packed_cache import corpus_digest

        train_digest = corpus_digest(split_specs["train"])
        val_digest = corpus_digest(split_specs["val"])
    # one spawn pool for the whole run (pool construction pickles the
    # corpus to every worker — paying that per epoch can rival the
    # packing it parallelizes); the pool itself is lazy, so a fully
    # warm packed-cache run never spawns a worker
    packer = val_packer = None
    if cfg.data.pack_workers > 1:
        from deepdfa_tpu.data.mp_pack import MpPacker

        packer = MpPacker(
            split_specs["train"], workers=cfg.data.pack_workers
        )
        val_packer = MpPacker(
            split_specs["val"], workers=cfg.data.pack_workers
        )
    # unified telemetry (docs/observability.md): entered BEFORE the lazy
    # packer pools spawn so workers inherit the exported trace dir; all
    # knobs default off (the session is then a no-op)
    from deepdfa_tpu import obs

    obs_cm = obs.session(cfg, run_dir)
    obs_cm.__enter__()
    try:
        # epoch-0 batches double as the warmup-schedule step estimate (the
        # undersampled epoch size; warmup_frac needs total_steps at
        # optimizer construction, train/state.py:make_optimizer)
        batches0 = _epoch_batches(
            cfg, split_specs["train"], mesh, shuffle_epoch=0,
            source_digest=train_digest, packer=packer,
        )
        trainer = GraphTrainer(
            model, cfg, mesh=mesh, pos_weight=pw,
            total_steps=len(batches0) * max(1, cfg.train.max_epochs),
        )
        state = trainer.init_state(batches0[0])
        ckpts = sharding_mod.if_primary(
            lambda: trainer.make_checkpoints(run_dir / "checkpoints")
        )

        def val_batches():
            out = _epoch_batches(
                cfg, split_specs["val"], mesh, phase="eval",
                source_digest=val_digest, packer=val_packer,
            )
            if cfg.data.packed_cache and val_packer is not None:
                # the eval entry (epoch-independent key) is cached now:
                # release the idle pool's workers + corpus copy for the
                # rest of the run; _get_pool respawns it if ever needed
                val_packer.close()
            return out

        # resilience runtime (docs/resilience.md): step-granular
        # checkpoint/resume, preemption handling, divergence guard,
        # watchdog — all off unless train.resilience.enabled
        from deepdfa_tpu.train.resilience import make_runner

        # every host RESTORES from the shared step-checkpoint tree (a
        # resume must re-align all hosts' state + cursor), but only
        # process 0 writes it (docs/sharding.md)
        res = make_runner(
            cfg, run_dir / "checkpoints-step", read_only=not primary
        )
        # deterministic fault injection for the resilience tests/harness
        # (scripts/fault_inject.py); armed only via DEEPDFA_FAULTS
        from deepdfa_tpu.testing.faults import injector_from_env

        injector = injector_from_env()

        def train_stream(epoch):
            s = _epoch_batches(
                cfg, split_specs["train"], mesh, epoch,
                source_digest=train_digest, packer=packer, lazy=True,
            )
            return injector.wrap(s) if injector is not None else s

        with (RunLogger(run_dir) if primary else NullRunLogger()) as run_log:
            state = trainer.fit(
                state,
                train_stream,
                val_batches=val_batches,
                checkpoints=ckpts,
                log_fn=nni_bridge.intermediate_log_fn(
                    cfg.train.monitor, run_log.log
                ),
                resilience=res,
            )
    finally:
        try:
            for p in (packer, val_packer):
                if p is not None:
                    p.close()
        finally:
            # after the packers (their workers' trace files are complete
            # by the time the session merges trace.json), but even if a
            # pool close raises the session must still tear down —
            # exported env, signal handler, tracer flush
            obs_cm.__exit__(None, None, None)
    best = ckpts.best_metrics() if ckpts is not None else None
    if best and cfg.train.monitor in best:
        nni_bridge.report_final(best[cfg.train.monitor])
    print("best:", best)


def cmd_test(args) -> None:
    import jax

    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train import GraphTrainer, classification_report

    cfg = _load_run_config(args)
    split_specs = _load_graph_splits(cfg)
    run_dir = paths.runs_dir(cfg.run_name)
    mesh = make_mesh(cfg.train.mesh)
    model = DeepDFA.from_config(cfg.model, input_dim=cfg.data.feat.input_dim)
    # eval-only: the optimizer is never stepped, but GraphTrainer still
    # constructs it — total_steps=1 satisfies a restored warmup-schedule
    # config (train.optim.warmup_frac>0) exactly as cmd_localize does
    trainer = GraphTrainer(model, cfg, mesh=mesh, total_steps=1)

    batches = _epoch_batches(cfg, split_specs[args.split], mesh, phase="eval")
    state = trainer.init_state(batches[0])
    ckpts = trainer.make_checkpoints(run_dir / "checkpoints")
    params = ckpts.restore(args.checkpoint, jax.device_get(state.params))

    import csv as _csv

    import numpy as np

    # single eval pass feeds metrics, the PR curve, and the export rows
    m = None
    rows = []
    from deepdfa_tpu.train import BinaryClassificationMetrics

    m = BinaryClassificationMetrics()
    loss_sum = 0.0
    count = 0.0
    import contextlib

    trace_ctx = contextlib.nullcontext()
    if args.xprof_dir:
        # on-device op timeline for TensorBoard's profile plugin (the
        # deep-dive analog of the reference's CUDA-event timing)
        from deepdfa_tpu.eval import xprof_trace

        trace_ctx = xprof_trace(args.xprof_dir)
    with trace_ctx:
        for batch in batches:
            probs, labels, mask, per = jax.device_get(
                trainer.eval_step(params, batch)
            )
            m.update(probs, labels, mask)
            valid = np.asarray(mask, bool)
            loss_sum += float(np.asarray(per, np.float64)[valid].sum())
            count += float(valid.sum())
            ids = np.asarray(batch.graph_ids).reshape(-1)
            for gid, p, y, v in zip(
                ids,
                np.asarray(probs).reshape(-1),
                np.asarray(labels).reshape(-1),
                valid.reshape(-1),
            ):
                if v and gid >= 0:
                    rows.append((int(gid), float(p), int(y)))
    metrics = m.compute()
    metrics["loss"] = loss_sum / count if count else float("nan")
    print(classification_report(m))
    print(json.dumps(metrics, indent=2))
    (run_dir / f"test_metrics_{args.split}.json").write_text(json.dumps(metrics))

    # PR curve artifact (reference: pr.csv / pr_binned.csv)
    curve = m.pr_curve()
    with (run_dir / f"pr_{args.split}.csv").open("w", newline="") as f:
        w = _csv.writer(f)
        w.writerow(["threshold", "precision", "recall"])
        for t, p, r in zip(
            curve["thresholds"], curve["precision"], curve["recall"]
        ):
            w.writerow([f"{t:.4f}", f"{p:.6f}", f"{r:.6f}"])

    if args.export:
        # per-example prediction dump (reference eval_export,
        # LineVul/unixcoder/linevul_main.py:742-830)
        with (run_dir / f"predictions_{args.split}.csv").open("w", newline="") as f:
            w = _csv.writer(f)
            w.writerow(["id", "prob", "label"])
            w.writerows(sorted(rows))
        print(f"exported {len(rows)} predictions")

    if args.profile:
        from deepdfa_tpu.eval import profile_model

        def fwd(p, b):
            return model.apply(p, b)

        from deepdfa_tpu.parallel import sharding as sharding_mod

        local = sharding_mod.split_logical(batches[0], 0)
        rec = profile_model(
            fwd,
            (params, local),
            examples_per_call=int(jax.device_get(local.graph_mask).sum()),
            out_path=run_dir / "profiledata.jsonl",
        )
        print(json.dumps(rec, indent=2))


def _combined_setup(args, cfg):
    """Tokenizer + encoder config + model config shared by train-combined
    and localize — these must match byte-for-byte for checkpoint restore,
    so they are built in exactly one place.

    --arch roberta (default) builds the LineVul/UniXcoder-style
    CombinedConfig; --arch t5 builds the CodeT5-style DefectConfig (eos
    pooling, T5 pad/eos frame)."""
    from deepdfa_tpu.data.tokenizer import BpeTokenizer, HashTokenizer
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.models.transformer import TransformerConfig

    arch = getattr(args, "arch", "roberta")
    valid_encoders = {
        "roberta": ("tiny", "codebert-base"),
        "t5": ("tiny", "codet5-base"),
    }[arch]
    if args.encoder not in valid_encoders:
        raise SystemExit(
            f"--encoder {args.encoder} is not valid for --arch {arch} "
            f"(choose from {valid_encoders})"
        )
    if arch == "t5" and args.tokenizer:
        raise SystemExit(
            "--arch t5 supports only the built-in hash tokenizer for now: "
            "BPE vocab.json assets use the RoBERTa special-id layout, which "
            "conflicts with T5's pad=0/eos=2 attention-mask convention"
        )
    if args.tokenizer:
        tok_dir = Path(args.tokenizer)
        tok = BpeTokenizer(
            next(tok_dir.glob("*vocab.json")), next(tok_dir.glob("*merges.txt"))
        )
    else:
        tok = HashTokenizer(vocab_size=4096, t5_frame=(arch == "t5"))

    use_graph = not getattr(args, "no_graph", False)
    sp_variant = getattr(args, "sp_variant", "ring")
    attn_impl = getattr(args, "attn_impl", "auto")
    remat_policy = getattr(args, "remat_policy", "full")
    if arch == "t5":
        if args.encoder == "codet5-base":
            enc_cfg = t5m.T5Config(
                dtype="bfloat16", sp_variant=sp_variant, attn_impl=attn_impl,
                remat_policy=remat_policy,
            )
        else:
            enc_cfg = t5m.T5Config.tiny(
                vocab_size=tok.vocab_size, sp_variant=sp_variant,
                attn_impl=attn_impl, remat_policy=remat_policy,
            )
        # T5's relative bias has no positional capacity of its own, so
        # bound sequence length to the recipe's max_length: a
        # misconfigured data.seq_buckets edge then fails loudly in
        # encode() instead of silently training on lengths the recipe
        # never meant to cover (the RoBERTa arch gets the same guard for
        # free from its position-table bound, models/transformer.py)
        import dataclasses as _dc

        enc_cfg = _dc.replace(enc_cfg, max_sequence_length=args.max_length)
        mcfg = t5m.DefectConfig(
            encoder=enc_cfg,
            graph_hidden_dim=cfg.model.hidden_dim,
            graph_input_dim=cfg.data.feat.input_dim,
            use_graph=use_graph,
        )
        return tok, enc_cfg, mcfg, t5m.params_from_hf_torch
    if args.encoder == "codebert-base":
        enc_cfg = TransformerConfig(
            dtype="bfloat16", sp_variant=sp_variant, attn_impl=attn_impl,
            remat_policy=remat_policy,
        )
    else:
        enc_cfg = TransformerConfig.tiny(
            vocab_size=tok.vocab_size,
            max_position_embeddings=args.max_length + 4,
            sp_variant=sp_variant,
            attn_impl=attn_impl,
            remat_policy=remat_policy,
        )
    mcfg = cmb.CombinedConfig(
        encoder=enc_cfg,
        graph_hidden_dim=cfg.model.hidden_dim,
        graph_input_dim=cfg.data.feat.input_dim,
        use_graph=use_graph,
    )
    from deepdfa_tpu.models.transformer import params_from_hf_torch as _rb_import

    return tok, enc_cfg, mcfg, _rb_import


def _require_cfg_gtype(cfg: Config, what: str) -> None:
    """The combined transformer+graph flows carry single-relation CFG
    graphs (as do the reference's combined models); typed cfg+dep stores
    are a graph-only experiment surface. Fail at startup, not mid-run."""
    if cfg.data.gtype != "cfg":
        raise SystemExit(
            f"{what} supports data.gtype=cfg only (got {cfg.data.gtype!r})"
        )


def cmd_train_combined(args) -> None:
    """DeepDFA+LineVul-style combined training over prepared artifacts."""
    import numpy as np

    from deepdfa_tpu.data.text import collate_shards
    from deepdfa_tpu.data.tokenizer import BpeTokenizer, HashTokenizer
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train import undersample_epoch
    from deepdfa_tpu.train.combined_loop import CombinedTrainer

    cfg = _load_config(args)
    _require_cfg_gtype(cfg, "train-combined")
    ds = cfg.data.dataset
    out_dir = paths.processed_dir(ds)
    run_dir = paths.runs_dir(cfg.run_name)
    # multi-host bring-up + process-0 artifact ownership (docs/sharding.md)
    from deepdfa_tpu.parallel import sharding as sharding_mod

    sharding_mod.init_runtime()
    # tuned layout AFTER init_runtime: the hardware-key lookup probes
    # jax.devices(), which must see the distributed topology
    cfg = _apply_tuned(cfg)
    primary = sharding_mod.is_primary()
    # run-config manifest, as cmd_train writes: localize/test restore
    # the checkpoint with the dims it was trained with (_load_run_config)
    if primary:
        config_mod.to_json(cfg, run_dir / "config.json")
    with (out_dir / "examples.pkl").open("rb") as f:
        examples = pickle.load(f)
    splits = json.loads((out_dir / "splits.json").read_text())

    tok, enc_cfg, mcfg, enc_import = _combined_setup(args, cfg)

    # the run-dir model manifest (serve/cascade.py): serving, fleet
    # co-serving, and the cascade's stage 2 rebuild the tokenizer +
    # encoder config from this, never from re-supplied CLI args
    from deepdfa_tpu.serve import cascade as _cascade_mod

    arch = getattr(args, "arch", "roberta")
    if args.tokenizer:
        tok_dir = Path(args.tokenizer)
        tok_desc = {
            "kind": "bpe",
            "vocab": str(next(tok_dir.glob("*vocab.json"))),
            "merges": str(next(tok_dir.glob("*merges.txt"))),
        }
    else:
        tok_desc = {
            "kind": "hash", "vocab_size": tok.vocab_size,
            "t5_frame": arch == "t5",
        }
    if primary:
        _cascade_mod.save_model_setup(
            run_dir, "t5" if arch == "t5" else "combined", mcfg, tok_desc,
            args.max_length,
        )

    from deepdfa_tpu.graphs import GraphStore

    store = None if args.no_graph else GraphStore(
        out_dir / _graphs_dirname(cfg)
    )
    graphs_by_id = {} if store is None else store.load_all()

    by_id = {e.id: e for e in examples}
    # only the splits that are actually batched get tokenized (BPE is the
    # slow host path; the test split is not touched by training)
    used_ids = {
        int(k) for k, v in splits.items() if v in ("train", "val") and int(k) in by_id
    }
    token_ids = {}
    labels = {}
    for e in examples:
        if e.id not in used_ids:
            continue
        token_ids[e.id] = tok.encode(e.code, max_length=args.max_length)
        labels[e.id] = int(e.label or 0)

    mesh = make_mesh(cfg.train.mesh)
    dp = mesh.shape.get("dp", 1)
    rows_per_shard = max(1, 16 // dp)
    bs = dp * rows_per_shard
    bcfg = cfg.data.batch

    # sequence-length bucketing (docs/input_pipeline.md): rows pad to
    # the smallest configured power-of-two edge >= their real length and
    # batches are sized by data.token_budget instead of the fixed
    # 16-row recipe; () keeps the legacy pad-to-max_length path
    buckets = tuple(int(b) for b in cfg.data.seq_buckets)
    lengths_by_id: dict[int, int] = {}
    if buckets:
        # the largest edge must be exactly max_length: smaller cannot
        # plan a full-length row (the planner raises), larger is
        # rejected by the encoder capacity guards this recipe configures
        # (T5Config.max_sequence_length / max_position_embeddings are
        # sized to max_length) — so warmup would crash on an edge the
        # model can never run
        if buckets[-1] != args.max_length:
            raise SystemExit(
                f"data.seq_buckets largest edge {buckets[-1]} != "
                f"--max-length {args.max_length}: the largest bucket "
                f"must equal the tokenizer frame (smaller edges cannot "
                f"hold a full-length row; larger edges exceed the "
                f"encoder's configured positional capacity)"
            )
        from deepdfa_tpu.data.text import (
            bucketed_collate_batches,
            lengths_for,
            plan_bucketed_batches,
        )

        order = sorted(token_ids)
        lengths_by_id = dict(
            zip(order, lengths_for(token_ids, order, tok.pad_id))
        )

    # bucketed streams ride the same host-pipeline levers as the graph
    # path: a spawn-pool collater (data.pack_workers) and the
    # content-keyed packed-batch cache (data.packed_cache) with the
    # bucket layout in the key
    text_packer = None
    if buckets and cfg.data.pack_workers > 1:
        from deepdfa_tpu.data.mp_pack import TextMpPacker

        text_packer = TextMpPacker(
            token_ids, labels, graphs_by_id, pad_id=tok.pad_id,
            workers=cfg.data.pack_workers,
        )
    text_cache = source_digest = None
    if buckets and cfg.data.packed_cache:
        from deepdfa_tpu.data.packed_cache import (
            PackedBatchCache,
            text_corpus_digest,
        )

        text_cache = PackedBatchCache(
            paths.cache_dir(ds) / "packed-text",
            max_entries=cfg.data.packed_cache_max_entries,
            io_retries=cfg.train.resilience.io_retries,
            io_backoff_s=cfg.train.resilience.io_backoff_s,
        )
        source_digest = (
            text_corpus_digest(token_ids, labels)
            + ":" + (store.digest() if store is not None else "")
        )

    def split_ids_for(name):
        return [int(k) for k, v in splits.items() if v == name and int(k) in by_id]

    # the 20%-linear-warmup AdamW schedule (reference linevul_main.py:
    # 150-162) needs the total step count at optimizer construction —
    # sized to the UNDERSAMPLED epoch when undersampling is on, or the
    # schedule would be stretched past the steps the run ever takes
    train_ids = split_ids_for("train")
    train_labels = np.array([labels[i] for i in train_ids])
    if cfg.data.undersample and len(train_ids):
        idx0 = undersample_epoch(train_labels, 0, seed=cfg.data.seed)
        epoch0_ids = [train_ids[i] for i in idx0]
    else:
        epoch0_ids = list(train_ids)
    n_epochs = max(1, cfg.train.max_epochs)
    if buckets:
        # bucketed batch count is data-dependent: run the (cheap,
        # bookkeeping-only) planner over every epoch's actual selection
        # — under per-epoch undersampling each resample buckets
        # differently, so extrapolating epoch 0 would drift the LR
        # schedule off the steps the run really takes
        def plan_count(sel_ids):
            return max(1, sum(
                1 for _ in plan_bucketed_batches(
                    [lengths_by_id[i] for i in sel_ids], sel_ids,
                    buckets, cfg.data.token_budget, dp,
                    bcfg.node_budget, bcfg.edge_budget,
                )
            ))

        if cfg.data.undersample and len(train_ids):
            total_steps = sum(
                plan_count([
                    train_ids[i] for i in undersample_epoch(
                        train_labels, e, seed=cfg.data.seed
                    )
                ])
                for e in range(n_epochs)
            )
        else:
            total_steps = plan_count(epoch0_ids) * n_epochs
    else:
        total_steps = max(1, -(-len(epoch0_ids) // bs)) * n_epochs
    trainer = CombinedTrainer(
        cfg, mcfg, mesh=mesh, freeze_graph=args.freeze_graph,
        total_steps=total_steps,
    )

    def fixed_batches(ids):
        out = []
        for k in range(0, len(ids), bs):
            sel = ids[k : k + bs]
            out.append(
                collate_shards(
                    np.stack([token_ids[i] for i in sel]),
                    [labels[i] for i in sel],
                    sel,
                    graphs_by_id,
                    num_shards=dp,
                    rows_per_shard=rows_per_shard,
                    node_budget=bcfg.node_budget,
                    edge_budget=bcfg.edge_budget,
                    pad_id=tok.pad_id,
                )
            )
        return out

    def bucketed_batches(ids, phase, epoch):
        def build():
            sel_lengths = [lengths_by_id[i] for i in ids]
            if text_packer is not None:
                return text_packer.bucketed_batches(
                    ids, buckets, cfg.data.token_budget, dp,
                    bcfg.node_budget, bcfg.edge_budget,
                    lengths=sel_lengths,
                )
            return bucketed_collate_batches(
                token_ids, labels, ids, graphs_by_id, buckets,
                cfg.data.token_budget, dp, bcfg.node_budget,
                bcfg.edge_budget, pad_id=tok.pad_id, lengths=sel_lengths,
            )

        # returned as a live iterator (like the graph path): the first
        # cold epoch trains off the packer/write_through stream instead
        # of materializing every batch in host RAM before step 1
        if text_cache is None:
            return build()
        import hashlib

        from deepdfa_tpu.data.packed_cache import cache_key

        undersampling = bool(phase == "train" and cfg.data.undersample)
        key = cache_key(
            dict(
                kind="text",
                seq_buckets=list(buckets),
                token_budget=cfg.data.token_budget,
                num_shards=dp,
                node_budget=bcfg.node_budget,
                edge_budget=bcfg.edge_budget,
                pad_id=tok.pad_id,
                max_length=args.max_length,
                phase=phase,
                # the ORDERED selection itself: the source digest covers
                # the train+val union, so a union-preserving repartition
                # (train/val swap, k-fold rotation) or a reorder — the
                # planner flushes buckets in arrival order — must miss,
                # never replay the previous partition's batches
                ids_digest=hashlib.sha256(
                    np.asarray(ids, np.int64).tobytes()
                ).hexdigest(),
                # epoch only shapes the stream when undersampling
                # resamples per epoch (same rule as the graph path)
                epoch=epoch if undersampling else None,
                undersample=undersampling,
                data_seed=cfg.data.seed,
            ),
            source_digest,
        )
        return text_cache.get_or_pack(key, build)

    def batches(ids, phase="train", epoch=None):
        if buckets:
            return bucketed_batches(list(ids), phase, epoch)
        return fixed_batches(ids)

    def epoch_batches(epoch):
        if cfg.data.undersample:
            idx = undersample_epoch(train_labels, epoch, seed=cfg.data.seed)
            ids = [train_ids[i] for i in idx]
        else:
            ids = train_ids
        return batches(ids, phase="train", epoch=epoch)

    state = trainer.init_state()
    if args.graph_checkpoint:
        # reference combined recipe: GGNN pretrained standalone, then its
        # encoder weights load (and optionally freeze) under the head
        import jax as _jax

        from deepdfa_tpu.models import DeepDFA
        from deepdfa_tpu.parallel import sharding as sharding_mod
        from deepdfa_tpu.train import CheckpointManager
        from deepdfa_tpu.graphs import pack_shards

        dd_model = DeepDFA.from_config(
            cfg.model, input_dim=cfg.data.feat.input_dim
        )
        # init needs shapes only, never graph content — an empty pack always
        # fits, whereas packing an arbitrary real graph raises BudgetExceeded
        # whenever it exceeds the tiny dummy budgets
        dummy = pack_shards([], 1, 1, 64, 256)
        dd_params = dd_model.init(
            _jax.random.key(0), sharding_mod.split_logical(dummy, 0)
        )
        ckpt_dir = Path(args.graph_checkpoint)
        if not ckpt_dir.exists():
            ckpt_dir = paths.runs_dir(args.graph_checkpoint) / "checkpoints"
        mgr = CheckpointManager(ckpt_dir)
        dd_params = mgr.restore("best", _jax.device_get(dd_params))
        state = trainer.load_graph_encoder_params(state, dd_params)
        print(f"loaded graph encoder from {ckpt_dir}"
              + (" (frozen)" if args.freeze_graph else ""))
    if args.pretrained:
        import torch

        sd = torch.load(args.pretrained, map_location="cpu")
        state = trainer.load_encoder(state, enc_import(enc_cfg, sd))

    ckpts = sharding_mod.if_primary(
        lambda: trainer.make_checkpoints(run_dir / "checkpoints-combined")
    )
    from deepdfa_tpu.testing.faults import injector_from_env
    from deepdfa_tpu.train.resilience import make_runner

    # every host restores the shared tree; process 0 writes it
    res = make_runner(
        cfg, run_dir / "checkpoints-combined-step", read_only=not primary
    )
    injector = injector_from_env()

    def train_stream(epoch):
        s = epoch_batches(epoch)
        return injector.wrap(s) if injector is not None else s

    # telemetry session before fit: the lazy TextMpPacker pool spawns
    # inside fit and must inherit the exported trace dir
    from deepdfa_tpu import obs

    obs_cm = obs.session(cfg, run_dir)
    obs_cm.__enter__()
    try:
        state = trainer.fit(
            state,
            train_stream,
            val_batches=lambda: batches(split_ids_for("val"), phase="val"),
            checkpoints=ckpts,
            resilience=res,
        )
    finally:
        try:
            if text_packer is not None:
                text_packer.close()
        finally:
            # session teardown even if the pool close raises (exported
            # env, signal handler, tracer flush + trace.json merge)
            obs_cm.__exit__(None, None, None)
    print("best:", ckpts.best_metrics() if ckpts is not None else None)


def _gen_setup(args, cfg, total_steps=None):
    """Shared train-gen / train-multi-gen preamble: tokenizer selection,
    GenConfig (tiny or full T5), mesh-sharded GenTrainer, and a fresh or
    --pretrained-initialized state. total_steps feeds the warmup/decay
    schedule when train.optim.warmup_frac > 0. Returns (tok, gcfg,
    trainer, state, dp, rows)."""
    from deepdfa_tpu.data.tokenizer import BpeTokenizer, HashTokenizer
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.models import t5_gen as genm
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train.gen_loop import GenTrainer

    if args.tokenizer == "bpe":
        tok = BpeTokenizer(args.vocab_file, args.merges_file)
    else:
        tok = HashTokenizer(vocab_size=args.vocab_size, t5_frame=True)

    enc_kw = dict(
        vocab_size=getattr(tok, "vocab_size", args.vocab_size),
        pad_token_id=tok.pad_id,
        eos_token_id=tok.sep_id,
    )
    enc_cfg = (
        t5m.T5Config.tiny(**enc_kw) if args.tiny else t5m.T5Config(**enc_kw)
    )
    gcfg = genm.GenConfig(
        encoder=enc_cfg,
        max_target_length=args.max_target_length,
        beam_size=args.beam_size,
    )
    mesh = make_mesh(cfg.train.mesh)
    dp = mesh.shape.get("dp", 1)
    rows = max(1, args.batch_size // dp)
    trainer = GenTrainer(cfg, gcfg, mesh=mesh, total_steps=total_steps)
    state = trainer.init_state()
    if args.pretrained:
        import torch

        sd = torch.load(args.pretrained, map_location="cpu")
        state = trainer.load_params(
            state, genm.gen_params_from_hf_torch(gcfg, sd)
        )
    return tok, gcfg, trainer, state, dp, rows


def _gen_encode_file(args, tok, task_name, filename, max_target_length=None):
    """Read one task file and encode with the reference's task prefix
    ("<family>: <source>", _utils.py:24-29). Returns (examples, src, tgt)."""
    import numpy as np

    from deepdfa_tpu.data import gen_data

    family = task_name.split("_")[0]
    if family not in gen_data.READERS:
        # the reference only accepts known families (run_gen/run_multi_gen
        # task tables); a silent summarize fallback would train a typo'd
        # --task-spec with the wrong reader/patience/target-length
        raise SystemExit(
            f"unknown task family {family!r} (task {task_name!r}); "
            f"known: {sorted(gen_data.READERS)}"
        )
    reader = gen_data.READERS[family]
    ex = reader(filename, args.data_num)
    src = tok.batch_encode(
        [f"{family}: {e.source}" for e in ex],
        max_length=args.max_source_length,
    )
    tgt = tok.batch_encode(
        [e.target for e in ex],
        max_length=max_target_length or args.max_target_length,
    )
    return ex, src.astype(np.int32), tgt.astype(np.int32)


def cmd_train_gen(args) -> None:
    """Seq2seq generation training (reference: CodeT5/run_gen.py main()).

    Reads task files in the reference formats (data/gen_data.py), trains
    the T5 seq2seq stack with dp sharding, evaluates dev ppl (+BLEU/EM
    with --do-eval-bleu), keeps best-ppl / best-bleu checkpoints, and with
    --do-test writes test_best-ppl.output / .gold prediction files
    (run_gen.py:eval_bleu_epoch file layout)."""
    from deepdfa_tpu.data import gen_data
    from deepdfa_tpu.models import t5_gen as genm

    cfg = _load_config(args)
    run_dir = paths.runs_dir(cfg.run_name)
    # eval-only invocations (no --train-file) never step the optimizer;
    # total_steps=1 keeps a warmup-schedule config constructible (the
    # same eval-path contract as cmd_test / cmd_localize)
    total_steps = 1
    if args.train_file:
        # reader-only pass (no tokenizer yet): the warmup/decay schedule
        # needs the real step count at optimizer construction
        family = args.task.split("_")[0]
        n_train = len(gen_data.READERS[family](args.train_file, args.data_num))
        steps_per_epoch = max(1, -(-n_train // max(1, args.batch_size)))
        total_steps = steps_per_epoch * max(1, cfg.train.max_epochs)
    tok, gcfg, trainer, state, dp, rows = _gen_setup(
        args, cfg, total_steps=total_steps
    )

    def load(filename):
        return _gen_encode_file(args, tok, args.task, filename)

    if args.train_file:
        _, train_src, train_tgt = load(args.train_file)
        dev = load(args.dev_file) if args.dev_file else None

        def train_batches(epoch):
            return gen_data.batches_of(
                train_src, train_tgt, dp, rows, pad_id=tok.pad_id,
                shuffle_seed=cfg.train.seed + epoch,
            )

        val_batches = None
        val_decode = None
        if dev is not None:
            dev_batches = gen_data.batches_of(
                dev[1], dev[2], dp, rows, pad_id=tok.pad_id
            )
            val_batches = lambda: dev_batches  # noqa: E731
            if args.do_eval_bleu:
                refs = genm.trim_at_eos(dev[2], tok.sep_id, tok.pad_id)
                val_decode = (dev[1], refs)
        # process-0 artifact ownership (docs/sharding.md): every host
        # trains the same sharded steps, one writes the checkpoints
        from deepdfa_tpu.parallel import sharding as sharding_mod

        sharding_mod.init_runtime()
        primary = sharding_mod.is_primary()
        ckpts = sharding_mod.if_primary(
            lambda: trainer.make_checkpoints(run_dir / "checkpoints-gen")
        )
        bleu_ckpts = (
            trainer.make_checkpoints(
                run_dir / "checkpoints-gen-bleu",
                monitor="val_bleu_em", mode="max",
            )
            if args.do_eval_bleu and primary
            else None
        )
        from deepdfa_tpu.testing.faults import injector_from_env
        from deepdfa_tpu.train.resilience import make_runner

        # every host restores the shared tree; process 0 writes it
        res = make_runner(
            cfg, run_dir / "checkpoints-gen-step", read_only=not primary
        )
        injector = injector_from_env()
        stream = train_batches
        if injector is not None:
            stream = lambda epoch: injector.wrap(train_batches(epoch))  # noqa: E731
        from deepdfa_tpu import obs

        with obs.session(cfg, run_dir):
            state = trainer.fit(
                state,
                stream,
                val_batches=val_batches,
                val_decode=val_decode,
                checkpoints=ckpts,
                bleu_checkpoints=bleu_ckpts,
                patience=args.patience,
                resilience=res,
            )
        print("best:", ckpts.best_metrics() if ckpts is not None else None)

    if args.test_file:
        ex, test_src, test_tgt = load(args.test_file)
        # decode from the saved best-ppl params, not the (possibly
        # early-stopped, degraded) final state — run_gen.py reloads
        # checkpoint-best-ppl before test decoding
        best_dir = run_dir / "checkpoints-gen" / "best"
        if best_dir.exists():
            import jax as _jax

            mgr = trainer.make_checkpoints(run_dir / "checkpoints-gen")
            params = mgr.restore("best", _jax.device_get(state.params))
            state = trainer.load_params(state, params)
        refs = genm.trim_at_eos(test_tgt, tok.sep_id, tok.pad_id)
        scores = trainer.eval_bleu_em(state, test_src, refs, return_preds=True)
        preds = scores.pop("preds")
        res_dir = run_dir / "results"
        res_dir.mkdir(parents=True, exist_ok=True)
        with (res_dir / "test_best-ppl.output").open("w") as f_out, (
            res_dir / "test_best-ppl.gold"
        ).open("w") as f_gold:
            for e, p, r in zip(ex, preds, refs):
                f_out.write(f"{e.idx}\t{' '.join(map(str, p))}\n")
                f_gold.write(f"{e.idx}\t{' '.join(map(str, r))}\n")
        print(json.dumps({"test_em": scores["em"], "test_bleu": scores["bleu"]}))


def cmd_train_multi_gen(args) -> None:
    """Multi-task generation training (reference: CodeT5/run_multi_gen.py).

    --task-spec name=train_file[:dev_file], repeatable. The name's
    "<family>_<subtask>" prefix selects the reader, the per-family
    early-stop patience (run_multi_gen.py:253-266), and the per-family
    target length (:52-67). One model/tokenizer is shared by every task;
    each step samples a task with size^0.7-tempered probability."""
    from deepdfa_tpu.data import gen_data
    from deepdfa_tpu.models import t5_gen as genm
    from deepdfa_tpu.train.multi_gen import (
        GenTask,
        fit_multi,
        task_target_length,
    )

    cfg = _load_config(args)
    run_dir = paths.runs_dir(cfg.run_name)

    specs: list[tuple[str, str, str | None]] = []
    for spec in args.task_spec:
        name, _, files = spec.partition("=")
        if not files:
            raise SystemExit(f"--task-spec {spec!r}: expected name=train[:dev]")
        if name.split("_")[0] not in gen_data.READERS:
            # fail before any model/backend setup: the reference only
            # accepts known task families (run_multi_gen.py task tables)
            raise SystemExit(
                f"--task-spec {spec!r}: unknown task family "
                f"{name.split('_')[0]!r}; known: {sorted(gen_data.READERS)}"
            )
        train_file, _, dev_file = files.partition(":")
        specs.append((name, train_file, dev_file or None))

    tok, gcfg, trainer, state, dp, rows = _gen_setup(
        args, cfg, total_steps=max(1, args.max_steps)
    )

    def load(name, filename):
        _, src, tgt = _gen_encode_file(
            args, tok, name, filename,
            max_target_length=min(
                args.max_target_length, task_target_length(name)
            ),
        )
        return src, tgt

    tasks = []
    for name, train_file, dev_file in specs:
        src, tgt = load(name, train_file)

        def factory(epoch, _src=src, _tgt=tgt):
            return gen_data.batches_of(
                _src, _tgt, dp, rows, pad_id=tok.pad_id,
                shuffle_seed=cfg.train.seed + epoch,
            )

        val_batches = val_decode = None
        if dev_file:
            dsrc, dtgt = load(name, dev_file)
            dev = gen_data.batches_of(dsrc, dtgt, dp, rows, pad_id=tok.pad_id)
            val_batches = lambda _dev=dev: _dev  # noqa: E731
            if args.do_eval_bleu:
                val_decode = (
                    dsrc, genm.trim_at_eos(dtgt, tok.sep_id, tok.pad_id)
                )
        tasks.append(
            GenTask(
                name, factory, size=src.shape[0],
                val_batches=val_batches, val_decode=val_decode,
            )
        )

    def checkpoints(task_name, monitor, mode):
        return trainer.make_checkpoints(
            run_dir / f"checkpoints-multi-{task_name}",
            monitor=monitor, mode=mode,
        )

    state, summary = fit_multi(
        trainer, state, tasks,
        max_steps=args.max_steps,
        eval_every=args.eval_every,
        checkpoints=checkpoints,
        seed=cfg.train.seed,
    )
    print(json.dumps({"tasks": summary}, default=float))


def cmd_train_clone(args) -> None:
    """Pairwise clone-detection training (reference: CodeT5/run_clone.py).

    Reads the reference clone format (pair index file + sibling
    data.jsonl), encodes each code of the pair, trains CloneTrainer with
    best-F1 checkpointing, and reports test P/R/F1."""
    import numpy as np

    from deepdfa_tpu.data import gen_data
    from deepdfa_tpu.data.tokenizer import BpeTokenizer, HashTokenizer
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.models import t5_gen as genm
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train.clone_loop import CloneTrainer, clone_batches_of

    cfg = _load_config(args)
    run_dir = paths.runs_dir(cfg.run_name)
    if args.tokenizer == "bpe":
        tok = BpeTokenizer(args.vocab_file, args.merges_file)
    else:
        tok = HashTokenizer(vocab_size=args.vocab_size, t5_frame=True)

    enc_kw = dict(
        vocab_size=getattr(tok, "vocab_size", args.vocab_size),
        pad_token_id=tok.pad_id,
        eos_token_id=tok.sep_id,
    )
    enc_cfg = (
        t5m.T5Config.tiny(**enc_kw) if args.tiny else t5m.T5Config(**enc_kw)
    )
    ccfg = genm.CloneConfig(encoder=enc_cfg)

    def load(filename):
        ex = gen_data.read_clone_examples(filename, args.data_num)
        a = tok.batch_encode(
            [f"clone: {e.source}" for e in ex], max_length=args.max_source_length
        )
        b = tok.batch_encode(
            [f"clone: {e.target}" for e in ex], max_length=args.max_source_length
        )
        pairs = np.stack([a, b], axis=1).astype(np.int32)
        return ex, pairs, np.array([e.label for e in ex], np.int32)

    mesh = make_mesh(cfg.train.mesh)
    dp = mesh.shape.get("dp", 1)
    rows = max(1, args.batch_size // dp)
    # eval-only invocations never step the optimizer; total_steps=1 keeps
    # a warmup-schedule config constructible (eval-path contract, as in
    # cmd_test / cmd_localize / cmd_gen)
    total_steps = 1
    if args.train_file:
        n_train = len(gen_data.read_clone_examples(args.train_file, args.data_num))
        steps_per_epoch = max(1, -(-n_train // max(1, args.batch_size)))
        total_steps = steps_per_epoch * max(1, cfg.train.max_epochs)
    trainer = CloneTrainer(cfg, ccfg, mesh=mesh, total_steps=total_steps)
    state = trainer.init_state()
    if args.pretrained:
        import torch

        sd = torch.load(args.pretrained, map_location="cpu")
        state = trainer.load_seq2seq(
            state,
            genm.gen_params_from_hf_torch(
                genm.GenConfig(encoder=enc_cfg), sd
            ),
        )

    if args.train_file:
        _, train_pairs, train_labels = load(args.train_file)

        def train_batches(epoch):
            return clone_batches_of(
                train_pairs, train_labels, dp, rows, pad_id=tok.pad_id,
                shuffle_seed=cfg.train.seed + epoch,
            )

        val_batches = None
        if args.dev_file:
            _, dev_pairs, dev_labels = load(args.dev_file)
            dev = clone_batches_of(
                dev_pairs, dev_labels, dp, rows, pad_id=tok.pad_id
            )
            val_batches = lambda: dev  # noqa: E731
        ckpts = trainer.make_checkpoints(run_dir / "checkpoints-clone")
        state = trainer.fit(
            state,
            train_batches,
            val_batches=val_batches,
            checkpoints=ckpts,
            patience=args.patience,
        )
        print("best:", ckpts.best_metrics())

    if args.test_file:
        _, test_pairs, test_labels = load(args.test_file)
        best_dir = run_dir / "checkpoints-clone" / "best"
        if best_dir.exists():
            import jax as _jax

            mgr = trainer.make_checkpoints(run_dir / "checkpoints-clone")
            params = mgr.restore("best", _jax.device_get(state.params))
            state = trainer.load_params(state, params)
        test = clone_batches_of(
            test_pairs, test_labels, dp, rows, pad_id=tok.pad_id
        )
        metrics, _ = trainer.evaluate(state, test)
        print(json.dumps({f"test_{k}": v for k, v in metrics.items()}))


def cmd_run_exp(args) -> None:
    """Experiment-matrix runner (reference: CodeT5/sh/run_exp.py).

    Either --matrix <json> (explicit runs) or --tasks/--seeds (built-in
    per-task defaults); executes each run as a CLI subprocess and writes
    per-run logs + summary.jsonl under <runs>/experiments/<tag>."""
    from deepdfa_tpu.train.experiments import (
        expand_matrix,
        load_matrix,
        run_matrix,
    )

    if args.matrix:
        runs = load_matrix(args.matrix)
        if args.override:
            # apply shared overrides to explicit matrix runs too
            from deepdfa_tpu.train.experiments import Run

            runs = [
                Run(r.name, r.cmd, r.args + tuple(args.override)) for r in runs
            ]
    elif args.tasks:
        runs = expand_matrix(
            args.tasks,
            seeds=args.seeds,
            extra_args=args.extra_arg,
            overrides=args.override,
        )
    else:
        raise SystemExit("pass --matrix or --tasks")
    out_dir = paths.runs_dir("experiments") / args.tag
    run_matrix(runs, out_dir, dry_run=args.dry_run)


def cmd_codebleu(args) -> None:
    """Score a generation hypothesis file against reference files
    (reference CLI: CodeT5/evaluator/CodeBLEU/calc_code_bleu.py:66-81)."""
    from deepdfa_tpu.eval.codebleu import get_codebleu

    refs_per_file = [
        [line.strip() for line in Path(f).read_text().splitlines()]
        for f in args.refs
    ]
    hyps = [line.strip() for line in Path(args.hyp).read_text().splitlines()]
    for rr in refs_per_file:
        if len(rr) != len(hyps):
            raise SystemExit("refs and hyp must have equal line counts")
    references = [
        [rr[i] for rr in refs_per_file] for i in range(len(hyps))
    ]
    out = get_codebleu(
        references, hyps, lang=args.lang,
        params=tuple(float(x) for x in args.params.split(",")),
    )
    print(json.dumps(out, indent=2))


def cmd_localize(args) -> None:
    """Line-level localization evaluation over a trained combined model:
    saliency (or attention) token scores -> per-line ranking -> top-k /
    IFA / effort metrics against the labeled vulnerable lines."""
    import jax
    import numpy as np

    from deepdfa_tpu.data.text import collate
    from deepdfa_tpu.eval.localize import (
        aggregate_line_scores,
        token_scores,
    )
    from deepdfa_tpu.eval.statements import (
        RankedExample,
        per_example_ifa,
        statement_report,
    )
    from deepdfa_tpu.graphs import GraphStore
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train.combined_loop import CombinedTrainer

    cfg = _load_run_config(args)
    _require_cfg_gtype(cfg, "localize")
    out_dir = paths.processed_dir(cfg.data.dataset)
    run_dir = paths.runs_dir(cfg.run_name)
    with (out_dir / "examples.pkl").open("rb") as f:
        examples = pickle.load(f)
    splits = json.loads((out_dir / "splits.json").read_text())

    tok, enc_cfg, mcfg, enc_import = _combined_setup(args, cfg)
    # eval-only path: the optimizer is never stepped, but the trainer
    # constructs it — total_steps=1 satisfies a warmup schedule config
    trainer = CombinedTrainer(
        cfg, mcfg, mesh=make_mesh(cfg.train.mesh), total_steps=1
    )
    state = trainer.init_state()
    ckpts = trainer.make_checkpoints(run_dir / "checkpoints-combined")
    params = ckpts.restore(args.checkpoint, jax.device_get(state.params))

    graphs_by_id = (
        {}
        if not mcfg.use_graph
        else GraphStore(out_dir / _graphs_dirname(cfg)).load_all()
    )

    targets = [
        e
        for e in examples
        if splits.get(str(e.id)) == args.split and e.vuln_lines
    ]
    if args.limit:
        targets = targets[: args.limit]
    ranked = []
    for e in targets:
        ids, tok_lines = tok.encode_with_lines(e.code, max_length=args.max_length)
        b = collate(
            ids[None], [int(e.label or 0)], [e.id], graphs_by_id,
            batch_rows=1,
            node_budget=cfg.data.batch.node_budget,
            edge_budget=cfg.data.batch.edge_budget,
            pad_id=tok.pad_id,
        )
        scores = token_scores(
            args.method, args.arch, mcfg, params, b.input_ids,
            b.graphs if mcfg.use_graph else None,
            b.has_graph if mcfg.use_graph else None,
        )
        # \n-only numbering: must agree with e.vuln_lines' coordinates
        n_lines = len(split_lines(e.code))
        line_scores = aggregate_line_scores(scores[0], tok_lines, n_lines)
        flagged = np.zeros(n_lines, bool)
        for ln in e.vuln_lines:
            if 1 <= ln <= n_lines:
                flagged[ln - 1] = True
        ranked.append(RankedExample(line_scores, flagged))

    report = statement_report(ranked)
    report["n_examples"] = len(ranked)
    report["method"] = args.method
    print(json.dumps(report, indent=2))
    (run_dir / f"localize_{args.split}_{args.method}.json").write_text(
        json.dumps(report)
    )
    # per-example IFA dump (reference ifa_records/ifa_<method>.txt,
    # unixcoder/linevul_main.py:700)
    ifa_dir = run_dir / "ifa_records"
    ifa_dir.mkdir(parents=True, exist_ok=True)
    (ifa_dir / f"ifa_{args.method}.txt").write_text(
        "\n".join(str(v) for v in per_example_ifa(ranked)) + "\n"
    )


def cmd_coverage(args) -> None:
    from deepdfa_tpu.eval import coverage_report

    cfg = _load_config(args)
    split_specs = _load_graph_splits(cfg)
    print(json.dumps(coverage_report(split_specs), indent=2))


def cmd_ivdetect(args) -> None:
    """Per-line IVDetect feature dump (reference: evaluate.py:19-191
    feature_extraction, cached per file under ivdetect_feat_ext/)."""
    from pathlib import Path

    from deepdfa_tpu.eval.ivdetect import dump_features

    for src in args.sources:
        src = Path(src)
        out = Path(args.out_dir) if args.out_dir else src.parent
        out.mkdir(parents=True, exist_ok=True)
        dest = out / f"{src.stem}.ivdetect.json"
        dump_features(src.read_text(), dest)
        print(dest)


def cmd_diag(args) -> None:
    """Render a run's telemetry (deepdfa_tpu/obs/diag.py): throughput
    timeline, host/device stage attribution from records AND the trace
    event stream, efficiency ledger (per-signature MFU/compile bars,
    HBM watermarks), postmortem forensics, resilience event log."""
    from deepdfa_tpu.obs import diag

    argv = []
    if args.run_dir:
        argv.append(args.run_dir)
    if args.json:
        argv.append("--json")
    if args.smoke:
        argv.append("--smoke")
    if getattr(args, "postmortem", None):
        argv += ["--postmortem", args.postmortem]
    if getattr(args, "fleet", None):
        argv += ["--fleet", args.fleet]
    rc = diag.main(argv)
    if rc:
        raise SystemExit(rc)


def cmd_alerts(args) -> None:
    """Replay a fleet log through the alert engine
    (deepdfa_tpu/obs/alerts.py; docs/alerts.md): re-evaluate the
    burn-rate / drift / fault rules over the recorded request stream at
    record time — what WOULD have fired, when, and did it resolve."""
    import json as _json

    from deepdfa_tpu.obs.alerts import (
        default_rules, replay_fleet_log, rule_from_doc,
    )

    rules = None
    if args.rules:
        docs = _json.loads(Path(args.rules).read_text())
        if not isinstance(docs, list):
            raise SystemExit(f"{args.rules}: expected a JSON list of rules")
        rules = [rule_from_doc(d) for d in docs]
    out = replay_fleet_log(args.fleet_log, rules=rules)
    if args.json:
        print(_json.dumps(out))
        return
    print(
        f"replayed {out['records']} record(s): "
        f"{len(out['transitions'])} transition(s), "
        f"fired=[{' '.join(out['fired']) or '-'}] "
        f"resolved=[{' '.join(out['resolved']) or '-'}]"
    )
    names = [r.name for r in (rules or default_rules())]
    print("rules: " + " ".join(names))
    still = out.get("firing") or []
    if still:
        print("STILL FIRING at end of log: " + " ".join(still))
        raise SystemExit(1)


def cmd_tune(args) -> None:
    """Offline measured-search autotuner (deepdfa_tpu/tune/,
    docs/tuning.md): compile-and-time kernel tile candidates under the
    PR-8 numerics contract, fit serve-ladder rungs + seq-bucket edges
    to the observed size distribution, persist the winners in a
    hardware-keyed tuned.json. --smoke is the tier-1 acceptance drive
    (reduced candidate set, synthetic skewed distributions, asserted
    fit-beats-pow2 + schema validity)."""
    from deepdfa_tpu.tune import cache as tune_cache, driver as tune_driver

    if args.smoke:
        report = tune_driver.run_tune_smoke(out_path=args.out)
        print(json.dumps(report), flush=True)
        bad = (
            not report["valid"]
            # the smoke's headline contract: a REAL search completed
            # (candidates timed, a winner chosen under the numerics
            # contract) and the measured ladder fit STRICTLY beats the
            # pow2 baseline on the skewed smoke distribution
            or report["winner"] is None
            or report["candidates_timed"] == 0
            or not (
                report["tuned_ladder_padding_waste"]
                < report["pow2_ladder_padding_waste"]
            )
            or not (
                report["seq_bucket_padding_waste"]
                <= report["seq_bucket_pow2_padding_waste"]
            )
        )
        if bad:
            raise SystemExit("tune smoke contract violated (see report)")
        return
    cfg = _load_run_config(args)
    report = tune_driver.run_tune(
        cfg,
        serve_logs=args.serve_log,
        manifest=args.manifest,
        out_path=args.out,
        skip_kernel=args.skip_kernel,
    )
    if not report["valid"]:
        raise SystemExit(
            "tuned.json failed validation: "
            + "; ".join(report["problems"])
        )
    # keep the trajectory contract visible: the committed TUNED_r*
    # documents gate round-over-round via scripts/bench_gate.py --tuned
    verdict = tune_cache.validate_tuned_file(report["tuned_path"])
    if not verdict["ok"]:
        raise SystemExit(
            "written tuned.json failed re-validation: "
            + "; ".join(verdict["problems"])
        )


def cmd_cascade_calibrate(args) -> None:
    """Fit the cascade's temperature + uncertainty band from a labeled
    dev set (docs/cascade.md calibration recipe): a JSONL of
    {"prob": p, "label": 0|1} rows (e.g. `score` output joined with
    labels) -> the serve.cascade_temperature / serve.cascade_band
    overrides to serve with."""
    from deepdfa_tpu.eval import calibrate as calibrate_mod

    probs, labels = [], []
    with open(args.scores) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            p, y = row.get(args.prob_key), row.get(args.label_key)
            if p is None or y is None:
                continue
            probs.append(float(p))
            labels.append(int(y))
    if not probs:
        raise SystemExit(
            f"no rows in {args.scores} carry both {args.prob_key!r} "
            f"and {args.label_key!r}"
        )
    result = calibrate_mod.calibrate(
        probs, labels, target_escalation=args.target_escalation
    )
    result["overrides"] = [
        f"serve.cascade_temperature={result['temperature']}",
        f"serve.cascade_band={json.dumps(result['band'])}",
    ]
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(result, indent=2))


def cmd_score(args) -> None:
    """Offline batch scoring of C source files against a trained
    checkpoint through the online serving path (docs/serving.md):
    cached frontend -> dynamic batcher -> AOT bucket executables. The
    summary asserts the serving contract (--smoke: zero steady-state
    recompiles) and a per-file scores JSONL lands in the run dir."""
    from deepdfa_tpu import obs
    from deepdfa_tpu.serve import driver

    cfg = _load_run_config(args)
    if args.smoke:
        cfg, run_dir, sources_dir = driver.build_smoke_run(
            extra_overrides=args.overrides
        )
        sources = driver.collect_sources([str(sources_dir)])
    else:
        if not args.sources:
            raise SystemExit("score needs source files/dirs (or --smoke)")
        cfg = _apply_tuned(cfg, serve_side=True)
        run_dir = paths.runs_dir(cfg.run_name)
        sources = driver.collect_sources(args.sources)
    with obs.session(cfg, run_dir):
        summary = driver.run_score(
            cfg, run_dir, sources, out_path=args.out, family=args.family
        )
    print(json.dumps(summary), flush=True)
    if args.smoke and summary["serve_steady_state_recompiles"]:
        raise SystemExit(
            f"smoke contract violated: "
            f"{summary['serve_steady_state_recompiles']} steady-state "
            f"recompiles (expected 0)"
        )


def cmd_serve(args) -> None:
    """Online scoring service (docs/serving.md): stdlib HTTP endpoint
    (/score, /healthz, /stats) over the dynamic batcher. --smoke starts
    on an ephemeral port, round-trips real HTTP requests, and exits."""
    from deepdfa_tpu import obs
    from deepdfa_tpu.serve import driver
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService, serve_forever

    if args.smoke:
        report = driver.run_serve_smoke(extra_overrides=args.overrides)
        print(json.dumps(report), flush=True)
        bad = (
            report["steady_state_recompiles"]
            or report["healthz_status"] != 200
            or report["stats_status"] != 200
            or any(s["status"] != 200 for s in report["scored"])
            # ISSUE 6 additions: a scrapeable /metrics, a deep healthz
            # with a backend verdict, and one request's spans flow-
            # linked (s/t/f chain) under its request_id in the trace
            or report["metrics_status"] != 200
            or report["deep_healthz_status"] != 200
            or report["trace_flow_phases"] != ["f", "s", "t"]
            # the device half of the chain: one inline span (serial) or
            # the dispatch+fetch pair (pipelined, ISSUE 17)
            or not (
                "device_execute" in report["trace_linked_spans"]
                or {"dispatch", "fetch"}
                <= set(report["trace_linked_spans"])
            )
            or "frontend" not in report["trace_linked_spans"]
            or "queue_wait" not in report["trace_linked_spans"]
            # ISSUE 8: the lines endpoint answered with ranked
            # attributions and compiled nothing after warmup
            or not report["line_attributions"]
            # ISSUE 10: every warmup compile was cost-accounted by the
            # efficiency ledger, and the flight recorder's dumped
            # postmortem validated (docs/efficiency.md)
            or not report["ledger_sites"]
            or not report["postmortem"]["ok"]
            # ISSUE 12: the cascade round trip — per-request stage
            # verdicts, escalation accounting, per-stage SLO windows,
            # zero recompiles on the stage-2 ladder, schema-valid
            # cascade serve_log (None = cascade overridden off)
            or (
                report.get("cascade") is not None
                and not report["cascade"]["ok"]
            )
        )
        if bad:
            raise SystemExit("serve smoke contract violated (see report)")
        return
    cfg = _load_run_config(args)
    cfg = _apply_tuned(cfg, serve_side=True)
    run_dir = paths.runs_dir(cfg.run_name)
    from deepdfa_tpu.serve.registry import serve_mesh

    registry = ModelRegistry(
        run_dir, family=args.family, checkpoint=cfg.serve.checkpoint,
        cfg=cfg, mesh=serve_mesh(cfg),
    )
    service = ScoringService(registry, cfg)
    with obs.session(cfg, run_dir):
        serve_forever(service, args.host, args.port)


def cmd_scan(args) -> None:
    """Whole-repo incremental scanning (docs/scanning.md): walk a
    repository, split C/C++ sources into functions, score each through
    the serving stack (shared frontend cache + AOT executables), stream
    findings to JSONL + SARIF 2.1.0. Re-scans of an edited repo touch
    only the changed functions (content-keyed manifest). --smoke trains
    a tiny checkpoint, scans a synthetic repo cold, edits one function,
    and asserts the incremental + zero-recompile contract."""
    from deepdfa_tpu import obs
    from deepdfa_tpu.scan import scanner as scan_mod

    if args.smoke:
        report = scan_mod.run_scan_smoke(extra_overrides=args.overrides)
        print(json.dumps(report), flush=True)
        cold, incr = report["cold"], report["incremental"]
        bad = (
            cold["scan_functions"] == 0
            or cold["scan_reused"] != 0
            or report["findings"] != cold["scan_functions"]
            or report["findings_with_lines"] == 0
            or report["sarif_problems"]
            or report["sarif_results"] == 0
            # the incremental contract: ONE function changed -> one
            # extraction, everything else reused from the manifest
            or incr["scan_extracted"] != 1
            or incr["scan_reused"] != incr["scan_functions"] - 1
            # the zero-steady-state-recompiles contract on BOTH the
            # scoring and the line-attribution executables, both scans
            or any(
                s[k]
                for s in (cold, incr)
                for k in ("scan_steady_state_recompiles",
                          "scan_lines_steady_state_recompiles")
            )
            # ISSUE 10: the scan smoke's dumped postmortem validated
            or not report["postmortem"]["ok"]
        )
        if bad:
            raise SystemExit("scan smoke contract violated (see report)")
        return
    if not args.repo:
        raise SystemExit("scan needs a repository path (or --smoke)")
    cfg = _load_run_config(args)
    cfg = _apply_tuned(cfg, serve_side=True)
    if args.lines:
        cfg = config_mod.apply_overrides(cfg, ["scan.lines=true"])
    if args.no_incremental:
        cfg = config_mod.apply_overrides(cfg, ["scan.incremental=false"])
    run_dir = paths.runs_dir(cfg.run_name)
    from deepdfa_tpu.serve.registry import ModelRegistry, serve_mesh
    from deepdfa_tpu.serve.server import ScoringService

    registry = ModelRegistry(
        run_dir, family=args.family, checkpoint=cfg.serve.checkpoint,
        cfg=cfg, mesh=serve_mesh(cfg),
    )
    service = ScoringService(registry, cfg)
    try:
        with obs.session(cfg, run_dir):
            summary = scan_mod.RepoScanner(service, cfg).scan(
                args.repo, out_jsonl=args.out, sarif_out=args.sarif,
            )
    finally:
        service.close()
    print(json.dumps(summary), flush=True)


def cmd_fleet(args) -> None:
    """Multi-replica serving fleet (docs/fleet.md): spawn N
    `fleet-replica` workers against one run dir, then front-door them
    with the health-gated router (least-outstanding routing, tenant
    admission, deadline-aware shedding). --smoke trains a tiny
    checkpoint and drives a 2-replica fleet end to end: bit-parity vs
    singleton serving, shed-before-device-time, kill-mid-stream
    failover, graceful drain, schema-valid fleet log."""
    from deepdfa_tpu.fleet import smoke as fleet_smoke

    if args.smoke:
        report = fleet_smoke.run_fleet_smoke(
            extra_overrides=args.overrides
        )
        print(json.dumps(report), flush=True)
        bad = fleet_smoke.smoke_verdict(report)
        if bad:
            raise SystemExit(
                "fleet smoke contract violated:\n  " + "\n  ".join(bad)
            )
        return
    import os as os_mod
    import signal as signal_mod
    import subprocess as subprocess_mod
    import sys as sys_mod
    import time as time_mod

    from deepdfa_tpu import obs
    from deepdfa_tpu.fleet import ha as fleet_ha
    from deepdfa_tpu.fleet.admission import plan_replicas
    from deepdfa_tpu.fleet.replica import (
        estimate_entry_bytes,
        spawn_replicas,
        wait_for_ready,
    )

    cfg = _load_run_config(args)
    run_dir = paths.runs_dir(cfg.run_name)
    fleet_dir = Path(cfg.fleet.fleet_dir or run_dir / "fleet")
    host = args.host if args.host is not None else cfg.fleet.host
    port = args.port if args.port is not None else cfg.fleet.port
    n = args.replicas if args.replicas is not None else cfg.fleet.replicas
    if n is None or int(n) <= 0:
        # fleet.replicas unset: size the fleet from the per-entry
        # param-bytes ledger signal (ROADMAP item 2) — checkpoint bytes
        # on disk arbitrated by plan_replicas against the HBM budget
        entry_bytes = estimate_entry_bytes(cfg, run_dir)
        n, plan = plan_replicas(
            entry_bytes, cfg.fleet.hbm_budget_bytes
        )
        print(json.dumps({"fleet_replica_plan": plan}), flush=True)
        import logging as logging_mod

        logging_mod.getLogger(__name__).warning(
            "fleet.replicas unset: running %d replica(s) per the "
            "param-bytes plan (%s; per-replica working set %.0f bytes "
            "vs budget %.0f)",
            n, plan["reason"], plan["per_replica_bytes"],
            plan["hbm_budget_bytes"],
        )
    procs = spawn_replicas(
        run_dir, fleet_dir, n, overrides=args.overrides
    )
    standby_proc = None
    # a scheduler stops the fleet with SIGTERM: convert it to the same
    # unwind Ctrl-C takes so the finally-drain (SIGTERM the replicas,
    # final summary record) actually runs
    def _sigterm_to_interrupt(signum, frame):
        raise KeyboardInterrupt

    signal_mod.signal(signal_mod.SIGTERM, _sigterm_to_interrupt)
    with obs.session(cfg, run_dir):
        # the front door is an HA member even solo: it publishes the
        # router.json rendezvous clients re-resolve from, and a standby
        # (fleet.standby_router, or any `fleet-router` process pointed
        # at the same fleet dir) takes over inside the failover window
        ha_router = fleet_ha.HARouter(
            cfg, fleet_dir, router_id=f"router-{os_mod.getpid()}",
            host=host, port=port,
            log_path=run_dir / "fleet_log.jsonl",
        )
        try:
            wait_for_ready(
                fleet_dir, [rid for rid, _ in procs],
                timeout_s=args.ready_timeout, procs=procs,
            )
            ha_router.start()
            if not ha_router.wait_active(timeout_s=60.0):
                raise SystemExit(
                    "router did not become active (another active "
                    f"router owns {fleet_ha.rendezvous_path(fleet_dir)}?)"
                )
            if cfg.fleet.standby_router:
                standby_proc = subprocess_mod.Popen([
                    sys_mod.executable, "-m", "deepdfa_tpu.cli",
                    "fleet-router",
                    "--run-dir", str(run_dir),
                    "--fleet-dir", str(fleet_dir),
                    "--host", host,
                    *(["--config", args.config] if args.config else []),
                    *sum((["--override", ov] for ov in args.overrides),
                         []),
                ])
            print(json.dumps({
                "fleet": True,
                "host": ha_router.host,
                "port": ha_router.port,
                "replicas": [rid for rid, _ in procs],
                "fleet_dir": str(fleet_dir),
                "rendezvous": str(fleet_ha.rendezvous_path(fleet_dir)),
                "standby": standby_proc is not None,
                **ha_router.router.topology(),
            }), flush=True)
            while True:
                time_mod.sleep(1.0)
        except KeyboardInterrupt:
            pass
        finally:
            # drain the replicas the way a scheduler would: SIGTERM,
            # then wait for the graceful exit
            for _, proc in procs:
                if proc.poll() is None:
                    proc.send_signal(signal_mod.SIGTERM)
            if standby_proc is not None and standby_proc.poll() is None:
                standby_proc.send_signal(signal_mod.SIGTERM)
            deadline = time_mod.time() + 60
            for _, proc in procs:
                try:
                    proc.wait(
                        timeout=max(1.0, deadline - time_mod.time())
                    )
                except Exception:
                    proc.kill()
            if standby_proc is not None:
                try:
                    standby_proc.wait(
                        timeout=max(1.0, deadline - time_mod.time())
                    )
                except Exception:
                    standby_proc.kill()
            ha_router.close()


def cmd_fleet_replica(args) -> None:
    """One fleet replica worker (docs/fleet.md): a full ScoringService
    with its own AOT-warmed ladders, announced via heartbeat file;
    SIGTERM drains gracefully (finish in-flight batches, final SLO
    snapshot, flight-recorder postmortem)."""
    from deepdfa_tpu import obs
    from deepdfa_tpu.core import config as _config_mod
    from deepdfa_tpu.fleet.replica import ReplicaWorker
    from deepdfa_tpu.serve.registry import load_run_config

    run_dir = Path(args.run_dir)
    if not run_dir.is_dir():
        candidate = paths.runs_dir(args.run_dir)
        if candidate.is_dir():
            run_dir = candidate
        else:
            raise SystemExit(f"no such run dir: {args.run_dir}")
    cfg = load_run_config(run_dir)
    cfg = _config_mod.apply_overrides(cfg, args.overrides)
    _config_mod.validate(cfg)
    _config_mod.apply_sanitizers(cfg)
    cfg = _apply_tuned(cfg, serve_side=True)
    worker = ReplicaWorker(
        cfg, run_dir, args.replica_id,
        fleet_dir=args.fleet_dir, host=args.host, port=args.port,
        family=args.family, shadow=getattr(args, "shadow", False),
    )
    # per-replica obs home: traces + postmortem never collide across
    # replicas sharing one run dir
    with obs.session(cfg, worker.obs_dir):
        raise SystemExit(worker.run())


def _resolve_fleet_run(args):
    """(cfg, run_dir, fleet_dir) for the fleet-router/fleet-rollout
    commands (the fleet-replica run-dir resolution, shared)."""
    from deepdfa_tpu.core import config as _config_mod
    from deepdfa_tpu.serve.registry import load_run_config

    run_dir = Path(args.run_dir)
    if not run_dir.is_dir():
        candidate = paths.runs_dir(args.run_dir)
        if candidate.is_dir():
            run_dir = candidate
        else:
            raise SystemExit(f"no such run dir: {args.run_dir}")
    if getattr(args, "config", None):
        # a fleet launched with an explicit --config runs on that file,
        # not the run dir's saved config — the standby/rollout must
        # resolve the SAME configuration (admission policy, failover
        # cadences) or a takeover silently changes policy
        cfg = _config_mod.load(Path(args.config))
    else:
        cfg = load_run_config(run_dir)
    cfg = _config_mod.apply_overrides(cfg, args.overrides)
    _config_mod.validate(cfg)
    fleet_dir = Path(
        args.fleet_dir or cfg.fleet.fleet_dir or run_dir / "fleet"
    )
    return cfg, run_dir, fleet_dir


def cmd_fleet_router(args) -> None:
    """One HA router process (docs/fleet.md): joins the active/standby
    pair over the shared fleet dir. With a fresh (or stale) rendezvous
    it becomes active and serves the front door; otherwise it stands by
    — tailing the heartbeat dir, health-checking the active via
    router.json — and takes over within the failover window, re-seeding
    admission token buckets from the fleet_log's last summary record."""
    import os as os_mod
    import signal as signal_mod
    import time as time_mod

    from deepdfa_tpu.fleet import ha as fleet_ha

    cfg, run_dir, fleet_dir = _resolve_fleet_run(args)
    router_id = args.router_id or f"router-{os_mod.getpid()}"
    host = args.host if args.host is not None else cfg.fleet.host
    port = args.port if args.port is not None else cfg.fleet.port
    ha_router = fleet_ha.HARouter(
        cfg, fleet_dir, router_id=router_id, host=host, port=port,
        log_path=run_dir / "fleet_log.jsonl",
    )

    def _sigterm_to_interrupt(signum, frame):
        raise KeyboardInterrupt

    signal_mod.signal(signal_mod.SIGTERM, _sigterm_to_interrupt)
    try:
        ha_router.start()
        print(json.dumps({
            "router_id": router_id,
            "role": ha_router.role,
            "host": ha_router.host,
            "port": ha_router.port if ha_router.role == "active" else None,
            "rendezvous": str(fleet_ha.rendezvous_path(fleet_dir)),
            "failover_timeout_s": cfg.fleet.router_failover_timeout_s,
        }), flush=True)
        while True:
            time_mod.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        ha_router.close()


def cmd_fleet_rollout(args) -> None:
    """Zero-downtime rollout (docs/fleet.md): hot-swap a checkpoint tag
    across every ready replica one at a time — drift-gated per replica,
    SLO-guarded between swaps, halted + rolled back on a breach. Exit 0
    only when every replica swapped with the census intact."""
    from deepdfa_tpu.fleet import rollout as fleet_rollout

    cfg, run_dir, fleet_dir = _resolve_fleet_run(args)
    router_addr = None
    if args.router:
        host, _, port = args.router.rpartition(":")
        router_addr = (host or "127.0.0.1", int(port))
    report = fleet_rollout.run_rollout(
        cfg, fleet_dir, args.checkpoint,
        router_addr=router_addr,
        log_path=run_dir / "fleet_log.jsonl",
    )
    print(json.dumps(report), flush=True)
    if not report.get("ok") or not report.get("census_ok"):
        raise SystemExit(1)


def cmd_flywheel(args) -> None:
    """Data-flywheel controller (docs/flywheel.md): watch the shadow
    comparison records a candidate's ride leaves in fleet_log.jsonl
    and act on the verdict — a candidate beating the incumbent past
    the configured margin for long enough is promoted through the
    SAME drift-gated, SLO-guarded `fleet-rollout` path a human would
    run; a trailing or drifting one is demoted with a schema-valid
    record. `--retrain` instead replays the serve log into a
    traffic-weighted calibration set and builds the candidate run dir
    the shadow replica serves. Exit 0 only when the decided action
    completed cleanly (a promote whose rollout halted exits 1)."""
    from deepdfa_tpu.flywheel import promote as flywheel_promote

    cfg, run_dir, fleet_dir = _resolve_fleet_run(args)
    log_path = run_dir / "fleet_log.jsonl"
    if args.retrain:
        from deepdfa_tpu.flywheel import retrain as flywheel_retrain

        out_dir = Path(args.out) if args.out else run_dir / "candidate"
        report = flywheel_retrain.build_candidate(
            cfg, run_dir, out_dir,
            Path(args.log) if args.log else log_path,
            steps=args.steps, max_examples=args.max_examples,
        )
        print(json.dumps(report), flush=True)
        return
    if not args.candidate:
        raise SystemExit("--candidate is required (the checkpoint tag "
                         "riding shadow) unless --retrain")
    router_addr = None
    if args.router:
        host, _, port = args.router.rpartition(":")
        router_addr = (host or "127.0.0.1", int(port))
    if args.watch:
        report = flywheel_promote.watch(
            cfg, fleet_dir, args.candidate, log_path,
            interval_s=args.interval, timeout_s=args.timeout,
            router_addr=router_addr,
        )
    else:
        report = flywheel_promote.run_promotion(
            cfg, fleet_dir, args.candidate, log_path,
            router_addr=router_addr,
        )
    print(json.dumps(report), flush=True)
    if report.get("reason") == "rollout_halted":
        raise SystemExit(1)


def cmd_fleet_drill(args) -> None:
    """Scheduled chaos drills (docs/fleet.md): run failure-matrix
    scenarios on a cadence and fold the measured failover/readmit/
    reseed/rollback times into one DRILL record — the gated trajectory
    `scripts/bench_gate.py --drill` regresses round over round, with
    the documented 3.2 s failover bound as an absolute ceiling.
    --smoke drills an in-process stub fleet (<60 s); full mode drives
    `scripts/fault_inject.py --fleet` scenario subprocesses (real
    replica processes, real SIGKILLs)."""
    import tempfile as tempfile_mod

    from deepdfa_tpu.core import config as _config_mod
    from deepdfa_tpu.fleet import drill as fleet_drill

    # cadence defaults come from config (fleet.drill_rounds /
    # fleet.drill_interval_s) so a scheduler entry and the CLI agree
    cfg = (
        _config_mod.load(Path(args.config)) if args.config
        else _config_mod.Config()
    )
    cfg = _config_mod.apply_overrides(cfg, args.overrides)
    rounds = (
        args.rounds if args.rounds is not None
        else cfg.fleet.drill_rounds
    )
    interval_s = (
        args.interval if args.interval is not None
        else cfg.fleet.drill_interval_s
    )
    if args.smoke:
        with tempfile_mod.TemporaryDirectory() as td:
            record = fleet_drill.DrillScheduler(
                runner=lambda i: fleet_drill.run_smoke_drill(
                    Path(td) / f"round{i}"
                ),
                rounds=rounds, interval_s=interval_s,
                scenarios=fleet_drill.SMOKE_SCENARIOS, mode="smoke",
            ).run()
    else:
        scenarios = (
            tuple(args.scenario) if args.scenario
            else fleet_drill.FULL_SCENARIOS
        )
        record = fleet_drill.DrillScheduler(
            runner=lambda i: fleet_drill.run_full_drill(scenarios),
            rounds=rounds, interval_s=interval_s,
            scenarios=scenarios, mode="full",
        ).run()
    if args.out:
        path = fleet_drill.write_drill_record(record, args.out)
        record["path"] = str(path)
    print(json.dumps(record), flush=True)
    if not record.get("ok"):
        raise SystemExit(1)


def cmd_bench(args) -> None:
    import bench

    bench.main()


def _apply_platform_override() -> None:
    """DEEPDFA_TPU_PLATFORM=cpu[:N] forces the JAX platform (e.g. run the
    pipeline on a host whose accelerator tunnel is down, or test multi-chip
    code on N virtual CPU devices). Must run before any backend use; works
    even where a sitecustomize pins JAX_PLATFORMS."""
    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()


def main(argv=None) -> None:
    _apply_platform_override()
    parser = argparse.ArgumentParser(prog="deepdfa_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("prepare")
    p.add_argument("--source", required=True, help="csv/json path or 'synthetic'")
    p.add_argument("--splits", default=None, help="optional splits csv")
    p.add_argument("--cross-project", action="store_true",
                   help="project-disjoint splits from the csv's project column")
    p.add_argument("--dep-closure", action="store_true",
                   help="expand line labels with data/control dependents")
    p.add_argument("--sample", type=int, default=None)
    p.add_argument("--n-examples", type=int, default=2000)
    p.add_argument("--synthetic-v2", action="store_true",
                   help="hardened synthetic corpus: order-sensitive bug "
                   "families + benign lookalikes + label noise "
                   "(docs/ROUND4_NOTES.md)")
    p.add_argument("--lookalike-rate", type=float, default=0.5)
    p.add_argument("--label-noise", type=float, default=0.02)
    p.add_argument("--format", default="auto",
                   choices=("auto", "bigvul", "devign", "dbgbench", "synthetic"),
                   help="source format (auto: by file extension)")
    p.add_argument("--mutated-jsonl", default=None,
                   help="mutated-variant jsonl to join onto the base dataset")
    p.add_argument("--mutated-flip", action="store_true",
                   help="use the jsonl 'source' field (the *_flip variants)")
    p.add_argument("--export-codet5", action="store_true",
                   help="also write per-split CodeT5 defect jsonl "
                        "(idx/code/target — the unixcoder export hook)")
    _add_common(p)
    p.set_defaults(fn=cmd_prepare)

    p = sub.add_parser("extract")
    p.add_argument("--workers", type=int, default=0)
    p.add_argument("--shard", type=int, default=0, help="job-array shard id")
    p.add_argument("--num-shards", type=int, default=1)
    p.add_argument("--vocab-from", default=None,
                   help="encode with another dataset's vocab json "
                        "(cross-dataset / DbgBench-style evaluation)")
    _add_common(p)
    p.set_defaults(fn=cmd_extract)

    p = sub.add_parser("extract-vocab")
    p.add_argument("--workers", type=int, default=0)
    _add_common(p)
    p.set_defaults(fn=cmd_extract_vocab)

    p = sub.add_parser("train-combined")
    p.add_argument("--arch", default="roberta", choices=["roberta", "t5"],
                   help="roberta (LineVul/UniXcoder style) | t5 (CodeT5 style)")
    p.add_argument("--encoder", default="tiny",
                   help="tiny | codebert-base | codet5-base")
    p.add_argument("--pretrained", default=None,
                   help="path to a torch state_dict for the encoder")
    p.add_argument("--tokenizer", default=None,
                   help="dir with vocab.json+merges.txt (default: hash tokenizer)")
    p.add_argument("--max-length", type=int, default=512)
    p.add_argument("--sp-variant", default="ring", choices=["ring", "ulysses"],
                   help="sequence-parallel attention scheme on sp>1 "
                        "meshes (both archs: ring k/v rotation or "
                        "ulysses all-to-all head sharding)")
    p.add_argument("--attn-impl", default="auto",
                   choices=["auto", "xla", "flash"],
                   help="encoder local-attention lowering, both archs: "
                        "auto picks the fused Pallas flash kernel on TPU "
                        "(measured +22%% over xla on roberta, "
                        "docs/DESIGN.md); t5 passes its relative-position "
                        "bias as the kernel's additive-bias operand")
    p.add_argument("--remat-policy", default="full",
                   choices=["full", "attn_saved"],
                   help="remat granularity, both archs: full recomputes the "
                        "whole layer in backward; attn_saved keeps each "
                        "layer's attention output (+~[B,T,D] HBM/layer), "
                        "which skips re-running attention in backward on "
                        "the FLASH lowering (its custom-vjp outputs carry "
                        "the saved names; the xla lowering still replays "
                        "its softmax for dq/dk/dv, so there it mostly "
                        "trades memory for little)")
    p.add_argument("--no-graph", action="store_true")
    p.add_argument("--graph-checkpoint", default=None,
                   help="run name or checkpoints dir of a pretrained "
                        "standalone DeepDFA to load into the graph branch")
    p.add_argument("--freeze-graph", action="store_true",
                   help="freeze the loaded graph encoder (reference "
                        "--freeze_graph)")
    _add_common(p)
    p.set_defaults(fn=cmd_train_combined)

    p = sub.add_parser("train")
    _add_common(p)
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("test")
    p.add_argument("--checkpoint", default="best")
    p.add_argument("--split", default="test")
    p.add_argument("--profile", action="store_true")
    p.add_argument("--xprof-dir", default=None,
                   help="dump a jax.profiler device trace of the eval "
                        "pass here (TensorBoard profile plugin)")
    p.add_argument("--export", action="store_true",
                   help="write per-example predictions csv")
    _add_common(p)
    p.set_defaults(fn=cmd_test)

    p = sub.add_parser("localize")
    p.add_argument("--arch", default="roberta", choices=["roberta", "t5"],
                   help="combined architecture the checkpoint was trained "
                        "with (attention method is roberta-only)")
    p.add_argument("--no-graph", action="store_true")
    p.add_argument(
        "--method", default="saliency",
        choices=["attention", "saliency", "input_x_gradient", "lig",
                 "deeplift", "deeplift_shap", "gradient_shap"],
    )
    p.add_argument("--checkpoint", default="best")
    p.add_argument("--split", default="test")
    p.add_argument("--encoder", default="tiny")
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--max-length", type=int, default=512)
    p.add_argument("--limit", type=int, default=None)
    _add_common(p)
    p.set_defaults(fn=cmd_localize)

    p = sub.add_parser("coverage")
    _add_common(p)
    p.set_defaults(fn=cmd_coverage)

    p = sub.add_parser(
        "ivdetect",
        help="dump per-line IVDetect features (subseq/ast/nametypes/"
        "data/control) for C files",
    )
    p.add_argument("sources", nargs="+", help="C/C++ source files")
    p.add_argument("--out-dir", default=None)
    p.set_defaults(fn=cmd_ivdetect)

    p = sub.add_parser("train-gen")
    p.add_argument("--task", choices=sorted(
        ("summarize", "translate", "refine", "concode", "defect")
    ), required=True)
    p.add_argument("--train-file", default=None)
    p.add_argument("--dev-file", default=None)
    p.add_argument("--test-file", default=None)
    p.add_argument("--data-num", type=int, default=-1)
    p.add_argument("--max-source-length", type=int, default=256)
    p.add_argument("--max-target-length", type=int, default=128)
    p.add_argument("--beam-size", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--patience", type=int, default=2)
    p.add_argument("--do-eval-bleu", action="store_true")
    p.add_argument("--tiny", action="store_true",
                   help="tiny T5 config (tests/smoke)")
    p.add_argument("--tokenizer", choices=("hash", "bpe"), default="hash")
    p.add_argument("--vocab-size", type=int, default=4096)
    p.add_argument("--vocab-file", default=None)
    p.add_argument("--merges-file", default=None)
    p.add_argument("--pretrained", default=None,
                   help="HF torch T5ForConditionalGeneration state_dict")
    _add_common(p)
    p.set_defaults(fn=cmd_train_gen)

    p = sub.add_parser("train-multi-gen")
    p.add_argument("--task-spec", action="append", required=True,
                   help="name=train_file[:dev_file]; name's <family>_* "
                        "prefix picks reader/patience/target-length "
                        "(repeatable)")
    p.add_argument("--max-steps", type=int, default=1000)
    p.add_argument("--eval-every", type=int, default=None)
    p.add_argument("--data-num", type=int, default=-1)
    p.add_argument("--max-source-length", type=int, default=256)
    p.add_argument("--max-target-length", type=int, default=128)
    p.add_argument("--beam-size", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--do-eval-bleu", action="store_true")
    p.add_argument("--tiny", action="store_true",
                   help="tiny T5 config (tests/smoke)")
    p.add_argument("--tokenizer", choices=("hash", "bpe"), default="hash")
    p.add_argument("--vocab-size", type=int, default=4096)
    p.add_argument("--vocab-file", default=None)
    p.add_argument("--merges-file", default=None)
    p.add_argument("--pretrained", default=None,
                   help="HF torch T5ForConditionalGeneration state_dict")
    _add_common(p)
    p.set_defaults(fn=cmd_train_multi_gen)

    # no _add_common here: positional overrides would be swallowed by the
    # nargs='*' flags — per-run config overrides go through --override
    p = sub.add_parser("run-exp")
    p.add_argument("--matrix", default=None, help="json run-matrix spec")
    p.add_argument("--tasks", nargs="*", default=None,
                   help="built-in task names (deepdfa/combined/summarize/...)")
    p.add_argument("--seeds", nargs="*", type=int, default=[0])
    p.add_argument("--extra-arg", action="append", default=[],
                   help="extra CLI flag passed to every run (repeatable)")
    p.add_argument("--override", action="append", default=[],
                   help="dotted key=value config override for every run "
                        "(repeatable)")
    p.add_argument("--tag", default="default")
    p.add_argument("--dry-run", action="store_true")
    p.set_defaults(fn=cmd_run_exp)

    p = sub.add_parser("train-clone")
    p.add_argument("--train-file", default=None)
    p.add_argument("--dev-file", default=None)
    p.add_argument("--test-file", default=None)
    p.add_argument("--data-num", type=int, default=-1)
    p.add_argument("--max-source-length", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--patience", type=int, default=2)
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--tokenizer", choices=("hash", "bpe"), default="hash")
    p.add_argument("--vocab-size", type=int, default=4096)
    p.add_argument("--vocab-file", default=None)
    p.add_argument("--merges-file", default=None)
    p.add_argument("--pretrained", default=None,
                   help="HF torch T5ForConditionalGeneration state_dict")
    _add_common(p)
    p.set_defaults(fn=cmd_train_clone)

    p = sub.add_parser("codebleu")
    p.add_argument("--refs", nargs="+", required=True,
                   help="reference files (one example per line)")
    p.add_argument("--hyp", required=True, help="hypothesis file")
    from deepdfa_tpu.eval.codebleu import LANG_DIALECT

    p.add_argument("--lang", default="c",
                   choices=sorted(set(LANG_DIALECT) | {"python"}))
    p.add_argument("--params", default="0.25,0.25,0.25,0.25",
                   help="alpha,beta,gamma,theta component weights")
    p.set_defaults(fn=cmd_codebleu)

    p = sub.add_parser(
        "diag",
        help="render run telemetry: throughput timeline, stage "
        "attribution, resilience events (docs/observability.md)",
    )
    p.add_argument("run_dir", nargs="?", default=None,
                   help="run directory or run name under storage/runs")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.add_argument("--smoke", action="store_true",
                   help="build + render a synthetic run dir (tier-1)")
    p.add_argument("--postmortem", default=None, metavar="PATH",
                   help="render one postmortem.json (crash flight "
                        "recorder dump, docs/efficiency.md) instead of "
                        "a run dir")
    p.add_argument("--fleet", default=None, metavar="FLEET_DIR",
                   help="fleet-wide mode: stitch shipped trace segments "
                        "into one Perfetto timeline, summarize metrics "
                        "snapshots + alert records (docs/alerts.md)")
    p.set_defaults(fn=cmd_diag)

    p = sub.add_parser(
        "alerts",
        help="replay a fleet log through the alert engine: what would "
        "have fired, when, and did it resolve (docs/alerts.md)",
    )
    p.add_argument("fleet_log",
                   help="path to a fleet_log.jsonl to replay")
    p.add_argument("--rules", default=None, metavar="PATH",
                   help="JSON list of rule docs to use instead of the "
                        "default catalog (docs/alerts.md)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable replay summary")
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser(
        "score",
        help="offline batch scoring of C sources through the serving "
        "path (frontend cache -> dynamic batcher -> AOT executables)",
    )
    p.add_argument("--sources", nargs="*", default=[],
                   help="C source files and/or directories (one function "
                        "per file)")
    p.add_argument("--out", default=None,
                   help="scores jsonl path (default <run>/scores.jsonl)")
    p.add_argument("--family", default="deepdfa",
                   choices=["deepdfa", "combined", "t5"],
                   help="model family to restore; combined/t5 need the "
                        "run's model_cfg.json manifest (train-combined "
                        "writes it; docs/cascade.md)")
    p.add_argument("--smoke", action="store_true",
                   help="self-contained: train a tiny synthetic "
                        "checkpoint, score its corpus, assert zero "
                        "steady-state recompiles (tier-1)")
    # no _add_common: positional overrides would be swallowed by the
    # nargs='*' --sources flag (the run-exp precedent) — use --override
    p.add_argument("--config", default=None, help="json config file")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_score)

    p = sub.add_parser(
        "tune",
        help="offline measured-search autotuner: kernel tiles + batch "
        "ladders fitted to observed traffic, persisted per hardware "
        "generation in tuned.json (docs/tuning.md)",
    )
    p.add_argument("--serve-log", action="append", default=[],
                   metavar="PATH",
                   help="serve_log.jsonl / fleet_log.jsonl to replay "
                        "the observed batch-size distribution from "
                        "(repeatable; needs serve.request_log=true "
                        "entries)")
    p.add_argument("--manifest", default=None, metavar="PATH",
                   help="training manifest of real token lengths (JSON "
                        "array, or JSONL with a length/tokens field) "
                        "for the seq-bucket fit")
    p.add_argument("--out", default=None,
                   help="tuned.json path (default tune.path, else "
                        "<storage>/tuned.json)")
    p.add_argument("--skip-kernel", action="store_true",
                   help="ladder fits only (skip the kernel candidate "
                        "compile-and-time pass)")
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 acceptance drive: real search over a "
                        "reduced candidate set + synthetic skewed "
                        "distributions; asserts fit-beats-pow2 and a "
                        "schema-valid tuned.json")
    # consistent override surface with score/serve (no positionals)
    p.add_argument("--config", default=None, help="json config file")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_tune)

    p = sub.add_parser(
        "cascade-calibrate",
        help="fit the cascade temperature + uncertainty band from a "
        "labeled dev-set scores jsonl (docs/cascade.md)",
    )
    p.add_argument("--scores", required=True,
                   help="jsonl with per-row prob + label fields")
    p.add_argument("--prob-key", default="prob")
    p.add_argument("--label-key", default="label")
    p.add_argument("--target-escalation", type=float, default=0.3,
                   help="dev-set fraction the band should escalate")
    p.add_argument("--out", default=None,
                   help="also write the result json here")
    p.set_defaults(fn=cmd_cascade_calibrate)

    p = sub.add_parser(
        "serve",
        help="online scoring service: HTTP /score /healthz /stats over "
        "the dynamic batcher (docs/serving.md)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8471)
    p.add_argument("--family", default="deepdfa",
                   choices=["deepdfa", "combined", "t5"])
    p.add_argument("--smoke", action="store_true",
                   help="ephemeral-port smoke: real HTTP round trips "
                        "against a just-trained tiny checkpoint (tier-1)")
    # consistent override surface with `score` (no positionals)
    p.add_argument("--config", default=None, help="json config file")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "scan",
        help="whole-repo incremental scan through the serving stack: "
        "findings JSONL + SARIF 2.1.0, content-keyed re-scans "
        "(docs/scanning.md)",
    )
    p.add_argument("repo", nargs="?", default=None,
                   help="repository root to scan")
    p.add_argument("--out", default=None,
                   help="findings jsonl path "
                        "(default <run>/scan/findings.jsonl)")
    p.add_argument("--sarif", default=None,
                   help="SARIF 2.1.0 path "
                        "(default <run>/scan/findings.sarif)")
    p.add_argument("--lines", action="store_true",
                   help="per-finding line attributions (scan.lines; "
                        "AOT attribution executables, docs/scanning.md)")
    p.add_argument("--no-incremental", action="store_true",
                   help="ignore the scan manifest (still written): "
                        "score every function cold")
    p.add_argument("--family", default="deepdfa", choices=["deepdfa"])
    p.add_argument("--smoke", action="store_true",
                   help="self-contained: tiny checkpoint, synthetic "
                        "repo, cold + incremental scans, SARIF/JSONL "
                        "validation, zero-recompile assert (tier-1)")
    # no _add_common: the optional positional would swallow overrides
    # (the score/serve precedent) — use --override
    p.add_argument("--config", default=None, help="json config file")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_scan)

    p = sub.add_parser(
        "fleet",
        help="multi-replica serving fleet: N replica workers behind a "
        "health-gated router with tenant admission + deadline-aware "
        "shedding (docs/fleet.md)",
    )
    p.add_argument("--host", default=None,
                   help="router bind address (default fleet.host)")
    p.add_argument("--port", type=int, default=None,
                   help="router port (default fleet.port)")
    p.add_argument("--replicas", type=int, default=None,
                   help="replica worker processes "
                        "(default fleet.replicas)")
    p.add_argument("--ready-timeout", type=float, default=600.0,
                   help="seconds to wait for every replica heartbeat "
                        "to reach 'ready'")
    p.add_argument("--smoke", action="store_true",
                   help="self-contained 2-replica acceptance drive: "
                        "bit-parity vs singleton serving, shed-before-"
                        "device-time, kill failover, graceful drain "
                        "(tier-1)")
    # consistent override surface with score/serve (no positionals)
    p.add_argument("--config", default=None, help="json config file")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser(
        "fleet-replica",
        help="one fleet replica worker (spawned by `fleet`): "
        "ScoringService + heartbeat + graceful SIGTERM drain",
    )
    p.add_argument("--run-dir", required=True,
                   help="run directory (or run name under storage/runs)")
    p.add_argument("--replica-id", required=True)
    p.add_argument("--fleet-dir", default=None,
                   help="heartbeat/obs dir (default <run_dir>/fleet)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral (published via heartbeat)")
    p.add_argument("--family", default="deepdfa", choices=["deepdfa"])
    p.add_argument("--shadow", action="store_true",
                   help="flywheel shadow role (docs/flywheel.md): "
                        "heartbeat carries shadow=true so the router "
                        "never routes live traffic here and rollouts "
                        "skip it; /score still answers for the mirror "
                        "stream")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_fleet_replica)

    p = sub.add_parser(
        "fleet-router",
        help="one HA router (active/standby negotiated via the "
        "router.json rendezvous file): the standby tails the heartbeat "
        "dir + fleet_log and takes over the front door within the "
        "failover window when the active dies (docs/fleet.md)",
    )
    p.add_argument("--run-dir", required=True,
                   help="run directory (or run name under storage/runs)")
    p.add_argument("--router-id", default=None,
                   help="router identity in the rendezvous/fleet_log "
                        "(default: router-<pid>)")
    p.add_argument("--fleet-dir", default=None,
                   help="heartbeat/rendezvous dir (default "
                        "<run_dir>/fleet)")
    p.add_argument("--host", default=None,
                   help="bind address when active (default fleet.host)")
    p.add_argument("--port", type=int, default=None,
                   help="preferred port when active; falls back to "
                        "ephemeral, clients re-resolve from router.json "
                        "(default fleet.port)")
    p.add_argument("--config", default=None,
                   help="json config file (default: the run dir's saved "
                        "config.json); pass the SAME file the fleet was "
                        "launched with so a takeover keeps its policy")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_fleet_router)

    p = sub.add_parser(
        "fleet-rollout",
        help="zero-downtime checkpoint rollout: drain->swap->re-warm->"
        "readmit one replica at a time under traffic, drift-gated and "
        "halted + rolled back on an SLO breach (docs/fleet.md)",
    )
    p.add_argument("--run-dir", required=True,
                   help="run directory (or run name under storage/runs)")
    p.add_argument("--checkpoint", required=True,
                   help="checkpoint tag to roll out (manifest tag; "
                        "@int8 composes)")
    p.add_argument("--fleet-dir", default=None,
                   help="heartbeat/rendezvous dir (default "
                        "<run_dir>/fleet)")
    p.add_argument("--router", default=None, metavar="HOST:PORT",
                   help="router address for the SLO guard (default: "
                        "resolved from the router.json rendezvous)")
    p.add_argument("--config", default=None,
                   help="json config file (default: the run dir's saved "
                        "config.json); pass the SAME file the fleet was "
                        "launched with")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_fleet_rollout)

    p = sub.add_parser(
        "flywheel",
        help="data-flywheel controller: watch a candidate's shadow-ride "
        "records in fleet_log.jsonl and promote it through the "
        "drift-gated fleet-rollout path when it beats the incumbent "
        "past fleet.flywheel_promote_margin (demote when trailing or "
        "drifting); --retrain builds the candidate run dir from the "
        "traffic-weighted serve log (docs/flywheel.md)",
    )
    p.add_argument("--run-dir", required=True,
                   help="run directory (or run name under storage/runs)")
    p.add_argument("--candidate", default=None,
                   help="checkpoint tag riding shadow (the tag "
                        "fleet-rollout swaps to on promotion)")
    p.add_argument("--watch", action="store_true",
                   help="poll until the verdict leaves 'hold' (or "
                        "--timeout expires, which demotes); default: "
                        "decide once and exit")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--watch poll cadence, seconds")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="--watch deadline: a candidate still on 'hold' "
                        "is demoted (insufficient evidence is a no)")
    p.add_argument("--router", default=None, metavar="HOST:PORT",
                   help="router address for the rollout's SLO guard "
                        "(default: resolved from router.json)")
    p.add_argument("--retrain", action="store_true",
                   help="build the candidate instead: replay the fleet "
                        "log into a traffic-weighted calibration set "
                        "and write a servable candidate run dir")
    p.add_argument("--log", default=None,
                   help="--retrain: fleet/serve log to weight from "
                        "(default <run_dir>/fleet_log.jsonl)")
    p.add_argument("--out", default=None,
                   help="--retrain: candidate run dir to write "
                        "(default <run_dir>/candidate)")
    p.add_argument("--steps", type=int, default=0,
                   help="--retrain: fine-tune steps on the weighted "
                        "set (0 = calibration-only warm start)")
    p.add_argument("--max-examples", type=int, default=512,
                   help="--retrain: weighted-selection budget")
    p.add_argument("--fleet-dir", default=None,
                   help="heartbeat/rendezvous dir (default "
                        "<run_dir>/fleet)")
    p.add_argument("--config", default=None,
                   help="json config file (default: the run dir's saved "
                        "config.json)")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_flywheel)

    p = sub.add_parser(
        "fleet-drill",
        help="scheduled chaos drills: failure-matrix scenarios on a "
        "cadence, measured recovery times folded into a gated "
        "DRILL_r* record (scripts/bench_gate.py --drill; "
        "docs/fleet.md)",
    )
    p.add_argument("--smoke", action="store_true",
                   help="in-process stub-fleet drill (<60 s, tier-1 "
                        "surface); default: full mode via "
                        "fault_inject.py --fleet subprocesses")
    p.add_argument("--rounds", type=int, default=None,
                   help="drill rounds to fold into one record "
                        "(default fleet.drill_rounds)")
    p.add_argument("--interval", type=float, default=None,
                   help="seconds between round STARTS "
                        "(default fleet.drill_interval_s)")
    p.add_argument("--scenario", action="append", default=[],
                   help="full-mode failure-matrix row (repeatable; "
                        "default wedge-backend, rollout, kill-router)")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="write the record to the next DRILL_rNN.json "
                        "slot under DIR (the repo root grows the "
                        "committed trajectory)")
    p.add_argument("--config", default=None, help="json config file")
    p.add_argument("--override", action="append", default=[],
                   dest="overrides",
                   help="dotted key=value config override (repeatable)")
    p.set_defaults(fn=cmd_fleet_drill)

    p = sub.add_parser("bench")
    _add_common(p)
    p.set_defaults(fn=cmd_bench)

    args = parser.parse_args(argv)
    try:
        args.fn(args)
    except Exception as e:
        # a clean preemption exit (train/resilience.py): the in-flight
        # step finished, the state + resume manifest are on disk, and
        # re-running the same command resumes where this one stopped
        from deepdfa_tpu.train.resilience import EXIT_PREEMPTED, Preempted

        if not isinstance(e, Preempted):
            raise
        print(f"preempted: {e}")
        if e.manifest is not None:
            print(f"resume manifest: {e.manifest} (re-run to resume)")
        raise SystemExit(EXIT_PREEMPTED)


if __name__ == "__main__":
    main()
