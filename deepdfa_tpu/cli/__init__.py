from deepdfa_tpu.cli.main import main

__all__ = ["main"]
